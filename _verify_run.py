import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax; jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as ge
ge.dryrun_multichip(8)
