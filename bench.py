"""Benchmark entry point.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Headline metric: Ok-Topk sparse-allreduce communication volume per worker per
step (bytes), measured on a multi-worker mesh in the threshold-tracking
regime, vs the dense-allreduce baseline (~2n elements/worker/step — the
BASELINE.md "allreduce bytes/step vs dense" north star). ``vs_baseline`` is
the reduction factor (dense bytes / oktopk bytes; higher is better; the
paper's property is volume < 6k elements, reference README.md:2).

The JSON line also carries the end-to-end numbers the volume claim has to be
anchored against (VERDICT r2 #2): VGG-16/CIFAR-10 train-step time with the
oktopk compressor and with dense psum on the available accelerator, their
variance, and the achieved MFU (XLA cost-analysis flops / step time / peak).

The volume measurement runs in a subprocess on a virtual 8-worker CPU mesh
(collectives need multiple devices; the benchmark chip is single-device), the
step-time measurement runs on the real accelerator in-process.

Timing note: through the remote-device tunnel ``block_until_ready`` can
return before execution finishes; every timed region here ends with a host
fetch of the loss scalar, which is the only honest synchronization point.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

BYTES_PER_ELEM = 4  # f32 scalars; indices are int32

# fp32 peak of one TPU v5e MXU chip; used only for the informational MFU
# figure. Override with OKTOPK_PEAK_FLOPS for other chips.
DEFAULT_PEAK_FLOPS = 197e12 / 2


def volume_probe():
    """Measure oktopk comm volume on an 8-worker virtual mesh (run in a
    subprocess with a CPU backend)."""
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from oktopk_tpu.collectives.api import batched_init_state, \
        build_allreduce_step
    from oktopk_tpu.comm.mesh import get_mesh
    from oktopk_tpu.config import OkTopkConfig

    P, n = 8, 1 << 20
    cfg = OkTopkConfig(n=n, num_workers=P, density=0.01, warmup_steps=0,
                       local_recompute_every=1, global_recompute_every=4)
    mesh = get_mesh((P,), ("data",))
    step = build_allreduce_step("oktopk", cfg, mesh, warmup=False)
    state = batched_init_state(cfg)
    rng = np.random.RandomState(0)
    base = rng.randn(P, n).astype(np.float32)
    vols, wires = [], []
    comp_errs, eff_dens, res_norms = [], [], []
    for i in range(13):
        grads = base + 0.3 * rng.randn(P, n).astype(np.float32)
        # offline dense-vs-sparse oracle (mirrors the in-jit quality tap,
        # obs/quality.py): what an exact allreduce of gradient + carried
        # residual would have delivered this step
        res_before = np.asarray(state.residual, dtype=np.float64)
        dense = (grads.astype(np.float64) + res_before).mean(0)
        reduced, state = step(jnp.asarray(grads), state)
        if i % 4 != 0:   # steady-state predicted steps
            vols.append(float(state.last_volume[0]))
            wires.append(float(state.last_wire_bytes[0]))
            r = np.asarray(reduced[0], dtype=np.float64)
            comp_errs.append(float(((r - dense) ** 2).sum()
                                   / ((dense ** 2).sum() + 1e-30)))
            eff_dens.append(float((r != 0).sum()) / n)
            res_norms.append(float(np.mean(np.sqrt(
                (np.asarray(state.residual, np.float64) ** 2).sum(-1)))))
    from oktopk_tpu.obs.volume import budget_bytes
    budget = budget_bytes("oktopk", cfg)
    mean_wire = sum(wires) / len(wires)
    out = {"n": n, "k": cfg.k, "mean_volume_elems": sum(vols) / len(vols),
           "dense_volume_elems": 2.0 * n,
           # bytes per transmitted (index, value) pair: int32 index + the
           # configured wire value dtype (bf16 wire = 6, f32 wire = 8)
           "wire_pair_bytes": cfg.wire_pair_bytes,
           "wire_dtype": cfg.wire_dtype,
           # realised bytes on the wire (SparseState accounting) vs the
           # paper's 6k-scalar analytic budget (obs/volume.py): <= 1.0
           # means the O(k) volume claim held on the wire
           "wire_bytes": mean_wire,
           "volume_budget_bytes": budget,
           "conformance_ratio": mean_wire / budget,
           # signal fidelity (steady-state means, offline oracle — the
           # same definitions the in-jit taps journal; watchable via
           # RegressionDetector.quality_limits)
           "quality_comp_err": sum(comp_errs) / len(comp_errs),
           "quality_eff_density": sum(eff_dens) / len(eff_dens),
           "quality_res_norm": sum(res_norms) / len(res_norms)}
    # step-anatomy tail (obs/anatomy.py): per-phase breakdown + overlap
    # scorecard on the same mesh. A missing/failed profiler capture must
    # not cost the volume headline — it degrades to anatomy_unavailable.
    try:
        import tempfile
        from oktopk_tpu.obs.anatomy import capture_pipeline_anatomy, \
            phase_totals
        # capped n: at the probe's full 1M elements the CPU profiler's
        # event buffer overflows and silently drops the later phase
        # spans (the phase MIX is the measurement, not absolute ms);
        # 64K over 2 buckets is the scale verified to capture every span
        acfg = cfg.replace(n=min(cfg.n, 1 << 16))
        with tempfile.TemporaryDirectory(prefix="oktopk_anat_") as td:
            analysis = capture_pipeline_anatomy(
                acfg, mesh, td, num_buckets=2, iters=2)
        if analysis is None:
            out["anatomy_unavailable"] = "no usable profiler capture"
        else:
            out["anatomy_phase_ms"] = {
                k: round(float(v), 4)
                for k, v in phase_totals(analysis).items()}
            out["anatomy_overlap_ratio"] = round(
                float(analysis["overlap_ratio"]), 6)
            out["anatomy_step_ms"] = round(float(analysis["step_ms"]), 4)
            out["anatomy_ideal_ms"] = round(float(analysis["ideal_ms"]), 4)
    except Exception as e:   # profiler quirks must never kill the probe
        out["anatomy_unavailable"] = repr(e)[:200]
    print("VOLUME_PROBE " + json.dumps(out))


def _time_steps(trainer, batch, iters):
    """Per-step wall times (s), each honestly synced via a loss fetch."""
    import numpy as np
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        m = trainer.train_step(batch)
        float(np.asarray(m["loss"]))
        times.append(time.perf_counter() - t0)
    return times


def step_time_probe(iters=10):
    """VGG-16/CIFAR oktopk vs dense train-step time + MFU on the available
    accelerator (single-chip mesh: measures the compute+selection path).

    Config order is a priority list — the parent's deadline kills the
    TAIL, so the headline measurements come first: dense baseline, the
    oktopk kernel path (VERDICT r3 #1), then the bs-256 probes whose MFU
    amortizes the tunnel's ~10 ms dispatch floor (VERDICT r3 #2: the bs-16
    MFU is measurement-bound, not framework-bound), then the bucketed /
    bf16 variants."""
    import jax
    import numpy as np

    from oktopk_tpu.comm.mesh import get_mesh
    from oktopk_tpu.config import TrainConfig
    from oktopk_tpu.data.synthetic import synthetic_batch
    from oktopk_tpu.train.trainer import Trainer
    from oktopk_tpu.utils.flops import model_complexity

    dev = jax.devices()[0]
    mesh = get_mesh((1,), ("data",), devices=[dev])
    rng = np.random.RandomState(0)
    # place batches once: the tunnel's host->device path is not part of
    # the step (real runs use the prefetching loader)
    batches = {16: jax.device_put(synthetic_batch("vgg16", 16, rng)),
               256: jax.device_put(synthetic_batch("vgg16", 256, rng))}

    out = {"device": dev.platform}
    flops_by_bs = {}
    # oktopk_b4 = 4 reverse-layer-order buckets (comm/backward overlap,
    # reference VGG/allreducer.py:27) — the delta vs single-bucket oktopk
    # is the measured overlap benefit
    # dense_bf16 = mixed-precision compute (2x MXU peak) — the TPU-first
    # headroom above the reference's f32 VGG workload
    for name, comp, buckets, dt, bs in (
            ("dense", "dense", 1, "float32", 16),
            ("oktopk", "oktopk", 1, "float32", 16),
            ("dense_bs256", "dense", 1, "float32", 256),
            ("oktopk_bs256", "oktopk", 1, "float32", 256),
            ("dense_bf16_bs256", "dense", 1, "bfloat16", 256),
            ("oktopk_b4", "oktopk", 4, "float32", 16),
            ("dense_bf16", "dense", 1, "bfloat16", 16)):
        times = None
        batch = batches[bs]
        # the Pallas selection kernels are auto-enabled on TPU meshes; if a
        # Mosaic compile fails on this chip generation, degrade one rung at
        # a time so the record still carries an oktopk step time: first
        # drop only the fused single-sweep front-end (oktopk_fused_failed;
        # the per-pass Pallas kernels keep running), then the whole Pallas
        # selection path (oktopk_pallas_failed)
        for use_pallas, fuse in ((None, None), (None, False), (False, None)):
            try:
                cfg = TrainConfig(dnn="vgg16", dataset="cifar10",
                                  batch_size=bs,
                                  lr=0.1, compressor=comp,
                                  density=0.02, num_workers=1,
                                  num_buckets=buckets, compute_dtype=dt)
                from oktopk_tpu.config import OkTopkConfig
                acfg = OkTopkConfig(use_pallas=use_pallas,
                                    fuse_select=fuse)
                if comp == "oktopk":
                    out.setdefault("threshold_method",
                                   acfg.threshold_method)
                trainer = Trainer(cfg, mesh=mesh, warmup=False,
                                  algo_cfg=acfg)
                _ = _time_steps(trainer, batch, 2)    # compile + warm
                # bs-256 steps carry ~16x the work per timing sample and
                # exist to amortize the dispatch floor, not to build a
                # variance estimate — half the samples suffice
                times = _time_steps(trainer, batch,
                                    iters if bs == 16 else max(3, iters // 2))
                break
            except Exception as e:
                print(f"[bench] {name} probe "
                      f"(use_pallas={use_pallas}, fuse_select={fuse}) "
                      f"failed: {e!r}",
                      file=sys.stderr)
                # only a kernel-compile failure justifies switching the
                # headline number to a degraded selection path — a
                # transient tunnel error must not be misattributed
                looks_compile = any(t in repr(e) for t in
                                    ("Mosaic", "mosaic", "Pallas",
                                     "NotImplemented", "lowering"))
                if (comp != "oktopk" or use_pallas is False
                        or not looks_compile):
                    break
                if fuse is None:
                    out[f"{name}_fused_failed"] = True
                else:
                    out[f"{name}_pallas_failed"] = True
        if times is None:
            # a config that fails to compile/run must not take down the
            # others' numbers (first contact already succeeded by here);
            # and without a fallback measurement the flags would imply one
            out.pop(f"{name}_pallas_failed", None)
            out.pop(f"{name}_fused_failed", None)
            continue
        ms = [t * 1e3 for t in times]
        out[f"{name}_ms"] = statistics.median(ms)
        out[f"{name}_ms_std"] = statistics.pstdev(ms)
        # progress line BEFORE the cost-analysis compile below: if the
        # parent's deadline kills this probe mid-way (model_complexity is
        # a fresh remote compile, minutes for a new bs-256 shape; the
        # Pallas-path configs compile many Mosaic kernels at ~13 s each
        # through the tunnel), the step time just measured still reaches
        # the record via the partial stdout
        print("STEP_PROBE " + json.dumps(out), flush=True)
        # in-loop cost analysis only for the bs-16 shape (already
        # compiled by the dense timing). The bs-256 analysis is a FRESH
        # remote lowering+compile (minutes through the tunnel) that must
        # not sit between the dense_bs256 and oktopk_bs256 timings — it
        # runs after the loop so a deadline kill costs the MFU ratio,
        # never a headline step time.
        if (bs == 16 and comp == "dense" and dt == "float32"
                and bs not in flops_by_bs):
            try:
                rng_key = jax.random.PRNGKey(0)
                cost = model_complexity(
                    lambda s, b, r: trainer.step_fn(s, b, r),
                    trainer.state, batch, rng_key)
                if cost["flops"] > 0:
                    flops_by_bs[bs] = cost["flops"]
                    out["flops_per_step"] = cost["flops"]
            except Exception as e:
                print(f"[bench] cost analysis unavailable: {e!r}",
                      file=sys.stderr)
        # MFU only against the known TPU peak — and only for the names
        # main()'s record keeps; on a CPU fallback the ratio would be
        # meaningless in the machine-readable record (the tunnelled chip
        # reports platform "axon", a real TPU v5e)
        if (bs in flops_by_bs
                and name in ("dense", "oktopk", "dense_bs256",
                             "oktopk_bs256")
                and (dev.platform != "cpu"
                     or "OKTOPK_PEAK_FLOPS" in os.environ)):
            peak = float(os.environ.get("OKTOPK_PEAK_FLOPS",
                                        DEFAULT_PEAK_FLOPS))
            out["peak_flops_assumed"] = peak   # v5e fp32 unless overridden
            out[f"mfu_{name}"] = (flops_by_bs[bs]
                                  / (out[f"{name}_ms"] / 1e3) / peak)
        print("STEP_PROBE " + json.dumps(out), flush=True)

    # bs-256 MFU, after every timing is safe: a real cost analysis (one
    # fresh compile) with a linear-scaling fallback — VGG step flops are
    # conv/matmul-dominated and exactly proportional to batch, the
    # remainder (optimizer/selection) is batch-independent and small.
    # Gate on ANY bs-256 timing: a failed dense_bs256 probe must not
    # silently drop the other bs-256 MFUs when their timings exist
    # (ADVICE r4)
    if (any(f"{nm}_ms" in out for nm in
            ("dense_bs256", "oktopk_bs256", "dense_bf16_bs256"))
            and 16 in flops_by_bs):
        try:
            cfg = TrainConfig(dnn="vgg16", dataset="cifar10",
                              batch_size=256, lr=0.1, compressor="dense",
                              density=0.02, num_workers=1)
            tr = Trainer(cfg, mesh=mesh, warmup=False)
            cost = model_complexity(
                lambda s, b, r: tr.step_fn(s, b, r),
                tr.state, batches[256], jax.random.PRNGKey(0))
            if cost["flops"] > 0:
                flops_by_bs[256] = cost["flops"]
        except Exception as e:
            print(f"[bench] bs-256 cost analysis unavailable: {e!r}",
                  file=sys.stderr)
        if 256 not in flops_by_bs:
            flops_by_bs[256] = flops_by_bs[16] * 16.0
            out["flops_per_step_bs256_scaled"] = True
        out["flops_per_step_bs256"] = flops_by_bs[256]
        if dev.platform != "cpu" or "OKTOPK_PEAK_FLOPS" in os.environ:
            peak = float(os.environ.get("OKTOPK_PEAK_FLOPS",
                                        DEFAULT_PEAK_FLOPS))
            out["peak_flops_assumed"] = peak
            for nm in ("dense_bs256", "oktopk_bs256"):
                if f"{nm}_ms" in out:
                    out[f"mfu_{nm}"] = (flops_by_bs[256]
                                        / (out[f"{nm}_ms"] / 1e3) / peak)
            # the bf16 probe runs the MXU in its native precision, so its
            # utilization is measured against the full bf16 peak (2x the
            # fp32 figure on v5e) — the mixed-precision headroom the
            # reference gets from apex (BERT/bert/main_bert.py:1009-1023)
            if "dense_bf16_bs256_ms" in out:
                bf16_peak = 2.0 * peak
                out["peak_flops_bf16_assumed"] = bf16_peak
                out["mfu_dense_bf16_bs256"] = (
                    flops_by_bs[256]
                    / (out["dense_bf16_bs256_ms"] / 1e3) / bf16_peak)
        print("STEP_PROBE " + json.dumps(out), flush=True)
    # autotuned variant, last (the deadline kill policy: a new metric must
    # never cost the headline ones above): the tuner calibrates the fabric,
    # trials dense vs oktopk per bucket, and the step runs the chosen plan.
    # On this single-chip mesh there is no wire to win back, so a correct
    # tuner converges the oktopk workload onto dense per-bucket — the
    # oktopk_autotuned_ms vs oktopk_ms gap is the recovered crossover.
    try:
        cfg = TrainConfig(dnn="vgg16", dataset="cifar10", batch_size=16,
                          lr=0.1, compressor="oktopk", density=0.02,
                          num_workers=1, num_buckets=4, autotune=True,
                          autotune_candidates=("dense", "oktopk"),
                          autotune_trial_steps=2)
        trainer = Trainer(cfg, mesh=mesh, warmup=False)
        plans = trainer.autotune(step=0)
        out["autotune_plan"] = [
            {"bucket": p.bucket, "n": p.n, "algo": p.algo,
             "density": p.density, "predicted_ms": round(p.predicted_ms, 3),
             "measured_ms": round(p.measured_ms, 3)} for p in plans]
        _ = _time_steps(trainer, batches[16], 2)     # compile + warm
        ms = [t * 1e3 for t in _time_steps(trainer, batches[16], iters)]
        out["oktopk_autotuned_ms"] = statistics.median(ms)
        out["oktopk_autotuned_ms_std"] = statistics.pstdev(ms)
        print("STEP_PROBE " + json.dumps(out), flush=True)
    except Exception as e:
        print(f"[bench] oktopk_autotuned probe failed: {e!r}",
              file=sys.stderr)

    # hierarchical two-level probe (collectives/hierarchical.py): dense
    # intra-pod psum + oktopk across pods over a (pod, data) mesh built
    # from ALL visible devices. Needs >= 2 pods' worth of devices —
    # single-chip runs degrade gracefully (the record simply lacks
    # hierarchical_ms, like any killed tail probe). Raw collective step,
    # not a Trainer: the point is the two-level exchange price next to
    # the flat numbers above, on the same record.
    try:
        from oktopk_tpu.collectives.api import (batched_init_state,
                                                build_allreduce_step,
                                                time_allreduce_step)
        from oktopk_tpu.collectives.hierarchical import \
            make_hierarchical_config
        from oktopk_tpu.comm.mesh import local_hierarchical_mesh
        from oktopk_tpu.config import OkTopkConfig

        ndev = len(jax.devices())
        if ndev < 2:
            raise RuntimeError(f"needs >= 2 devices for 2 pods, have {ndev}")
        hmesh = local_hierarchical_mesh(num_pods=2)
        total = hmesh.devices.size
        n = 1 << 18
        flat = OkTopkConfig(n=n, num_workers=total, density=0.02,
                            warmup_steps=0)
        hcfg = make_hierarchical_config(flat, num_pods=2, outer="oktopk")
        hstep = build_allreduce_step("hierarchical", hcfg, hmesh)
        grads = jax.device_put(
            np.asarray(rng.standard_normal((total, n)), np.float32),
            jax.sharding.NamedSharding(
                hmesh, jax.sharding.PartitionSpec(
                    (hcfg.inter_axis, hcfg.intra_axis))))
        hst = batched_init_state(hcfg)
        ms, _ = time_allreduce_step(hstep, grads, hst, iters=iters)
        out["hierarchical_ms"] = statistics.median(ms)
        out["hierarchical_ms_std"] = statistics.pstdev(ms)
        out["hierarchical_plan"] = {"num_pods": hcfg.num_pods,
                                    "pod_size": hcfg.pod_size,
                                    "levels": hcfg.level_plan()}
        print("STEP_PROBE " + json.dumps(out), flush=True)
    except Exception as e:
        print(f"[bench] hierarchical probe failed: {e!r}", file=sys.stderr)

    # numeric-health tail (resilience/): a few guarded oktopk steps so the
    # bench driver tracks numeric health alongside latency — steps_skipped
    # and fallback_events must be 0 on a healthy chip, and grad_nonfinite
    # flags the blow-up step when they are not. The durable-state leg
    # rides along: one save+verify round trip through the
    # AsyncCheckpointer, so ckpt_saves tracks that the storage path
    # publishes verified checkpoints (ckpt_verify_failures must be 0).
    # Last in the priority order: a deadline kill here costs no timing.
    try:
        cfg = TrainConfig(dnn="vgg16", dataset="cifar10", batch_size=16,
                          lr=0.1, compressor="oktopk", density=0.02,
                          num_workers=1, resilience=True)
        trainer = Trainer(cfg, mesh=mesh, warmup=False)
        for step in range(1, 3):
            m = trainer.train_step(batches[16])
            trainer.supervise(step, m)
        import numpy as _np
        out["grad_nonfinite"] = int(_np.asarray(m["grad_nonfinite"]))
        out["steps_skipped"] = int(_np.asarray(m["steps_skipped"]))
        out["fallback_events"] = trainer.supervisor.fallback_events
        out["remesh_events"] = trainer.supervisor.remesh_events
        out["retune_events"] = trainer.retune_events
        import tempfile as _tempfile

        from oktopk_tpu.train.durable import AsyncCheckpointer
        with _tempfile.TemporaryDirectory() as ckpt_dir:
            with AsyncCheckpointer(ckpt_dir) as ckpt:
                ckpt.save(trainer.state, 2,
                          qualified=trainer.checkpoint_qualified)
                ckpt.drain(timeout=120.0)
            out["ckpt_saves"] = ckpt.saves
            out["ckpt_verify_failures"] = ckpt.verify_failures
        print("STEP_PROBE " + json.dumps(out), flush=True)
    except Exception as e:
        print(f"[bench] resilience probe failed: {e!r}", file=sys.stderr)

    print(f"[bench] {out}", file=sys.stderr)
    return out


def main():
    if "--volume-probe" in sys.argv:
        volume_probe()
        return
    if "--step-probe" in sys.argv:
        print("STEP_PROBE " + json.dumps(step_time_probe()))
        return

    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the site plugin's TPU-tunnel registration dials a local relay at
    # startup; a CPU-only subprocess must never touch it (a down relay
    # would hang the probe)
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--volume-probe"],
        capture_output=True, text=True, env=env, cwd=here, timeout=1800)
    probe = None
    for line in proc.stdout.splitlines():
        if line.startswith("VOLUME_PROBE "):
            probe = json.loads(line[len("VOLUME_PROBE "):])
    if probe is None:
        print(proc.stdout, file=sys.stderr)
        print(proc.stderr[-4000:], file=sys.stderr)
        raise RuntimeError("volume probe failed")

    def _record(steps):
        # volume_elems counts transmitted scalars (2 per (index, value)
        # pair); bytes follow the wire format: int32 index + bf16/f32
        # value per pair, dense baseline = 2n f32 values (ring
        # allreduce), no indices
        pairs = probe["mean_volume_elems"] / 2.0
        value = pairs * probe.get("wire_pair_bytes", 2 * BYTES_PER_ELEM)
        dense = probe["dense_volume_elems"] * BYTES_PER_ELEM
        rec = {
            "metric": "oktopk_sparse_allreduce_volume_bytes_per_step",
            "value": round(value, 1),
            "unit": "bytes/step/worker",
            "vs_baseline": round(dense / value, 2),
            "volume_elems": round(probe["mean_volume_elems"], 1),
            "wire_dtype": probe.get("wire_dtype", "float32"),
        }
        # measured-on-the-wire conformance (obs/volume.py): present when
        # the probe ran a build that threads wire-byte accounting
        for key in ("wire_bytes", "volume_budget_bytes",
                    "conformance_ratio"):
            if key in probe:
                rec[key] = round(float(probe[key]), 3)
        # offline signal-fidelity oracle (same definitions as the in-jit
        # quality taps) — carried so the BENCH trajectory can baseline
        # fidelity drift, not just step time and volume
        for key in ("quality_comp_err", "quality_eff_density",
                    "quality_res_norm"):
            if key in probe:
                rec[key] = round(float(probe[key]), 6)
        # step-anatomy tail (phase breakdown + overlap scorecard from the
        # probe subprocess; anatomy_unavailable when capture failed)
        for key in ("anatomy_phase_ms", "anatomy_overlap_ratio",
                    "anatomy_step_ms", "anatomy_ideal_ms",
                    "anatomy_unavailable"):
            if key in probe:
                rec[key] = probe[key]
        for key in ("device", "oktopk_ms", "oktopk_ms_std", "dense_ms",
                    "dense_ms_std", "dense_bs256_ms", "dense_bs256_ms_std",
                    "oktopk_bs256_ms", "oktopk_bs256_ms_std",
                    "oktopk_b4_ms", "oktopk_b4_ms_std",
                    "oktopk_autotuned_ms", "oktopk_autotuned_ms_std",
                    "autotune_plan",
                    "hierarchical_ms", "hierarchical_ms_std",
                    "hierarchical_plan",
                    "dense_bf16_ms", "dense_bf16_ms_std",
                    "dense_bf16_bs256_ms", "dense_bf16_bs256_ms_std",
                    "oktopk_pallas_failed", "oktopk_bs256_pallas_failed",
                    "oktopk_b4_pallas_failed",
                    "oktopk_fused_failed", "oktopk_bs256_fused_failed",
                    "oktopk_b4_fused_failed", "threshold_method",
                    "flops_per_step", "flops_per_step_bs256",
                    "flops_per_step_bs256_scaled", "peak_flops_assumed",
                    "peak_flops_bf16_assumed",
                    "mfu_dense", "mfu_oktopk", "mfu_dense_bs256",
                    "mfu_oktopk_bs256", "mfu_dense_bf16_bs256",
                    "grad_nonfinite", "steps_skipped", "fallback_events",
                    "remesh_events", "retune_events",
                    "ckpt_saves", "ckpt_verify_failures"):
            if key in steps:
                rec[key] = (round(steps[key], 3)
                            if isinstance(steps[key], float)
                            else steps[key])
        return rec

    # Provisional record NOW: the step-probe section below can poll/block
    # for many minutes, and an outer timeout kill there must not cost the
    # volume headline — the driver takes the last JSON line, and the
    # final enriched record (if reached) prints after this one.
    print(json.dumps(_record({})), flush=True)

    # step-time probe with a bounded retry, in a subprocess: first contact
    # with the real accelerator through the tunnel occasionally times out —
    # and when the tunnel relay is down entirely, jax.devices() BLOCKS
    # forever inside C (no exception, SIGALRM handlers never run), so the
    # only reliable deadline is a killable child process. Whatever happens,
    # the volume JSON line still gets printed.
    steps = {}
    deadline = int(os.environ.get("OKTOPK_BENCH_STEP_DEADLINE", "900"))

    from oktopk_tpu.utils.tunnel import relay_expected, relay_listening

    attempts = 2
    # Total wall budget for the whole step-probe phase (poll + attempts):
    # keeps this phase bounded so an outer driver timeout calibrated to
    # the deadline cannot kill bench mid-probe after a long poll.
    phase_budget = float(attempts * deadline)
    phase_start = time.monotonic()
    # When this environment reaches the accelerator through the tunnel
    # relay (the site plugin's env vars are present) and nothing listens
    # at it, do NOT burn the deadline on a probe that would hang in
    # jax.devices(): poll the relay socket cheaply instead (round 4 died
    # at a single 120 s attempt while the relay was down; the relay flaps
    # up/down on ~30 min scales, so a window can open mid-bench). If the
    # relay appears, fall through to the attempt loop with the budget
    # that remains; if it never does, make one short attempt anyway in
    # case the socket probe is wrong. An explicitly set
    # OKTOPK_BENCH_STEP_DEADLINE skips the poll-and-clamp entirely and
    # always gets the full direct-attempt policy (the operator override
    # for a misconfigured/unprobeable relay port).
    if (relay_expected() and not relay_listening()
            and "OKTOPK_BENCH_STEP_DEADLINE" not in os.environ):
        print(f"[bench] tunnel relay not listening; polling socket within "
              f"the {deadline}s window", file=sys.stderr)
        waited = 0.0
        while waited < deadline and not relay_listening():
            time.sleep(15)
            waited += 15
        if relay_listening():
            print(f"[bench] relay came up after {waited:.0f}s; running "
                  "step probe with remaining budget", file=sys.stderr)
        else:
            print("[bench] relay never appeared; single short probe "
                  "attempt only", file=sys.stderr)
            deadline = min(120, deadline)
            attempts = 1
            phase_budget = float(deadline)
            phase_start = time.monotonic()
    # persistent compilation cache: a retry (or the second config sharing a
    # shape) skips the ~13 s/kernel remote Mosaic compiles where supported
    step_env = dict(os.environ)
    step_env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/oktopk_jax_cache")

    def _last_step_line(text):
        found = None
        for line in (text or "").splitlines():
            if line.startswith("STEP_PROBE "):
                try:
                    found = json.loads(line[len("STEP_PROBE "):])
                except ValueError:
                    pass   # deadline kill can truncate a line mid-write
        return found

    for attempt in range(attempts):
        remaining = phase_budget - (time.monotonic() - phase_start)
        if remaining < 60:
            print(f"[bench] step-probe phase budget exhausted "
                  f"({remaining:.0f}s left); stopping", file=sys.stderr)
            break
        try:
            sp = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--step-probe"],
                capture_output=True, text=True, cwd=here,
                timeout=min(deadline, remaining),
                env=step_env)
            got = _last_step_line(sp.stdout)
            if got:
                steps = {**steps, **got}
            # "device" alone means contact succeeded but every config
            # failed (transient first-compile errors) — retry that too
            if any(k.endswith("_ms") for k in steps):
                break
            print(sp.stderr[-2000:], file=sys.stderr)
        except subprocess.TimeoutExpired as e:
            print(f"[bench] step-time probe attempt {attempt}: timed out "
                  f"after {deadline}s", file=sys.stderr)
            # keep whatever configs completed before the deadline
            out = e.stdout
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            partial = _last_step_line(out)
            if partial:
                # merge: a shorter second partial must not discard configs
                # a previous attempt already measured
                steps = {**steps, **partial}
                print(f"[bench] kept partial step probe: "
                      f"{sorted(k for k in steps if k.endswith('_ms'))}",
                      file=sys.stderr)
        if attempt == 0 and attempts > 1:
            time.sleep(20)

    print(json.dumps(_record(steps)))


if __name__ == "__main__":
    main()
