"""Benchmark entry point.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric: Ok-Topk sparse-allreduce communication volume per worker per
step (bytes), measured on a multi-worker mesh in the threshold-tracking
regime, vs the dense-allreduce baseline (~2n elements/worker/step — the
BASELINE.md "allreduce bytes/step vs dense" north star). ``vs_baseline`` is
the reduction factor (dense bytes / oktopk bytes; higher is better; the
paper's property is volume < 6k elements, reference README.md:2).

Also measures (stderr, informational): the end-to-end VGG-16/CIFAR-10
oktopk train-step time on the available accelerator.

The volume measurement runs in a subprocess on a virtual 8-worker CPU mesh
(collectives need multiple devices; the benchmark chip is single-device), the
step-time measurement runs on the real accelerator in-process.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BYTES_PER_ELEM = 4  # f32 scalars; indices are int32


def volume_probe():
    """Measure oktopk comm volume on an 8-worker virtual mesh (run in a
    subprocess with a CPU backend)."""
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from oktopk_tpu.collectives.api import batched_init_state, \
        build_allreduce_step
    from oktopk_tpu.comm.mesh import get_mesh
    from oktopk_tpu.config import OkTopkConfig

    P, n = 8, 1 << 20
    cfg = OkTopkConfig(n=n, num_workers=P, density=0.01, warmup_steps=0,
                       local_recompute_every=1, global_recompute_every=4)
    mesh = get_mesh((P,), ("data",))
    step = build_allreduce_step("oktopk", cfg, mesh, warmup=False)
    state = batched_init_state(cfg)
    rng = np.random.RandomState(0)
    base = rng.randn(P, n).astype(np.float32)
    vols = []
    for i in range(9):
        grads = jnp.asarray(base + 0.3 * rng.randn(P, n).astype(np.float32))
        _, state = step(grads, state)
        if i % 4 != 0:   # steady-state predicted steps
            vols.append(float(state.last_volume[0]))
    out = {"n": n, "k": cfg.k, "mean_volume_elems": sum(vols) / len(vols),
           "dense_volume_elems": 2.0 * n}
    print("VOLUME_PROBE " + json.dumps(out))


def step_time_probe():
    """VGG-16/CIFAR oktopk train-step time on the available accelerator
    (single-chip mesh: measures the compute+selection path)."""
    import jax
    import numpy as np

    from oktopk_tpu.comm.mesh import get_mesh
    from oktopk_tpu.config import TrainConfig
    from oktopk_tpu.data.synthetic import synthetic_batch
    from oktopk_tpu.train.trainer import Trainer

    dev = jax.devices()[0]
    mesh = get_mesh((1,), ("data",), devices=[dev])
    cfg = TrainConfig(dnn="vgg16", dataset="cifar10", batch_size=16,
                      lr=0.1, compressor="oktopk", density=0.02,
                      num_workers=1)
    trainer = Trainer(cfg, mesh=mesh, warmup=False)
    rng = np.random.RandomState(0)
    batch = synthetic_batch("vgg16", 16, rng)
    m = trainer.train_step(batch)          # compile
    jax.block_until_ready(m["loss"])
    t0 = time.time()
    iters = 20
    for _ in range(iters):
        m = trainer.train_step(batch)
    jax.block_until_ready(m["loss"])
    dt = (time.time() - t0) / iters
    print(f"[bench] device={dev.platform} vgg16 oktopk step "
          f"{dt * 1e3:.1f} ms  ({16 / dt:.1f} images/s/chip)",
          file=sys.stderr)
    return dt


def main():
    if "--volume-probe" in sys.argv:
        volume_probe()
        return

    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--volume-probe"],
        capture_output=True, text=True, env=env, cwd=here, timeout=1800)
    probe = None
    for line in proc.stdout.splitlines():
        if line.startswith("VOLUME_PROBE "):
            probe = json.loads(line[len("VOLUME_PROBE "):])
    if probe is None:
        print(proc.stdout, file=sys.stderr)
        print(proc.stderr[-4000:], file=sys.stderr)
        raise RuntimeError("volume probe failed")

    try:
        step_time_probe()
    except Exception as e:  # informational only — never break the headline
        print(f"[bench] step-time probe skipped: {e!r}", file=sys.stderr)

    value = probe["mean_volume_elems"] * BYTES_PER_ELEM
    dense = probe["dense_volume_elems"] * BYTES_PER_ELEM
    print(json.dumps({
        "metric": "oktopk_sparse_allreduce_volume_bytes_per_step",
        "value": round(value, 1),
        "unit": "bytes/step/worker",
        "vs_baseline": round(dense / value, 2),
    }))


if __name__ == "__main__":
    main()
