// Prefetching shuffled batch loader, native runtime component.
//
// The reference feeds its trainers through torch DataLoader worker
// processes (VGG/dl_trainer.py:286-343, num_workers=1 subprocess per rank).
// TPU-native equivalent: the dataset lives in host RAM as one contiguous
// array-of-records; a background pthread gathers shuffled records into a
// ring of pre-allocated batch buffers so batch assembly fully overlaps the
// device step and never contends for the Python GIL.
//
// Shuffle: Fisher-Yates over an index vector, reseeded per epoch from
// (seed, epoch) via splitmix64 — deterministic and worker-shardable: with
// shard/num_shards the loader walks only its residue class, matching the
// reference's DistributedSampler partitioning (VGG/dl_trainer.py:336-343).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

uint64_t splitmix64(uint64_t& x) {
  x += 0x9E3779B97F4A7C15ULL;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

struct Loader {
  const uint8_t* data = nullptr;   // [n_items, item_bytes] borrowed buffer
  int64_t n_items = 0;
  int64_t item_bytes = 0;
  int64_t batch = 0;
  int64_t shard = 0, num_shards = 1;
  uint64_t seed = 0;
  bool drop_last = true;

  // ring of prefetched batch buffers
  std::vector<std::vector<uint8_t>> ring;
  std::vector<int64_t> ring_count;     // records actually in each slot
  size_t head = 0, tail = 0, filled = 0;
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::thread worker;
  std::atomic<bool> stop{false};

  // shuffle state (worker-side)
  std::vector<int64_t> order;
  size_t pos = 0;
  uint64_t epoch = 0;

  void reshuffle() {
    int64_t total = n_items / num_shards;
    order.resize(static_cast<size_t>(total));
    for (int64_t i = 0; i < total; ++i)
      order[static_cast<size_t>(i)] = i * num_shards + shard;
    uint64_t s = seed * 0x9E3779B97F4A7C15ULL + epoch + 1;
    for (size_t i = order.size(); i > 1; --i) {
      size_t j = static_cast<size_t>(splitmix64(s) % i);
      std::swap(order[i - 1], order[j]);
    }
    pos = 0;
    ++epoch;
  }

  void fill_slot(size_t slot) {
    int64_t count = 0;
    uint8_t* dst = ring[slot].data();
    // drop_last: discard the epoch tail *before* starting a batch so one
    // batch never mixes records of two epochs (torch-DataLoader semantics;
    // only a dataset smaller than one batch still wraps mid-batch)
    if (drop_last && order.size() - pos < static_cast<size_t>(batch)
        && order.size() >= static_cast<size_t>(batch))
      reshuffle();
    while (count < batch) {
      if (pos >= order.size()) {
        if (!drop_last && count > 0) break;  // partial final batch
        reshuffle();
        if (order.empty()) break;  // shard holds zero records
      }
      int64_t rec = order[pos++];
      std::memcpy(dst + count * item_bytes, data + rec * item_bytes,
                  static_cast<size_t>(item_bytes));
      ++count;
    }
    ring_count[slot] = count;
  }

  void run() {
    for (;;) {
      std::unique_lock<std::mutex> lk(mu);
      cv_empty.wait(lk, [&] { return stop.load() || filled < ring.size(); });
      if (stop.load()) return;
      size_t slot = tail;
      lk.unlock();
      fill_slot(slot);           // copy outside the lock
      lk.lock();
      tail = (tail + 1) % ring.size();
      ++filled;
      cv_full.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* okn_loader_new(const uint8_t* data, int64_t n_items, int64_t item_bytes,
                     int64_t batch, uint64_t seed, int64_t shard,
                     int64_t num_shards, int64_t prefetch_depth,
                     int drop_last) {
  auto* l = new Loader;
  l->data = data;
  l->n_items = n_items;
  l->item_bytes = item_bytes;
  l->batch = batch;
  l->seed = seed;
  l->shard = shard;
  l->num_shards = num_shards < 1 ? 1 : num_shards;
  l->drop_last = drop_last != 0;
  if (prefetch_depth < 1) prefetch_depth = 2;
  l->ring.resize(static_cast<size_t>(prefetch_depth));
  l->ring_count.assign(static_cast<size_t>(prefetch_depth), 0);
  for (auto& b : l->ring)
    b.resize(static_cast<size_t>(batch * item_bytes));
  l->reshuffle();
  l->worker = std::thread([l] { l->run(); });
  return l;
}

// Blocks until a prefetched batch is ready; copies it into out
// ([batch, item_bytes]) and returns the record count (< batch only for a
// partial final batch with drop_last=0, or 0 when the loader is stopping —
// without the stop check here, okn_loader_free racing a blocked next()
// would join a worker that already exited and deadlock the waiter).
int64_t okn_loader_next(void* h, uint8_t* out) {
  auto* l = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(l->mu);
  l->cv_full.wait(lk, [&] { return l->stop.load() || l->filled > 0; });
  if (l->filled == 0) return 0;  // stopping, nothing buffered
  size_t slot = l->head;
  int64_t count = l->ring_count[slot];
  std::memcpy(out, l->ring[slot].data(),
              static_cast<size_t>(count * l->item_bytes));
  l->head = (l->head + 1) % l->ring.size();
  --l->filled;
  l->cv_empty.notify_one();
  return count;
}

// Wake the worker and any thread blocked in okn_loader_next (they return 0
// once the ring drains). Does NOT release the Loader: the caller must keep
// the handle alive until every in-flight okn_loader_next has returned, then
// call okn_loader_free — the Python wrapper tracks in-flight calls under
// its own lock, which is what makes free-vs-blocked-next safe (a C-side
// "wait for waiters" handshake can't see a caller that is between reading
// the handle and entering the call).
void okn_loader_stop(void* h) {
  auto* l = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lk(l->mu);
    l->stop.store(true);
  }
  l->cv_empty.notify_all();
  l->cv_full.notify_all();
}

void okn_loader_free(void* h) {
  auto* l = static_cast<Loader*>(h);
  okn_loader_stop(h);
  if (l->worker.joinable()) l->worker.join();
  delete l;
}

}  // extern "C"
