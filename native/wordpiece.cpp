// WordPiece tokenizer, native runtime component.
//
// Matches oktopk_tpu/data/tokenization.py (itself modeled on the reference's
// vendored BERT/bert/transformers/tokenization.py): BasicTokenizer
// (lowercase, NFD accent strip, punctuation split) -> greedy longest-match
// WordPiece over a vocab hash -> ids, plus the [CLS]/[SEP] pair encoding
// with longest-first truncation (reference _truncate_seq_pair).
//
// Unicode scope: full UTF-8 iteration; lowercase/accent-strip cover ASCII +
// Latin-1 supplement + Latin Extended-A (the ranges BERT's uncased English
// vocab actually contains); other code points pass through unchanged and
// split only on ASCII/Unicode-general-punctuation. The Python implementation
// remains the reference for exotic scripts; parity tests pin the two
// together on the Latin ranges.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct WpTokenizer {
  std::unordered_map<std::string, int32_t> vocab;
  int32_t unk_id = 1;
  bool do_lower = true;
  int max_chars = 100;  // per-token cap (tokenization.py:57)
};

// ---- UTF-8 helpers ---------------------------------------------------------

// Decode one code point starting at s[i]; advances i. Invalid bytes decode
// as themselves (latin-1 style) so we never stall.
uint32_t decode_utf8(const unsigned char* s, size_t n, size_t& i) {
  unsigned char c = s[i];
  if (c < 0x80) { i += 1; return c; }
  if ((c >> 5) == 0x6 && i + 1 < n) {
    uint32_t cp = ((c & 0x1F) << 6) | (s[i + 1] & 0x3F);
    i += 2; return cp;
  }
  if ((c >> 4) == 0xE && i + 2 < n) {
    uint32_t cp = ((c & 0x0F) << 12) | ((s[i + 1] & 0x3F) << 6)
                  | (s[i + 2] & 0x3F);
    i += 3; return cp;
  }
  if ((c >> 3) == 0x1E && i + 3 < n) {
    uint32_t cp = ((c & 0x07) << 18) | ((s[i + 1] & 0x3F) << 12)
                  | ((s[i + 2] & 0x3F) << 6) | (s[i + 3] & 0x3F);
    i += 4; return cp;
  }
  i += 1;
  return c;
}

void append_utf8(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out.push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

// Lowercase + accent-strip one code point (0 = drop, e.g. combining marks).
// Covers ASCII, Latin-1 supplement and Latin Extended-A; mirrors Python's
// lower() + NFD + remove-Mn pipeline on those ranges exactly: only letters
// with a canonical decomposition lose their accent, the rest just lowercase
// (e.g. Đ -> đ, Ł -> ł, Ø -> ø — none of which NFD-decompose).
uint32_t lower_strip(uint32_t cp) {
  if (cp >= 'A' && cp <= 'Z') return cp + 32;
  if (cp >= 0x300 && cp <= 0x36F) return 0;  // combining marks (Mn)

  if (cp >= 0xC0 && cp <= 0xFF) {  // Latin-1 supplement
    switch (cp) {
      case 0xC6: case 0xE6: return 0xE6;  // ae ligature (no decomposition)
      case 0xD0: case 0xF0: return 0xF0;  // eth
      case 0xD7: return 0xD7;             // multiplication sign
      case 0xD8: case 0xF8: return 0xF8;  // o-slash
      case 0xDE: case 0xFE: return 0xFE;  // thorn
      case 0xDF: return 0xDF;             // sharp s
      case 0xF7: return 0xF7;             // division sign
      default: break;
    }
    uint32_t lo = cp < 0xE0 ? cp + 0x20 : cp;  // lowercase first
    // decomposable accented letters -> base
    if (lo >= 0xE0 && lo <= 0xE5) return 'a';
    if (lo == 0xE7) return 'c';
    if (lo >= 0xE8 && lo <= 0xEB) return 'e';
    if (lo >= 0xEC && lo <= 0xEF) return 'i';
    if (lo == 0xF1) return 'n';
    if ((lo >= 0xF2 && lo <= 0xF6)) return 'o';
    if (lo >= 0xF9 && lo <= 0xFC) return 'u';
    if (lo == 0xFD || lo == 0xFF) return 'y';
    return lo;
  }

  if (cp >= 0x100 && cp <= 0x17F) {  // Latin Extended-A
    switch (cp) {  // letters with NO canonical decomposition: lowercase only
      case 0x110: case 0x111: return 0x111;  // d-stroke
      case 0x126: case 0x127: return 0x127;  // h-stroke
      case 0x131: return 0x131;              // dotless i
      case 0x132: case 0x133: return 0x133;  // ij ligature
      case 0x138: return 0x138;              // kra
      case 0x13F: case 0x140: return 0x140;  // l-middle-dot (NFKD only)
      case 0x141: case 0x142: return 0x142;  // l-stroke
      case 0x149: return 0x149;              // 'n (NFKD only)
      case 0x14A: case 0x14B: return 0x14B;  // eng
      case 0x152: case 0x153: return 0x153;  // oe ligature
      case 0x166: case 0x167: return 0x167;  // t-stroke
      case 0x17F: return 0x17F;              // long s (NFKD only)
      default: break;
    }
    if (cp <= 0x105) return 'a';
    if (cp <= 0x10D) return 'c';
    if (cp <= 0x10F) return 'd';
    if (cp <= 0x11B) return 'e';
    if (cp <= 0x123) return 'g';
    if (cp <= 0x125) return 'h';
    if (cp <= 0x130) return 'i';
    if (cp <= 0x135) return 'j';
    if (cp <= 0x137) return 'k';
    if (cp <= 0x13E) return 'l';
    if (cp <= 0x148) return 'n';
    if (cp <= 0x151) return 'o';
    if (cp <= 0x159) return 'r';
    if (cp <= 0x161) return 's';
    if (cp <= 0x165) return 't';
    if (cp <= 0x173) return 'u';
    if (cp <= 0x175) return 'w';
    if (cp <= 0x178) return 'y';
    return 'z';
  }
  return cp;
}

bool is_space(uint32_t cp) {
  return cp == ' ' || cp == '\t' || cp == '\n' || cp == '\r' || cp == 0x0B
         || cp == 0x0C || cp == 0xA0 || cp == 0x2028 || cp == 0x2029
         || (cp >= 0x2000 && cp <= 0x200A) || cp == 0x3000;
}

bool is_punct(uint32_t cp) {
  // ASCII punctuation blocks (tokenization.py:15-20) ...
  if ((cp >= 33 && cp <= 47) || (cp >= 58 && cp <= 64)
      || (cp >= 91 && cp <= 96) || (cp >= 123 && cp <= 126))
    return true;
  // ... plus Latin-1 supplement category-P code points (¡ § « ¶ · » ¿ —
  // the other A1-BF signs are category S, not punctuation in Python either)
  if (cp == 0xA1 || cp == 0xA7 || cp == 0xAB || cp == 0xB6 || cp == 0xB7
      || cp == 0xBB || cp == 0xBF)
    return true;
  // ... plus General Punctuation and CJK punctuation (category P)
  return (cp >= 0x2010 && cp <= 0x2027) || (cp >= 0x2030 && cp <= 0x205E)
         || (cp >= 0x3001 && cp <= 0x3011) || (cp >= 0xFF01 && cp <= 0xFF0F);
}

// BasicTokenizer: split text into words/punctuation (tokenization.py:23-49).
std::vector<std::string> basic_tokenize(const WpTokenizer& t,
                                        const char* text) {
  const auto* s = reinterpret_cast<const unsigned char*>(text);
  size_t n = std::strlen(text);
  std::vector<std::string> out;
  std::string word;
  size_t i = 0;
  while (i < n) {
    uint32_t cp = decode_utf8(s, n, i);
    if (t.do_lower) {
      cp = lower_strip(cp);
      if (cp == 0) continue;  // stripped combining mark
    }
    if (is_space(cp)) {
      if (!word.empty()) { out.push_back(word); word.clear(); }
    } else if (is_punct(cp)) {
      if (!word.empty()) { out.push_back(word); word.clear(); }
      std::string p;
      append_utf8(p, cp);
      out.push_back(p);
    } else {
      append_utf8(word, cp);
    }
  }
  if (!word.empty()) out.push_back(word);
  return out;
}

size_t utf8_len(const std::string& s) {
  size_t count = 0;
  for (unsigned char c : s)
    if ((c & 0xC0) != 0x80) ++count;
  return count;
}

// byte offsets of each code-point boundary (for longest-match backoff)
std::vector<size_t> char_offsets(const std::string& s) {
  std::vector<size_t> offs;
  for (size_t i = 0; i < s.size(); ++i)
    if ((static_cast<unsigned char>(s[i]) & 0xC0) != 0x80) offs.push_back(i);
  offs.push_back(s.size());
  return offs;
}

// Greedy longest-match WordPiece (tokenization.py:52-78) -> ids.
void wordpiece_ids(const WpTokenizer& t, const std::string& token,
                   std::vector<int32_t>& out) {
  if (utf8_len(token) > static_cast<size_t>(t.max_chars)) {
    out.push_back(t.unk_id);
    return;
  }
  auto offs = char_offsets(token);
  size_t nchars = offs.size() - 1;
  std::vector<int32_t> pieces;
  size_t start = 0;
  while (start < nchars) {
    size_t end = nchars;
    int32_t cur = -1;
    while (start < end) {
      std::string sub = token.substr(offs[start], offs[end] - offs[start]);
      if (start > 0) sub = "##" + sub;
      auto it = t.vocab.find(sub);
      if (it != t.vocab.end()) { cur = it->second; break; }
      --end;
    }
    if (cur < 0) {
      out.push_back(t.unk_id);
      return;
    }
    pieces.push_back(cur);
    start = end;
  }
  out.insert(out.end(), pieces.begin(), pieces.end());
}

void encode_text(const WpTokenizer& t, const char* text,
                 std::vector<int32_t>& out) {
  for (const auto& tok : basic_tokenize(t, text)) wordpiece_ids(t, tok, out);
}

}  // namespace

extern "C" {

void* okn_wp_new_from_buffer(const char* buf, int64_t len, int do_lower) {
  auto* t = new WpTokenizer;
  t->do_lower = do_lower != 0;
  std::string line;
  int32_t idx = 0;
  for (int64_t i = 0; i <= len; ++i) {
    if (i == len || buf[i] == '\n') {
      // rstrip("\n") semantics: the line text is everything up to \n
      t->vocab.emplace(line, idx++);
      line.clear();
      if (i == len) break;
    } else {
      line.push_back(buf[i]);
    }
  }
  auto it = t->vocab.find("[UNK]");
  t->unk_id = it == t->vocab.end() ? 0 : it->second;
  return t;
}

void okn_wp_free(void* h) { delete static_cast<WpTokenizer*>(h); }

int64_t okn_wp_vocab_size(void* h) {
  return static_cast<WpTokenizer*>(h)->vocab.size();
}

// Tokenize+encode `text`; writes at most max_out ids. Returns the number of
// ids produced (may exceed max_out to signal truncation).
int64_t okn_wp_encode(void* h, const char* text, int32_t* out_ids,
                      int64_t max_out) {
  auto* t = static_cast<WpTokenizer*>(h);
  std::vector<int32_t> ids;
  encode_text(*t, text, ids);
  int64_t n = static_cast<int64_t>(ids.size());
  std::memcpy(out_ids, ids.data(),
              sizeof(int32_t) * static_cast<size_t>(std::min(n, max_out)));
  return n;
}

// [CLS] a [SEP] (b [SEP]) with longest-first pair truncation and padding
// (tokenization.py:119-138). Buffers must hold max_len entries. Returns the
// unpadded length.
int64_t okn_wp_encode_pair(void* h, const char* text_a, const char* text_b,
                           int64_t max_len, int32_t cls_id, int32_t sep_id,
                           int32_t* ids, int32_t* types, int32_t* mask) {
  auto* t = static_cast<WpTokenizer*>(h);
  if (max_len < 2) return 0;  // no room for even [CLS] [SEP]
  std::vector<int32_t> a, b;
  encode_text(*t, text_a, a);
  if (text_b != nullptr && text_b[0] != '\0') encode_text(*t, text_b, b);
  // like the Python reference, pair mode is decided by the *tokenized*
  // second text (whitespace-only text_b has no second segment)
  bool has_b = !b.empty();
  if (has_b && max_len < 3) { b.clear(); has_b = false; }
  int64_t budget = max_len - (has_b ? 3 : 2);
  while (static_cast<int64_t>(a.size() + b.size()) > budget) {
    if (a.size() > b.size()) a.pop_back(); else b.pop_back();
  }
  // Python re-tests `if tb:` AFTER truncation: a fully-truncated second
  // segment emits no second [SEP] (budget stays the 3-special one)
  if (b.empty()) has_b = false;
  int64_t pos = 0;
  ids[pos] = cls_id; types[pos] = 0; mask[pos] = 1; ++pos;
  for (int32_t v : a) { ids[pos] = v; types[pos] = 0; mask[pos] = 1; ++pos; }
  ids[pos] = sep_id; types[pos] = 0; mask[pos] = 1; ++pos;
  if (has_b) {
    for (int32_t v : b) { ids[pos] = v; types[pos] = 1; mask[pos] = 1; ++pos; }
    ids[pos] = sep_id; types[pos] = 1; mask[pos] = 1; ++pos;
  }
  int64_t used = pos;
  for (; pos < max_len; ++pos) { ids[pos] = 0; types[pos] = 0; mask[pos] = 0; }
  return used;
}

}  // extern "C"
