"""oktopk_tpu — a TPU-native (JAX/XLA/pjit/Pallas) distributed training framework
with the capabilities of Shigangli/Ok-Topk (PPoPP'22, arXiv 2201.07598).

The reference implements sparse gradient allreduce over mpi4py on GPU clusters
(/root/reference/VGG/allreducer.py, LSTM/allreducer.py, BERT/bert/allreducer.py).
This package re-designs the same capability set TPU-first:

- ``comm``        — mesh + typed collective substrate (replaces mpi4py verbs)
- ``ops``         — functional compression kernels (replaces compression.py)
- ``collectives`` — the sparse allreduce algorithms (oktopk + all baselines)
- ``optim``       — distributed optimizers (SGD, BertAdam) as pure grad transforms
- ``models``      — Flax model zoo (VGG/ResNet/LSTM/DeepSpeech/BERT, ...)
- ``data``        — dataset pipelines with distributed sharding
- ``train``       — trainer, metrics, checkpointing (incl. algorithm state)
- ``parallel``    — sequence parallelism (ring attention) and pipeline (GPipe) extensions
"""

__version__ = "0.1.0"

from oktopk_tpu.config import (  # noqa: F401
    CommConfig,
    OkTopkConfig,
    TrainConfig,
)
