"""Per-bucket algorithm/density autotuner.

The sparse collectives only beat dense allreduce in the regime the fabric,
gradient size, and density put them in (PAPERS.md: "On the Utility of
Gradient Compression..." arXiv 2103.00543; SparCML's dynamic sparse/dense
switching, arXiv 1802.08021). The repo holds both halves of the decision —
an analytic α-β cost model (`utils/cost_model.py`) and a per-bucket
registry/trainer (`collectives/registry.py`, `train/trainer.py`) — and this
package connects them: the algorithm choice becomes a measured runtime
decision per gradient bucket instead of a CLI flag.

Pipeline (mirroring the paper's periodic threshold re-estimation cadence):

1. `calibrate`  — fit ICI_ALPHA/ICI_BETA per fabric from a few timed probe
   collectives at startup (least squares on the α-β allreduce law),
   replacing the hard-coded `utils/cost_model.py` constants.
2. `trial`      — time each candidate (algorithm, density) for K steps per
   bucket on-device, reusing `collectives.api.build_allreduce_step`.
3. `policy`     — cost-model prior orders the candidates, trial
   measurements form the posterior; hysteresis + a re-tune period keep
   decisions from thrashing jit recompilation.
4. `journal`    — JSONL decision log (bucket, candidates, predicted vs
   measured ms, chosen algo/density): the observability surface.
"""

from oktopk_tpu.autotune.calibrate import (  # noqa: F401
    FabricCoefficients,
    fit_alpha_beta,
    probe_fabric,
)
from oktopk_tpu.autotune.journal import DecisionJournal, read_journal  # noqa: F401
from oktopk_tpu.autotune.policy import (  # noqa: F401
    Autotuner,
    AutotunePolicy,
    BucketPlan,
    Candidate,
    predict_ms,
)
from oktopk_tpu.autotune.trial import TrialRunner  # noqa: F401
