"""Online α-β fabric calibration.

`utils/cost_model.py` ships Piz Daint-era MPI constants and hand-estimated
ICI ones; neither describes the fabric a run actually lands on (CPU test
mesh, a tunnelled v5e, a future multi-host slice). This module measures it:
time a few dense allreduce probes of increasing size over the real mesh,
then least-squares fit the ring-allreduce α-β law

    t(n) = msgs(P) * α + elems(n, P) * β,
    msgs(P) = 2 (P-1),  elems(n, P) = 2 n (P-1) / P        (P > 1)

which is linear in (α, β). With P == 1 the collective is a no-op and the
probe times only dispatch + memory traffic; the design matrix degenerates
to (1, n) so α absorbs the dispatch floor and β the per-element pass —
exactly the quantities the single-chip cost comparison needs.

The fitted coefficients feed `policy.predict_ms` as the prior over
candidates; they replace (per run, not in source) the ICI_ALPHA/ICI_BETA
defaults, which remain the fallback when probing is disabled.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from oktopk_tpu.utils.cost_model import ICI_ALPHA, ICI_BETA

# Probe sizes: span the bucket sizes real models produce (64k..4M elements
# covers mnistnet through VGG-16 buckets) without making startup slow.
DEFAULT_PROBE_SIZES = (1 << 16, 1 << 18, 1 << 20, 1 << 22)


@dataclasses.dataclass(frozen=True)
class FabricCoefficients:
    """Measured (or default) α-β coefficients for one fabric."""

    alpha: float                   # seconds per message round
    beta: float                    # seconds per element
    source: str = "default"        # "measured" | "default" | "injected"
    nsamples: int = 0
    residual: float = 0.0          # rms relative fit error over the samples

    def as_dict(self):
        return dataclasses.asdict(self)


def default_coefficients() -> FabricCoefficients:
    return FabricCoefficients(alpha=ICI_ALPHA, beta=ICI_BETA,
                              source="default")


def _design_row(n: int, p: int) -> Tuple[float, float]:
    """(α-coefficient, β-coefficient) of one probe in the allreduce law."""
    if p > 1:
        return 2.0 * (p - 1), 2.0 * n * (p - 1) / p
    return 1.0, float(n)


def fit_alpha_beta(sizes: Sequence[int], times_s: Sequence[float],
                   num_workers: int,
                   source: str = "measured") -> FabricCoefficients:
    """Least-squares α-β fit of measured allreduce times.

    ``times_s[i]`` is the per-step time (seconds) of an allreduce over
    ``sizes[i]`` f32 elements on ``num_workers`` workers. Coefficients are
    clamped to a tiny positive floor — a fit driven negative by noise would
    otherwise make every predicted cost meaningless.
    """
    sizes = list(sizes)
    times = np.asarray(list(times_s), np.float64)
    if len(sizes) != len(times) or len(sizes) < 2:
        raise ValueError(
            f"need >= 2 (size, time) samples, got {len(sizes)}/{len(times)}")
    A = np.asarray([_design_row(n, num_workers) for n in sizes], np.float64)
    coef, *_ = np.linalg.lstsq(A, times, rcond=None)
    alpha = float(max(coef[0], 1e-12))
    beta = float(max(coef[1], 1e-15))
    pred = A @ np.asarray([alpha, beta])
    rel = (pred - times) / np.maximum(times, 1e-12)
    return FabricCoefficients(
        alpha=alpha, beta=beta, source=source, nsamples=len(sizes),
        residual=float(np.sqrt(np.mean(rel ** 2))))


def _default_measure(mesh, axis_name: str,
                     repeats: int) -> Callable[[int], Sequence[float]]:
    """Time a real psum over the mesh at size n (median-friendly repeat
    list; each sample synced by a host fetch — the only honest sync point
    through the remote-device tunnel, see bench.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from oktopk_tpu.comm import compat

    p = int(np.prod([mesh.shape[a] for a in (axis_name,)]))

    def measure(n: int) -> Sequence[float]:
        def shard_fn(x):
            return jax.lax.pmean(x, axis_name)

        spec = P(axis_name)
        step = jax.jit(compat.shard_map(
            shard_fn, mesh=mesh, in_specs=(spec,), out_specs=spec,
            check_vma=False))
        x = jnp.zeros((p, n), jnp.float32)
        float(np.asarray(step(x))[0, 0])          # compile + warm
        out = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            y = step(x)
            float(np.asarray(y)[0, 0])
            out.append(time.perf_counter() - t0)
        return out

    return measure


def probe_fabric(mesh=None, axis_name: str = "data",
                 sizes: Sequence[int] = DEFAULT_PROBE_SIZES,
                 repeats: int = 3,
                 measure: Optional[Callable[[int], Sequence[float]]] = None,
                 num_workers: Optional[int] = None) -> FabricCoefficients:
    """Measure the fabric: run probe allreduces and fit α-β.

    ``measure(n) -> [seconds, ...]`` can be injected (tests, or fabrics
    timed elsewhere); the default builds and times a real psum over
    ``mesh``. The median over repeats of each size enters the fit.
    """
    src = "injected"
    if measure is None:
        if mesh is None:
            raise ValueError("probe_fabric needs a mesh or a measure fn")
        num_workers = int(np.prod([mesh.shape[a] for a in (axis_name,)]))
        measure = _default_measure(mesh, axis_name, repeats)
        src = "measured"
    elif num_workers is None:
        raise ValueError("num_workers is required with an injected measure")
    med = [float(np.median(list(measure(n)))) for n in sizes]
    return fit_alpha_beta(sizes, med, num_workers, source=src)
