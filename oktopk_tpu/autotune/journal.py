"""JSONL decision journal — the autotuner's observability surface.

Every calibration and per-bucket decision appends one JSON line, so tuner
quality is auditable after the fact (predicted vs measured ms per
candidate, why a plan was kept or switched). The format is line-delimited
JSON on purpose: it survives crashes mid-run (every line that made it to
disk parses alone) and greps cleanly, like the reference's per-rank
profiling logs (VGG/allreducer.py:702-703) but machine-readable.

Schema — the first record is always an environment header, so decision
logs are comparable across containers/relays (the same tuner on jax
0.4.x/CPU vs 0.9/TPU legitimately decides differently); subsequent
events carry ``event`` and ``step``:

  {"event": "header", "jax": "0.4.37", "jaxlib": "0.4.36",
   "device_kind": "cpu", "platform": "cpu", "world_size": 8}

  {"event": "calibration", "step": 0, "num_workers": 8,
   "alpha": 1.1e-6, "beta": 9.8e-12, "sizes": [...], "times_ms": [...],
   "residual": 0.02, "source": "measured" | "default"}

  {"event": "decision", "step": 0, "bucket": 0, "n": 1182720,
   "num_workers": 8,
   "candidates": [{"algo": "dense", "density": 1.0,
                   "predicted_ms": 3.1, "measured_ms": 2.9}, ...],
   "chosen": {"algo": "oktopk", "density": 0.02},
   "incumbent": {"algo": "dense", "density": 1.0} | null,
   "reason": "trial" | "hold" | "plan"}

``reason`` is "hold" when hysteresis kept the incumbent despite a
challenger measuring faster (within the hysteresis margin), "trial"
otherwise — or "plan" when the tuner ran in fabric-preset plan mode
(no trials; the cost-model prior stood in for the posterior). Plan-mode
decisions additionally carry ``fabric`` and ``num_pods``, and
hierarchical candidates/chosen carry ``outer`` plus a ``levels`` list of
per-level (algorithm, density).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from oktopk_tpu.obs.events import SCHEMA_VERSION

# standalone journal event name -> unified-bus event name. The file
# view keeps its historical "decision" name; the bus renames it so a
# consumer of the unified run journal can tell the streams apart.
_BUS_EVENT_REMAP = {"decision": "autotune_decision"}


def environment_header() -> Dict[str, Any]:
    """The jax/jaxlib/device/world identification every journal leads
    with. Tolerant of an uninitialisable backend (the header must never
    be the reason a journal cannot be written)."""
    import jax

    hdr: Dict[str, Any] = {"jax": jax.__version__,
                           "schema_version": SCHEMA_VERSION}
    try:
        import jaxlib
        hdr["jaxlib"] = getattr(jaxlib, "__version__", None)
    except Exception:
        hdr["jaxlib"] = None
    try:
        devs = jax.devices()
        hdr["device_kind"] = getattr(devs[0], "device_kind",
                                     devs[0].platform)
        hdr["platform"] = devs[0].platform
        hdr["world_size"] = len(devs)
    except Exception:
        hdr.update(device_kind=None, platform=None, world_size=0)
    return hdr


class DecisionJournal:
    """Append-only JSONL writer. ``path=None`` keeps entries in memory only
    (tests, or callers that just want the plan). ``header=True`` writes
    the :func:`environment_header` as the first record.

    With ``bus=`` (an ``obs.journal.EventBus``) every recorded event is
    ALSO forwarded onto the unified run journal's bus — except the
    header, which belongs to this standalone file only (the run journal
    writes exactly one header of its own) — making this file a thin
    view of the unified stream."""

    def __init__(self, path: Optional[str] = None, header: bool = True,
                 bus=None):
        self.path = path
        self.bus = bus
        self.entries: List[Dict[str, Any]] = []
        if path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            # truncate: one journal per tuner lifetime; re-tunes append
            with open(path, "w"):
                pass
        if header:
            self.record("header", **environment_header())

    def record(self, event: str, **fields) -> Dict[str, Any]:
        entry = {"event": event, **fields}
        self.entries.append(entry)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(entry) + "\n")
        if self.bus is not None and event != "header":
            self.bus.emit(_BUS_EVENT_REMAP.get(event, event), **fields)
        return entry


def read_journal(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL journal back into a list of entries."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
