"""Plan selection: cost-model prior -> trial posterior, with hysteresis.

The decision unit is the gradient bucket (`optim.distributed.
bucket_partition`): each bucket independently picks a collective algorithm
and density. Priors come from the α-β cost model with coefficients
calibrated by `autotune.calibrate`; posteriors are the measured trial
step times from `autotune.trial`. The chosen plan only changes when a
challenger beats the incumbent's *fresh* measurement by more than the
hysteresis margin — mirroring the paper's periodic threshold
re-estimation cadence, and keeping borderline buckets from flip-flopping
the jitted train step into recompilation every re-tune.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

from oktopk_tpu.autotune.calibrate import (FabricCoefficients,
                                           default_coefficients)
from oktopk_tpu.autotune.journal import DecisionJournal
from oktopk_tpu.comm.fabric import (PLAN_SELECT_GAMMA, TwoLevelFabric,
                                    resolve_two_level)
from oktopk_tpu.utils.cost_model import (allgather_cost, allreduce_cost,
                                         sparse_allreduce_cost, topk_cost)

# Algorithms whose wire pattern is "local top-k, then allgather the
# winners" — their comm volume scales as kP pairs (logs/algo_sweep.json
# measured 2kP transmitted scalars for topkA), unlike oktopk's balanced
# O(k) two-phase exchange.
_ALLGATHER_FAMILY = ("topkA", "topkA2", "topkAopt", "gtopk", "gaussiank",
                     "gaussiankconcat", "gaussiankSA", "topkSA", "topkDSA")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One (algorithm, density) point in the search space. ``density`` is
    1.0 for dense (ignored by the algorithm, kept for the journal).

    ``algo="hierarchical"`` names the two-level composition
    (collectives/hierarchical.py): dense intra-pod plus ``outer`` (a flat
    registry algorithm) across pods at ``density``. Hierarchical
    candidates are priced by the per-level fabric model and require the
    tuner's ``fabric``/``num_pods`` plan-mode inputs."""

    algo: str
    density: float = 1.0
    outer: Optional[str] = None     # hierarchical only: inter-level algo

    def key(self) -> Tuple[str, float, Optional[str]]:
        return (self.algo, self.density, self.outer)

    def as_dict(self):
        d = {"algo": self.algo, "density": self.density}
        if self.algo == "hierarchical":
            out = self.outer or "oktopk"
            d["outer"] = out
            # the per-level (algorithm, density) plan the journal carries
            d["levels"] = [
                {"level": "intra", "algo": "dense", "density": 1.0},
                {"level": "inter", "algo": out, "density": self.density},
            ]
        return d


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """The tuner's decision for one gradient bucket."""

    bucket: int                  # bucket index (reverse-layer order)
    n: int                       # flat element count of the bucket
    algo: str
    density: float
    predicted_ms: float          # cost-model prior of the chosen candidate
    measured_ms: float           # trial posterior of the chosen candidate
    outer: Optional[str] = None  # hierarchical plans: inter-level algo

    def key(self) -> Tuple[str, float, Optional[str]]:
        return (self.algo, self.density, self.outer)

    def as_dict(self):
        return dataclasses.asdict(self)


def predict_ms(algo: str, density: float, n: int, num_workers: int,
               coeffs: FabricCoefficients, *,
               fabric: Optional[TwoLevelFabric] = None,
               num_pods: Optional[int] = None,
               outer: Optional[str] = None,
               select_gamma: Optional[float] = None) -> float:
    """α-β cost-model prior for one candidate, in milliseconds.

    dense: ring allreduce of n elements. oktopk: local selection +
    the paper's two-phase O(k) exchange. The allgather family: local
    selection + ring allgather of every worker's 2k-scalar (index, value)
    winners. Selection cost uses the sort-free γ·n estimate shared by all
    sparse candidates — the model only needs to rank, the trial phase
    measures.

    ``algo="hierarchical"`` prices the two-level composition per level
    with a :class:`~oktopk_tpu.comm.fabric.TwoLevelFabric`: a dense ring
    allreduce of the pod (``num_workers / num_pods`` members) on the
    intra fabric, plus the flat ``outer`` candidate at ``density`` among
    ``num_pods`` leaders on the inter fabric. When a ``fabric`` is given
    (preset planning, no measured chip), selection is priced with
    ``select_gamma`` — defaulting to ``PLAN_SELECT_GAMMA``, the HBM-class
    element-pass rate — uniformly across candidates so flat and
    hierarchical compete on the same scale.
    """
    a, b = coeffs.alpha, coeffs.beta
    p = max(1, num_workers)
    if select_gamma is None and fabric is not None:
        select_gamma = PLAN_SELECT_GAMMA
    if algo == "hierarchical":
        if fabric is None or num_pods is None:
            raise ValueError(
                "hierarchical candidate needs fabric=TwoLevelFabric and "
                "num_pods (per-level pricing has no single-coeffs form)")
        two = resolve_two_level(fabric)
        pods = max(1, int(num_pods))
        pod = max(1, p // pods)
        t_intra = (allreduce_cost(n, pod, two.intra.alpha_s,
                                  two.intra.beta_elem()) * 1e3
                   if pod > 1 else 0.0)
        return t_intra + predict_ms(outer or "oktopk", density, n, pods,
                                    two.inter.coefficients(),
                                    select_gamma=select_gamma)
    if algo == "dense":
        if p == 1:
            # same degenerate (1, n) law the P=1 calibration fits: alpha
            # is the dispatch floor, beta the per-element memory pass —
            # the ring formula would predict exactly 0 for every n
            from oktopk_tpu.autotune.calibrate import _design_row
            ca, cb = _design_row(n, p)
            return (ca * a + cb * b) * 1e3
        return allreduce_cost(n, p, a, b) * 1e3
    k = max(1, int(density * n))
    sel = topk_cost(n) if select_gamma is None else topk_cost(n, select_gamma)
    if algo == "oktopk":
        return (sel + sparse_allreduce_cost(k, p, a, b)) * 1e3
    if algo in _ALLGATHER_FAMILY:
        return (sel + allgather_cost(2 * k, p, a, b)) * 1e3
    raise ValueError(f"no cost model for algorithm {algo!r}")


@dataclasses.dataclass(frozen=True)
class AutotunePolicy:
    """Decision knobs (see TrainConfig.autotune_* for the CLI surface)."""

    candidates: Tuple[Candidate, ...]
    hysteresis: float = 0.15       # challenger must win by this fraction
    retune_every: int = 0          # steps between re-tunes; 0 = tune once
    max_trials: int = 0            # 0 = trial every candidate; else only
    # the top-``max_trials`` by cost-model prior are measured (prior
    # pruning — the "cost-model prior -> trial posterior" funnel)

    def __post_init__(self):
        if not self.candidates:
            raise ValueError("autotune needs at least one candidate")
        if not 0.0 <= self.hysteresis < 1.0:
            raise ValueError(
                f"hysteresis must be in [0, 1), got {self.hysteresis}")

    def decide(self, bucket: int, n: int, num_workers: int,
               coeffs: FabricCoefficients,
               measure: Optional[Callable[[str, int, float], float]],
               incumbent: Optional[BucketPlan] = None,
               journal: Optional[DecisionJournal] = None,
               step: int = 0,
               fabric: Optional[TwoLevelFabric] = None,
               num_pods: Optional[int] = None,
               select_gamma: Optional[float] = None) -> BucketPlan:
        """Pick the plan for one bucket; journals the full evidence.

        ``measure=None`` is PLAN mode: no trial runs, the cost-model
        prior stands in for the posterior (reason ``"plan"``) — used
        when planning for a target (P, fabric) the current chips cannot
        measure. Hierarchical candidates are always model-priced (a
        flat trial mesh cannot run the two-level composition)."""
        if fabric is not None:
            fabric = resolve_two_level(fabric)

        def _predict(c: Candidate) -> float:
            return predict_ms(c.algo, c.density, n, num_workers, coeffs,
                              fabric=fabric, num_pods=num_pods,
                              outer=c.outer, select_gamma=select_gamma)

        scored = [(_predict(c), c) for c in self.candidates]
        scored.sort(key=lambda pc: pc[0])
        trialed = scored
        if self.max_trials > 0:
            trialed = scored[:self.max_trials]
            # the incumbent is always re-measured: hysteresis compares
            # against its FRESH time, not a stale one
            if incumbent is not None and not any(
                    c.key() == incumbent.key() for _, c in trialed):
                trialed = trialed + [
                    (p, c) for p, c in scored if c.key() == incumbent.key()]

        def _posterior(pred: float, c: Candidate) -> float:
            if measure is None or c.algo == "hierarchical":
                return pred
            return measure(c.algo, n, c.density)

        rows = [{**c.as_dict(), "predicted_ms": pred,
                 "measured_ms": _posterior(pred, c)}
                for pred, c in trialed]
        trialed_keys = {c.key() for _, c in trialed}
        skipped = [{**c.as_dict(), "predicted_ms": pred, "measured_ms": None}
                   for pred, c in scored[len(trialed):]
                   if c.key() not in trialed_keys]
        best = min(rows, key=lambda r: r["measured_ms"])
        reason = "plan" if measure is None else "trial"
        chosen = best
        if incumbent is not None:
            inc_fresh = next(
                (r for r in rows
                 if (r["algo"], r["density"], r.get("outer")) ==
                 incumbent.key()), None)
            if inc_fresh is not None and (
                    best["measured_ms"]
                    >= inc_fresh["measured_ms"] * (1.0 - self.hysteresis)):
                chosen, reason = inc_fresh, "hold"
        plan = BucketPlan(bucket=bucket, n=n, algo=chosen["algo"],
                          density=chosen["density"],
                          predicted_ms=chosen["predicted_ms"],
                          measured_ms=chosen["measured_ms"],
                          outer=chosen.get("outer"))
        if journal is not None:
            chosen_dict = {k: chosen[k]
                           for k in ("algo", "density", "outer", "levels")
                           if k in chosen}
            journal.record(
                "decision", step=step, bucket=bucket, n=n,
                num_workers=num_workers, candidates=rows + skipped,
                chosen=chosen_dict,
                incumbent=(None if incumbent is None else
                           {"algo": incumbent.algo,
                            "density": incumbent.density,
                            **({"outer": incumbent.outer}
                               if incumbent.outer else {})}),
                reason=reason,
                **({"fabric": fabric.name, "num_pods": int(num_pods or 1)}
                   if fabric is not None else {}))
        return plan


def make_candidates(algos: Sequence[str],
                    densities: Sequence[float],
                    hierarchical_outers: Sequence[str] = ()
                    ) -> Tuple[Candidate, ...]:
    """Cross sparse algorithms with the density grid; dense gets the single
    density-1.0 point. ``hierarchical_outers`` adds two-level candidates —
    one per (outer algorithm, density) pair — for plan-mode tuners that
    carry a ``fabric``/``num_pods`` target."""
    out: List[Candidate] = []
    for a in algos:
        if a == "dense":
            out.append(Candidate("dense", 1.0))
        else:
            for d in densities:
                out.append(Candidate(a, float(d)))
    for o in hierarchical_outers:
        if o == "dense":
            out.append(Candidate("hierarchical", 1.0, outer="dense"))
        else:
            for d in densities:
                out.append(Candidate("hierarchical", float(d), outer=o))
    return tuple(out)


class Autotuner:
    """Orchestrates calibrate -> trial -> policy over a bucket list.

    ``bucket_sizes`` are the flat element counts from
    ``optim.distributed.bucket_sizes`` (reverse-layer order, like the
    per-bucket SparseState). The tuner owns the decision journal and the
    current plan list; the trainer consults ``plans`` when (re)building
    its step and calls ``should_retune``/``tune`` on the configured
    cadence.

    ``fabric`` switches the tuner to PLAN mode: a named fabric preset
    (``"dcn"``), a :class:`~oktopk_tpu.comm.fabric.FabricPreset`, or a
    :class:`~oktopk_tpu.comm.fabric.TwoLevelFabric` describing the
    TARGET deployment rather than the chips underfoot. Calibration then
    takes α-β from the preset's inter edge (no probing), trials are
    skipped (``measure=None`` — the prior stands), and hierarchical
    candidates become priceable (``num_pods`` splits ``num_workers``
    into pods). ``runner`` may be ``None`` in plan mode.
    """

    def __init__(self, bucket_sizes: Sequence[int], num_workers: int,
                 policy: AutotunePolicy, runner,
                 coeffs: Optional[FabricCoefficients] = None,
                 journal: Optional[DecisionJournal] = None,
                 calibration_sizes: Optional[Sequence[int]] = None,
                 fabric=None, num_pods: Optional[int] = None):
        self.bucket_sizes = [int(s) for s in bucket_sizes]
        self.num_workers = int(num_workers)
        self.policy = policy
        self.runner = runner
        self.journal = journal if journal is not None else DecisionJournal()
        self.coeffs = coeffs
        self.calibration_sizes = calibration_sizes
        self.fabric: Optional[TwoLevelFabric] = (
            None if fabric is None else resolve_two_level(fabric))
        self.num_pods = None if num_pods is None else int(num_pods)
        if self.fabric is None and runner is None:
            raise ValueError("Autotuner needs a trial runner unless a "
                             "fabric preset puts it in plan mode")
        self.plans: Optional[List[BucketPlan]] = None
        self.last_tune_step: Optional[int] = None

    def calibrate(self, mesh=None, step: int = 0) -> FabricCoefficients:
        """Fit α-β from probe collectives (falls back to the cost-model
        defaults when no mesh is available to probe). In plan mode the
        preset's inter-edge coefficients are used verbatim — the point is
        to price a fabric the current chips cannot exhibit."""
        from oktopk_tpu.autotune.calibrate import (DEFAULT_PROBE_SIZES,
                                                   probe_fabric)

        if self.fabric is not None:
            self.coeffs = self.fabric.inter.coefficients()
        elif mesh is not None:
            sizes = tuple(self.calibration_sizes or DEFAULT_PROBE_SIZES)
            self.coeffs = probe_fabric(mesh, sizes=sizes)
        elif self.coeffs is None:
            self.coeffs = default_coefficients()
        self.journal.record("calibration", step=step,
                            num_workers=self.num_workers,
                            **self.coeffs.as_dict())
        return self.coeffs

    def should_retune(self, step: int) -> bool:
        if self.plans is None:
            return True
        if self.policy.retune_every <= 0:
            return False
        return step - (self.last_tune_step or 0) >= self.policy.retune_every

    def tune(self, step: int = 0, mesh=None) -> List[BucketPlan]:
        """One full trial pass over every bucket. Returns the new plan
        list; ``plans_changed`` against the previous one tells the caller
        whether the train step must be rebuilt."""
        if self.coeffs is None:
            self.calibrate(mesh=mesh, step=step)
        old = self.plans
        plan_mode = self.fabric is not None
        measure = None if plan_mode else self.runner.measure
        self.plans = [
            self.policy.decide(
                bi, n, self.num_workers, self.coeffs, measure,
                incumbent=(old[bi] if old is not None else None),
                journal=self.journal, step=step,
                fabric=self.fabric, num_pods=self.num_pods,
                select_gamma=PLAN_SELECT_GAMMA if plan_mode else None)
            for bi, n in enumerate(self.bucket_sizes)]
        self.last_tune_step = step
        return self.plans

    @staticmethod
    def plans_changed(new: Optional[Sequence[BucketPlan]],
                      old: Optional[Sequence[BucketPlan]]) -> bool:
        if old is None or new is None:
            return old is not new
        return [p.key() for p in new] != [p.key() for p in old]
