"""Plan selection: cost-model prior -> trial posterior, with hysteresis.

The decision unit is the gradient bucket (`optim.distributed.
bucket_partition`): each bucket independently picks a collective algorithm
and density. Priors come from the α-β cost model with coefficients
calibrated by `autotune.calibrate`; posteriors are the measured trial
step times from `autotune.trial`. The chosen plan only changes when a
challenger beats the incumbent's *fresh* measurement by more than the
hysteresis margin — mirroring the paper's periodic threshold
re-estimation cadence, and keeping borderline buckets from flip-flopping
the jitted train step into recompilation every re-tune.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

from oktopk_tpu.autotune.calibrate import (FabricCoefficients,
                                           default_coefficients)
from oktopk_tpu.autotune.journal import DecisionJournal
from oktopk_tpu.utils.cost_model import (allgather_cost, allreduce_cost,
                                         sparse_allreduce_cost, topk_cost)

# Algorithms whose wire pattern is "local top-k, then allgather the
# winners" — their comm volume scales as kP pairs (logs/algo_sweep.json
# measured 2kP transmitted scalars for topkA), unlike oktopk's balanced
# O(k) two-phase exchange.
_ALLGATHER_FAMILY = ("topkA", "topkA2", "topkAopt", "gtopk", "gaussiank",
                     "gaussiankconcat", "gaussiankSA", "topkSA", "topkDSA")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One (algorithm, density) point in the search space. ``density`` is
    1.0 for dense (ignored by the algorithm, kept for the journal)."""

    algo: str
    density: float = 1.0

    def key(self) -> Tuple[str, float]:
        return (self.algo, self.density)

    def as_dict(self):
        return {"algo": self.algo, "density": self.density}


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """The tuner's decision for one gradient bucket."""

    bucket: int                  # bucket index (reverse-layer order)
    n: int                       # flat element count of the bucket
    algo: str
    density: float
    predicted_ms: float          # cost-model prior of the chosen candidate
    measured_ms: float           # trial posterior of the chosen candidate

    def key(self) -> Tuple[str, float]:
        return (self.algo, self.density)

    def as_dict(self):
        return dataclasses.asdict(self)


def predict_ms(algo: str, density: float, n: int, num_workers: int,
               coeffs: FabricCoefficients) -> float:
    """α-β cost-model prior for one candidate, in milliseconds.

    dense: ring allreduce of n elements. oktopk: local selection +
    the paper's two-phase O(k) exchange. The allgather family: local
    selection + ring allgather of every worker's 2k-scalar (index, value)
    winners. Selection cost uses the sort-free γ·n estimate shared by all
    sparse candidates — the model only needs to rank, the trial phase
    measures.
    """
    a, b = coeffs.alpha, coeffs.beta
    p = max(1, num_workers)
    if algo == "dense":
        if p == 1:
            # same degenerate (1, n) law the P=1 calibration fits: alpha
            # is the dispatch floor, beta the per-element memory pass —
            # the ring formula would predict exactly 0 for every n
            from oktopk_tpu.autotune.calibrate import _design_row
            ca, cb = _design_row(n, p)
            return (ca * a + cb * b) * 1e3
        return allreduce_cost(n, p, a, b) * 1e3
    k = max(1, int(density * n))
    sel = topk_cost(n)
    if algo == "oktopk":
        return (sel + sparse_allreduce_cost(k, p, a, b)) * 1e3
    if algo in _ALLGATHER_FAMILY:
        return (sel + allgather_cost(2 * k, p, a, b)) * 1e3
    raise ValueError(f"no cost model for algorithm {algo!r}")


@dataclasses.dataclass(frozen=True)
class AutotunePolicy:
    """Decision knobs (see TrainConfig.autotune_* for the CLI surface)."""

    candidates: Tuple[Candidate, ...]
    hysteresis: float = 0.15       # challenger must win by this fraction
    retune_every: int = 0          # steps between re-tunes; 0 = tune once
    max_trials: int = 0            # 0 = trial every candidate; else only
    # the top-``max_trials`` by cost-model prior are measured (prior
    # pruning — the "cost-model prior -> trial posterior" funnel)

    def __post_init__(self):
        if not self.candidates:
            raise ValueError("autotune needs at least one candidate")
        if not 0.0 <= self.hysteresis < 1.0:
            raise ValueError(
                f"hysteresis must be in [0, 1), got {self.hysteresis}")

    def decide(self, bucket: int, n: int, num_workers: int,
               coeffs: FabricCoefficients,
               measure: Callable[[str, int, float], float],
               incumbent: Optional[BucketPlan] = None,
               journal: Optional[DecisionJournal] = None,
               step: int = 0) -> BucketPlan:
        """Pick the plan for one bucket; journals the full evidence."""
        scored = [(predict_ms(c.algo, c.density, n, num_workers, coeffs), c)
                  for c in self.candidates]
        scored.sort(key=lambda pc: pc[0])
        trialed = scored
        if self.max_trials > 0:
            trialed = scored[:self.max_trials]
            # the incumbent is always re-measured: hysteresis compares
            # against its FRESH time, not a stale one
            if incumbent is not None and not any(
                    c.key() == incumbent.key() for _, c in trialed):
                trialed = trialed + [
                    (p, c) for p, c in scored if c.key() == incumbent.key()]
        rows = [{"algo": c.algo, "density": c.density,
                 "predicted_ms": pred,
                 "measured_ms": measure(c.algo, n, c.density)}
                for pred, c in trialed]
        skipped = [{"algo": c.algo, "density": c.density,
                    "predicted_ms": pred, "measured_ms": None}
                   for pred, c in scored[len(trialed):]
                   if not any(r["algo"] == c.algo
                              and r["density"] == c.density for r in rows)]
        best = min(rows, key=lambda r: r["measured_ms"])
        reason = "trial"
        chosen = best
        if incumbent is not None:
            inc_fresh = next((r for r in rows
                              if (r["algo"], r["density"]) ==
                              incumbent.key()), None)
            if inc_fresh is not None and (
                    best["measured_ms"]
                    >= inc_fresh["measured_ms"] * (1.0 - self.hysteresis)):
                chosen, reason = inc_fresh, "hold"
        plan = BucketPlan(bucket=bucket, n=n, algo=chosen["algo"],
                          density=chosen["density"],
                          predicted_ms=chosen["predicted_ms"],
                          measured_ms=chosen["measured_ms"])
        if journal is not None:
            journal.record(
                "decision", step=step, bucket=bucket, n=n,
                num_workers=num_workers, candidates=rows + skipped,
                chosen={"algo": plan.algo, "density": plan.density},
                incumbent=(None if incumbent is None else
                           {"algo": incumbent.algo,
                            "density": incumbent.density}),
                reason=reason)
        return plan


def make_candidates(algos: Sequence[str],
                    densities: Sequence[float]) -> Tuple[Candidate, ...]:
    """Cross sparse algorithms with the density grid; dense gets the single
    density-1.0 point."""
    out: List[Candidate] = []
    for a in algos:
        if a == "dense":
            out.append(Candidate("dense", 1.0))
        else:
            for d in densities:
                out.append(Candidate(a, float(d)))
    return tuple(out)


class Autotuner:
    """Orchestrates calibrate -> trial -> policy over a bucket list.

    ``bucket_sizes`` are the flat element counts from
    ``optim.distributed.bucket_sizes`` (reverse-layer order, like the
    per-bucket SparseState). The tuner owns the decision journal and the
    current plan list; the trainer consults ``plans`` when (re)building
    its step and calls ``should_retune``/``tune`` on the configured
    cadence.
    """

    def __init__(self, bucket_sizes: Sequence[int], num_workers: int,
                 policy: AutotunePolicy, runner,
                 coeffs: Optional[FabricCoefficients] = None,
                 journal: Optional[DecisionJournal] = None,
                 calibration_sizes: Optional[Sequence[int]] = None):
        self.bucket_sizes = [int(s) for s in bucket_sizes]
        self.num_workers = int(num_workers)
        self.policy = policy
        self.runner = runner
        self.journal = journal if journal is not None else DecisionJournal()
        self.coeffs = coeffs
        self.calibration_sizes = calibration_sizes
        self.plans: Optional[List[BucketPlan]] = None
        self.last_tune_step: Optional[int] = None

    def calibrate(self, mesh=None, step: int = 0) -> FabricCoefficients:
        """Fit α-β from probe collectives (falls back to the cost-model
        defaults when no mesh is available to probe)."""
        from oktopk_tpu.autotune.calibrate import (DEFAULT_PROBE_SIZES,
                                                   probe_fabric)

        if mesh is not None:
            sizes = tuple(self.calibration_sizes or DEFAULT_PROBE_SIZES)
            self.coeffs = probe_fabric(mesh, sizes=sizes)
        elif self.coeffs is None:
            self.coeffs = default_coefficients()
        self.journal.record("calibration", step=step,
                            num_workers=self.num_workers,
                            **self.coeffs.as_dict())
        return self.coeffs

    def should_retune(self, step: int) -> bool:
        if self.plans is None:
            return True
        if self.policy.retune_every <= 0:
            return False
        return step - (self.last_tune_step or 0) >= self.policy.retune_every

    def tune(self, step: int = 0, mesh=None) -> List[BucketPlan]:
        """One full trial pass over every bucket. Returns the new plan
        list; ``plans_changed`` against the previous one tells the caller
        whether the train step must be rebuilt."""
        if self.coeffs is None:
            self.calibrate(mesh=mesh, step=step)
        old = self.plans
        self.plans = [
            self.policy.decide(
                bi, n, self.num_workers, self.coeffs, self.runner.measure,
                incumbent=(old[bi] if old is not None else None),
                journal=self.journal, step=step)
            for bi, n in enumerate(self.bucket_sizes)]
        self.last_tune_step = step
        return self.plans

    @staticmethod
    def plans_changed(new: Optional[Sequence[BucketPlan]],
                      old: Optional[Sequence[BucketPlan]]) -> bool:
        if old is None or new is None:
            return old is not new
        return [p.key() for p in new] != [p.key() for p in old]
