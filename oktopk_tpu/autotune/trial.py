"""Trial phase: time candidate (algorithm, density) pairs on-device.

Each trial builds the same jitted collective program the training step
would run (``collectives.api.build_allreduce_step``) at the bucket's size,
feeds it synthetic N(0,1) gradients, and times K steps via the honest
host-fetch sync (``collectives.api.time_allreduce_step``). The measured
median per-step ms is the policy's posterior over candidates.

Compiled trial programs are memoised per (algo, n, density) — jit is the
expensive part — but every ``measure`` call re-TIMES the cached program,
so a re-tune sees the fabric as it is now, not as it was at startup
(`invalidate()` additionally drops the compiled programs, e.g. after an
elastic resize changes the mesh).

Fake-timing injection (``fake_ms``) replaces the device entirely — the
CPU test suite verifies policy behaviour (crossovers, hysteresis, journal
schema) against a synthetic fabric without a TPU, per the tier-1
``JAX_PLATFORMS=cpu`` contract.
"""

from __future__ import annotations

import statistics
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from oktopk_tpu.config import OkTopkConfig


class TrialRunner:
    """Times candidate collectives over a mesh (or a fake fabric).

    ``fake_ms(algo, n, density) -> ms`` short-circuits the device path.
    ``base_cfg`` carries the algorithm knobs (cadences, wire dtype, ...)
    every trial shares; n/density are overridden per candidate.
    """

    def __init__(self, mesh=None, axis_name: str = "data",
                 trial_steps: int = 3, seed: int = 0,
                 base_cfg: Optional[OkTopkConfig] = None,
                 fake_ms: Optional[Callable[[str, int, float], float]] = None):
        if mesh is None and fake_ms is None:
            raise ValueError("TrialRunner needs a mesh or a fake_ms injector")
        self.mesh = mesh
        self.axis_name = axis_name
        self.trial_steps = max(1, int(trial_steps))
        self.seed = seed
        self.base_cfg = base_cfg or OkTopkConfig()
        self.fake_ms = fake_ms
        self._cache: Dict[Tuple[str, int, float], float] = {}
        self._grads: Dict[int, object] = {}

    @property
    def num_workers(self) -> int:
        if self.mesh is None:
            return self.base_cfg.num_workers or 1
        return int(np.prod([self.mesh.shape[a] for a in (self.axis_name,)]))

    def invalidate(self):
        """Drop memoised compiled programs (e.g. after the mesh changed)."""
        self._cache.clear()
        self._grads.clear()

    def measure(self, algo: str, n: int, density: float) -> float:
        """Median per-step ms of ``algo`` on an n-element bucket."""
        if self.fake_ms is not None:
            return float(self.fake_ms(algo, int(n), float(density)))
        return self._measure_real(algo, int(n), float(density))

    def _bucket_grads(self, n: int):
        import jax.numpy as jnp

        if n not in self._grads:
            rng = np.random.RandomState(self.seed)
            self._grads[n] = jnp.asarray(
                rng.randn(self.num_workers, n).astype(np.float32))
        return self._grads[n]

    def _measure_real(self, algo: str, n: int, density: float) -> float:
        from oktopk_tpu.collectives.api import (batched_init_state,
                                                build_allreduce_step,
                                                time_allreduce_step)

        # dense ignores density; pin it so the program cache key is shared
        # across whatever densities the candidate list carries
        d = 1.0 if algo == "dense" else density
        key = (algo, n, d)
        if key not in self._cache:
            cfg = self.base_cfg.replace(
                n=n, num_workers=self.num_workers, density=min(d, 1.0),
                warmup_steps=0, density_schedule=None)
            step = build_allreduce_step(algo, cfg, self.mesh,
                                        axis_name=self.axis_name,
                                        warmup=False)
            self._cache[key] = (step, batched_init_state(cfg))
        step, state = self._cache[key]
        times_ms, _ = time_allreduce_step(step, state=state,
                                          grads=self._bucket_grads(n),
                                          iters=self.trial_steps)
        return float(statistics.median(times_ms))
