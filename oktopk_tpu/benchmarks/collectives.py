"""Standalone sparse-allreduce micro-benchmark.

Reference C26 analogue: ``benchmark_gtopk_sparse_allreduce``
(VGG/allreducer.py:1649-1677, run as ``python -m mpi4py allreducer.py`` on
random 25M-float tensors) and the two-process collective timing scripts
under BERT/tests/communication/.

Usage:
    python -m oktopk_tpu.benchmarks.collectives --algo oktopk --n 1048576 \\
        --density 0.01 --steps 10 [--fake-devices 8]

Prints per-step wall time, comm volume, and EPS vs dense.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--algo", default="oktopk")
    p.add_argument("--n", type=int, default=1 << 20)
    p.add_argument("--density", type=float, default=0.01)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--fake-devices", type=int, default=0)
    p.add_argument("--local-recompute-every", type=int, default=1)
    p.add_argument("--global-recompute-every", type=int, default=4)
    args = p.parse_args(argv)

    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}")
    import jax
    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from oktopk_tpu.collectives.api import (
        batched_init_state, build_allreduce_step, eps_vs_dense)
    from oktopk_tpu.comm.mesh import get_mesh
    from oktopk_tpu.config import OkTopkConfig

    P = len(jax.devices())
    mesh = get_mesh((P,), ("data",))
    cfg = OkTopkConfig(
        n=args.n, num_workers=P, density=args.density, warmup_steps=0,
        local_recompute_every=args.local_recompute_every,
        global_recompute_every=args.global_recompute_every)
    step = build_allreduce_step(args.algo, cfg, mesh, warmup=False)
    state = batched_init_state(cfg)

    rng = np.random.RandomState(0)
    base = rng.randn(P, args.n).astype(np.float32)
    grads = jnp.asarray(base)
    out, state = step(grads, state)           # compile
    jax.block_until_ready(out)
    print(f"algo={args.algo} n={args.n} P={P} k={cfg.k} "
          f"device={jax.devices()[0].platform}")
    for i in range(args.steps):
        grads = jnp.asarray(
            base + 0.3 * rng.randn(P, args.n).astype(np.float32))
        t0 = time.time()
        out, state = step(grads, state)
        jax.block_until_ready(out)
        dt = time.time() - t0
        eps = float(eps_vs_dense(jnp.mean(grads, 0), out[0]))
        print(f"step {i}: {dt * 1e3:8.2f} ms  "
              f"volume {float(state.last_volume[0]):10.0f} elems  "
              f"eps_vs_dense {eps:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
