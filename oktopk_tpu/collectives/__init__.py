"""Sparse allreduce algorithms (the reference's allreducer.py, TPU-native).

Each algorithm is a pure jittable function
``(grad: f32[n], state: SparseState, cfg: OkTopkConfig) -> (f32[n], SparseState)``
meant to run *per-shard* inside ``shard_map`` over the ``data`` mesh axis —
the direct analogue of the reference's per-rank ``AllReducer.run`` body
(VGG/allreducer.py:549) with MPI verbs replaced by XLA collectives.

Algorithm census (reference names, SURVEY.md §2 C1/C2):

==============  ====================================================
``dense``       plain psum mean (VGG/allreducer.py:175-180)
``topkA``       fixed-k allgather (VGG/allreducer.py:34-69)
``topkA2``      topkA + re-top-k after reduce (VGG/allreducer.py:519-525)
``topkAopt``    threshold-based allgather variant (VGG/allreducer.py:1100-1151)
``gtopk``       recursive-halving tree merge (VGG/allreducer.py:76-172)
``gaussiank``   Gaussian-threshold allgather (VGG/allreducer.py:1420-1465)
``gaussiankconcat``  packed single-buffer variant (VGG/allreducer.py:1467-1501)
``gaussiankSA`` ring reduce-scatter variant (VGG/allreducer.py:1503-1620)
``topkSA``      static-region split-allreduce ("topkDSA")
                (VGG/allreducer.py:1153-1357)
``oktopk``      the paper's two-phase algorithm (VGG/allreducer.py:575-1098)
==============  ====================================================
"""

from oktopk_tpu.collectives.state import SparseState, init_state  # noqa: F401
from oktopk_tpu.collectives.registry import get_algorithm, ALGORITHMS  # noqa: F401
