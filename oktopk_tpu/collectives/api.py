"""Host-level entry points: run a sparse allreduce over a device mesh.

The per-shard algorithm functions (this package) correspond to the body the
reference runs on every MPI rank; this module is the analogue of wiring them
into the process world — except the "world" is a ``jax.sharding.Mesh`` and the
wiring is ``shard_map`` + jit. Also provides the EPS-vs-dense equivalence
harness mirroring the reference's PROFILING_NORM measurement
(VGG/allreducer.py:584-606,1072-1080: EPS = ‖dense−sparse‖₂/‖dense‖₂).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.collectives.hierarchical import HierarchicalConfig
from oktopk_tpu.collectives.registry import get_algorithm
from oktopk_tpu.collectives.state import SparseState, init_state
from oktopk_tpu.comm import compat
from oktopk_tpu.config import OkTopkConfig


def batched_init_state(cfg, dtype=jnp.float32) -> SparseState:
    """Per-worker state stacked on a leading device axis [P, ...] so it can be
    sharded over the data axis (each worker owns its residual/thresholds,
    as each rank does in the reference).

    A :class:`HierarchicalConfig` is accepted too: the state is the OUTER
    level's (residual/thresholds live among pod leaders only) replicated
    across all ``num_pods * pod_size`` worker rows — each pod's members
    carry identical copies, mirroring the leader-replication the
    emulated exchange performs."""
    base = cfg.outer_cfg if isinstance(cfg, HierarchicalConfig) else cfg
    s = init_state(base, dtype)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (cfg.num_workers,) + x.shape), s)


def _hierarchical_setup(name: str, cfg, mesh, warmup: bool):
    """Shared validation/normalisation for the hierarchical build paths:
    returns ``(cfg, spec)`` with pallas resolved on the outer config and
    the shard spec covering (inter, intra) on the leading grad axis."""
    from oktopk_tpu.ops.compaction import resolve_use_pallas
    if name != "hierarchical":
        raise ValueError(
            f"config is a HierarchicalConfig but algorithm is {name!r}; "
            "pass name='hierarchical' (outer algorithm goes in cfg.outer)")
    if not isinstance(cfg, HierarchicalConfig):
        raise TypeError(
            f"build step for {name!r} needs a HierarchicalConfig "
            "(collectives.hierarchical.make_hierarchical_config), got "
            f"{type(cfg).__name__}")
    for ax, want in ((cfg.inter_axis, cfg.num_pods),
                     (cfg.intra_axis, cfg.pod_size)):
        have = dict(zip(mesh.axis_names, mesh.devices.shape)).get(ax)
        if have != want:
            raise ValueError(
                f"mesh axis {ax!r} has size {have}, config wants {want} "
                f"(mesh axes {dict(zip(mesh.axis_names, mesh.devices.shape))})")
    cfg = cfg.replace(outer_cfg=resolve_use_pallas(cfg.outer_cfg, mesh),
                      outer_warmup=warmup)
    return cfg, P((cfg.inter_axis, cfg.intra_axis))


def build_allreduce_step(name: str, cfg, mesh: Mesh,
                         axis_name: str = "data", warmup: bool = True,
                         check_vma: bool = True, donate_state: bool = False):
    """jit-compiled ``(grads [P, n], state) -> (results [P, n], state)``.

    ``results`` is the same reduced vector replicated per worker row (every
    rank gets the full result, as after the reference's allgather phase).

    ``cfg`` is an ``OkTopkConfig`` for the flat algorithms, or a
    ``HierarchicalConfig`` with ``name="hierarchical"`` — then ``mesh``
    must be two-level (comm.mesh.hierarchical_mesh) and grads'/state's
    leading [P] axis is sharded over (inter, intra); ``axis_name`` is
    ignored (both axes come from the config).

    ``check_vma=False`` disables shard_map's varying-axes tracking — needed
    when running the Pallas selection kernel through its interpreter on a
    CPU mesh (the interpreter cannot mix VMA-tracked operands).

    ``donate_state=True`` donates the state argument's buffers to the call,
    letting XLA write the new residual (and the oktopk phase-(a) ``reduced``
    scratch) into the old residual's n-length allocation instead of
    materialising a second dense buffer. Opt-in because a donated state is
    consumed: callers that re-use one state across calls — e.g. the
    profiling loops in scripts/profile_step.py — must leave it off, while
    the train-loop pattern ``out, state = step(g, state)`` is safe.
    """
    from oktopk_tpu.ops.compaction import resolve_use_pallas
    if name == "hierarchical" or isinstance(cfg, HierarchicalConfig):
        # two-level path: spec covers (inter, intra) on the leading grad
        # axis; warmup is composed on the OUTER level (registry.py)
        cfg, spec = _hierarchical_setup(name, cfg, mesh, warmup)
        algo, axis_arg = get_algorithm("hierarchical", warmup=False), None
    else:
        cfg = resolve_use_pallas(cfg, mesh)
        algo, axis_arg = get_algorithm(name, warmup=warmup), axis_name
        spec = P(axis_name)

    def shard_fn(g, s):
        g1 = g[0]
        s1 = jax.tree.map(lambda x: x[0], s)
        out, s2 = algo(g1, s1, cfg, axis_arg)
        return out[None], jax.tree.map(lambda x: x[None], s2)

    mapped = compat.shard_map(shard_fn, mesh=mesh,
                              in_specs=(spec, spec), out_specs=(spec, spec),
                              check_vma=check_vma)
    if donate_state:
        return jax.jit(mapped, donate_argnums=(1,))
    return jax.jit(mapped)


def build_quality_allreduce_step(name: str, cfg, mesh: Mesh,
                                 quality, axis_name: str = "data",
                                 warmup: bool = True,
                                 check_vma: bool = True):
    """``build_allreduce_step`` plus the in-jit signal-fidelity tap:
    ``(grads [P, n], state, qbuf) -> (results, state, qbuf)``.

    ``quality`` is an ``obs.quality.QualityConfig``; ``qbuf`` a batched
    ``obs.metrics_buffer.QualityBuffer`` ([P, ...] leaves, e.g. from
    broadcasting ``init_buffer`` like :func:`batched_init_state` does).
    The tap is the EXACT code path the trainer threads through
    ``optim.build_sparse_grad_step`` — same ``measure_bucket``, same
    ring commit — so the dense-vs-sparse oracle tests
    (tests/test_quality.py) validate what training runs journal, not a
    reimplementation."""
    from oktopk_tpu.obs.quality import commit, measure_bucket
    from oktopk_tpu.ops.compaction import resolve_use_pallas
    from jax import lax
    hier = name == "hierarchical" or isinstance(cfg, HierarchicalConfig)
    if hier:
        cfg, spec = _hierarchical_setup(name, cfg, mesh, warmup)
        algo, axis_arg = get_algorithm("hierarchical", warmup=False), None
    else:
        cfg = resolve_use_pallas(cfg, mesh)
        algo, axis_arg = get_algorithm(name, warmup=warmup), axis_name
        spec = P(axis_name)
    del quality  # static config lives in the buffer's shapes

    def shard_fn(g, s, q):
        g1 = g[0]
        s1 = jax.tree.map(lambda x: x[0], s)
        q1 = jax.tree.map(lambda x: x[0], q)
        out, s2 = algo(g1, s1, cfg, axis_arg)
        if hier:
            # the intra psum is lossless, so the fidelity oracle is the
            # unchanged pre-selection dense gradient: the full-world mean
            # of grad plus the (pod-level) error-feedback residual
            dense = lax.pmean(
                lax.pmean(g1, cfg.intra_axis) + s1.residual, cfg.inter_axis)
        else:
            dense = lax.pmean(g1 + s1.residual, axis_name)
        scalars = measure_bucket(out, dense, s2, q1.prev_sig,
                                 q1.prev_res_norm)
        q2 = commit(q1, s2.step, scalars, jnp.asarray(False))
        return (out[None], jax.tree.map(lambda x: x[None], s2),
                jax.tree.map(lambda x: x[None], q2))

    mapped = compat.shard_map(shard_fn, mesh=mesh,
                              in_specs=(spec, spec, spec),
                              out_specs=(spec, spec, spec),
                              check_vma=check_vma)
    return jax.jit(mapped)


def time_allreduce_step(step_fn, grads, state, iters: int = 3,
                        warmup_iters: int = 1):
    """Honest per-step wall times of a ``build_allreduce_step`` program.

    The autotuner's trial phase (autotune/trial.py) needs step times it can
    compare across algorithms; each timed call ends with a host fetch of
    one result scalar — through the remote-device tunnel
    ``block_until_ready`` can return before execution finishes, so the
    fetch is the only honest synchronization point (see bench.py).

    Returns ``(times_ms, state)`` with ``len(times_ms) == iters``;
    ``warmup_iters`` untimed calls first absorb compilation.
    """
    import time

    import numpy as np

    for _ in range(warmup_iters):
        out, state = step_fn(grads, state)
        float(np.asarray(out[0, 0]))
    times_ms = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out, state = step_fn(grads, state)
        float(np.asarray(out[0, 0]))
        times_ms.append((time.perf_counter() - t0) * 1e3)
    return times_ms, state


@partial(jax.jit, static_argnames=())
def eps_vs_dense(dense_result: jnp.ndarray, sparse_result: jnp.ndarray):
    """EPS = ‖dense − sparse‖₂ / ‖dense‖₂ (reference VGG/allreducer.py:1072-1080)."""
    num = jnp.linalg.norm(dense_result - sparse_result)
    den = jnp.linalg.norm(dense_result) + 1e-12
    return num / den
