"""Dense allreduce baseline + the shared warmup wrapper.

Reference: the ``dense`` compressor branch (VGG/allreducer.py:175-180,532-547)
and the dense-allreduce warmup that every sparse algorithm starts with
(512 iters for VGG, VGG/allreducer.py:573; 128 for LSTM; disabled for BERT).
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
from jax import lax

from oktopk_tpu.collectives.state import SparseState, bump
from oktopk_tpu.collectives.wire import dense_wire_bytes
from oktopk_tpu.comm.primitives import pvary_like
from oktopk_tpu.config import OkTopkConfig
from oktopk_tpu.obs.anatomy import phase_scope


def dense_allreduce(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
                    axis_name: str = "data"):
    """psum-mean over the data axis (ring allreduce moves ~2n per worker)."""
    with phase_scope("exchange", cfg.bucket_index):
        out = lax.pmean(grad, axis_name)
    out, state = pvary_like(
        (out, bump(state, volume=2.0 * cfg.n,
                   wire_bytes=dense_wire_bytes(2.0 * cfg.n),
                   local_count=cfg.n, global_count=cfg.n)), grad)
    return out, state


def with_warmup(algo_fn):
    """Run dense allreduce for the first ``cfg.warmup_steps`` steps, then the
    sparse algorithm (reference VGG/allreducer.py:573-574). Both branches are
    traced with identical shapes, as ``lax.cond`` requires."""

    def wrapped(grad, state, cfg: OkTopkConfig, axis_name: str = "data"):
        if cfg.warmup_steps <= 0:
            return algo_fn(grad, state, cfg, axis_name)
        return lax.cond(
            state.step < cfg.warmup_steps,
            partial(dense_allreduce, cfg=cfg, axis_name=axis_name),
            partial(algo_fn, cfg=cfg, axis_name=axis_name),
            grad, state,
        )

    wrapped.__name__ = f"warmup({getattr(algo_fn, '__name__', 'algo')})"
    return wrapped
