"""gaussiank family: Gaussian-threshold allgather allreduces.

Reference: ``gaussiank`` (VGG/allreducer.py:1420-1465), ``gaussiankconcat``
(VGG/allreducer.py:1467-1501). The point of the family is to avoid exact
top-k entirely: the threshold comes from a normal fit + bounded refinement
each step (ops/gaussian.py), so there is never an O(n log n) sort.

``gaussiankconcat`` differs from ``gaussiank`` only in wire layout (one packed
[indexes‖values] buffer instead of two Allgatherv calls). On TPU both are one
``all_gather`` of a fixed-capacity triple — same compiled program — so the
registry maps both names to this function; the distinction is kept only for
flag parity with the reference.
"""

from __future__ import annotations

import jax.numpy as jnp

from oktopk_tpu.collectives.state import SparseState, bump
from oktopk_tpu.comm import all_gather, psum
from oktopk_tpu.config import OkTopkConfig
from oktopk_tpu.obs.anatomy import phase_scope
from oktopk_tpu.ops import gaussian_threshold, scatter_sparse, select_by_threshold
from oktopk_tpu.ops.residual import add_residual
from oktopk_tpu.collectives.wire import (
    on_wire,
    pair_wire_bytes,
    residual_after_selection,
)


def gaussian_k(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
               axis_name: str = "data"):
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    cap = cfg.cap_local
    bkt = cfg.bucket_index
    with phase_scope("select", bkt):
        acc = add_residual(grad, state.residual)

        t = gaussian_threshold(acc, k,
                               cfg.gaussian_refine_iters).astype(acc.dtype)
    with phase_scope("stage", bkt):
        vals, idx, count = select_by_threshold(
            acc, t, cap, use_pallas=bool(cfg.use_pallas))
        packed_mask = jnp.zeros((n,), bool).at[idx].set(True, mode="drop")
        residual = residual_after_selection(acc, packed_mask, cfg)

    with phase_scope("exchange", bkt):
        gv = all_gather(on_wire(vals, cfg, state.step),
                        axis_name).astype(acc.dtype)
        gi = all_gather(idx, axis_name)
    with phase_scope("combine", bkt):
        result = scatter_sparse(n, gv, gi) / P

    total = psum(count, axis_name)
    return result, bump(state, volume=2.0 * total,
                        wire_bytes=pair_wire_bytes(total, cfg),
                        residual=residual,
                        local_threshold=t,
                        local_count=count, global_count=total)
