"""gTop-k: recursive-halving tree merge of top-k pairs.

Reference: ``gtopk_sparse_allreduce`` (VGG/allreducer.py:76-172), from the
gTop-k SGD paper. The reference does log2(P) rounds of paired Send/Recv where
the receiver merges two k-sparse lists and re-selects top-k, then rank 0
Bcasts the final result (:162).

TPU form: a symmetric butterfly — every round exchanges with the partner at
XOR distance d via ``ppermute`` and *both* sides merge, so after log2(P)
rounds every worker already holds the identical global result and the final
Bcast disappears. Merging two k-sparse lists is a scatter-add into a dense
staging vector followed by ``lax.top_k`` (duplicate indices sum, as in the
reference's merge at :130-140).

Volume: 2k scalars sent + 2k received per round × log2(P) rounds.
Requires P to be a power of two (the reference's recursive halving does too).
"""

from __future__ import annotations

import jax.numpy as jnp

from oktopk_tpu.collectives.state import SparseState, bump
from oktopk_tpu.comm.primitives import ppermute_pair
from oktopk_tpu.config import OkTopkConfig
from oktopk_tpu.obs.anatomy import phase_scope
from oktopk_tpu.ops import exact_topk, scatter_sparse
from oktopk_tpu.ops.residual import add_residual
from oktopk_tpu.collectives.wire import (
    on_wire,
    pair_wire_bytes,
    residual_after_selection,
    wire_round,
)


def gtopk(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
          axis_name: str = "data"):
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    if P & (P - 1):
        raise ValueError(f"gtopk requires power-of-two workers, got {P}")
    bkt = cfg.bucket_index
    with phase_scope("select", bkt):
        acc = add_residual(grad, state.residual)
        vals, idx = exact_topk(acc, k)
        sel_mask = jnp.zeros((n,), bool).at[idx].set(True)
        residual = residual_after_selection(acc, sel_mask, cfg)

    rounds = P.bit_length() - 1
    d = 1
    for _ in range(rounds):
        # round own values through the wire dtype BEFORE merging so both
        # partners merge identical multisets — otherwise each rank would
        # combine its own f32 values with the partner's rounded ones and
        # the all-ranks-identical-result invariant breaks. The first
        # round's loss is captured by the selection residual above;
        # later rounds re-round merged sums (collectives/wire.py).
        with phase_scope("exchange", bkt):
            vals = wire_round(vals, cfg)
            pv = ppermute_pair(on_wire(vals, cfg, state.step), axis_name,
                               d).astype(acc.dtype)  # vals already rounded
            pi = ppermute_pair(idx, axis_name, d)
        with phase_scope("combine", bkt):
            merged = scatter_sparse(n, jnp.concatenate([vals, pv]),
                                    jnp.concatenate([idx, pi]))
            vals, idx = exact_topk(merged, k)
        d <<= 1

    # Merge losers return to error feedback: the reference's caller keeps
    # every originally-selected value whose index did NOT survive the
    # global re-selection (``included_indexes`` from
    # VGG/allreducer.py:171-172, consumed by ``add_residuals`` at
    # :1406-1411 — residual clears only at selected-AND-won slots).
    # Dropping them loses ~(P-1)/P of the selected gradient mass per step
    # and stalls convergence (observed: mnistnet stuck at chance).
    with phase_scope("combine", bkt):
        winner_mask = jnp.zeros((n,), bool).at[idx].set(True)
        lost = sel_mask & ~winner_mask
        residual = jnp.where(lost, acc, residual)

        result = scatter_sparse(n, vals, idx) / P
    vol = 4.0 * k * rounds
    return result, bump(state, volume=vol,
                        wire_bytes=pair_wire_bytes(2.0 * k * rounds, cfg),
                        residual=residual,
                        local_count=k, global_count=k)
