"""Two-level hierarchical sparse allreduce: dense intra-pod, sparse
inter-pod.

docs/PERF.md's crossover analysis shows sparse collectives only win
where per-worker bandwidth collapses (DCN-spanning multi-pod data
parallelism, ~2.1-2.4 GB/s/worker); inside a pod the 100 GB/s ICI ring
makes dense psum the optimum. SparCML's hierarchical sparse-streaming
allreduce over heterogeneous fabrics (arXiv 1802.08021) is the
blueprint: reduce densely over the fast local links, run the sparse
exchange only across the slow edge, broadcast the result back down.

This module is a *composition over the registry*, not a tenth monolith:

    hierarchical(grad) = broadcast_intra(outer_algo(pmean_intra(grad)))

- **intra level (level 0)**: dense ``pmean`` over ``intra_axis`` — the
  pod-mean gradient, lossless (so the quality oracle is unchanged:
  comp_err still measures compression against the pre-selection dense
  gradient).
- **inter level (level 1)**: any registry algorithm (``outer``:
  "dense", "oktopk", "topkA", ...) over ``inter_axis`` with
  ``outer_cfg`` (``num_workers == num_pods``). All ``SparseState``
  (residual, thresholds, wire accounting) lives here — the intra psum
  has no error feedback to keep.
- **broadcast**: free by construction under shard_map emulation — after
  the intra pmean every pod member holds identical data, so every
  member runs the identical inter exchange and already holds the
  result. On a real two-fabric slice the inter collective would be
  gated to one leader per pod and the result broadcast over ICI; the
  wire accounting below prices that leader pattern (one inter exchange
  per pod), which is also what each emulated member measures.

Wire bytes are tracked PER LEVEL (``SparseState.wire_bytes_intra`` /
``wire_bytes_inter``) so the DCN edge — the scarce resource — is priced
separately; ``obs/volume.py`` holds each level against its own analytic
budget (intra: dense ring 2n(P_pod-1)/P_pod values; inter: the outer
algorithm's existing budget at P=num_pods).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
from jax import lax

from oktopk_tpu.collectives.state import SparseState
from oktopk_tpu.collectives.wire import dense_wire_bytes
from oktopk_tpu.comm.mesh import DATA_AXIS, POD_AXIS
from oktopk_tpu.comm.primitives import pvary_like
from oktopk_tpu.config import OkTopkConfig
from oktopk_tpu.obs.anatomy import phase_scope


@dataclasses.dataclass(frozen=True)
class HierarchicalConfig:
    """Static configuration of the two-level composition.

    Wraps the OUTER algorithm's :class:`OkTopkConfig` (``outer_cfg``,
    with ``num_workers == num_pods`` and the inter-level density) plus
    the topology and axis names. Hashable and static under jit, like
    ``OkTopkConfig``. Build via :func:`make_hierarchical_config`, which
    derives ``outer_cfg`` from a flat config by splitting the density
    budget per level.
    """

    outer_cfg: OkTopkConfig
    num_pods: int = 1
    pod_size: int = 1
    inner: str = "dense"            # intra-level algorithm (dense only)
    outer: str = "oktopk"           # inter-level registry algorithm
    inter_axis: str = POD_AXIS      # mesh axis crossing pods (slow edge)
    intra_axis: str = DATA_AXIS     # mesh axis within a pod (fast edge)
    outer_warmup: bool = True       # wrap the outer algo in dense warmup
    # Share of the end-to-end density budget granted to the inter level.
    # The intra psum is dense (lossless), so the full budget (1.0) goes
    # to the inter exchange by default; < 1.0 reserves headroom.
    density_split: float = 1.0

    def __post_init__(self):
        if self.num_pods < 1 or self.pod_size < 1:
            raise ValueError("need num_pods >= 1 and pod_size >= 1, got "
                             f"{self.num_pods}x{self.pod_size}")
        if self.inner != "dense":
            raise ValueError(
                f"inner level supports only 'dense' (got {self.inner!r}); "
                "the intra-pod fabric is where dense is already optimal")
        if self.outer_cfg.num_workers != self.num_pods:
            raise ValueError(
                f"outer_cfg.num_workers ({self.outer_cfg.num_workers}) "
                f"must equal num_pods ({self.num_pods})")
        if self.inter_axis == self.intra_axis:
            raise ValueError("inter_axis and intra_axis must differ, got "
                             f"{self.inter_axis!r} twice")
        if not 0.0 < self.density_split <= 1.0:
            raise ValueError(
                f"density_split must be in (0, 1], got {self.density_split}")

    # Flat-config conveniences so generic machinery (batched_init_state,
    # obs/volume.volume_report) can read the combined geometry.
    @property
    def n(self) -> int:
        return self.outer_cfg.n

    @property
    def num_workers(self) -> int:
        """Total world size across both levels."""
        return self.num_pods * self.pod_size

    @property
    def density(self) -> float:
        """End-to-end delivered density = the inter level's density
        (the intra psum is lossless)."""
        return self.outer_cfg.density

    def replace(self, **kw) -> "HierarchicalConfig":
        return dataclasses.replace(self, **kw)

    def level_plan(self):
        """The per-level (algorithm, density) plan — what autotune
        decisions journal and bench records carry."""
        return [
            {"level": "intra", "algo": self.inner, "density": 1.0},
            {"level": "inter", "algo": self.outer,
             "density": self.outer_cfg.density},
        ]


def make_hierarchical_config(cfg: OkTopkConfig, num_pods: int,
                             pod_size: Optional[int] = None, *,
                             inner: str = "dense", outer: str = "oktopk",
                             density_split: float = 1.0,
                             inter_axis: str = POD_AXIS,
                             intra_axis: str = DATA_AXIS,
                             ) -> HierarchicalConfig:
    """Derive a :class:`HierarchicalConfig` from a FLAT config.

    ``cfg`` describes the flat world (``num_workers`` = total workers,
    ``density`` = end-to-end budget); the outer config inherits every
    algorithm knob but runs at ``num_workers=num_pods`` with
    ``density * density_split`` (dense outer keeps density 1.0).
    """
    if pod_size is None:
        if cfg.num_workers % num_pods:
            raise ValueError(f"num_workers ({cfg.num_workers}) not "
                             f"divisible by num_pods ({num_pods})")
        pod_size = cfg.num_workers // num_pods
    if num_pods * pod_size != cfg.num_workers:
        raise ValueError(
            f"num_pods*pod_size ({num_pods}x{pod_size}) must equal "
            f"cfg.num_workers ({cfg.num_workers})")
    outer_density = 1.0 if outer == "dense" else cfg.density * density_split
    outer_cfg = cfg.replace(num_workers=num_pods, density=outer_density)
    return HierarchicalConfig(outer_cfg=outer_cfg, num_pods=num_pods,
                              pod_size=pod_size, inner=inner, outer=outer,
                              inter_axis=inter_axis, intra_axis=intra_axis,
                              density_split=density_split)


def hierarchical(grad: jnp.ndarray, state: SparseState,
                 cfg: HierarchicalConfig, axis_name: Optional[str] = None):
    """The two-level collective body, shard_map'd over a (pod, data)
    mesh by ``collectives/api.build_allreduce_step``.

    ``axis_name`` is accepted for registry-signature compatibility and
    must be None or ``cfg.inter_axis`` — the axes in play come from the
    config (two of them, which the flat signature cannot carry).
    """
    from oktopk_tpu.collectives.registry import get_algorithm
    if axis_name is not None and axis_name != cfg.inter_axis:
        raise ValueError(
            f"hierarchical runs over cfg axes ({cfg.inter_axis!r}, "
            f"{cfg.intra_axis!r}); got axis_name={axis_name!r}")
    ocfg = cfg.outer_cfg
    bkt = ocfg.bucket_index

    # level 0 — dense pmean down the intra axis (ICI): the pod-mean
    # gradient, identical on every pod member afterwards.
    with phase_scope("exchange", bkt, level=0):
        g_pod = lax.pmean(grad, cfg.intra_axis)

    # level 1 — the outer registry algorithm among pod leaders (DCN).
    # Each pod member traces the identical exchange on identical inputs,
    # which is the emulation of leader-exchange + intra broadcast.
    outer_fn = get_algorithm(cfg.outer, warmup=cfg.outer_warmup)
    with phase_scope(None, bkt, level=1):
        out, s2 = outer_fn(g_pod, state, ocfg, cfg.inter_axis)

    # Per-level accounting. The outer algorithm's bump() already added
    # its own (inter) bytes/volume on top of ``state``; fold the intra
    # ring allreduce on top and split the ledgers.
    pod = cfg.pod_size
    intra_vals = 2.0 * ocfg.n * (pod - 1) / max(1, pod)
    intra_wb = dense_wire_bytes(intra_vals)
    inter_wb = s2.last_wire_bytes
    s2 = s2.replace(
        volume_elems=s2.volume_elems + intra_vals,
        last_volume=s2.last_volume + intra_vals,
        wire_bytes=s2.wire_bytes + intra_wb,
        last_wire_bytes=s2.last_wire_bytes + intra_wb,
        wire_bytes_intra=state.wire_bytes_intra + intra_wb,
        last_wire_bytes_intra=jnp.asarray(intra_wb, jnp.float32),
        wire_bytes_inter=state.wire_bytes_inter + inter_wb,
        last_wire_bytes_inter=inter_wb,
    )
    # Align the VMA of the (replicated-over-intra) results back to the
    # full two-axis variance of the inputs so out_specs over both mesh
    # axes type-check under check_vma.
    return pvary_like((out, s2), grad)
