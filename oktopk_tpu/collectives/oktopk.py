"""Ok-Topk: the paper's two-phase O(6k) sparse allreduce, TPU-native.

Reference: the oktopk branch of ``AllReducer.run`` (VGG/allreducer.py:575-1098;
call-stack walkthrough in SURVEY.md §3.2). Phase (a) is a reduce-scatter-like
exchange into per-worker *load-balanced regions*; phase (b) allgathers each
region's globally-selected winners. Thresholds are predicted (multiplicative
adaptation) and only recomputed exactly every ``*_recompute_every`` steps;
regions are repartitioned from local top-k index density every
``repartition_every`` steps.

TPU-first mapping (SURVEY.md §5.8, §7.3):
- the throttled tagged Isend/Irecv rounds (reference :672-794) collapse into
  ONE ``lax.all_to_all`` over fixed-capacity [P, cap] buffers — the rotated
  dst/src schedule, the size Alltoall (:708) and the chunked overlap logic all
  vanish (XLA pipelines the collective with surrounding compute);
- ``torch.split`` by data-dependent boundaries (:667-670) becomes region-id
  masks + one packing scatter (ops/select.pack_by_region) — shapes stay
  static;
- the two ``Allgatherv`` calls (:819,1031) become ``lax.all_gather`` of
  fixed-capacity triples;
- the boundary-averaging ``MPI.Allreduce`` (:638) is a tiny ``psum``;
- iteration-dependent control flow (recompute vs predict) is ``lax.cond`` on
  the step counter carried in SparseState — both branches same shapes.

Communication volume (analytic, tracked in SparseState): phase (a) sends
~2k and receives ~2k (balanced regions), phase (b) sends ~2k/P and receives
~2k(P-1)/P — total < 6k scalars per worker per step, the paper's headline
(reference README.md:2).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from oktopk_tpu.collectives.state import SparseState, bump
from oktopk_tpu.comm import all_gather, all_to_all, axis_rank, psum
from oktopk_tpu.obs.anatomy import phase_scope
from oktopk_tpu.comm.primitives import pvary_like
from oktopk_tpu.config import OkTopkConfig, scheduled_k
from oktopk_tpu.ops import (
    pack_by_region,
    scatter_sparse,
    select_by_threshold,
    select_mask,
)
from oktopk_tpu.ops.topk import k2threshold_method
from oktopk_tpu.ops.hist_threshold import (
    hist_to_threshold,
    k2threshold_hist,
    log2_hist,
)
from oktopk_tpu.ops.fused_select import (
    fused_pack_finalize,
    fused_select_stage,
)
from oktopk_tpu.ops.residual import add_residual
from oktopk_tpu.collectives.wire import (
    on_wire as _on_wire,
    pair_wire_bytes,
    residual_after_winners,
)


def _target_k(k, n: int, factor: float):
    """The controller setpoint ``factor * k`` as a selection count —
    python int for a static k (the "sort" threshold method needs it
    static), traced otherwise. Full-density operation (k == n) must stay
    exactly dense, so the sub-k setpoint applies only when genuinely
    sparse."""
    if isinstance(k, int):
        return k if k >= n else max(1, int(round(factor * k)))
    kk = jnp.maximum(1, jnp.round(factor * k)).astype(jnp.int32)
    return jnp.where(k >= n, k, kk)


def _newton_adapt(thresh, count, count_probe, k, cfg: OkTopkConfig,
                  band_hi=None, target=None):
    """Threshold feedback toward the [band_lo*k, band_hi*k] count band.

    The reference nudges +-1.2% per step (VGG/allreducer.py:696-699,
    :1054-1057), which cannot re-enter the band within a recompute window
    once drift or a bad prediction pushes counts far out; a fixed
    proportional gain is miscalibrated because the count-threshold slope
    depends on the (changing) tail shape. So: measure the slope with a
    second count at ``thresh * probe_ratio`` — it fuses into the same
    reduction pass over the data, zero extra communication beyond widening
    an existing psum — and take one Newton step on the log-log curve:

        slope = dlog(count)/dlog(t),   t *= (count/k)^(-1/slope)

    Inside the band the threshold is left alone (dead zone, as the
    reference); per-step correction is clamped to ``adapt_max_step``."""
    c = jnp.maximum(count, 1).astype(jnp.float32)
    cp = jnp.maximum(count_probe, 1).astype(jnp.float32)
    slope = (jnp.log(cp) - jnp.log(c)) / jnp.log(cfg.probe_ratio)
    exponent = jnp.clip(-1.0 / jnp.minimum(slope, -0.5),
                        cfg.newton_exp_lo, cfg.newton_exp_hi)
    # corrections aim at the setpoint (<= k); the dead zone stays defined
    # by the reference band around k, so in-band counts are never touched
    corr = (c / (k if target is None else target)) ** exponent
    corr = jnp.clip(corr, 1.0 / cfg.adapt_max_step, cfg.adapt_max_step)
    hi = cfg.band_hi if band_hi is None else band_hi
    in_band = (count >= cfg.band_lo * k) & (count <= hi * k)
    return jnp.where(in_band, thresh, thresh * corr.astype(thresh.dtype))


def _repartition(abs_acc, local_thresh, cfg: OkTopkConfig, axis_name: str):
    """Load-balanced region boundaries from local selection density.

    The reference takes equal-count quantiles of its own top-k indices and
    averages the boundaries across workers with an MPI.Allreduce
    (VGG/allreducer.py:626-654). Here: cumulative hit count -> searchsorted
    quantile cut points -> psum-mean -> monotonic int offsets. Invariant
    preserved: boundaries[0] == 0, boundaries[-1] == n (the reference asserts
    sum(region sizes) == n at :648).
    """
    P, n = cfg.num_workers, cfg.n
    mask = abs_acc >= local_thresh
    csum = jnp.cumsum(mask.astype(jnp.int32))
    total = csum[-1]
    targets = (jnp.arange(1, P) * total).astype(jnp.float32) / P
    interior = jnp.searchsorted(
        csum.astype(jnp.float32), targets, side="left").astype(jnp.float32)
    avg = psum(interior, axis_name) / P
    interior_i = jnp.clip(jnp.round(avg).astype(jnp.int32), 0, n)
    interior_i = jnp.sort(interior_i)
    out = jnp.concatenate([
        jnp.zeros((1,), jnp.int32), interior_i,
        jnp.full((1,), n, jnp.int32)])
    # psum output is replication-invariant; the carried boundaries are
    # per-shard ("varying") under shard_map's VMA tracking — align them.
    return pvary_like(out, abs_acc)


def oktopk(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
           axis_name: str = "data"):
    P, n = cfg.num_workers, cfg.n
    # With a density_schedule, k is a traced scalar of the step counter:
    # the threshold controller chases the scheduled target while every
    # fixed-capacity buffer stays sized by the max density (config.py).
    k = scheduled_k(cfg, state.step)
    rank = axis_rank(axis_name)
    up = bool(cfg.use_pallas)
    bkt = cfg.bucket_index   # anatomy scope names carry the bucket id
    hist_mode = cfg.threshold_method == "hist"
    # Fused selection front-end (ops/fused_select.py): ONE Pallas sweep
    # over (grad, residual) yields acc, the staging rows, the realised and
    # Newton-probe counts, and the threshold histogram — replacing the
    # separate add_residual / abs / mask / count / probe / pack passes
    # below. The unfused path stays as the bit-parity oracle
    # (tests/test_fused_select.py) and bench.py's degradation rung
    # (cfg.fuse_select=False -> `oktopk_fused_failed`).
    fuse = (up and cfg.fuse_select is not False
            and grad.dtype == jnp.float32)
    if not fuse:
        with phase_scope("select", bkt):
            acc = add_residual(grad, state.residual)
            abs_acc = jnp.abs(acc)

    def _abs_acc_branch():
        # fused steps carry no precomputed |acc| buffer; the rare branches
        # that need one (exact bisect recompute, first-sparse hist prime)
        # recompute it inside their cond — bit-identical values, and the
        # extra sweeps price only the steps that take the branch
        return jnp.abs(add_residual(grad, state.residual)) if fuse \
            else abs_acc

    # The reference's warmup length is a multiple of the recompute cadence
    # (512 % 32 == 0, VGG/allreducer.py:573,577) so its first sparse step
    # always recomputes exactly; we make that explicit so any warmup length
    # is safe (predicted thresholds start at 0 and would select everything).
    first_sparse = state.step == cfg.warmup_steps
    recompute_local = (state.step % cfg.local_recompute_every == 0) | first_sparse
    recompute_global = (state.step % cfg.global_recompute_every == 0) | first_sparse

    # ---- local threshold: exact every local_recompute_every, else predicted
    # (reference VGG/allreducer.py:593 vs :696-699). "Exact" uses the
    # sort-free bisection by default (cfg.threshold_method).
    #
    # Drift tracking: under error feedback at low density the unselected
    # mass — and with it the selection threshold — grows every step; the
    # reference's fixed +-1.2% band nudges cannot follow it at cadence 32.
    # Each exact recompute therefore also measures the realised per-step
    # growth rate over the elapsed window, and predicted steps multiply
    # BOTH thresholds by that rate — "prediction instead of recomputation"
    # (VGG/allreducer.py:593) applied to the drift as well as the level.
    prev_lt = state.local_threshold
    tkl = _target_k(k, n, cfg.local_k_target)

    if hist_mode:
        # LAGGED exact recompute (config.threshold_method="hist"): every
        # step selects with the carried drift-predicted threshold; the
        # exact level is read off the histogram this same selection pass
        # emits (zero extra passes fused, one standalone) and becomes
        # lt_next in the controller block below — next step's
        # ``prev_lt * drift`` compensates the one step of staleness. Only
        # the first sparse step, which has no carried threshold yet, pays
        # a standalone one-pass histogram prime inside the cond.
        def lt_prime():
            return k2threshold_hist(_abs_acc_branch(),
                                    tkl).astype(grad.dtype)

        with phase_scope("select", bkt):
            lt = lax.cond(first_sparse, lt_prime,
                          lambda: prev_lt * state.drift)
        drift = state.drift   # re-measured from the histogram below
    else:
        def lt_exact():
            # exact recompute lands the count at the local setpoint (<= k,
            # inside the reference band) rather than exactly k: phase-(a)
            # volume is 4*count*(P-1)/P, so the setpoint directly buys
            # budget margin at the same nominal density
            lt_new = k2threshold_method(_abs_acc_branch(), tkl,
                                        cfg.threshold_method,
                                        cfg.bisect_iters).astype(grad.dtype)
            # drift measured between consecutive *exact* thresholds (the
            # running predicted one is polluted by the controller's own
            # corrections), as a per-step rate over the elapsed window
            gap = max(1, cfg.local_recompute_every)
            base_lt = state.last_exact_lt
            ratio = jnp.where((lt_new > 0) & (base_lt > 0),
                              lt_new / jnp.maximum(base_lt, 1e-30), 1.0)
            per_step = jnp.clip(ratio ** (1.0 / gap),
                                cfg.drift_clip_lo, cfg.drift_clip_hi)
            # EMA over recompute windows damps oscillation; the first exact
            # recompute has no meaningful baseline -> keep drift
            mixed = ((1.0 - cfg.drift_ema) * state.drift
                     + cfg.drift_ema * per_step)
            drift_new = jnp.where(base_lt > 0, mixed, state.drift)
            return lt_new, drift_new.astype(grad.dtype), lt_new

        def lt_predicted():
            return prev_lt * state.drift, state.drift, state.last_exact_lt

        with phase_scope("select", bkt):
            lt, drift, last_exact_lt = lax.cond(recompute_local, lt_exact,
                                                lt_predicted)

    # ---- phase (a): select, exchange to region owners, scatter-add reduce.
    # Region repartition every repartition_every steps (reference
    # :626-654); the fused kernel is region-blind (regions are assigned in
    # its cap-scale finalize), so on fused steps the boundaries can be
    # computed from the kernel's own acc output in between stage and
    # finalize — repartition's extra |acc| sweep prices only its cadence.
    repart = (state.step % cfg.repartition_every == 0) | first_sparse
    if fuse:
        with phase_scope("select", bkt):
            st = fused_select_stage(grad, state.residual, lt,
                                    lt * cfg.probe_ratio)
            acc = st.acc
        with phase_scope("stage", bkt):
            boundaries = lax.cond(
                repart,
                lambda: _repartition(jnp.abs(acc), lt, cfg, axis_name),
                lambda: state.boundaries)
            s_vals, s_idx, s_counts = fused_pack_finalize(
                st, boundaries, P, cfg.cap_pair)
        local_count = st.local_count
        local_probe = st.probe_count
        hist = st.hist
        # only the bf16 wire's residual path reads the sent mask; it fuses
        # into the single consumer pass over acc at the bottom (and is
        # DCE'd entirely under the f32 wire). The kernel's own staging
        # mask clamps the threshold to min-normal f32 (ops/compaction.py
        # _prep) — identical whenever lt is normal, i.e. every step after
        # the first exact recompute.
        mask = jnp.abs(acc) >= lt
    else:
        with phase_scope("stage", bkt):
            boundaries = lax.cond(
                repart,
                lambda: _repartition(abs_acc, lt, cfg, axis_name),
                lambda: state.boundaries)
        with phase_scope("select", bkt):
            mask = abs_acc >= lt
            local_count = jnp.sum(mask)
        with phase_scope("stage", bkt):
            s_vals, s_idx, s_counts = pack_by_region(
                acc, mask, boundaries, P, cfg.cap_pair, thresh=lt,
                use_pallas=up)
        # threshold feedback probe (fuses into the same pass over abs_acc)
        with phase_scope("select", bkt):
            local_probe = jnp.sum(abs_acc >= lt * cfg.probe_ratio)
        # "hist" standalone pays its one histogram pass lazily, inside the
        # recompute cond below (the fused kernel emits it for free)
        hist = None
    with phase_scope("exchange", bkt):
        r_vals = all_to_all(_on_wire(s_vals, cfg, state.step), axis_name) \
            .astype(acc.dtype)                 # [P, cap_pair]
        r_idx = all_to_all(s_idx, axis_name)
    with phase_scope("combine", bkt):
        reduced = scatter_sparse(n, r_vals, r_idx)  # own region only

    # Wire volume: the capped buffers bound what is actually sent (elements
    # beyond cap stay in the residual) — unlike the reference, whose MPI
    # sends are unbounded when counts drift above band between recomputes.
    sent_count = jnp.sum(s_counts)
    recv_count = jnp.sum(r_idx < n)
    own_count = s_counts[rank]
    vol_a = 2.0 * (sent_count - own_count) + 2.0 * (recv_count - own_count)

    # ---- local threshold feedback for the next step
    if hist_mode:
        def lt_measured():
            # lagged exact recompute: adopt the k-th-value level read from
            # this step's histogram, and re-measure the drift rate against
            # the previous exact level (same machinery as lt_exact above).
            # Unfused steps build the histogram here, inside the branch —
            # integer counts, bit-identical to the kernel's
            h = hist if hist is not None else log2_hist(acc)
            lt_new = hist_to_threshold(h, tkl).astype(grad.dtype)
            gap = max(1, cfg.local_recompute_every)
            base_lt = state.last_exact_lt
            ratio = jnp.where((lt_new > 0) & (base_lt > 0),
                              lt_new / jnp.maximum(base_lt, 1e-30), 1.0)
            per_step = jnp.clip(ratio ** (1.0 / gap),
                                cfg.drift_clip_lo, cfg.drift_clip_hi)
            mixed = ((1.0 - cfg.drift_ema) * state.drift
                     + cfg.drift_ema * per_step)
            drift_new = jnp.where(base_lt > 0, mixed, state.drift)
            return lt_new, drift_new.astype(grad.dtype), lt_new

        def lt_adapted():
            return (_newton_adapt(lt, local_count, local_probe, k, cfg,
                                  target=tkl),
                    state.drift, state.last_exact_lt)

        with phase_scope("select", bkt):
            lt_next, drift, last_exact_lt = lax.cond(recompute_local,
                                                     lt_measured, lt_adapted)
    else:
        with phase_scope("select", bkt):
            lt_next = _newton_adapt(lt, local_count, local_probe, k, cfg,
                                    target=tkl)

    # ---- phase (b): global winner selection + allgather.
    cap_g = cfg.cap_gather
    k_cand = min(cfg.cap_exact, n)

    def exact_branch():
        # Every global_recompute_every steps the reference gathers all
        # nonzeros of the reduced region and takes an exact global top-k
        # (VGG/allreducer.py:819-846) — unbounded on the wire. TPU form:
        # each region contributes its top cap_exact ~ 4k/P candidates
        # (load-balanced regions hold ~k/P global winners each — the
        # balance the repartition maintains is exactly what makes the
        # paper's volume O(k), not O(kP)) selected by a sort-free
        # per-region threshold; the k-th value of the gathered pool becomes
        # the new global threshold. No O(n log n) sort anywhere.
        with phase_scope("select", bkt):
            t_cand = k2threshold_method(jnp.abs(reduced), k_cand,
                                        cfg.threshold_method,
                                        cfg.bisect_iters)
            if up:
                # the kernel's min-normal clamp already excludes zeros
                vals, idx, cand_count = select_by_threshold(
                    reduced, t_cand, k_cand, use_pallas=True)
            else:
                cand_mask = (jnp.abs(reduced) >= t_cand) & (reduced != 0.0)
                vals, idx, cand_count = select_mask(reduced, cand_mask,
                                                    k_cand)
        with phase_scope("exchange", bkt):
            gv = all_gather(_on_wire(vals, cfg, state.step), axis_name) \
                .astype(acc.dtype)                     # [P, k_cand]
            gi = all_gather(idx, axis_name)
        # Python min when k is static (the "sort" method needs it so);
        # a scheduled k is traced, and the schedule guarantees "bisect"
        # (count-based, traced-k-capable)
        k_pool = (min(k, P * k_cand) if isinstance(k, int)
                  else jnp.minimum(k, P * k_cand))
        with phase_scope("select", bkt):
            gt = k2threshold_method(jnp.abs(gv).reshape(-1), k_pool,
                                    cfg.threshold_method,
                                    cfg.bisect_iters).astype(acc.dtype)
            keep = (jnp.abs(gv) >= gt) & (gi < n)
        # values pre-divided by P at cap scale: every gathered index is
        # unique (regions are disjoint and each worker's winners are
        # deduplicated), so scatter(gv / P) == scatter(gv) / P bit-for-bit
        # — and the old dense n-scale division pass disappears
        with phase_scope("combine", bkt):
            result = scatter_sparse(n, jnp.where(keep, gv, 0.0) / P,
                                    jnp.where(keep, gi, n))
        g_count = jnp.sum(keep)
        total_c = psum(cand_count, axis_name)
        vol = 2.0 * cand_count + 2.0 * (total_c - cand_count)
        return pvary_like((result, gt, g_count, vol), acc)

    def predicted_branch():
        # Otherwise: threshold-select own region, fixed-capacity allgather,
        # rebuild, adapt the global threshold (reference :894,1031-1057).
        # The reference predicts the next global threshold by multiplicative
        # count feedback alone, which assumes a near-stationary gradient
        # distribution; here gt additionally rides the measured per-step
        # drift rate (see the local-threshold block above) at zero comm
        # cost.
        gt_use = state.global_threshold * drift
        with phase_scope("select", bkt):
            gvals, gidx, gcount = select_by_threshold(reduced, gt_use,
                                                      cap_g, use_pallas=up)
        with phase_scope("exchange", bkt):
            gv = all_gather(_on_wire(gvals, cfg, state.step), axis_name) \
                .astype(acc.dtype)                     # [P, cap_g]
            gi = all_gather(gidx, axis_name)
        with phase_scope("combine", bkt):
            result = scatter_sparse(n, gv / P, gi)  # pre-divided
            # (see exact_branch)
        # Newton probe count rides the same psum as the realised count —
        # one 2-vector allreduce (the reference pays a full size-exchange
        # Allgather for less information, VGG/allreducer.py:807)
        probe_c = jnp.sum((jnp.abs(reduced) >= gt_use * cfg.probe_ratio)
                          & (reduced != 0.0))
        totals = psum(jnp.stack([gcount, probe_c]).astype(jnp.float32),
                      axis_name)
        total_g = totals[0].astype(jnp.int32)
        gt_next = _newton_adapt(gt_use, total_g, totals[1].astype(jnp.int32),
                                k, cfg, band_hi=cfg.band_hi_global,
                                target=_target_k(k, n, cfg.global_k_target))
        vol = 2.0 * gcount + 2.0 * (total_g - gcount)
        return pvary_like((result, gt_next, total_g, vol), acc)

    result, gt_next, g_count, vol_b = lax.cond(
        recompute_global, exact_branch, predicted_branch)

    # ---- residual: zero only at indices that made the global result
    # (reference VGG/allreducer.py:1051-1052); under the bf16 wire the
    # rounding errors stay in the residual (collectives/wire.py).
    # With the phase-(b) values pre-divided at cap scale, the old
    # result/P + winner_mask + residual trio collapses into ONE consumer
    # pass over (result, acc, reduced) — the last n-scale sweep of the
    # step (docs/PERF.md "selection hot path").
    with phase_scope("combine", bkt):
        winner_mask = result != 0.0
        residual = residual_after_winners(acc, winner_mask, mask, reduced,
                                          cfg)

    # Both phases move (index, value) pairs and count volume as scalars
    # (2 per pair), so the realised wire bytes follow from the same
    # counts — the measured side of the paper's 6k-scalar budget.
    wb = pair_wire_bytes(0.5 * (vol_a + vol_b), cfg)

    return result, bump(state, volume=vol_a + vol_b, wire_bytes=wb,
                        residual=residual,
                        local_threshold=lt_next, global_threshold=gt_next,
                        boundaries=boundaries, drift=drift,
                        last_exact_lt=last_exact_lt,
                        local_count=local_count, global_count=g_count)
