"""Algorithm registry (reference ``compressors`` dict,
VGG/compression.py:512-523, + the ``--compressor`` dispatch in
``AllReducer.run``, VGG/allreducer.py:481-547)."""

from __future__ import annotations

from oktopk_tpu.collectives.dense import dense_allreduce, with_warmup
from oktopk_tpu.collectives.gaussiank import gaussian_k
from oktopk_tpu.collectives.gtopk import gtopk
from oktopk_tpu.collectives.oktopk import oktopk
from oktopk_tpu.collectives.topk_allgather import topk_a, topk_a2, topk_a_opt
from oktopk_tpu.collectives.topk_sa import gaussian_k_sa, topk_sa

ALGORITHMS = {
    "dense": dense_allreduce,
    "topkA": topk_a,
    "topkA2": topk_a2,
    "topkAopt": topk_a_opt,
    "gtopk": gtopk,
    "gaussiank": gaussian_k,
    # Same compiled program on TPU; see gaussiank.py docstring.
    "gaussiankconcat": gaussian_k,
    "gaussiankSA": gaussian_k_sa,
    "topkSA": topk_sa,
    # Script alias used by the reference job files (e.g. lstm_topkdsa.sh).
    "topkDSA": topk_sa,
    "oktopk": oktopk,
}


def get_algorithm(name: str, warmup: bool = True):
    """Look up an algorithm; ``warmup=True`` wraps it with the dense warmup
    the reference applies to every sparse run (VGG/allreducer.py:573)."""
    try:
        fn = ALGORITHMS[name]
    except KeyError:
        raise ValueError(
            f"unknown compressor {name!r}; available: {sorted(ALGORITHMS)}")
    if warmup and name != "dense":
        fn = with_warmup(fn)
    return fn
