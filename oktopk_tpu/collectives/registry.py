"""Algorithm registry (reference ``compressors`` dict,
VGG/compression.py:512-523, + the ``--compressor`` dispatch in
``AllReducer.run``, VGG/allreducer.py:481-547)."""

from __future__ import annotations

from oktopk_tpu.collectives.dense import dense_allreduce, with_warmup
from oktopk_tpu.collectives.gaussiank import gaussian_k
from oktopk_tpu.collectives.gtopk import gtopk
from oktopk_tpu.collectives.hierarchical import hierarchical
from oktopk_tpu.collectives.oktopk import oktopk
from oktopk_tpu.collectives.topk_allgather import topk_a, topk_a2, topk_a_opt
from oktopk_tpu.collectives.topk_sa import gaussian_k_sa, topk_sa

ALGORITHMS = {
    "dense": dense_allreduce,
    "topkA": topk_a,
    "topkA2": topk_a2,
    "topkAopt": topk_a_opt,
    "gtopk": gtopk,
    "gaussiank": gaussian_k,
    # Same compiled program on TPU; see gaussiank.py docstring.
    "gaussiankconcat": gaussian_k,
    "gaussiankSA": gaussian_k_sa,
    "topkSA": topk_sa,
    # Script alias used by the reference job files (e.g. lstm_topkdsa.sh).
    "topkDSA": topk_sa,
    "oktopk": oktopk,
    # Two-level composition (collectives/hierarchical.py): dense psum
    # intra-pod, any of the above inter-pod. Takes a HierarchicalConfig
    # and a (pod, data) mesh — build via api.build_allreduce_step.
    "hierarchical": hierarchical,
}


def list_algorithms():
    """Sorted registry listing (the names ``get_algorithm`` accepts)."""
    return sorted(ALGORITHMS)


def get_algorithm(name: str, warmup: bool = True):
    """Look up an algorithm; ``warmup=True`` wraps it with the dense warmup
    the reference applies to every sparse run (VGG/allreducer.py:573)."""
    try:
        fn = ALGORITHMS[name]
    except KeyError:
        raise ValueError(
            f"unknown compressor {name!r}; available: {list_algorithms()}")
    if warmup and name not in ("dense", "hierarchical"):
        # hierarchical handles warmup on its OUTER level (the dense-outer
        # warmup branch composed with the always-dense intra psum IS the
        # full dense warmup); wrapping here would need a flat axis name.
        fn = with_warmup(fn)
    return fn
