"""Per-bucket algorithm state.

The reference's AllReducer holds this state as instance dicts keyed by bucket
name: allreduce counter, local/global thresholds, region boundaries/offsets
(VGG/allreducer.py:240-244). Here it is an explicit pytree threaded through
the jitted step — which makes it checkpointable (the reference never saves
residuals or thresholds; resume silently resets error feedback, SURVEY.md
§5.4) and makes every per-step quantity observable, including the analytic
communication volume counters that reproduce the paper's <6k claim without
reading XLA internals (SURVEY.md §7.3.7).
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from oktopk_tpu.config import OkTopkConfig


@flax.struct.dataclass
class SparseState:
    step: jnp.ndarray                 # i32 — allreduce counter
    local_threshold: jnp.ndarray      # f32 — predicted local sel. threshold
    global_threshold: jnp.ndarray     # f32 — predicted global sel. threshold
    # Estimated per-step multiplicative growth of the selection threshold,
    # measured between consecutive exact local recomputes (collectives/
    # oktopk.py). Under error feedback at low density the unselected mass
    # grows every step, so thresholds must ride that drift between
    # recomputes — the reference's fixed +-1.2% band nudges
    # (VGG/allreducer.py:696-699) cannot track it.
    drift: jnp.ndarray                # f32 — ~1.0
    # The threshold measured at the last *exact* local recompute — the
    # clean baseline for the next drift measurement (the running predicted
    # threshold is polluted by the controller's own corrections).
    last_exact_lt: jnp.ndarray        # f32

    boundaries: jnp.ndarray           # i32[P+1] — region offsets, [0..n]
    residual: jnp.ndarray             # f32[n] — error-feedback buffer
    # Analytic comm-volume accounting (elements sent by this worker):
    volume_elems: jnp.ndarray         # f32 — cumulative over all steps
    last_volume: jnp.ndarray          # f32 — last step only
    # Wire-level byte accounting (obs/volume.py): realised payload bytes
    # crossing the collectives for this worker, wire-dtype-aware (bf16
    # pairs are 6 bytes, f32 pairs 8, dense psum values 4). Unlike
    # volume_elems — scalars in the paper's counting — these are the
    # bytes the conformance checker holds against each algorithm's
    # analytic budget. Threaded as traced values so lax.cond branches
    # (dense fallbacks, exact recomputes) account what actually ran.
    wire_bytes: jnp.ndarray           # f32 — cumulative over all steps
    last_wire_bytes: jnp.ndarray      # f32 — last step only
    # Per-level wire accounting (collectives/hierarchical.py): bytes on
    # the fast intra-pod edge vs the scarce inter-pod edge, so the DCN
    # link is priced separately (obs/volume.py hierarchical budgets).
    # Flat single-level algorithms leave all four at zero;
    # wire_bytes == wire_bytes_intra + wire_bytes_inter when hierarchical.
    wire_bytes_intra: jnp.ndarray      # f32 — cumulative, intra level
    last_wire_bytes_intra: jnp.ndarray  # f32 — last step only
    wire_bytes_inter: jnp.ndarray      # f32 — cumulative, inter level
    last_wire_bytes_inter: jnp.ndarray  # f32 — last step only
    # realised selected counts (observability; reference logs these under
    # settings.PROFILING, VGG/allreducer.py:702-703)
    last_local_count: jnp.ndarray     # i32
    last_global_count: jnp.ndarray    # i32


def init_state(cfg: OkTopkConfig, dtype=jnp.float32) -> SparseState:
    """Fresh state: equal static region split (the reference starts from an
    even split too, VGG/allreducer.py:240-244), zero thresholds (first step
    always takes the exact-recompute branch since step % every == 0)."""
    P, n = cfg.num_workers, cfg.n
    base, rem = divmod(n, P)
    sizes = jnp.asarray([base + (1 if i < rem else 0) for i in range(P)],
                        jnp.int32)
    boundaries = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(sizes)])
    return SparseState(
        step=jnp.asarray(0, jnp.int32),
        local_threshold=jnp.asarray(0.0, dtype),
        global_threshold=jnp.asarray(0.0, dtype),
        drift=jnp.asarray(1.0, dtype),
        last_exact_lt=jnp.asarray(0.0, dtype),
        boundaries=boundaries,
        residual=jnp.zeros((n,), dtype),
        volume_elems=jnp.asarray(0.0, jnp.float32),
        last_volume=jnp.asarray(0.0, jnp.float32),
        wire_bytes=jnp.asarray(0.0, jnp.float32),
        last_wire_bytes=jnp.asarray(0.0, jnp.float32),
        wire_bytes_intra=jnp.asarray(0.0, jnp.float32),
        last_wire_bytes_intra=jnp.asarray(0.0, jnp.float32),
        wire_bytes_inter=jnp.asarray(0.0, jnp.float32),
        last_wire_bytes_inter=jnp.asarray(0.0, jnp.float32),
        last_local_count=jnp.asarray(0, jnp.int32),
        last_global_count=jnp.asarray(0, jnp.int32),
    )


def bump(state: SparseState, *, volume, wire_bytes=None, local_count=None,
         global_count=None, **updates) -> SparseState:
    """Advance the step counter and record per-step accounting.

    ``wire_bytes`` is the step's realised wire-level byte count (None —
    external callers predating the counter — records 0 for the step)."""
    vol = jnp.asarray(volume, jnp.float32)
    wb = jnp.asarray(0.0 if wire_bytes is None else wire_bytes, jnp.float32)
    kw = dict(
        step=state.step + 1,
        volume_elems=state.volume_elems + vol,
        last_volume=vol,
        wire_bytes=state.wire_bytes + wb,
        last_wire_bytes=wb,
    )
    if local_count is not None:
        kw["last_local_count"] = jnp.asarray(local_count, jnp.int32)
    if global_count is not None:
        kw["last_global_count"] = jnp.asarray(global_count, jnp.int32)
    kw.update(updates)
    return state.replace(**kw)
