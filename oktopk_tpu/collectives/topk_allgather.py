"""topkA family: allgather-based sparse allreduces.

Reference: ``topk_sparse_allreduce`` (VGG/allreducer.py:34-69) selected by the
``topkA``/``topkA2`` compressor names (dispatch at VGG/allreducer.py:481-530),
and the threshold-based ``topkAopt`` variant (VGG/allreducer.py:1100-1151).

TPU design notes: the reference gathers ragged (values, indexes) with
``Allgatherv``; here topkA/topkA2 gather exactly-k buffers (naturally static)
and topkAopt gathers fixed-capacity triples (ops/select.py). The scatter-add
rebuild (reference ``decompress``/dense fill) is one ``.at[].add`` under XLA.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from oktopk_tpu.collectives.state import SparseState, bump
from oktopk_tpu.comm import all_gather, psum
from oktopk_tpu.obs.anatomy import phase_scope
from oktopk_tpu.config import OkTopkConfig
from oktopk_tpu.ops import (
    exact_topk,
    scatter_sparse,
    select_by_threshold,
)
from oktopk_tpu.ops.topk import k2threshold_method
from oktopk_tpu.ops.residual import add_residual
from oktopk_tpu.collectives.wire import (
    on_wire,
    pair_wire_bytes,
    residual_after_selection,
)


def _adapt_threshold(thresh, count, k, cfg: OkTopkConfig):
    """Multiplicative threshold feedback toward the [band_lo*k, band_hi*k]
    count band (reference VGG/allreducer.py:696-699)."""
    grow = count > cfg.band_hi * k
    shrink = count < cfg.band_lo * k
    scale = jnp.where(grow, cfg.local_adapt_scale,
                      jnp.where(shrink, 1.0 / cfg.local_adapt_scale, 1.0))
    return thresh * scale


def topk_a(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
           axis_name: str = "data"):
    """topkA: exact local top-k, allgather of [P, k] values+indices,
    scatter-add, mean (reference VGG/allreducer.py:34-69)."""
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    bkt = cfg.bucket_index
    with phase_scope("select", bkt):
        acc = add_residual(grad, state.residual)
        vals, idx = exact_topk(acc, k)
        sel_mask = jnp.zeros((n,), bool).at[idx].set(True)
        residual = residual_after_selection(acc, sel_mask, cfg)

    with phase_scope("exchange", bkt):
        gv = all_gather(on_wire(vals, cfg, state.step),
                        axis_name).astype(acc.dtype)   # [P, k]
        gi = all_gather(idx, axis_name)                # [P, k]
    with phase_scope("combine", bkt):
        result = scatter_sparse(n, gv, gi) / P

    vol = 2.0 * k + 2.0 * k * (P - 1)         # send + receive, idx+val scalars
    return result, bump(state, volume=vol,
                        wire_bytes=pair_wire_bytes(1.0 * k * P, cfg),
                        residual=residual,
                        local_count=k, global_count=k * P)


def topk_a2(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
            axis_name: str = "data"):
    """topkA2: topkA then re-top-k of the reduced result, so the applied
    update is exactly k-sparse (reference VGG/allreducer.py:519-525)."""
    result, new_state = topk_a(grad, state, cfg, axis_name)
    k = cfg.k
    with phase_scope("combine", cfg.bucket_index):
        vals, idx = exact_topk(result, k)
        result2 = scatter_sparse(cfg.n, vals, idx)
    return result2, new_state


def topk_a_opt(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
               axis_name: str = "data"):
    """topkAopt: threshold-predicted local selection (exact recompute every
    ``local_recompute_every`` steps, multiplicative adaptation otherwise) +
    fixed-capacity allgather (reference VGG/allreducer.py:1100-1151)."""
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    cap = cfg.cap_local
    bkt = cfg.bucket_index
    with phase_scope("select", bkt):
        acc = add_residual(grad, state.residual)
        abs_acc = jnp.abs(acc)

        recompute = ((state.step % cfg.local_recompute_every == 0)
                     | (state.step == cfg.warmup_steps))  # see oktopk.py
        lt = lax.cond(recompute,
                      lambda: k2threshold_method(
                          abs_acc, k, cfg.threshold_method,
                          cfg.bisect_iters).astype(acc.dtype),
                      lambda: state.local_threshold)

    with phase_scope("stage", bkt):
        vals, idx, count = select_by_threshold(
            acc, lt, cap, use_pallas=bool(cfg.use_pallas))
        packed_mask = jnp.zeros((n,), bool).at[idx].set(True, mode="drop")
        residual = residual_after_selection(acc, packed_mask, cfg)

    with phase_scope("exchange", bkt):
        gv = all_gather(on_wire(vals, cfg, state.step),
                        axis_name).astype(acc.dtype)
        gi = all_gather(idx, axis_name)
    with phase_scope("combine", bkt):
        result = scatter_sparse(n, gv, gi) / P

    total = psum(count, axis_name)
    lt_next = _adapt_threshold(lt, count, k, cfg)
    vol = 2.0 * total                          # sent 2c + received 2(total-c)
    return result, bump(state, volume=vol,
                        wire_bytes=pair_wire_bytes(total, cfg),
                        residual=residual,
                        local_threshold=lt_next,
                        local_count=count, global_count=total)
