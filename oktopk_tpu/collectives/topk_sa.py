"""Split-allreduce baselines: topkSA ("topkDSA") and gaussiankSA.

Reference: ``topkSA`` (VGG/allreducer.py:1153-1357) — oktopk's phase (a) with
*static* equal regions instead of load-balanced repartitioning, plus a
density-adaptive fallback to a dense gather when the reduced result is >= 2/3
dense (:1318-1351); and ``gaussiankSA`` (VGG/allreducer.py:1503-1620) — the
same split-exchange shape with the per-step Gaussian threshold (the
reference implements the exchange as a ring reduce-scatter; one
``all_to_all`` on fixed-capacity buffers is the TPU-native equivalent with
the same volume).

The dense fallback branch is a plain ``psum`` of the disjoint per-region
partials — exactly the dense allgather of regions the reference falls back
to, with volume 2n.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from oktopk_tpu.collectives.state import SparseState, bump
from oktopk_tpu.comm import all_gather, all_to_all, axis_rank, psum
from oktopk_tpu.comm.primitives import pvary_like
from oktopk_tpu.config import OkTopkConfig
from oktopk_tpu.obs.anatomy import phase_scope
from oktopk_tpu.ops import (
    gaussian_threshold,
    pack_by_region,
    scatter_sparse,
)
from oktopk_tpu.ops.select import select_nonzero
from oktopk_tpu.ops.topk import k2threshold_method
from oktopk_tpu.ops.residual import add_residual
from oktopk_tpu.collectives.wire import (
    dense_wire_bytes,
    on_wire,
    pair_wire_bytes,
    residual_after_winners,
)


def _split_allreduce(acc, lt, state: SparseState, cfg: OkTopkConfig,
                     axis_name: str, dense_fallback: bool):
    """Shared body: threshold-select -> all_to_all into static regions ->
    scatter-add -> gather phase (sparse allgather or dense-fallback psum)."""
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    rank = axis_rank(axis_name)
    bkt = cfg.bucket_index
    boundaries = state.boundaries      # static equal split from init_state

    with phase_scope("select", bkt):
        mask = jnp.abs(acc) >= lt
        local_count = jnp.sum(mask)
    with phase_scope("stage", bkt):
        s_vals, s_idx, s_counts = pack_by_region(
            acc, mask, boundaries, P, cfg.cap_pair, thresh=lt,
            use_pallas=bool(cfg.use_pallas))
    with phase_scope("exchange", bkt):
        r_vals = all_to_all(on_wire(s_vals, cfg, state.step),
                            axis_name).astype(acc.dtype)
        r_idx = all_to_all(s_idx, axis_name)
    with phase_scope("combine", bkt):
        reduced = scatter_sparse(n, r_vals, r_idx)

    sent_count = jnp.sum(s_counts)   # capped wire volume (see oktopk.py)
    recv_count = jnp.sum(r_idx < n)
    own_count = s_counts[rank]
    vol_a = 2.0 * (sent_count - own_count) + 2.0 * (recv_count - own_count)

    nnz = jnp.sum(reduced != 0.0)
    total_nnz = psum(nnz, axis_name)

    cap_g = cfg.cap_local

    def sparse_gather():
        with phase_scope("select", bkt):
            gvals, gidx, gcount = select_nonzero(
                reduced, cap_g, use_pallas=bool(cfg.use_pallas))
        with phase_scope("exchange", bkt):
            gv = all_gather(on_wire(gvals, cfg, state.step),
                            axis_name).astype(acc.dtype)
            gi = all_gather(gidx, axis_name)
        with phase_scope("combine", bkt):
            result = scatter_sparse(n, gv, gi)
        total = psum(gcount, axis_name)
        vol = 2.0 * gcount + 2.0 * (total - gcount)
        return pvary_like((result, vol, pair_wire_bytes(total, cfg),
                           jnp.float32(1.0)), acc)

    def dense_gather():
        # Regions are disjoint, so psum of the partials is the dense gather
        # the reference falls back to (VGG/allreducer.py:1318-1351). The
        # psum is NOT wire-rounded, so the owner's gather-rounding
        # compensation must be off (last element 0.0) — and its wire bytes
        # are bare f32 values (no indices), not sparse pairs.
        return pvary_like(
            (psum(reduced, axis_name), jnp.asarray(2.0 * n, jnp.float32),
             dense_wire_bytes(2.0 * n), jnp.float32(0.0)),
            acc)

    if dense_fallback:
        result, vol_b, wb_b, gather_rounded = lax.cond(
            total_nnz >= cfg.sa_dense_fallback_ratio * n,
            dense_gather, sparse_gather)
    else:
        result, vol_b, wb_b, gather_rounded = sparse_gather()

    with phase_scope("combine", bkt):
        result = result / P
        winner_mask = result != 0.0
        residual = residual_after_winners(acc, winner_mask, mask, reduced,
                                          cfg, owner_scale=gather_rounded)
    wb = pair_wire_bytes(0.5 * vol_a, cfg) + wb_b
    return result, residual, vol_a + vol_b, wb, local_count, total_nnz


def topk_sa(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
            axis_name: str = "data"):
    """topkSA / "topkDSA": predicted top-k threshold + static split-allreduce
    (reference VGG/allreducer.py:1153-1357)."""
    k = cfg.k
    with phase_scope("select", cfg.bucket_index):
        acc = add_residual(grad, state.residual)
        abs_acc = jnp.abs(acc)
        recompute = ((state.step % cfg.local_recompute_every == 0)
                     | (state.step == cfg.warmup_steps))  # see oktopk.py
        lt = lax.cond(recompute,
                      lambda: k2threshold_method(
                          abs_acc, k, cfg.threshold_method,
                          cfg.bisect_iters).astype(acc.dtype),
                      lambda: state.local_threshold)
    result, residual, vol, wb, lc, gc = _split_allreduce(
        acc, lt, state, cfg, axis_name, dense_fallback=True)
    grow = lc > cfg.band_hi * k
    shrink = lc < cfg.band_lo * k
    lt_next = lt * jnp.where(grow, cfg.local_adapt_scale,
                             jnp.where(shrink, 1.0 / cfg.local_adapt_scale, 1.0))
    return result, bump(state, volume=vol, wire_bytes=wb, residual=residual,
                        local_threshold=lt_next,
                        local_count=lc, global_count=gc)


def gaussian_k_sa(grad: jnp.ndarray, state: SparseState, cfg: OkTopkConfig,
                  axis_name: str = "data"):
    """gaussiankSA: Gaussian per-step threshold + static split-allreduce
    (reference VGG/allreducer.py:1503-1620)."""
    with phase_scope("select", cfg.bucket_index):
        acc = add_residual(grad, state.residual)
        t = gaussian_threshold(acc, cfg.k,
                               cfg.gaussian_refine_iters).astype(acc.dtype)
    result, residual, vol, wb, lc, gc = _split_allreduce(
        acc, t, state, cfg, axis_name, dense_fallback=False)
    return result, bump(state, volume=vol, wire_bytes=wb, residual=residual,
                        local_threshold=t,
                        local_count=lc, global_count=gc)
