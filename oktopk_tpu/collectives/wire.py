"""bf16 wire format for sparse message values, shared by all algorithms.

The TPU-native analogue of the reference's custom float16 MPI datatype +
sum op (VGG/allreducer.py:20-25): message VALUES travel as bfloat16 while
indices stay int32, cutting an (index, value) pair from 8 to 6 bytes.
``OkTopkConfig.wire_dtype`` selects it; "float32" restores the
reference-exact semantics.

The rounding error is folded back into the error-feedback residual
(standard quantization error feedback), so quantized mass is delivered on
a later step rather than lost:

- selection-residual algorithms (topkA family, gaussiank, gtopk's first
  hop): the residual keeps ``acc - round(acc)`` at selected slots instead
  of 0 (``residual_after_selection``);
- winner-residual algorithms (oktopk, topkSA/gaussiankSA): senders keep
  ``acc - round(acc)`` at winners they actually sent, and the region owner
  additionally keeps the phase-(b) gather rounding of its reduced sums
  (``residual_after_winners``), conserving total mass exactly.

Multi-hop merges (gtopk's butterfly) re-round intermediate SUMS; that
error is not attributable to any single worker's residual and stays
unrecovered (bounded by bf16 eps per hop) — but every rank must round its
own buffer before each exchange so partners merge identical multisets and
the all-ranks-identical-result invariant survives.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from oktopk_tpu.config import OkTopkConfig
from oktopk_tpu.ops.residual import (
    update_residual_at_selection,
    update_residual_at_winners,
)

# Fault-injection seam (resilience/faults.py): a trace-time transform
# applied to every value buffer as it crosses a collective. Installed
# before building a step, the corruption is baked into that jitted
# program; the default (None) traces nothing extra at all. The hook
# receives ``(buffer, cfg, step)`` with ``step`` the bucket's allreduce
# counter (a traced i32 scalar) — algorithms pass it so a FaultPlan can
# target one step deterministically.
_WIRE_FAULT: Optional[Callable] = None


def install_wire_fault(hook: Optional[Callable]) -> Optional[Callable]:
    """Install (or, with None, clear) the wire fault hook; returns the
    previous hook so chaos tests can restore it."""
    global _WIRE_FAULT
    prev = _WIRE_FAULT
    _WIRE_FAULT = hook
    return prev


def on_wire(x, cfg: OkTopkConfig, step=None):
    """The value buffer as it actually crosses the collective."""
    if cfg.wire_dtype != "float32":
        x = x.astype(jnp.bfloat16)
    if _WIRE_FAULT is not None:
        x = _WIRE_FAULT(x, cfg, step)
    return x


def pair_wire_bytes(pairs, cfg: OkTopkConfig):
    """Bytes for ``pairs`` transmitted (index, value) pairs under the
    configured wire format: int32 index (4 B) + bf16/f32 value (2/4 B).
    ``pairs`` may be traced (realised counts from inside the step); the
    result feeds ``state.wire_bytes`` via ``bump`` (obs/volume.py checks
    it against each algorithm's analytic budget)."""
    return jnp.asarray(pairs, jnp.float32) * float(cfg.wire_pair_bytes)


def dense_wire_bytes(values, value_bytes: int = 4):
    """Bytes for ``values`` transmitted bare value scalars — the dense
    psum/pmean paths, which carry no indices and are NOT wire-rounded
    (always f32 unless stated otherwise)."""
    return jnp.asarray(values, jnp.float32) * float(value_bytes)


def wire_round(x, cfg: OkTopkConfig):
    """Round ``x`` through the wire dtype (identity for float32).

    bf16 -> f32 is exact, so ``acc - wire_round(acc)`` is the true wire
    loss and error feedback can capture it exactly."""
    if cfg.wire_dtype == "float32":
        return x
    return x.astype(jnp.bfloat16).astype(x.dtype)


def residual_after_selection(acc, sel_mask, cfg: OkTopkConfig):
    """update_residual_at_selection (reference VGG/compression.py:343) plus
    quantization error feedback: selected slots keep the wire rounding
    error instead of 0."""
    if cfg.wire_dtype == "float32":
        return update_residual_at_selection(acc, sel_mask)
    return jnp.where(sel_mask, acc - wire_round(acc, cfg), acc)


def residual_after_winners(acc, winner_mask, sent_mask, reduced,
                           cfg: OkTopkConfig, owner_scale=None):
    """update_residual_at_winners (reference VGG/allreducer.py:1051-1052)
    plus quantization error feedback.

    At winners this worker sent (``sent_mask``), keep ``acc - round(acc)``;
    at winners it never selected, keep 0 (reference semantics: that mass is
    discarded); elsewhere keep acc. The region owner — identified by
    ``reduced != 0`` since the phase-(a) scatter leaves ``reduced`` nonzero
    only in the own region — additionally keeps the phase-(b) gather
    rounding of its reduced sums. ``owner_scale`` (0/1) disables that term
    when the gather was NOT rounded (topkSA's dense psum fallback)."""
    if cfg.wire_dtype == "float32":
        return update_residual_at_winners(acc, winner_mask)
    quant_err = acc - wire_round(acc, cfg)
    res = jnp.where(winner_mask, jnp.where(sent_mask, quant_err, 0.0), acc)
    comp = jnp.where(winner_mask & (reduced != 0.0),
                     reduced - wire_round(reduced, cfg), 0.0)
    if owner_scale is not None:
        comp = comp * owner_scale
    return res + comp
