"""Mesh + collective substrate (replaces the reference's mpi4py layer).

Primitive census of the reference (SURVEY.md §5.8, reference
VGG/allreducer.py:638,708,750-754,807,819,1031) and the TPU-native mapping
implemented here:

- ``MPI.Allreduce``            -> :func:`psum` / :func:`pmean`
- ``MPI.Allgather``            -> :func:`all_gather`
- ``MPI.Allgatherv``           -> :func:`all_gather` over fixed-capacity
                                   (values, indices, count) triples
- ``MPI.Alltoall``             -> :func:`all_to_all`
- tagged ``Isend/Irecv`` rounds-> :func:`ppermute_shift` ring rounds /
                                   one :func:`all_to_all`
- ``MPI.Bcast`` of model state -> parameter replication by sharding spec
                                   (free under pjit; no code needed)
"""

from oktopk_tpu.comm.fabric import (  # noqa: F401
    FABRIC_PRESETS,
    FabricPreset,
    TwoLevelFabric,
    get_fabric,
    two_level,
)
from oktopk_tpu.comm.mesh import (  # noqa: F401
    DATA_AXIS,
    POD_AXIS,
    get_mesh,
    hierarchical_mesh,
    local_hierarchical_mesh,
    local_mesh,
)
from oktopk_tpu.comm.primitives import (  # noqa: F401
    all_gather,
    all_to_all,
    axis_rank,
    axis_size,
    pmean,
    ppermute_shift,
    psum,
    psum_scatter,
)
