"""jax version compatibility seam.

The framework is written against the current jax API (``jax.shard_map``,
varying-manual-axes types via ``jax.typeof(x).vma`` / ``lax.pvary``,
``lax.axis_size``). Older jax releases (0.4.x) expose the same machinery
under different names — ``jax.experimental.shard_map.shard_map`` with a
``check_rep`` flag instead of ``check_vma``, no VMA type tracking at all —
so every use of a moved/renamed symbol goes through this module. Each
helper resolves the capability once at import time; callers never branch
on the jax version themselves.

On pre-VMA jax the vma helpers degrade to inert values (``frozenset()`` /
identity): the VMA discipline is a static type check, not a semantic
transform, so dropping it preserves results. ``shard_map`` likewise maps
``check_vma`` onto ``check_rep=False`` there — 0.4.x's replication checker
predates the pvary-based typing discipline the algorithms are written
with and rejects valid programs (e.g. ``lax.cond`` branches whose
replication it cannot prove).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

HAS_VMA = hasattr(jax, "typeof") and hasattr(lax, "pvary")
HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions (new kwarg ``check_vma``,
    old ``jax.experimental.shard_map.shard_map`` kwarg ``check_rep``)."""
    if HAS_NATIVE_SHARD_MAP:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    # check_rep=False unconditionally: 0.4.x's static replication checker
    # predates the pvary typing discipline (inert here) and rejects valid
    # programs (e.g. the seq/pipe composition steps' out_specs). The cost
    # is that loss-psum gradient transposes lose their replication
    # bookkeeping on 0.4.x — the composed-mesh equivalence tests that
    # compare such gradients against oracles document this (see
    # ROADMAP.md open item "jax-version compat").
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def typeof_vma(x) -> frozenset:
    """The varying-manual-axes set of ``x``'s type (empty on pre-VMA jax,
    or for non-traced values whose type carries no vma)."""
    if not HAS_VMA:
        return frozenset()
    try:
        return frozenset(jax.typeof(x).vma)
    except Exception:
        return frozenset()


def pvary(x, axes):
    """``lax.pvary`` where it exists; identity otherwise (pre-VMA jax has
    no varying/invariant distinction to adjust)."""
    axes = tuple(axes)
    if not axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if HAS_VMA:
        return lax.pvary(x, axes)
    return x


def shape_dtype_struct(shape, dtype, vma=None) -> jax.ShapeDtypeStruct:
    """``jax.ShapeDtypeStruct`` with the ``vma`` type argument when this
    jax supports it (pre-VMA signatures reject the kwarg)."""
    if HAS_VMA and vma is not None:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def axis_size(axis_name: str) -> int:
    """``lax.axis_size`` across versions. ``lax.psum`` of a Python literal
    is evaluated statically, so both forms give a concrete int usable to
    build ppermute tables at trace time."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
