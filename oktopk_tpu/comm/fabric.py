"""Named fabric presets and the two-level (intra, inter) fabric model.

Single source of truth for the alpha-beta coefficients that were
previously duplicated as literals inside ``scripts/project_multichip.py``.
A preset is the projection convention ``(alpha seconds/message-round,
bandwidth GB/s per worker)``:

- ``ici``  — deliberately conservative effective ring bandwidth for a
  v5e-class 2D torus slice;
- ``dcn``  — multi-host pod-to-pod data-center network;
- ``gbe``  — the 1.25 GB/s-class Ethernet the reference's cluster
  results were gathered on.

``TwoLevelFabric`` pairs an intra-pod link with an inter-pod link — the
topology the hierarchical collective (collectives/hierarchical.py) runs
on and the autotuner's per-level cost model prices
(autotune/policy.predict_ms): dense psum rides the fast intra fabric,
the sparse exchange crosses the scarce inter edge.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple, Union

#: Selection gamma (seconds/element) used when PLANNING for a target
#: accelerator fabric from a preset. The cost-model default
#: (utils/cost_model.topk_cost, 1e-9 s/elem ~ a CPU pass) overprices
#: selection for an HBM-class chip by ~an order of magnitude; 2e-10
#: models a few count/compact passes at effective HBM bandwidth and is
#: applied uniformly to every sparse candidate so the ranking stays a
#: fabric comparison, not a gamma artifact.
PLAN_SELECT_GAMMA = 2e-10


@dataclasses.dataclass(frozen=True)
class FabricPreset:
    """One named link: alpha-beta coefficients in projection convention."""

    name: str
    alpha_s: float            # seconds per message round
    gbps: float               # effective GB/s per worker

    def beta_elem(self, elem_bytes: int = 4) -> float:
        """Seconds per transmitted element of ``elem_bytes`` bytes — the
        beta the autotune cost model (seconds/element) consumes."""
        return float(elem_bytes) / (self.gbps * 1e9)

    def coefficients(self, elem_bytes: int = 4):
        """This preset as ``autotune.calibrate.FabricCoefficients`` (the
        planning substitute for a measured probe fit)."""
        from oktopk_tpu.autotune.calibrate import FabricCoefficients
        return FabricCoefficients(alpha=self.alpha_s,
                                  beta=self.beta_elem(elem_bytes),
                                  source=f"preset:{self.name}")


FABRIC_PRESETS: Dict[str, FabricPreset] = {
    "ici": FabricPreset("ici", 1e-6, 100.0),
    "dcn": FabricPreset("dcn", 10e-6, 25.0),
    "gbe": FabricPreset("gbe", 50e-6, 1.25),
}


def get_fabric(name: str) -> FabricPreset:
    try:
        return FABRIC_PRESETS[name]
    except KeyError:
        raise ValueError(f"unknown fabric preset {name!r}; "
                         f"available: {sorted(FABRIC_PRESETS)}")


def alpha_beta_table() -> Dict[str, Tuple[float, float]]:
    """``{name: (alpha_s, gbps)}`` — the legacy literal shape
    ``scripts/project_multichip.py`` exposes as its (mutable, per-run)
    ``FABRICS`` module attribute. Returns a fresh dict each call so
    callers may add scenario entries without mutating the presets."""
    return {n: (p.alpha_s, p.gbps) for n, p in FABRIC_PRESETS.items()}


@dataclasses.dataclass(frozen=True)
class TwoLevelFabric:
    """An (intra-pod, inter-pod) link pair for hierarchical planning."""

    intra: FabricPreset
    inter: FabricPreset

    @property
    def name(self) -> str:
        return f"{self.intra.name}+{self.inter.name}"


def two_level(inter: Union[str, FabricPreset] = "dcn",
              intra: Union[str, FabricPreset] = "ici") -> TwoLevelFabric:
    """Build a :class:`TwoLevelFabric`; string arguments name presets."""
    if isinstance(inter, str):
        inter = get_fabric(inter)
    if isinstance(intra, str):
        intra = get_fabric(intra)
    return TwoLevelFabric(intra=intra, inter=inter)


def resolve_two_level(
        spec: Union[str, FabricPreset, TwoLevelFabric]) -> TwoLevelFabric:
    """Normalise a fabric override to a :class:`TwoLevelFabric`.

    A bare preset (or preset name) names the INTER edge — the scarce
    resource a plan is made for — with ``ici`` assumed inside each pod
    (so ``"ici"`` degenerates to a flat ici+ici world)."""
    if isinstance(spec, TwoLevelFabric):
        return spec
    if isinstance(spec, FabricPreset):
        return TwoLevelFabric(intra=FABRIC_PRESETS["ici"], inter=spec)
    return TwoLevelFabric(intra=FABRIC_PRESETS["ici"],
                          inter=get_fabric(spec))
