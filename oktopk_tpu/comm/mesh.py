"""Device mesh construction.

The reference's "topology" is a flat MPI communicator sized by SLURM
(reference BERT/bert/main_bert.py:159-203 discovers ranks from SLURM_* env
vars). On TPU the analogue is a named-axis ``jax.sharding.Mesh`` over
``jax.devices()``; rank discovery, rendezvous and broadcast all disappear into
the sharding annotations.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
# Two-level data parallelism (collectives/hierarchical.py): the OUTER
# axis crossing the slow inter-pod edge; DATA_AXIS stays the intra-pod
# axis so flat single-axis programs keep their name.
POD_AXIS = "pod"


def get_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices=None,
) -> Mesh:
    """Build a mesh over the available devices.

    ``shape=None`` puts every device on the first axis (pure data
    parallelism — the reference's only real mode, SURVEY.md §2.3).
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def local_mesh(num: int = 1, axis_names: Sequence[str] = (DATA_AXIS,)) -> Mesh:
    """Mesh over the first ``num`` devices (single-chip testing)."""
    return get_mesh((num,) + (1,) * (len(axis_names) - 1), axis_names,
                    devices=jax.devices()[:num])


def hierarchical_mesh(
    num_pods: int,
    pod_size: int,
    axis_names: Sequence[str] = (POD_AXIS, DATA_AXIS),
    devices=None,
) -> Mesh:
    """Two-level ``(pod, data)`` mesh: ``num_pods`` groups of ``pod_size``
    devices. Devices are taken in order, so consecutive devices share a
    pod — the layout under which intra-pod collectives ride the fast
    links on real slices (and under which the emulated CPU mesh's pod
    grouping is deterministic)."""
    if num_pods < 1 or pod_size < 1:
        raise ValueError(
            f"need num_pods >= 1 and pod_size >= 1, got {num_pods}x{pod_size}")
    if devices is None:
        devices = jax.devices()
    need = num_pods * pod_size
    if len(devices) < need:
        raise ValueError(f"hierarchical_mesh({num_pods}x{pod_size}) needs "
                         f"{need} devices, have {len(devices)}")
    return get_mesh((num_pods, pod_size), axis_names, devices=devices[:need])


def local_hierarchical_mesh(num_pods: int = 2,
                            pod_size: Optional[int] = None) -> Mesh:
    """The emulated local device set presented as a two-level mesh —
    8 virtual CPU devices become 2x4 (default) or 4x2. ``pod_size=None``
    divides the available devices evenly over ``num_pods``."""
    devices = jax.devices()
    if pod_size is None:
        pod_size = max(1, len(devices) // max(1, num_pods))
    return hierarchical_mesh(num_pods, pod_size, devices=devices)
