"""Device mesh construction.

The reference's "topology" is a flat MPI communicator sized by SLURM
(reference BERT/bert/main_bert.py:159-203 discovers ranks from SLURM_* env
vars). On TPU the analogue is a named-axis ``jax.sharding.Mesh`` over
``jax.devices()``; rank discovery, rendezvous and broadcast all disappear into
the sharding annotations.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"


def get_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices=None,
) -> Mesh:
    """Build a mesh over the available devices.

    ``shape=None`` puts every device on the first axis (pure data
    parallelism — the reference's only real mode, SURVEY.md §2.3).
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def local_mesh(num: int = 1, axis_names: Sequence[str] = (DATA_AXIS,)) -> Mesh:
    """Mesh over the first ``num`` devices (single-chip testing)."""
    return get_mesh((num,) + (1,) * (len(axis_names) - 1), axis_names,
                    devices=jax.devices()[:num])
