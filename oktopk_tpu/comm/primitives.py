"""Typed collective wrappers, usable inside ``shard_map`` / pjit.

These are thin on purpose: XLA already implements the collectives over
ICI/DCN; the value here is (a) one place that names the mapping from the
reference's MPI verbs (SURVEY.md §5.8), (b) a stable seam for tests and for
analytic communication-volume accounting (``collectives.state``), and (c) a
place to swap in Pallas remote-DMA kernels later without touching algorithms.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from oktopk_tpu.comm import compat


def axis_size(axis_name: str):
    """World size along an axis (reference: comm.size)."""
    return compat.axis_size(axis_name)


def axis_rank(axis_name: str):
    """This shard's index along an axis (reference: comm.rank)."""
    return lax.axis_index(axis_name)


def psum(x, axis_name: str):
    """Dense allreduce-sum (reference MPI.Allreduce, VGG/allreducer.py:178)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    """Allreduce-mean (the reference divides by size after Allreduce)."""
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = False):
    """Fixed-size allgather (reference MPI.Allgather, VGG/allreducer.py:807).

    The reference's variable-size ``Allgatherv`` (VGG/allreducer.py:819,1031)
    has no XLA analogue; callers gather fixed-capacity (values, indices,
    count) triples instead — see ``ops.select.select_by_threshold``.
    """
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def all_to_all(x, axis_name: str, *, split_axis: int = 0, concat_axis: int = 0,
               tiled: bool = False):
    """All-to-all (replaces both the reference's size-transpose
    MPI.Alltoall at VGG/allreducer.py:708 and the throttled tagged
    Isend/Irecv pairwise exchange at VGG/allreducer.py:740-794: with
    fixed-capacity buffers the size exchange is unnecessary and the pairwise
    data exchange is exactly one all_to_all on a [P, cap] buffer)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def psum_scatter(x, axis_name: str, *, scatter_dimension: int = 0,
                 tiled: bool = True):
    """Reduce-scatter (the dense-masked collapse of oktopk phase (a) when
    density permits; SURVEY.md §5.8)."""
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def ppermute_shift(x, axis_name: str, shift: int = 1):
    """Ring shift by ``shift`` positions (reference's rotated dst/src
    schedule, VGG/allreducer.py:246-251, is exactly P-1 such shifts; also the
    building block for gtopk's tree exchange and ring attention)."""
    n = compat.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def pvary_like(tree, ref):
    """Mark every leaf varying over the axes ``ref`` is varying over.

    The collectives use this to align ``lax.cond`` branch types with their
    operands: a psum/iota-derived branch output is invariant (or varying
    over the collective axis only), while carried state matches the
    gradient's full vma — which under a composed mesh (data x pipe, data x
    seq) spans MORE than the collective axis."""
    vma = compat.typeof_vma(jnp.asarray(ref))
    return jax.tree.map(lambda x: pvary_to(jnp.asarray(x), vma), tree)


def carry_vma(*arrays, axis_name):
    """Varying-manual-axes a scan carry must be initialised with under
    ``shard_map(check_vma=True)``: the union of the inputs' vma plus
    ``axis_name`` (a ppermute output is always varying over its axis).
    Shared by the pipeline schedules and ring attention."""
    vma = {axis_name}
    for a in arrays:
        for leaf in jax.tree.leaves(a):
            vma |= set(compat.typeof_vma(leaf))
    return tuple(sorted(vma))


def pvary_to(x, vma):
    """Mark ``x`` varying over exactly the axes in ``vma`` it isn't yet."""
    missing = tuple(sorted(set(vma) - set(compat.typeof_vma(x))))
    return compat.pvary(x, missing)


def ppermute_pair(x, axis_name: str, distance: int):
    """Butterfly exchange with the partner at XOR ``distance`` (reference
    gtopk's recursive-halving tree, VGG/allreducer.py:76-172, expressed as a
    symmetric exchange so every rank ends with the same merged result and the
    final Bcast at VGG/allreducer.py:162 is unnecessary)."""
    n = compat.axis_size(axis_name)
    perm = [(i, i ^ distance) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)
