"""Typed configuration tree.

The reference scatters its real tuning surface across three layers (shell conf
files, argparse, and magic constants in code — see e.g. THRESHOLD=640MiB at
reference VGG/allreducer.py:27, recompute intervals at VGG/allreducer.py:577-579
vs BERT/bert/allreducer.py:359-361, threshold scales at VGG/allreducer.py:209-211
vs BERT/bert/allreducer.py:188-190, dense warmup at VGG/allreducer.py:573).
Here every such constant is a field on one frozen dataclass so it is visible,
testable, and hashable (usable as a static arg under jit).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class OkTopkConfig:
    """Static configuration for the sparse allreduce algorithms.

    All fields are Python scalars so the config is hashable and can be closed
    over by jitted functions; anything that changes per-step lives in
    ``collectives.state.SparseState`` instead.
    """

    # Problem geometry (static under XLA: shapes must be known at trace time).
    n: int = 0                 # flattened gradient length
    num_workers: int = 1       # data-parallel world size (mesh axis length)
    density: float = 0.02      # target k = ceil(density * n); reference VGG run uses 0.02

    # Dynamic density schedule (reference get_current_density,
    # VGG/allreducer.py:264-268: per-epoch density lists, shipped
    # tuned-off). Sorted (start_step, density) pairs; the active density
    # is the last pair whose start_step <= state.step. TPU-first reading:
    # shapes must be static under jit, so the schedule changes the target
    # k the threshold controller chases (a traced scalar from the step
    # counter), while every fixed-capacity buffer stays sized by the MAX
    # density = ``density`` (validated below). Requires the sort-free
    # "bisect" threshold (count-based, traced-k-capable); ``lax.top_k``
    # needs a static k. oktopk only — the topkA family's exact local
    # top-k is itself a static-k sort.
    density_schedule: Optional[Tuple[Tuple[int, float], ...]] = None

    # Cadences (reference VGG/allreducer.py:577-579; BERT uses 128/128/64).
    local_recompute_every: int = 32    # exact local top-k threshold recompute
    global_recompute_every: int = 32   # exact global top-k threshold recompute
    repartition_every: int = 64        # load-balanced region repartition

    # Dense warmup (reference VGG/allreducer.py:573 = 512; LSTM 128; BERT 0).
    warmup_steps: int = 512

    # Multiplicative threshold adaptation for the baseline algorithms
    # (reference VGG/allreducer.py:209-211 uses 1.012/1.008;
    # BERT/bert/allreducer.py:188-190 uses 1.025/1.036).
    local_adapt_scale: float = 1.012
    global_adapt_scale: float = 1.008

    # Ok-Topk threshold controller (collectives/oktopk.py::_newton_adapt):
    # one Newton step on the measured log-count/log-threshold slope,
    # sampled with a second count at thresh*probe_ratio (fused into the
    # same data pass). Replaces the reference's fixed +-1.2% nudge, which
    # cannot re-enter the band within a recompute window under threshold
    # drift. newton_exp_* bound the step exponent (-1/slope); per-step
    # correction is clamped to adapt_max_step.
    # Half Newton steps + a 1.5x/step clamp: underdamped full steps
    # resonate with real training dynamics (gradient scale itself moves
    # with the updates the collective delivers).
    probe_ratio: float = 1.25
    newton_exp_lo: float = 0.03
    newton_exp_hi: float = 0.5
    adapt_max_step: float = 1.5
    # Per-step threshold drift estimate (SparseState.drift): clip range for
    # the measured rate and the EMA mixing factor across recompute windows.
    drift_clip_lo: float = 0.5
    drift_clip_hi: float = 2.0
    # 1.0 = adopt each window's measured rate outright; the damped Newton
    # controller absorbs measurement noise, and a lagging drift estimate
    # costs more than a noisy one (it decays into systematic under/over-
    # selection for the whole next window).
    drift_ema: float = 1.0

    # Control band for the per-step selected count, as multiples of k
    # (reference grows/shrinks the threshold toward [2k/3, 5k/4],
    # VGG/allreducer.py:696-699).
    band_lo: float = 2.0 / 3.0
    band_hi: float = 5.0 / 4.0
    # Global-count band ceiling. The volume identity is
    #   vol ~ 4k(P-1)/P + 2*E[global_count]
    # so with E at the reference's 5k/4 ceiling the total sits exactly ON
    # the 6k budget; capping the global dead zone at 1.0*k targets ~5.7k
    # with margin. Local selection keeps the full reference band.
    band_hi_global: float = 1.0
    # Controller setpoints, as factors of k. 1.0 chases exactly k (the
    # reference behaviour); slightly below 1 operates realised counts in
    # the lower half of the reference band [2k/3, 5k/4] — still the same
    # nominal density d, but with volume margin under the 6k budget
    # instead of sitting 5% from the line (VERDICT r4). local applies to
    # the exact local-threshold recompute and local feedback; global to
    # the predicted-phase global feedback (exact global recomputes still
    # deliver exactly k winners).
    local_k_target: float = 0.9
    global_k_target: float = 0.85

    # Fixed-capacity factors. XLA has no ragged collectives (no Allgatherv /
    # size Alltoall), so every variable-length exchange in the reference
    # becomes a fixed-capacity (values, indices, count) buffer here.
    # Capacities are multiples of the expected count; the reference's own
    # threshold feedback keeps realised counts inside the band above, so a
    # modest headroom factor suffices (SURVEY.md §7.3.1).
    cap_pair_factor: float = 2.0    # per (src -> dst-region) buffer, of k/P
    cap_gather_factor: float = 2.5  # per-region allgather buffer, of k/P
    # Exact-recompute candidate pool per region, of k/P. Load-balanced
    # regions hold ~k/P of the global top-k each (that balance is what makes
    # the paper's volume O(k) instead of O(kP)); 4x headroom covers drift
    # between repartitions. The reference instead gathers ALL nonzeros of
    # the reduced region (VGG/allreducer.py:819) — unbounded on the wire.
    cap_exact_factor: float = 4.0

    # Gaussian threshold estimation (reference compression.py:238-259 refines a
    # scipy ppf estimate in a bounded loop; we binary-search, see ops/gaussian).
    gaussian_refine_iters: int = 16
    sigma_scale: float = 2.5        # reference VGG/vgg16_oktopk.sh:28

    # Exact-threshold implementation for the periodic recomputes:
    # "bisect" (default, TPU-first): sort-free count-bisection — O(iters*n)
    #   VPU compares instead of the O(n log n) sort the reference pays for
    #   torch.topk (SURVEY.md §7.3.5); ties resolved within float tolerance.
    # "sort": exact lax.top_k (reference-faithful; fine on CPU/small n).
    # "hist": one-pass 256-bin log2-magnitude histogram cumsum read
    #   (ops/hist_threshold.py) — 1-bit within-octave resolution, but ONE
    #   data pass standalone and ZERO extra passes when the fused selection
    #   kernel emits the histogram as a byproduct (ops/fused_select.py).
    #   oktopk under "hist" uses LAGGED local recomputes: each step selects
    #   with the carried drift-predicted threshold while the exact level is
    #   read from the histogram that same selection pass produced, becoming
    #   next step's threshold (one drift-compensated step of staleness
    #   instead of ~11 extra HBM sweeps). "bisect" stays the oracle.
    threshold_method: str = "bisect"
    bisect_iters: int = 30

    # topkSA density-adaptive fallback: switch to dense allgather when the
    # reduced result is >= this dense (reference VGG/allreducer.py:1318-1351).
    sa_dense_fallback_ratio: float = 2.0 / 3.0

    # Selection compaction backend: True = Pallas stream-compaction kernel
    # (ops/compaction.py; TPU only), False = portable cumsum+scatter,
    # None = resolve from the mesh backend at step-build time
    # (collectives/api.py, optim/distributed.py).
    use_pallas: Optional[bool] = None

    # Fused selection front-end (ops/fused_select.py): ONE Pallas sweep
    # over (grad, residual) computes acc, the staging rows, the realised +
    # Newton-probe counts and the threshold histogram, replacing the
    # separate add_residual/abs/mask/count/probe/pack passes of
    # collectives/oktopk.py. None = auto (on whenever the Pallas backend
    # is active); False = force the unfused per-pass path (the parity
    # oracle, and bench.py's degradation rung when the fused kernel fails
    # to compile — `oktopk_fused_failed`); True = same as None (the kernel
    # still requires use_pallas; it cannot run on the portable path).
    # oktopk only; f32 gradients only (as all Pallas selection paths).
    fuse_select: Optional[bool] = None

    # Which reverse-layer-order gradient bucket this config instance
    # serves. Set by the multi-bucket step builder (optim/distributed.py)
    # so trace-time seams that only see the config — e.g. the wire
    # fault-injection hook (collectives/wire.py, resilience/faults.py) —
    # can target a single bucket. Purely informational for the
    # algorithms themselves.
    bucket_index: int = 0

    # Wire dtype for sparse message VALUES (indices stay int32). "bfloat16"
    # halves the value bytes of every exchange — the TPU-native analogue of
    # the reference's custom float16 MPI datatype + sum op
    # (VGG/allreducer.py:20-25) — with the rounding error folded back into
    # the error-feedback residual (collectives/oktopk.py), so the mass is
    # delivered later rather than lost. "float32" = uncompressed.
    wire_dtype: str = "bfloat16"

    @property
    def k(self) -> int:
        """Target number of selected elements (k = density * n). With a
        density_schedule this is the MAX over the schedule (capacity
        sizing); the per-step target is :func:`scheduled_k`."""
        return max(1, int(self.density * self.n))

    @property
    def k_region(self) -> int:
        """Expected per-region winner count (k / P)."""
        return max(1, self.k // max(1, self.num_workers))

    @property
    def cap_pair(self) -> int:
        """Capacity of each (worker -> region) exchange buffer."""
        cap = int(self.cap_pair_factor * self.k / max(1, self.num_workers)) + 8
        return min(self.n, cap)

    @property
    def cap_gather(self) -> int:
        """Capacity of each per-region allgather buffer (phase b)."""
        cap = int(self.cap_gather_factor * self.k / max(1, self.num_workers)) + 8
        return min(self.n, cap)

    @property
    def cap_exact(self) -> int:
        """Per-region candidate pool for the exact global recompute."""
        cap = int(self.cap_exact_factor * self.k / max(1, self.num_workers)) + 8
        return min(self.n, cap)

    @property
    def cap_local(self) -> int:
        """Capacity for whole-vector local selections (topkAopt / gaussiank)."""
        return min(self.n, int(self.cap_gather_factor * self.k) + 8)

    def __post_init__(self):
        if self.wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"wire_dtype must be 'float32' or 'bfloat16', "
                f"got {self.wire_dtype!r}")
        if self.density_schedule:
            starts = [s for s, _ in self.density_schedule]
            if starts != sorted(starts):
                raise ValueError(
                    f"density_schedule starts must be ascending: {starts}")
            if starts[0] != 0:
                raise ValueError(
                    f"density_schedule must start at step 0 (got "
                    f"{starts[0]}): every step needs an active pair — "
                    "add an explicit (0, density) entry for the early "
                    "phase")
            worst = max(d for _, d in self.density_schedule)
            if worst > self.density:
                raise ValueError(
                    f"density_schedule peaks at {worst} > density "
                    f"{self.density}; capacities are sized by `density`, "
                    "set it to the schedule's max")
            if self.threshold_method not in ("bisect", "hist"):
                raise ValueError(
                    "density_schedule needs threshold_method='bisect' or "
                    "'hist' (a traced target k; lax.top_k wants it "
                    "static)")
        if self.threshold_method not in ("sort", "bisect", "hist"):
            raise ValueError(
                f"threshold_method must be 'sort', 'bisect' or 'hist', "
                f"got {self.threshold_method!r}")
        for name in ("local_k_target", "global_k_target"):
            f = getattr(self, name)
            # below band_lo the setpoint fights its own dead zone (every
            # correction lands out-of-band low and is immediately pushed
            # back); above 1 it would overshoot the nominal density
            if not (self.band_lo <= f <= 1.0):
                raise ValueError(
                    f"{name}={f} must lie in [band_lo={self.band_lo:.3f}"
                    ", 1.0]")

    @property
    def wire_value_bytes(self) -> int:
        """Bytes per transmitted value scalar (indices are 4-byte int32)."""
        return 2 if self.wire_dtype == "bfloat16" else 4

    @property
    def wire_pair_bytes(self) -> int:
        """Bytes per transmitted (index, value) pair."""
        return 4 + self.wire_value_bytes

    def replace(self, **kw) -> "OkTopkConfig":
        return dataclasses.replace(self, **kw)


def scheduled_k(cfg: OkTopkConfig, step):
    """Per-step target k under ``cfg.density_schedule`` (a traced int32
    scalar of ``step``), or the static ``cfg.k`` without one.

    The reference looks its density up per epoch (get_current_density,
    VGG/allreducer.py:264-268) and re-sizes its MPI buffers implicitly;
    here the lookup is a tiny gather the step program traces once, and
    buffers never re-size (see the density_schedule field note)."""
    import jax.numpy as jnp

    if not cfg.density_schedule:
        return cfg.k
    starts = jnp.asarray([s for s, _ in cfg.density_schedule], jnp.int32)
    ks = jnp.asarray([max(1, int(d * cfg.n))
                      for _, d in cfg.density_schedule], jnp.int32)
    i = jnp.maximum(jnp.sum(step >= starts) - 1, 0)
    return ks[i]


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """Mesh geometry. The reference's world is a flat MPI communicator
    (MPI.COMM_WORLD); ours is a named-axis device mesh. ``data`` is the
    data-parallel axis (maps to the reference's rank space); ``model`` /
    ``pipe`` / ``seq`` are TPU-side extensions."""

    data_axis: str = "data"
    model_axis: str = "model"
    pipe_axis: str = "pipe"
    seq_axis: str = "seq"
    mesh_shape: Tuple[int, ...] = (1,)
    axis_names: Tuple[str, ...] = ("data",)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Trainer configuration (reference main_trainer.py argparse surface,
    VGG/main_trainer.py:144-159 + exp_configs/*.conf)."""

    dnn: str = "vgg16"
    dataset: str = "cifar10"
    batch_size: int = 16
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 5e-4
    nesterov: bool = False
    max_epochs: int = 161
    nsteps_update: int = 1          # local gradient accumulation steps
    compressor: str = "oktopk"
    density: float = 0.02
    sigma_scale: float = 2.5
    seed: int = 0
    num_workers: int = 1
    # LSTM-only gradient clipping (reference LSTM/main_trainer.py:94-99).
    grad_clip: Optional[float] = None
    # DGC-style momentum correction: fold momentum into the local gradient
    # stream before compression (reference VGG/distributed_optimizer.py:56,
    # 81-88); the base optimizer then runs momentum-free.
    momentum_correction: bool = False
    # BERT-style warmup-linear schedule knobs (transformers/optimization.py).
    warmup_proportion: float = 0.01
    total_steps: int = 0
    # Mixed precision: computation dtype for the model's matmuls/convs
    # ("bfloat16" doubles MXU throughput; master params, grads, the sparse
    # collective and the optimizer all stay float32). This replaces the
    # reference's NVIDIA-apex amp path (BERT/bert/main_bert.py:15,1009-1023,
    # SURVEY.md §2.4).
    compute_dtype: str = "float32"
    # Comm/backward overlap: number of reverse-layer-order gradient buckets,
    # each with its own sparse collective + SparseState (reference <=640 MiB
    # bucketing, VGG/allreducer.py:27,272-330). 1 = whole-model flat.
    num_buckets: int = 1

    # ---- per-bucket algorithm/density autotuning (autotune/) ----------
    # When True the trainer runs calibrate -> trial -> policy before the
    # first step (and again on the retune cadence) and builds each
    # bucket's collective from the resulting plan; ``compressor`` becomes
    # the fallback for buckets the tuner has not planned yet.
    autotune: bool = False
    # Candidate algorithms (registry names). Sparse ones are crossed with
    # ``autotune_densities``; "dense" is the single density-1.0 point.
    autotune_candidates: Tuple[str, ...] = ("dense", "oktopk")
    # Density grid for sparse candidates; () = just ``density``.
    autotune_densities: Tuple[float, ...] = ()
    # Timed steps per candidate per bucket in the trial phase.
    autotune_trial_steps: int = 3
    # Steps between re-tunes; 0 = tune once before the first step.
    autotune_retune_every: int = 0
    # A challenger must beat the incumbent's fresh measurement by this
    # fraction to flip a bucket's plan (anti-thrash dead zone: a flip
    # rebuilds + recompiles the jitted train step).
    autotune_hysteresis: float = 0.15
    # Trial only the top-N candidates by cost-model prior (0 = all).
    autotune_max_trials: int = 0
    # JSONL decision-journal path; None keeps the journal in memory.
    autotune_journal: Optional[str] = None

    # ---- numeric-health guard + escalation (resilience/) --------------
    # When True the distributed step carries the in-step anomaly guard:
    # nonfinite local gradients or nonfinite/absurd post-collective
    # values trip a psum-agreed skip — the optimizer update AND the
    # compressor residual/threshold updates roll back for that step (no
    # error-feedback poisoning) — and the trainer runs the host-side
    # supervisor (strike counters -> per-bucket dense fallback ->
    # checkpoint restore on divergence).
    resilience: bool = False
    # Reduced-gradient magnitude ceiling: finite-but-absurd values (wire
    # bit-flips land near 1e38) count as anomalies above it.
    resilience_abs_limit: float = 1e18
    # Guard trips on a bucket before the supervisor flips it to dense.
    resilience_strikes: int = 3
    # Consecutive skipped steps before a restore from the last good
    # checkpoint is attempted.
    resilience_divergence_limit: int = 8
    # Steps the supervisor waits after an escalation before escalating
    # again (retry/backoff: one fault burst must not cascade).
    resilience_cooldown: int = 4
    # Supervisor poll cadence in steps. Each check fetches the guard
    # metrics to host (a device sync); 1 = react within a step.
    resilience_check_every: int = 1
    # JSONL health-journal path; None keeps the journal in memory.
    resilience_journal: Optional[str] = None

    # ---- closed-loop policies (resilience/feedback.py, density.py) ----
    # Fault→autotune feedback: when True (and obs is on) the trainer
    # watches the bus for sustained regression/guard_trip streams and
    # forces an autotune re-calibrate + re-tune when the vote passes —
    # a degraded fabric re-tunes the plan instead of degrading forever.
    resilience_feedback: bool = False
    # Sliding evidence window (steps) and the votes needed inside it.
    resilience_feedback_window: int = 32
    resilience_feedback_signals: int = 3
    # Steps to back off after a forced re-tune (re-tuning recompiles).
    resilience_feedback_cooldown: int = 64
    # Guard-aware density backoff: when True (with resilience) the
    # effective selection density hysteretically backs off after
    # repeated near-abs_limit / guard-skip steps and re-advances after
    # a clean streak (resilience/density.py).
    resilience_density_backoff: bool = False
    # "Near" band: reduced_absmax > near_ratio * abs_limit is pressure.
    resilience_near_ratio: float = 0.1
    # Consecutive pressured steps before backing off one level.
    resilience_backoff_steps: int = 3
    # Density multiplier per level, and the level bound.
    resilience_backoff_factor: float = 0.5
    resilience_backoff_max_level: int = 3
    # Consecutive clean steps before re-advancing one level.
    resilience_clean_streak: int = 8

    # ---- unified observability (obs/) ---------------------------------
    # When True the trainer runs an event bus + run journal: per-step
    # metrics, autotune decisions, guard trips, fallbacks, checkpoints,
    # trace captures and end-of-run volume reports all land in ONE
    # JSONL file behind one environment header (obs/journal.py).
    obs: bool = False
    # Run-journal path; None keeps the journal in memory only.
    obs_journal: Optional[str] = None
    # Arm a bounded jax.profiler trace window on guard_trip/fallback
    # events (obs/tracing.py AnomalyTracer).
    obs_trace_on_anomaly: bool = False
    # Steps per anomaly-triggered trace window.
    obs_trace_steps: int = 3
    # Directory for anomaly trace captures; None derives from the
    # journal path (or a temp dir when the journal is in-memory).
    obs_trace_dir: Optional[str] = None
    # Max anomaly windows per run (a flapping guard must not fill disk).
    obs_max_traces: int = 3
    # BENCH_r*.json parsed key to build the step-time regression
    # baseline from (obs/regress.py); None disables regression checks.
    obs_regress_key: Optional[str] = None
    # Step time above tolerance x baseline journals a regression event.
    obs_regress_tolerance: float = 1.5
    # Per-phase duration limits in milliseconds ({"exchange": 50.0, ...});
    # a host-phase summary entry above its limit journals a regression
    # event with key="phase:<name>" (obs/regress.py observe_phases).
    obs_phase_limits: Optional[Dict[str, float]] = None
    # ---- signal-fidelity telemetry (obs/quality.py) -------------------
    # When True (with obs) the jitted step computes per-bucket fidelity
    # scalars — compression error vs the pre-selection dense gradient,
    # residual norm/growth, realised density, threshold drift, winner
    # churn — into a device-side ring (obs/metrics_buffer.py) flushed
    # to `quality` journal events; obs/rollup.py aggregates them with
    # breach detection feeding the closed-loop seams.
    obs_quality: bool = False
    # Flush cadence in steps (= ring capacity). Steady state pays NO
    # per-step host sync; each flush is one device_get.
    obs_quality_every: int = 32
    # Churn-signature bins (power of two; obs/quality.py).
    obs_quality_sig_bins: int = 512
    # Breach thresholds (obs/rollup.py): window-mean residual growth
    # ratio above this flags residual_growth ...
    obs_quality_growth_limit: float = 1.5
    # ... realised density below this fraction of the bucket target
    # flags density_collapse ...
    obs_quality_collapse_ratio: float = 0.25
    # ... mean winner churn above this flags churn_spike ...
    obs_quality_churn_limit: float = 0.9
    # ... and mean compression error above this flags comp_err.
    obs_quality_comp_err_limit: float = 1.0

    def experiment_slug(self) -> str:
        """Reference experiment naming convention
        (VGG/main_trainer.py:163-166)."""
        mode = "comp" if self.compressor != "dense" else "dense"
        return (
            f"allreduce-{mode}-{self.compressor}-gwarmup-dc1-model-mgwfbp"
            f"-{self.dnn}-n{self.num_workers}-bs{self.batch_size}"
            f"-lr{self.lr:.4f}-ns{self.nsteps_update}-ds{self.density}"
        )
