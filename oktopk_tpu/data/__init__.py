"""Dataset pipelines (reference C19, SURVEY.md §2.2: cifar10/imagenet/mnist/
ptb/an4 prep in VGG/dl_trainer.py:262-446 and the BERT Wikipedia pipeline in
BERT/bert/main_bert.py:257-366).

Every loader yields numpy batches shaped [global_batch, ...]; the distributed
step shards them over the data axis (the analogue of the reference's
``DistributedSampler`` partitioning, VGG/dl_trainer.py:286-288). When the
real dataset files are absent (this container has zero egress) loaders fall
back to deterministic synthetic data with identical shapes/dtypes so every
pipeline stays exercisable end-to-end.
"""

from oktopk_tpu.data.synthetic import synthetic_iterator  # noqa: F401
from oktopk_tpu.data.loaders import make_dataset  # noqa: F401
