"""AN4 audio pipeline: WAV -> log-spectrogram -> padded CTC batches.

Reference: the audio_data loader package the LSTM harness downloads
(dataset prep at LSTM/dl_trainer.py:420-446) — librosa STFT spectrograms
(20ms window / 10ms hop @16kHz => 161 freq bins), per-utterance
normalisation, character labels for CTC.

This is dependency-free: stdlib ``wave`` + a numpy STFT. Batches are padded
to a fixed time length (static shapes for XLA) instead of the reference's
per-batch dynamic padding.
"""

from __future__ import annotations

import os
import wave
from typing import Dict, Iterator, List, Tuple

import numpy as np

AN4_LABELS = "_'ABCDEFGHIJKLMNOPQRSTUVWXYZ "   # blank at index 0
SAMPLE_RATE = 16000
WINDOW = 320        # 20 ms
HOP = 160           # 10 ms
N_FREQ = WINDOW // 2 + 1    # 161


def read_wav(path: str) -> np.ndarray:
    with wave.open(path, "rb") as w:
        data = np.frombuffer(w.readframes(w.getnframes()), np.int16)
    return data.astype(np.float32) / 32768.0


def log_spectrogram(audio: np.ndarray) -> np.ndarray:
    """[N_FREQ, T] log magnitude STFT with per-utterance normalisation."""
    if len(audio) < WINDOW:
        audio = np.pad(audio, (0, WINDOW - len(audio)))
    n_frames = 1 + (len(audio) - WINDOW) // HOP
    idx = (np.arange(WINDOW)[None, :]
           + HOP * np.arange(n_frames)[:, None])
    frames = audio[idx] * np.hamming(WINDOW)
    spec = np.abs(np.fft.rfft(frames, axis=1)).T       # [N_FREQ, T]
    spec = np.log1p(spec)
    mean, std = spec.mean(), spec.std() + 1e-6
    return ((spec - mean) / std).astype(np.float32)


def text_to_labels(text: str) -> List[int]:
    table = {c: i for i, c in enumerate(AN4_LABELS)}
    return [table[c] for c in text.upper() if c in table]


def load_manifest(manifest_path: str) -> List[Tuple[str, str]]:
    """CSV manifest lines: wav_path,transcript_path (the reference's
    manifest format)."""
    base = os.path.dirname(manifest_path)
    items = []
    with open(manifest_path) as f:
        for line in f:
            wav, txt = line.strip().split(",")[:2]
            if not os.path.isabs(wav):
                wav = os.path.join(base, wav)
                txt = os.path.join(base, txt)
            items.append((wav, txt))
    return items


def an4_iterator(manifest_path: str, batch_size: int, max_frames: int = 400,
                 max_label_len: int = 80, seed: int = 0,
                 shuffle: bool = True) -> Iterator[Dict]:
    items = load_manifest(manifest_path)
    rng = np.random.RandomState(seed)
    while True:
        order = rng.permutation(len(items)) if shuffle else range(len(items))
        batch: List[int] = []
        for j in order:
            batch.append(j)
            if len(batch) < batch_size:
                continue
            spect = np.zeros((batch_size, N_FREQ, max_frames, 1), np.float32)
            spect_lengths = np.zeros((batch_size,), np.int32)
            labels = np.zeros((batch_size, max_label_len), np.int32)
            label_lengths = np.zeros((batch_size,), np.int32)
            for b, jj in enumerate(batch):
                wav, txt = items[jj]
                s = log_spectrogram(read_wav(wav))
                t = min(s.shape[1], max_frames)
                spect[b, :, :t, 0] = s[:, :t]
                spect_lengths[b] = t
                with open(txt) as f:
                    lab = text_to_labels(f.read().strip())[:max_label_len]
                labels[b, :len(lab)] = lab
                label_lengths[b] = len(lab)
            yield {"spect": spect, "spect_lengths": spect_lengths,
                   "labels": labels, "label_lengths": label_lengths}
            batch = []
