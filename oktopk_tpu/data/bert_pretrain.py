"""BERT pretraining example creation: sentence pairs + MLM masking + NSP.

Reference: ``BERTDataset`` (BERT/bert/main_bert.py:257-366) builds
sentence-pair examples from a line-per-sentence corpus (blank lines separate
documents), with 50% random-next-sentence negatives, and
``convert_example_to_features`` (:528-614) applies the standard 15% masking
(80% [MASK] / 10% random / 10% keep) with ignore_index -1 labels; the
Wikipedia shard creators live in BERT/bert/sources.py / dataset.py.

This module is pure numpy + the framework tokenizer, yields static-shape
batches for the distributed step, and falls back to synthetic ids when no
corpus is on disk.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional

import numpy as np

from oktopk_tpu.data.tokenization import FullTokenizer


def load_documents(path: str) -> List[List[str]]:
    """Corpus file(s): one sentence per line, blank line between documents."""
    docs: List[List[str]] = [[]]
    files = ([os.path.join(path, f) for f in sorted(os.listdir(path))]
             if os.path.isdir(path) else [path])
    for fname in files:
        with open(fname, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    if docs[-1]:
                        docs.append([])
                else:
                    docs[-1].append(line)
    return [d for d in docs if len(d) >= 2]


def mask_tokens(ids: np.ndarray, rng: np.random.RandomState,
                vocab_size: int, mask_id: int, special_mask: np.ndarray,
                mlm_prob: float = 0.15):
    """15% positions: 80% -> [MASK], 10% -> random, 10% -> unchanged;
    labels are the original ids at masked positions, -1 elsewhere."""
    labels = np.full_like(ids, -1)
    cand = (~special_mask) & (rng.rand(*ids.shape) < mlm_prob)
    labels[cand] = ids[cand]
    r = rng.rand(*ids.shape)
    ids = np.where(cand & (r < 0.8), mask_id, ids)
    rand_ids = rng.randint(0, vocab_size, size=ids.shape)
    ids = np.where(cand & (r >= 0.8) & (r < 0.9), rand_ids, ids)
    return ids, labels


def pretrain_iterator(corpus_path: Optional[str], tokenizer: FullTokenizer,
                      batch_size: int, max_seq_len: int = 128,
                      seed: int = 0,
                      vocab_size: int = 30522) -> Iterator[Dict]:
    """Yield MLM+NSP batches from a corpus on disk."""
    docs = load_documents(corpus_path)
    rng = np.random.RandomState(seed)
    mask_id = tokenizer.vocab.get("[MASK]", 4)
    cls_id = tokenizer.vocab.get("[CLS]", 2)
    sep_id = tokenizer.vocab.get("[SEP]", 3)

    def one_example():
        d = docs[rng.randint(len(docs))]
        i = rng.randint(len(d) - 1)
        a = d[i]
        if rng.rand() < 0.5:
            b, nsp = d[i + 1], 0              # IsNext = 0 (reference label)
        else:
            rd = docs[rng.randint(len(docs))]
            b, nsp = rd[rng.randint(len(rd))], 1
        ids, types, mask = tokenizer.encode_pair(a, b, max_seq_len)
        return np.asarray(ids), np.asarray(types), np.asarray(mask), nsp

    while True:
        ids = np.zeros((batch_size, max_seq_len), np.int32)
        types = np.zeros_like(ids)
        attn = np.zeros_like(ids)
        nsp = np.zeros((batch_size,), np.int32)
        for b in range(batch_size):
            ids[b], types[b], attn[b], nsp[b] = one_example()
        special = (ids == cls_id) | (ids == sep_id) | (attn == 0)
        masked, labels = mask_tokens(ids, rng, vocab_size, mask_id, special)
        yield {"input_ids": masked.astype(np.int32),
               "token_type_ids": types,
               "attention_mask": attn,
               "mlm_labels": labels.astype(np.int32),
               "nsp_labels": nsp}
