"""Dataset loaders with zero-egress synthetic fallback.

Real-data parity map (reference VGG/dl_trainer.py): cifar10 (:312, torchvision
pickle batches), mnist (:351, idx files), imagenet (:262, HDF5 via
VGG/datasets.py:8), ptb (:382 via VGG/ptb_reader.py:32), an4 (:420, audio
loader), BERT Wikipedia sentence pairs (BERT/bert/main_bert.py:257-366).

Each ``make_dataset`` call returns ``(iterator, meta)``. If the expected
files are missing the loader yields synthetic batches with identical
shapes/dtypes (this container cannot download datasets), and ``meta`` notes
it — so correctness of the pipeline code stays testable without the bytes.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from oktopk_tpu.data.synthetic import synthetic_iterator

CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR_STD = np.array([0.2023, 0.1994, 0.2010], np.float32)


def _batched(x: Dict[str, np.ndarray], batch_size: int, seed: int,
             shuffle: bool = True) -> Iterator[Dict[str, np.ndarray]]:
    """Shuffled epoch batches. Training iterators use the native
    prefetching loader (C++ background thread, oktopk_tpu/native/loader.py
    — the torch-DataLoader-worker replacement) when the OKTOPK_NATIVE
    policy resolves to it (see oktopk_tpu.native.resolve: explicit opt-in
    for multi-process runs, never a silent per-host fallback)."""
    if shuffle:
        from oktopk_tpu import native
        if native.resolve("loader"):
            from oktopk_tpu.native.loader import make_prefetch_iter
            it = make_prefetch_iter(x, batch_size, seed=seed)
            if it is not None:
                return it

    def gen():
        n = len(next(iter(x.values())))
        rng = np.random.RandomState(seed)
        while True:
            order = rng.permutation(n) if shuffle else np.arange(n)
            for i in range(0, n - batch_size + 1, batch_size):
                sel = order[i:i + batch_size]
                yield {k: v[sel] for k, v in x.items()}

    return gen()


def load_cifar10(path: str, split: str = "train"):
    """torchvision-layout pickle batches (cifar-10-batches-py)."""
    base = os.path.join(path, "cifar-10-batches-py")
    files = ([f"data_batch_{i}" for i in range(1, 6)]
             if split == "train" else ["test_batch"])
    images, labels = [], []
    for f in files:
        with open(os.path.join(base, f), "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        images.append(d[b"data"])
        labels.extend(d[b"labels"])
    x = np.concatenate(images).reshape(-1, 3, 32, 32).astype(np.float32) / 255.
    x = x.transpose(0, 2, 3, 1)            # NCHW -> NHWC (TPU layout)
    x = (x - CIFAR_MEAN) / CIFAR_STD
    return {"image": x, "label": np.asarray(labels, np.int32)}


def load_mnist(path: str, split: str = "train"):
    """Raw idx files (train-images-idx3-ubyte etc.)."""
    prefix = "train" if split == "train" else "t10k"
    with open(os.path.join(path, f"{prefix}-images-idx3-ubyte"), "rb") as f:
        f.read(16)
        x = np.frombuffer(f.read(), np.uint8).reshape(-1, 28, 28, 1)
    with open(os.path.join(path, f"{prefix}-labels-idx1-ubyte"), "rb") as f:
        f.read(8)
        y = np.frombuffer(f.read(), np.uint8)
    return {"image": (x.astype(np.float32) / 255. - 0.1307) / 0.3081,
            "label": y.astype(np.int32)}


IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _bilinear_resize(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Vectorised numpy bilinear resize, HWC float32."""
    h, w = img.shape[:2]
    if h == out_h and w == out_w:
        return img
    ys = (np.arange(out_h, dtype=np.float32) + 0.5) * (h / out_h) - 0.5
    xs = (np.arange(out_w, dtype=np.float32) + 0.5) * (w / out_w) - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int32), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int32), 0, w - 1)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def _random_resized_crop(img: np.ndarray, size: int,
                         rng: np.random.RandomState) -> np.ndarray:
    """Numpy form of torchvision RandomResizedCrop (scale [0.08, 1],
    ratio [3/4, 4/3]) used by the reference's ImageNet transform
    (VGG/dl_trainer.py:274-276)."""
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target = area * rng.uniform(0.08, 1.0)
        ratio = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
        cw = int(round(np.sqrt(target * ratio)))
        ch = int(round(np.sqrt(target / ratio)))
        if 0 < cw <= w and 0 < ch <= h:
            y = rng.randint(0, h - ch + 1)
            x = rng.randint(0, w - cw + 1)
            return _bilinear_resize(img[y:y + ch, x:x + cw], size, size)
    # fallback: center crop of the short side
    s = min(h, w)
    y, x = (h - s) // 2, (w - s) // 2
    return _bilinear_resize(img[y:y + s, x:x + s], size, size)


def _center_crop(img: np.ndarray, size: int) -> np.ndarray:
    h, w = img.shape[:2]
    s = min(h, w)
    y, x = (h - s) // 2, (w - s) // 2
    return _bilinear_resize(img[y:y + s, x:x + s], size, size)


def imagenet_hdf5_iterator(h5path: str, batch_size: int,
                           split: str = "train", seed: int = 0,
                           image_size: int = 224,
                           chunk_batches: int = 16):
    """Streaming ImageNet batches from the reference's HDF5 layout
    (``imagenet-shuffled.hdf5`` with ``{split}_img`` [N, H, W, C] uint8 and
    ``{split}_labels`` [N] — VGG/datasets.py:8-36, VGG/dl_trainer.py:262).

    TPU-first IO shape: the reference reads one image per __getitem__
    through DataLoader worker processes — random single-index HDF5 reads
    that thrash the chunk cache. Here a *contiguous* slab of
    ``chunk_batches * batch_size`` images is read per HDF5 access (the file
    is pre-shuffled, hence its name) and augmentation
    (RandomResizedCrop + horizontal flip + ImageNet normalise, matching the
    reference's torchvision transform) runs vectorised in numpy.
    Yields {"image": [B, size, size, 3] f32 NHWC, "label": [B] i32}.
    """
    import h5py

    def gen():
        rng = np.random.RandomState(seed)
        with h5py.File(h5path, "r", libver="latest", swmr=True) as hf:
            imgs = hf[f"{split}_img"]
            labels = np.asarray(hf[f"{split}_labels"]).astype(np.int32)
            n = imgs.shape[0]
            slab = max(batch_size, chunk_batches * batch_size)
            train = split == "train"
            while True:
                starts = np.arange(0, n - batch_size + 1, slab)
                if train:
                    rng.shuffle(starts)
                for s0 in starts:
                    hi = min(n, s0 + slab)
                    raw = np.asarray(imgs[s0:hi])
                    order = (rng.permutation(hi - s0) if train
                             else np.arange(hi - s0))
                    for b0 in range(0, hi - s0 - batch_size + 1, batch_size):
                        sel = order[b0:b0 + batch_size]
                        out = np.empty(
                            (batch_size, image_size, image_size, 3),
                            np.float32)
                        for j, idx in enumerate(sel):
                            im = raw[idx].astype(np.float32) / 255.0
                            if im.ndim == 2:
                                im = np.repeat(im[:, :, None], 3, axis=2)
                            if train:
                                im = _random_resized_crop(im, image_size,
                                                          rng)
                                if rng.rand() < 0.5:
                                    im = im[:, ::-1]
                            else:
                                im = _center_crop(im, image_size)
                            out[j] = (im - IMAGENET_MEAN) / IMAGENET_STD
                        yield {"image": out,
                               "label": labels[s0 + sel]}

    return gen()


def load_ptb(path: str, split: str = "train", num_steps: int = 35):
    """Word-level PTB (reference VGG/ptb_reader.py:32 builds the vocab from
    ptb.train.txt and id-izes each split)."""
    def read(fname):
        with open(os.path.join(path, fname)) as f:
            return f.read().replace("\n", " <eos> ").split()

    train_words = read("ptb.train.txt")
    vocab = {w: i for i, w in enumerate(sorted(set(train_words)))}
    words = train_words if split == "train" else read(f"ptb.{split}.txt")
    ids = np.asarray([vocab[w] for w in words if w in vocab], np.int32)
    n = (len(ids) - 1) // num_steps
    toks = ids[:n * num_steps].reshape(-1, num_steps)
    tgts = ids[1:n * num_steps + 1].reshape(-1, num_steps)
    return {"tokens": toks, "targets": tgts}, len(vocab)


def make_dataset(dataset: str, dnn: str, batch_size: int,
                 path: Optional[str] = None, split: str = "train",
                 seed: int = 0,
                 seq_len: Optional[int] = None) -> Tuple[Iterator, Dict]:
    """Build a batch iterator for (dataset, dnn). Falls back to synthetic
    data when files are absent. ``seq_len`` overrides the per-model default
    token length (BERT long-context runs)."""
    path = path or os.environ.get("OKTOPK_DATA_DIR", "./data")
    try:
        if dataset == "wikipedia":
            from oktopk_tpu.data.bert_pretrain import pretrain_iterator
            from oktopk_tpu.data.tokenization import FullTokenizer
            corpus = os.path.join(path, "wikipedia")
            if not os.path.exists(corpus):
                raise FileNotFoundError(corpus)
            vocab_file = os.path.join(path, "vocab.txt")
            tok = None
            if os.path.exists(vocab_file):
                from oktopk_tpu import native
                if native.resolve("tokenizer"):
                    from oktopk_tpu.native.tokenizer import NativeTokenizer
                    nat = NativeTokenizer(vocab_file)
                    if nat.native:
                        tok = nat
            vocab_size = 1024 if dnn == "bert_tiny" else 30522
            if tok is None:
                # hash fallback must emit ids inside the model's embedding
                # table (OOB ids NaN silently on XLA)
                tok = FullTokenizer(
                    vocab_file if os.path.exists(vocab_file) else None,
                    fallback_size=vocab_size)
            seq = seq_len or (32 if dnn == "bert_tiny" else 128)
            return (pretrain_iterator(corpus, tok, batch_size, seq,
                                      seed, vocab_size),
                    {"synthetic": False, "num_examples": 50000})
        if dataset == "imagenet":
            h5path = os.path.join(path, "imagenet-shuffled.hdf5")
            if not os.path.exists(h5path):
                raise FileNotFoundError(h5path)
            import h5py
            with h5py.File(h5path, "r") as hf:
                key = "train_img" if split == "train" else "val_img"
                num = int(hf[key].shape[0])
            it = imagenet_hdf5_iterator(h5path, batch_size, split=split,
                                        seed=seed)
            return it, {"synthetic": False, "num_examples": num}
        if dataset == "an4":
            from oktopk_tpu.data.audio import an4_iterator
            manifest = os.path.join(
                path, "an4_train_manifest.csv" if split == "train"
                else "an4_val_manifest.csv")
            if not os.path.exists(manifest):
                raise FileNotFoundError(manifest)
            it = an4_iterator(manifest, batch_size, seed=seed,
                              shuffle=split == "train")
            return it, {"synthetic": False, "num_examples": 948}
        if dataset == "cifar10":
            arrays = load_cifar10(path, split)
        elif dataset == "mnist":
            arrays = load_mnist(path, split)
        elif dataset == "ptb":
            arrays, vocab = load_ptb(os.path.join(path, "ptb"), split)
            return (_batched(arrays, batch_size, seed, split == "train"),
                    {"synthetic": False, "vocab_size": vocab,
                     "num_examples": len(arrays["tokens"])})
        else:
            raise FileNotFoundError(dataset)
        return (_batched(arrays, batch_size, seed, split == "train"),
                {"synthetic": False,
                 "num_examples": len(arrays["label"])})
    except (FileNotFoundError, OSError):
        return (synthetic_iterator(dnn, batch_size, seed, seq_len=seq_len),
                {"synthetic": True, "num_examples": 50000})
