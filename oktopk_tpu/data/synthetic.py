"""Deterministic synthetic batches for every workload (shapes/dtypes match
the real pipelines; used for smoke tests, benchmarks and as the zero-egress
fallback)."""

from __future__ import annotations

from typing import Dict, Iterator

import numpy as np


def synthetic_batch(dnn: str, batch_size: int, rng: np.random.RandomState,
                    seq_len: int = None) -> Dict[str, np.ndarray]:
    if dnn in ("lstm", "lstm_tiny"):
        t = seq_len or 35
        vocab = 1024 if dnn == "lstm_tiny" else 10000
        # Bigram-structured sequences (fixed random successor table, 10%
        # uniform noise): uniform-random tokens carry no learnable signal
        # beyond rote memorization, which makes LM loss curves useless for
        # algorithm comparisons; a bigram chain gives every optimizer the
        # same structured next-token task (entropy floor ~0.1*ln(V)), the
        # LM analogue of teacher_iterator's linear teacher for images.
        # The table comes from its own fixed-seed stream — drawing it from
        # ``rng`` would hand the infinite synthetic_iterator a fresh table
        # every batch, leaving no cross-batch signal to learn.
        trans = np.random.RandomState(vocab + 17).randint(
            0, vocab, size=(vocab,))
        toks = np.empty((batch_size, t + 1), np.int64)
        toks[:, 0] = rng.randint(0, vocab, size=(batch_size,))
        for i in range(t):
            noise = rng.rand(batch_size) < 0.1
            toks[:, i + 1] = np.where(
                noise, rng.randint(0, vocab, size=(batch_size,)),
                trans[toks[:, i]])
        return {"tokens": toks[:, :-1].astype(np.int32),
                "targets": toks[:, 1:].astype(np.int32)}
    if dnn.startswith("bert"):
        t = seq_len or (32 if dnn == "bert_tiny" else 128)
        vocab = 1024 if dnn == "bert_tiny" else 30522
        ids = rng.randint(0, vocab, size=(batch_size, t)).astype(np.int32)
        mlm = np.full((batch_size, t), -1, np.int32)
        mask_pos = rng.rand(batch_size, t) < 0.15
        mlm[mask_pos] = ids[mask_pos]
        return {"input_ids": ids,
                "token_type_ids": np.zeros((batch_size, t), np.int32),
                "attention_mask": np.ones((batch_size, t), np.int32),
                "mlm_labels": mlm,
                "nsp_labels": rng.randint(0, 2, size=(batch_size,))
                .astype(np.int32)}
    if dnn.startswith("lstman4"):
        # Tone-coded utterances: each character is rendered as ~8 frames of
        # energy in its own 5-bin frequency band (29 chars * 5 <= 161 bins)
        # over a noise floor. Random spectrograms with random labels carry
        # no audio->text relation, so CTC loss curves on them are
        # meaningless; a tone code gives the model a real alignment task —
        # the CTC analogue of the bigram chain above and the linear teacher
        # of teacher_iterator — so WER from the greedy decoder is a real
        # learning signal (reference trains DeepSpeech on AN4 to WER,
        # LSTM/dl_trainer.py:420-446, decoder VGG/decoder.py:23-197).
        f, t = 161, seq_len or 201
        fpc = 8                           # frames per character
        max_len = max(1, min(20, (t - 1) // fpc))
        min_len = min(5, max_len)         # short seq_len: fewer chars fit
        spect = (0.3 * rng.randn(batch_size, f, t, 1)).astype(np.float32)
        label_lengths = rng.randint(min_len, max_len + 1,
                                    size=(batch_size,)).astype(np.int32)
        labels = np.zeros((batch_size, 40), np.int32)
        for b in range(batch_size):
            ln = int(label_lengths[b])
            seq = rng.randint(1, 29, size=(ln,))
            labels[b, :ln] = seq
            for i, c in enumerate(seq):
                spect[b, c * 5:c * 5 + 5, i * fpc:(i + 1) * fpc, 0] += 1.0
        return {"spect": spect,
                "spect_lengths": (label_lengths * fpc).astype(np.int32),
                "labels": labels,
                "label_lengths": label_lengths}
    if dnn == "mnistnet":
        return {"image": rng.randn(batch_size, 28, 28, 1).astype(np.float32),
                "label": rng.randint(0, 10, size=(batch_size,))
                .astype(np.int32)}
    if dnn == "resnet50":
        return {"image": rng.randn(batch_size, 224, 224, 3)
                .astype(np.float32),
                "label": rng.randint(0, 1000, size=(batch_size,))
                .astype(np.int32)}
    return {"image": rng.randn(batch_size, 32, 32, 3).astype(np.float32),
            "label": rng.randint(0, 10, size=(batch_size,)).astype(np.int32)}


def synthetic_iterator(dnn: str, batch_size: int, seed: int = 0,
                       seq_len: int = None) -> Iterator[Dict[str, np.ndarray]]:
    rng = np.random.RandomState(seed)
    while True:
        yield synthetic_batch(dnn, batch_size, rng, seq_len)


def finite_pool_iterator(dnn: str, batch_size: int, num_examples: int = 256,
                         seed: int = 0,
                         seq_len: int = None) -> Iterator[Dict[str, np.ndarray]]:
    """Finite synthetic dataset, shuffled and recycled forever.

    The convergence analogue of ``teacher_iterator`` for the token
    workloads (BERT/LSTM/CTC), where a linear teacher over pixels doesn't
    apply: a FINITE pool of examples is memorizable, so the loss trend is
    a real optimization signal and dense-vs-sparse gaps on the same pool
    measure the compression (fresh random tokens every step would be
    unfittable in expectation). Used by scripts/convergence.py for
    bert_*/lstm convergence evidence."""
    if batch_size > num_examples:
        raise ValueError(f"batch_size {batch_size} > pool size "
                         f"{num_examples}: the cycle would never yield")
    rng = np.random.RandomState(seed)
    pool = synthetic_batch(dnn, num_examples, rng, seq_len)
    order_rng = np.random.RandomState(seed + 1)
    while True:
        order = order_rng.permutation(num_examples)
        for i in range(0, num_examples - batch_size + 1, batch_size):
            sel = order[i:i + batch_size]
            yield {k: v[sel] for k, v in pool.items()}


def teacher_iterator(dnn: str, batch_size: int, num_examples: int = 512,
                     seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Finite image dataset with *learnable* labels from a fixed random
    linear teacher (label = argmax(W @ flatten(image))).

    Random labels are unfittable in expectation, which makes loss curves
    meaningless for convergence comparisons; a teacher labeling gives every
    optimizer the same structured task, so dense-vs-sparse gaps measure the
    compression, not noise memorisation. Used by the convergence harness
    (scripts/convergence.py, tests/test_convergence.py) — the stand-in for
    the reference's accuracy-log runs (VGG/dl_trainer.py:606-616)."""
    rng = np.random.RandomState(seed)
    proto = synthetic_batch(dnn, num_examples, rng)
    if "image" not in proto:
        raise ValueError(f"teacher_iterator supports image workloads, "
                         f"not {dnn}")
    images = proto["image"]
    nclass = int(proto["label"].max()) + 1
    w = rng.randn(images[0].size, nclass).astype(np.float32)
    logits = images.reshape(num_examples, -1) @ w
    labels = np.argmax(logits, axis=1).astype(np.int32)
    order_rng = np.random.RandomState(seed + 1)
    while True:
        order = order_rng.permutation(num_examples)
        for i in range(0, num_examples - batch_size + 1, batch_size):
            sel = order[i:i + batch_size]
            yield {"image": images[sel], "label": labels[sel]}
