"""Deterministic synthetic batches for every workload (shapes/dtypes match
the real pipelines; used for smoke tests, benchmarks and as the zero-egress
fallback)."""

from __future__ import annotations

from typing import Dict, Iterator

import numpy as np


def synthetic_batch(dnn: str, batch_size: int, rng: np.random.RandomState,
                    seq_len: int = None) -> Dict[str, np.ndarray]:
    if dnn == "lstm":
        t = seq_len or 35
        vocab = 10000
        toks = rng.randint(0, vocab, size=(batch_size, t + 1))
        return {"tokens": toks[:, :-1].astype(np.int32),
                "targets": toks[:, 1:].astype(np.int32)}
    if dnn.startswith("bert"):
        t = seq_len or (32 if dnn == "bert_tiny" else 128)
        vocab = 1024 if dnn == "bert_tiny" else 30522
        ids = rng.randint(0, vocab, size=(batch_size, t)).astype(np.int32)
        mlm = np.full((batch_size, t), -1, np.int32)
        mask_pos = rng.rand(batch_size, t) < 0.15
        mlm[mask_pos] = ids[mask_pos]
        return {"input_ids": ids,
                "token_type_ids": np.zeros((batch_size, t), np.int32),
                "attention_mask": np.ones((batch_size, t), np.int32),
                "mlm_labels": mlm,
                "nsp_labels": rng.randint(0, 2, size=(batch_size,))
                .astype(np.int32)}
    if dnn == "lstman4":
        f, t = 161, seq_len or 201
        return {"spect": rng.randn(batch_size, f, t, 1).astype(np.float32),
                "spect_lengths": np.full((batch_size,), t // 2, np.int32),
                "labels": rng.randint(1, 29, size=(batch_size, 40))
                .astype(np.int32),
                "label_lengths": rng.randint(5, 20, size=(batch_size,))
                .astype(np.int32)}
    if dnn == "mnistnet":
        return {"image": rng.randn(batch_size, 28, 28, 1).astype(np.float32),
                "label": rng.randint(0, 10, size=(batch_size,))
                .astype(np.int32)}
    if dnn == "resnet50":
        return {"image": rng.randn(batch_size, 224, 224, 3)
                .astype(np.float32),
                "label": rng.randint(0, 1000, size=(batch_size,))
                .astype(np.int32)}
    return {"image": rng.randn(batch_size, 32, 32, 3).astype(np.float32),
            "label": rng.randint(0, 10, size=(batch_size,)).astype(np.int32)}


def synthetic_iterator(dnn: str, batch_size: int, seed: int = 0,
                       seq_len: int = None) -> Iterator[Dict[str, np.ndarray]]:
    rng = np.random.RandomState(seed)
    while True:
        yield synthetic_batch(dnn, batch_size, rng, seq_len)
