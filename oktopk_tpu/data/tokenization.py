"""WordPiece tokenization (reference vendored
BERT/bert/transformers/tokenization.py: BasicTokenizer — lowercase, strip
accents, punctuation split — plus greedy longest-match WordpieceTokenizer
over a vocab file). Dependency-free re-implementation; when no vocab file is
available a deterministic hash-vocab fallback keeps the pipelines runnable
in this zero-egress container."""

from __future__ import annotations

import os
import unicodedata
import zlib
from typing import Dict, List, Optional


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


class BasicTokenizer:
    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        if self.do_lower_case:
            text = text.lower()
            text = unicodedata.normalize("NFD", text)
            text = "".join(c for c in text
                           if unicodedata.category(c) != "Mn")
        out: List[str] = []
        word = []
        for ch in text:
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif _is_punct(ch):
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out


class WordpieceTokenizer:
    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_chars: int = 100):
        self.vocab = vocab
        self.unk = unk_token
        self.max_chars = max_chars

    def tokenize(self, token: str) -> List[str]:
        if len(token) > self.max_chars:
            return [self.unk]
        pieces, start = [], 0
        while start < len(token):
            end = len(token)
            cur = None
            while start < end:
                sub = token[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk]
            pieces.append(cur)
            start = end
        return pieces


class FullTokenizer:
    """BasicTokenizer -> WordpieceTokenizer -> ids."""

    SPECIALS = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]

    def __init__(self, vocab_file: Optional[str] = None,
                 do_lower_case: bool = True, fallback_size: int = 30522):
        if vocab_file and os.path.exists(vocab_file):
            self.vocab: Dict[str, int] = {}
            with open(vocab_file, encoding="utf-8") as f:
                for i, line in enumerate(f):
                    self.vocab[line.rstrip("\n")] = i
            self.hash_fallback = False
        else:
            # deterministic hash vocab: specials pinned, everything else
            # bucketed — tokenization stays stable without the real file
            self.vocab = {t: i for i, t in enumerate(self.SPECIALS)}
            self.hash_fallback = True
            self.fallback_size = fallback_size
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(self.vocab)

    @property
    def vocab_size(self) -> int:
        """Id-space size: every emitted id is < vocab_size (model embedding
        tables must be at least this big — OOB ids NaN silently on XLA)."""
        return self.fallback_size if self.hash_fallback else len(self.vocab)

    def tokenize(self, text: str) -> List[str]:
        if self.hash_fallback:
            return self.basic.tokenize(text)
        out = []
        for tok in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(tok))
        return out

    def convert_tokens_to_ids(self, tokens: List[str]) -> List[int]:
        if self.hash_fallback:
            n = self.fallback_size
            ns = len(self.SPECIALS)
            # crc32, not builtin hash(): ids must be stable across
            # processes (a pretrain run and a later fine-tune warm-start
            # must agree), and hash() is salted per interpreter
            return [self.vocab.get(t) if t in self.vocab
                    else ns + (zlib.crc32(t.encode("utf-8")) % (n - ns))
                    for t in tokens]
        return [self.vocab.get(t, self.vocab["[UNK]"]) for t in tokens]

    def encode_pair(self, text_a: str, text_b: Optional[str],
                    max_len: int):
        """[CLS] a [SEP] (b [SEP]) with pair truncation (longest-first, the
        reference's _truncate_seq_pair) and padding to max_len.

        Returns (input_ids, token_type_ids, attention_mask)."""
        ta = self.tokenize(text_a)
        tb = self.tokenize(text_b) if text_b else []
        budget = max_len - (3 if tb else 2)
        while len(ta) + len(tb) > budget:
            (ta if len(ta) > len(tb) else tb).pop()
        tokens = ["[CLS]"] + ta + ["[SEP]"]
        types = [0] * len(tokens)
        if tb:
            tokens += tb + ["[SEP]"]
            types += [1] * (len(tb) + 1)
        ids = self.convert_tokens_to_ids(tokens)
        mask = [1] * len(ids)
        pad = max_len - len(ids)
        return (ids + [0] * pad, types + [0] * pad, mask + [0] * pad)
