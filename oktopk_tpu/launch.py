"""Multi-host launch / rendezvous layer.

Reference C11 (SURVEY.md §2.1): ``BERT/launch.py:108-173`` spawns per-rank
processes with ``--rank/--local_rank`` env, and ``init_distrib_slurm``
(``BERT/bert/main_bert.py:159-203``) discovers rank/world size from
``SLURM_*`` / ``LOCAL_RANK`` env vars, with MASTER_ADDR derived from
``srun hostname`` (``BERT/bert/bert_oktopk.sh:23``).

TPU-native shape: there is no torch.distributed rendezvous — each host runs
the same driver, calls :func:`maybe_initialize` once, and
``jax.distributed.initialize`` wires the hosts into one JAX runtime whose
``jax.devices()`` spans every chip in the slice. After that, "rank" is just
``jax.process_index()`` and model broadcast (reference
``VGG/main_trainer.py:52-54``) is free: replicated shardings under pjit.

Environment discovery order (first match wins):

1. Explicit ``OKTOPK_COORDINATOR`` / ``OKTOPK_NUM_PROCS`` / ``OKTOPK_PROC_ID``.
2. SLURM: ``SLURM_PROCID``/``SLURM_NTASKS``/``SLURM_STEP_NODELIST`` (the
   coordinator is the first host of the nodelist — parsed natively, no
   ``scontrol`` dependency).
3. OpenMPI: ``OMPI_COMM_WORLD_RANK``/``OMPI_COMM_WORLD_SIZE`` (coordinator
   must then come from ``OKTOPK_COORDINATOR``).
4. Cloud TPU metadata: fall back to ``jax.distributed.initialize()`` with no
   arguments, which autodetects on TPU pods.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import List, Optional

DEFAULT_PORT = 8476


@dataclass(frozen=True)
class ProcessEnv:
    """One process's place in the job (reference's rank/world_size pair)."""

    process_id: int
    num_processes: int
    coordinator: Optional[str]  # "host:port" or None (autodetect)
    source: str                 # which discovery rule fired

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def expand_nodelist(nodelist: str) -> List[str]:
    """Expand a compact SLURM nodelist ("nid0[1234-1236,1240],login1") into
    hostnames without shelling out to ``scontrol show hostnames`` (which the
    reference's sbatch scripts rely on implicitly via ``srun hostname``,
    ``BERT/bert/bert_oktopk.sh:23``)."""
    hosts: List[str] = []
    # split on commas not inside brackets
    parts, depth, cur = [], 0, []
    for ch in nodelist:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))

    for part in parts:
        m = re.fullmatch(r"([^\[\]]*)\[([^\]]+)\](.*)", part)
        if not m:
            if part:
                hosts.append(part)
            continue
        prefix, body, suffix = m.groups()
        for item in body.split(","):
            if "-" in item:
                lo, hi = item.split("-", 1)
                width = len(lo)
                for i in range(int(lo), int(hi) + 1):
                    hosts.append(f"{prefix}{i:0{width}d}{suffix}")
            else:
                hosts.append(f"{prefix}{item}{suffix}")
    return hosts


def discover(env: Optional[dict] = None, port: int = DEFAULT_PORT) -> ProcessEnv:
    """Discover this process's coordinates (reference ``init_distrib_slurm``,
    BERT/bert/main_bert.py:159-203 — SLURM first, then explicit env)."""
    e = os.environ if env is None else env

    if "OKTOPK_NUM_PROCS" in e:
        coord = e.get("OKTOPK_COORDINATOR")
        if coord and ":" not in coord:
            coord = f"{coord}:{port}"
        nprocs = int(e["OKTOPK_NUM_PROCS"])
        if nprocs > 1 and "OKTOPK_PROC_ID" not in e:
            # Without a per-host id every host would claim process 0 and the
            # rendezvous would hang waiting for the missing ranks.
            raise RuntimeError(
                "OKTOPK_NUM_PROCS > 1 but OKTOPK_PROC_ID is unset; export a "
                "distinct OKTOPK_PROC_ID in [0, num_procs) on each host")
        return ProcessEnv(
            process_id=int(e.get("OKTOPK_PROC_ID", "0")),
            num_processes=nprocs,
            coordinator=coord, source="explicit")

    if "SLURM_NTASKS" in e and "SLURM_PROCID" in e:
        nodelist = e.get("SLURM_STEP_NODELIST", e.get("SLURM_NODELIST", ""))
        hosts = expand_nodelist(nodelist) if nodelist else []
        coord = f"{hosts[0]}:{port}" if hosts else None
        return ProcessEnv(
            process_id=int(e["SLURM_PROCID"]),
            num_processes=int(e["SLURM_NTASKS"]),
            coordinator=coord, source="slurm")

    if "OMPI_COMM_WORLD_SIZE" in e:
        coord = e.get("OKTOPK_COORDINATOR")
        if coord and ":" not in coord:
            coord = f"{coord}:{port}"
        if coord is None and int(e["OMPI_COMM_WORLD_SIZE"]) > 1:
            raise RuntimeError(
                "OpenMPI launch detected but OKTOPK_COORDINATOR is unset; "
                "export OKTOPK_COORDINATOR=<rank-0 host> on every rank "
                "(jax.distributed cannot autodetect an OpenMPI rendezvous)")
        return ProcessEnv(
            process_id=int(e["OMPI_COMM_WORLD_RANK"]),
            num_processes=int(e["OMPI_COMM_WORLD_SIZE"]),
            coordinator=coord, source="openmpi")

    return ProcessEnv(process_id=0, num_processes=1, coordinator=None,
                      source="single")


_initialized = False


def maybe_initialize(env: Optional[dict] = None, port: int = DEFAULT_PORT,
                     force: bool = False) -> ProcessEnv:
    """Initialize ``jax.distributed`` if this is a multi-process job.

    Idempotent; single-process jobs (and CPU dry runs) skip initialization
    entirely so tests and ``--fake-devices`` paths are unaffected.
    """
    global _initialized
    penv = discover(env, port)
    if penv.num_processes <= 1 and not force:
        return penv
    if _initialized:
        return penv
    import jax

    kwargs = dict(num_processes=penv.num_processes,
                  process_id=penv.process_id)
    if penv.coordinator is not None:
        kwargs["coordinator_address"] = penv.coordinator
    jax.distributed.initialize(**kwargs)
    _initialized = True
    from oktopk_tpu import native
    native.check_multiprocess_consistency()
    return penv
