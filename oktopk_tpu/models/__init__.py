"""Flax model zoo (reference C16/C18, SURVEY.md §2.2).

Reference workload models (`_support_dnns`, VGG/dl_trainer.py:39):
resnet50, resnet20/56/110, vgg19/vgg16, alexnet, lstman4 (DeepSpeech), lstm
(PTB), plus mnistnet and BERT (BERT/bert/transformers/modeling.py).

TPU-first conventions used throughout:
- NHWC layout (XLA TPU's native conv layout);
- a ``dtype`` knob for bfloat16 compute with float32 params;
- BatchNorm takes an optional ``axis_name`` for cross-replica statistics
  (the reference relies on per-GPU batch stats; on a TPU mesh syncing them
  over the data axis is one flag);
- no data-dependent Python control flow inside ``__call__``.
"""

from oktopk_tpu.models.registry import create_model, MODELS  # noqa: F401
from oktopk_tpu.models.vgg import VGG  # noqa: F401
from oktopk_tpu.models.resnet import CifarResNet  # noqa: F401
from oktopk_tpu.models.imagenet_resnet import ResNet50  # noqa: F401
from oktopk_tpu.models.alexnet import AlexNet  # noqa: F401
from oktopk_tpu.models.mnistnet import MnistNet  # noqa: F401
from oktopk_tpu.models.lstm import PTBLSTM  # noqa: F401
from oktopk_tpu.models.deepspeech import DeepSpeech  # noqa: F401
from oktopk_tpu.models.bert import BertConfig, BertForPreTraining  # noqa: F401
