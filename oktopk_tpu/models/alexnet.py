"""AlexNet (reference VGG/models/alexnet.py, CIFAR-sized variant)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = lambda f, k, s=1, p=0: nn.Conv(
            f, (k, k), strides=s, padding=p, dtype=self.dtype)
        x = conv(64, 3, 2, 1)(x); x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = conv(192, 3, 1, 1)(x); x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = conv(384, 3, 1, 1)(x); x = nn.relu(x)
        x = conv(256, 3, 1, 1)(x); x = nn.relu(x)
        x = conv(256, 3, 1, 1)(x); x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
