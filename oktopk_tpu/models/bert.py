"""BERT for pretraining (MLM + NSP), from-scratch Flax.

Reference: the vendored HF modeling (BERT/bert/transformers/modeling.py:
``BertEmbeddings``, ``BertSelfAttention``, ``BertLayer``, ``BertPooler``,
``BertForPreTraining`` with the MLM transform head and NSP classifier; word
embeddings are weight-tied into the MLM decoder — the staged model re-ties
them explicitly at BERT/bert/models/bert/depth=4/__init__.py:17).

TPU-first notes: attention mask enters as an additive bias built once
(the reference materialises the same -10000.0 bias in its InputSource,
BERT/bert/main_bert.py:621-638); all matmuls are dtype-parametric for
bfloat16; shapes are static (fixed seq len, the reference uses 128).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @staticmethod
    def base(**kw) -> "BertConfig":
        return BertConfig(**kw)

    @staticmethod
    def large(**kw) -> "BertConfig":
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                          intermediate_size=4096, **kw)

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        """For tests and dry runs (not in the reference)."""
        return BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                          num_heads=2, intermediate_size=128,
                          max_position=128, **kw)


class BertEmbeddings(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, train: bool = True):
        c = self.cfg
        word = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                        name="word_embeddings")
        pos = nn.Embed(c.max_position, c.hidden_size, dtype=c.dtype,
                       name="position_embeddings")
        typ = nn.Embed(c.type_vocab_size, c.hidden_size, dtype=c.dtype,
                       name="token_type_embeddings")
        positions = jnp.arange(input_ids.shape[1])[None, :]
        x = word(input_ids) + pos(positions) + typ(token_type_ids)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype)(x)
        return nn.Dropout(c.dropout, deterministic=not train)(x)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attn_mask, train: bool = True):
        c = self.cfg
        drop = nn.Dropout(c.dropout, deterministic=not train)
        ln = lambda nm: nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                                     name=nm)
        attn = nn.MultiHeadDotProductAttention(
            num_heads=c.num_heads, qkv_features=c.hidden_size,
            out_features=c.hidden_size, dropout_rate=c.dropout,
            deterministic=not train, dtype=c.dtype, name="attention")
        y = attn(x, x, x, mask=attn_mask)
        x = ln("attention_ln")(x + drop(y))
        h = nn.Dense(c.intermediate_size, dtype=c.dtype, name="intermediate")(x)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(c.hidden_size, dtype=c.dtype, name="output")(h)
        return ln("output_ln")(x + drop(h))


class BertEncoder(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attn_mask, train: bool = True):
        for i in range(self.cfg.num_layers):
            x = BertLayer(self.cfg, name=f"layer_{i}")(x, attn_mask, train)
        return x


class BertModel(nn.Module):
    cfg: BertConfig

    def setup(self):
        self.embeddings = BertEmbeddings(self.cfg)
        self.encoder = BertEncoder(self.cfg)
        self.pooler = nn.Dense(self.cfg.hidden_size, dtype=self.cfg.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 train: bool = True):
        c = self.cfg
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        # boolean attend-mask [B, 1, Tq, Tk] — the semantic equivalent of the
        # reference's additive -10000.0 extended_attention_mask
        # (BERT/bert/main_bert.py:633); flax applies the big-negative fill
        # internally.
        mask = attention_mask[:, None, None, :].astype(bool)
        mask = jnp.broadcast_to(
            mask, (input_ids.shape[0], 1, input_ids.shape[1],
                   input_ids.shape[1]))
        x = self.embeddings(input_ids, token_type_ids, train)
        x = self.encoder(x, mask, train)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled

    def word_embedding_table(self):
        return self.embeddings.variables["params"]["word_embeddings"]["embedding"]


class BertForSequenceClassification(nn.Module):
    """Pooled-output classifier/regressor head for GLUE fine-tuning
    (reference compute_glue_scores.py uses the HF classification head over
    the same pooler)."""
    cfg: BertConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 train: bool = True):
        _, pooled = BertModel(self.cfg, name="bert")(
            input_ids, token_type_ids, attention_mask, train)
        x = nn.Dropout(self.cfg.dropout, deterministic=not train)(pooled)
        logits = nn.Dense(self.num_labels, dtype=self.cfg.dtype)(x)
        return logits.astype(jnp.float32)


class BertForPreTraining(nn.Module):
    """MLM + NSP heads over BertModel; MLM decoder tied to the word
    embedding table (reference modeling.py BertPreTrainingHeads)."""
    cfg: BertConfig

    def setup(self):
        c = self.cfg
        self.bert = BertModel(c)
        self.mlm_dense = nn.Dense(c.hidden_size, dtype=c.dtype)
        self.mlm_ln = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype)
        self.mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                                   (c.vocab_size,))
        self.nsp = nn.Dense(2, dtype=c.dtype)

    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 train: bool = True):
        c = self.cfg
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask,
                                train)
        h = self.mlm_dense(seq)
        h = nn.gelu(h, approximate=False)
        h = self.mlm_ln(h)
        # weight tying: decode against the embedding table
        table = self.bert.embeddings.variables["params"][
            "word_embeddings"]["embedding"]
        mlm_logits = jnp.einsum("bth,vh->btv", h, table.astype(c.dtype))
        mlm_logits = mlm_logits + self.mlm_bias
        nsp_logits = self.nsp(pooled)
        return mlm_logits.astype(jnp.float32), nsp_logits.astype(jnp.float32)
