"""Staged BERT: the pretraining model split for pipeline parallelism.

Reference parity: the staged model zoo (C16) — BERT split into N stages of
``BertLayer``s with an embedding-carrying start stage and a head-carrying
end stage that re-ties the word-embedding table
(/root/reference/BERT/bert/models/bert/depth=4/__init__.py:12-19, stage
modules start_stage.py/intermediate_stage.py/end_stage.py), consumed by the
StageRuntime (BERT/runtime.py:842).

TPU-first decomposition: the reference carves the module list into
heterogeneous stage objects and moves tensors by name between processes.
Under SPMD the pipeline wants one homogeneous program per rank, so the split
is:

- **Pipelined**: the ``num_layers`` transformer blocks, ``layers_per_stage``
  per pipeline rank, parameters stacked on a leading stage axis (sharded
  over the ``pipe`` mesh axis). Every activation on the wire is one
  [mb, T, H] tensor — the restriction parallel/pipeline.py documents.
- **Replicated**: embeddings, pooler and the MLM/NSP heads. Embedding
  lookup is memory-bound-cheap and the head needs the embedding table
  anyway (weight tying), so replicating both keeps the tie exact with zero
  cross-stage traffic — the reference instead passes the table object
  between its first and last stage, which only works because its shipped
  configs run every stage in one process (SURVEY.md §2.3). The cost is the
  LM-head matmul running on every pipe rank; their grads are psum'd over
  the pipe axis (nonzero only where the fwd actually consumed them).

``split``/``merge`` convert between this layout and the single-module
``BertForPreTraining`` params, so checkpoints interchange and equivalence
is testable layer-for-layer.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from oktopk_tpu.models.bert import (BertConfig, BertEmbeddings,
                                    BertForPreTraining, BertLayer)


class StagedBertPretrain:
    """Functional views of BertForPreTraining for the pipeline runtime."""

    def __init__(self, cfg: BertConfig, num_stages: int):
        if cfg.num_layers % num_stages != 0:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by "
                f"num_stages={num_stages}")
        self.cfg = cfg
        self.num_stages = num_stages
        self.layers_per_stage = cfg.num_layers // num_stages
        self._module = BertForPreTraining(cfg)
        self._emb = BertEmbeddings(cfg)
        self._layer = BertLayer(cfg)

    # ---- parameter layout -------------------------------------------------

    def init(self, rng, batch_size: int = 2, seq_len: int = 16):
        ex = jnp.zeros((batch_size, seq_len), jnp.int32)
        return self._module.init(
            {"params": rng, "dropout": jax.random.fold_in(rng, 1)},
            ex, ex, jnp.ones_like(ex), train=False)["params"]

    def split(self, params) -> Tuple[Any, Dict[str, Any]]:
        """Single-module params -> (stage_stack, shared).

        ``stage_stack`` leaves carry a leading [num_stages] axis (shard over
        the pipe axis); per-stage structure is {"sub_0".."sub_{k-1}"} of
        BertLayer params. ``shared`` holds embeddings/pooler/heads."""
        enc = params["bert"]["encoder"]
        k = self.layers_per_stage
        per_stage = [
            {f"sub_{j}": enc[f"layer_{s * k + j}"] for j in range(k)}
            for s in range(self.num_stages)
        ]
        stage_stack = jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)
        shared = {
            "embeddings": params["bert"]["embeddings"],
            "pooler": params["bert"]["pooler"],
            "mlm_dense": params["mlm_dense"],
            "mlm_ln": params["mlm_ln"],
            "mlm_bias": params["mlm_bias"],
            "nsp": params["nsp"],
        }
        return stage_stack, shared

    def merge(self, stage_stack, shared):
        """Inverse of :meth:`split` (checkpoint interchange)."""
        k = self.layers_per_stage
        enc = {}
        for s in range(self.num_stages):
            stage = jax.tree.map(lambda x: x[s], stage_stack)
            for j in range(k):
                enc[f"layer_{s * k + j}"] = stage[f"sub_{j}"]
        return {
            "bert": {"embeddings": shared["embeddings"],
                     "encoder": enc,
                     "pooler": shared["pooler"]},
            "mlm_dense": shared["mlm_dense"],
            "mlm_ln": shared["mlm_ln"],
            "mlm_bias": shared["mlm_bias"],
            "nsp": shared["nsp"],
        }

    # ---- functional pieces ------------------------------------------------

    def attn_mask(self, attention_mask):
        """[B, T] 0/1 -> boolean [B, 1, T, T] attend-mask (models/bert.py)."""
        B, T = attention_mask.shape
        m = attention_mask[:, None, None, :].astype(bool)
        return jnp.broadcast_to(m, (B, 1, T, T))

    def embed(self, shared, input_ids, token_type_ids, train: bool = False,
              rngs=None):
        return self._emb.apply({"params": shared["embeddings"]},
                               input_ids, token_type_ids, train,
                               rngs=rngs)

    def apply_stage(self, stage_params, x, attn_mask, train: bool = False,
                    rngs=None):
        """Run this stage's ``layers_per_stage`` BertLayers."""
        for j in range(self.layers_per_stage):
            x = self._layer.apply({"params": stage_params[f"sub_{j}"]},
                                  x, attn_mask, train, rngs=rngs)
        return x

    def head_logits(self, shared, h, train: bool = False):
        """(mlm_logits, nsp_logits) from final hidden states [B, T, H] —
        the math of BertForPreTraining.__call__ after the encoder."""
        c = self.cfg
        pooled = jnp.tanh(nn.Dense(c.hidden_size, dtype=c.dtype).apply(
            {"params": shared["pooler"]}, h[:, 0]))
        hm = nn.Dense(c.hidden_size, dtype=c.dtype).apply(
            {"params": shared["mlm_dense"]}, h)
        hm = nn.gelu(hm, approximate=False)
        hm = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype).apply(
            {"params": shared["mlm_ln"]}, hm)
        table = shared["embeddings"]["word_embeddings"]["embedding"]
        mlm = jnp.einsum("bth,vh->btv", hm, table.astype(c.dtype))
        mlm = mlm + shared["mlm_bias"]
        nsp = nn.Dense(2, dtype=c.dtype).apply({"params": shared["nsp"]},
                                               pooled)
        return mlm.astype(jnp.float32), nsp.astype(jnp.float32)

    def reference_loss(self, params, batch, train: bool = False, rngs=None):
        """Single-module loss on the same batch (equivalence oracle)."""
        from oktopk_tpu.train import losses
        mlm, nsp = self._module.apply(
            {"params": params}, batch["input_ids"], batch["token_type_ids"],
            batch["attention_mask"], train=train, rngs=rngs)
        loss, _ = losses.bert_pretrain_loss(mlm, nsp, batch["mlm_labels"],
                                            batch["nsp_labels"])
        return loss
