"""Caffe's classic cifar10_quick net (reference VGG/models/caffe_cifar.py:
3 conv-pool stages + 2 dense layers)."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class CaffeCifar(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(32, (5, 5), padding=2, dtype=self.dtype)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((0, 1), (0, 1)))
        x = nn.relu(x)
        x = nn.Conv(32, (5, 5), padding=2, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (3, 3), strides=(2, 2), padding=((0, 1), (0, 1)))
        x = nn.Conv(64, (5, 5), padding=2, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (3, 3), strides=(2, 2), padding=((0, 1), (0, 1)))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(64, dtype=self.dtype)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
