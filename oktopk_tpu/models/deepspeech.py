"""DeepSpeech-style CTC speech model (reference VGG/models/lstm_models.py:148
— 2-conv spectrogram frontend (41x11 s(2,2), 21x11 s(2,1)) + hardtanh, a
stack of bidirectional BatchRNN LSTM layers whose two directions are summed
(:97-106), SequenceWise BatchNorm between layers (:21-43), and a bias-free
classifier head (:199); the AN4 harness builds it with 5 layers × 800 hidden
via VGG/models/lstman4.py:7).

Input here is NHWC-ish [B, freq, time, 1] spectrograms; the head returns
per-frame logits [B, T', num_classes] for ``optax.ctc_loss`` (the TPU
replacement for warpctc, SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


def hardtanh(x, lo=0.0, hi=20.0):
    return jnp.clip(x, lo, hi)


# Net time-axis downsampling of the conv frontend: the first conv strides
# time by 2, the second by 1 (kernel 11, padding (5,5): T -> ceil(T/2)).
# Length metadata from the loaders (input-spectrogram frames) must be
# divided by this before reaching ctc_loss / the decoder, exactly as the
# reference scales lengths by its frontend stride.
CONV_TIME_STRIDE = 2


class BatchRNN(nn.Module):
    """Bidirectional LSTM with summed directions + preceding BatchNorm
    (reference lstm_models.py:83-106)."""
    hidden: int
    batch_norm: bool = True
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.batch_norm:
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             dtype=self.dtype, axis_name=self.axis_name)(x)
        fwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype))
        bwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype),
                     reverse=True, keep_order=True)
        return nn.Bidirectional(fwd, bwd, merge_fn=lambda a, b: a + b)(x)


class DeepSpeech(nn.Module):
    num_classes: int = 29          # AN4 label set incl. blank
    rnn_hidden: int = 800          # reference lstman4 config (SURVEY.md §2.2)
    num_layers: int = 5
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, spect, train: bool = True):
        """spect [B, freq, time, 1] -> logits [B, T', num_classes]."""
        bn = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                  dtype=self.dtype, axis_name=self.axis_name)
        x = nn.Conv(32, (41, 11), strides=(2, 2), padding=((20, 20), (5, 5)),
                    dtype=self.dtype)(spect)
        x = bn()(x)
        x = hardtanh(x)
        x = nn.Conv(32, (21, 11), strides=(2, 1), padding=((10, 10), (5, 5)),
                    dtype=self.dtype)(x)
        x = bn()(x)
        x = hardtanh(x)
        # [B, F', T', 32] -> [B, T', F'*32] (reference collapses channelxfreq
        # before the RNN stack, lstm_models.py:178-184)
        b, f, t, c = x.shape
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape((b, t, f * c))
        first = BatchRNN(self.rnn_hidden, batch_norm=False, dtype=self.dtype,
                         axis_name=self.axis_name)
        x = first(x, train)
        for _ in range(self.num_layers - 1):
            x = BatchRNN(self.rnn_hidden, dtype=self.dtype,
                         axis_name=self.axis_name)(x, train)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, axis_name=self.axis_name)(x)
        logits = nn.Dense(self.num_classes, use_bias=False,
                          dtype=self.dtype)(x)
        return logits.astype(jnp.float32)
