"""DenseNet for CIFAR (reference VGG/models/densenet.py: dense blocks with
growth-rate concatenation, bottleneck option, transition compression)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class DenseLayer(nn.Module):
    growth_rate: int
    bottleneck: bool = True
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        bn = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                  dtype=self.dtype, axis_name=self.axis_name)
        y = nn.relu(bn()(x))
        if self.bottleneck:
            y = nn.Conv(4 * self.growth_rate, (1, 1), use_bias=False,
                        dtype=self.dtype)(y)
            y = nn.relu(bn()(y))
        y = nn.Conv(self.growth_rate, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(y)
        return jnp.concatenate([x, y], axis=-1)


class DenseNet(nn.Module):
    depth: int = 100
    growth_rate: int = 12
    compression: float = 0.5
    num_classes: int = 10
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        bn = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                  dtype=self.dtype, axis_name=self.axis_name)
        n = (self.depth - 4) // 6       # layers per block (bottleneck)
        x = nn.Conv(2 * self.growth_rate, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(x)
        for block in range(3):
            for _ in range(n):
                x = DenseLayer(self.growth_rate, dtype=self.dtype,
                               axis_name=self.axis_name)(x, train)
            if block < 2:
                x = nn.relu(bn()(x))
                out_ch = int(x.shape[-1] * self.compression)
                x = nn.Conv(out_ch, (1, 1), use_bias=False,
                            dtype=self.dtype)(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(bn()(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
