"""ImageNet ResNet-50 (reference VGG/models/imagenet_resnet.py: standard
bottleneck resnet50 used for the imagenet runs)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        bn = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                  dtype=self.dtype, axis_name=self.axis_name)
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = bn()(y); y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), strides=self.strides, padding=1,
                    use_bias=False, dtype=self.dtype)(y)
        y = bn()(y); y = nn.relu(y)
        y = nn.Conv(4 * self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = bn()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(4 * self.filters, (1, 1), strides=self.strides,
                               use_bias=False, dtype=self.dtype)(x)
            residual = bn()(residual)
        return nn.relu(y + residual)


class ResNet50(nn.Module):
    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(64, (7, 7), strides=2, padding=3, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, axis_name=self.axis_name)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, nblocks in enumerate(self.stage_sizes):
            filters = 64 * (2 ** stage)
            for block in range(nblocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = Bottleneck(filters, strides, self.dtype,
                               self.axis_name)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
