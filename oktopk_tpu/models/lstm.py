"""PTB language-model LSTM (reference VGG/models/lstm.py:5 — 2×1500 LSTM,
1500-d embedding, dropout keep 0.35, 35-step truncated BPTT).

The reference threads torch hidden state across iterations and
``repackage_hidden``s it to cut the graph (VGG/models/lstm.py:42); here the
carry is an explicit pytree the trainer passes back in — no graph surgery
needed under jit.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class PTBLSTM(nn.Module):
    vocab_size: int = 10000
    hidden_size: int = 1500
    num_layers: int = 2
    dropout_keep: float = 0.35
    dtype: Any = jnp.float32

    def initial_carry(self, batch_size: int):
        shape = (batch_size, self.hidden_size)
        zeros = jnp.zeros(shape, self.dtype)
        return tuple((zeros, zeros) for _ in range(self.num_layers))

    @nn.compact
    def __call__(self, tokens, carry=None, train: bool = True):
        """tokens [B, T] int32 -> (logits [B, T, V], new_carry)."""
        drop = nn.Dropout(1.0 - self.dropout_keep, deterministic=not train)
        x = nn.Embed(self.vocab_size, self.hidden_size,
                     dtype=self.dtype)(tokens)
        x = drop(x)
        if carry is None:
            carry = self.initial_carry(tokens.shape[0])
        new_carry = []
        for layer in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype)
            c, x = nn.RNN(cell, return_carry=True)(
                x, initial_carry=carry[layer])
            new_carry.append(c)
            x = drop(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype)(x)
        return logits.astype(jnp.float32), tuple(new_carry)
