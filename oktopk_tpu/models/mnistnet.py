"""Small MNIST CNN (reference supports an 'mnistnet' path through
dl_trainer's mnist data prep, VGG/dl_trainer.py:351)."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(32, (5, 5), padding=2, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding=2, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
