"""Pre-activation ResNet for CIFAR (reference VGG/models/preresnet.py:
BN-ReLU-Conv ordering, identity shortcuts)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class PreActBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        bn = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                  dtype=self.dtype, axis_name=self.axis_name)
        y = nn.relu(bn()(x))
        shortcut = x
        if x.shape[-1] != self.filters or self.strides != 1:
            shortcut = nn.Conv(self.filters, (1, 1), strides=self.strides,
                               use_bias=False, dtype=self.dtype)(y)
        y = nn.Conv(self.filters, (3, 3), strides=self.strides, padding=1,
                    use_bias=False, dtype=self.dtype)(y)
        y = nn.relu(bn()(y))
        y = nn.Conv(self.filters, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(y)
        return shortcut + y


class PreResNet(nn.Module):
    depth: int = 110
    num_classes: int = 10
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        assert (self.depth - 2) % 6 == 0
        n = (self.depth - 2) // 6
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(x)
        for stage, filters in enumerate([16, 32, 64]):
            for block in range(n):
                strides = 2 if stage > 0 and block == 0 else 1
                x = PreActBlock(filters, strides, self.dtype,
                                self.axis_name)(x, train)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, axis_name=self.axis_name)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
