"""Model registry (reference ``_support_dnns``, VGG/dl_trainer.py:39, plus
BERT). ``create_model(dnn)`` returns ``(module, example_input_fn)`` where the
example input matches the workload's dataset shapes."""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax.numpy as jnp

from oktopk_tpu.models.alexnet import AlexNet
from oktopk_tpu.models.caffe_cifar import CaffeCifar
from oktopk_tpu.models.densenet import DenseNet
from oktopk_tpu.models.preresnet import PreResNet
from oktopk_tpu.models.resnext import ResNeXt
from oktopk_tpu.models.bert import BertConfig, BertForPreTraining
from oktopk_tpu.models.deepspeech import DeepSpeech
from oktopk_tpu.models.imagenet_resnet import ResNet50
from oktopk_tpu.models.lstm import PTBLSTM
from oktopk_tpu.models.mnistnet import MnistNet
from oktopk_tpu.models.resnet import CifarResNet
from oktopk_tpu.models.vgg import VGG


def _img(h, w, c):
    return lambda bs: jnp.zeros((bs, h, w, c), jnp.float32)


def _tokens(t, vocab):
    return lambda bs: jnp.zeros((bs, t), jnp.int32)


MODELS: Dict[str, Callable[..., Tuple[Any, Callable]]] = {
    "vgg16": lambda **kw: (VGG(name_cfg="vgg16", **kw), _img(32, 32, 3)),
    "vgg19": lambda **kw: (VGG(name_cfg="vgg19", **kw), _img(32, 32, 3)),
    "resnet20": lambda **kw: (CifarResNet(depth=20, **kw), _img(32, 32, 3)),
    "resnet56": lambda **kw: (CifarResNet(depth=56, **kw), _img(32, 32, 3)),
    "resnet110": lambda **kw: (CifarResNet(depth=110, **kw), _img(32, 32, 3)),
    "resnet50": lambda **kw: (ResNet50(**kw), _img(224, 224, 3)),
    "alexnet": lambda **kw: (AlexNet(**kw), _img(32, 32, 3)),
    "densenet100": lambda **kw: (DenseNet(**{"depth": 100, **kw}),
                                 _img(32, 32, 3)),
    "preresnet110": lambda **kw: (PreResNet(**{"depth": 110, **kw}),
                                  _img(32, 32, 3)),
    "resnext29": lambda **kw: (ResNeXt(**{"depth": 29, **kw}),
                               _img(32, 32, 3)),
    "caffe_cifar": lambda **kw: (CaffeCifar(**kw), _img(32, 32, 3)),
    "mnistnet": lambda **kw: (MnistNet(**kw), _img(28, 28, 1)),
    "lstm": lambda **kw: (PTBLSTM(**kw), _tokens(35, 10000)),
    # CPU-mesh-sized PTB LSTM (convergence evidence for the LSTM family,
    # the role bert_tiny plays for BERT). No dropout: the convergence probe
    # memorizes a finite pool, where the reference's keep=0.35 (applied
    # after the embedding and every layer) only drowns the algorithm
    # comparison in noise.
    "lstm_tiny": lambda **kw: (
        PTBLSTM(**{"vocab_size": 1024, "hidden_size": 192,
                   "dropout_keep": 1.0, **kw}),
        _tokens(35, 1024)),
    "lstman4": lambda **kw: (DeepSpeech(**kw),
                             lambda bs: jnp.zeros((bs, 161, 201, 1),
                                                  jnp.float32)),
    # CPU-mesh-sized DeepSpeech (the CTC convergence probe, the role
    # lstm_tiny/bert_tiny play for their families): same 2-conv frontend +
    # summed-bidirectional stack, 2x128 instead of 5x800.
    "lstman4_tiny": lambda **kw: (
        DeepSpeech(**{"rnn_hidden": 128, "num_layers": 2, **kw}),
        lambda bs: jnp.zeros((bs, 161, 201, 1), jnp.float32)),
    "bert_base": lambda **kw: (
        BertForPreTraining(BertConfig.base(**kw)), _tokens(128, 30522)),
    "bert_large": lambda **kw: (
        BertForPreTraining(BertConfig.large(**kw)), _tokens(128, 30522)),
    "bert_tiny": lambda **kw: (
        BertForPreTraining(BertConfig.tiny(**kw)), _tokens(32, 1024)),
}


def create_model(dnn: str, **kw):
    try:
        factory = MODELS[dnn]
    except KeyError:
        raise ValueError(f"unknown dnn {dnn!r}; supported: {sorted(MODELS)}")
    return factory(**kw)
