"""CIFAR ResNets — resnet20/56/110 (reference VGG/models/resnet.py: basic
blocks, widths 16/32/64, n = (depth-2)/6 blocks per stage)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        bn = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                  dtype=self.dtype, axis_name=self.axis_name)
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=self.strides, padding=1,
                    use_bias=False, dtype=self.dtype)(x)
        y = bn()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(y)
        y = bn()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), strides=self.strides,
                               use_bias=False, dtype=self.dtype)(x)
            residual = bn()(residual)
        return nn.relu(y + residual)


class CifarResNet(nn.Module):
    depth: int = 20
    num_classes: int = 10
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        assert (self.depth - 2) % 6 == 0, "depth must be 6n+2"
        n = (self.depth - 2) // 6
        x = nn.Conv(16, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, axis_name=self.axis_name)(x)
        x = nn.relu(x)
        for stage, filters in enumerate([16, 32, 64]):
            for block in range(n):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(filters, strides, self.dtype,
                               self.axis_name)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
