"""ResNeXt for CIFAR (reference VGG/models/resnext.py: grouped-convolution
bottleneck blocks, cardinality x base-width)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class ResNeXtBlock(nn.Module):
    filters: int            # output channels
    cardinality: int = 8
    base_width: int = 64
    strides: int = 1
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        bn = lambda: nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                  dtype=self.dtype, axis_name=self.axis_name)
        width = self.cardinality * self.base_width * self.filters // 256
        y = nn.Conv(width, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = nn.relu(bn()(y))
        y = nn.Conv(width, (3, 3), strides=self.strides, padding=1,
                    feature_group_count=self.cardinality, use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(bn()(y))
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = bn()(y)
        shortcut = x
        if x.shape[-1] != self.filters or self.strides != 1:
            shortcut = nn.Conv(self.filters, (1, 1), strides=self.strides,
                               use_bias=False, dtype=self.dtype)(x)
            shortcut = bn()(shortcut)
        return nn.relu(y + shortcut)


class ResNeXt(nn.Module):
    depth: int = 29
    cardinality: int = 8
    base_width: int = 64
    num_classes: int = 10
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        assert (self.depth - 2) % 9 == 0
        n = (self.depth - 2) // 9
        x = nn.Conv(64, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, axis_name=self.axis_name)(x)
        x = nn.relu(x)
        for stage, filters in enumerate([256, 512, 1024]):
            for block in range(n):
                strides = 2 if stage > 0 and block == 0 else 1
                x = ResNeXtBlock(filters, self.cardinality, self.base_width,
                                 strides, self.dtype,
                                 self.axis_name)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
