"""VGG with BatchNorm (reference VGG/models/vgg.py:14 — 'VGG16' = conv cfg D
with BN + single 512->num_classes classifier head, CIFAR-sized)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

CFG = {
    "vgg11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "vgg13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"],
    "vgg16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"],
    "vgg19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    """CIFAR VGG: conv stacks from CFG, then averaged 1x1 -> Dense head
    (the reference flattens 512*1*1 -> Linear(512, 10),
    VGG/models/vgg.py:20-24)."""

    name_cfg: str = "vgg16"
    num_classes: int = 10
    dtype: Any = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        for v in CFG[self.name_cfg]:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding=1, use_bias=True,
                            dtype=self.dtype)(x)
                x = nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype,
                                 axis_name=self.axis_name)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
