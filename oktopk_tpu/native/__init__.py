"""Native (C++) runtime components, bound through ctypes.

The reference delegates its native performance to external libraries (MPI,
cuDNN, apex — SURVEY.md §2.4); the TPU build keeps the *compute* path in
XLA and implements the host-side runtime pieces natively here:

- ``native/wordpiece.cpp`` — WordPiece tokenizer (the vendored
  BERT/bert/transformers/tokenization.py hot loop);
- ``native/prefetch.cpp`` — background-thread shuffled batch loader (the
  torch DataLoader worker replacement, VGG/dl_trainer.py:286-343).

The library is compiled on first use with the in-image g++ (no pip deps;
pybind11 intentionally avoided — plain C ABI + ctypes). Every consumer
falls back to the pure-Python implementation when a toolchain is missing,
so the framework never hard-requires the native path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")
_LIB_PATH = os.path.join(_HERE, "liboktopk_native.so")

_lock = threading.Lock()
_lib = None
_build_error: str | None = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for f in os.listdir(_SRC_DIR):
        if f.endswith(".cpp") and os.path.getmtime(
                os.path.join(_SRC_DIR, f)) > lib_mtime:
            return True
    return False


def _build() -> None:
    srcs = sorted(
        os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR)
        if f.endswith(".cpp"))
    # compile to a per-pid temp and atomically rename: concurrent processes
    # (multi-rank launch, parallel pytest) must never load a half-written .so
    tmp = f"{_LIB_PATH}.tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-fPIC", "-std=c++17", "-Wall", "-Wextra",
           "-shared", "-pthread", "-o", tmp] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True,
                       timeout=300)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _declare(lib: ctypes.CDLL) -> None:
    i64, i32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)
    lib.okn_wp_new_from_buffer.restype = ctypes.c_void_p
    lib.okn_wp_new_from_buffer.argtypes = [ctypes.c_char_p, i64, ctypes.c_int]
    lib.okn_wp_free.argtypes = [ctypes.c_void_p]
    lib.okn_wp_vocab_size.restype = i64
    lib.okn_wp_vocab_size.argtypes = [ctypes.c_void_p]
    lib.okn_wp_encode.restype = i64
    lib.okn_wp_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i32p, i64]
    lib.okn_wp_encode_pair.restype = i64
    lib.okn_wp_encode_pair.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, i64,
        ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p]

    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.okn_loader_new.restype = ctypes.c_void_p
    lib.okn_loader_new.argtypes = [u8p, i64, i64, i64, ctypes.c_uint64,
                                   i64, i64, i64, ctypes.c_int]
    lib.okn_loader_next.restype = i64
    lib.okn_loader_next.argtypes = [ctypes.c_void_p, u8p]
    lib.okn_loader_free.argtypes = [ctypes.c_void_p]


def load():
    """The shared library, building it if needed; None when unavailable
    (no g++, sandboxed filesystem, OKTOPK_NO_NATIVE=1)."""
    global _lib, _build_error
    if os.environ.get("OKTOPK_NO_NATIVE") == "1":
        return None
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if _needs_build():
                _build()
            _lib = ctypes.CDLL(_LIB_PATH)
            _declare(_lib)
        except Exception as e:  # toolchain missing, etc. — fall back
            _build_error = str(e)
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def build_error() -> str | None:
    load()
    return _build_error
