"""Native (C++) runtime components, bound through ctypes.

The reference delegates its native performance to external libraries (MPI,
cuDNN, apex — SURVEY.md §2.4); the TPU build keeps the *compute* path in
XLA and implements the host-side runtime pieces natively here:

- ``native/wordpiece.cpp`` — WordPiece tokenizer (the vendored
  BERT/bert/transformers/tokenization.py hot loop);
- ``native/prefetch.cpp`` — background-thread shuffled batch loader (the
  torch DataLoader worker replacement, VGG/dl_trainer.py:286-343).

The library is compiled on first use with the in-image g++ (no pip deps;
pybind11 intentionally avoided — plain C ABI + ctypes). Every consumer
falls back to the pure-Python implementation when a toolchain is missing,
so the framework never hard-requires the native path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")
_LIB_PATH = os.path.join(_HERE, "liboktopk_native.so")

_lock = threading.Lock()
_lib = None
_build_error: str | None = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for f in os.listdir(_SRC_DIR):
        if f.endswith(".cpp") and os.path.getmtime(
                os.path.join(_SRC_DIR, f)) > lib_mtime:
            return True
    return False


def _build() -> None:
    srcs = sorted(
        os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR)
        if f.endswith(".cpp"))
    # compile to a per-pid temp and atomically rename: concurrent processes
    # (multi-rank launch, parallel pytest) must never load a half-written .so
    tmp = f"{_LIB_PATH}.tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-fPIC", "-std=c++17", "-Wall", "-Wextra",
           "-shared", "-pthread", "-o", tmp] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True,
                       timeout=300)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _declare(lib: ctypes.CDLL) -> None:
    i64, i32p = ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)
    lib.okn_wp_new_from_buffer.restype = ctypes.c_void_p
    lib.okn_wp_new_from_buffer.argtypes = [ctypes.c_char_p, i64, ctypes.c_int]
    lib.okn_wp_free.argtypes = [ctypes.c_void_p]
    lib.okn_wp_vocab_size.restype = i64
    lib.okn_wp_vocab_size.argtypes = [ctypes.c_void_p]
    lib.okn_wp_encode.restype = i64
    lib.okn_wp_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p, i32p, i64]
    lib.okn_wp_encode_pair.restype = i64
    lib.okn_wp_encode_pair.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, i64,
        ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p]

    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.okn_loader_new.restype = ctypes.c_void_p
    lib.okn_loader_new.argtypes = [u8p, i64, i64, i64, ctypes.c_uint64,
                                   i64, i64, i64, ctypes.c_int]
    lib.okn_loader_next.restype = i64
    lib.okn_loader_next.argtypes = [ctypes.c_void_p, u8p]
    lib.okn_loader_stop.argtypes = [ctypes.c_void_p]
    lib.okn_loader_free.argtypes = [ctypes.c_void_p]


def load():
    """The shared library, building it if needed; None when unavailable
    (no g++, sandboxed filesystem, OKTOPK_NO_NATIVE=1)."""
    global _lib, _build_error
    if os.environ.get("OKTOPK_NO_NATIVE") == "1":
        return None
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if _needs_build():
                _build()
            _lib = ctypes.CDLL(_LIB_PATH)
            _declare(_lib)
        except Exception as e:  # toolchain missing, etc. — fall back
            _build_error = str(e)
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def build_error() -> str | None:
    load()
    return _build_error


_resolved: dict = {}

_OFF_MODES = ("0", "off", "no", "false")
_REQUIRE_MODES = ("1", "require", "on", "true")


def _multi_process() -> bool:
    """True when this is one process of a multi-host run. Probes the
    jax.distributed global state directly — NOT jax.process_count(), which
    initialises a backend (here that would lock in the axon TPU plugin
    before the caller can force a platform). Not initialised ⇒ treated as
    single-process; launch.maybe_initialize() re-checks consistency after
    rendezvous (check_multiprocess_consistency)."""
    import sys
    if sys.modules.get("jax") is None:
        return False
    try:
        from jax._src import distributed
        n = getattr(distributed.global_state, "num_processes", None)
        return n is not None and n > 1
    except Exception:
        return False


def resolve(component: str) -> bool:
    """Whether ``component`` ("loader", "tokenizer") should use the native
    path. Policy via OKTOPK_NATIVE:

    - ``1``/``require``/``on`` — native required; raises if the toolchain is
      missing (so a multi-host run fails loudly instead of diverging);
    - ``0``/``off``/``no`` (or legacy OKTOPK_NO_NATIVE=1) — pure Python;
    - unset/``auto`` — native when available in *single-process* runs only.

    In multi-process runs ``auto`` resolves to the Python path: the native
    shuffle (splitmix64 Fisher-Yates) and tokenizer are each deterministic
    but differ from their Python counterparts, so a per-host build failure
    under a silent try/except would feed hosts different data into the same
    sharded step with no error (advisor finding r1). The choice must be a
    global config decision, not per-host toolchain luck.
    """
    mode = os.environ.get("OKTOPK_NATIVE", "auto").strip().lower()
    if os.environ.get("OKTOPK_NO_NATIVE") == "1":
        mode = "0"
    key = (component, mode, _multi_process())
    if key in _resolved:
        return _resolved[key]
    import logging
    log = logging.getLogger("oktopk_tpu.native")
    if mode in _OFF_MODES:
        use = False
        log.info("native %s: disabled (OKTOPK_NATIVE=%s)", component, mode)
    elif mode in _REQUIRE_MODES:
        if load() is None:
            raise RuntimeError(
                f"OKTOPK_NATIVE={mode} but the native library is "
                f"unavailable for {component}: {build_error()}")
        use = True
        log.info("native %s: enabled (required)", component)
    else:  # auto
        if _multi_process():
            use = False
            log.info("native %s: off in multi-process run under auto "
                     "policy (set OKTOPK_NATIVE=1 to force it everywhere)",
                     component)
        else:
            use = load() is not None
            log.info("native %s: %s (auto%s)", component,
                     "enabled" if use else "unavailable, python fallback",
                     "" if use else f"; {build_error()}")
    _resolved[key] = use
    return use


def check_multiprocess_consistency() -> None:
    """Called by launch.maybe_initialize() right after
    jax.distributed.initialize. If a component already resolved to the
    native path under the 'auto' policy while this process looked
    single-process (data pipeline built before rendezvous), the choice was
    per-host toolchain luck after all — refuse to continue rather than let
    hosts silently shuffle/tokenize differently (advisor finding r1)."""
    if not _multi_process():
        return
    # ANY pre-rendezvous auto resolution is unverifiable cross-host — a host
    # that resolved to python (toolchain failure) is just as divergent as one
    # that resolved to native, and must error here rather than hang in the
    # first collective while its peer raises.
    tainted = [comp for (comp, mode, multi), use in _resolved.items()
               if mode not in _OFF_MODES + _REQUIRE_MODES and not multi]
    if tainted:
        raise RuntimeError(
            "native components %s were auto-resolved before "
            "jax.distributed.initialize; in multi-host runs set "
            "OKTOPK_NATIVE=1 (require everywhere) or OKTOPK_NATIVE=0 "
            "(disable everywhere) explicitly" % sorted(set(tainted)))
