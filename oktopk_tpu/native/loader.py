"""ctypes wrapper over the native prefetching batch loader
(native/prefetch.cpp) — the torch-DataLoader-worker replacement
(reference VGG/dl_trainer.py:286-343, DistributedSampler partitioning
:336-343).

The dataset is handed over as one contiguous records array; a C++ thread
gathers shuffled batches into a ring of buffers, so batch assembly overlaps
the device step without touching the GIL."""

from __future__ import annotations

import ctypes
import threading
from typing import Dict, Iterator, Optional

import numpy as np

from oktopk_tpu.native import load


class PrefetchLoader:
    """Iterate shuffled batches of a structured record array.

    ``arrays`` maps field name -> np.ndarray with a common leading dim; the
    fields are packed into one byte-record per example (so one memcpy moves
    an example) and unpacked to the original dtypes/shapes per batch.
    """

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int,
                 seed: int = 0, shard: int = 0, num_shards: int = 1,
                 prefetch_depth: int = 2, drop_last: bool = True):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable: "
                               "use the Python batcher instead")
        self._lib = lib
        names = sorted(arrays)
        n = arrays[names[0]].shape[0]
        for k in names:
            assert arrays[k].shape[0] == n, f"ragged field {k}"
        if n // max(1, num_shards) == 0:
            raise ValueError(
                f"shard {shard}/{num_shards} of {n} records is empty")

        self._fields = []
        contiguous = {}
        offset = 0
        for k in names:
            a = np.ascontiguousarray(arrays[k])
            contiguous[k] = a
            item_shape = a.shape[1:]
            nbytes = int(a.dtype.itemsize * np.prod(item_shape, dtype=int))
            self._fields.append((k, a.dtype, item_shape, offset, nbytes))
            offset += nbytes
        self._item_bytes = offset
        self.batch_size = batch_size
        self.num_examples = n

        # pack fields into one records buffer (kept alive: the C++ side
        # borrows this pointer for the loader's lifetime)
        self._records = np.empty((n, self._item_bytes), np.uint8)
        for k, dtype, item_shape, off, nbytes in self._fields:
            self._records[:, off:off + nbytes] = (
                contiguous.pop(k).reshape(n, -1).view(np.uint8))
        self._out = np.empty((batch_size, self._item_bytes), np.uint8)

        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._handle = lib.okn_loader_new(
            self._records.ctypes.data_as(u8p), n, self._item_bytes,
            batch_size, seed, shard, num_shards, prefetch_depth,
            1 if drop_last else 0)
        # close() must not free the C loader while another thread is inside
        # okn_loader_next (or between reading the handle and entering it):
        # in-flight calls are counted under _mu and close() drains them
        # after okn_loader_stop wakes any blocked waiter.
        self._mu = threading.Condition()
        self._inflight = 0

    def next_batch(self) -> Dict[str, np.ndarray]:
        u8p = ctypes.POINTER(ctypes.c_uint8)
        with self._mu:
            handle = self._handle
            if handle is None:
                count = 0
            else:
                self._inflight += 1
        if handle is not None:
            try:
                count = self._lib.okn_loader_next(
                    handle, self._out.ctypes.data_as(u8p))
            finally:
                with self._mu:
                    self._inflight -= 1
                    self._mu.notify_all()
        batch = self._out[:count]
        out = {}
        for k, dtype, item_shape, off, nbytes in self._fields:
            # copy() (not ascontiguousarray, which no-ops on a contiguous
            # single-field slice): the returned arrays must not alias the
            # ring output buffer the next next_batch() overwrites
            raw = batch[:, off:off + nbytes].copy()
            out[k] = raw.view(dtype).reshape((count,) + item_shape)
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.next_batch()

    def close(self) -> None:
        if getattr(self, "_handle", None) is None:
            return
        with self._mu:
            handle, self._handle = self._handle, None
            if handle is None:
                return
        self._lib.okn_loader_stop(handle)  # wake blocked next_batch calls
        with self._mu:
            while self._inflight:
                self._mu.wait()
        self._lib.okn_loader_free(handle)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def make_prefetch_iter(arrays: Dict[str, np.ndarray], batch_size: int,
                       seed: int = 0, shard: int = 0,
                       num_shards: int = 1) -> Optional[Iterator]:
    """Prefetching batch iterator, or None when the native lib is absent
    (callers fall back to the Python batcher)."""
    if load() is None:
        return None
    return iter(PrefetchLoader(arrays, batch_size, seed=seed, shard=shard,
                               num_shards=num_shards))
