"""ctypes wrapper over the native WordPiece tokenizer
(native/wordpiece.cpp), API-compatible with
``oktopk_tpu.data.tokenization.FullTokenizer`` for the encoding entry
points the pipelines use (``encode`` and ``encode_pair``)."""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

from oktopk_tpu.native import load


class NativeTokenizer:
    """Vocab-file WordPiece encoder backed by the C++ implementation.

    Falls back transparently to the Python FullTokenizer when the native
    library is unavailable (``.native`` tells which one is active).
    """

    def __init__(self, vocab_file: str, do_lower_case: bool = True,
                 max_ids: int = 4096):
        with open(vocab_file, encoding="utf-8") as f:
            vocab_text = f.read()
        lines = vocab_text.split("\n")
        if lines and lines[-1] == "":
            lines.pop()  # trailing newline is not an entry
        self._vocab = {tok: i for i, tok in enumerate(lines)}
        self.cls_id = self._vocab.get("[CLS]", 2)
        self.sep_id = self._vocab.get("[SEP]", 3)
        self._max_ids = max_ids

        lib = load()
        self._lib = lib
        self._handle = None
        if lib is not None:
            buf = "\n".join(lines).encode("utf-8")
            self._handle = lib.okn_wp_new_from_buffer(
                buf, len(buf), 1 if do_lower_case else 0)
        if self._handle is None:
            from oktopk_tpu.data.tokenization import FullTokenizer
            self._py = FullTokenizer(vocab_file, do_lower_case)
        else:
            self._py = None

    @property
    def native(self) -> bool:
        return self._handle is not None

    @property
    def vocab(self):
        """token -> id mapping (drop-in for FullTokenizer.vocab)."""
        return self._vocab

    @property
    def vocab_size(self) -> int:
        return len(self._vocab)

    def encode(self, text: str) -> List[int]:
        """text -> wordpiece ids (no specials)."""
        if self._handle is None:
            return self._py.convert_tokens_to_ids(self._py.tokenize(text))
        utf8 = text.encode("utf-8")
        cap = self._max_ids
        while True:
            out = np.empty(cap, np.int32)
            n = self._lib.okn_wp_encode(
                self._handle, utf8,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
            if n <= cap:  # n > cap signals truncation: grow and retry
                return out[:n].tolist()
            cap = int(n)

    def encode_pair(self, text_a: str, text_b: Optional[str],
                    max_len: int) -> Tuple[List[int], List[int], List[int]]:
        """[CLS] a [SEP] (b [SEP]) padded to max_len ->
        (input_ids, token_type_ids, attention_mask)."""
        if self._handle is None:
            return self._py.encode_pair(text_a, text_b, max_len)
        ids = np.empty(max_len, np.int32)
        types = np.empty(max_len, np.int32)
        mask = np.empty(max_len, np.int32)
        p = ctypes.POINTER(ctypes.c_int32)
        self._lib.okn_wp_encode_pair(
            self._handle, text_a.encode("utf-8"),
            (text_b or "").encode("utf-8"), max_len,
            self.cls_id, self.sep_id,
            ids.ctypes.data_as(p), types.ctypes.data_as(p),
            mask.ctypes.data_as(p))
        return ids.tolist(), types.tolist(), mask.tolist()

    def __del__(self):
        lib, handle = getattr(self, "_lib", None), getattr(self, "_handle",
                                                           None)
        if lib is not None and handle is not None:
            lib.okn_wp_free(handle)
