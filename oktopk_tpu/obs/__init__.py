"""Unified observability layer: typed run-journal events, wire-level
volume conformance, and anomaly-triggered tracing.

Deliberately import-free: ``autotune/journal.py`` imports
``obs.events`` (for the schema version) while ``obs.journal`` imports
``autotune/journal.py`` (for the environment header and JSONL reader).
Importing either submodule here would close that loop into a cycle, so
callers import the submodules directly:

  - :mod:`oktopk_tpu.obs.events`  — schema-versioned event definitions +
    validation (no oktopk imports at all).
  - :mod:`oktopk_tpu.obs.journal` — :class:`EventBus` and
    :class:`RunJournal` (the single per-run JSONL sink).
  - :mod:`oktopk_tpu.obs.volume`  — per-algorithm analytic wire-byte
    budgets and conformance ratios.
  - :mod:`oktopk_tpu.obs.tracing` — :class:`AnomalyTracer` (bounded
    ``jax.profiler`` windows armed by guard trips) and
    :class:`ChromeTraceSink` (host-phase Chrome trace export).
  - :mod:`oktopk_tpu.obs.regress` — step-time regression detection
    against the repo's BENCH_r*.json trajectory.
"""
