"""Unified observability layer: typed run-journal events, wire-level
volume conformance, and anomaly-triggered tracing.

Deliberately import-free: ``autotune/journal.py`` imports
``obs.events`` (for the schema version) while ``obs.journal`` imports
``autotune/journal.py`` (for the environment header and JSONL reader).
Importing either submodule here would close that loop into a cycle, so
callers import the submodules directly:

  - :mod:`oktopk_tpu.obs.events`  — schema-versioned event definitions +
    validation (no oktopk imports at all).
  - :mod:`oktopk_tpu.obs.journal` — :class:`EventBus` and
    :class:`RunJournal` (the single per-run JSONL sink).
  - :mod:`oktopk_tpu.obs.volume`  — per-algorithm analytic wire-byte
    budgets and conformance ratios.
  - :mod:`oktopk_tpu.obs.tracing` — :class:`AnomalyTracer` (bounded
    ``jax.profiler`` windows armed by guard trips) and
    :class:`ChromeTraceSink` (host-phase Chrome trace export).
  - :mod:`oktopk_tpu.obs.regress` — step-time regression detection
    against the repo's BENCH_r*.json trajectory (plus quality-summary
    watching and baseline-gap warnings).
  - :mod:`oktopk_tpu.obs.quality` — in-jit signal-fidelity taps:
    per-bucket compression error, residual growth, effective density,
    threshold drift and winner-index churn (docs/OBSERVABILITY.md
    "Signal fidelity").
  - :mod:`oktopk_tpu.obs.metrics_buffer` — the device-side metric ring
    the taps accumulate into (host flush only on the configured
    cadence; zero steady-state syncs).
  - :mod:`oktopk_tpu.obs.rollup` — windowed rollups over flushed
    quality events with breach detection feeding the closed-loop
    seams.
  - :mod:`oktopk_tpu.obs.export` — Prometheus-textfile export of the
    latest quality rollups.
"""
