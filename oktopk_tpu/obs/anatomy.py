"""Step-anatomy plane: phase annotation contract + device-trace attribution.

The jitted train step is one opaque XLA program; the reference's
per-phase timers (VGG/allreducer.py:256-262) have no analogue inside
it. This module gives the step a time-domain anatomy in three pieces:

1. **Naming contract** — ``scope_name(phase, bucket)`` produces names
   like ``anat/b003/exchange``. ``phase_scope(...)`` wraps pipeline
   regions in ``jax.named_scope`` so the names reach compiled-HLO op
   metadata (``op_name="jit(step)/.../anat/b000/select/..."``) and
   therefore the device lanes of a ``jax.profiler`` capture on
   backends that attribute per-op device time (TPU). The scopes are
   pure metadata: computation is bit-identical annotations-on vs
   annotations-off and no host callback is ever introduced
   (tests/test_anatomy.py pins both). ``trace_annotation(...)`` is the
   host-side twin (``jax.profiler.TraceAnnotation``) used by capture
   drivers on backends whose traces carry no per-op device lanes
   (CPU: only host threads appear, so the driver dispatches per-phase
   subprograms under annotations instead).

2. **Trace analyzer** — parses captured profiler output (the perfetto
   trace-event JSON ``jax.profiler.start_trace(...,
   create_perfetto_trace=True)`` writes, or any Chrome trace-event
   file incl. ChromeTraceSink's, plus checked-in synthetic fixtures in
   CI) into per-(bucket, phase) durations, classifies events into
   compute vs collective lanes, computes the compute/comm overlap
   ratio and a time-sweep critical-path attribution of the measured
   span.

3. **Journal events** — ``step_anatomy`` (one per bucket; model-level
   unbucketed phases land on bucket -1) and one ``overlap_report``
   carrying the scorecard: measured span vs the ideal fully-overlapped
   lower bound ``max(compute_ms, comm_ms)``. Malformed or empty traces
   journal one ``anatomy_warning`` — analysis never raises
   (observability must never take down the thing it observes).

Scorecard semantics (docs/OBSERVABILITY.md "Step anatomy"):
``overlap_ratio = overlap_ms / comm_ms`` — the fraction of collective
time hidden under compute. A fully serial step scores 0.0; the
ROADMAP's bucket-pipelined overlap item is judged by how far it moves
this number toward 1.0 while ``step_ms`` approaches ``ideal_ms``.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, List, Optional, Tuple

SCOPE_PREFIX = "anat"

# the phase vocabulary of the collectives pipeline, in pipeline order
PHASES = ("fwd_bwd", "select", "stage", "exchange", "combine", "optimizer")

# phases whose time is wire time; everything else in the contract is
# compute. Raw op names matching _COLLECTIVE_OPS inside a contract
# scope are classified collective regardless of phase (a psum inside a
# select region is still wire time).
COLLECTIVE_PHASES = frozenset({"exchange"})
_COLLECTIVE_OPS = re.compile(
    r"all-to-all|all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|alltoall|allreduce|allgather|ppermute\b|\bpsum\b", re.I)

_BUCKET_RE = re.compile(r"^b(\d+)$")
# Optional hierarchy-level lane (collectives/hierarchical.py):
# ``anat/b000/lvl1/exchange`` — level 0 = intra-pod, level 1 = inter-pod.
# Legacy names carry no lvl component and parse exactly as before.
_LEVEL_RE = re.compile(r"^lvl(\d+)$")

# module-level switch for the bit-identity test and for opting the
# annotations out entirely (OKTOPK_ANATOMY=0). Scopes are applied at
# trace time, so flipping this only affects steps built afterwards.
_ENABLED = os.environ.get("OKTOPK_ANATOMY", "1").lower() not in (
    "0", "false", "off")


def set_annotations(enabled: bool) -> bool:
    """Enable/disable the in-jit named scopes; returns the previous
    setting. Affects only steps traced after the call."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(enabled)
    return prev


def annotations_enabled() -> bool:
    return _ENABLED


def scope_name(phase: Optional[str] = None,
               bucket: Optional[int] = None,
               level: Optional[int] = None) -> str:
    """The contract name: ``anat``, ``anat/b003``, ``anat/select``,
    ``anat/b003/select`` or — with a hierarchy level —
    ``anat/b003/lvl1/exchange``."""
    parts = [SCOPE_PREFIX]
    if bucket is not None:
        parts.append(f"b{int(bucket):03d}")
    if level is not None:
        parts.append(f"lvl{int(level)}")
    if phase is not None:
        parts.append(str(phase))
    return "/".join(parts)


def phase_scope(phase: Optional[str] = None, bucket: Optional[int] = None,
                level: Optional[int] = None):
    """``jax.named_scope`` bearing the contract name (nullcontext when
    annotations are disabled). Pure metadata — usable inside jit,
    shard_map and ``lax.cond`` branches."""
    if not _ENABLED:
        return nullcontext()
    import jax
    return jax.named_scope(scope_name(phase, bucket, level))


@contextmanager
def trace_annotation(phase: Optional[str] = None,
                     bucket: Optional[int] = None):
    """Host-side ``jax.profiler.TraceAnnotation`` with the contract
    name — the capture-driver twin of :func:`phase_scope` for backends
    whose device traces carry no per-op lanes. Degrades to a no-op if
    the profiler annotation cannot start."""
    name = scope_name(phase, bucket)
    try:
        import jax
        cm = jax.profiler.TraceAnnotation(name)
    except Exception:
        cm = nullcontext()
    with cm:
        yield


def parse_scope_level(
        name: Any) -> Optional[Tuple[Optional[str], Optional[int],
                                     Optional[int]]]:
    """Extract ``(phase, bucket, level)`` from any name carrying the
    contract — a bare annotation (``anat/b000/select``,
    ``anat/b000/lvl1/exchange``) or a compiled-HLO op path
    (``jit(step)/.../anat/b000/anat/select/add``). Nested scopes merge:
    bucket, level and phase may come from different ``anat`` components.
    Returns None when the name carries no contract component; ``level``
    is None for legacy (single-level) names."""
    if not isinstance(name, str) or SCOPE_PREFIX not in name:
        return None
    parts = name.split("/")
    phase: Optional[str] = None
    bucket: Optional[int] = None
    level: Optional[int] = None
    seen = False
    for i, part in enumerate(parts):
        if part != SCOPE_PREFIX:
            continue
        seen = True
        j = i + 1
        if j < len(parts):
            m = _BUCKET_RE.match(parts[j])
            if m:
                bucket = int(m.group(1))
                j += 1
        if j < len(parts):
            m = _LEVEL_RE.match(parts[j])
            if m:
                level = int(m.group(1))
                j += 1
        if j < len(parts) and parts[j] in PHASES:
            phase = parts[j]
    return (phase, bucket, level) if seen else None


def parse_scope(name: Any) -> Optional[Tuple[Optional[str], Optional[int]]]:
    """Legacy ``(phase, bucket)`` view of :func:`parse_scope_level` —
    level-lane components are transparent, so names with and without a
    ``lvlN`` component round-trip identically."""
    parsed = parse_scope_level(name)
    return None if parsed is None else parsed[:2]


def lane_of(phase: Optional[str], name: str = "") -> str:
    """compute vs collective lane for one contract-scoped event."""
    if phase in COLLECTIVE_PHASES or _COLLECTIVE_OPS.search(name or ""):
        return "collective"
    return "compute"


# ---------------------------------------------------------------------------
# trace loading


def find_trace_file(path: str) -> Optional[str]:
    """Resolve ``path`` to one trace-event JSON file. A file path is
    used as-is; a profiler logdir is searched for the newest capture
    (``plugins/profile/<ts>/*trace.json[.gz]`` is where
    ``jax.profiler.start_trace`` puts perfetto output)."""
    if os.path.isfile(path):
        return path
    if not os.path.isdir(path):
        return None
    patterns = ("**/perfetto_trace.json.gz", "**/*.trace.json.gz",
                "**/*.trace.json", "**/*.json")
    candidates: List[str] = []
    for pat in patterns:
        candidates = glob.glob(os.path.join(path, pat), recursive=True)
        if candidates:
            break
    if not candidates:
        return None
    return max(candidates, key=os.path.getmtime)


def load_trace_events(path: str) -> Tuple[List[Dict[str, Any]],
                                          Optional[str], Optional[str]]:
    """``(events, resolved_path, problem)``. Never raises: an
    unreadable/malformed trace returns ``([], path, reason)``. Accepts
    ``{"traceEvents": [...]}`` docs and bare event lists, gzipped or
    plain."""
    resolved = find_trace_file(path)
    if resolved is None:
        return [], None, f"no trace file under {path!r}"
    try:
        opener = gzip.open if resolved.endswith(".gz") else open
        with opener(resolved, "rt") as f:
            doc = json.load(f)
    except Exception as e:
        return [], resolved, f"unreadable trace: {e!r}"
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
    elif isinstance(doc, list):
        events = doc
    else:
        events = None
    if not isinstance(events, list):
        return [], resolved, "trace carries no traceEvents list"
    return [e for e in events if isinstance(e, dict)], resolved, None


# ---------------------------------------------------------------------------
# analysis


def _merged(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _union_ms(intervals: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in _merged(intervals))


def _intersection_ms(a: List[Tuple[float, float]],
                     b: List[Tuple[float, float]]) -> float:
    am, bm = _merged(a), _merged(b)
    i = j = 0
    total = 0.0
    while i < len(am) and j < len(bm):
        lo = max(am[i][0], bm[j][0])
        hi = min(am[i][1], bm[j][1])
        if hi > lo:
            total += hi - lo
        if am[i][1] <= bm[j][1]:
            i += 1
        else:
            j += 1
    return total


def analyze_events(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Attribute contract-scoped trace events into the step anatomy.

    Returns None when no contract event is present (the caller
    journals an ``anatomy_warning``). Times in the trace are
    microseconds (trace-event convention); everything returned is
    milliseconds."""
    spans: List[Tuple[float, float, Optional[str], Optional[int], str,
                      Optional[int]]] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        parsed = parse_scope_level(e.get("name"))
        if parsed is None:
            continue
        ts, dur = e.get("ts"), e.get("dur")
        if not isinstance(ts, (int, float)) or not isinstance(
                dur, (int, float)) or dur < 0:
            continue
        phase, bucket, level = parsed
        start, end = float(ts) / 1e3, (float(ts) + float(dur)) / 1e3
        spans.append((start, end, phase, bucket,
                      lane_of(phase, str(e.get("name"))), level))
    if not spans:
        return None

    t0 = min(s for s, *_ in spans)
    # per-(bucket, phase) totals; phase-less contract events (a bare
    # "anat/b000" container) attribute to phase "other". Level-tagged
    # spans (hierarchical collectives) get their own lane key
    # ("lvl1/exchange") so the two levels of one phase never merge;
    # legacy keys are unchanged.
    per: Dict[Tuple[int, str], Dict[str, Any]] = {}
    compute_iv: List[Tuple[float, float]] = []
    comm_iv: List[Tuple[float, float]] = []
    for start, end, phase, bucket, lane, level in spans:
        pkey = phase or "other"
        if level is not None:
            pkey = f"lvl{int(level)}/{pkey}"
        key = (-1 if bucket is None else int(bucket), pkey)
        d = per.setdefault(key, {"ms": 0.0, "count": 0, "lane": lane})
        if level is not None:
            d["level"] = int(level)
        d["ms"] += end - start
        d["count"] += 1
        if lane == "collective":
            d["lane"] = "collective"
            comm_iv.append((start, end))
        else:
            compute_iv.append((start, end))

    compute_ms = _union_ms(compute_iv)
    comm_ms = _union_ms(comm_iv)
    overlap_ms = _intersection_ms(compute_iv, comm_iv)
    step_ms = max(e for _, e, *_ in spans) - t0
    ideal_ms = max(compute_ms, comm_ms)

    # critical-path attribution: sweep the span's elementary intervals;
    # each instant's duration is split equally among the phases active
    # then (idle gaps — host dispatch between probes, tails — land on
    # "idle"). The dominant entry is what a latency optimisation must
    # attack first.
    bounds = sorted({b for s, e, *_ in spans for b in (s, e)})
    critical: Dict[str, float] = {}
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        if hi <= lo:
            continue
        active = [ph or "other" for s, e, ph, _b, _l, _lv in spans
                  if s <= lo and e >= hi]
        if not active:
            critical["idle"] = critical.get("idle", 0.0) + (hi - lo)
            continue
        share = (hi - lo) / len(active)
        for ph in active:
            critical[ph] = critical.get(ph, 0.0) + share
    ranked = sorted(((ph, ms) for ph, ms in critical.items()
                     if ph != "idle"), key=lambda kv: -kv[1])
    critical_phase = ranked[0][0] if ranked else None

    buckets: Dict[int, Dict[str, Dict[str, Any]]] = {}
    for (bucket, phase), d in sorted(per.items()):
        entry = {"ms": round(d["ms"], 4), "count": d["count"],
                 "lane": d["lane"]}
        if "level" in d:
            entry["level"] = d["level"]
        buckets.setdefault(bucket, {})[phase] = entry
    return {
        "buckets": buckets,
        "compute_ms": round(compute_ms, 4),
        "comm_ms": round(comm_ms, 4),
        "overlap_ms": round(overlap_ms, 4),
        "overlap_ratio": round(overlap_ms / comm_ms, 6) if comm_ms > 0
        else 0.0,
        "step_ms": round(step_ms, 4),
        "ideal_ms": round(ideal_ms, 4),
        "serialization_ms": round(max(0.0, step_ms - ideal_ms), 4),
        "critical_path": {ph: round(ms, 4)
                          for ph, ms in sorted(critical.items())},
        "critical_phase": critical_phase,
        "events": len(spans),
    }


def phase_totals(analysis: Dict[str, Any]) -> Dict[str, float]:
    """Per-phase-family total ms summed across buckets — the shape
    ``RegressionDetector.observe_phases`` checks limits against."""
    totals: Dict[str, float] = {}
    for phases in analysis.get("buckets", {}).values():
        for ph, d in phases.items():
            # level-tagged keys ("lvl1/exchange") fold into their phase
            # family so regression limits keyed by phase keep applying
            if _LEVEL_RE.match(ph.split("/", 1)[0]):
                ph = ph.split("/", 1)[1] if "/" in ph else "other"
            totals[ph] = round(totals.get(ph, 0.0) + float(d["ms"]), 4)
    return totals


def emit_anatomy(bus, analysis: Optional[Dict[str, Any]], step: int = 0,
                 source: str = "trace",
                 warn_reason: Optional[str] = None,
                 warn_path: Optional[str] = None) -> None:
    """Journal one capture: ``step_anatomy`` per bucket + one
    ``overlap_report`` — or a single ``anatomy_warning`` when there is
    nothing to attribute. ``bus`` may be an EventBus or a RunJournal
    (anything with ``emit``/``record``)."""
    if bus is None:
        return
    put = getattr(bus, "emit", None) or getattr(bus, "record")
    if analysis is None:
        put("anatomy_warning", step=int(step),
            reason=str(warn_reason or "empty or malformed trace"),
            path=warn_path, source=source)
        return
    for bucket, phases in sorted(analysis["buckets"].items()):
        levels = sorted({d["level"] for d in phases.values()
                         if "level" in d})
        extra = {"levels": levels} if levels else {}
        put("step_anatomy", step=int(step), bucket=int(bucket),
            phases=phases,
            total_ms=round(sum(d["ms"] for d in phases.values()), 4),
            source=source, **extra)
    put("overlap_report", step=int(step),
        compute_ms=analysis["compute_ms"], comm_ms=analysis["comm_ms"],
        overlap_ms=analysis["overlap_ms"],
        overlap_ratio=analysis["overlap_ratio"],
        step_ms=analysis["step_ms"], ideal_ms=analysis["ideal_ms"],
        serialization_ms=analysis["serialization_ms"],
        critical_path=analysis["critical_path"],
        critical_phase=analysis["critical_phase"],
        num_buckets=len(analysis["buckets"]),
        events=analysis["events"], source=source)


def analyze_capture(path: str, bus=None, step: int = 0,
                    source: str = "trace") -> Optional[Dict[str, Any]]:
    """Load + analyze + journal one captured trace. Never raises; a
    missing/malformed/contract-free trace journals an
    ``anatomy_warning`` and returns None."""
    try:
        events, resolved, problem = load_trace_events(path)
        analysis = analyze_events(events) if events else None
        if analysis is None and problem is None:
            problem = "no anatomy-scoped events in trace"
        emit_anatomy(bus, analysis, step=step, source=source,
                     warn_reason=problem, warn_path=resolved or path)
        return analysis
    except Exception as e:   # pragma: no cover - belt and braces
        emit_anatomy(bus, None, step=step, source=source,
                     warn_reason=f"analysis failed: {e!r}", warn_path=path)
        return None


# ---------------------------------------------------------------------------
# capture driver


def capture_pipeline_anatomy(cfg, mesh, logdir: str, num_buckets: int = 4,
                             iters: int = 3, axis_name: str = "data",
                             bus=None, step: int = 0,
                             fwd_bwd_elems: int = 1 << 16):
    """Capture + attribute one step anatomy on the given mesh.

    On backends whose device traces carry no per-op lanes (CPU), the
    in-jit named scopes never reach the trace, so this driver measures
    the anatomy by dispatching separately-jitted per-phase subprograms
    (the profile_step.py decomposition) under host
    ``TraceAnnotation``s — same shapes and caps as the configured
    pipeline, one annotation span per (bucket, phase) per iteration.
    Dispatch is serial by construction, so the resulting
    ``overlap_ratio`` is the honest floor of today's un-pipelined step;
    an in-jit device capture on TPU flows through the same analyzer and
    credits real overlap.

    Returns the analysis dict (journalled on ``bus`` when given), or
    None when the profiler cannot capture — the caller records
    ``anatomy_unavailable``/``anatomy_warning`` instead of dying."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from oktopk_tpu.comm import all_gather, all_to_all, compat
    from oktopk_tpu.ops import pack_by_region, scatter_sparse, \
        select_by_threshold
    from oktopk_tpu.ops.topk import k2threshold_method
    from jax.sharding import PartitionSpec as P_

    P = int(cfg.num_workers)
    nb = max(1, int(num_buckets))
    sizes = [cfg.n // nb] * nb
    sizes[-1] += cfg.n - sum(sizes)
    rng = np.random.RandomState(0)

    def sync(x):
        jax.tree.map(lambda a: np.asarray(a), x)

    probes = []   # (phase, bucket, fn) in dispatch order

    # model-level fwd/bwd stand-in: a matmul-chain gradient sized to be
    # visible next to the bucket probes (the real model's fwd/bwd is
    # profiled by profile_step.py's fwd_bwd_dense probe)
    d = max(32, int(np.sqrt(fwd_bwd_elems)) // 32 * 32)
    w = jax.device_put(jnp.asarray(rng.randn(d, d).astype(np.float32)))
    x0 = jax.device_put(jnp.asarray(rng.randn(8, d).astype(np.float32)))
    fwd_bwd = jax.jit(jax.grad(
        lambda wv: jnp.sum(jnp.tanh(x0 @ wv @ wv.T) ** 2)))
    sync(fwd_bwd(w))
    probes.append(("fwd_bwd", None, lambda: fwd_bwd(w)))

    for bi, n_b in enumerate(sizes):
        cfg_b = cfg.replace(n=n_b, bucket_index=bi)
        k_b, cap_p, cap_g = cfg_b.k, cfg_b.cap_pair, cfg_b.cap_gather
        g_b = jax.device_put(jnp.asarray(
            rng.randn(n_b).astype(np.float32)))
        bnd = jnp.asarray(
            [round(i * n_b / P) for i in range(P + 1)], jnp.int32)

        sel = jax.jit(lambda x, k=k_b, cap=cap_g, c=cfg_b:
                      select_by_threshold(
                          x, k2threshold_method(
                              jnp.abs(x), k, c.threshold_method,
                              c.bisect_iters).astype(x.dtype),
                          cap, use_pallas=False))
        sync(sel(g_b))
        t_b = jax.jit(lambda x, k=k_b, c=cfg_b: k2threshold_method(
            jnp.abs(x), k, c.threshold_method, c.bisect_iters))(g_b)

        stage = jax.jit(lambda x, t, b=bnd, cap=cap_p:
                        pack_by_region(x, jnp.abs(x) >= t, b, P, cap,
                                       thresh=t, use_pallas=False))
        sync(stage(g_b, t_b))
        s_vals, s_idx, _ = stage(g_b, t_b)

        def _exchange(sv, si, gv):
            # shard_map blocks keep the sharded axis at size 1 — drop it
            # so all_to_all sees split-axis size == mesh size, and re-add
            # it so out_specs can concatenate the per-shard results
            rv = all_to_all(sv[0], axis_name)
            ri = all_to_all(si[0], axis_name)
            gg = all_gather(gv[0], axis_name)
            return rv[None], ri[None], gg[None]

        exchange = jax.jit(compat.shard_map(
            _exchange, mesh=mesh,
            in_specs=(P_(axis_name), P_(axis_name), P_(axis_name)),
            out_specs=(P_(axis_name),) * 3, check_vma=False))
        sv8 = jnp.broadcast_to(s_vals, (P,) + s_vals.shape)
        si8 = jnp.broadcast_to(s_idx, (P,) + s_idx.shape)
        gv8 = jnp.asarray(rng.randn(P, cap_g).astype(np.float32))
        sync(exchange(sv8, si8, gv8))
        rv8, ri8, _ = exchange(sv8, si8, gv8)

        combine = jax.jit(
            lambda rv, ri, x, n_b=n_b:
            jnp.where(scatter_sparse(n_b, rv, ri) != 0.0, 0.0, x))
        sync(combine(rv8[0], ri8[0], g_b))

        probes.append(("select", bi, lambda g=g_b, f=sel: f(g)))
        probes.append(("stage", bi,
                       lambda g=g_b, t=t_b, f=stage: f(g, t)))
        probes.append(("exchange", bi,
                       lambda a=sv8, b=si8, c=gv8, f=exchange: f(a, b, c)))
        probes.append(("combine", bi,
                       lambda a=rv8[0], b=ri8[0], g=g_b, f=combine:
                       f(a, b, g)))

    # model-level optimizer: SGD-momentum update on the flat vector
    gm = jax.device_put(jnp.asarray(rng.randn(cfg.n).astype(np.float32)))
    pm = jnp.zeros_like(gm)
    opt = jax.jit(lambda p, m, g: (p - 0.1 * (0.9 * m + g), 0.9 * m + g))
    sync(opt(pm, pm, gm))
    probes.append(("optimizer", None, lambda: opt(pm, pm, gm)))

    os.makedirs(logdir, exist_ok=True)
    try:
        jax.profiler.start_trace(logdir, create_perfetto_trace=True)
    except Exception:
        return None
    try:
        for _ in range(max(1, int(iters))):
            for phase, bucket, fn in probes:
                with trace_annotation(phase, bucket):
                    sync(fn())
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            return None
    return analyze_capture(logdir, bus=bus, step=step, source="host_probe")
