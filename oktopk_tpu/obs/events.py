"""Typed, schema-versioned run-journal events.

The run journal (obs/journal.py) is one JSONL file per training run that
carries every observability stream — per-step metrics, autotune
decisions, guard trips, dense fallbacks, checkpoints, captured traces,
volume conformance — behind ONE environment header, so a single ``grep``
or ``read_journal`` reconstructs the whole incident timeline.

This module is the schema authority and imports nothing from the rest of
the package (``autotune/journal.py`` imports it for ``SCHEMA_VERSION``,
so any oktopk import here would be a cycle).

Validation is deliberately permissive about EXTRA fields — emitters may
attach context freely — and strict about required fields and their
types: a journal that validates here is guaranteed to render in
``scripts/obs_report.py`` and to be parseable by the regression and
conformance tooling.
"""

from __future__ import annotations

from typing import Any, Dict, List

SCHEMA_VERSION = 1

_NUM = (int, float)
_STR = (str,)
_OPT_STR = (str, type(None))
_BOOL = (bool,)
_LIST = (list,)
_DICT = (dict,)
_OPT_LIST = (list, type(None))
_OPT_DICT = (dict, type(None))

# event -> {"required": {field: allowed types},
#           "optional": {field: allowed types}}
# Unknown extra fields are always allowed; required fields must be
# present AND type-check; optional fields type-check when present.
EVENT_SCHEMAS: Dict[str, Dict[str, Dict[str, tuple]]] = {
    # one per journal, always first (autotune/journal.py
    # environment_header + schema_version)
    "header": {
        "required": {"jax": _OPT_STR},
        "optional": {"jaxlib": _OPT_STR, "device_kind": _OPT_STR,
                     "platform": _OPT_STR, "world_size": _NUM,
                     "schema_version": _NUM},
    },
    # per-step training metrics (trainer.py flush cadence; host-side
    # floats, already device-meaned)
    "step": {
        "required": {"step": _NUM},
        "optional": {"loss": _NUM, "grad_norm": _NUM,
                     "grad_nonfinite": _NUM, "comm_volume": _NUM,
                     "wire_bytes": _NUM, "local_k": _NUM,
                     "global_k": _NUM, "eps_vs_dense": _NUM,
                     "step_skipped": _NUM, "steps_skipped": _NUM,
                     "bucket_anomalies": _NUM, "dt_ms": _NUM,
                     "reduced_absmax": _NUM},
    },
    # autotuner fabric calibration (autotune/policy.py)
    "calibration": {
        "required": {"step": _NUM},
        "optional": {"num_workers": _NUM, "alpha": _NUM, "beta": _NUM,
                     "sizes": _LIST, "times_ms": _LIST,
                     "residual": _NUM, "source": _STR},
    },
    # per-bucket autotune decision. "decision" is the event name the
    # standalone DecisionJournal file keeps (pre-obs compatibility);
    # "autotune_decision" is the same payload on the unified bus
    # (journal.py _BUS_EVENT_REMAP).
    # Plan-mode decisions (fabric-preset pricing, no trials) add
    # "fabric" (preset name, e.g. "ici+dcn") and "num_pods"; their
    # chosen/candidates dicts may carry "outer" and a per-level
    # "levels" list for hierarchical candidates.
    "decision": {
        "required": {"step": _NUM, "bucket": _NUM, "chosen": _DICT,
                     "reason": _STR},
        "optional": {"n": _NUM, "num_workers": _NUM,
                     "candidates": _LIST, "incumbent": _OPT_DICT,
                     "fabric": _STR, "num_pods": _NUM},
    },
    "autotune_decision": {
        "required": {"step": _NUM, "bucket": _NUM, "chosen": _DICT,
                     "reason": _STR},
        "optional": {"n": _NUM, "num_workers": _NUM,
                     "candidates": _LIST, "incumbent": _OPT_DICT,
                     "fabric": _STR, "num_pods": _NUM},
    },
    # resilience events (resilience/journal.py HealthJournal)
    "guard_trip": {
        "required": {"step": _NUM, "buckets": _LIST,
                     "consecutive_skips": _NUM, "strikes": _LIST},
        "optional": {},
    },
    "fault_seen": {
        "required": {"step": _NUM, "kind": _STR},
        "optional": {"buckets": _LIST, "counts": _OPT_LIST,
                     "workers": _OPT_LIST},
    },
    "fallback": {
        "required": {"step": _NUM, "bucket": _NUM, "algo": _STR,
                     "strikes": _NUM},
        "optional": {},
    },
    "restore": {
        "required": {"step": _NUM, "ckpt": _STR,
                     "last_good_step": _NUM},
        "optional": {},
    },
    "restore_unavailable": {
        "required": {"step": _NUM, "last_good_step": _NUM},
        "optional": {},
    },
    # elastic resize (train/trainer.py resize_workers): which state
    # carried across the world-size change vs was re-initialised, and
    # what triggered it ("chip_loss" via the supervisor remesh action,
    # "manual" for operator-driven resizes)
    "remesh": {
        "required": {"step": _NUM, "old_world": _NUM, "new_world": _NUM,
                     "trigger": _STR},
        "optional": {"dead_workers": _LIST, "carried": _LIST,
                     "reinitialised": _LIST},
    },
    # forced autotune re-calibration (resilience/feedback.py via
    # Trainer.force_retune); "signals" are the evidence steps — the
    # regression/guard_trip events that voted. Followed in the journal
    # by the calibration + autotune_decision events it caused.
    "retune": {
        "required": {"step": _NUM, "trigger": _STR},
        "optional": {"signals": _LIST, "cleared": _STR},
    },
    # guard-aware density backoff level change (resilience/density.py)
    "density_backoff": {
        "required": {"step": _NUM, "direction": _STR, "level": _NUM,
                     "scale": _NUM},
        "optional": {"trigger": _STR},
    },
    # checkpoint written (resilience/supervisor.py note_checkpoint;
    # qualified=False means skips were in flight so it is NOT a
    # restore target)
    "checkpoint": {
        "required": {"step": _NUM, "path": _STR, "qualified": _BOOL},
        "optional": {},
    },
    # durable state plane (train/durable.py): a checkpoint file was
    # written AND verified against its manifest ("source" says whether
    # the AsyncCheckpointer or a synchronous save published it)
    "ckpt_saved": {
        "required": {"step": _NUM, "path": _STR},
        "optional": {"bytes": _NUM, "digest": _STR, "qualified": _BOOL,
                     "duration_ms": _NUM, "source": _STR},
    },
    # a checkpoint file failed verification (digest/size mismatch, torn
    # or failed write, undecodable legacy file) — restore skips it and
    # falls back to the next-older candidate
    "ckpt_verify_failed": {
        "required": {"step": _NUM, "path": _STR, "reason": _STR},
        "optional": {},
    },
    # a verified restore completed; fallback_depth counts the newer
    # corrupt checkpoints skipped to reach this one, legacy flags a
    # manifest-less file accepted unverified
    "ckpt_restore": {
        "required": {"step": _NUM, "path": _STR},
        "optional": {"ckpt_step": _NUM, "fallback_depth": _NUM,
                     "legacy": _BOOL},
    },
    # bounded profiler window closed (obs/tracing.py AnomalyTracer)
    "trace_captured": {
        "required": {"step": _NUM, "start_step": _NUM,
                     "num_steps": _NUM, "trigger": _STR},
        "optional": {"logdir": _OPT_STR},
    },
    # end-of-run per-bucket wire-volume conformance (trainer.py +
    # obs/volume.py). Two-level runs emit one report per level plus a
    # combined one, tagged "level": "intra" | "inter" | "total"
    # (obs/volume.hierarchical_volume_report); flat reports omit it.
    "volume_report": {
        "required": {"step": _NUM, "bucket": _NUM, "algo": _STR},
        "optional": {"n": _NUM, "density": _NUM, "steps": _NUM,
                     "wire_bytes": _NUM, "mean_wire_bytes": _NUM,
                     "budget_bytes": _NUM, "capacity_bytes": _NUM,
                     "conformance_ratio": _NUM, "level": _STR},
    },
    # host phase-timer snapshot (utils/profiling.py PhaseTimers.summary)
    "phase": {
        "required": {"step": _NUM},
        "optional": {"phases": _DICT},
    },
    # step-time regression vs the BENCH trajectory (obs/regress.py)
    "regression": {
        "required": {"step": _NUM, "ms": _NUM, "baseline_ms": _NUM,
                     "ratio": _NUM},
        "optional": {"key": _OPT_STR, "tolerance": _NUM},
    },
    # per-bucket signal-fidelity flush (obs/quality.py via the trainer):
    # one event per bucket per flush window, carrying parallel per-step
    # lists drained from the device-side metric ring. Non-finite values
    # are sanitised to null at flush time (JSON has no NaN), so list
    # entries are number-or-null.
    "quality": {
        "required": {"step": _NUM, "bucket": _NUM},
        "optional": {"algo": _STR, "count": _NUM, "steps": _LIST,
                     "comp_err": _LIST, "res_norm": _LIST,
                     "res_growth": _LIST, "eff_density": _LIST,
                     "thr_drift": _LIST, "churn": _LIST,
                     "skipped": _LIST},
    },
    # windowed aggregate over one quality flush (obs/rollup.py
    # RollupEngine) with breach detection — "breaches" names which
    # fidelity invariants failed ("residual_growth", "density_collapse",
    # "churn_spike", "comp_err"). Aggregate fields are omitted (not
    # null) when every sample in the window was non-finite.
    "quality_rollup": {
        "required": {"step": _NUM, "bucket": _NUM, "breaches": _LIST},
        "optional": {"algo": _STR, "window": _NUM, "skipped": _NUM,
                     "comp_err_mean": _NUM, "comp_err_max": _NUM,
                     "res_norm_mean": _NUM, "res_norm_last": _NUM,
                     "res_growth_mean": _NUM, "res_growth_max": _NUM,
                     "eff_density_mean": _NUM, "eff_density_min": _NUM,
                     "thr_drift_mean": _NUM, "churn_mean": _NUM,
                     "churn_max": _NUM, "target_density": _NUM},
    },
    # a detector could not build (or refused) its baseline — advisory,
    # journalled instead of raising (obs/regress.py)
    "baseline_warning": {
        "required": {"step": _NUM, "key": _STR, "reason": _STR},
        "optional": {"files": _NUM, "malformed": _LIST},
    },
    # step-anatomy attribution for one bucket (obs/anatomy.py): phases
    # maps phase name -> {"ms", "count", "lane"}; model-level unbucketed
    # phases (fwd_bwd, optimizer) land on bucket -1. "source" says how
    # the trace was captured ("host_probe" for the CPU per-phase
    # dispatch driver, "trace" for an in-jit device capture). Two-level
    # collectives tag phases with a level lane (anat/bNNN/lvlN/phase);
    # "levels" lists the distinct level indices seen in the capture.
    "step_anatomy": {
        "required": {"step": _NUM, "bucket": _NUM, "phases": _DICT},
        "optional": {"total_ms": _NUM, "source": _STR,
                     "schema_version": _NUM, "levels": _LIST},
    },
    # the overlap scorecard for one captured step (obs/anatomy.py):
    # compute/comm lane unions, their intersection, overlap_ratio =
    # overlap_ms / comm_ms, the measured span vs the ideal
    # fully-overlapped lower bound max(compute, comm), and the
    # critical-path split of the span across phases
    "overlap_report": {
        "required": {"step": _NUM, "compute_ms": _NUM, "comm_ms": _NUM,
                     "overlap_ms": _NUM, "overlap_ratio": _NUM},
        "optional": {"step_ms": _NUM, "ideal_ms": _NUM,
                     "serialization_ms": _NUM, "critical_path": _DICT,
                     "critical_phase": _OPT_STR, "num_buckets": _NUM,
                     "events": _NUM, "source": _STR,
                     "schema_version": _NUM},
    },
    # anatomy capture/analysis could not produce an attribution
    # (missing profiler, empty or malformed trace, no contract-scoped
    # events) — advisory, journalled instead of raising
    "anatomy_warning": {
        "required": {"step": _NUM, "reason": _STR},
        "optional": {"path": _OPT_STR, "source": _STR},
    },
}


def validate_event(entry: Any) -> List[str]:
    """Problems with one journal entry (empty list = valid)."""
    problems: List[str] = []
    if not isinstance(entry, dict):
        return [f"entry is {type(entry).__name__}, not dict"]
    event = entry.get("event")
    if not isinstance(event, str):
        return ["missing or non-string 'event' field"]
    schema = EVENT_SCHEMAS.get(event)
    if schema is None:
        return [f"unknown event {event!r} (schema v{SCHEMA_VERSION})"]
    for field, types in schema["required"].items():
        if field not in entry:
            problems.append(f"{event}: missing required field {field!r}")
        elif not isinstance(entry[field], types):
            problems.append(
                f"{event}: field {field!r} is "
                f"{type(entry[field]).__name__}, expected one of "
                f"{tuple(t.__name__ for t in types)}")
    for field, types in schema["optional"].items():
        if field in entry and not isinstance(entry[field], types):
            problems.append(
                f"{event}: field {field!r} is "
                f"{type(entry[field]).__name__}, expected one of "
                f"{tuple(t.__name__ for t in types)}")
    return problems


def validate_journal(entries: List[Dict[str, Any]]) -> List[str]:
    """Problems with a whole journal: exactly one header, first, and
    every entry valid. Empty list = conformant."""
    problems: List[str] = []
    if not entries:
        return ["journal is empty"]
    if entries[0].get("event") != "header":
        problems.append("first entry is not an environment header")
    n_headers = sum(1 for e in entries
                    if isinstance(e, dict) and e.get("event") == "header")
    if n_headers != 1:
        problems.append(f"expected exactly 1 header, found {n_headers}")
    for i, entry in enumerate(entries):
        problems.extend(f"entry {i}: {p}" for p in validate_event(entry))
    return problems
