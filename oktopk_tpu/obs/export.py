"""Prometheus-textfile export of the quality telemetry plane.

Renders the LATEST ``quality_rollup`` per bucket (plus run-level
counters) in the node-exporter textfile-collector format, so a run's
fidelity posture can be scraped next to its host metrics without any
bespoke collector:

    python scripts/obs_report.py run_journal.jsonl --prom quality.prom

Gauges carry ``bucket`` and ``algo`` labels; every exposition is
self-describing (# HELP / # TYPE) and deterministic in ordering so
textfile diffs are meaningful in CI.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List

_PREFIX = "oktopk_quality"

# rollup field -> (metric suffix, help text)
_GAUGES = (
    ("comp_err_mean", "compression error ||g_hat-g||^2/||g||^2, window mean"),
    ("comp_err_max", "compression error, window max"),
    ("res_norm_mean", "error-feedback residual L2 norm, window mean"),
    ("res_growth_mean", "step-over-step residual growth ratio, window mean"),
    ("eff_density_mean", "realised selection density k_hat/n, window mean"),
    ("eff_density_min", "realised selection density, window min"),
    ("thr_drift_mean", "predicted/exact threshold ratio, window mean"),
    ("churn_mean", "step-over-step winner-index churn, window mean"),
)


def _esc(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def render_prometheus(entries: List[Dict[str, Any]]) -> str:
    """Prometheus exposition text from a journal's entries."""
    latest: Dict[int, Dict[str, Any]] = {}
    breaches: Dict[int, int] = {}
    for e in entries:
        if e.get("event") != "quality_rollup":
            continue
        b = int(e.get("bucket", 0))
        latest[b] = e
        breaches[b] = breaches.get(b, 0) + len(e.get("breaches") or [])
    lines: List[str] = []
    for field, help_text in _GAUGES:
        name = f"{_PREFIX}_{field}"
        samples = []
        for b in sorted(latest):
            v = latest[b].get(field)
            if isinstance(v, (int, float)) and math.isfinite(float(v)):
                labels = (f'bucket="{b}",'
                          f'algo="{_esc(latest[b].get("algo", "?"))}"')
                samples.append(f"{name}{{{labels}}} {float(v):.10g}")
        if samples:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} gauge")
            lines.extend(samples)
    if latest:
        name = f"{_PREFIX}_breaches_total"
        lines.append(f"# HELP {name} fidelity breaches flagged across "
                     "the run's rollups")
        lines.append(f"# TYPE {name} counter")
        for b in sorted(latest):
            labels = (f'bucket="{b}",'
                      f'algo="{_esc(latest[b].get("algo", "?"))}"')
            lines.append(f"{name}{{{labels}}} {breaches.get(b, 0)}")
        name = f"{_PREFIX}_last_step"
        lines.append(f"# HELP {name} journal step of the newest rollup")
        lines.append(f"# TYPE {name} gauge")
        for b in sorted(latest):
            labels = (f'bucket="{b}",'
                      f'algo="{_esc(latest[b].get("algo", "?"))}"')
            lines.append(f"{name}{{{labels}}} "
                         f"{int(latest[b].get('step', 0))}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_textfile(entries: List[Dict[str, Any]], path: str) -> str:
    """Atomic write (tmp -> rename) — the textfile collector must
    never scrape a torn exposition."""
    text = render_prometheus(entries)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path
