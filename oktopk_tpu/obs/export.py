"""Prometheus-textfile export of the quality + anatomy telemetry planes.

Renders the LATEST ``quality_rollup`` per bucket (plus run-level
counters) and the latest step-anatomy attribution (per-phase durations
from ``step_anatomy``, the overlap scorecard from ``overlap_report``)
in the node-exporter textfile-collector format, so a run's fidelity
and time-domain posture can be scraped next to its host metrics
without any bespoke collector:

    python scripts/obs_report.py run_journal.jsonl --prom quality.prom

Gauges carry ``bucket`` and ``algo`` labels (anatomy phases add
``phase``/``lane``); every exposition is self-describing
(# HELP / # TYPE) and deterministic in ordering so textfile diffs are
meaningful in CI.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List

_PREFIX = "oktopk_quality"

# rollup field -> (metric suffix, help text)
_GAUGES = (
    ("comp_err_mean", "compression error ||g_hat-g||^2/||g||^2, window mean"),
    ("comp_err_max", "compression error, window max"),
    ("res_norm_mean", "error-feedback residual L2 norm, window mean"),
    ("res_growth_mean", "step-over-step residual growth ratio, window mean"),
    ("eff_density_mean", "realised selection density k_hat/n, window mean"),
    ("eff_density_min", "realised selection density, window min"),
    ("thr_drift_mean", "predicted/exact threshold ratio, window mean"),
    ("churn_mean", "step-over-step winner-index churn, window mean"),
)


def _esc(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


_ANATOMY_PREFIX = "oktopk_anatomy"

# overlap_report field -> (gauge suffix == field, help text)
_OVERLAP_GAUGES = (
    ("overlap_ratio", "fraction of collective time hidden under compute "
                      "(overlap_ms / comm_ms)"),
    ("compute_ms", "union of compute-lane time in the captured step"),
    ("comm_ms", "union of collective-lane time in the captured step"),
    ("overlap_ms", "compute/collective lane intersection"),
    ("step_ms", "measured captured-step span"),
    ("ideal_ms", "fully-overlapped lower bound max(compute, comm)"),
    ("serialization_ms", "measured span above the ideal lower bound"),
)


def _render_anatomy(entries: List[Dict[str, Any]]) -> List[str]:
    """Gauge lines for the newest step_anatomy (per bucket) and
    overlap_report events; [] when the journal carries neither."""
    latest_anat: Dict[int, Dict[str, Any]] = {}
    latest_overlap: Dict[str, Any] = {}
    for e in entries:
        if e.get("event") == "step_anatomy":
            latest_anat[int(e.get("bucket", 0))] = e
        elif e.get("event") == "overlap_report":
            latest_overlap = e
    lines: List[str] = []
    name = f"{_ANATOMY_PREFIX}_phase_ms"
    samples = []
    for b in sorted(latest_anat):
        phases = latest_anat[b].get("phases")
        if not isinstance(phases, dict):
            continue
        for ph in sorted(phases):
            d = phases[ph] if isinstance(phases[ph], dict) else {}
            v = d.get("ms", phases[ph])
            if isinstance(v, (int, float)) and math.isfinite(float(v)):
                labels = (f'bucket="{b}",phase="{_esc(ph)}",'
                          f'lane="{_esc(d.get("lane", "compute"))}"')
                samples.append(f"{name}{{{labels}}} {float(v):.10g}")
    if samples:
        lines.append(f"# HELP {name} per-phase attributed device/probe "
                     "time from the latest step-anatomy capture")
        lines.append(f"# TYPE {name} gauge")
        lines.extend(samples)
    for field, help_text in _OVERLAP_GAUGES:
        v = latest_overlap.get(field)
        if isinstance(v, (int, float)) and math.isfinite(float(v)):
            name = f"{_ANATOMY_PREFIX}_{field}"
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {float(v):.10g}")
    return lines


def render_prometheus(entries: List[Dict[str, Any]]) -> str:
    """Prometheus exposition text from a journal's entries."""
    latest: Dict[int, Dict[str, Any]] = {}
    breaches: Dict[int, int] = {}
    for e in entries:
        if e.get("event") != "quality_rollup":
            continue
        b = int(e.get("bucket", 0))
        latest[b] = e
        breaches[b] = breaches.get(b, 0) + len(e.get("breaches") or [])
    lines: List[str] = []
    for field, help_text in _GAUGES:
        name = f"{_PREFIX}_{field}"
        samples = []
        for b in sorted(latest):
            v = latest[b].get(field)
            if isinstance(v, (int, float)) and math.isfinite(float(v)):
                labels = (f'bucket="{b}",'
                          f'algo="{_esc(latest[b].get("algo", "?"))}"')
                samples.append(f"{name}{{{labels}}} {float(v):.10g}")
        if samples:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} gauge")
            lines.extend(samples)
    if latest:
        name = f"{_PREFIX}_breaches_total"
        lines.append(f"# HELP {name} fidelity breaches flagged across "
                     "the run's rollups")
        lines.append(f"# TYPE {name} counter")
        for b in sorted(latest):
            labels = (f'bucket="{b}",'
                      f'algo="{_esc(latest[b].get("algo", "?"))}"')
            lines.append(f"{name}{{{labels}}} {breaches.get(b, 0)}")
        name = f"{_PREFIX}_last_step"
        lines.append(f"# HELP {name} journal step of the newest rollup")
        lines.append(f"# TYPE {name} gauge")
        for b in sorted(latest):
            labels = (f'bucket="{b}",'
                      f'algo="{_esc(latest[b].get("algo", "?"))}"')
            lines.append(f"{name}{{{labels}}} "
                         f"{int(latest[b].get('step', 0))}")
    lines.extend(_render_anatomy(entries))
    return "\n".join(lines) + ("\n" if lines else "")


def write_textfile(entries: List[Dict[str, Any]], path: str) -> str:
    """Atomic write (tmp -> rename) — the textfile collector must
    never scrape a torn exposition."""
    text = render_prometheus(entries)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path
