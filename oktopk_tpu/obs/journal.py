"""Event bus + unified run journal.

One training run, one JSONL file: per-step metrics, autotune decisions,
guard trips, fallbacks, checkpoints, trace captures and volume reports
all flow through a single :class:`EventBus` into a single
:class:`RunJournal`, behind ONE environment header. The pre-existing
standalone journals (``autotune/journal.py`` DecisionJournal,
``resilience/journal.py`` HealthJournal) keep writing their own files —
they become thin views: constructed with ``bus=``, every event they
record is also forwarded to the bus (with ``decision`` renamed to
``autotune_decision`` so bus consumers can tell the streams apart).

The bus is host-side and synchronous — emit() fans an event dict out to
each subscriber in turn. Subscriber exceptions are swallowed and
counted (``bus.dropped``): observability must never be the reason a
training step fails.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

from oktopk_tpu.autotune.journal import environment_header, read_journal  # noqa: F401
from oktopk_tpu.obs.events import SCHEMA_VERSION  # noqa: F401


class EventBus:
    """Synchronous fan-out of event dicts to subscriber callables."""

    def __init__(self):
        self._subscribers: List[Callable[[Dict[str, Any]], None]] = []
        self.dropped = 0          # subscriber exceptions swallowed

    def subscribe(self, fn: Callable[[Dict[str, Any]], None]):
        self._subscribers.append(fn)
        return fn

    def emit(self, event: str, **fields) -> Dict[str, Any]:
        entry = {"event": event, **fields}
        for fn in list(self._subscribers):
            try:
                fn(dict(entry))   # own copy: subscribers may mutate
            except Exception:
                self.dropped += 1
        return entry


class RunJournal:
    """The single per-run JSONL sink.

    Writes its own environment header directly (NOT via the bus), then
    subscribes to the bus and appends every event EXCEPT ``header`` —
    thin-view journals each write a header to their own standalone
    file, and forwarding those would break the one-header-per-run
    invariant that ``obs.events.validate_journal`` checks.

    ``path=None`` keeps entries in memory only (tests).
    """

    def __init__(self, path: Optional[str] = None,
                 bus: Optional[EventBus] = None, header: bool = True):
        self.path = path
        self.entries: List[Dict[str, Any]] = []
        if path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "w"):   # truncate: one journal per run
                pass
        if header:
            self._write({"event": "header", **environment_header()})
        if bus is not None:
            bus.subscribe(self._on_event)

    def _on_event(self, entry: Dict[str, Any]):
        if entry.get("event") == "header":
            return
        self._write(entry)

    def _write(self, entry: Dict[str, Any]):
        self.entries.append(entry)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(entry) + "\n")

    def record(self, event: str, **fields) -> Dict[str, Any]:
        """Direct append, bypassing the bus (for events that only the
        run journal should carry)."""
        entry = {"event": event, **fields}
        self._write(entry)
        return entry
