"""Device-side metric ring buffer for the in-jit quality taps.

The signal-fidelity scalars (obs/quality.py) are computed inside the
jitted train step; fetching them to host every step would add a device
sync the steady-state loop never otherwise pays. Instead each bucket
owns a :class:`QualityBuffer` — a fixed-capacity f32 ring living in
``DistTrainState.quality`` — that the step pushes one row into per
call. Only on the flush cadence (``obs_quality_every`` steps) does the
trainer ``device_get`` the whole ring and drain the new rows into
``quality`` journal events, so steady state adds ZERO extra host
transfers (the acceptance property tests/test_quality.py pins).

The cursor is MONOTONIC (total pushes, not a wrapped index): the host
keeps its last-seen cursor and :func:`rows_since` reconstructs exactly
the rows pushed since, in order, from ``cursor % capacity``. A ring
sized to the flush cadence therefore never drops a row; an undersized
ring degrades gracefully to the newest ``capacity`` rows.

Rows are pushed UNCONDITIONALLY — guard-skipped steps included — so
quality accounting stays consistent with the wire/step accounting that
also advances on skips (optim/distributed.py guard block); the
``skipped`` column marks those rows instead. Only the step-over-step
baselines (``prev_res_norm``, ``prev_sig``) freeze across a skip,
because the rolled-back residual/selection next step is compared
against the last *committed* state, not the discarded one.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp
import numpy as np
from jax import lax

# ring columns, in order (host-side names for the flush payload)
COLUMNS = ("step", "comp_err", "res_norm", "res_growth", "eff_density",
           "thr_drift", "churn", "skipped")
NUM_COLS = len(COLUMNS)


@flax.struct.dataclass
class QualityBuffer:
    """Per-bucket on-device fidelity ring + step-over-step baselines."""
    ring: jnp.ndarray           # f32[capacity, NUM_COLS]
    cursor: jnp.ndarray         # i32 — monotonic push count
    prev_res_norm: jnp.ndarray  # f32 — last committed residual norm
    prev_sig: jnp.ndarray       # f32[sig_bins] — last committed winner sig


def init_buffer(capacity: int, sig_bins: int,
                dtype=jnp.float32) -> QualityBuffer:
    capacity = max(1, int(capacity))
    return QualityBuffer(
        ring=jnp.zeros((capacity, NUM_COLS), dtype),
        cursor=jnp.asarray(0, jnp.int32),
        prev_res_norm=jnp.asarray(0.0, dtype),
        prev_sig=jnp.zeros((int(sig_bins),), dtype))


def push_row(buf: QualityBuffer, row: jnp.ndarray, sig: jnp.ndarray,
             res_norm: jnp.ndarray, skipped: jnp.ndarray) -> QualityBuffer:
    """Append one row (traced, in-jit). ``skipped`` freezes the
    baselines but never the ring — the row itself always lands."""
    cap = buf.ring.shape[0]
    idx = lax.rem(buf.cursor, jnp.asarray(cap, buf.cursor.dtype))
    ring = lax.dynamic_update_slice(
        buf.ring, row.astype(buf.ring.dtype)[None],
        (idx, jnp.asarray(0, idx.dtype)))
    keep = skipped.astype(bool)
    return buf.replace(
        ring=ring, cursor=buf.cursor + 1,
        prev_res_norm=jnp.where(keep, buf.prev_res_norm,
                                res_norm.astype(buf.prev_res_norm.dtype)),
        prev_sig=jnp.where(keep, buf.prev_sig,
                           sig.astype(buf.prev_sig.dtype)))


def rows_since(ring: np.ndarray, cursor: int, prev_cursor: int) -> np.ndarray:
    """Host-side drain: the rows pushed in ``(prev_cursor, cursor]``,
    oldest first. ``ring`` may carry a leading worker axis ([P, cap, C]
    off the sharded state) — worker rows are averaged, which is exact
    for the replicated columns and the worker-mean for the per-worker
    ones (residual norm, threshold drift)."""
    ring = np.asarray(ring, np.float64)
    if ring.ndim == 3:
        ring = ring.mean(axis=0)
    cap = ring.shape[0]
    count = min(int(cursor) - int(prev_cursor), cap)
    if count <= 0:
        return np.zeros((0, ring.shape[1]), np.float64)
    idx = [(int(cursor) - count + i) % cap for i in range(count)]
    return ring[idx]
