"""In-jit compression-quality taps: per-bucket fidelity scalars.

Ok-Topk's convergence argument (PAPER.md) rests on two quantities no
byte or millisecond counter can see: the error-feedback residual
staying bounded, and local selection actually approximating the global
top-k. This module computes those — on device, inside the traced step,
next to values the collectives already materialise — and stages them
into the :mod:`obs.metrics_buffer` ring so steady state adds zero host
syncs (the tap's only per-step cost is one dense ``pmean`` and a
handful of reductions over buffers already in registers/VMEM).

Per-bucket scalars (ring columns, obs/metrics_buffer.py COLUMNS):

- ``comp_err``   — ``‖ĝ−g‖²/‖g‖²`` of the delivered reduced gradient
  against the pre-selection dense gradient ``g = pmean(grad+residual)``
  (the exact vector the selection approximates; dense-warmup steps
  score ~0).
- ``res_norm``   — ‖residual‖₂ after the step (per-worker; the flush
  averages workers).
- ``res_growth`` — step-over-step ratio vs the last *committed*
  residual norm (guard-skipped steps don't advance the baseline).
- ``eff_density``— realised k̂/n of the delivered vector (nonzero
  count of ``reduced``), covering repair/overflow/fallback branches —
  what actually reached the optimizer, not what the config asked for.
- ``thr_drift``  — predicted local threshold vs the last exact
  recompute's measured one (how far the threshold controller has
  drifted off its calibration).
- ``churn``      — 1 − overlap of this step's selected positions with
  the last committed step's, via a hashed Bloom-style signature
  (:func:`winner_signature`) so no index history is materialised.

The same tap functions serve both the trainer's step
(optim/distributed.py) and the standalone oracle harness
(collectives/api.py ``build_quality_allreduce_step``), so the offline
dense-vs-sparse oracle in tests/test_quality.py checks the exact code
the trainer journals through.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional

import jax.numpy as jnp

from oktopk_tpu.obs.metrics_buffer import (COLUMNS, QualityBuffer,
                                           init_buffer, push_row)

_TINY = 1e-30

# Knuth's multiplicative hash constant (2^32 / phi) — cheap, stateless,
# and uniform enough for a presence signature over coordinate indices.
_HASH_MULT = 2654435761


@dataclasses.dataclass(frozen=True)
class QualityConfig:
    """Static tap configuration (trace-time constants).

    ``every`` is both the flush cadence and the ring capacity, so a
    flush always drains exactly the window since the last one.
    ``sig_bins`` sizes the churn signature; power of two so the hash
    reduces with a shift, and small enough (default 512) that the
    per-step signature compare is noise next to the collective."""
    every: int = 32
    sig_bins: int = 512

    def __post_init__(self):
        if self.every < 1:
            raise ValueError(f"every must be >= 1, got {self.every}")
        b = int(self.sig_bins)
        if b < 2 or (b & (b - 1)) != 0:
            raise ValueError(
                f"sig_bins must be a power of two >= 2, got {self.sig_bins}")


def winner_signature(reduced: jnp.ndarray, sig_bins: int) -> jnp.ndarray:
    """Bloom-style presence signature of the selected positions.

    Hashes every coordinate index into ``sig_bins`` buckets and max-
    scatters the selection mask, giving a fixed-size f32 vector whose
    min/max overlap approximates index-set overlap — no sorted index
    list, no step-over-step index history."""
    n = reduced.shape[0]
    shift = 32 - int(math.log2(sig_bins))
    h = ((jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(_HASH_MULT))
         >> jnp.uint32(shift)).astype(jnp.int32)
    mask = (reduced != 0).astype(jnp.float32)
    return jnp.zeros((sig_bins,), jnp.float32).at[h].max(mask)


def measure_bucket(reduced: jnp.ndarray, dense: jnp.ndarray, sp_new,
                   prev_sig: jnp.ndarray,
                   prev_res_norm: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """All fidelity scalars for one bucket, one step (traced).

    ``dense`` must be the pre-selection dense gradient — the pmean of
    exactly what each worker handed the compressor plus its residual.
    Returns a dict keyed like COLUMNS (minus step/skipped) plus the new
    signature under ``"sig"``."""
    n = reduced.shape[0]
    reduced = reduced.astype(jnp.float32)
    dense = dense.astype(jnp.float32)
    comp_err = (jnp.sum((reduced - dense) ** 2)
                / (jnp.sum(dense ** 2) + _TINY))
    res_norm = jnp.sqrt(
        jnp.sum(sp_new.residual.astype(jnp.float32) ** 2))
    res_growth = jnp.where(prev_res_norm > 0,
                           res_norm / jnp.maximum(prev_res_norm, _TINY),
                           jnp.asarray(1.0, jnp.float32))
    eff_density = (jnp.sum(reduced != 0).astype(jnp.float32)
                   / jnp.asarray(n, jnp.float32))
    lt = sp_new.local_threshold.astype(jnp.float32)
    le = sp_new.last_exact_lt.astype(jnp.float32)
    thr_drift = jnp.where(le > 0, lt / jnp.maximum(le, _TINY),
                          jnp.asarray(1.0, jnp.float32))
    sig = winner_signature(reduced, prev_sig.shape[0])
    inter = jnp.sum(jnp.minimum(sig, prev_sig))
    union = jnp.maximum(jnp.sum(jnp.maximum(sig, prev_sig)), 1.0)
    churn = 1.0 - inter / union
    return {"comp_err": comp_err, "res_norm": res_norm,
            "res_growth": res_growth, "eff_density": eff_density,
            "thr_drift": thr_drift, "churn": churn, "sig": sig}


def commit(buf: QualityBuffer, step, scalars: Dict[str, jnp.ndarray],
           skipped) -> QualityBuffer:
    """Push one measured step into the ring (traced). ``step`` is the
    bucket's SparseState counter post-bump; ``skipped`` the agreed
    guard flag (freezes the baselines, never the push)."""
    skipped = jnp.asarray(skipped)
    row = jnp.stack([
        jnp.asarray(step, jnp.float32),
        scalars["comp_err"], scalars["res_norm"], scalars["res_growth"],
        scalars["eff_density"], scalars["thr_drift"], scalars["churn"],
        skipped.astype(jnp.float32)])
    return push_row(buf, row, scalars["sig"], scalars["res_norm"], skipped)


# ---- host-side flush helpers ---------------------------------------------

def _sanitize(v: float) -> Optional[float]:
    v = float(v)
    return v if math.isfinite(v) else None


def quality_event(step: int, bucket: int, algo: str,
                  rows) -> Dict[str, Any]:
    """A schema-conformant ``quality`` event payload from drained ring
    rows (``metrics_buffer.rows_since`` output). Non-finite samples
    become null — JSON has no NaN, and the rollup skips them."""
    ev: Dict[str, Any] = {"step": int(step), "bucket": int(bucket),
                          "algo": str(algo), "count": int(len(rows))}
    cols: Dict[str, List[Any]] = {c: [] for c in COLUMNS}
    for row in rows:
        for c, v in zip(COLUMNS, row):
            if c == "step":
                cols[c].append(int(v))
            elif c == "skipped":
                cols[c].append(int(v > 0.5))
            else:
                cols[c].append(_sanitize(v))
    ev["steps"] = cols.pop("step")
    ev.update(cols)
    return ev
