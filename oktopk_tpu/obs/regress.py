"""Step-time regression detection against the BENCH trajectory.

The repo root accumulates ``BENCH_r*.json`` records — one per growth
round, each with a ``parsed`` dict of per-algorithm millisecond
timings (e.g. ``{"oktopk_ms": 177.6, "dense_ms": 67.3, ...}``). Their
median is a cheap, already-maintained baseline for "how fast should a
step be on this container", so a live run can flag when its own step
time drifts past ``tolerance ×`` that history and journal a
``regression`` event the report surfaces.

The detector is advisory: it never throws, and with no baseline
available (no records, or none carrying the key) it stays silent.
A warmup window skips the first observations — compile time dominates
them and would always "regress".
"""

from __future__ import annotations

import glob
import json
import os
import statistics
from typing import Any, Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_bench_values(key: str,
                      root: Optional[str] = None) -> List[float]:
    """All ``parsed[key]`` values from BENCH_r*.json under ``root``
    (repo root by default). Tolerates missing/garbled records."""
    root = root or _REPO_ROOT
    out: List[float] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
            val = (rec.get("parsed") or {}).get(key)
            if isinstance(val, (int, float)):
                out.append(float(val))
        except Exception:
            continue
    return out


class RegressionDetector:
    """Flags step times above ``tolerance × baseline_ms``."""

    def __init__(self, baseline_ms: Optional[float],
                 tolerance: float = 1.5, warmup_windows: int = 2,
                 bus=None, key: Optional[str] = None):
        self.baseline_ms = baseline_ms
        self.tolerance = float(tolerance)
        self.warmup_windows = int(warmup_windows)
        self.bus = bus
        self.key = key
        self.observations = 0
        self.flagged: List[Dict[str, Any]] = []

    @classmethod
    def from_bench_records(cls, key: str = "oktopk_ms",
                           root: Optional[str] = None,
                           **kwargs) -> "RegressionDetector":
        vals = load_bench_values(key, root=root)
        baseline = statistics.median(vals) if vals else None
        return cls(baseline, key=key, **kwargs)

    def observe(self, step: int, ms: float) -> Optional[Dict[str, Any]]:
        """Feed one measured step time (milliseconds). Returns the
        regression record when flagged, else None."""
        self.observations += 1
        if self.baseline_ms is None or self.baseline_ms <= 0:
            return None
        if self.observations <= self.warmup_windows:
            return None
        ms = float(ms)
        if ms <= self.tolerance * self.baseline_ms:
            return None
        rec = {"step": int(step), "ms": ms,
               "baseline_ms": float(self.baseline_ms),
               "ratio": ms / self.baseline_ms,
               "tolerance": self.tolerance, "key": self.key}
        self.flagged.append(rec)
        if self.bus is not None:
            self.bus.emit("regression", **rec)
        return rec
