"""Step-time regression detection against the BENCH trajectory.

The repo root accumulates ``BENCH_r*.json`` records — one per growth
round, each with a ``parsed`` dict of per-algorithm millisecond
timings (e.g. ``{"oktopk_ms": 177.6, "dense_ms": 67.3, ...}``). Their
median is a cheap, already-maintained baseline for "how fast should a
step be on this container", so a live run can flag when its own step
time drifts past ``tolerance ×`` that history and journal a
``regression`` event the report surfaces.

The detector is advisory: it never throws. With no baseline available
(no records, none carrying the key, or only malformed files) it makes
no step-time judgements, but journals one ``baseline_warning`` event so
the gap is visible in the report rather than silent. A warmup window
skips the first observations — compile time dominates them and would
always "regress". ``observe_quality`` additionally checks fidelity
summary fields (from the quality telemetry plane, obs/quality.py)
against configured limits, journalling ``regression`` events with
``key="quality:<field>"``. ``observe_phases`` does the same for
per-phase durations (host PhaseTimers summaries or the anatomy plane's
phase totals) against ``phase_limits``, with ``key="phase:<name>"``.
"""

from __future__ import annotations

import glob
import json
import os
import statistics
from typing import Any, Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def scan_bench_records(key: str, root: Optional[str] = None):
    """Scan BENCH_r*.json under ``root`` (repo root by default) for
    ``key``. Returns ``(values, n_files, malformed)`` where ``malformed``
    lists basenames of records that existed but could not be used
    (unreadable JSON, or not a dict) — so callers can journal a
    ``baseline_warning`` instead of silently training unbaselined.

    The key is looked up in the record's ``parsed`` dict first, then at
    the top level — quality summary keys (e.g. ``quality_comp_err``)
    land wherever bench.py's ``_record`` copied them."""
    root = root or _REPO_ROOT
    values: List[float] = []
    malformed: List[str] = []
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    for path in paths:
        try:
            with open(path) as f:
                rec = json.load(f)
            if not isinstance(rec, dict):
                malformed.append(os.path.basename(path))
                continue
            parsed = rec.get("parsed")
            val = (parsed or {}).get(key) if isinstance(parsed, dict) \
                else None
            if val is None:
                val = rec.get(key)
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                values.append(float(val))
        except Exception:
            malformed.append(os.path.basename(path))
    return values, len(paths), malformed


def load_bench_values(key: str,
                      root: Optional[str] = None) -> List[float]:
    """All usable ``key`` values from BENCH_r*.json under ``root``
    (repo root by default). Tolerates missing/garbled records."""
    return scan_bench_records(key, root=root)[0]


class RegressionDetector:
    """Flags step times above ``tolerance × baseline_ms``."""

    def __init__(self, baseline_ms: Optional[float],
                 tolerance: float = 1.5, warmup_windows: int = 2,
                 bus=None, key: Optional[str] = None,
                 quality_limits: Optional[Dict[str, float]] = None,
                 phase_limits: Optional[Dict[str, float]] = None):
        self.baseline_ms = baseline_ms
        self.tolerance = float(tolerance)
        self.warmup_windows = int(warmup_windows)
        self.bus = bus
        self.key = key
        self.quality_limits = dict(quality_limits or {})
        self.phase_limits = dict(phase_limits or {})
        self.observations = 0
        self.flagged: List[Dict[str, Any]] = []

    @classmethod
    def from_bench_records(cls, key: str = "oktopk_ms",
                           root: Optional[str] = None,
                           **kwargs) -> "RegressionDetector":
        vals, n_files, malformed = scan_bench_records(key, root=root)
        baseline = statistics.median(vals) if vals else None
        det = cls(baseline, key=key, **kwargs)
        if baseline is None and det.bus is not None:
            # an unusable baseline must not kill training (the detector
            # is advisory) — but it must not vanish silently either
            reason = ("no BENCH records" if n_files == 0
                      else f"no usable '{key}' value in {n_files} records")
            det.bus.emit("baseline_warning", step=0, key=str(key),
                         reason=reason, files=n_files,
                         malformed=list(malformed))
        return det

    def observe(self, step: int, ms: float) -> Optional[Dict[str, Any]]:
        """Feed one measured step time (milliseconds). Returns the
        regression record when flagged, else None."""
        self.observations += 1
        if self.baseline_ms is None or self.baseline_ms <= 0:
            return None
        if self.observations <= self.warmup_windows:
            return None
        ms = float(ms)
        if ms <= self.tolerance * self.baseline_ms:
            return None
        rec = {"step": int(step), "ms": ms,
               "baseline_ms": float(self.baseline_ms),
               "ratio": ms / self.baseline_ms,
               "tolerance": self.tolerance, "key": self.key}
        self.flagged.append(rec)
        if self.bus is not None:
            self.bus.emit("regression", **rec)
        return rec

    def observe_quality(self, step: int,
                        summary: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Check a quality summary (e.g. a rollup's fields) against the
        configured ``quality_limits`` (``{"comp_err_mean": 0.5, ...}``).
        Each exceeded limit is journalled as a ``regression`` event with
        ``key="quality:<field>"`` — the same event the feedback window
        votes on, so fidelity drift can force a re-tune exactly like a
        step-time regression. No warmup gating: quality values are not
        compile-time-polluted."""
        flagged: List[Dict[str, Any]] = []
        for field, limit in self.quality_limits.items():
            val = summary.get(field)
            if not isinstance(val, (int, float)) or limit <= 0:
                continue
            val = float(val)
            if val != val or val <= float(limit):   # NaN or within limit
                continue
            rec = {"step": int(step), "ms": val,
                   "baseline_ms": float(limit), "ratio": val / float(limit),
                   "tolerance": 1.0, "key": f"quality:{field}"}
            flagged.append(rec)
            self.flagged.append(rec)
            if self.bus is not None:
                self.bus.emit("regression", **rec)
        return flagged

    def observe_phases(self, step: int,
                       phases: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Check per-phase durations against ``phase_limits``
        (``{"exchange": 50.0, ...}``, milliseconds). ``phases`` maps
        phase name to a plain ms number OR a stats dict (a PhaseTimers
        summary entry or a step_anatomy phase entry) — ``ms`` then
        ``mean_ms`` is read from it. Each exceeded limit journals a
        ``regression`` with ``key="phase:<name>"``, the same event the
        retune feedback window votes on. No warmup gating: the caller
        feeds post-compile summaries."""
        flagged: List[Dict[str, Any]] = []
        for name, limit in self.phase_limits.items():
            val = phases.get(name)
            if isinstance(val, dict):
                val = val.get("ms", val.get("mean_ms"))
            if not isinstance(val, (int, float)) or isinstance(val, bool) \
                    or float(limit) <= 0:
                continue
            val = float(val)
            if val != val or val <= float(limit):   # NaN or within limit
                continue
            rec = {"step": int(step), "ms": val,
                   "baseline_ms": float(limit), "ratio": val / float(limit),
                   "tolerance": 1.0, "key": f"phase:{name}"}
            flagged.append(rec)
            self.flagged.append(rec)
            if self.bus is not None:
                self.bus.emit("regression", **rec)
        return flagged
