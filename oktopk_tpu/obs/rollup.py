"""Windowed quality rollups + breach detection on the run-journal bus.

``RollupEngine`` subscribes to the bus: every per-bucket ``quality``
flush (obs/quality.py) is immediately aggregated into one
``quality_rollup`` event — means/extremes over the window's samples,
guard-skipped rows excluded from the aggregates (their values were
observed pre-rollback and may be the fault itself) but counted — with
a ``breaches`` list naming which fidelity invariants failed:

- ``residual_growth``  — mean step-over-step residual growth above
  ``growth_limit`` with real residual mass present: error feedback is
  accumulating faster than it drains (the paper's bounded-residual
  premise failing live).
- ``density_collapse`` — mean realised density below
  ``collapse_ratio ×`` the bucket's target WITH nonzero compression
  error: selection is delivering a fraction of the k it was tuned for
  (capacity overflow, threshold runaway). Lossless windows are exempt
  — dense-warmup steps (and genuinely concentrated gradients the
  selection captures whole) score comp_err ≈ 0 while realised density
  reflects the dense gradient's own sparsity, which is not a failure.
- ``churn_spike``      — mean index churn above ``churn_limit``: the
  selected support is thrashing step to step, so error feedback keeps
  paying first-selection cost.
- ``comp_err``         — mean compression error above
  ``comp_err_limit``: the delivered gradient no longer approximates
  the dense one at all.

Because the RunJournal subscribes to the bus before this engine is
built (train/trainer.py constructs them in that order), the nested
emit lands the rollup right after its quality event in the journal.
Breached rollups feed the existing closed-loop seams: the
AnomalyTracer arms on them (obs/tracing.py), AutotuneFeedback counts
them as retune evidence (resilience/feedback.py), and the trainer's
``on_breach`` callback routes fidelity breaches into
``DensityBackoff.note_quality_breach`` — the quality half of the
density loop that guard pressure alone could only push downward.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence


def _agg(vals: Sequence[Any], fn) -> Optional[float]:
    clean = [float(v) for v in vals if isinstance(v, (int, float))]
    return fn(clean) if clean else None


def _mean(vals: Sequence[Any]) -> Optional[float]:
    return _agg(vals, lambda v: sum(v) / len(v))


def rollup_quality_event(entry: Dict[str, Any],
                         growth_limit: float = 1.5,
                         collapse_ratio: float = 0.25,
                         churn_limit: float = 0.9,
                         comp_err_limit: float = 1.0,
                         target_density: Optional[float] = None,
                         ) -> Dict[str, Any]:
    """One ``quality`` event -> one ``quality_rollup`` payload."""
    skipped = [int(s) for s in (entry.get("skipped") or [])]
    n_rows = int(entry.get("count") or len(entry.get("steps") or []))

    def live(col: str) -> List[Any]:
        vals = entry.get(col) or []
        if skipped and len(skipped) == len(vals):
            return [v for v, s in zip(vals, skipped) if not s]
        return list(vals)

    roll: Dict[str, Any] = {
        "step": int(entry.get("step", 0)),
        "bucket": int(entry.get("bucket", 0)),
        "window": n_rows, "skipped": sum(skipped),
    }
    if entry.get("algo"):
        roll["algo"] = str(entry["algo"])
    stats = {
        "comp_err_mean": _mean(live("comp_err")),
        "comp_err_max": _agg(live("comp_err"), max),
        "res_norm_mean": _mean(live("res_norm")),
        "res_norm_last": _agg(live("res_norm")[-1:], lambda v: v[0]),
        "res_growth_mean": _mean(live("res_growth")),
        "res_growth_max": _agg(live("res_growth"), max),
        "eff_density_mean": _mean(live("eff_density")),
        "eff_density_min": _agg(live("eff_density"), min),
        "thr_drift_mean": _mean(live("thr_drift")),
        "churn_mean": _mean(live("churn")),
        "churn_max": _agg(live("churn"), max),
    }
    roll.update({k: v for k, v in stats.items() if v is not None})
    if target_density is not None:
        roll["target_density"] = float(target_density)

    breaches: List[str] = []
    g = stats["res_growth_mean"]
    if (g is not None and g > growth_limit
            and (stats["res_norm_mean"] or 0.0) > 0.0):
        breaches.append("residual_growth")
    d = stats["eff_density_mean"]
    if (d is not None and target_density is not None
            and target_density > 0 and d < collapse_ratio * target_density
            and (stats["comp_err_mean"] or 0.0) > 1e-6):
        breaches.append("density_collapse")
    c = stats["churn_mean"]
    if c is not None and c > churn_limit:
        breaches.append("churn_spike")
    e = stats["comp_err_mean"]
    if e is not None and e > comp_err_limit:
        breaches.append("comp_err")
    roll["breaches"] = breaches
    return roll


class RollupEngine:
    """Bus subscriber: quality flush in, windowed rollup out.

    ``target_densities`` (per-bucket, kept current by the trainer at
    flush time) anchors density-collapse detection; ``on_breach(step,
    bucket, breaches)`` is the closed-loop hook. A subscriber must
    never raise — the bus swallows failures, but evidence would be
    lost silently — so aggregation is defensive about missing fields.
    """

    def __init__(self, bus, growth_limit: float = 1.5,
                 collapse_ratio: float = 0.25, churn_limit: float = 0.9,
                 comp_err_limit: float = 1.0,
                 on_breach: Optional[Callable[[int, int, List[str]],
                                              Any]] = None):
        self.bus = bus
        self.growth_limit = float(growth_limit)
        self.collapse_ratio = float(collapse_ratio)
        self.churn_limit = float(churn_limit)
        self.comp_err_limit = float(comp_err_limit)
        self.on_breach = on_breach
        self.target_densities: List[float] = []
        self.rollups: List[Dict[str, Any]] = []
        self.breached = 0
        if bus is not None:
            bus.subscribe(self._on_event)

    def _target_for(self, bucket: int) -> Optional[float]:
        if 0 <= bucket < len(self.target_densities):
            return float(self.target_densities[bucket])
        return None

    def _on_event(self, entry: Dict[str, Any]) -> None:
        if entry.get("event") != "quality":
            return
        roll = rollup_quality_event(
            entry, growth_limit=self.growth_limit,
            collapse_ratio=self.collapse_ratio,
            churn_limit=self.churn_limit,
            comp_err_limit=self.comp_err_limit,
            target_density=self._target_for(int(entry.get("bucket", 0))))
        self.rollups.append(roll)
        if self.bus is not None:
            # nested emit: EventBus iterates a snapshot of subscribers,
            # so re-entrant emission is safe and the rollup journals
            # directly after the quality event that produced it
            self.bus.emit("quality_rollup", **roll)
        if roll["breaches"]:
            self.breached += 1
            if self.on_breach is not None:
                self.on_breach(roll["step"], roll["bucket"],
                               list(roll["breaches"]))
