"""Anomaly-triggered profiler windows + Chrome trace export.

``AnomalyTracer`` subscribes to the run-journal event bus: a
``guard_trip``, ``fallback``, or breach-flagged ``quality_rollup``
event ARMS it, and the next
``on_step()`` call opens a bounded ``jax.profiler`` trace window over
the following N steps, closing with a ``trace_captured`` journal event
that ties the capture back to its trigger (``"guard_trip@step12"``).
The expensive instrument therefore runs only when something is already
wrong — the steady-state overhead is one predicate per step.

Capture count is capped (``max_captures``): a flapping guard must not
fill the disk with traces. Profiler failures are tolerated — the
window is journalled with ``logdir: null`` rather than raising, since
observability must never take down training (some backends/platforms
cannot start a trace at all).

``ChromeTraceSink`` collects host-phase samples (utils/profiling.py
``PhaseTimers``) as Chrome trace-event ``"X"`` (complete) events for
``chrome://tracing`` / Perfetto.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

_TRIGGERS = ("guard_trip", "fallback", "quality_rollup")


class AnomalyTracer:
    """Arms on anomaly events, captures a bounded trace window."""

    def __init__(self, logdir: str, bus=None, num_steps: int = 3,
                 max_captures: int = 3):
        self.logdir = logdir
        self.bus = bus
        self.num_steps = max(1, int(num_steps))
        self.max_captures = max(0, int(max_captures))
        self.captures: List[Dict[str, Any]] = []
        self._armed: Optional[str] = None      # trigger description
        self._start_step: Optional[int] = None
        self._active_dir: Optional[str] = None
        self._profiler_ok = False
        if bus is not None:
            bus.subscribe(self._on_event)

    @property
    def active(self) -> bool:
        return self._start_step is not None

    def _on_event(self, entry: Dict[str, Any]):
        event = entry.get("event")
        if event not in _TRIGGERS:
            return
        if event == "quality_rollup" and not entry.get("breaches"):
            return                 # only breached rollups are anomalies
        if self.active or self._armed is not None:
            return                 # one window at a time
        if len(self.captures) >= self.max_captures:
            return
        self._armed = f"{event}@step{entry.get('step')}"

    def on_step(self, step: int):
        """Call once per training step (host side, before the step)."""
        step = int(step)
        if self.active:
            if step >= self._start_step + self.num_steps:
                self._stop(step)
            return
        if self._armed is not None:
            self._start(step)

    def _start(self, step: int):
        d = os.path.join(self.logdir, f"anomaly_step{step}")
        self._profiler_ok = False
        try:
            import jax
            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
            self._profiler_ok = True
            self._active_dir = d
        except Exception:
            self._active_dir = None   # journal the window anyway
        self._start_step = step

    def _stop(self, step: int):
        if self._profiler_ok:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                self._active_dir = None
        cap = {"step": int(step), "start_step": int(self._start_step),
               "num_steps": int(step - self._start_step),
               "logdir": self._active_dir,
               "trigger": self._armed or "unknown"}
        self.captures.append(cap)
        self._armed = None
        self._start_step = None
        self._active_dir = None
        self._profiler_ok = False
        if self.bus is not None:
            self.bus.emit("trace_captured", **cap)

    def finish(self, step: int):
        """Force-close any open window (end of train())."""
        if self.active:
            self._stop(int(step))


class ChromeTraceSink:
    """Collects host phase samples as Chrome trace-event JSON.

    Each bucket/phase family gets its own tid (first-seen order), so
    Perfetto renders one row per family instead of interleaving every
    sample on a single track; ``write()`` prepends trace metadata
    ("M") events naming the process and each lane. Output stays
    backward-readable: the "X" events carry the same fields as before
    (plus distinct tids) and old consumers that only scan "X" events
    see an identical payload shape.
    """

    def __init__(self):
        self.events: List[Dict[str, Any]] = []
        self._lanes: Dict[str, int] = {}

    def _lane(self, name: str) -> str:
        """Lane key for one sample: anatomy-contract names group by
        (bucket, phase) family; anything else gets its own row."""
        from oktopk_tpu.obs.anatomy import parse_scope, scope_name
        parsed = parse_scope(name)
        if parsed is not None and parsed != (None, None):
            return scope_name(*parsed)
        return name

    def add(self, name: str, ts_s: float, dur_s: float):
        """One complete ("X") event; times in seconds (host clock)."""
        tid = self._lanes.setdefault(self._lane(name), len(self._lanes))
        self.events.append({
            "name": name, "ph": "X", "pid": 0, "tid": tid,
            "ts": float(ts_s) * 1e6, "dur": float(dur_s) * 1e6,
        })

    def _metadata_events(self) -> List[Dict[str, Any]]:
        meta: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
            "args": {"name": "oktopk host phases"},
        }]
        for lane, tid in sorted(self._lanes.items(), key=lambda kv: kv[1]):
            meta.append({"name": "thread_name", "ph": "M", "pid": 0,
                         "tid": tid, "args": {"name": lane}})
            meta.append({"name": "thread_sort_index", "ph": "M", "pid": 0,
                         "tid": tid, "args": {"sort_index": tid}})
        return meta

    def write(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": self._metadata_events() + self.events,
                       "displayTimeUnit": "ms"}, f)
        return path
