"""Per-algorithm analytic wire-byte budgets and conformance ratios.

The collectives now thread REALISED payload bytes through
``SparseState.wire_bytes`` (collectives/state.py, wire-dtype-aware:
bf16 pairs are 6 bytes, f32 pairs 8, dense psum values 4 — see
``collectives/wire.py`` pair_wire_bytes/dense_wire_bytes). This module
supplies the analytic side: what each algorithm is ALLOWED to move per
worker per steady-state step, so ``conformance_ratio = measured /
budget <= 1.0`` is a checkable invariant for all eight algorithms.

Budget semantics differ by family, on purpose:

- ``oktopk``: the paper's O(k) claim — 6k scalars = 3k (index, value)
  pairs per step (Ok-Topk §4). This is a *paper-conformance* bound:
  the measured steady-state traffic (prediction steps, not the
  every-``global_recompute_every`` exact recomputes, which draw from
  the larger ``cap_exact`` pool) must fit under it. Realised traffic
  is ≈2.4k pairs, so the ratio lands near 0.8 with headroom that is
  the algorithm's safety margin, not slack in the test.
- ``topkA``/``topkA2``: exactly kP pairs — the allgather of [P, k]
  buffers admits no variance, so the ratio is exactly 1.0.
- ``gtopk``: 2k pairs per butterfly round × log2(P) rounds (tight).
- ``topkAopt``/``gaussiank``/``gaussiankconcat``: P·cap_local pairs —
  the fixed-capacity buffers' hard guarantee. Threshold selection can
  overshoot k (Gaussian fit error, stale thresholds), so a k-based
  band budget would flake; the capacity ceiling is the contract the
  fixed buffers actually enforce (and which the reference's ragged
  Allgatherv lacks).
- ``topkSA``/``topkDSA``: split phase ≤ 2(P−1)·cap_pair pairs, plus a
  gather phase that may densify — max(P·cap_local pairs, 2n f32
  values) covers the dense fallback branch.
- ``gaussiankSA``: same split phase + always-sparse gather.
- ``dense``: 2n f32 values (ring-allreduce send+receive; the psum is
  never wire-rounded).
- ``hierarchical``: PER LEVEL (``hierarchical_budget_bytes``). The
  intra level is a dense ring over the pod — 2n(P_pod−1)/P_pod f32
  values, exact. The inter level is the OUTER algorithm's existing
  budget evaluated at P=num_pods. The flat entry points accept a
  ``HierarchicalConfig`` with ``name="hierarchical"`` and return the
  level sum; ``hierarchical_volume_report`` emits one level-tagged
  ``volume_report`` payload per level plus a combined total.

``capacity_bytes`` is the static buffer ceiling for every algorithm —
the absolute worst case any step (including oktopk exact recomputes)
can move — reported alongside the budget for context.
"""

from __future__ import annotations

import math

from oktopk_tpu.config import OkTopkConfig

# registry aliases (collectives/registry.py): same function, same wire
_ALIAS = {"gaussiankconcat": "gaussiank", "topkDSA": "topkSA"}


def _canon(name: str) -> str:
    return _ALIAS.get(name, name)


def _intra_budget_bytes(hcfg) -> float:
    """Dense ring allreduce over the pod: 2n(P_pod−1)/P_pod f32 values —
    the exact pattern collectives/hierarchical.py accounts per step."""
    pod = hcfg.pod_size
    return 2.0 * hcfg.n * (pod - 1) / max(1, pod) * 4.0


def hierarchical_budget_bytes(hcfg) -> dict:
    """Per-level steady-state budgets for a ``HierarchicalConfig``:
    ``{"intra": dense-ring bytes over the pod, "inter": the outer
    algorithm's flat budget at P=num_pods}``."""
    return {"intra": _intra_budget_bytes(hcfg),
            "inter": budget_bytes(hcfg.outer, hcfg.outer_cfg)}


def _as_hierarchical(name: str, cfg):
    """Return cfg as a HierarchicalConfig when ``name`` names the
    two-level composition, else None (lazy import keeps obs free of a
    static collectives dependency)."""
    if name != "hierarchical":
        return None
    from oktopk_tpu.collectives.hierarchical import HierarchicalConfig
    if not isinstance(cfg, HierarchicalConfig):
        raise TypeError("'hierarchical' volume accounting needs a "
                        f"HierarchicalConfig, got {type(cfg).__name__}")
    return cfg


def budget_bytes(name: str, cfg: OkTopkConfig) -> float:
    """Per-worker steady-state wire-byte budget for one step of
    algorithm ``name`` under ``cfg``. Measured ``last_wire_bytes`` must
    satisfy ``measured <= budget`` (conformance ratio <= 1.0).

    ``name="hierarchical"`` (with a ``HierarchicalConfig``) returns the
    level sum — see :func:`hierarchical_budget_bytes` for the split."""
    hcfg = _as_hierarchical(name, cfg)
    if hcfg is not None:
        return float(sum(hierarchical_budget_bytes(hcfg).values()))
    name = _canon(name)
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    pair = float(cfg.wire_pair_bytes)
    if name == "dense":
        return 2.0 * n * 4.0
    if name in ("topkA", "topkA2"):
        return float(k) * P * pair
    if name == "gtopk":
        rounds = max(1, int(math.log2(P)))
        return 2.0 * k * rounds * pair
    if name == "oktopk":
        return 3.0 * k * pair          # the paper's 6k scalars
    if name in ("topkAopt", "gaussiank"):
        return float(P) * cfg.cap_local * pair
    if name == "topkSA":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        gather = max(float(P) * cfg.cap_local * pair, 2.0 * n * 4.0)
        return split + gather
    if name == "gaussiankSA":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        return split + float(P) * cfg.cap_local * pair
    raise ValueError(f"no wire-byte budget for algorithm {name!r}")


def capacity_bytes(name: str, cfg: OkTopkConfig) -> float:
    """Static worst-case ceiling: the most any single step (including
    oktopk's exact-recompute steps) can put on the wire per worker.
    Hierarchical: the (exact) intra ring plus the outer capacity."""
    hcfg = _as_hierarchical(name, cfg)
    if hcfg is not None:
        return float(_intra_budget_bytes(hcfg)
                     + capacity_bytes(hcfg.outer, hcfg.outer_cfg))
    name = _canon(name)
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    pair = float(cfg.wire_pair_bytes)
    if name == "dense":
        return 2.0 * n * 4.0
    if name in ("topkA", "topkA2"):
        return float(k) * P * pair
    if name == "gtopk":
        rounds = max(1, int(math.log2(P)))
        return 2.0 * k * rounds * pair
    if name == "oktopk":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        gather = float(P) * max(cfg.cap_gather, cfg.cap_exact) * pair
        return split + gather
    if name in ("topkAopt", "gaussiank"):
        return float(P) * cfg.cap_local * pair
    if name == "topkSA":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        gather = max(float(P) * cfg.cap_local * pair, 2.0 * n * 4.0)
        return split + gather
    if name == "gaussiankSA":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        return split + float(P) * cfg.cap_local * pair
    raise ValueError(f"no wire-byte capacity for algorithm {name!r}")


def conformance_ratio(name: str, cfg: OkTopkConfig,
                      measured_bytes: float) -> float:
    """measured / budget. <= 1.0 means the algorithm kept its analytic
    volume promise on the wire."""
    b = budget_bytes(name, cfg)
    return float(measured_bytes) / b if b > 0 else float("inf")


def volume_report(name: str, cfg: OkTopkConfig, mean_wire_bytes: float,
                  *, bucket: int = 0, step: int = 0,
                  steps: int = 0) -> dict:
    """Assemble one ``volume_report`` event payload
    (obs/events.py schema) from a measured per-step mean."""
    return {
        "step": int(step), "bucket": int(bucket), "algo": name,
        "n": int(cfg.n), "density": float(cfg.density),
        "steps": int(steps),
        "mean_wire_bytes": float(mean_wire_bytes),
        "budget_bytes": float(budget_bytes(name, cfg)),
        "capacity_bytes": float(capacity_bytes(name, cfg)),
        "conformance_ratio": conformance_ratio(name, cfg,
                                               mean_wire_bytes),
    }


def hierarchical_volume_report(hcfg, mean_intra_bytes: float,
                               mean_inter_bytes: float, *,
                               bucket: int = 0, step: int = 0,
                               steps: int = 0) -> list:
    """Per-level ``volume_report`` payloads for a two-level run.

    Takes the measured per-step means of ``SparseState.
    last_wire_bytes_intra`` / ``last_wire_bytes_inter`` and returns
    THREE level-tagged payloads — ``level="intra"`` (dense ring vs its
    exact budget), ``level="inter"`` (the outer algorithm vs its flat
    budget at P=num_pods), and ``level="total"`` (the sums, whose
    ``conformance_ratio`` is the combined invariant the acceptance
    tests hold <= 1.0). Each payload validates against the flat
    ``volume_report`` schema; ``level`` is the only added field."""
    budgets = hierarchical_budget_bytes(hcfg)
    ocfg = hcfg.outer_cfg
    base = {"step": int(step), "bucket": int(bucket), "n": int(hcfg.n),
            "steps": int(steps)}
    intra_b = budgets["intra"]
    levels = [
        {**base, "level": "intra", "algo": hcfg.inner, "density": 1.0,
         "mean_wire_bytes": float(mean_intra_bytes),
         "budget_bytes": float(intra_b),
         "capacity_bytes": float(intra_b),
         "conformance_ratio": (float(mean_intra_bytes) / intra_b
                               if intra_b > 0 else float("inf"))},
        {**base, "level": "inter", "algo": hcfg.outer,
         "density": float(ocfg.density),
         "mean_wire_bytes": float(mean_inter_bytes),
         "budget_bytes": float(budgets["inter"]),
         "capacity_bytes": float(capacity_bytes(hcfg.outer, ocfg)),
         "conformance_ratio": conformance_ratio(hcfg.outer, ocfg,
                                                mean_inter_bytes)},
    ]
    total_mean = float(mean_intra_bytes) + float(mean_inter_bytes)
    total_budget = float(sum(budgets.values()))
    levels.append(
        {**base, "level": "total", "algo": "hierarchical",
         "density": float(hcfg.density),
         "mean_wire_bytes": total_mean,
         "budget_bytes": total_budget,
         "capacity_bytes": float(capacity_bytes("hierarchical", hcfg)),
         "conformance_ratio": (total_mean / total_budget
                               if total_budget > 0 else float("inf"))})
    return levels
