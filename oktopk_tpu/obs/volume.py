"""Per-algorithm analytic wire-byte budgets and conformance ratios.

The collectives now thread REALISED payload bytes through
``SparseState.wire_bytes`` (collectives/state.py, wire-dtype-aware:
bf16 pairs are 6 bytes, f32 pairs 8, dense psum values 4 — see
``collectives/wire.py`` pair_wire_bytes/dense_wire_bytes). This module
supplies the analytic side: what each algorithm is ALLOWED to move per
worker per steady-state step, so ``conformance_ratio = measured /
budget <= 1.0`` is a checkable invariant for all eight algorithms.

Budget semantics differ by family, on purpose:

- ``oktopk``: the paper's O(k) claim — 6k scalars = 3k (index, value)
  pairs per step (Ok-Topk §4). This is a *paper-conformance* bound:
  the measured steady-state traffic (prediction steps, not the
  every-``global_recompute_every`` exact recomputes, which draw from
  the larger ``cap_exact`` pool) must fit under it. Realised traffic
  is ≈2.4k pairs, so the ratio lands near 0.8 with headroom that is
  the algorithm's safety margin, not slack in the test.
- ``topkA``/``topkA2``: exactly kP pairs — the allgather of [P, k]
  buffers admits no variance, so the ratio is exactly 1.0.
- ``gtopk``: 2k pairs per butterfly round × log2(P) rounds (tight).
- ``topkAopt``/``gaussiank``/``gaussiankconcat``: P·cap_local pairs —
  the fixed-capacity buffers' hard guarantee. Threshold selection can
  overshoot k (Gaussian fit error, stale thresholds), so a k-based
  band budget would flake; the capacity ceiling is the contract the
  fixed buffers actually enforce (and which the reference's ragged
  Allgatherv lacks).
- ``topkSA``/``topkDSA``: split phase ≤ 2(P−1)·cap_pair pairs, plus a
  gather phase that may densify — max(P·cap_local pairs, 2n f32
  values) covers the dense fallback branch.
- ``gaussiankSA``: same split phase + always-sparse gather.
- ``dense``: 2n f32 values (ring-allreduce send+receive; the psum is
  never wire-rounded).

``capacity_bytes`` is the static buffer ceiling for every algorithm —
the absolute worst case any step (including oktopk exact recomputes)
can move — reported alongside the budget for context.
"""

from __future__ import annotations

import math

from oktopk_tpu.config import OkTopkConfig

# registry aliases (collectives/registry.py): same function, same wire
_ALIAS = {"gaussiankconcat": "gaussiank", "topkDSA": "topkSA"}


def _canon(name: str) -> str:
    return _ALIAS.get(name, name)


def budget_bytes(name: str, cfg: OkTopkConfig) -> float:
    """Per-worker steady-state wire-byte budget for one step of
    algorithm ``name`` under ``cfg``. Measured ``last_wire_bytes`` must
    satisfy ``measured <= budget`` (conformance ratio <= 1.0)."""
    name = _canon(name)
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    pair = float(cfg.wire_pair_bytes)
    if name == "dense":
        return 2.0 * n * 4.0
    if name in ("topkA", "topkA2"):
        return float(k) * P * pair
    if name == "gtopk":
        rounds = max(1, int(math.log2(P)))
        return 2.0 * k * rounds * pair
    if name == "oktopk":
        return 3.0 * k * pair          # the paper's 6k scalars
    if name in ("topkAopt", "gaussiank"):
        return float(P) * cfg.cap_local * pair
    if name == "topkSA":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        gather = max(float(P) * cfg.cap_local * pair, 2.0 * n * 4.0)
        return split + gather
    if name == "gaussiankSA":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        return split + float(P) * cfg.cap_local * pair
    raise ValueError(f"no wire-byte budget for algorithm {name!r}")


def capacity_bytes(name: str, cfg: OkTopkConfig) -> float:
    """Static worst-case ceiling: the most any single step (including
    oktopk's exact-recompute steps) can put on the wire per worker."""
    name = _canon(name)
    P, n, k = cfg.num_workers, cfg.n, cfg.k
    pair = float(cfg.wire_pair_bytes)
    if name == "dense":
        return 2.0 * n * 4.0
    if name in ("topkA", "topkA2"):
        return float(k) * P * pair
    if name == "gtopk":
        rounds = max(1, int(math.log2(P)))
        return 2.0 * k * rounds * pair
    if name == "oktopk":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        gather = float(P) * max(cfg.cap_gather, cfg.cap_exact) * pair
        return split + gather
    if name in ("topkAopt", "gaussiank"):
        return float(P) * cfg.cap_local * pair
    if name == "topkSA":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        gather = max(float(P) * cfg.cap_local * pair, 2.0 * n * 4.0)
        return split + gather
    if name == "gaussiankSA":
        split = 2.0 * (P - 1) * cfg.cap_pair * pair
        return split + float(P) * cfg.cap_local * pair
    raise ValueError(f"no wire-byte capacity for algorithm {name!r}")


def conformance_ratio(name: str, cfg: OkTopkConfig,
                      measured_bytes: float) -> float:
    """measured / budget. <= 1.0 means the algorithm kept its analytic
    volume promise on the wire."""
    b = budget_bytes(name, cfg)
    return float(measured_bytes) / b if b > 0 else float("inf")


def volume_report(name: str, cfg: OkTopkConfig, mean_wire_bytes: float,
                  *, bucket: int = 0, step: int = 0,
                  steps: int = 0) -> dict:
    """Assemble one ``volume_report`` event payload
    (obs/events.py schema) from a measured per-step mean."""
    return {
        "step": int(step), "bucket": int(bucket), "algo": name,
        "n": int(cfg.n), "density": float(cfg.density),
        "steps": int(steps),
        "mean_wire_bytes": float(mean_wire_bytes),
        "budget_bytes": float(budget_bytes(name, cfg)),
        "capacity_bytes": float(capacity_bytes(name, cfg)),
        "conformance_ratio": conformance_ratio(name, cfg,
                                               mean_wire_bytes),
    }
