"""Functional compression kernels (replaces reference compression.py).

The reference's compressors (TopKCompressor / GaussianCompressor and eight
subclasses, reference VGG/compression.py) are stateful classes with class-attr
residual dicts. Here every operation is a pure function over explicit arrays;
residual state lives in ``collectives.state.SparseState`` and is threaded
through jit, so it is checkpointable (fixing the reference gap noted in
SURVEY.md §5.4: residuals were never saved).
"""

from oktopk_tpu.ops.topk import (  # noqa: F401
    exact_topk,
    ratio2threshold,
    k2threshold,
)
from oktopk_tpu.ops.select import (  # noqa: F401
    SENTINEL,
    count_by_threshold,
    scatter_sparse,
    select_by_threshold,
    select_mask,
    select_nonzero,
    pack_by_region,
)
from oktopk_tpu.ops.gaussian import gaussian_threshold  # noqa: F401
from oktopk_tpu.ops.hist_threshold import (  # noqa: F401
    hist_to_threshold,
    k2threshold_hist,
    log2_hist,
)
from oktopk_tpu.ops.fused_select import (  # noqa: F401
    fused_pack_finalize,
    fused_select_pallas,
    fused_select_reference,
    fused_select_stage,
)
from oktopk_tpu.ops.residual import (  # noqa: F401
    add_residual,
    update_residual_at_winners,
    update_residual_at_selection,
)
