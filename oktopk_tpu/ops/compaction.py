"""Stream compaction: pack masked elements into fixed-capacity buffers.

This is the selection hot path of every sparse collective (SURVEY.md §7.3.5).
The portable implementation (ops/select.py ``select_mask``) builds a full-
length cumsum and a full-length scatter — on TPU the n-operand scatter
serialises (~69 ms for n=14.7M on v5e, measured) and dominated the train
step in rounds 1-2. TPU has no scatter unit, so the fast path splits the
work by what the hardware is good at:

1. A Pallas *staging* kernel does the n-scale work: per 1024-element block
   (one [8, 128] f32 tile), threshold-mask -> in-block exclusive prefix sum
   (Hillis-Steele shifted adds on the VPU) -> one [1,128] x [capb,128]^T
   MXU matmul per sublane row that drops each survivor's in-block offset
   (< 1024, exact in f32 at Precision.HIGHEST) into its packed slot. Each
   block writes its own staging row — standard blocked VMEM outputs, no
   cross-block sequencing, so the grid pipelines freely.
2. Plain-XLA post-processing does the cap-scale work with *gathers* (the
   measured costs on v5e: gather ~10 ns/elem/round, cap-operand scatter
   ~4.7 ns/elem, n-operand scatter ~4700 ns/1000 elem): the per-output-slot
   staging address and element base both *telescope* along the output axis
   (crossing a block's end advances them by fixed per-block jumps), so one
   small scatter-add of the jumps + a cap-scale cumsum replaces any
   searchsorted/base-gather, leaving exactly 2 cap-scale gather rounds
   (the staged offset, then the value) — see ``_materialize``.

Why not DMA-append inside the kernel (the round-3 first attempt): Mosaic
cannot slice a tiled VMEM scratch per row, and 1-D memrefs — HBM included —
carry a (1024) tiling whose dynamic-offset slices need a divisibility
proof that a running element count cannot give. Block-granular staging
sidesteps every such constraint: all kernel outputs are statically blocked.

Exactness: the staging width ``capb`` (128) caps how many survivors one
block can stage. Blocks almost never exceed it in the threshold-band
regime (~20 survivors/block at the paper's densities), but a correlated
gradient can: the kernel therefore also emits *raw* per-block survivor
counts, and the wrapper switches (``lax.cond``) to a capb=1024 kernel —
which can never drop anything — whenever a block overflowed and the drop
could matter. Both paths reproduce the portable result bit-for-bit
(asserted in tests/test_compaction.py and on real hardware in
tests/test_tpu_hw.py).

The reference's analogous code is the boolean-mask nonzero select
(``compressbythreshold``, VGG/compression.py:122-142) — a cheap op on GPU,
the wrong shape for TPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np


def _interpret_default() -> bool:
    """OKTOPK_PALLAS_INTERPRET=1 runs the kernel in the Pallas interpreter
    (CPU-mesh tests of the full pallas-path algorithms)."""
    return os.environ.get("OKTOPK_PALLAS_INTERPRET", "0") == "1"


BLK_ROWS = 8          # f32 min tile is (8, 128)
BLK_COLS = 128
BLK = BLK_ROWS * BLK_COLS

# sub-blocks per grid step: staging rows come 8 at a time so every output
# block is a full (8, capb) tile — 2-D (1, capb) blocks fail the (8, 128)
# divisibility rule and 1-D (capb,) blocks fail XLA's T(1024) layout
SB = 8

CAPB_FAST = 128       # staging width of the fast kernel (one lane row)


def _shift_right(x, d, axis):
    """x shifted ``d`` slots toward higher indices along ``axis``, zero-fill.

    Concat + static slice only (``jnp.pad`` is not guaranteed a Mosaic
    lowering)."""
    zshape = list(x.shape)
    zshape[axis] = d
    sl = [slice(None), slice(None)]
    sl[axis] = slice(0, x.shape[axis] - d)
    return jnp.concatenate([jnp.zeros(zshape, x.dtype), x[tuple(sl)]],
                           axis=axis)


def _block_prefix(m):
    """Exclusive prefix sum of an [8, 128] i32 tile in row-major order,
    via Hillis-Steele shifted adds (no cumsum primitive needed in-kernel).

    Only static positive slices and full reductions — scalar extraction
    like ``r[-1, 0]`` traces to ``dynamic_slice``, which Mosaic's TC
    lowering rejects (caught on the real chip; the interpreter accepts it).
    The across-row scan runs full-width: a narrow ``[8, 1]`` slice of
    column 127 keeps lane offset 127 in its vreg, and ``tpu.concatenate``
    requires operands to agree on the non-concat (lane) offset — another
    hardware-only constraint the interpreter accepts.
    """
    s = m
    for d in (1, 2, 4, 8, 16, 32, 64):           # within-row inclusive scan
        s = s + _shift_right(s, d, axis=1)
    # per-row totals replicated across lanes (offset-0 layout)
    rt = jnp.broadcast_to(s[:, BLK_COLS - 1:BLK_COLS], (BLK_ROWS, BLK_COLS))
    r = rt
    for d in (1, 2, 4):                           # across-row inclusive scan
        r = r + _shift_right(r, d, axis=0)
    return s - m + (r - rt), jnp.sum(m)           # (excl. positions, total)


def _stage_tile(woff, sel, capb):
    """The MXU "scatter": stage[j] = in-block offset of the element whose
    packed slot is ``j``, as one [1, capb] f32 row.

    Mosaic rejects cross-lane reshapes — the obvious ``[8,128] -> [BLK,1]``
    one-hot layout is an "unsupported shape cast" on real hardware (the
    interpreter accepts it, which is why only a chip run catches it). So
    everything stays in tile layout: per sublane-row, broadcast the row's
    slot vector along a fresh sublane axis, compare with a sublane iota to
    get the transposed one-hot [capb, 128], and contract both operands on
    their lane axis (an NT matmul — dimension numbers ((1,),(1,))). Slots
    are distinct across rows so the accumulation is collision-free."""
    # i32 iota/compare: tpu.iota verifies only integer result types (a
    # float iota fails Mosaic verification on the real chip; the
    # interpreter accepts it)
    jio = jax.lax.broadcasted_iota(jnp.int32, (capb, BLK_COLS), 0)
    acc = jnp.zeros((1, capb), jnp.float32)
    for r in range(BLK_ROWS):
        selr = jax.lax.slice(sel, (r, 0), (r + 1, BLK_COLS))   # [1, 128]
        onehot_t = (jnp.broadcast_to(selr, (capb, BLK_COLS)) == jio) \
            .astype(jnp.float32)                               # [capb, 128]
        wr = jax.lax.slice(woff, (r, 0),
                           (r + 1, BLK_COLS)).astype(jnp.float32)
        # HIGHEST precision: the default matmul path feeds the MXU bf16
        # inputs (8 mantissa bits), silently rounding offsets > 256;
        # HIGHEST decomposes f32 exactly, keeping one-hot x offset exact.
        acc = acc + jax.lax.dot_general(
            wr, onehot_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
    return acc


def _stage_kernel(capb, t_ref, r_ref, x_ref, w_ref, cr_ref):
    """Stage SB consecutive blocks: w_ref[s, j] = in-block offset of the
    j-th survivor of sub-block s, cr_ref = raw survivor counts (broadcast
    over 128 lanes; the stored count is min(raw, capb) by construction —
    survivor ranks are dense — so it is derived in the wrapper, not
    written)."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    xs = x_ref[:]                                         # [SB*8, 128] f32
    woff = (jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 0)
            * BLK_COLS
            + jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 1))
    rows_w, rows_r = [], []
    for sb in range(SB):
        x = jax.lax.slice(xs, (sb * BLK_ROWS, 0),
                          ((sb + 1) * BLK_ROWS, BLK_COLS))
        gidx = (i * SB + sb) * BLK + woff
        # [lo, hi) element-range restriction (region-restricted select);
        # full range by default
        mask = ((jnp.abs(x) >= t_ref[0])
                & (gidx >= r_ref[0]) & (gidx < r_ref[1]))
        m = mask.astype(jnp.int32)
        pos, raw = _block_prefix(m)

        kept = mask & (pos < capb)
        sel = jnp.where(kept, pos, capb)                  # capb = dropped

        rows_w.append(_stage_tile(jnp.where(kept, woff, 0), sel, capb))
        rows_r.append(jnp.full((1, BLK_COLS), raw, jnp.int32))
    w_ref[:] = jnp.concatenate(rows_w, axis=0)
    cr_ref[:] = jnp.concatenate(rows_r, axis=0)


def _run_stage(xp, t, rng, capb, nblocks, interpret, vma):
    """pallas_call wrapper: (w_stage [nb, capb] f32, stored [nb], raw [nb])."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    out_shapes = [
        jax.ShapeDtypeStruct((nblocks, capb), jnp.float32, vma=vma),
        jax.ShapeDtypeStruct((nblocks, BLK_COLS), jnp.int32, vma=vma),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nblocks // SB,),
        in_specs=[pl.BlockSpec((SB * BLK_ROWS, BLK_COLS),
                               lambda i, t, r: (i, 0))],
        out_specs=[
            pl.BlockSpec((SB, capb), lambda i, t, r: (i, 0)),
            pl.BlockSpec((SB, BLK_COLS), lambda i, t, r: (i, 0)),
        ],
    )
    w, cr = pl.pallas_call(
        functools.partial(_stage_kernel, capb),
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=interpret,
    )(t, rng, xp)
    raw = cr[:, 0]
    return w, jnp.minimum(raw, capb), raw


def _materialize(w_stage, xflat, cnt_rb, off_rb, capb, cap, counts, n):
    """Materialise ``(values [R, cap], indices [R, cap])`` from a packed
    staging ``w_stage [nb, capb]`` whose block b holds (ascending-index)
    the survivors counted by ``cnt_rb [nb, R]`` per region, region r's run
    starting at in-row offset ``off_rb[b, r]`` (None = zeros, the R=1
    whole-vector select).

    Region r's output slot j reads staging slot
        b*capb + off_rb[b, r] + (j - C_excl[b, r])
    of block b = searchsorted(C[:, r], j), and its element index is
    b*BLK + staged offset. Both per-slot bases *telescope* along j:
    crossing block b (at output position C[b, r]) advances the staging
    base by capb + off_rb[b+1, r] - off_rb[b, r] - cnt_rb[b, r] and the
    element base by BLK, starting from off_rb[0, r] and 0. One small
    scatter-add of those jumps + a per-row cap-scale cumsum therefore
    replaces any searchsorted and per-slot base gather (the element base
    needs no accumulator of its own: a live slot's in-row offset is < capb,
    so its block is ``flat // capb``); only two cap-scale gather rounds
    remain (the staged offset, then the value).
    """
    nblocks, R = cnt_rb.shape
    if off_rb is None:
        off_rb = jnp.zeros_like(cnt_rb)
    c_rb = jnp.cumsum(cnt_rb, axis=0)                 # [nb, R] inclusive
    off_next = jnp.concatenate([off_rb[1:], off_rb[-1:]], axis=0)
    fval = capb + off_next - off_rb - cnt_rb          # [nb, R]
    pos = jnp.minimum(c_rb, cap)
    rgrid = jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32)[None, :],
                             (nblocks, R))
    fjump = jnp.zeros((R, cap + 1), jnp.int32).at[rgrid.T, pos.T].add(fval.T)
    j = jnp.arange(cap, dtype=jnp.int32)[None, :]
    flat = off_rb[0][:, None] + jnp.cumsum(fjump, axis=1)[:, :cap] + j
    # live slots always sit inside their block's staging row (in-row offset
    # < capb), so the source block is just flat // capb — a shift, no
    # second jump accumulator needed
    flat = jnp.clip(flat, 0, nblocks * capb - 1)
    w = w_stage.reshape(-1)[flat].astype(jnp.int32)   # gather round 1
    idx = (flat // capb) * BLK + w
    live = j < counts[:, None]
    values = jnp.where(live, xflat[jnp.minimum(idx, xflat.size - 1)],
                       0.0)                           # gather round 2
    indices = jnp.where(live, idx, n).astype(jnp.int32)
    return values, indices


def _prep(x, thresh, lo, hi):
    """Shared padding/threshold/range prep. Returns (xp2d, xflat, t, rng,
    n, nblocks)."""
    n = x.size
    pad = (-n) % (SB * BLK)
    xflat = jnp.pad(x.reshape(-1), (0, pad))
    xp = xflat.reshape(-1, BLK_COLS)
    nblocks = xp.shape[0] // BLK_ROWS
    # clamp to the smallest normal f32: a zero/negative threshold selects
    # every nonzero element rather than the padded tail (subnormals flush
    # to zero on TPU anyway)
    t = jnp.reshape(jnp.maximum(jnp.asarray(thresh, x.dtype),
                                jnp.float32(1.17549435e-38)), (1,))
    rng = jnp.stack([
        jnp.asarray(0 if lo is None else lo, jnp.int32),
        jnp.asarray(n if hi is None else hi, jnp.int32)])
    return xp, xflat, t, rng, n, nblocks


def _vma_of(xp):
    # under shard_map's VMA tracking the outputs vary over the same mesh
    # axes as the input shard, and every operand must agree
    try:
        return jax.typeof(xp).vma
    except Exception:
        return frozenset()


def _pvary_to(arr, vma):
    missing = tuple(vma - jax.typeof(arr).vma)
    return jax.lax.pvary(arr, missing) if missing else arr


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def select_by_threshold_pallas(x: jnp.ndarray, thresh, cap: int,
                               lo=None, hi=None,
                               interpret: bool | None = None):
    """Fixed-capacity threshold select, Pallas TPU fast path.

    Same contract as ops.select.select_by_threshold: returns
    ``(values[cap], indices[cap], count)`` with slots >= count holding
    value 0 / index n, elements packed in ascending index order, overflow
    beyond ``cap`` dropped with lowest-index-first retention (identical to
    the portable path). ``lo``/``hi`` restrict selection to the element
    range [lo, hi).
    """
    if interpret is None:
        interpret = _interpret_default()
    xp, xflat, t, rng, n, nblocks = _prep(x, thresh, lo, hi)
    vma = _vma_of(xp)
    if vma:
        t = _pvary_to(t, vma)
        rng = _pvary_to(rng, vma)

    capb_f = CAPB_FAST
    w_f, stored_f, raw = _run_stage(xp, t, rng, capb_f, nblocks, interpret,
                                    vma)
    count = jnp.minimum(jnp.sum(raw), cap)

    def _post(w_stage, stored, capb):
        values, indices = _materialize(
            w_stage, xflat, stored[:, None], None, capb, cap,
            count[None], n)
        return values[0], indices[0]

    if cap > capb_f:
        def wide(_):
            w_w, stored_w, _raw = _run_stage(xp, t, rng, BLK, nblocks,
                                             interpret, vma)
            return _post(w_w, stored_w, BLK)

        # A block's drops have in-block position >= capb, hence global
        # survivor rank >= excl_cumsum(raw)[b] + capb. When every drop
        # ranks >= cap, no output slot can see one (a survivor with true
        # rank < cap has no drop before it either, so the stored ordering
        # of the first cap slots is exact) — skip the full-width re-stage.
        excl = jnp.cumsum(raw) - raw
        values, indices = jax.lax.cond(
            jnp.any((raw > capb_f) & (excl + capb_f < cap)), wide,
            lambda _: _post(w_f, stored_f, capb_f), None)
    else:
        # drops beyond capb have in-block position >= capb >= cap, hence
        # global position >= cap: they can never make the first-cap prefix
        values, indices = _post(w_f, stored_f, capb_f)
    return values, indices, count


def pack_by_region_pallas(x: jnp.ndarray, thresh, boundaries,
                          num_regions: int, cap: int,
                          interpret: bool | None = None):
    """Pack ``|x| >= thresh`` into per-region fixed-capacity buffers in ONE
    pass over ``x`` (the Pallas fast path of ops.select.pack_by_region).

    ``boundaries``: i32 [num_regions + 1] cumulative offsets that MUST span
    exactly [0, n]: ``boundaries[0] == 0`` and ``boundaries[-1] == n``.
    The kernel is region-blind (it stages every survivor over [0, n); the
    post-processing assigns region ids from the interior boundaries only),
    so a survivor outside ``[boundaries[0], boundaries[-1])`` would be
    silently attributed to the first/last region rather than masked out.
    ``_repartition`` maintains the invariant by construction (the
    reference asserts the same: sum of region sizes == n,
    VGG/allreducer.py:648); callers with concrete boundaries get a cheap
    host-side check. Returns ``(values [R, cap], indices [R, cap],
    counts [R])`` with the same contract as the portable path. The
    ascending-index staging is already region-grouped (regions are
    contiguous index ranges); all region arithmetic happens in the
    cap-scale post-processing.
    """
    # The invariant check must run BEFORE jit: inside the trace every
    # array is a tracer (isinstance(np.ndarray) is False and np.asarray
    # raises), so a guard in the jitted body can never fire. Concrete
    # boundaries (numpy / committed jax arrays / int sequences) convert;
    # tracers (e.g. the jitted oktopk caller, whose _repartition keeps
    # the invariant by construction) raise and skip the check.
    try:
        b = np.asarray(boundaries)
        concrete = b.dtype != object
    except Exception:
        concrete = False
    if concrete and (b[0] != 0 or b[-1] != x.size):
        raise ValueError(
            f"boundaries must span exactly [0, n={x.size}]; got "
            f"[{b[0]}, {b[-1]}] (the kernel is region-blind — see "
            "docstring)")
    return _pack_by_region_pallas(x, thresh, boundaries, num_regions, cap,
                                  interpret)


@functools.partial(jax.jit,
                   static_argnames=("num_regions", "cap", "interpret"))
def _pack_by_region_pallas(x, thresh, boundaries, num_regions: int,
                           cap: int, interpret: bool | None = None):
    if interpret is None:
        interpret = _interpret_default()
    R = num_regions
    xp, xflat, t, rng, n, nblocks = _prep(x, thresh, None, None)
    vma = _vma_of(xp)
    bnd = jnp.asarray(boundaries, jnp.int32)
    if vma:
        t = _pvary_to(t, vma)
        rng = _pvary_to(rng, vma)

    w_f, stored_f, raw = _run_stage(xp, t, rng, CAPB_FAST, nblocks,
                                    interpret, vma)

    def _post(w_stage, stored, capb):
        # Region reconstruction requires every survivor staged, which the
        # caller guarantees (no overflow, or the capb=BLK kernel). Regions
        # are contiguous index ranges, so a block's region is determined by
        # its START index alone — except for the <= R-1 blocks that contain
        # an interior boundary, whose split is read off their (ascending-
        # offset) staging rows. Everything here is nb- or (R-1)*capb-scale;
        # the round-4 version ran searchsorted + a scatter-add over the
        # whole [nb, capb] grid, which on the capb=BLK wide path is
        # n-scale — measured 150+ ms of the VGG-16 step on the chip (the
        # very scatter cost this module exists to avoid).
        bi = jnp.arange(nblocks, dtype=jnp.int32)
        rblock = jnp.searchsorted(bnd[1:-1], bi * BLK,
                                  side="right").astype(jnp.int32)   # [nb]
        rgrid = jnp.arange(R, dtype=jnp.int32)
        cnt_rb = jnp.where(rblock[:, None] == rgrid[None, :],
                           stored[:, None], 0)            # [nb, R]
        if R > 1:
            # boundary-straddling blocks: exact per-region counts from the
            # staged offsets. Duplicate bm rows (several boundaries inside
            # one block) compute identical replacement rows, so the
            # .at[].set is deterministic.
            # clamp: a boundary equal to n with zero padding puts bm one
            # past the last block; the clamped block's replacement row is
            # recomputed from its own staging, so the overwrite stays exact
            bm = jnp.minimum((bnd[1:-1] // BLK).astype(jnp.int32),
                             nblocks - 1)                 # [R-1]
            wb = w_stage[bm].astype(jnp.int32)            # [R-1, capb]
            rid_b = jnp.searchsorted(bnd[1:-1], bm[:, None] * BLK + wb,
                                     side="right").astype(jnp.int32)
            valid_b = (jnp.arange(capb, dtype=jnp.int32)[None, :]
                       < stored[bm][:, None])             # [R-1, capb]
            rowg = jnp.broadcast_to(
                jnp.arange(R - 1, dtype=jnp.int32)[:, None], rid_b.shape)
            cnt_rows = jnp.zeros((R - 1, R), jnp.int32).at[
                rowg, rid_b].add(valid_b.astype(jnp.int32))
            cnt_rb = cnt_rb.at[bm].set(cnt_rows)
        off_rb = jnp.cumsum(cnt_rb, axis=1) - cnt_rb      # region start in row
        counts = jnp.minimum(jnp.sum(cnt_rb, axis=0), cap)  # [R]
        values, indices = _materialize(
            w_stage, xflat, cnt_rb, off_rb, capb, cap, counts, n)
        return values, indices, counts

    def wide(_):
        w_w, stored_w, _raw = _run_stage(xp, t, rng, BLK, nblocks,
                                         interpret, vma)
        return _post(w_w, stored_w, BLK)

    return jax.lax.cond(jnp.any(raw > CAPB_FAST), wide,
                        lambda _: _post(w_f, stored_f, CAPB_FAST), None)


def mesh_supports_pallas(mesh) -> bool:
    """True when every device of the mesh is a TPU (incl. the tunnelled
    "axon" platform) — the backends the compaction kernel targets."""
    try:
        plats = {d.platform for d in np.asarray(mesh.devices).flat}
    except Exception:
        return False
    return bool(plats) and plats.issubset({"tpu", "axon"})


def resolve_use_pallas(cfg, mesh):
    """Fill OkTopkConfig.use_pallas from the mesh backend when unset."""
    if cfg.use_pallas is not None:
        return cfg
    return cfg.replace(use_pallas=mesh_supports_pallas(mesh))
