"""Stream compaction: pack masked elements into fixed-capacity buffers.

This is the selection hot path of every sparse collective (SURVEY.md §7.3.5).
The portable implementation (ops/select.py ``select_mask``) builds a full-
length cumsum and a full-length scatter — on TPU the n-operand scatter
serialises (~69 ms for n=14.7M on v5e, measured) and dominated the train
step in rounds 1-2. TPU has no scatter unit, so the fast path splits the
work by what the hardware is good at:

1. A Pallas *staging* kernel does the n-scale work: per 1024-element block
   (one [8, 128] f32 tile), threshold-mask -> in-block exclusive prefix sum
   (Hillis-Steele shifted adds on the VPU) -> one [1,128] x [capb,128]^T
   MXU matmul per sublane row that drops each survivor's in-block offset
   (< 1024, exact in f32 at Precision.HIGHEST) into its packed slot. Each
   block writes its own staging row — standard blocked VMEM outputs, no
   cross-block sequencing, so the grid pipelines freely.
2. Plain-XLA post-processing does the cap-scale work with *gathers* (the
   measured costs on v5e: gather ~10 ns/elem/round, cap-operand scatter
   ~4.7 ns/elem, n-operand scatter ~4700 ns/1000 elem): the per-output-slot
   staging address and element base both *telescope* along the output axis
   (crossing a block's end advances them by fixed per-block jumps), so one
   small scatter-add of the jumps + a cap-scale cumsum replaces any
   searchsorted/base-gather, leaving exactly 2 cap-scale gather rounds
   (the staged offset, then the value) — see ``_materialize``.

Why not DMA-append inside the kernel (the round-3 first attempt): Mosaic
cannot slice a tiled VMEM scratch per row, and 1-D memrefs — HBM included —
carry a (1024) tiling whose dynamic-offset slices need a divisibility
proof that a running element count cannot give. Block-granular staging
sidesteps every such constraint: all kernel outputs are statically blocked.

Exactness: the staging width ``capb`` (128) caps how many survivors one
block can stage. The mean is ~20 survivors/block at the paper's densities,
but conv gradients are spatially correlated: on a real VGG-16 gradient at
d=0.02, 4.3% of blocks overflow (max 826/1024) — every step. The kernel
therefore also emits *raw* per-block survivor counts, and the wrapper
dispatches (``lax.switch``) on the overflow census:

  * no overflow that matters  -> fast rows alone (the common small-n case);
  * <= ``_novf_cap`` blocks   -> a *repair* kernel re-stages only the
    overflowing blocks at full 1024 width (their ids scalar-prefetched
    into the input index_map), ~nblocks/8 block-stagings instead of
    nblocks — measured 9 ms vs the 69 ms full-wide re-stage on v5e;
    ``_materialize_het`` then reads the mixed 128/1024-wide layout via
    one extra telescoping accumulator (the per-slot source block);
  * more                       -> the capb=1024 kernel over everything
    (can never drop anything), as before.

All paths reproduce the portable result bit-for-bit in interpret mode
(asserted in tests/test_compaction.py); tests/test_tpu_hw.py mirrors
them for real-chip Mosaic compilation, but the last recorded on-chip pass
(logs/tpu_hw_status.json) predates the repair branch — re-run
``OKTOPK_TPU_HW=1`` on a live relay to refresh the stamp before trusting
the repair kernel + _materialize_het on silicon.

The reference's analogous code is the boolean-mask nonzero select
(``compressbythreshold``, VGG/compression.py:122-142) — a cheap op on GPU,
the wrong shape for TPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from oktopk_tpu.comm import compat


def _interpret_default() -> bool:
    """OKTOPK_PALLAS_INTERPRET=1 runs the kernel in the Pallas interpreter
    (CPU-mesh tests of the full pallas-path algorithms)."""
    return os.environ.get("OKTOPK_PALLAS_INTERPRET", "0") == "1"


BLK_ROWS = 8          # f32 min tile is (8, 128)
BLK_COLS = 128
BLK = BLK_ROWS * BLK_COLS

# sub-blocks per grid step: staging rows come 8 at a time so every output
# block is a full (8, capb) tile — 2-D (1, capb) blocks fail the (8, 128)
# divisibility rule and 1-D (capb,) blocks fail XLA's T(1024) layout
SB = 8

CAPB_FAST = 128       # staging width of the fast kernel (one lane row)


def _shift_right(x, d, axis):
    """x shifted ``d`` slots toward higher indices along ``axis``, zero-fill.

    Concat + static slice only (``jnp.pad`` is not guaranteed a Mosaic
    lowering)."""
    zshape = list(x.shape)
    zshape[axis] = d
    sl = [slice(None), slice(None)]
    sl[axis] = slice(0, x.shape[axis] - d)
    return jnp.concatenate([jnp.zeros(zshape, x.dtype), x[tuple(sl)]],
                           axis=axis)


def _block_prefix(m):
    """Exclusive prefix sum of an [8, 128] i32 tile in row-major order,
    via Hillis-Steele shifted adds (no cumsum primitive needed in-kernel).

    Only static positive slices and full reductions — scalar extraction
    like ``r[-1, 0]`` traces to ``dynamic_slice``, which Mosaic's TC
    lowering rejects (caught on the real chip; the interpreter accepts it).
    The across-row scan runs full-width: a narrow ``[8, 1]`` slice of
    column 127 keeps lane offset 127 in its vreg, and ``tpu.concatenate``
    requires operands to agree on the non-concat (lane) offset — another
    hardware-only constraint the interpreter accepts.
    """
    s = m
    for d in (1, 2, 4, 8, 16, 32, 64):           # within-row inclusive scan
        s = s + _shift_right(s, d, axis=1)
    # per-row totals replicated across lanes (offset-0 layout)
    rt = jnp.broadcast_to(s[:, BLK_COLS - 1:BLK_COLS], (BLK_ROWS, BLK_COLS))
    r = rt
    for d in (1, 2, 4):                           # across-row inclusive scan
        r = r + _shift_right(r, d, axis=0)
    return s - m + (r - rt), jnp.sum(m)           # (excl. positions, total)


def _stage_tile(woff, sel, capb):
    """The MXU "scatter": stage[j] = in-block offset of the element whose
    packed slot is ``j``, as one [1, capb] f32 row.

    Mosaic rejects cross-lane reshapes — the obvious ``[8,128] -> [BLK,1]``
    one-hot layout is an "unsupported shape cast" on real hardware (the
    interpreter accepts it, which is why only a chip run catches it). So
    everything stays in tile layout: per sublane-row, broadcast the row's
    slot vector along a fresh sublane axis, compare with a sublane iota to
    get the transposed one-hot [capb, 128], and contract both operands on
    their lane axis (an NT matmul — dimension numbers ((1,),(1,))). Slots
    are distinct across rows so the accumulation is collision-free."""
    # i32 iota/compare: tpu.iota verifies only integer result types (a
    # float iota fails Mosaic verification on the real chip; the
    # interpreter accepts it)
    jio = jax.lax.broadcasted_iota(jnp.int32, (capb, BLK_COLS), 0)
    acc = jnp.zeros((1, capb), jnp.float32)
    for r in range(BLK_ROWS):
        selr = jax.lax.slice(sel, (r, 0), (r + 1, BLK_COLS))   # [1, 128]
        onehot_t = (jnp.broadcast_to(selr, (capb, BLK_COLS)) == jio) \
            .astype(jnp.float32)                               # [capb, 128]
        wr = jax.lax.slice(woff, (r, 0),
                           (r + 1, BLK_COLS)).astype(jnp.float32)
        # HIGHEST precision: the default matmul path feeds the MXU bf16
        # inputs (8 mantissa bits), silently rounding offsets > 256;
        # HIGHEST decomposes f32 exactly, keeping one-hot x offset exact.
        acc = acc + jax.lax.dot_general(
            wr, onehot_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
    return acc


def _stage_kernel(capb, t_ref, r_ref, x_ref, w_ref, cr_ref):
    """Stage SB consecutive blocks: w_ref[s, j] = in-block offset of the
    j-th survivor of sub-block s, cr_ref = raw survivor counts (broadcast
    over 128 lanes; the stored count is min(raw, capb) by construction —
    survivor ranks are dense — so it is derived in the wrapper, not
    written)."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    xs = x_ref[:]                                         # [SB*8, 128] f32
    woff = (jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 0)
            * BLK_COLS
            + jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 1))
    rows_w, rows_r = [], []
    for sb in range(SB):
        x = jax.lax.slice(xs, (sb * BLK_ROWS, 0),
                          ((sb + 1) * BLK_ROWS, BLK_COLS))
        gidx = (i * SB + sb) * BLK + woff
        # [lo, hi) element-range restriction (region-restricted select);
        # full range by default
        mask = ((jnp.abs(x) >= t_ref[0])
                & (gidx >= r_ref[0]) & (gidx < r_ref[1]))
        m = mask.astype(jnp.int32)
        pos, raw = _block_prefix(m)

        kept = mask & (pos < capb)
        sel = jnp.where(kept, pos, capb)                  # capb = dropped

        rows_w.append(_stage_tile(jnp.where(kept, woff, 0), sel, capb))
        rows_r.append(jnp.full((1, BLK_COLS), raw, jnp.int32))
    w_ref[:] = jnp.concatenate(rows_w, axis=0)
    cr_ref[:] = jnp.concatenate(rows_r, axis=0)


def _run_stage(xp, t, rng, capb, nblocks, interpret, vma):
    """pallas_call wrapper: (w_stage [nb, capb] f32, stored [nb], raw [nb])."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    out_shapes = [
        compat.shape_dtype_struct((nblocks, capb), jnp.float32, vma=vma),
        compat.shape_dtype_struct((nblocks, BLK_COLS), jnp.int32, vma=vma),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nblocks // SB,),
        in_specs=[pl.BlockSpec((SB * BLK_ROWS, BLK_COLS),
                               lambda i, t, r: (i, 0))],
        out_specs=[
            pl.BlockSpec((SB, capb), lambda i, t, r: (i, 0)),
            pl.BlockSpec((SB, BLK_COLS), lambda i, t, r: (i, 0)),
        ],
    )
    w, cr = pl.pallas_call(
        functools.partial(_stage_kernel, capb),
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=interpret,
    )(t, rng, xp)
    raw = cr[:, 0]
    return w, jnp.minimum(raw, capb), raw


def _novf_cap(nblocks: int) -> int:
    """Static capacity of the repair list: an eighth of the blocks (3x the
    measured 4.3% overflow rate on real VGG-16 gradients at d=0.02)."""
    return max((nblocks + 7) // 8, 8)


def _repair_kernel(t_ref, r_ref, bl_ref, x_ref, w_ref):
    """Re-stage ONE overflowing block (id scalar-prefetched via ``bl_ref``)
    at full 1024 width, written as eight 128-wide *pages* (page p holds
    packed slots [128p, 128(p+1))) — a [1, 1024] staging row would need a
    cross-lane reshape Mosaic rejects; pages keep every store a [1, 128]
    lane row. Row-major [8, 128] flatten == the 1024-wide row layout."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    b = bl_ref[i]
    x = x_ref[:]                                          # [8, 128]
    woff = (jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 0)
            * BLK_COLS
            + jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 1))
    gidx = b * BLK + woff
    mask = ((jnp.abs(x) >= t_ref[0])
            & (gidx >= r_ref[0]) & (gidx < r_ref[1]))
    pos, _raw = _block_prefix(mask.astype(jnp.int32))
    for p in range(BLK_ROWS):
        kept_p = mask & (pos >= p * BLK_COLS) & (pos < (p + 1) * BLK_COLS)
        sel_p = jnp.where(kept_p, pos - p * BLK_COLS, BLK_COLS)
        w_ref[p:p + 1, :] = _stage_tile(jnp.where(kept_p, woff, 0), sel_p,
                                        BLK_COLS)


def _run_repair(xp, t, rng, bl, novf_cap, interpret, vma):
    """pallas_call wrapper: w_rep [novf_cap * 8, 128] f32 staging pages for
    the blocks listed in ``bl`` (padded entries re-stage block 0; their
    rows are never addressed — see ``_materialize_het``)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(novf_cap,),
        in_specs=[pl.BlockSpec((BLK_ROWS, BLK_COLS),
                               lambda i, t, r, bl: (bl[i], 0))],
        out_specs=[pl.BlockSpec((BLK_ROWS, BLK_COLS),
                                lambda i, t, r, bl: (i, 0))],
    )
    (w,) = pl.pallas_call(
        _repair_kernel,
        grid_spec=grid_spec,
        out_shape=[compat.shape_dtype_struct((novf_cap * BLK_ROWS, BLK_COLS),
                                             jnp.float32, vma=vma)],
        interpret=interpret,
    )(t, rng, bl, xp)
    return w


def _materialize(w_stage, xflat, cnt_rb, off_rb, capb, cap, counts, n):
    """Materialise ``(values [R, cap], indices [R, cap])`` from a packed
    staging ``w_stage [nb, capb]`` whose block b holds (ascending-index)
    the survivors counted by ``cnt_rb [nb, R]`` per region, region r's run
    starting at in-row offset ``off_rb[b, r]`` (None = zeros, the R=1
    whole-vector select).

    Region r's output slot j reads staging slot
        b*capb + off_rb[b, r] + (j - C_excl[b, r])
    of block b = searchsorted(C[:, r], j), and its element index is
    b*BLK + staged offset. Both per-slot bases *telescope* along j:
    crossing block b (at output position C[b, r]) advances the staging
    base by capb + off_rb[b+1, r] - off_rb[b, r] - cnt_rb[b, r] and the
    element base by BLK, starting from off_rb[0, r] and 0. One small
    scatter-add of those jumps + a per-row cap-scale cumsum therefore
    replaces any searchsorted and per-slot base gather (the element base
    needs no accumulator of its own: a live slot's in-row offset is < capb,
    so its block is ``flat // capb``); only two cap-scale gather rounds
    remain (the staged offset, then the value).
    """
    nblocks, R = cnt_rb.shape
    if off_rb is None:
        off_rb = jnp.zeros_like(cnt_rb)
    c_rb = jnp.cumsum(cnt_rb, axis=0)                 # [nb, R] inclusive
    off_next = jnp.concatenate([off_rb[1:], off_rb[-1:]], axis=0)
    fval = capb + off_next - off_rb - cnt_rb          # [nb, R]
    pos = jnp.minimum(c_rb, cap)
    rgrid = jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32)[None, :],
                             (nblocks, R))
    fjump = jnp.zeros((R, cap + 1), jnp.int32).at[rgrid.T, pos.T].add(fval.T)
    j = jnp.arange(cap, dtype=jnp.int32)[None, :]
    flat = off_rb[0][:, None] + jnp.cumsum(fjump, axis=1)[:, :cap] + j
    # live slots always sit inside their block's staging row (in-row offset
    # < capb), so the source block is just flat // capb — a shift, no
    # second jump accumulator needed
    flat = jnp.clip(flat, 0, nblocks * capb - 1)
    w = w_stage.reshape(-1)[flat].astype(jnp.int32)   # gather round 1
    idx = (flat // capb) * BLK + w
    live = j < counts[:, None]
    values = jnp.where(live, xflat[jnp.minimum(idx, xflat.size - 1)],
                       0.0)                           # gather round 2
    indices = jnp.where(live, idx, n).astype(jnp.int32)
    return values, indices


def _materialize_het(w_fast, w_rep, ovf, xflat, cnt_rb, off_rb, capf, cap,
                     counts, n):
    """``_materialize`` over the mixed staging layout of the repair path:
    block b's row is ``w_rep`` page-row ``rank(b)`` (1024 wide) when
    ``ovf[b]``, else ``w_fast[b]`` (``capf`` wide).

    Same telescoping-jump construction, with per-block widths ``capb_b``
    in the jump values and ONE extra accumulator carrying the per-slot
    source block id b (jump +1 at every block crossing) — b can no longer
    be recovered as ``flat // capb`` — plus one nb-operand gather of
    ``delta[b] = phys_base[b] - vbase[b]`` translating virtual addresses
    into the concatenated [w_fast | w_rep] physical array."""
    nblocks, R = cnt_rb.shape
    if off_rb is None:
        off_rb = jnp.zeros_like(cnt_rb)
    capb_b = jnp.where(ovf, BLK, capf)                    # [nb]
    vbase = jnp.cumsum(capb_b) - capb_b                   # virtual row base
    rank = jnp.cumsum(ovf.astype(jnp.int32)) - ovf        # repair row of b
    fast_sz = nblocks * capf
    phys_base = jnp.where(ovf, fast_sz + rank * BLK,
                          jnp.arange(nblocks, dtype=jnp.int32) * capf)
    delta = phys_base - vbase                             # [nb]

    c_rb = jnp.cumsum(cnt_rb, axis=0)                     # [nb, R] inclusive
    off_next = jnp.concatenate([off_rb[1:], off_rb[-1:]], axis=0)
    fval = capb_b[:, None] + off_next - off_rb - cnt_rb   # [nb, R]
    pos = jnp.minimum(c_rb, cap)
    rgrid = jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32)[None, :],
                             (nblocks, R))
    fjump = jnp.zeros((R, cap + 1), jnp.int32).at[rgrid.T, pos.T].add(fval.T)
    bjump = jnp.zeros((R, cap + 1), jnp.int32).at[rgrid.T, pos.T].add(
        jnp.ones_like(fval.T))
    j = jnp.arange(cap, dtype=jnp.int32)[None, :]
    flat = off_rb[0][:, None] + jnp.cumsum(fjump, axis=1)[:, :cap] + j
    b = jnp.minimum(jnp.cumsum(bjump, axis=1)[:, :cap], nblocks - 1)
    stage_all = jnp.concatenate([w_fast.reshape(-1), w_rep.reshape(-1)])
    phys = jnp.clip(flat + delta[b], 0, stage_all.size - 1)
    w = stage_all[phys].astype(jnp.int32)                 # gather round 1
    idx = b * BLK + w
    live = j < counts[:, None]
    values = jnp.where(live, xflat[jnp.minimum(idx, xflat.size - 1)],
                       0.0)                               # gather round 2
    indices = jnp.where(live, idx, n).astype(jnp.int32)
    return values, indices


def _region_counts(stage_flat, phys_base, stored_v, capb_max, bnd, R,
                   nblocks):
    """Per-(block, region) staged-survivor counts [nb, R] for contiguous
    index-range regions, at nb scale: a block's region follows from its
    start index; only the <= R-1 boundary-straddling blocks read their
    staging rows (fetched from ``stage_flat`` at ``phys_base`` — uniform
    and heterogeneous layouts both reduce to a base array)."""
    rgrid = jnp.arange(R, dtype=jnp.int32)
    bi = jnp.arange(nblocks, dtype=jnp.int32)
    rblock = jnp.searchsorted(bnd[1:-1], bi * BLK,
                              side="right").astype(jnp.int32)
    cnt_rb = jnp.where(rblock[:, None] == rgrid[None, :],
                       stored_v[:, None], 0)
    if R > 1:
        # clamp: a boundary equal to n with zero padding puts bm one past
        # the last block; the clamped block's replacement row is recomputed
        # from its own staging, so the overwrite stays exact
        bm = jnp.minimum((bnd[1:-1] // BLK).astype(jnp.int32), nblocks - 1)
        rowidx = phys_base[bm][:, None] + jnp.arange(capb_max,
                                                     dtype=jnp.int32)[None, :]
        wb = stage_flat[jnp.clip(rowidx, 0, stage_flat.size - 1)] \
            .astype(jnp.int32)                            # [R-1, capb_max]
        rid_b = jnp.searchsorted(bnd[1:-1], bm[:, None] * BLK + wb,
                                 side="right").astype(jnp.int32)
        valid_b = (jnp.arange(capb_max, dtype=jnp.int32)[None, :]
                   < stored_v[bm][:, None])
        rowg = jnp.broadcast_to(
            jnp.arange(R - 1, dtype=jnp.int32)[:, None], rid_b.shape)
        cnt_rows = jnp.zeros((R - 1, R), jnp.int32).at[
            rowg, rid_b].add(valid_b.astype(jnp.int32))
        cnt_rb = cnt_rb.at[bm].set(cnt_rows)
    return cnt_rb


def _prep(x, thresh, lo, hi):
    """Shared padding/threshold/range prep. Returns (xp2d, xflat, t, rng,
    n, nblocks)."""
    n = x.size
    pad = (-n) % (SB * BLK)
    xflat = jnp.pad(x.reshape(-1), (0, pad))
    xp = xflat.reshape(-1, BLK_COLS)
    nblocks = xp.shape[0] // BLK_ROWS
    # clamp to the smallest normal f32: a zero/negative threshold selects
    # every nonzero element rather than the padded tail (subnormals flush
    # to zero on TPU anyway)
    t = jnp.reshape(jnp.maximum(jnp.asarray(thresh, x.dtype),
                                jnp.float32(1.17549435e-38)), (1,))
    rng = jnp.stack([
        jnp.asarray(0 if lo is None else lo, jnp.int32),
        jnp.asarray(n if hi is None else hi, jnp.int32)])
    return xp, xflat, t, rng, n, nblocks


def _vma_of(xp):
    # under shard_map's VMA tracking the outputs vary over the same mesh
    # axes as the input shard, and every operand must agree
    return compat.typeof_vma(xp)


def _pvary_to(arr, vma):
    missing = tuple(vma - compat.typeof_vma(arr))
    return compat.pvary(arr, missing)


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def select_by_threshold_pallas(x: jnp.ndarray, thresh, cap: int,
                               lo=None, hi=None,
                               interpret: bool | None = None):
    """Fixed-capacity threshold select, Pallas TPU fast path.

    Same contract as ops.select.select_by_threshold: returns
    ``(values[cap], indices[cap], count)`` with slots >= count holding
    value 0 / index n, elements packed in ascending index order, overflow
    beyond ``cap`` dropped with lowest-index-first retention (identical to
    the portable path). ``lo``/``hi`` restrict selection to the element
    range [lo, hi).
    """
    if interpret is None:
        interpret = _interpret_default()
    xp, xflat, t, rng, n, nblocks = _prep(x, thresh, lo, hi)
    vma = _vma_of(xp)
    if vma:
        t = _pvary_to(t, vma)
        rng = _pvary_to(rng, vma)

    capb_f = CAPB_FAST
    w_f, stored_f, raw = _run_stage(xp, t, rng, capb_f, nblocks, interpret,
                                    vma)
    count = jnp.minimum(jnp.sum(raw), cap)

    def _post(w_stage, stored, capb):
        values, indices = _materialize(
            w_stage, xflat, stored[:, None], None, capb, cap,
            count[None], n)
        return values[0], indices[0]

    if cap > capb_f:
        # A block's drops have in-block position >= capb, hence global
        # survivor rank >= excl_cumsum(raw)[b] + capb. When every drop
        # ranks >= cap, no output slot can see one (a survivor with true
        # rank < cap has no drop before it either, so the stored ordering
        # of the first cap slots is exact) — such blocks need no re-stage.
        excl = jnp.cumsum(raw) - raw
        matters = (raw > capb_f) & (excl + capb_f < cap)
        novf = jnp.sum(matters)
        ncap = _novf_cap(nblocks)
        bl = jnp.nonzero(matters, size=ncap,
                         fill_value=0)[0].astype(jnp.int32)

        def fast(_):
            return _post(w_f, stored_f, capb_f)

        def repair(_):
            blv = _pvary_to(bl, vma) if vma else bl
            w_rep = _run_repair(xp, t, rng, blv, ncap, interpret, vma)
            stored_v = jnp.where(matters, raw, stored_f)
            values, indices = _materialize_het(
                w_f, w_rep, matters, xflat, stored_v[:, None], None,
                capb_f, cap, count[None], n)
            return values[0], indices[0]

        def wide(_):
            w_w, stored_w, _raw = _run_stage(xp, t, rng, BLK, nblocks,
                                             interpret, vma)
            return _post(w_w, stored_w, BLK)

        sel = ((novf > 0).astype(jnp.int32)
               + (novf > ncap).astype(jnp.int32))
        values, indices = jax.lax.switch(sel, [fast, repair, wide], None)
    else:
        # drops beyond capb have in-block position >= capb >= cap, hence
        # global position >= cap: they can never make the first-cap prefix
        values, indices = _post(w_f, stored_f, capb_f)
    return values, indices, count


def pack_by_region_pallas(x: jnp.ndarray, thresh, boundaries,
                          num_regions: int, cap: int,
                          interpret: bool | None = None):
    """Pack ``|x| >= thresh`` into per-region fixed-capacity buffers in ONE
    pass over ``x`` (the Pallas fast path of ops.select.pack_by_region).

    ``boundaries``: i32 [num_regions + 1] cumulative offsets that MUST span
    exactly [0, n]: ``boundaries[0] == 0`` and ``boundaries[-1] == n``.
    The kernel is region-blind (it stages every survivor over [0, n); the
    post-processing assigns region ids from the interior boundaries only),
    so a survivor outside ``[boundaries[0], boundaries[-1])`` would be
    silently attributed to the first/last region rather than masked out.
    ``_repartition`` maintains the invariant by construction (the
    reference asserts the same: sum of region sizes == n,
    VGG/allreducer.py:648); callers with concrete boundaries get a cheap
    host-side check. Returns ``(values [R, cap], indices [R, cap],
    counts [R])`` with the same contract as the portable path. The
    ascending-index staging is already region-grouped (regions are
    contiguous index ranges); all region arithmetic happens in the
    cap-scale post-processing.
    """
    # The invariant check must run BEFORE jit: inside the trace every
    # array is a tracer (isinstance(np.ndarray) is False and np.asarray
    # raises), so a guard in the jitted body can never fire. Concrete
    # boundaries (numpy / committed jax arrays / int sequences) convert;
    # tracers (e.g. the jitted oktopk caller, whose _repartition keeps
    # the invariant by construction) raise and skip the check.
    try:
        b = np.asarray(boundaries)
        concrete = b.dtype != object
    except Exception:
        concrete = False
    if concrete and (b[0] != 0 or b[-1] != x.size):
        raise ValueError(
            f"boundaries must span exactly [0, n={x.size}]; got "
            f"[{b[0]}, {b[-1]}] (the kernel is region-blind — see "
            "docstring)")
    return _pack_by_region_pallas(x, thresh, boundaries, num_regions, cap,
                                  interpret)


@functools.partial(jax.jit,
                   static_argnames=("num_regions", "cap", "interpret"))
def _pack_by_region_pallas(x, thresh, boundaries, num_regions: int,
                           cap: int, interpret: bool | None = None):
    if interpret is None:
        interpret = _interpret_default()
    R = num_regions
    xp, xflat, t, rng, n, nblocks = _prep(x, thresh, None, None)
    vma = _vma_of(xp)
    bnd = jnp.asarray(boundaries, jnp.int32)
    if vma:
        t = _pvary_to(t, vma)
        rng = _pvary_to(rng, vma)

    w_f, stored_f, raw = _run_stage(xp, t, rng, CAPB_FAST, nblocks,
                                    interpret, vma)
    return _pack_finalize(xp, xflat, t, rng, bnd, R, cap, nblocks, n,
                          interpret, vma, w_f, stored_f, raw)


def _pack_finalize(xp, xflat, t, rng, bnd, R, cap, nblocks, n, interpret,
                   vma, w_f, stored_f, raw):
    """Cap-scale region post-processing shared by ``pack_by_region_pallas``
    and the fused selection front-end (ops/fused_select.py): overflow
    census -> fast/repair/wide dispatch over already-staged fast rows."""
    # Region reconstruction requires every survivor staged (fast rows when
    # nothing overflowed, repaired rows for the <= ncap overflow blocks,
    # or the capb=BLK kernel otherwise). _region_counts is nb-scale — the
    # round-4 version ran searchsorted + a scatter-add over the whole
    # [nb, capb] grid, which on the capb=BLK wide path is n-scale:
    # measured 150+ ms of the VGG-16 step on the chip (the very scatter
    # cost this module exists to avoid).
    def _finish(cnt_rb, mat):
        off_rb = jnp.cumsum(cnt_rb, axis=1) - cnt_rb    # region start in row
        counts = jnp.minimum(jnp.sum(cnt_rb, axis=0), cap)  # [R]
        values, indices = mat(cnt_rb, off_rb, counts)
        return values, indices, counts

    bi = jnp.arange(nblocks, dtype=jnp.int32)
    ovf = raw > CAPB_FAST
    novf = jnp.sum(ovf)
    ncap = _novf_cap(nblocks)
    bl = jnp.nonzero(ovf, size=ncap, fill_value=0)[0].astype(jnp.int32)

    def fast(_):
        cnt_rb = _region_counts(w_f.reshape(-1), bi * CAPB_FAST, stored_f,
                                CAPB_FAST, bnd, R, nblocks)
        return _finish(cnt_rb, lambda c, o, ct: _materialize(
            w_f, xflat, c, o, CAPB_FAST, cap, ct, n))

    def repair(_):
        blv = _pvary_to(bl, vma) if vma else bl
        w_rep = _run_repair(xp, t, rng, blv, ncap, interpret, vma)
        stored_v = jnp.where(ovf, raw, stored_f)
        rank = jnp.cumsum(ovf.astype(jnp.int32)) - ovf
        phys_base = jnp.where(ovf, nblocks * CAPB_FAST + rank * BLK,
                              bi * CAPB_FAST)
        stage_all = jnp.concatenate([w_f.reshape(-1), w_rep.reshape(-1)])
        cnt_rb = _region_counts(stage_all, phys_base, stored_v, BLK, bnd,
                                R, nblocks)
        return _finish(cnt_rb, lambda c, o, ct: _materialize_het(
            w_f, w_rep, ovf, xflat, c, o, CAPB_FAST, cap, ct, n))

    def wide(_):
        w_w, stored_w, _raw = _run_stage(xp, t, rng, BLK, nblocks,
                                         interpret, vma)
        cnt_rb = _region_counts(w_w.reshape(-1), bi * BLK, stored_w, BLK,
                                bnd, R, nblocks)
        return _finish(cnt_rb, lambda c, o, ct: _materialize(
            w_w, xflat, c, o, BLK, cap, ct, n))

    sel = (novf > 0).astype(jnp.int32) + (novf > ncap).astype(jnp.int32)
    return jax.lax.switch(sel, [fast, repair, wide], None)


def mesh_supports_pallas(mesh) -> bool:
    """True when every device of the mesh is a TPU (incl. the tunnelled
    "axon" platform) — the backends the compaction kernel targets."""
    try:
        plats = {d.platform for d in np.asarray(mesh.devices).flat}
    except Exception:
        return False
    return bool(plats) and plats.issubset({"tpu", "axon"})


def resolve_use_pallas(cfg, mesh):
    """Fill OkTopkConfig.use_pallas from the mesh backend when unset."""
    if cfg.use_pallas is not None:
        return cfg
    return cfg.replace(use_pallas=mesh_supports_pallas(mesh))
