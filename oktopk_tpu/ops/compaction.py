"""Stream compaction: pack masked elements into fixed-capacity buffers.

This is the selection hot path of every sparse collective (SURVEY.md §7.3.5).
The portable implementation (ops/select.py ``select_mask``) builds a full-
length cumsum and a full-length scatter — on TPU that scatter serialises and
dominates the train step. TPU has no scatter unit, so the fast path is a
Pallas kernel that does what the hardware is good at:

  per 1024-element block (one [8, 128] f32 tile):
    mask -> in-block exclusive prefix sum (7+3 shifted adds on the VPU)
    -> per sublane-row transposed one-hot [capb, 128] (VPU compares; built
       by sublane-broadcast + iota, never reshaping across lanes — Mosaic
       rejects cross-lane shape casts like [8,128]->[1024,1])
    -> eight [4, 128] x [capb, 128]^T MXU matmuls  (the "scatter")
    -> sliced DMA append to the output at the running base offset.

The matmuls compact four row vectors at once: the value and the global index,
each split into two 16-bit halves (every half is < 2^16, exact in f32; the
dots run at Precision.HIGHEST because the default matmul path rounds MXU
inputs to bf16's 8 mantissa bits; recombined by bit ops after the
kernel). The running base lives in SMEM scratch and the grid is declared
sequential ("arbitrary" dimension semantics), so each block's DMA lands after
the previous block's — a block writes its full ``capb`` staging row and the
next block's write overwrites the garbage tail, which is why the output
carries ``capb`` slack slots that the caller masks off with the returned
count.

``capb`` — the per-block staging width — is ``min(BLK, cap)`` rounded up to
a lane multiple, which makes the kernel's retention *identical* to the
portable path's lowest-index-first-within-``cap``: a block can never need to
contribute more than min(its survivors, remaining cap) <= capb slots to the
global first-``cap`` prefix. The one-hot compare cost scales with ``capb``,
so callers with small caps (the in-band sparse regime, a few percent of a
block) pay for a narrow 128-wide matmul while rare large-cap calls (the
periodic exact recompute) widen it.

The reference's analogous code is the boolean-mask nonzero select
(``compressbythreshold``, VGG/compression.py:122-142) — a cheap op on GPU,
the wrong shape for TPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np


def _interpret_default() -> bool:
    """OKTOPK_PALLAS_INTERPRET=1 runs the kernel in the Pallas interpreter
    (CPU-mesh tests of the full pallas-path algorithms)."""
    return os.environ.get("OKTOPK_PALLAS_INTERPRET", "0") == "1"


BLK_ROWS = 8          # f32 min tile is (8, 128)
BLK_COLS = 128
BLK = BLK_ROWS * BLK_COLS


def _capb_for(cap: int) -> int:
    """Per-block staging width: enough for any block's contribution to the
    global first-``cap`` prefix, lane-aligned."""
    need = min(BLK, cap)
    return max(BLK_COLS, -(-need // BLK_COLS) * BLK_COLS)


def _shift_right(x, d, axis):
    """x shifted ``d`` slots toward higher indices along ``axis``, zero-fill.

    Concat + static slice only (``jnp.pad`` is not guaranteed a Mosaic
    lowering)."""
    zshape = list(x.shape)
    zshape[axis] = d
    sl = [slice(None), slice(None)]
    sl[axis] = slice(0, x.shape[axis] - d)
    return jnp.concatenate([jnp.zeros(zshape, x.dtype), x[tuple(sl)]],
                           axis=axis)


def _block_prefix(m):
    """Exclusive prefix sum of an [8, 128] i32 tile in row-major order,
    via Hillis-Steele shifted adds (no cumsum primitive needed in-kernel).

    Only static positive slices and full reductions — scalar extraction
    like ``r[-1, 0]`` traces to ``dynamic_slice``, which Mosaic's TC
    lowering rejects (caught on the real chip; the interpreter accepts it).
    """
    s = m
    for d in (1, 2, 4, 8, 16, 32, 64):           # within-row inclusive scan
        s = s + _shift_right(s, d, axis=1)
    row_tot = s[:, BLK_COLS - 1:BLK_COLS]         # [8, 1]
    r = row_tot
    for d in (1, 2, 4):                           # across-row inclusive scan
        r = r + _shift_right(r, d, axis=0)
    row_excl = r - row_tot                        # exclusive row offsets
    return s - m + row_excl, jnp.sum(m)           # (excl. positions, total)


def _quantity_rows(x, gidx, kept):
    """The four compacted quantities — value hi/lo half and global-index
    hi/lo half — as separate [8, 128] i32 tiles, zeroed outside ``kept``.
    16-bit pieces are exactly representable in f32 (|q| < 2^16 < 2^24),
    but only survive the MXU when the dot runs at Precision.HIGHEST — see
    ``_compact_tile``."""
    from jax.experimental.pallas import tpu as pltpu

    vbits = pltpu.bitcast(x, jnp.int32)
    zero = jnp.zeros_like(vbits)
    return (jnp.where(kept, vbits >> 16, zero),           # arithmetic shift
            jnp.where(kept, vbits & 0xFFFF, zero),
            jnp.where(kept, gidx >> 16, zero),
            jnp.where(kept, gidx & 0xFFFF, zero))


def _compact_tile(qs, sel, capb):
    """The MXU "scatter": stage[s, j] = s-th quantity of the element whose
    in-block slot is ``j``.

    Mosaic rejects cross-lane reshapes — the obvious ``[8,128] -> [BLK,1]``
    one-hot layout is an "unsupported shape cast" on real hardware (the
    interpreter accepts it, which is why only a chip run catches it). So
    everything stays in tile layout: per sublane-row, broadcast the row's
    slot vector along a fresh sublane axis, compare with a sublane iota to
    get the transposed one-hot [capb, 128], and contract both operands on
    their lane axis (an NT matmul — dimension numbers ((1,),(1,))). Eight
    [4,128] x [capb,128]^T matmuls replace the single [4,BLK] x [BLK,capb]
    one; slots are distinct across rows so the accumulation is collision-
    free and exact."""
    # i32 iota/compare: tpu.iota verifies only integer result types (a
    # float iota fails Mosaic verification on the real chip; the
    # interpreter accepts it)
    jio = jax.lax.broadcasted_iota(jnp.int32, (capb, BLK_COLS), 0)
    acc = jnp.zeros((4, capb), jnp.float32)
    for r in range(BLK_ROWS):
        selr = jax.lax.slice(sel, (r, 0), (r + 1, BLK_COLS))   # [1, 128]
        onehot_t = (jnp.broadcast_to(selr, (capb, BLK_COLS)) == jio) \
            .astype(jnp.float32)                               # [capb, 128]
        rows4 = jnp.concatenate(
            [jax.lax.slice(q, (r, 0), (r + 1, BLK_COLS)).astype(jnp.float32)
             for q in qs], axis=0)                             # [4, 128]
        # HIGHEST precision: the default matmul path feeds the MXU bf16
        # inputs (8 mantissa bits), silently rounding the 16-bit halves;
        # HIGHEST decomposes f32 exactly, keeping one-hot x half exact.
        acc = acc + jax.lax.dot_general(
            rows4, onehot_t, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
    return acc


def _compact_kernel(capb, t_ref, r_ref, x_ref, vh_ref, vl_ref, ih_ref,
                    il_ref, cnt_ref, base_ref, stage_ref, sem_ref):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    @pl.when(i == 0)
    def _():
        base_ref[0] = 0

    x = x_ref[:]                                          # [8, 128] f32
    gidx = (i * BLK
            + jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 0)
            * BLK_COLS
            + jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 1))
    # [lo, hi) element-range restriction (region packing); full range for a
    # whole-vector select
    mask = ((jnp.abs(x) >= t_ref[0])
            & (gidx >= r_ref[0]) & (gidx < r_ref[1]))
    m = mask.astype(jnp.int32)
    pos, _ = _block_prefix(m)

    kept = mask & (pos < capb)
    sel = jnp.where(kept, pos, capb)                      # capb = dropped
    stored = jnp.sum(kept.astype(jnp.int32))

    stage_ref[:] = _compact_tile(_quantity_rows(x, gidx, kept), sel, capb)

    base = base_ref[0]
    cap = vh_ref.shape[0] - capb                          # slack appended
    base_w = jnp.minimum(base, cap)
    for j, out in enumerate((vh_ref, vl_ref, ih_ref, il_ref)):
        copy = pltpu.make_async_copy(
            stage_ref.at[j], out.at[pl.ds(base_w, capb)], sem_ref)
        copy.start()
        copy.wait()

    base_ref[0] = base_w + stored

    @pl.when(i == nblocks - 1)
    def _():
        cnt_ref[0, 0] = jnp.minimum(base_ref[0], cap)     # stored (<= cap)


@functools.partial(jax.jit, static_argnames=("cap", "interpret"))
def select_by_threshold_pallas(x: jnp.ndarray, thresh, cap: int,
                               lo=None, hi=None,
                               interpret: bool | None = None):
    """Fixed-capacity threshold select, Pallas TPU fast path.

    Same contract as ops.select.select_by_threshold: returns
    ``(values[cap], indices[cap], count)`` with slots >= count holding
    value 0 / index n, elements packed in ascending index order, overflow
    beyond ``cap`` dropped with lowest-index-first retention (identical to
    the portable path — see the module docstring on ``capb``). ``lo``/``hi``
    restrict selection to the element range [lo, hi) — the per-region form
    used by region packing.

    The threshold is clamped to the smallest normal f32, so a zero/negative
    threshold selects every nonzero element rather than the padded tail
    (subnormals flush to zero on TPU anyway).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    n = x.size
    capb = _capb_for(cap)
    pad = (-n) % BLK
    xp = jnp.pad(x.reshape(-1), (0, pad)).reshape(-1, BLK_COLS)
    nblocks = xp.shape[0] // BLK_ROWS
    t = jnp.reshape(jnp.maximum(jnp.asarray(thresh, x.dtype),
                                jnp.float32(1.17549435e-38)), (1,))
    rng = jnp.stack([
        jnp.asarray(0 if lo is None else lo, jnp.int32),
        jnp.asarray(n if hi is None else hi, jnp.int32)])

    # under shard_map's VMA tracking the outputs vary over the same mesh
    # axes as the input shard, and every operand must agree
    try:
        vma = jax.typeof(xp).vma
    except Exception:
        vma = frozenset()
    if vma:
        t = jax.lax.pvary(t, tuple(vma - jax.typeof(t).vma))
        rng = jax.lax.pvary(rng, tuple(vma - jax.typeof(rng).vma))
    out_shapes = [jax.ShapeDtypeStruct((cap + capb,), jnp.float32, vma=vma)
                  for _ in range(4)]
    out_shapes.append(jax.ShapeDtypeStruct((1, 1), jnp.int32, vma=vma))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((BLK_ROWS, BLK_COLS),
                               lambda i, t, r: (i, 0))],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4
        + [pl.BlockSpec(memory_space=pltpu.SMEM)],
        scratch_shapes=[
            pltpu.SMEM((1,), jnp.int32),
            pltpu.VMEM((4, capb), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    vh, vl, ih, il, cnts = pl.pallas_call(
        functools.partial(_compact_kernel, capb),
        grid_spec=grid_spec,
        out_shape=out_shapes,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(t, rng, xp)

    count = cnts[0, 0]
    live = jnp.arange(cap) < count
    vbits = ((vh[:cap].astype(jnp.int32) << 16)
             | (vl[:cap].astype(jnp.int32) & 0xFFFF))
    values = jnp.where(live, jax.lax.bitcast_convert_type(vbits, jnp.float32),
                       0.0)
    indices = jnp.where(
        live,
        (ih[:cap].astype(jnp.int32) << 16)
        | (il[:cap].astype(jnp.int32) & 0xFFFF),
        n).astype(jnp.int32)
    return values, indices, count


def _pack_regions_kernel(num_regions, capb, t_ref, b_ref, x_ref,
                         vh_ref, vl_ref, ih_ref, il_ref, cnt_ref,
                         base_ref, stage_ref, sem_ref):
    """One sweep over x, packing each region's survivors into its own
    fixed-capacity buffer (outputs are [num_regions, cap + capb]).

    Per block, only the regions that intersect the block run their
    compaction (predicated with @pl.when) — load-balanced regions are
    contiguous spans much wider than one block, so typically 1-2 of the
    ``num_regions`` iterations do work. This is what makes the whole
    phase-(a) pack O(n) HBM reads instead of the per-region-call form's
    O(P*n)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    nblocks = pl.num_programs(0)

    @pl.when(i == 0)
    def _():
        for r in range(num_regions):
            base_ref[r] = 0

    x = x_ref[:]                                          # [8, 128] f32
    gidx = (i * BLK
            + jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 0)
            * BLK_COLS
            + jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 1))
    mask = jnp.abs(x) >= t_ref[0]
    blk_start = i * BLK
    blk_end = blk_start + BLK
    cap = vh_ref.shape[1] - capb

    for r in range(num_regions):
        @pl.when((b_ref[r] < blk_end) & (b_ref[r + 1] > blk_start))
        def _(r=r):
            mask_r = mask & (gidx >= b_ref[r]) & (gidx < b_ref[r + 1])
            m = mask_r.astype(jnp.int32)
            pos, _ = _block_prefix(m)
            kept = mask_r & (pos < capb)
            sel = jnp.where(kept, pos, capb)
            stored = jnp.sum(kept.astype(jnp.int32))
            stage_ref[:] = _compact_tile(_quantity_rows(x, gidx, kept),
                                         sel, capb)
            base_w = jnp.minimum(base_ref[r], cap)
            for j, out in enumerate((vh_ref, vl_ref, ih_ref, il_ref)):
                copy = pltpu.make_async_copy(
                    stage_ref.at[j], out.at[r, pl.ds(base_w, capb)],
                    sem_ref)
                copy.start()
                copy.wait()
            base_ref[r] = base_w + stored

    @pl.when(i == nblocks - 1)
    def _():
        for r in range(num_regions):
            cnt_ref[0, r] = jnp.minimum(base_ref[r], cap)


@functools.partial(jax.jit,
                   static_argnames=("num_regions", "cap", "interpret"))
def pack_by_region_pallas(x: jnp.ndarray, thresh, boundaries,
                          num_regions: int, cap: int,
                          interpret: bool | None = None):
    """Pack ``|x| >= thresh`` into per-region fixed-capacity buffers in ONE
    pass over ``x`` (the Pallas fast path of ops.select.pack_by_region).

    ``boundaries``: i32 [num_regions + 1] cumulative offsets. Returns
    ``(values [R, cap], indices [R, cap], counts [R])`` with the same
    contract as the portable path."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    n = x.size
    capb = _capb_for(cap)
    pad = (-n) % BLK
    xp = jnp.pad(x.reshape(-1), (0, pad)).reshape(-1, BLK_COLS)
    nblocks = xp.shape[0] // BLK_ROWS
    t = jnp.reshape(jnp.maximum(jnp.asarray(thresh, x.dtype),
                                jnp.float32(1.17549435e-38)), (1,))
    b = jnp.asarray(boundaries, jnp.int32)

    try:
        vma = jax.typeof(xp).vma
    except Exception:
        vma = frozenset()
    if vma:
        t = jax.lax.pvary(t, tuple(vma - jax.typeof(t).vma))
        b = jax.lax.pvary(b, tuple(vma - jax.typeof(b).vma))
    out_shapes = [jax.ShapeDtypeStruct((num_regions, cap + capb),
                                       jnp.float32, vma=vma)
                  for _ in range(4)]
    out_shapes.append(jax.ShapeDtypeStruct((1, num_regions), jnp.int32,
                                           vma=vma))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((BLK_ROWS, BLK_COLS),
                               lambda i, t, b: (i, 0))],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 4
        + [pl.BlockSpec(memory_space=pltpu.SMEM)],
        scratch_shapes=[
            pltpu.SMEM((num_regions,), jnp.int32),
            pltpu.VMEM((4, capb), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    vh, vl, ih, il, cnts = pl.pallas_call(
        functools.partial(_pack_regions_kernel, num_regions, capb),
        grid_spec=grid_spec,
        out_shape=out_shapes,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(t, b, xp)

    counts = cnts[0]                                     # [R]
    live = jnp.arange(cap)[None, :] < counts[:, None]
    vbits = ((vh[:, :cap].astype(jnp.int32) << 16)
             | (vl[:, :cap].astype(jnp.int32) & 0xFFFF))
    values = jnp.where(live,
                       jax.lax.bitcast_convert_type(vbits, jnp.float32),
                       0.0)
    indices = jnp.where(
        live,
        (ih[:, :cap].astype(jnp.int32) << 16)
        | (il[:, :cap].astype(jnp.int32) & 0xFFFF),
        n).astype(jnp.int32)
    return values, indices, counts


def mesh_supports_pallas(mesh) -> bool:
    """True when every device of the mesh is a TPU (incl. the tunnelled
    "axon" platform) — the backends the compaction kernel targets."""
    try:
        plats = {d.platform for d in np.asarray(mesh.devices).flat}
    except Exception:
        return False
    return bool(plats) and plats.issubset({"tpu", "axon"})


def resolve_use_pallas(cfg, mesh):
    """Fill OkTopkConfig.use_pallas from the mesh backend when unset."""
    if cfg.use_pallas is not None:
        return cfg
    return cfg.replace(use_pallas=mesh_supports_pallas(mesh))
