"""Fused selection front-end: residual add + select + stage in ONE sweep.

The steady-state oktopk step front-end used to make ~6 separate n-scale
HBM sweeps over the gradient: ``add_residual`` (read grad + residual, write
acc), ``jnp.abs`` (read acc), the threshold mask + realised count (read),
the Newton probe count (read), and the staging pass of
``ops/compaction.py`` (read). This module's kernel makes ONE: it reads
(grad, residual) block by block, computes ``acc = grad + residual``
in-register, and emits in the same grid step

- the acc block itself (the only n-scale write; every later consumer —
  repartition, the residual update — reads this buffer),
- the compaction staging rows + raw per-block survivor counts of
  ``ops/compaction.py`` (same layout, bit-identical — the cap-scale
  post-processing ``_pack_finalize`` is shared),
- the per-block Newton probe counts (``|acc| >= thresh * probe_ratio``,
  previously a separate sweep in collectives/oktopk.py),
- a 256-bin log2-magnitude histogram partial (ops/hist_threshold.py bins,
  bit-identical to ``log2_hist``) — which makes the "hist" exact threshold
  recompute ZERO extra passes on fused steps.

Steady-state sweeps over n after this module: the fused pass (2 reads +
1 write), the phase-(a) scatter, and the single consumer pass (result
scale + winner mask + residual) — see docs/PERF.md.

The staging mask uses the min-normal-clamped threshold exactly as
``_prep`` does; the probe count deliberately uses the UNCLAMPED probe
threshold so it is bit-identical to the portable
``jnp.sum(abs_acc >= lt * probe_ratio)`` (which has no clamp). The
histogram covers nonzero in-range elements only, so the zero padding the
kernel adds never shows up in any output.

All outputs reproduce the portable path bit-for-bit in interpret mode
(tests/test_fused_select.py, same contract as ops/compaction.py);
tests/test_tpu_hw.py mirrors them for real-chip Mosaic compilation.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from oktopk_tpu.comm import compat
from oktopk_tpu.ops.compaction import (
    BLK,
    BLK_COLS,
    BLK_ROWS,
    CAPB_FAST,
    SB,
    _block_prefix,
    _interpret_default,
    _pack_finalize,
    _pvary_to,
    _stage_tile,
    _vma_of,
)
from oktopk_tpu.obs.anatomy import phase_scope
from oktopk_tpu.ops.hist_threshold import HIST_BINS, log2_bins, log2_hist


def _fused_kernel(capb, t_ref, tp_ref, r_ref, g_ref, res_ref,
                  acc_ref, w_ref, cr_ref, pr_ref, h_ref):
    """Stage SB consecutive blocks of acc = grad + residual in one sweep.

    Outputs per grid step: the acc tile, the staging rows + raw counts of
    ``_stage_kernel`` (identical layout), per-block probe counts, and a
    [SB, HIST_BINS] histogram accumulator (constant index_map: the block
    stays resident in VMEM across grid steps and row sb accumulates
    sub-block sb — the standard reduction-output pattern). Counts are f32
    (MXU one-hot matmuls); each accumulator cell is bounded by n/SB, exact
    in f32 for n up to 2^24 * SB = 134M elements.
    """
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    acc = g_ref[:] + res_ref[:]                           # [SB*8, 128] f32
    acc_ref[:] = acc
    woff = (jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 0)
            * BLK_COLS
            + jax.lax.broadcasted_iota(jnp.int32, (BLK_ROWS, BLK_COLS), 1))

    @pl.when(i == 0)
    def _():
        h_ref[:] = jnp.zeros_like(h_ref)

    rows_w, rows_r, rows_p, rows_h = [], [], [], []
    for sb in range(SB):
        x = jax.lax.slice(acc, (sb * BLK_ROWS, 0),
                          ((sb + 1) * BLK_ROWS, BLK_COLS))
        ax = jnp.abs(x)
        gidx = (i * SB + sb) * BLK + woff
        inr = (gidx >= r_ref[0]) & (gidx < r_ref[1])
        mask = (ax >= t_ref[0]) & inr
        m = mask.astype(jnp.int32)
        pos, raw = _block_prefix(m)

        kept = mask & (pos < capb)
        sel = jnp.where(kept, pos, capb)                  # capb = dropped
        rows_w.append(_stage_tile(jnp.where(kept, woff, 0), sel, capb))
        rows_r.append(jnp.full((1, BLK_COLS), raw, jnp.int32))

        # Newton probe: unclamped threshold (bit-parity with the portable
        # jnp.sum(abs_acc >= lt * probe_ratio)), range-masked so padding
        # never counts even when the probe threshold is 0
        probe = jnp.sum(((ax >= tp_ref[0]) & inr).astype(jnp.int32))
        rows_p.append(jnp.full((1, BLK_COLS), probe, jnp.int32))

        # log2-magnitude histogram of live in-range elements: same one-hot
        # NT matmul as the staging rows, with collisions doing the counting
        bins = log2_bins(x)                               # -1 marks zeros
        live = (bins >= 0) & inr
        rows_h.append(_stage_tile(live.astype(jnp.int32),
                                  jnp.maximum(bins, 0), HIST_BINS))
    w_ref[:] = jnp.concatenate(rows_w, axis=0)
    cr_ref[:] = jnp.concatenate(rows_r, axis=0)
    pr_ref[:] = jnp.concatenate(rows_p, axis=0)
    h_ref[:] = h_ref[:] + jnp.concatenate(rows_h, axis=0)


def _run_fused_stage(gp, rp, t, tp, rng, capb, nblocks, interpret, vma):
    """pallas_call wrapper: (acc_p [nb*8, 128], w_stage [nb, capb],
    stored [nb], raw [nb], probe [nb], hist [HIST_BINS])."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    out_shapes = [
        compat.shape_dtype_struct((nblocks * BLK_ROWS, BLK_COLS),
                                  jnp.float32, vma=vma),
        compat.shape_dtype_struct((nblocks, capb), jnp.float32, vma=vma),
        compat.shape_dtype_struct((nblocks, BLK_COLS), jnp.int32, vma=vma),
        compat.shape_dtype_struct((nblocks, BLK_COLS), jnp.int32, vma=vma),
        compat.shape_dtype_struct((SB, HIST_BINS), jnp.float32, vma=vma),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nblocks // SB,),
        in_specs=[
            pl.BlockSpec((SB * BLK_ROWS, BLK_COLS),
                         lambda i, t, tp, r: (i, 0)),
            pl.BlockSpec((SB * BLK_ROWS, BLK_COLS),
                         lambda i, t, tp, r: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((SB * BLK_ROWS, BLK_COLS),
                         lambda i, t, tp, r: (i, 0)),
            pl.BlockSpec((SB, capb), lambda i, t, tp, r: (i, 0)),
            pl.BlockSpec((SB, BLK_COLS), lambda i, t, tp, r: (i, 0)),
            pl.BlockSpec((SB, BLK_COLS), lambda i, t, tp, r: (i, 0)),
            pl.BlockSpec((SB, HIST_BINS), lambda i, t, tp, r: (0, 0)),
        ],
    )
    acc_p, w, cr, pr, h = pl.pallas_call(
        functools.partial(_fused_kernel, capb),
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=interpret,
    )(t, tp, rng, gp, rp)
    raw = cr[:, 0]
    hist = jnp.sum(h, axis=0).astype(jnp.int32)
    return acc_p, w, jnp.minimum(raw, capb), raw, pr[:, 0], hist


class FusedStage(NamedTuple):
    """Single-sweep front-end outputs plus the staging internals the
    region finalisation (``fused_pack_finalize``) consumes."""
    acc: jnp.ndarray           # [n] f32 — grad + residual
    local_count: jnp.ndarray   # i32 — realised count(|acc| >= thresh)
    probe_count: jnp.ndarray   # i32 — count(|acc| >= probe_thresh)
    hist: jnp.ndarray          # [HIST_BINS] i32 — log2_hist(acc)
    # staging internals (padded layout)
    accp: jnp.ndarray          # [nb*8, 128] padded acc tiles
    accflat: jnp.ndarray       # [nb*8*128] padded acc flat
    w_f: jnp.ndarray           # [nb, CAPB_FAST] fast staging rows
    stored_f: jnp.ndarray      # [nb] min(raw, CAPB_FAST)
    raw: jnp.ndarray           # [nb] raw per-block survivor counts
    t: jnp.ndarray             # [1] clamped staging threshold
    rng: jnp.ndarray           # [2] element range [0, n)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_select_stage(grad: jnp.ndarray, residual: jnp.ndarray, thresh,
                       probe_thresh, interpret: bool | None = None
                       ) -> FusedStage:
    """Run the fused kernel over (grad, residual): one sweep computes acc,
    the fast staging rows, the realised/probe counts and the histogram.

    The staging threshold is min-normal-clamped exactly as
    ``select_by_threshold_pallas`` (``_prep``); ``probe_thresh`` is used
    unclamped (see module docstring). Region assembly is a separate
    cap-scale step (``fused_pack_finalize``) so the caller can compute
    data-dependent boundaries from ``acc`` in between (the repartition
    cadence of collectives/oktopk.py).
    """
    if interpret is None:
        interpret = _interpret_default()
    if grad.shape != residual.shape:
        raise ValueError(f"grad {grad.shape} != residual {residual.shape}")
    # the anatomy scope lives INSIDE the jitted wrapper so the contract
    # name reaches this program's own op metadata (a caller-side scope
    # stops at the nested pjit call op)
    with phase_scope("select"):
        return _fused_select_stage_impl(grad, residual, thresh,
                                        probe_thresh, interpret)


def _fused_select_stage_impl(grad, residual, thresh, probe_thresh,
                             interpret):
    n = grad.size
    pad = (-n) % (SB * BLK)
    gp = jnp.pad(grad.reshape(-1), (0, pad)).reshape(-1, BLK_COLS)
    rp = jnp.pad(residual.reshape(-1), (0, pad)).reshape(-1, BLK_COLS)
    nblocks = gp.shape[0] // BLK_ROWS
    t = jnp.reshape(jnp.maximum(jnp.asarray(thresh, grad.dtype),
                                jnp.float32(1.17549435e-38)), (1,))
    tp = jnp.reshape(jnp.asarray(probe_thresh, grad.dtype), (1,))
    rng = jnp.stack([jnp.asarray(0, jnp.int32), jnp.asarray(n, jnp.int32)])
    vma = _vma_of(gp)
    if vma:
        t = _pvary_to(t, vma)
        tp = _pvary_to(tp, vma)
        rng = _pvary_to(rng, vma)

    accp, w_f, stored_f, raw, probe_blk, hist = _run_fused_stage(
        gp, rp, t, tp, rng, CAPB_FAST, nblocks, interpret, vma)
    accflat = accp.reshape(-1)
    return FusedStage(
        acc=accflat[:n], local_count=jnp.sum(raw),
        probe_count=jnp.sum(probe_blk), hist=hist,
        accp=accp, accflat=accflat, w_f=w_f, stored_f=stored_f, raw=raw,
        t=t, rng=rng)


@functools.partial(jax.jit,
                   static_argnames=("num_regions", "cap", "interpret"))
def fused_pack_finalize(st: FusedStage, boundaries, num_regions: int,
                        cap: int, interpret: bool | None = None):
    """Per-region (values, indices, counts) from an already-run fused
    stage — the cap-scale half of ``pack_by_region_pallas``, shared
    verbatim (``_pack_finalize``): overflowing blocks are re-staged from
    the kernel's own acc output by the repair/wide kernels, so overflow
    costs extra passes only when it happens, exactly as before."""
    if interpret is None:
        interpret = _interpret_default()
    n = st.acc.size
    nblocks = st.w_f.shape[0]
    bnd = jnp.asarray(boundaries, jnp.int32)
    vma = _vma_of(st.accp)
    with phase_scope("stage"):
        return _pack_finalize(st.accp, st.accflat, st.t, st.rng, bnd,
                              num_regions, cap, nblocks, n, interpret, vma,
                              st.w_f, st.stored_f, st.raw)


@functools.partial(jax.jit,
                   static_argnames=("num_regions", "cap", "interpret"))
def fused_select_pallas(grad: jnp.ndarray, residual: jnp.ndarray, thresh,
                        probe_thresh, boundaries, num_regions: int,
                        cap: int, interpret: bool | None = None):
    """One-call form (unit tests / profiling): stage + finalize.

    Returns ``(acc, values [R, cap], indices [R, cap], counts [R],
    local_count, probe_count, hist [HIST_BINS])`` — bit-identical to
    :func:`fused_select_reference`.
    """
    st = fused_select_stage(grad, residual, thresh, probe_thresh,
                            interpret=interpret)
    values, indices, counts = fused_pack_finalize(
        st, boundaries, num_regions, cap, interpret=interpret)
    return (st.acc, values, indices, counts, st.local_count,
            st.probe_count, st.hist)


def fused_select_reference(grad: jnp.ndarray, residual: jnp.ndarray,
                           thresh, probe_thresh, boundaries,
                           num_regions: int, cap: int):
    """Portable semantics twin (the parity oracle, and the CPU profile
    probe): the same outputs from the separate portable sweeps. The
    selection mask uses the min-normal-clamped threshold (as the kernel
    and ``pack_by_region_pallas`` do); the probe count uses the raw one
    (as collectives/oktopk.py always has)."""
    from oktopk_tpu.ops.select import pack_by_region

    acc = grad.reshape(-1) + residual.reshape(-1)
    t = jnp.maximum(jnp.asarray(thresh, acc.dtype),
                    jnp.float32(1.17549435e-38))
    abs_acc = jnp.abs(acc)
    mask = abs_acc >= t
    values, indices, counts = pack_by_region(
        acc, mask, jnp.asarray(boundaries, jnp.int32), num_regions, cap)
    local_count = jnp.sum(mask)
    probe_count = jnp.sum(abs_acc >= jnp.asarray(probe_thresh, acc.dtype))
    return (acc, values, indices, counts, local_count, probe_count,
            log2_hist(acc))
