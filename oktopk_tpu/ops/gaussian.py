"""Gaussian threshold estimation.

The reference's GaussianCompressor (VGG/compression.py:167-260) estimates a
selection threshold from a normal fit — ``gen_threshold_from_normal_distribution``
computes the two-sided ppf of N(mean, std) (VGG/utils.py:136-138) — then
refines it in a bounded loop of nonzero-counts until the realised count lands
near k (VGG/compression.py:238-259).

Here the ppf is closed-form via ``erfinv`` and the refinement is a fixed-trip
bisection on |x| (bounded, branch-free — jit-friendly), which converges at
least as tightly as the reference's multiplicative loop. Avoiding a full
``top_k`` sort is the point of the Gaussian family: O(iters * n) compares on
the VPU instead of an O(n log n) sort.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _normal_ppf(p, mean, std):
    """Inverse CDF of N(mean, std) (scipy.stats.norm.ppf equivalent,
    reference VGG/utils.py:136-138)."""
    return mean + std * jnp.sqrt(2.0) * lax.erf_inv(2.0 * p - 1.0)


def gaussian_threshold(x: jnp.ndarray, k: int, refine_iters: int = 16):
    """Threshold t such that count(|x| >= t) ~= k, without sorting.

    Initial estimate from the normal fit (two-sided), then ``refine_iters``
    bisection steps between 0 and max|x|.
    """
    abs_x = jnp.abs(x)
    mean = jnp.mean(x)
    std = jnp.std(x) + 1e-12
    ratio = jnp.clip(k / x.size, 1e-9, 0.5)
    t0 = jnp.abs(_normal_ppf(1.0 - ratio / 2.0, mean, std))

    hi0 = jnp.max(abs_x)
    t0 = jnp.clip(t0, 0.0, hi0)

    def body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        count = jnp.sum(abs_x >= mid)
        # too many selected -> raise threshold (move lo up)
        lo = jnp.where(count > k, mid, lo)
        hi = jnp.where(count > k, hi, mid)
        return lo, hi

    # Seed the bracket around the ppf estimate: check which side it is on.
    count0 = jnp.sum(abs_x >= t0)
    lo = jnp.where(count0 > k, t0, 0.0)
    hi = jnp.where(count0 > k, hi0, t0)
    lo, hi = lax.fori_loop(0, refine_iters, body, (lo, hi))
    return 0.5 * (lo + hi)
