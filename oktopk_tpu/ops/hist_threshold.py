"""One-pass histogram selection thresholds (the fused-path recompute).

``k2threshold_bisect`` (ops/pallas_topk.py) narrows a log-space bracket
3 bits per memory pass — ~10 n-scale HBM sweeps per exact recompute at the
default ``bisect_iters=30``, plus the max|x| anchor pass. This module reads
the k-th-value threshold off a 256-bin log2-magnitude histogram instead:

- ``log2_hist``: ONE pass over the data builds the histogram. Bins are the
  f32 *biased exponent* (bits 30..23), one bin per binary octave, covering
  the entire normal-f32 range with no data-dependent anchor — which is what
  lets the fused selection kernel (ops/fused_select.py) emit the same
  histogram as a byproduct of its single sweep, making the exact recompute
  ZERO extra passes on fused steps and one pass standalone.
- ``hist_to_threshold``: the cumsum read (256-scalar work, no data pass).

Bracket-floor semantics and the min-normal clamp are preserved from the
bisection (the absorbing-zero lesson, ops/pallas_topk.py): the returned
threshold is the largest bin lower edge with count(|x| >= edge) >= k, always
a normal power of two >= 2^-126, and exactly 0 only when the input is all
zero. Within-octave resolution is 1 bit (t in (kth/2, kth]) versus the
bisection's ~2^-30 — "bisect" stays the oracle and the default; "hist" is
the fused fast path (OkTopkConfig.threshold_method).

Subnormal inputs (CPU only; TPU flushes them to zero) are binned at the
min-normal edge, matching the selection kernel's own threshold clamp
(ops/compaction.py ``_prep``): a threshold of 2^-126 selects exactly the
nonzeros on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

HIST_BINS = 256

# f32 exponent bias; bin j (1 <= j <= 254) counts 2^(j-127) <= |x| < 2^(j-126)
_BIAS = 127
_MAX_EDGE_BIN = 254   # bin 255 holds inf/nan; its edge (2^128) is not f32


def log2_bins(x: jnp.ndarray) -> jnp.ndarray:
    """Per-element histogram bin: the f32 biased exponent of |x|, with
    subnormals promoted to bin 1 (the min-normal edge) and exact zeros
    marked -1 (excluded from the histogram).

    Bit extraction, not ``floor(log2(x))``: the float log is inexact at
    octave boundaries (log2(2^-10) can round below -10) and the fused
    kernel must reproduce these bins bit-for-bit (ops/fused_select.py).
    """
    bits = lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)
    mag = bits & jnp.int32(0x7FFFFFFF)
    e = jnp.right_shift(mag, 23)
    return jnp.where(mag == 0, jnp.int32(-1),
                     jnp.maximum(e, jnp.int32(1)))


def log2_hist(x: jnp.ndarray) -> jnp.ndarray:
    """[HIST_BINS] i32 counts of the nonzero elements of ``x`` by binary
    octave (``log2_bins``), in ONE pass over the data.

    Standalone form is a scatter-add (zeros parked in a spilled 257th bin
    so no index is ever out of range or negative). An n-operand scatter
    serialises on TPU (ops/compaction.py module docstring) — but on the
    TPU fast path this function never runs per-step: the fused selection
    kernel emits the identical histogram via MXU one-hot accumulation
    (ops/fused_select.py), and the oktopk "hist" controller only calls
    the standalone form inside its recompute/priming cond branches.
    Counts are integers, so both constructions agree bit-for-bit.
    """
    b = log2_bins(x).reshape(-1)
    b = jnp.where(b < 0, jnp.int32(HIST_BINS), b)
    h = jnp.zeros(HIST_BINS + 1, jnp.int32).at[b].add(1)
    return h[:HIST_BINS]


def hist_to_threshold(hist: jnp.ndarray, k) -> jnp.ndarray:
    """k-th-value threshold from a ``log2_hist`` histogram: the largest bin
    lower edge 2^(j-127) whose suffix count is >= k (bracket floor), j
    clamped to [1, 254] so the result is always a normal f32 (min-normal
    clamp; the absorbing-zero lesson). Exactly 0 only for an empty
    histogram (all-zero input). ``k`` may be traced (a scheduled target).

    When fewer than k elements are live the floor degenerates to the
    min-normal edge — like the bisection's positive bracket floor, this
    selects exactly the live elements, never everything.
    """
    hist = hist.astype(jnp.int32)
    cum = jnp.cumsum(hist[::-1])[::-1]          # cum[j] = count(bin >= j)
    j = jnp.arange(HIST_BINS, dtype=jnp.int32)
    ok = (cum >= k) & (j >= 1) & (j <= _MAX_EDGE_BIN)
    jstar = jnp.max(jnp.where(ok, j, jnp.int32(1)))
    # assemble 2^(jstar-127) from the exponent bits directly: jnp.exp2 is
    # not trustworthy at the normal-range floor (XLA's f32 exp2 flushes
    # exp2(-126) to 0 on some backends — exactly the absorbing zero this
    # function must never produce)
    t = lax.bitcast_convert_type(jnp.left_shift(jstar, 23), jnp.float32)
    return jnp.where(cum[0] > 0, t, jnp.float32(0.0))


def k2threshold_hist(x_abs: jnp.ndarray, k) -> jnp.ndarray:
    """Standalone one-pass form: histogram + cumsum read. Same contract as
    ``k2threshold_bisect`` up to the 1-bit bin resolution: the result t
    satisfies count(|x| >= t) >= k and kth/2 < t <= kth whenever at least
    k elements are live."""
    return hist_to_threshold(log2_hist(x_abs), k).astype(x_abs.dtype)
