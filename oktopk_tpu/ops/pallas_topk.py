"""Sort-free selection thresholds (the periodic exact recomputes).

The reference's exact threshold recompute is ``torch.topk`` on the full flat
gradient (VGG/compression.py:86-106) — O(n log n) and the reason it only
recomputes every 32 steps. On TPU a k-th-value threshold only needs
*counting*, not sorting: multi-way bisection on the value axis with a fused
compare-and-count per trip (O(passes*n) VPU work, no sort, SURVEY.md
§7.3.5). XLA fuses each pass's searchsorted-compare-reduce into one
HBM-bandwidth-bound sweep, so no hand-written kernel is needed here; the
Pallas effort goes to the compaction that *uses* the threshold
(ops/compaction.py), where the portable path's giant scatter is the real
TPU bottleneck.

``k2threshold_bisect`` replaces ``ops.topk.k2threshold``'s sort, selectable
via ``OkTopkConfig.threshold_method`` ("bisect" is the default).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_WAYS = 8  # brackets per pass; each memory pass narrows log2(_WAYS) bits


_LOG_RANGE_BITS = 64.0   # dynamic range below max|x| the bracket covers


def k2threshold_bisect(x_abs: jnp.ndarray, k: int, iters: int = 30):
    """Sort-free k-th-largest estimate via multi-way bisection IN LOG
    SPACE.

    Each trip splits the bracket into ``_WAYS`` geometric sub-intervals
    and counts all boundaries in ONE pass over the data (per-element
    ``searchsorted`` into the 7 interior cut points + a fused streaming
    reduce), then keeps the sub-interval where count(|x| >= t) crosses k.
    One memory pass narrows the bracket 8x — the hot selection path is
    HBM-bandwidth-bound (SURVEY.md §7.3.5).

    Why log space: a LINEAR bracket [0, max] resolves only max/2^iters.
    Under error feedback at convergence the k-th |value| sits many orders
    of magnitude below a few large residuals (> 30 bits of dynamic
    range), so the linear form returned exactly 0 — and zero is an
    ABSORBING state for the multiplicative threshold controller
    (0 x corr == 0 forever): observed as local_k == n, saturated
    capacity buffers, and an eventual loss blow-up on the convergence
    harness. Geometric cuts resolve the full f32 range and the returned
    lower edge is always > 0 (max|x| * 2^-64 at worst) so the controller
    can always recover.

    Returns the bracket's lower edge with count(>= lo) >= k whenever at
    least k elements lie within 2^-64 of max|x|. DELIBERATE divergence
    from the "sort" method when fewer do (sparse / dead accumulators):
    "sort" returns 0 and selects everything including zeros; this returns
    the positive bracket floor and selects only the live elements —
    strictly less wire traffic, and never the absorbing zero. The result
    is clamped to the smallest normal f32 exponent so it cannot underflow
    back to 0 (TPU flushes subnormals anyway); exactly 0 only when
    ``x_abs`` is all zero.
    """
    hi0 = jnp.max(x_abs)
    flat = x_abs.reshape(-1)
    bits_per_pass = max(1, int(_WAYS).bit_length() - 1)  # log2(_WAYS)
    passes = -(-iters // bits_per_pass)

    e_hi = jnp.log2(jnp.maximum(hi0, jnp.float32(1e-38))) + 1e-3
    e_lo = e_hi - jnp.float32(_LOG_RANGE_BITS)

    def body(_, carry):
        lo, hi = carry                              # log2 exponents
        frac = jnp.arange(1, _WAYS, dtype=jnp.float32) / _WAYS
        cuts_e = lo + (hi - lo) * frac
        cuts = jnp.exp2(cuts_e).astype(x_abs.dtype)
        b = jnp.searchsorted(cuts, flat, side="left").astype(jnp.int32)
        counts = jnp.sum(
            b[:, None] >= jnp.arange(_WAYS, dtype=jnp.int32)[None, :],
            axis=0)
        # counts[0] = n (>= k always); counts[j>=1] = #{x > cuts[j-1]}
        # (side="left" makes the count strict). Keep the bracket whose
        # lower edge still has >= k above it.
        enough = counts >= k
        j = jnp.max(jnp.where(enough, jnp.arange(_WAYS), 0))
        edges = jnp.concatenate([lo[None], cuts_e, hi[None]])
        return edges[j], edges[j + 1]

    lo, hi = lax.fori_loop(0, passes, body, (e_lo, e_hi))
    # clamp to the min normal exponent: exp2(e_hi - 64) underflows to an
    # exact 0 for max|x| below ~2^-85, which would re-enter the absorbing
    # zero state this function exists to prevent
    t = jnp.exp2(jnp.maximum(lo, jnp.float32(-126.0))).astype(x_abs.dtype)
    return jnp.where(hi0 > 0, t, jnp.zeros_like(t))
