"""Sort-free selection thresholds (the periodic exact recomputes).

The reference's exact threshold recompute is ``torch.topk`` on the full flat
gradient (VGG/compression.py:86-106) — O(n log n) and the reason it only
recomputes every 32 steps. On TPU a k-th-value threshold only needs
*counting*, not sorting: multi-way bisection on the value axis with a fused
compare-and-count per trip (O(passes*n) VPU work, no sort, SURVEY.md
§7.3.5). XLA fuses each pass's searchsorted-compare-reduce into one
HBM-bandwidth-bound sweep, so no hand-written kernel is needed here; the
Pallas effort goes to the compaction that *uses* the threshold
(ops/compaction.py), where the portable path's giant scatter is the real
TPU bottleneck.

``k2threshold_bisect`` replaces ``ops.topk.k2threshold``'s sort, selectable
via ``OkTopkConfig.threshold_method`` ("bisect" is the default).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

_WAYS = 8  # brackets per pass; each memory pass narrows log2(_WAYS) bits


def k2threshold_bisect(x_abs: jnp.ndarray, k: int, iters: int = 30):
    """Sort-free k-th-largest estimate to ``iters`` bits of precision.

    Multi-way bisection: each trip splits the bracket [lo, hi) into
    ``_WAYS`` sub-intervals and counts all boundaries in ONE pass over the
    data (per-element ``searchsorted`` into the 7 interior cut points +
    bincount), then keeps the sub-interval where count(|x| >= t) crosses k.
    One memory pass narrows 3 bits instead of the 1 bit of classic
    bisection, so 30-bit precision costs 10 passes instead of 30 — the hot
    selection path is HBM-bandwidth-bound (SURVEY.md §7.3.5).

    Returns the bracket's lower edge (count(>= lo) >= k), matching
    ``k2threshold``'s inclusivity. The final bracket is max|x|/2^iters wide
    — below float32 resolution for the default 30.
    """
    hi0 = jnp.max(x_abs)
    flat = x_abs.reshape(-1)
    bits_per_pass = max(1, int(_WAYS).bit_length() - 1)  # log2(_WAYS)
    passes = -(-iters // bits_per_pass)

    def body(_, carry):
        lo, hi = carry
        # interior cut points t_1 < ... < t_{W-1} of [lo, hi)
        frac = jnp.arange(1, _WAYS, dtype=x_abs.dtype) / _WAYS
        cuts = lo + (hi - lo) * frac
        # ONE data pass: per-element bucket id (3 register compares via
        # searchsorted), then counts[j] = #elements above cut j as a fused
        # streaming reduce — no scatter, nothing materialised at [n, W]
        b = jnp.searchsorted(cuts, flat, side="left").astype(jnp.int32)
        counts = jnp.sum(
            b[:, None] >= jnp.arange(_WAYS, dtype=jnp.int32)[None, :],
            axis=0)
        # counts[0] = n (>= k always); counts[j>=1] = #{x > cuts[j-1]}.
        # Keep the bracket whose lower edge still has >= k above it.
        enough = counts >= k
        j = jnp.max(jnp.where(enough, jnp.arange(_WAYS), 0))
        edges = jnp.concatenate([lo[None], cuts, hi[None]])
        return edges[j], edges[j + 1]

    lo, hi = lax.fori_loop(
        0, passes, body,
        (jnp.zeros_like(hi0), hi0 * (1 + 1e-6) + 1e-30))
    return lo
