"""Pallas TPU kernels for the selection hot path + sort-free thresholds.

The reference's exact threshold recompute is ``torch.topk`` on the full flat
gradient (VGG/compression.py:86-106) — O(n log n) and the reason it only
recomputes every 32 steps. On TPU a k-th-value threshold only needs
*counting*, not sorting: bisection on the value axis with a fused
abs-compare-count per trip (O(iters·n) VPU work, no sort, SURVEY.md §7.3.5).

``count_ge`` is the Pallas kernel (blocked VMEM reduction); on non-TPU
backends it falls back to plain jnp (the tests run on the CPU mesh).
``k2threshold_bisect`` is the sort-free replacement for
``ops.topk.k2threshold``, selectable via ``OkTopkConfig.threshold_method``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_BLOCK = 8 * 1024


def _count_kernel(x_ref, t_ref, out_ref):
    out_ref[0] = jnp.sum(
        (jnp.abs(x_ref[:]) >= t_ref[0]).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("use_pallas",))
def count_ge(x: jnp.ndarray, thresh, use_pallas: bool = False):
    """Number of elements with |x| >= thresh."""
    if not use_pallas:
        return jnp.sum(jnp.abs(x) >= thresh)

    from jax.experimental import pallas as pl

    n = x.size
    pad = (-n) % _BLOCK
    xp = jnp.pad(x.reshape(-1), (0, pad))      # zeros never pass t > 0
    nblocks = xp.size // _BLOCK
    t = jnp.reshape(thresh.astype(x.dtype), (1,))
    partial_counts = pl.pallas_call(
        _count_kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((_BLOCK,), lambda i: (i,)),
                  pl.BlockSpec((1,), lambda i: (0,))],
        out_specs=pl.BlockSpec((1,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((nblocks,), jnp.int32),
    )(xp, t)
    return jnp.sum(partial_counts)


def k2threshold_bisect(x_abs: jnp.ndarray, k: int, iters: int = 30,
                       use_pallas: bool = False):
    """Sort-free k-th-largest estimate: bisection between 0 and max|x| until
    count(|x| >= t) ~= k. After ``iters`` trips the bracket is max|x|/2^iters
    wide — far below float32 resolution for 30 trips. Returns the lower edge
    (count >= k), matching ``k2threshold``'s inclusivity."""
    hi0 = jnp.max(x_abs)

    def body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        c = count_ge(x_abs, mid, use_pallas=use_pallas)
        # keep count(>= lo) >= k invariant: converge onto the k-th value
        enough = c >= k
        return jnp.where(enough, mid, lo), jnp.where(enough, hi, mid)

    lo, hi = lax.fori_loop(0, iters, body, (jnp.zeros_like(hi0), hi0))
    return lo
