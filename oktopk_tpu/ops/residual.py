"""Error-feedback residual transforms.

The reference keeps residuals in class-attribute dicts keyed by tensor name
(VGG/compression.py:28,170) and mutates them in place. Here they are explicit
arrays threaded through the algorithm state, with each algorithm's exact
semantics preserved (SURVEY.md §7.3.4):

- oktopk zeroes the residual only at indices that made the *global* result
  (VGG/allreducer.py:1051-1052 via compression.py:467-471);
- topkA-style compressors zero at the *local* selection
  (VGG/compression.py:343);
- the adaptive path adds everything back and re-subtracts what was sent
  (add2residual, VGG/compression.py:384-404) — equivalent to the masked forms
  below on the accumulated tensor.
"""

from __future__ import annotations

import jax.numpy as jnp


def add_residual(grad: jnp.ndarray, residual: jnp.ndarray) -> jnp.ndarray:
    """acc = grad + residual (the compensation add every compressor starts
    with, reference VGG/compression.py:90,151-160)."""
    return grad + residual


def update_residual_at_winners(acc: jnp.ndarray,
                               winner_mask: jnp.ndarray) -> jnp.ndarray:
    """oktopk semantics: keep acc as residual except at global winners
    (reference VGG/allreducer.py:1051-1052)."""
    return jnp.where(winner_mask, 0.0, acc)


def update_residual_at_selection(acc: jnp.ndarray,
                                 selected_mask: jnp.ndarray) -> jnp.ndarray:
    """topkA semantics: residual keeps everything not locally selected
    (reference VGG/compression.py:343)."""
    return jnp.where(selected_mask, 0.0, acc)
