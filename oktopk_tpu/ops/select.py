"""Fixed-capacity sparse selection and packing.

This is the load-bearing design decision of the TPU port (SURVEY.md §7.3.1):
every variable-length (index, value) list in the reference — the
``compressbythreshold`` nonzero selects (VGG/compression.py:122-142), the
``Allgatherv`` packed buffers (VGG/allreducer.py:819,1031) and the per-peer
``Isend`` payloads (VGG/allreducer.py:740-754) — becomes a static-shape
``(values[cap], indices[cap], count)`` triple. Slots past ``count`` carry a
sentinel index equal to the source length, which every scatter drops via
``mode='drop'``. The reference's own threshold feedback keeps realised counts
inside a [2k/3, 5k/4] band (VGG/allreducer.py:696-699), which is what makes a
fixed capacity with modest headroom sound; overflow beyond ``cap`` is dropped
deterministically (lowest-index-first retention) and the dropped mass stays in
the error-feedback residual, so nothing is lost from training.
"""

from __future__ import annotations

import jax.numpy as jnp

# Padding slots use index == len(source); scatters with mode='drop' ignore it.
SENTINEL = "index==n sentinel (see module docstring)"


def count_by_threshold(x: jnp.ndarray, thresh) -> jnp.ndarray:
    """Number of elements with |x| >= thresh (reference uses the realised
    nonzero count to adapt thresholds, VGG/allreducer.py:696-699)."""
    return jnp.sum(jnp.abs(x) >= thresh)


def select_by_threshold(x: jnp.ndarray, thresh, cap: int,
                        use_pallas: bool = False):
    """Pack elements with |x| >= thresh into a fixed-capacity triple.

    Replaces reference ``compressbythreshold`` (VGG/compression.py:122-142),
    which returns a ragged nonzero select.

    Returns ``(values[cap], indices[cap], count)`` where slots >= count hold
    value 0 and index n. Elements are packed in ascending index order; if more
    than ``cap`` elements pass the threshold the tail is dropped (and should
    remain in the caller's residual).

    ``use_pallas`` selects the TPU stream-compaction kernel
    (ops/compaction.py) instead of the portable cumsum+scatter, which
    serialises on TPU. Resolved from the mesh backend by the step builders
    (OkTopkConfig.use_pallas).
    """
    if use_pallas and x.dtype == jnp.float32:   # kernel is f32-only
        from oktopk_tpu.ops.compaction import select_by_threshold_pallas
        return select_by_threshold_pallas(x, thresh, cap)
    return select_mask(x, jnp.abs(x) >= thresh, cap)


def select_mask(x: jnp.ndarray, mask: jnp.ndarray, cap: int):
    """Pack elements where ``mask`` is True into a fixed-capacity triple
    (same layout as :func:`select_by_threshold`)."""
    n = x.size
    pos = jnp.cumsum(mask) - 1
    pos = jnp.where(mask & (pos < cap), pos, cap)
    values = jnp.zeros((cap,), x.dtype).at[pos].set(
        jnp.where(mask, x, 0), mode="drop")
    indices = jnp.full((cap,), n, jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    count = jnp.minimum(jnp.sum(mask), cap)
    return values, indices, count


def select_nonzero(x: jnp.ndarray, cap: int, use_pallas: bool = False):
    """Pack the nonzeros of ``x`` (the reference's plain nonzero extract of
    its reduced region before Allgatherv, VGG/allreducer.py:1326).

    The portable path must NOT emulate this with a tiny threshold:
    subnormal thresholds flush to zero on TPU/XLA and select everything.
    The Pallas path clamps its threshold to the smallest *normal* f32,
    which selects exactly the nonzeros on TPU (subnormals flush there).
    """
    if use_pallas and x.dtype == jnp.float32:   # kernel is f32-only
        from oktopk_tpu.ops.compaction import select_by_threshold_pallas
        return select_by_threshold_pallas(x, 0.0, cap)
    return select_mask(x, x != 0.0, cap)


def scatter_sparse(n: int, values: jnp.ndarray, indices: jnp.ndarray,
                   base: jnp.ndarray | None = None) -> jnp.ndarray:
    """Scatter-add (values, indices) triples into a dense length-n vector.

    Replaces the reference's result rebuild after Allgatherv
    (VGG/allreducer.py:1038-1044). Sentinel indices (== n) are dropped.
    ``values``/``indices`` may have any leading batch shape.
    """
    if base is None:
        base = jnp.zeros((n,), values.dtype)
    return base.at[indices.reshape(-1)].add(values.reshape(-1), mode="drop")


def pack_by_region(x: jnp.ndarray, mask: jnp.ndarray,
                   boundaries: jnp.ndarray, num_regions: int, cap: int,
                   thresh=None, use_pallas: bool = False):
    """Pack masked elements of ``x`` into per-region fixed-capacity buffers.

    This is the TPU form of oktopk phase (a)'s send-side: the reference
    physically splits the gradient by region boundaries
    (``torch.split(new_tensor, boundaries)``, VGG/allreducer.py:667-670) and
    threshold-selects each split into a ragged per-peer payload. XLA needs
    static shapes, so instead we compute each element's region id from the
    boundary offsets and scatter hits into a ``[num_regions, cap]`` buffer,
    ready for one ``all_to_all``.

    Args:
      x: flat vector [n].
      mask: boolean [n], which elements to send.
      boundaries: int32 [num_regions + 1] cumulative offsets,
        boundaries[0] == 0, boundaries[-1] == n (the reference's invariant
        ``sum(boundaries) == tensor_size``, VGG/allreducer.py:648).
      cap: per-region capacity.
      thresh: when given (with ``use_pallas``), the mask is known to be
        ``|x| >= thresh`` and the TPU compaction kernel packs each region
        directly (one range-restricted pass per region) instead of the
        portable full-length cumsum + scatter.

    Returns:
      (values [num_regions, cap], indices [num_regions, cap] with global
      element ids, counts [num_regions] clipped to cap).
    """
    n = x.size
    if use_pallas and thresh is not None and x.dtype == jnp.float32:
        from oktopk_tpu.ops.compaction import pack_by_region_pallas
        return pack_by_region_pallas(x, thresh, boundaries, num_regions,
                                     cap)
    ids = jnp.arange(n, dtype=jnp.int32)
    # region id per element; boundaries[1:-1] are the interior cut points.
    rid = jnp.searchsorted(boundaries[1:-1], ids, side="right").astype(jnp.int32)

    csum = jnp.cumsum(mask)                          # inclusive hit count
    starts = boundaries[:-1]
    # hits strictly before each region's start offset
    start_counts = jnp.where(starts > 0, csum[jnp.maximum(starts - 1, 0)], 0)
    pos_in_region = csum - 1 - start_counts[rid]
    pos = jnp.where(mask & (pos_in_region < cap), pos_in_region, cap)

    values = jnp.zeros((num_regions, cap), x.dtype).at[rid, pos].set(
        jnp.where(mask, x, 0), mode="drop")
    indices = jnp.full((num_regions, cap), n, jnp.int32).at[rid, pos].set(
        ids, mode="drop")

    ends = boundaries[1:]
    end_counts = jnp.where(ends > 0, csum[jnp.maximum(ends - 1, 0)], 0)
    counts = jnp.minimum(end_counts - start_counts, cap)
    return values, indices, counts


def region_mask(n: int, boundaries: jnp.ndarray, region: jnp.ndarray):
    """Boolean mask of the elements belonging to ``region``.

    The reference slices its own reduced region physically
    (VGG/allreducer.py:894); with static shapes we mask the flat vector.
    """
    ids = jnp.arange(n, dtype=jnp.int32)
    return (ids >= boundaries[region]) & (ids < boundaries[region + 1])
