"""Exact top-k selection and threshold computation.

Replaces the reference's ``torch.topk``-based paths:
- ``TopKCompressor.ratio2threshold`` (reference VGG/compression.py:86-106):
  exact k-th-largest |grad| after residual add.
- ``k2globalthreshold`` (reference VGG/compression.py:407-415): exact k-th
  largest of a gathered value buffer.

On TPU, ``lax.top_k`` maps to an XLA sort/partition; for the very large flat
gradients a Pallas bucketed-count kernel can replace it (ops/pallas_topk.py).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def exact_topk(x: jnp.ndarray, k: int):
    """(values, indices) of the k largest |x|, values keep their sign.

    Reference TopKCompressor.compress (VGG/compression.py:63-84).
    """
    absx = jnp.abs(x)
    _, idx = lax.top_k(absx, k)
    return x[idx], idx


def k2threshold(x_abs: jnp.ndarray, k: int):
    """The k-th largest value of ``x_abs`` (selection threshold).

    Reference k2globalthreshold (VGG/compression.py:407-415).
    """
    vals = lax.top_k(x_abs, k)[0]
    return vals[k - 1]


def k2threshold_method(x_abs: jnp.ndarray, k: int, method: str = "sort",
                       bisect_iters: int = 30):
    """Dispatch between the exact sort-based threshold, the sort-free
    bisection (ops/pallas_topk.py) and the one-pass histogram read
    (ops/hist_threshold.py) — selected by
    ``OkTopkConfig.threshold_method``."""
    if method == "bisect":
        from oktopk_tpu.ops.pallas_topk import k2threshold_bisect
        return k2threshold_bisect(x_abs, k, iters=bisect_iters)
    if method == "hist":
        from oktopk_tpu.ops.hist_threshold import k2threshold_hist
        return k2threshold_hist(x_abs, k)
    return k2threshold(x_abs, k)


def ratio2threshold(x: jnp.ndarray, density: float):
    """Exact threshold such that |x| >= t selects ~density*n elements.

    Reference TopKCompressor.ratio2threshold (VGG/compression.py:86-106) —
    the every-32-iterations exact recompute of the local threshold.
    """
    k = max(1, int(density * x.size))
    return k2threshold(jnp.abs(x), k)
