"""Distributed optimizers (reference L3, SURVEY.md §1).

The reference wraps torch optimizers: ``_DistributedOptimizer`` dynamically
subclasses the user's SGD and feeds a background allreducer thread
(VGG/distributed_optimizer.py:21-207); BERT's ``BertAdam`` flattens all grads
and calls the allreducer synchronously inside ``step()``
(BERT/bert/transformers/optimization.py:68-224).

Here optimizers are pure ``(grads, state, params) -> (updates, state)``
transforms (optax-compatible protocol, so optax optimizers drop in too), and
the "distributed" part — flatten grads, run the sparse collective, unflatten,
update — is one jitted train step (optim/distributed.py). There are no
threads: compute/communication overlap is XLA's async-collective scheduling,
not a background Python thread (SURVEY.md §7.1.4).
"""

from oktopk_tpu.optim.sgd import sgd  # noqa: F401
from oktopk_tpu.optim.bert_adam import bert_adam  # noqa: F401
from oktopk_tpu.optim.schedules import SCHEDULES, warmup_linear  # noqa: F401
from oktopk_tpu.optim.distributed import (  # noqa: F401
    DistTrainState,
    build_sparse_grad_step,
)
