"""BertAdam: Adam without bias correction + warmup schedule + grad clipping.

Reference: ``BertAdam`` (BERT/bert/transformers/optimization.py:68-224) —
the BERT pretraining optimizer whose ``step()`` also hosts the sparse
allreduce (flatten grads -> allreducer.run -> split -> Adam update,
:145-224). Here the allreduce lives in the train step
(optim/distributed.py); this module is the pure parameter update:

    m = b1*m + (1-b1)*g ;  v = b2*v + (1-b2)*g^2
    update = m / (sqrt(v) + eps) + weight_decay * p
    p -= lr * schedule(step/t_total, warmup) * update

(no bias correction — BertAdam's signature quirk, reference :188-205), with
global-norm gradient clipping to ``max_grad_norm`` (reference :183).
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from oktopk_tpu.optim.schedules import SCHEDULES


@flax.struct.dataclass
class BertAdamState:
    step: jnp.ndarray
    m: any
    v: any


class BertAdam:
    def __init__(self, lr: float = 2e-4, warmup: float = 0.01,
                 t_total: int = -1, schedule: str = "warmup_linear",
                 b1: float = 0.9, b2: float = 0.999, eps: float = 1e-6,
                 weight_decay: float = 0.01, max_grad_norm: float = 1.0):
        self.lr, self.warmup, self.t_total = lr, warmup, t_total
        self.schedule_fn = SCHEDULES[schedule]
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm

    def init(self, params) -> BertAdamState:
        return BertAdamState(
            step=jnp.asarray(0, jnp.int32),
            m=jax.tree.map(jnp.zeros_like, params),
            v=jax.tree.map(jnp.zeros_like, params))

    def lr_t(self, step):
        if self.t_total > 0:
            x = step.astype(jnp.float32) / self.t_total
            return self.lr * self.schedule_fn(x, self.warmup)
        return jnp.asarray(self.lr, jnp.float32)

    def update(self, grads, state: BertAdamState, params=None):
        if self.max_grad_norm > 0:
            leaves = jax.tree.leaves(grads)
            gnorm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2)
                                 for g in leaves))
            scale = jnp.minimum(1.0, self.max_grad_norm / (gnorm + 1e-12))
            grads = jax.tree.map(lambda g: g * scale, grads)

        b1, b2 = self.b1, self.b2
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state.m, grads)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                         state.v, grads)
        lr_t = self.lr_t(state.step)

        def upd(m_, v_, p):
            u = m_ / (jnp.sqrt(v_) + self.eps)
            if self.weight_decay > 0 and p is not None:
                u = u + self.weight_decay * p
            return -lr_t * u

        updates = jax.tree.map(upd, m, v, params)
        return updates, BertAdamState(step=state.step + 1, m=m, v=v)


def bert_adam(**kw) -> BertAdam:
    return BertAdam(**kw)
