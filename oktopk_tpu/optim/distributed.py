"""The distributed training step: grads -> sparse allreduce -> update.

This replaces the reference's entire L3/L4 concurrency machinery
(SURVEY.md §3.1): the per-parameter autograd hooks
(VGG/distributed_optimizer.py:63-94), the background allreducer thread and
its two-queue handshake (VGG/allreducer.py:549, :1640-1643), and the
``synchronize()`` join (:96-105). Under XLA all of that is one traced
program: backward, reverse-layer-order bucket flatten (the analogue of the
reference's bucket merge, VGG/allreducer.py:272-330; with ``num_buckets=1``
the whole model is one bucket like the BERT variant's "myallreduce" flat
tensor, BERT/bert/allreducer.py:200), one sparse collective per bucket,
unflatten, optimizer update. Compute/communication overlap is XLA's async collective
scheduling instead of Python threads.

Local gradient accumulation (``nsteps_update``, reference
VGG/main_trainer.py:82-100) is a ``lax.scan`` over microbatches before the
single allreduce.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.collectives.registry import get_algorithm
from oktopk_tpu.collectives.state import SparseState, init_state
from oktopk_tpu.comm import compat
from oktopk_tpu.config import OkTopkConfig
from oktopk_tpu.obs.anatomy import phase_scope


@flax.struct.dataclass
class DistTrainState:
    """Replicated training state + per-worker sparse state (leading device
    axis on every SparseState leaf). ``local_momentum`` is the per-worker
    flat momentum buffer used only under momentum correction.
    ``health`` is the replicated :class:`resilience.guard.HealthState`
    (attempt/skip counters), present only when the step carries the
    anomaly guard or a fault plan. ``quality`` is the per-worker
    :class:`obs.metrics_buffer.QualityBuffer` fidelity ring (per-bucket
    tuple when bucketed, mirroring ``sparse_state``), present only when
    the step carries the in-jit quality taps; checkpoints saved before
    the field existed restore cleanly (checkpoint.py template merge)."""
    params: Any
    model_state: Any          # e.g. flax batch_stats collection
    opt_state: Any
    sparse_state: SparseState
    local_momentum: Any = None
    health: Any = None
    quality: Any = None


def flat_size(params) -> int:
    return int(sum(x.size for x in jax.tree.leaves(params)))


def bucket_partition(params, num_buckets: int):
    """Contiguous leaf-index buckets in REVERSE flattened order,
    greedily balanced by element count.

    Reference semantics: the allreducer consumes layer grads in reverse
    layer order as backward produces them and merges them into <=640 MiB
    buckets (VGG/allreducer.py:27,272-330) — bucket 0 holds the LAST
    layers, whose grads are ready first, so its collective can overlap the
    remaining backward (under XLA: independent collectives schedule
    against compute).

    Returns a list of leaf-index lists (ascending within each bucket).
    """
    sizes = [x.size for x in jax.tree.leaves(params)]
    total = sum(sizes)
    L = len(sizes)
    num_buckets = max(1, min(num_buckets, L))
    target = total / num_buckets
    buckets, cur, acc = [], [], 0
    for pos, i in enumerate(reversed(range(L))):   # last layers first
        cur.append(i)
        acc += sizes[i]
        leaves_left = L - pos - 1
        still_needed = num_buckets - len(buckets) - 1
        if len(buckets) < num_buckets - 1 and (
                acc >= target - 1e-9            # fair share reached, or
                or leaves_left == still_needed  # must close to keep every
        ):                                      # later bucket non-empty
            buckets.append(sorted(cur))
            cur, acc = [], 0
    buckets.append(sorted(cur))
    assert len(buckets) == num_buckets and all(buckets), buckets
    return buckets


def bucket_sizes(params, buckets):
    sizes = [x.size for x in jax.tree.leaves(params)]
    return [int(sum(sizes[i] for i in b)) for b in buckets]


def init_dist_state(params, model_state, optimizer, cfg: OkTopkConfig,
                    dtype=jnp.float32,
                    momentum_correction: bool = False,
                    opt_state: Any = None,
                    num_buckets: int = 1,
                    with_health: bool = False,
                    quality=None) -> DistTrainState:
    """``momentum_correction`` must be truthy iff the step builder gets a
    nonzero ``momentum_correction`` factor — the shard_map specs key off the
    presence of ``local_momentum``. Pass ``opt_state`` to carry over existing
    optimizer state (e.g. across an elastic resize) instead of allocating a
    fresh one. With ``num_buckets > 1`` the sparse state (and momentum) is a
    tuple of per-bucket states matching :func:`bucket_partition`.
    ``with_health`` must be truthy iff the step builder gets a guard or a
    fault plan — the shard_map specs key off the presence of ``health``.
    ``quality`` (an ``obs.quality.QualityConfig``) must likewise match the
    step builder's ``quality`` argument: it allocates the per-bucket
    fidelity rings the in-jit taps push into."""
    def batched(n_b):
        s = init_state(cfg.replace(n=n_b), dtype)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, (cfg.num_workers,) + x.shape), s)

    def qbatched():
        from oktopk_tpu.obs.metrics_buffer import init_buffer
        b = init_buffer(quality.every, quality.sig_bins, dtype)
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, (cfg.num_workers,) + x.shape), b)

    if num_buckets > 1:
        nbs = bucket_sizes(params, bucket_partition(params, num_buckets))
        s = tuple(batched(n_b) for n_b in nbs)
        mom = (tuple(jnp.zeros((cfg.num_workers, n_b), dtype)
                     for n_b in nbs) if momentum_correction else None)
        qual = (tuple(qbatched() for _ in nbs)
                if quality is not None else None)
    else:
        s = batched(cfg.n)
        mom = (jnp.zeros((cfg.num_workers, cfg.n), dtype)
               if momentum_correction else None)
        qual = qbatched() if quality is not None else None
    health = None
    if with_health:
        from oktopk_tpu.resilience.guard import init_health
        health = init_health(num_buckets)
    return DistTrainState(params=params, model_state=model_state,
                          opt_state=(optimizer.init(params)
                                     if opt_state is None else opt_state),
                          sparse_state=s, local_momentum=mom,
                          health=health, quality=qual)


def build_sparse_grad_step(
    loss_fn: Callable,
    optimizer,
    cfg: OkTopkConfig,
    mesh: Mesh,
    compressor: Union[str, Sequence[str]] = "oktopk",
    axis_name: str = "data",
    nsteps_update: int = 1,
    grad_clip: Optional[float] = None,
    warmup: bool = True,
    profile_norm: bool = False,
    momentum_correction: float = 0.0,
    num_buckets: int = 1,
    bucket_densities: Optional[Sequence[float]] = None,
    guard=None,
    fault_plan=None,
    quality=None,
):
    """Build the jitted distributed train step.

    Args:
      loss_fn: ``(params, model_state, batch, rng) -> (loss, (model_state,
        metrics))`` evaluated on the *local* microbatch shard.
      optimizer: object with ``init(params)`` / ``update(grads, state,
        params)`` (optim.sgd / optim.bert_adam / any optax transform).
      cfg: algorithm config; ``cfg.n`` must equal the flat parameter count.
      nsteps_update: local accumulation microsteps before one allreduce
        (reference VGG/main_trainer.py:85-89).
      grad_clip: optional global-norm clip applied to the *local* grad before
        the allreduce (reference LSTM/main_trainer.py:94-99).
      profile_norm: add an ``eps_vs_dense`` metric — the reference's
        PROFILING_NORM instrumentation (EPS = ‖dense−sparse‖₂/‖dense‖₂,
        VGG/allreducer.py:1072-1080). Costs one extra dense pmean per step.
      momentum_correction: DGC-style local momentum factor applied BEFORE
        compression (reference _DistributedOptimizer's momentum-correction
        option, VGG/distributed_optimizer.py:56,81-88). The optimizer should
        then be momentum-free SGD, since momentum is already folded into the
        compressed gradient stream.
      num_buckets: > 1 runs one sparse collective per reverse-layer-order
        bucket (reference <=640 MiB bucketing, VGG/allreducer.py:27,
        272-330) with per-bucket SparseState — bucket 0 depends only on
        the last layers' grads, so XLA can overlap its collective with the
        remaining backward. Selection becomes per-bucket top-k, exactly
        the reference's per-merged-group compression.
      compressor: one registry name for every bucket, or a sequence of
        ``num_buckets`` names — the per-bucket plan the autotuner
        (autotune/policy.py) produces. All variants trace into ONE jitted
        program; changing the plan means rebuilding the step.
      bucket_densities: optional per-bucket density overrides, parallel to
        the compressor sequence (the autotuner's chosen densities).
      guard: optional ``resilience.guard.GuardConfig`` — adds the in-step
        anomaly guard: per-bucket nonfinite/absurd-value counts are
        psum-agreed across replicas, and on any trip the optimizer update
        AND every bucket's compressor residual/threshold update roll back
        (bit-identical training state; only step counters and volume
        accounting advance). Emits ``step_skipped``/``steps_skipped``/
        ``bucket_anomalies`` metrics. Requires ``state.health``
        (``init_dist_state(with_health=True)``).
      fault_plan: optional ``resilience.faults.FaultPlan`` — bakes the
        plan's deterministic NaN/Inf gradient injection into the traced
        step (wire-payload faults install separately via
        ``collectives.wire.install_wire_fault``). Chaos drills only.
      quality: optional ``obs.quality.QualityConfig`` — adds the in-jit
        signal-fidelity taps: per-bucket compression error vs the
        pre-selection dense gradient, residual norm/growth, realised
        density, threshold drift and winner-index churn, pushed into the
        device-side ring in ``state.quality`` every step (guard-skipped
        steps included, flagged). Purely read-only on the training
        computation — the trajectory is bit-identical taps-on vs
        taps-off — and host-sync-free: the ring is drained only when the
        trainer flushes it (docs/OBSERVABILITY.md "Signal fidelity").
        Requires ``state.quality`` (``init_dist_state(quality=...)``).

    Returns ``step(state: DistTrainState, batch, rng) -> (state, metrics)``.
    ``batch`` leaves are [num_workers * nsteps_update * mb, ...] and get
    sharded over the data axis.
    """
    from oktopk_tpu.ops.compaction import resolve_use_pallas
    cfg = resolve_use_pallas(cfg, mesh)
    nb = max(1, num_buckets)
    names = ([compressor] * nb if isinstance(compressor, str)
             else list(compressor))
    if len(names) != nb:
        raise ValueError(
            f"compressor plan has {len(names)} entries for {nb} buckets")
    if bucket_densities is not None and len(bucket_densities) != nb:
        raise ValueError(
            f"bucket_densities has {len(bucket_densities)} entries for "
            f"{nb} buckets")
    algos = [get_algorithm(nm, warmup=warmup) for nm in names]
    has_health = guard is not None or fault_plan is not None
    if has_health:
        from oktopk_tpu.resilience import faults as _faults  # noqa: F401
        from oktopk_tpu.resilience import guard as _guard_mod
    has_quality = quality is not None
    if has_quality:
        from oktopk_tpu.obs import quality as _quality_mod

    def shard_fn(state: DistTrainState, batch, rng):
        if has_health and state.health is None:
            raise ValueError(
                "guard/fault_plan need state.health: build the state with "
                "init_dist_state(with_health=True)")
        if has_quality and state.quality is None:
            raise ValueError(
                "quality taps need state.quality: build the state with "
                "init_dist_state(quality=...)")
        rng = jax.random.fold_in(rng, lax.axis_index(axis_name))

        # --- local grads, with optional microbatch accumulation ---
        def micro(carry, mb):
            acc_grads, acc_loss, model_state, rng = carry
            rng, sub = jax.random.split(rng)
            (loss, (model_state, _)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, model_state, mb, sub)
            acc_grads = jax.tree.map(jnp.add, acc_grads, grads)
            return (acc_grads, acc_loss + loss, model_state, rng), None

        zero_grads = jax.tree.map(jnp.zeros_like, state.params)
        with phase_scope("fwd_bwd"):
            if nsteps_update > 1:
                mb_batch = jax.tree.map(
                    lambda x: x.reshape((nsteps_update, -1) + x.shape[1:]),
                    batch)
                (grads, loss, model_state, rng), _ = lax.scan(
                    micro, (zero_grads, 0.0, state.model_state, rng),
                    mb_batch)
                grads = jax.tree.map(lambda g: g / nsteps_update, grads)
                loss = loss / nsteps_update
            else:
                (grads, loss, model_state, rng), _ = micro(
                    (zero_grads, 0.0, state.model_state, rng), batch)

            if grad_clip is not None:
                gnorm = jnp.sqrt(sum(jnp.sum(g ** 2)
                                     for g in jax.tree.leaves(grads)))
                scale = jnp.minimum(1.0, grad_clip / (gnorm + 1e-12))
                grads = jax.tree.map(lambda g: g * scale, grads)

        # --- sparse allreduce of the gradient: one collective per
        # reverse-layer-order bucket. num_buckets == 1 degenerates to the
        # whole model as a single flat vector (the BERT variant's
        # "myallreduce" form, BERT/bert/allreducer.py:200); the outer state
        # layout stays a bare SparseState in that case for checkpoint
        # compatibility. ---
        buckets = bucket_partition(grads, num_buckets)  # static sizes
        leaves, treedef = jax.tree.flatten(grads)
        assert sum(x.size for x in leaves) == cfg.n, (
            f"cfg.n={cfg.n} != flat grad size "
            f"{sum(x.size for x in leaves)}")
        single = num_buckets <= 1
        states_in = ([state.sparse_state] if single
                     else list(state.sparse_state))
        moms_in = (([state.local_momentum] if single
                    else list(state.local_momentum))
                   if momentum_correction else None)
        quals_in = (([state.quality] if single else list(state.quality))
                    if has_quality else None)
        results = [None] * len(leaves)
        sp_olds, sp_news, new_moms, bad_counts = [], [], [], []
        absmaxes, qual_taps = [], []
        vol = lk = gk = wbytes = jnp.asarray(0.0, jnp.float32)
        eps_num = eps_den = jnp.asarray(0.0, jnp.float32)
        for bi, idxs in enumerate(buckets):
            # copy-free single-leaf bucket: reshape is a view under XLA,
            # while a 1-element concatenate still materialises a second
            # n-length buffer (and the matching slice-back below a third)
            if len(idxs) == 1:
                flat = leaves[idxs[0]].reshape(-1)
            else:
                flat = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
            over = {}
            if not single:
                over["n"] = int(flat.size)
                over["bucket_index"] = bi
            if bucket_densities is not None:
                over["density"] = float(bucket_densities[bi])
            cfg_b = cfg.replace(**over) if over else cfg
            sp = jax.tree.map(lambda x: x[0], states_in[bi])
            if fault_plan is not None:
                # chaos drill: deterministic NaN/Inf poisoning of this
                # bucket's local gradient, indexed by the monotonic
                # attempted-step counter (a guard skip must not freeze a
                # one-step fault into a permanent one)
                flat = _faults.inject_grad_faults(
                    fault_plan, flat, state.health.step,
                    lax.axis_index(axis_name), bi)
            if momentum_correction:
                flat = momentum_correction * moms_in[bi][0] + flat
                new_moms.append(flat[None])
            # bucket container scope: the collective's own phase scopes
            # nest inside it, so trace names carry the bucket id even for
            # algorithms annotated without one
            with phase_scope(bucket=bi):
                reduced, sp_new = algos[bi](flat, sp, cfg_b, axis_name)
            if has_quality:
                # fidelity tap (obs/quality.py): reference is the dense
                # gradient the selection approximated — exactly what this
                # worker handed the compressor (faults and momentum fold
                # included) plus its residual, pmean'd. Measured here
                # (pre-guard, observed values); committed into the ring
                # after the guard agrees on the skip flag.
                qb = jax.tree.map(lambda x: x[0], quals_in[bi])
                dense_q = lax.pmean(flat + sp.residual, axis_name)
                qual_taps.append((qb, _quality_mod.measure_bucket(
                    reduced, dense_q, sp_new, qb.prev_sig,
                    qb.prev_res_norm)))
            if guard is not None:
                bad_counts.append(
                    _guard_mod.local_anomaly_count(flat, reduced, guard))
                # peak reduced magnitude: the guard-pressure signal the
                # density-backoff policy watches (how close delivered
                # gradients crowd cfg.abs_limit without tripping it)
                absmaxes.append(jnp.max(jnp.abs(reduced)))
            if len(idxs) == 1:
                results[idxs[0]] = reduced.reshape(leaves[idxs[0]].shape)
            else:
                off = 0
                for i in idxs:
                    sz = leaves[i].size
                    results[i] = reduced[off:off + sz] \
                        .reshape(leaves[i].shape)
                    off += sz
            sp_olds.append(sp)
            sp_news.append(sp_new)
            vol = vol + sp_new.last_volume
            wbytes = wbytes + sp_new.last_wire_bytes
            lk = lk + sp_new.last_local_count
            gk = gk + sp_new.last_global_count
            if profile_norm:
                dense = lax.pmean(flat, axis_name)
                eps_num = eps_num + jnp.sum((dense - reduced) ** 2)
                eps_den = eps_den + jnp.sum(dense ** 2)
        grads = jax.tree.unflatten(treedef, results)
        if momentum_correction:
            new_momentum = new_moms[0] if single else tuple(new_moms)
        else:
            new_momentum = state.local_momentum
        grad_norm = jnp.sqrt(sum(jnp.sum(r ** 2) for r in results))
        # nonfinite reduced-gradient elements (the reference warns when
        # the gradient sparsity goes NaN, VGG/dl_trainer.py:608-609; a
        # count in the metrics makes the blow-up step identifiable)
        grad_nonfinite = sum(jnp.sum(~jnp.isfinite(r)) for r in results)
        eps = (jnp.sqrt(eps_num) / (jnp.sqrt(eps_den) + 1e-12)
               if profile_norm else None)

        # --- optimizer update (identical on every worker) ---
        with phase_scope("optimizer"):
            updates, opt_state = optimizer.update(grads, state.opt_state,
                                                  state.params)
            params = jax.tree.map(jnp.add, state.params, updates)

        metrics = {
            "loss": lax.pmean(loss, axis_name),
            "grad_norm": grad_norm,
            "grad_nonfinite": grad_nonfinite,
            "comm_volume": vol,
            "wire_bytes": wbytes,
            "local_k": lk,
            "global_k": gk,
        }
        if eps is not None:
            metrics["eps_vs_dense"] = eps

        # --- in-step anomaly guard (resilience/guard.py): agree on a
        # global skip flag, then make the whole step a training no-op —
        # optimizer update discarded, compressor residual/threshold
        # updates rolled back bucket-by-bucket so error feedback is never
        # poisoned. Step counters and wire-volume accounting still
        # advance (the skipped step consumed its batch and its wire). ---
        health = state.health
        if guard is not None:
            flags, any_bad = _guard_mod.agree(bad_counts, axis_name)
            params = _guard_mod.guarded(any_bad, state.params, params)
            opt_state = _guard_mod.guarded(any_bad, state.opt_state,
                                           opt_state)
            model_state = _guard_mod.guarded(any_bad, state.model_state,
                                             model_state)
            if momentum_correction:
                new_momentum = _guard_mod.guarded(
                    any_bad, state.local_momentum, new_momentum)
            sp_news = [
                _guard_mod.guarded(
                    any_bad,
                    old.replace(step=new.step,
                                volume_elems=new.volume_elems,
                                last_volume=new.last_volume,
                                wire_bytes=new.wire_bytes,
                                last_wire_bytes=new.last_wire_bytes,
                                last_local_count=new.last_local_count,
                                last_global_count=new.last_global_count),
                    new)
                for old, new in zip(sp_olds, sp_news)]
            health = _guard_mod.advance(health, any_bad, flags)
            metrics["step_skipped"] = any_bad.astype(jnp.int32)
            metrics["steps_skipped"] = health.steps_skipped
            metrics["bucket_anomalies"] = (flags > 0).astype(jnp.int32)
            # replicated (reduced is post-collective, identical on every
            # worker); NaN when the step carried nonfinites — consumers
            # treat the skip flag as authoritative there
            metrics["reduced_absmax"] = jnp.max(jnp.stack(absmaxes))
        elif has_health:
            # fault plan without a guard: the attempt counter still has
            # to advance or a one-step fault would re-inject forever
            health = _guard_mod.advance(
                health, jnp.asarray(False),
                jnp.zeros_like(health.bucket_trips))

        quality_out = state.quality
        if has_quality:
            # commit the taps AFTER the guard: the ring row always lands
            # (quality accounting advances on skips, exactly like the
            # wire accounting above) with the skip flag recorded, while
            # the step-over-step baselines freeze on skipped steps —
            # next step compares against the last COMMITTED state, which
            # is what the rollback restored
            skip = (any_bad if guard is not None
                    else jnp.asarray(False))
            new_quals = [
                jax.tree.map(
                    lambda x: x[None],
                    _quality_mod.commit(qb, sp_news[bi].step, scalars,
                                        skip))
                for bi, (qb, scalars) in enumerate(qual_taps)]
            quality_out = new_quals[0] if single else tuple(new_quals)

        new_sparse = [jax.tree.map(lambda x: x[None], s) for s in sp_news]
        sparse_out = new_sparse[0] if single else tuple(new_sparse)
        new_state = DistTrainState(
            params=params, model_state=model_state, opt_state=opt_state,
            sparse_state=sparse_out,
            local_momentum=new_momentum,
            health=health,
            quality=quality_out)
        return new_state, metrics

    state_specs = DistTrainState(
        params=P(), model_state=P(), opt_state=P(),
        sparse_state=P(axis_name),
        local_momentum=P(axis_name) if momentum_correction else None,
        health=P() if has_health else None,
        quality=P(axis_name) if has_quality else None)
    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(state_specs, P(axis_name), P()),
        out_specs=(state_specs, P()),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,))
