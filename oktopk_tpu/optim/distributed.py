"""The distributed training step: grads -> sparse allreduce -> update.

This replaces the reference's entire L3/L4 concurrency machinery
(SURVEY.md §3.1): the per-parameter autograd hooks
(VGG/distributed_optimizer.py:63-94), the background allreducer thread and
its two-queue handshake (VGG/allreducer.py:549, :1640-1643), and the
``synchronize()`` join (:96-105). Under XLA all of that is one traced
program: backward, flatten (``ravel_pytree`` — the analogue of the
reference's reverse-layer-order bucket merge, VGG/allreducer.py:272-330,
except the whole model is one bucket like the BERT variant's "myallreduce"
flat tensor, BERT/bert/allreducer.py:200), sparse collective, unflatten,
optimizer update. Compute/communication overlap is XLA's async collective
scheduling instead of Python threads.

Local gradient accumulation (``nsteps_update``, reference
VGG/main_trainer.py:82-100) is a ``lax.scan`` over microbatches before the
single allreduce.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.collectives.registry import get_algorithm
from oktopk_tpu.collectives.state import SparseState, init_state
from oktopk_tpu.config import OkTopkConfig


@flax.struct.dataclass
class DistTrainState:
    """Replicated training state + per-worker sparse state (leading device
    axis on every SparseState leaf). ``local_momentum`` is the per-worker
    flat momentum buffer used only under momentum correction."""
    params: Any
    model_state: Any          # e.g. flax batch_stats collection
    opt_state: Any
    sparse_state: SparseState
    local_momentum: Any = None


def flat_size(params) -> int:
    return int(sum(x.size for x in jax.tree.leaves(params)))


def init_dist_state(params, model_state, optimizer, cfg: OkTopkConfig,
                    dtype=jnp.float32,
                    momentum_correction: bool = False,
                    opt_state: Any = None) -> DistTrainState:
    """``momentum_correction`` must be truthy iff the step builder gets a
    nonzero ``momentum_correction`` factor — the shard_map specs key off the
    presence of ``local_momentum``. Pass ``opt_state`` to carry over existing
    optimizer state (e.g. across an elastic resize) instead of allocating a
    fresh one."""
    s = init_state(cfg, dtype)
    s = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (cfg.num_workers,) + x.shape), s)
    mom = (jnp.zeros((cfg.num_workers, cfg.n), dtype)
           if momentum_correction else None)
    return DistTrainState(params=params, model_state=model_state,
                          opt_state=(optimizer.init(params)
                                     if opt_state is None else opt_state),
                          sparse_state=s, local_momentum=mom)


def build_sparse_grad_step(
    loss_fn: Callable,
    optimizer,
    cfg: OkTopkConfig,
    mesh: Mesh,
    compressor: str = "oktopk",
    axis_name: str = "data",
    nsteps_update: int = 1,
    grad_clip: Optional[float] = None,
    warmup: bool = True,
    profile_norm: bool = False,
    momentum_correction: float = 0.0,
):
    """Build the jitted distributed train step.

    Args:
      loss_fn: ``(params, model_state, batch, rng) -> (loss, (model_state,
        metrics))`` evaluated on the *local* microbatch shard.
      optimizer: object with ``init(params)`` / ``update(grads, state,
        params)`` (optim.sgd / optim.bert_adam / any optax transform).
      cfg: algorithm config; ``cfg.n`` must equal the flat parameter count.
      nsteps_update: local accumulation microsteps before one allreduce
        (reference VGG/main_trainer.py:85-89).
      grad_clip: optional global-norm clip applied to the *local* grad before
        the allreduce (reference LSTM/main_trainer.py:94-99).
      profile_norm: add an ``eps_vs_dense`` metric — the reference's
        PROFILING_NORM instrumentation (EPS = ‖dense−sparse‖₂/‖dense‖₂,
        VGG/allreducer.py:1072-1080). Costs one extra dense pmean per step.
      momentum_correction: DGC-style local momentum factor applied BEFORE
        compression (reference _DistributedOptimizer's momentum-correction
        option, VGG/distributed_optimizer.py:56,81-88). The optimizer should
        then be momentum-free SGD, since momentum is already folded into the
        compressed gradient stream.

    Returns ``step(state: DistTrainState, batch, rng) -> (state, metrics)``.
    ``batch`` leaves are [num_workers * nsteps_update * mb, ...] and get
    sharded over the data axis.
    """
    from oktopk_tpu.ops.compaction import resolve_use_pallas
    cfg = resolve_use_pallas(cfg, mesh)
    algo = get_algorithm(compressor, warmup=warmup)

    def shard_fn(state: DistTrainState, batch, rng):
        sparse = jax.tree.map(lambda x: x[0], state.sparse_state)
        rng = jax.random.fold_in(rng, lax.axis_index(axis_name))

        # --- local grads, with optional microbatch accumulation ---
        def micro(carry, mb):
            acc_grads, acc_loss, model_state, rng = carry
            rng, sub = jax.random.split(rng)
            (loss, (model_state, _)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, model_state, mb, sub)
            acc_grads = jax.tree.map(jnp.add, acc_grads, grads)
            return (acc_grads, acc_loss + loss, model_state, rng), None

        zero_grads = jax.tree.map(jnp.zeros_like, state.params)
        if nsteps_update > 1:
            mb_batch = jax.tree.map(
                lambda x: x.reshape((nsteps_update, -1) + x.shape[1:]), batch)
            (grads, loss, model_state, rng), _ = lax.scan(
                micro, (zero_grads, 0.0, state.model_state, rng), mb_batch)
            grads = jax.tree.map(lambda g: g / nsteps_update, grads)
            loss = loss / nsteps_update
        else:
            (grads, loss, model_state, rng), _ = micro(
                (zero_grads, 0.0, state.model_state, rng), batch)

        if grad_clip is not None:
            gnorm = jnp.sqrt(sum(jnp.sum(g ** 2)
                                 for g in jax.tree.leaves(grads)))
            scale = jnp.minimum(1.0, grad_clip / (gnorm + 1e-12))
            grads = jax.tree.map(lambda g: g * scale, grads)

        # --- sparse allreduce of the flat gradient ---
        flat, unravel = ravel_pytree(grads)
        assert flat.size == cfg.n, (
            f"cfg.n={cfg.n} != flat grad size {flat.size}")
        if momentum_correction:
            mom = momentum_correction * state.local_momentum[0] + flat
            flat = mom
            new_momentum = mom[None]
        else:
            new_momentum = state.local_momentum
        reduced, sparse = algo(flat, sparse, cfg, axis_name)
        grads = unravel(reduced)

        # --- optimizer update (identical on every worker) ---
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = jax.tree.map(jnp.add, state.params, updates)

        metrics = {
            "loss": lax.pmean(loss, axis_name),
            "grad_norm": jnp.linalg.norm(reduced),
            "comm_volume": sparse.last_volume,
            "local_k": sparse.last_local_count,
            "global_k": sparse.last_global_count,
        }
        if profile_norm:
            dense = lax.pmean(flat, axis_name)
            metrics["eps_vs_dense"] = (
                jnp.linalg.norm(dense - reduced)
                / (jnp.linalg.norm(dense) + 1e-12))
        new_state = DistTrainState(
            params=params, model_state=model_state, opt_state=opt_state,
            sparse_state=jax.tree.map(lambda x: x[None], sparse),
            local_momentum=new_momentum)
        return new_state, metrics

    state_specs = DistTrainState(
        params=P(), model_state=P(), opt_state=P(),
        sparse_state=P(axis_name),
        local_momentum=P(axis_name) if momentum_correction else None)
    mapped = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(state_specs, P(axis_name), P()),
        out_specs=(state_specs, P()),
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(0,))
