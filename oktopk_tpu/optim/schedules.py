"""LR schedules.

- BertAdam's warmup schedules (reference
  BERT/bert/transformers/optimization.py:41-58: warmup_cosine,
  warmup_constant, warmup_linear over progress x = step / t_total).
- The CNN multi-step decay the reference trainer applies
  (VGG/dl_trainer.py:507-570).
"""

from __future__ import annotations

import jax.numpy as jnp


def warmup_cosine(x, warmup=0.002):
    return jnp.where(x < warmup, x / warmup,
                     0.5 * (1.0 + jnp.cos(jnp.pi * x)))


def warmup_constant(x, warmup=0.002):
    return jnp.where(x < warmup, x / warmup, 1.0)


def warmup_linear(x, warmup=0.002):
    return jnp.where(x < warmup, x / warmup, jnp.maximum(1.0 - x, 0.0))


SCHEDULES = {
    "warmup_cosine": warmup_cosine,
    "warmup_constant": warmup_constant,
    "warmup_linear": warmup_linear,
}


def multistep_lr(base_lr: float, milestones, gamma: float = 0.1):
    """Step decay at epoch milestones (reference VGG/dl_trainer.py:507-570
    decays lr at fixed epoch boundaries)."""
    ms = jnp.asarray(milestones)

    def schedule(epoch):
        drops = jnp.sum(epoch >= ms)
        return base_lr * (gamma ** drops)

    return schedule
