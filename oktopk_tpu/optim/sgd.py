"""SGD with momentum / weight decay / nesterov.

Exact semantics of the reference's custom ``_step``
(VGG/distributed_optimizer.py:107-145), which reimplements torch SGD on the
allreduced sparse gradients:

    d_p = grad + weight_decay * p
    buf = momentum * buf + d_p                  (dampening = 0)
    d_p = d_p + momentum * buf   if nesterov else buf
    p  -= lr * d_p
"""

from __future__ import annotations

from typing import Callable, Union

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class SGDState:
    step: jnp.ndarray
    momentum_buf: any = flax.struct.field(default=None)


class SGD:
    def __init__(self, lr: Union[float, Callable], momentum: float = 0.9,
                 weight_decay: float = 0.0, nesterov: bool = False):
        self.lr = lr if callable(lr) else (lambda step: lr)
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov

    def init(self, params) -> SGDState:
        buf = jax.tree.map(jnp.zeros_like, params) if self.momentum else None
        return SGDState(step=jnp.asarray(0, jnp.int32), momentum_buf=buf)

    def update(self, grads, state: SGDState, params=None):
        lr = self.lr(state.step)
        wd, m = self.weight_decay, self.momentum

        if wd:
            grads = jax.tree.map(lambda g, p: g + wd * p, grads, params)
        if m:
            buf = jax.tree.map(lambda b, g: m * b + g,
                               state.momentum_buf, grads)
            if self.nesterov:
                d_p = jax.tree.map(lambda g, b: g + m * b, grads, buf)
            else:
                d_p = buf
        else:
            buf, d_p = state.momentum_buf, grads
        updates = jax.tree.map(lambda d: -lr * d, d_p)
        return updates, SGDState(step=state.step + 1, momentum_buf=buf)


def sgd(lr, momentum: float = 0.9, weight_decay: float = 0.0,
        nesterov: bool = False) -> SGD:
    return SGD(lr, momentum, weight_decay, nesterov)
