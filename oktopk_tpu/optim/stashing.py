"""Weight stashing for pipeline-parallel training (PipeDream-style).

Reference parity target: C9 — the pipeline weight-versioning optimizers
(reference ``BERT/optimizer_with_stashing.py:19``
``OptimizerWithStashing``, ``BERT/optimizer_with_stashing_and_aggregation.py:19``
``OptimizerWithStashingAndAggregation``, ``BERT/optimizer.py:19``,
``BERT/optimizer_with_aggregation.py``), validated there by the repo's only
true unit tests (``BERT/tests/backprop/sgd_with_stashing.py:28-107``).

Semantics (ported exactly, re-expressed functionally):

- A ring buffer ("queue") of the last ``num_versions`` parameter versions,
  initialised with ``num_versions`` clones of the initial params
  (reference ``initialize_queue``, optimizer_with_stashing.py:63-68).
- ``backward_params``: the OLDEST version in the queue (``queue[0]``) — the
  weights a delayed backward pass must see so its gradient matches the
  forward that produced the activations
  (reference ``load_backward_params``, :115-117).
- ``forward_params``: the NEWEST version (``queue[-1]``) — what new
  minibatches enter the pipe with, and what the optimizer step updates
  (reference ``load_forward_params`` :119-121 and ``_load_step_params``).
- ``step``: divide grads by ``update_interval`` (reference
  optimizer_with_stashing.py:144-146), apply the base optimizer update to
  the newest version, bump the version counter, and push the result into the
  ring (evicting the oldest; reference :152-157).

With ``num_versions == 1`` the queue collapses and forward == backward ==
latest: plain SGD (the reference test's ``test(1, [False, False])`` case).

The aggregation variant (``AggregatingStash``) reproduces
``OptimizerWithStashingAndAggregation``: ``num_versions`` is fixed at 2, and
the version used for a given forward/backward pass is selected by
``counter // update_interval`` (reference …_and_aggregation.py:117-147), with
the version bump once per ``update_interval`` steps (:157-178).

Everything is a pure pytree transform: state in, state out — no module
mutation, no deque of cloned state_dicts. The queue is a stacked leading
axis (``[V, ...]`` per leaf), so stash rotation is one ``concatenate`` per
leaf and the whole thing jits.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class StashState(NamedTuple):
    """Ring buffer of parameter versions.

    queue: pytree whose leaves have a leading axis of size ``num_versions``;
      ``leaf[0]`` is the oldest version, ``leaf[-1]`` the newest.
    latest_version: int32 scalar — number of optimizer steps taken
      (reference ``Version`` counter).
    """
    queue: Any
    latest_version: jnp.ndarray


def stash_init(params, num_versions: int) -> StashState:
    """Fill the queue with ``num_versions`` copies of ``params``
    (reference ``initialize_queue``)."""
    if num_versions < 1:
        raise ValueError(f"num_versions must be >= 1, got {num_versions}")
    queue = jax.tree.map(
        lambda p: jnp.broadcast_to(p[None], (num_versions,) + p.shape), params)
    return StashState(queue=queue, latest_version=jnp.int32(0))


def backward_params(state: StashState):
    """Oldest stashed version — weights for a delayed backward pass
    (reference ``load_backward_params``)."""
    return jax.tree.map(lambda q: q[0], state.queue)


def forward_params(state: StashState):
    """Newest version — weights for new forward passes and for the step
    (reference ``load_forward_params`` / ``_load_step_params``)."""
    return jax.tree.map(lambda q: q[-1], state.queue)


def stash_step(state: StashState, grads, update_fn: Callable,
               opt_state, update_interval: int = 1):
    """One optimizer step with weight stashing.

    Args:
      state: current stash.
      grads: gradient pytree (matching one version's structure).
      update_fn: ``(params, grads, opt_state) -> (new_params, new_opt_state)``
        — the base optimizer (e.g. ``sgd.sgd_update``; reference
        ``base_optimizer.step``).
      opt_state: base optimizer state.
      update_interval: grads are pre-divided by this
        (reference optimizer_with_stashing.py:144-146).

    Returns: ``(new_stash_state, new_opt_state)``.
    """
    params = forward_params(state)
    if update_interval != 1:
        grads = jax.tree.map(lambda g: g / update_interval, grads)
    new_params, new_opt_state = update_fn(params, grads, opt_state)
    # push newest, evict oldest (deque.append with maxlen, reference :157)
    queue = jax.tree.map(
        lambda q, p: jnp.concatenate([q[1:], p[None]], axis=0),
        state.queue, new_params)
    return (StashState(queue=queue, latest_version=state.latest_version + 1),
            new_opt_state)


class AggregatingStashState(NamedTuple):
    """State for the stashing-and-aggregation variant (2 fixed versions +
    forward/backward counters; reference …_and_aggregation.py:36-55)."""
    stash: StashState
    forward_counter: jnp.ndarray
    backward_counter: jnp.ndarray


def aggregating_init(params, update_interval: int) -> AggregatingStashState:
    # num_stages==1 degenerates to no stashing in the reference (:40-42);
    # callers express that by update_interval == 1, which makes version
    # selection always pick the newest.
    del update_interval
    return AggregatingStashState(
        stash=stash_init(params, num_versions=2),
        forward_counter=jnp.int32(0),
        backward_counter=jnp.int32(0))


def _select_version(state: AggregatingStashState, counter,
                    update_interval: int):
    """Reference …_and_aggregation.py:117-147: desired version is
    ``max(counter // update_interval - 1, 0)``; the queue holds versions
    ``[latest-1, latest]`` (or ``[0, 0]`` before any step)."""
    desired = jnp.maximum(counter // update_interval - 1, 0)
    latest = state.stash.latest_version
    newest_tree = forward_params(state.stash)
    oldest_tree = backward_params(state.stash)
    take_newest = desired >= latest
    return jax.tree.map(
        lambda new, old: jnp.where(take_newest, new, old),
        newest_tree, oldest_tree)


def aggregating_forward_params(state: AggregatingStashState,
                               update_interval: int):
    """Params for the next forward pass; bumps the forward counter."""
    params = _select_version(state, state.forward_counter, update_interval)
    new_state = state._replace(forward_counter=state.forward_counter + 1)
    return params, new_state


def aggregating_backward_params(state: AggregatingStashState,
                                update_interval: int):
    """Params for the next backward pass; bumps the backward counter."""
    params = _select_version(state, state.backward_counter, update_interval)
    new_state = state._replace(backward_counter=state.backward_counter + 1)
    return params, new_state


def aggregating_step(state: AggregatingStashState, grads,
                     update_fn: Callable, opt_state,
                     update_interval: int):
    """Step once per aggregation window: grads (already summed over the
    window by the caller) are divided by ``update_interval``
    (reference …_and_aggregation.py grad scaling), applied to the newest
    version, and the ring rotates."""
    new_stash, new_opt_state = stash_step(
        state.stash, grads, update_fn, opt_state,
        update_interval=update_interval)
    return state._replace(stash=new_stash), new_opt_state
