"""Parallelism beyond data-parallel.

The reference is data-parallel only: TP/SP/EP are absent and its PipeDream
pipeline machinery ships disabled (stage maps commented out, configs
single-stage — reference BERT/runtime.py:156-273, SURVEY.md §2.3). This
package carries (a) a working GPipe-style pipeline equivalent to the
machinery the reference ships (microbatch flushes, recompute), and (b) the
TPU-first extensions the reference lacks but a TPU framework needs as
first-class citizens: ring-attention sequence/context parallelism over a
``seq`` mesh axis. Both are flagged as extensions in docs where they exceed
reference parity (SURVEY.md §5.7).
"""

from oktopk_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_self_attention,
)
from oktopk_tpu.parallel.pipeline import gpipe_apply  # noqa: F401
