"""Expert-parallel Mixture-of-Experts BERT over an ``expert`` mesh axis.

The reference has no expert parallelism (SURVEY.md §2.3 — absent); this is
a TPU-side extension completing the mesh-axes story (data x pipe x seq x
model x expert). Each encoder layer's FFN becomes a Switch-style top-1
MoE in the GShard formulation — the TPU-canonical shape where routing is
einsums over a fixed-capacity dispatch tensor and the cross-device hop is
ONE ``lax.all_to_all`` each way:

  tokens [n, H] -> gate top-1 -> dispatch one-hot [n, E, C]
    -> einsum dispatch: expert inputs [E, C, H]
    -> all_to_all over the leading expert-group dim (tokens ride ICI to
       the rank owning their expert; E = P * E_local)
    -> batched expert FFN einsum [E_local, P*C, H]
    -> all_to_all back -> combine einsum weighted by the gate prob.

Fixed capacity ``C`` per (expert, source rank) with overflow dropped is
the same static-shape discipline as the sparse collectives' capacity
buffers (ops/select.py): a dropped token contributes 0 and passes through
the residual connection (standard Switch behavior). The Switch
load-balance auxiliary loss keeps routing spread.

The batch is sharded over the ``expert`` axis (data and expert
parallelism folded on one axis, as in Switch), attention and everything
outside the FFNs stay replicated. ``experts_from_dense`` tiles a dense
``BertForPreTraining`` FFN into E identical experts, making the MoE loss
equivalence-testable against the single-module oracle: with identical
experts and no overflow, ANY routing reproduces the dense FFN exactly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.comm import compat

from oktopk_tpu.models.bert import BertConfig
from oktopk_tpu.parallel.bert_seq import _dense, _layer_norm


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 4
    capacity_factor: float = 1.25   # C = ceil(n * factor / E) per rank
    aux_weight: float = 0.01        # Switch load-balance loss weight


def experts_from_dense(params, num_experts: int,
                       gate_scale: float = 0.0, seed: int = 0):
    """(single-module params) -> (moe_stack, shared).

    Every layer's intermediate/output FFN is tiled into ``num_experts``
    identical experts (leading [E] axis) plus a gate; everything else goes
    to ``shared``. With the default zero gate, identical experts + no
    overflow make the MoE forward equal the dense forward for any routing
    — the equivalence oracle. REAL training must pass ``gate_scale > 0``:
    a zero gate gives uniform probs, argmax breaks the tie toward expert 0
    for every token, and the default capacity factor then drops most of
    the batch while experts 1..E-1 starve (Switch/GShard init the router
    with small noise for exactly this reason)."""
    gate_rng = jax.random.PRNGKey(seed)
    enc = params["bert"]["encoder"]
    moe_layers, sh_layers = {}, {}
    for name, lp in enc.items():
        tile = lambda x: jnp.broadcast_to(
            x[None], (num_experts,) + x.shape).copy()
        hidden = lp["intermediate"]["kernel"].shape[0]
        moe_layers[name] = {
            "wi": tile(lp["intermediate"]["kernel"]),   # [E, H, F]
            "bi": tile(lp["intermediate"]["bias"]),     # [E, F]
            "wo": tile(lp["output"]["kernel"]),         # [E, F, H]
            "bo": tile(lp["output"]["bias"]),           # [E, H]
        }
        gate_rng, sub = jax.random.split(gate_rng)
        gate = gate_scale * jax.random.normal(
            sub, (hidden, num_experts), jnp.float32) if gate_scale else \
            jnp.zeros((hidden, num_experts), jnp.float32)
        sh_layers[name] = {
            "attention": lp["attention"],
            "attention_ln": lp["attention_ln"],
            "output_ln": lp["output_ln"],
            "gate": gate,
        }
    shared = {
        "embeddings": params["bert"]["embeddings"],
        "pooler": params["bert"]["pooler"],
        "mlm_dense": params["mlm_dense"],
        "mlm_ln": params["mlm_ln"],
        "mlm_bias": params["mlm_bias"],
        "nsp": params["nsp"],
        "layers": sh_layers,
    }
    return moe_layers, shared


def _attention(p, x, attn_mask):
    """Plain replicated multi-head attention (flax param layout, as
    models/bert.py)."""
    def proj(pp):
        return jnp.einsum("bte,ehd->bthd", x, pp["kernel"]) + pp["bias"]

    q, k, v = proj(p["query"]), proj(p["key"]), proj(p["value"])
    d = q.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", q * (d ** -0.5), k)
    s = jnp.where(attn_mask, s, jnp.asarray(-1e30, s.dtype))
    o = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(s, axis=-1), v)
    return jnp.einsum("bthd,hde->bte", o, p["out"]["kernel"]) \
        + p["out"]["bias"]


def moe_ffn(experts_local, gate, x, mcfg: MoEConfig, axis_name,
            stats_axes=None):
    """GShard top-1 MoE FFN inside ``shard_map``.

    experts_local: this rank's expert stack (leaves [E_local, ...]);
    gate [H, E] replicated; x [b, T, H] this rank's batch shard. Returns
    (y [b, T, H], aux_loss scalar — the Switch load-balance term with
    f/p statistics averaged over ``stats_axes``, default the expert axis
    only). The aux is NONLINEAR in f/p (sum of products), so global
    semantics require globally averaged STATS — a mean of per-shard aux
    values is a different objective (mean of products != product of
    means)."""
    Pn = compat.axis_size(axis_name)
    E = mcfg.num_experts
    e_local = experts_local["wi"].shape[0]
    assert e_local * Pn == E, (e_local, Pn, E)
    b, T, H = x.shape
    n = b * T
    C = max(1, int(-(-n * mcfg.capacity_factor // E)))

    xt = x.reshape(n, H)
    logits = jnp.einsum("nh,he->ne", xt, gate)
    probs = jax.nn.softmax(logits, axis=-1)
    e_star = jnp.argmax(probs, axis=-1)                     # [n]
    g = jnp.take_along_axis(probs, e_star[:, None], 1)[:, 0]

    # Switch load-balance aux: E * sum_e f_e * p_e, f/p averaged globally
    axes = (axis_name,) if stats_axes is None else stats_axes
    onehot = jax.nn.one_hot(e_star, E, dtype=xt.dtype)      # [n, E]
    f_e = lax.pmean(jnp.mean(onehot, axis=0), axes)
    p_e = lax.pmean(jnp.mean(probs, axis=0), axes)
    aux = E * jnp.sum(f_e * p_e)

    # position of each token within its expert's capacity (per source rank)
    pos = jnp.cumsum(onehot, axis=0) - onehot               # [n, E] excl.
    pos = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [n]
    keep = pos < C
    # dispatch one-hot [n, E, C]: token n -> (its expert, its slot)
    disp = (onehot * keep[:, None])[:, :, None] \
        * jax.nn.one_hot(pos, C, dtype=xt.dtype)[:, None, :]

    xin = jnp.einsum("nec,nh->ech", disp, xt)               # [E, C, H]
    # ship capacity blocks to expert owners: [E=P*El, C, H] -> regroup so
    # the all_to_all splits the leading dim across ranks
    xin = all_to_all_leading(xin, Pn, e_local, axis_name)   # [P, El, C, H]
    xin = xin.transpose(1, 0, 2, 3).reshape(e_local, Pn * C, H)

    h = jnp.einsum("ekh,ehf->ekf", xin, experts_local["wi"]) \
        + experts_local["bi"][:, None]
    h = jax.nn.gelu(h, approximate=False)
    y = jnp.einsum("ekf,efh->ekh", h, experts_local["wo"]) \
        + experts_local["bo"][:, None]

    y = y.reshape(e_local, Pn, C, H).transpose(1, 0, 2, 3)  # [P, El, C, H]
    y = all_to_all_leading_back(y, Pn, e_local, axis_name)  # [E, C, H]
    out = jnp.einsum("nec,ech->nh", disp, y) * g[:, None]
    return out.reshape(b, T, H), aux


def all_to_all_leading(x, Pn, e_local, axis_name):
    """[E=P*El, C, H] -> [P, El, C, H] where output row p holds rank p's
    capacity block for this rank's experts."""
    x = x.reshape(Pn, e_local, *x.shape[1:])
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)


def all_to_all_leading_back(y, Pn, e_local, axis_name):
    """Inverse of :func:`all_to_all_leading`."""
    y = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                       tiled=False)
    return y.reshape(Pn * e_local, *y.shape[2:])


def bert_moe_loss(moe_layers, shared, batch, cfg: BertConfig,
                  mcfg: MoEConfig, axis_name: str = "expert",
                  data_axis=None, stats_data_axis=None):
    """Batch-sharded MLM+NSP+aux loss with expert-parallel MoE FFNs
    (inside shard_map; ``moe_layers`` leaves are this rank's expert
    shards, ``batch`` leaves this rank's batch shard). With ``data_axis``
    (the composed data x expert mesh) experts are replicated over data —
    their gradients psum across it in the shard_map transpose — and the
    loss reductions span both axes; the dispatch all_to_all stays within
    each data row's expert group. ``stats_data_axis`` extends the aux
    f/p statistics over a data axis even when the MLM/NSP reductions stay
    row-local (``data_axis=None``) — the sparse composition needs
    per-row losses but the GLOBAL load-balance objective."""
    import optax

    axes = (axis_name,) if data_axis is None else (data_axis, axis_name)
    sda = stats_data_axis if stats_data_axis is not None else data_axis
    stats_axes = (axis_name,) if sda is None else (axis_name, sda)

    ids = batch["input_ids"]
    B, T = ids.shape
    emb = shared["embeddings"]
    positions = jnp.arange(T)[None, :]
    x = (emb["word_embeddings"]["embedding"][ids]
         + emb["position_embeddings"]["embedding"][positions]
         + emb["token_type_embeddings"]["embedding"][batch["token_type_ids"]])
    x = _layer_norm(emb["LayerNorm_0"], x, cfg.layer_norm_eps)

    mask = batch["attention_mask"][:, None, None, :].astype(bool)
    aux_total = jnp.float32(0.0)
    for i in range(cfg.num_layers):
        lp = moe_layers[f"layer_{i}"]
        sh = shared["layers"][f"layer_{i}"]
        y = _attention(sh["attention"], x, mask)
        x = _layer_norm(sh["attention_ln"], x + y, cfg.layer_norm_eps)
        h, aux = moe_ffn(lp, sh["gate"], x, mcfg, axis_name,
                         stats_axes=stats_axes)
        aux_total = aux_total + aux
        x = _layer_norm(sh["output_ln"], x + h, cfg.layer_norm_eps)

    pooled = jnp.tanh(_dense(shared["pooler"], x[:, 0]))
    h = _dense(shared["mlm_dense"], x)
    h = jax.nn.gelu(h, approximate=False)
    h = _layer_norm(shared["mlm_ln"], h, cfg.layer_norm_eps)
    table = emb["word_embeddings"]["embedding"]
    mlm = (jnp.einsum("bth,vh->btv", h, table.astype(cfg.dtype))
           + shared["mlm_bias"]).astype(jnp.float32)
    nsp = _dense(shared["nsp"], pooled).astype(jnp.float32)

    lmask = (batch["mlm_labels"] >= 0).astype(jnp.float32)
    safe = jnp.maximum(batch["mlm_labels"], 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(mlm, safe)
    num = lax.psum(jnp.sum(per_tok * lmask), axes)
    den = lax.psum(jnp.sum(lmask), axes)
    mlm_loss = num / jnp.maximum(den, 1.0)
    nsp_ce = optax.softmax_cross_entropy_with_integer_labels(
        nsp, batch["nsp_labels"])
    nsp_loss = lax.pmean(nsp_ce.mean(), axes)
    return mlm_loss + nsp_loss \
        + mcfg.aux_weight * aux_total / cfg.num_layers


def make_moe_mesh(num_shards: int, devices=None, data_size: int = 1) -> Mesh:
    """1-D ("expert",) mesh, or 2-D ("data", "expert") when
    ``data_size > 1`` (experts replicated over data)."""
    import numpy as np
    devices = list(devices if devices is not None else jax.devices())
    need = num_shards * data_size
    if len(devices) < need:
        raise ValueError(f"expert parallelism needs {need} devices, "
                         f"have {len(devices)}")
    if data_size > 1:
        return Mesh(np.asarray(devices[:need]).reshape(data_size,
                                                       num_shards),
                    ("data", "expert"))
    return Mesh(np.asarray(devices[:num_shards]), ("expert",))


def build_moe_loss(cfg: BertConfig, mcfg: MoEConfig, mesh: Mesh,
                   axis_name: str = "expert"):
    """jit ``(moe_stack, shared, batch) -> loss``: moe_stack sharded on
    the leading expert dim (replicated over data when the mesh has that
    axis), batch sharded on the leading batch dim over data x expert,
    shared replicated."""
    data_axis = "data" if "data" in mesh.axis_names else None
    batch_spec = P(axis_name) if data_axis is None \
        else P((data_axis, axis_name))

    def shard_fn(moe_layers, shared, batch):
        return bert_moe_loss(moe_layers, shared, batch, cfg, mcfg,
                             axis_name, data_axis=data_axis)

    mapped = compat.shard_map(shard_fn, mesh=mesh,
                              in_specs=(P(axis_name), P(), batch_spec),
                              out_specs=P())
    return jax.jit(mapped)


def build_moe_sparse_train_step(cfg: BertConfig, mcfg: MoEConfig,
                                mesh: Mesh, optimizer, algo_cfg,
                                compressor: str = "oktopk",
                                warmup: bool = True,
                                axis_name: str = "expert",
                                data_axis: str = "data"):
    """Sparse DP composed with expert parallelism: jit ``((moe, shared),
    (moe_sstate, shared_sstate), opt_state, batch) -> (...)`` on a
    (data, expert) mesh.

    Completes the sparse x {seq, pipe, expert} composition matrix. Each
    data row computes its own gradient (the loss psums span the expert
    axis only), then two sparse collectives run over ``data``: one on the
    row's local expert-shard flat gradient (per-(data rank, expert shard)
    SparseState), one on the shared bucket (whose cotangents arrive
    expert-complete from the AD transpose — no explicit psum, see
    bert_pipeline.py). Replica layout as in the other compositions:
    moe leaves [dp, E, ...] (sharded data x expert), shared [dp, ...]."""
    from oktopk_tpu.collectives.registry import get_algorithm
    from oktopk_tpu.ops.compaction import resolve_use_pallas
    from oktopk_tpu.utils.flatten import flatten_tree, unflatten_tree

    algo_cfg = resolve_use_pallas(algo_cfg, mesh)
    algo_cfg = algo_cfg.replace(num_workers=int(mesh.shape[data_axis]))
    algo = get_algorithm(compressor, warmup=warmup)

    def shard_fn(params, sstates, opt_states, batch):
        moe, shared = params
        moe_ss, shared_ss = sstates
        opt_moe_st, opt_shared_st = opt_states
        row = lambda t: jax.tree.map(lambda x: x[0], t)
        moe_l, shared_l = row(moe), row(shared)
        my_moe_ss = jax.tree.map(lambda x: x[0, 0], moe_ss)
        my_shared_ss = row(shared_ss)
        # moe opt state is vmapped-per-expert (init_moe_sparse_opt), so
        # its every leaf carries the expert dim the spec shards
        opt_moe, opt_shared = row(opt_moe_st), row(opt_shared_st)

        loss, (g_moe, g_shared) = jax.value_and_grad(
            lambda m, s: bert_moe_loss(m, s, batch, cfg, mcfg, axis_name,
                                       data_axis=None,
                                       stats_data_axis=data_axis),
            argnums=(0, 1))(moe_l, shared_l)

        flat_m, leaves_m, td_m = flatten_tree(g_moe)
        cfg_m = algo_cfg.replace(n=int(flat_m.size))
        red_m, my_moe_ss = algo(flat_m, my_moe_ss, cfg_m, data_axis)
        g_moe = unflatten_tree(red_m, leaves_m, td_m)
        flat_s, leaves_s, td_s = flatten_tree(g_shared)
        cfg_s = algo_cfg.replace(n=int(flat_s.size))
        red_s, my_shared_ss = algo(flat_s, my_shared_ss, cfg_s, data_axis)
        g_shared = unflatten_tree(red_s, leaves_s, td_s)

        upd_m, opt_moe = jax.vmap(optimizer.update)(g_moe, opt_moe, moe_l)
        moe_l = jax.tree.map(jnp.add, moe_l, upd_m)
        upd_s, opt_shared = optimizer.update(g_shared, opt_shared,
                                             shared_l)
        shared_l = jax.tree.map(jnp.add, shared_l, upd_s)

        unrow = lambda t: jax.tree.map(lambda x: x[None], t)
        vol = my_moe_ss.last_volume + my_shared_ss.last_volume
        return ((unrow(moe_l), unrow(shared_l)),
                (jax.tree.map(lambda x: x[None, None], my_moe_ss),
                 unrow(my_shared_ss)),
                (unrow(opt_moe), unrow(opt_shared)),
                {"loss": lax.pmean(loss, data_axis),
                 "comm_volume": lax.pmean(vol, (data_axis, axis_name))})

    de = P(data_axis, axis_name)
    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=((de, P(data_axis)), (de, P(data_axis)),
                  (de, P(data_axis)), P((data_axis, axis_name))),
        out_specs=((de, P(data_axis)), (de, P(data_axis)),
                   (de, P(data_axis)), P()),
        check_vma=True)
    return jax.jit(mapped, donate_argnums=(0, 1, 2))


def init_moe_sparse_states(moe, shared, algo_cfg, dp: int, num_shards: int):
    """Sparse states for :func:`build_moe_sparse_train_step`: the MoE
    bucket state per (data rank, expert shard) — [dp, Pe, ...] — sized to
    the LOCAL expert-shard flat gradient; the shared bucket [dp, ...]."""
    from oktopk_tpu.collectives.state import init_state

    n_moe_total = int(sum(x.size for x in jax.tree.leaves(moe)))
    assert n_moe_total % num_shards == 0, (n_moe_total, num_shards)
    cfg_m = algo_cfg.replace(n=n_moe_total // num_shards, num_workers=dp)
    cfg_s = algo_cfg.replace(
        n=int(sum(x.size for x in jax.tree.leaves(shared))),
        num_workers=dp)

    def stack(s, lead):
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, lead + x.shape), s)

    return (stack(init_state(cfg_m), (dp, num_shards)),
            stack(init_state(cfg_s), (dp,)))


def init_moe_sparse_opt(optimizer, moe, shared, dp: int):
    """Replica-layout optimizer states: the MoE state vmapped over the
    expert dim (every leaf then carries it, so one (data, expert) spec
    covers moments AND step counters), the shared state plain; both
    stacked [dp, ...]."""
    from oktopk_tpu.parallel.bert_seq import stack_replicas
    return (stack_replicas(jax.vmap(optimizer.init)(moe), dp),
            stack_replicas(optimizer.init(shared), dp))


def build_moe_train_step(cfg: BertConfig, mcfg: MoEConfig, mesh: Mesh,
                         optimizer, axis_name: str = "expert"):
    """jit ``((moe, shared), opt_state, batch) -> ((moe, shared),
    opt_state, loss)``.

    Expert shards train in place (each rank updates its own experts —
    their gradients arrive naturally sharded from the all_to_all
    transpose); shared params are replicated and their gradients are
    identical across ranks (the loss psums make the loss invariant), so
    one optimizer covers the whole tree."""
    loss_fn = build_moe_loss(cfg, mcfg, mesh, axis_name)

    @jax.jit
    def step(params, opt_state, batch):
        moe, shared = params
        loss, grads = jax.value_and_grad(
            lambda m, s: loss_fn(m, s, batch), argnums=(0, 1))(moe, shared)
        updates, opt_state = optimizer.update(grads, opt_state,
                                              (moe, shared))
        params = jax.tree.map(jnp.add, (moe, shared), updates)
        return params, opt_state, loss

    return step
