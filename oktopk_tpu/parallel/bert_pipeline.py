"""BERT pretraining through the pipeline: data x pipe mesh wiring.

Reference parity: StageRuntime driving the staged BERT model in its
GPipe-with-flushes loop (BERT/runtime.py:842, main_bert.py:1075), stage
modules from models/bert/depth=N (SURVEY.md C7/C16). Here the same schedule
is the ``lax.scan`` pipeline of parallel/pipeline.py over a 2-D
``Mesh((dp, pp), ("data", "pipe"))``:

- batch sharded over ``data``; transformer layers sharded over ``pipe``
  (models/bert_staged.py layout: stage_stack [S, ...], shared replicated);
- each tick's activation hop is a ``ppermute`` along ``pipe``;
- gradients: stage grads live on their pipe rank and are psum'd over
  ``data`` (plain DP within a stage, the reference's stage DP groups);
  shared (embeddings/heads) grads are psum'd over BOTH axes — embedding
  cotangents materialise only on pipe rank 0 and head cotangents only on
  the last rank, so the pipe-psum is a gather, not an overcount.

The optimizer step is dense-DP over stage-sharded flat vectors; composing
the sparse collectives per stage group rides the same seams (the algorithm
functions only need the ``data`` axis in scope) and is exposed via
``compressor=``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.models.bert_staged import StagedBertPretrain
from oktopk_tpu.parallel.pipeline import gpipe_apply
from oktopk_tpu.train import losses


def _global_pretrain_loss(mlm, nsp, batch, data_axis):
    """Global weighted pretrain loss across data shards.

    A pmean of per-shard mean losses is NOT the global loss when shards
    carry different masked-token counts; sum numerators and denominators
    over the data axis instead (keeps pipeline loss bit-comparable to the
    single-module oracle)."""
    import optax
    mask = (batch["mlm_labels"] >= 0).astype(jnp.float32)
    safe = jnp.maximum(batch["mlm_labels"], 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(mlm, safe)
    mlm_num = lax.psum(jnp.sum(per_tok * mask), data_axis)
    mlm_den = lax.psum(jnp.sum(mask), data_axis)
    nsp_ce = optax.softmax_cross_entropy_with_integer_labels(
        nsp, batch["nsp_labels"])
    nsp_num = lax.psum(jnp.sum(nsp_ce), data_axis)
    nsp_den = lax.psum(jnp.asarray(nsp_ce.shape[0], jnp.float32), data_axis)
    return mlm_num / jnp.maximum(mlm_den, 1.0) + nsp_num / nsp_den


def make_pipeline_mesh(num_stages: int, devices=None) -> Mesh:
    """Mesh((dp, pp), ("data", "pipe")) using all (or given) devices."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % num_stages != 0:
        raise ValueError(f"{len(devices)} devices not divisible by "
                         f"pipeline depth {num_stages}")
    dp = len(devices) // num_stages
    arr = np.asarray(devices).reshape(dp, num_stages)
    return Mesh(arr, ("data", "pipe"))


def _microbatch(x, M):
    return x.reshape((M, x.shape[0] // M) + x.shape[1:])


def build_pipeline_loss(staged: StagedBertPretrain, mesh: Mesh,
                        num_microbatches: int, train: bool = False,
                        remat: bool = False):
    """jit ``(stage_stack, shared, batch[, rng]) -> loss`` over the mesh.

    ``batch`` leaves are [global_B, ...] (sharded over ``data``);
    ``stage_stack`` leaves are [S, ...] (sharded over ``pipe``); ``shared``
    is replicated. Loss is the replicated global mean.
    """
    M = num_microbatches

    def shard_fn(stage_stack, shared, batch, rng):
        my_stage = jax.tree.map(lambda x: x[0], stage_stack)
        rngs = None
        if train:
            r = jax.random.fold_in(rng, lax.axis_index("data"))
            rngs = {"dropout": r}

        ids = batch["input_ids"]
        h0 = staged.embed(shared, ids, batch["token_type_ids"], train,
                          rngs=rngs)
        mask_mb = _microbatch(staged.attn_mask(batch["attention_mask"]), M)
        h0_mb = _microbatch(h0, M)

        def stage_fn(p, x, stage, mb_idx):
            m = lax.dynamic_index_in_dim(mask_mb, mb_idx, 0, keepdims=False)
            return staged.apply_stage(p, x, m, train, rngs=rngs)

        outs = gpipe_apply(stage_fn, my_stage, h0_mb, "pipe", M,
                           remat=remat)
        h = outs.reshape(ids.shape[0], ids.shape[1], -1)
        mlm, nsp = staged.head_logits(shared, h, train)
        return _global_pretrain_loss(mlm, nsp, batch, "data")

    spec_b = P("data")
    batch_specs = {k: spec_b for k in ("input_ids", "token_type_ids",
                                       "attention_mask", "mlm_labels",
                                       "nsp_labels")}
    mapped = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("pipe"), P(), batch_specs, P()),
        out_specs=P())
    return jax.jit(mapped)


def init_pipeline_opt_state(optimizer, stage_stack, shared):
    """Outer-layout optimizer states: stage moments stacked [S, ...]
    (vmapped init, shard over ``pipe``), shared moments replicated."""
    return (jax.vmap(optimizer.init)(stage_stack), optimizer.init(shared))


def build_pipeline_train_step(staged: StagedBertPretrain, mesh: Mesh,
                              num_microbatches: int, optimizer,
                              remat: bool = False,
                              grad_clip: Optional[float] = None):
    """jit ``(stage_stack, shared, opt_states, batch, rng) ->
    (stage_stack, shared, opt_states, metrics)`` — pipeline fwd/bwd +
    flush + optimizer step (the reference's run_training_loop_with_flushes
    + BertAdam.step, BERT/runtime.py:842, transformers/optimization.py:135).
    ``opt_states`` from :func:`init_pipeline_opt_state`."""
    M = num_microbatches

    def shard_fn(stage_stack, shared, opt_states, batch, rng):
        opt_stage_st, opt_shared_st = opt_states
        my_stage = jax.tree.map(lambda x: x[0], stage_stack)
        my_opt = jax.tree.map(lambda x: x[0], opt_stage_st)
        r = jax.random.fold_in(rng, lax.axis_index("data"))
        rngs = {"dropout": r}

        def loss_fn(my_stage_, shared_):
            ids = batch["input_ids"]
            h0 = staged.embed(shared_, ids, batch["token_type_ids"], True,
                              rngs=rngs)
            mask_mb = _microbatch(
                staged.attn_mask(batch["attention_mask"]), M)
            h0_mb = _microbatch(h0, M)

            def stage_fn(p, x, stage, mb_idx):
                m = lax.dynamic_index_in_dim(mask_mb, mb_idx, 0,
                                             keepdims=False)
                return staged.apply_stage(p, x, m, True, rngs=rngs)

            outs = gpipe_apply(stage_fn, my_stage_, h0_mb, "pipe", M,
                               remat=remat)
            h = outs.reshape(ids.shape[0], ids.shape[1], -1)
            mlm, nsp = staged.head_logits(shared_, h, True)
            return _global_pretrain_loss(mlm, nsp, batch, "data")

        loss, (g_stage, g_shared) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(my_stage, shared)
        # the loss is already the GLOBAL weighted mean (psum of sums),
        # so each shard's grads are partial contributions: psum over data
        # completes them. Shared grads additionally psum over pipe
        # (embedding cotangents exist only on pipe rank 0, head cotangents
        # only on the last rank).
        g_stage = jax.tree.map(lambda g: lax.psum(g, "data"), g_stage)
        g_shared = jax.tree.map(
            lambda g: lax.psum(lax.psum(g, "pipe"), "data"), g_shared)
        if grad_clip is not None:
            flat = jnp.sqrt(sum(jnp.sum(g ** 2) for g in
                                jax.tree.leaves((g_stage, g_shared))))
            scale = jnp.minimum(1.0, grad_clip / (flat + 1e-12))
            g_stage, g_shared = jax.tree.map(
                lambda g: g * scale, (g_stage, g_shared))

        upd_s, my_opt = optimizer.update(g_stage, my_opt, my_stage)
        my_stage = jax.tree.map(jnp.add, my_stage, upd_s)
        upd_h, opt_shared_st = optimizer.update(g_shared, opt_shared_st,
                                                shared)
        shared = jax.tree.map(jnp.add, shared, upd_h)

        stage_stack = jax.tree.map(lambda x: x[None], my_stage)
        opt_stage_st = jax.tree.map(lambda x: x[None], my_opt)
        return (stage_stack, shared, (opt_stage_st, opt_shared_st),
                {"loss": loss})

    spec_b = P("data")
    batch_specs = {k: spec_b for k in ("input_ids", "token_type_ids",
                                       "attention_mask", "mlm_labels",
                                       "nsp_labels")}
    mapped = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("pipe"), P(), (P("pipe"), P()), batch_specs, P()),
        out_specs=(P("pipe"), P(), (P("pipe"), P()), P()))
    return jax.jit(mapped)
