"""BERT pretraining through the pipeline: data x pipe mesh wiring.

Reference parity: StageRuntime driving the staged BERT model in its
GPipe-with-flushes loop (BERT/runtime.py:842, main_bert.py:1075), stage
modules from models/bert/depth=N (SURVEY.md C7/C16). Here the same schedule
is the ``lax.scan`` pipeline of parallel/pipeline.py over a 2-D
``Mesh((dp, pp), ("data", "pipe"))``:

- batch sharded over ``data``; transformer layers sharded over ``pipe``
  (models/bert_staged.py layout: stage_stack [S, ...], shared replicated);
- each tick's activation hop is a ``ppermute`` along ``pipe``;
- gradients: params are replicated over the axes they don't shard on, and
  shard_map's VMA-aware AD transpose already completes their cotangents
  over those axes (stage grads arrive data-complete, shared grads
  data x pipe-complete) — no explicit grad psums (adding them overcounts
  by the axis size; pinned by the sparse-composition oracle test).

The optimizer step is dense-DP over stage-sharded flat vectors; composing
the sparse collectives per stage group rides the same seams (the algorithm
functions only need the ``data`` axis in scope) and is exposed via
``compressor=``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.comm import compat

from oktopk_tpu.models.bert_staged import StagedBertPretrain
from oktopk_tpu.parallel.pipeline import gpipe_apply
from oktopk_tpu.train import losses
from oktopk_tpu.utils.flatten import flatten_tree, unflatten_tree


def _global_pretrain_loss(mlm, nsp, batch, data_axis):
    """Global weighted pretrain loss across data shards.

    A pmean of per-shard mean losses is NOT the global loss when shards
    carry different masked-token counts; sum numerators and denominators
    over the data axis instead (keeps pipeline loss bit-comparable to the
    single-module oracle). ``data_axis=None`` keeps the loss LOCAL to this
    data row (the sparse-DP composition needs independent per-row
    gradients)."""
    import optax
    psum = (lambda x: x) if data_axis is None \
        else (lambda x: lax.psum(x, data_axis))
    mask = (batch["mlm_labels"] >= 0).astype(jnp.float32)
    safe = jnp.maximum(batch["mlm_labels"], 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(mlm, safe)
    mlm_num = psum(jnp.sum(per_tok * mask))
    mlm_den = psum(jnp.sum(mask))
    nsp_ce = optax.softmax_cross_entropy_with_integer_labels(
        nsp, batch["nsp_labels"])
    nsp_num = psum(jnp.sum(nsp_ce))
    nsp_den = psum(jnp.asarray(nsp_ce.shape[0], jnp.float32))
    return mlm_num / jnp.maximum(mlm_den, 1.0) + nsp_num / nsp_den


def make_pipeline_mesh(num_stages: int, devices=None) -> Mesh:
    """Mesh((dp, pp), ("data", "pipe")) using all (or given) devices."""
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % num_stages != 0:
        raise ValueError(f"{len(devices)} devices not divisible by "
                         f"pipeline depth {num_stages}")
    dp = len(devices) // num_stages
    arr = np.asarray(devices).reshape(dp, num_stages)
    return Mesh(arr, ("data", "pipe"))


def _microbatch(x, M):
    return x.reshape((M, x.shape[0] // M) + x.shape[1:])


def build_pipeline_loss(staged: StagedBertPretrain, mesh: Mesh,
                        num_microbatches: int, train: bool = False,
                        remat: bool = False):
    """jit ``(stage_stack, shared, batch[, rng]) -> loss`` over the mesh.

    ``batch`` leaves are [global_B, ...] (sharded over ``data``);
    ``stage_stack`` leaves are [S, ...] (sharded over ``pipe``); ``shared``
    is replicated. Loss is the replicated global mean.
    """
    M = num_microbatches

    def shard_fn(stage_stack, shared, batch, rng):
        my_stage = jax.tree.map(lambda x: x[0], stage_stack)
        rngs = None
        if train:
            r = jax.random.fold_in(rng, lax.axis_index("data"))
            rngs = {"dropout": r}

        ids = batch["input_ids"]
        h0 = staged.embed(shared, ids, batch["token_type_ids"], train,
                          rngs=rngs)
        mask_mb = _microbatch(staged.attn_mask(batch["attention_mask"]), M)
        h0_mb = _microbatch(h0, M)

        def stage_fn(p, x, stage, mb_idx):
            m = lax.dynamic_index_in_dim(mask_mb, mb_idx, 0, keepdims=False)
            return staged.apply_stage(p, x, m, train, rngs=rngs)

        outs = gpipe_apply(stage_fn, my_stage, h0_mb, "pipe", M,
                           remat=remat)
        h = outs.reshape(ids.shape[0], ids.shape[1], -1)
        mlm, nsp = staged.head_logits(shared, h, train)
        return _global_pretrain_loss(mlm, nsp, batch, "data")

    spec_b = P("data")
    batch_specs = {k: spec_b for k in ("input_ids", "token_type_ids",
                                       "attention_mask", "mlm_labels",
                                       "nsp_labels")}
    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("pipe"), P(), batch_specs, P()),
        out_specs=P())
    return jax.jit(mapped)


def init_pipeline_opt_state(optimizer, stage_stack, shared):
    """Outer-layout optimizer states: stage moments stacked [S, ...]
    (vmapped init, shard over ``pipe``), shared moments replicated."""
    return (jax.vmap(optimizer.init)(stage_stack), optimizer.init(shared))


def build_pipeline_train_step(staged: StagedBertPretrain, mesh: Mesh,
                              num_microbatches: int, optimizer,
                              remat: bool = False,
                              grad_clip: Optional[float] = None):
    """jit ``(stage_stack, shared, opt_states, batch, rng) ->
    (stage_stack, shared, opt_states, metrics)`` — pipeline fwd/bwd +
    flush + optimizer step (the reference's run_training_loop_with_flushes
    + BertAdam.step, BERT/runtime.py:842, transformers/optimization.py:135).
    ``opt_states`` from :func:`init_pipeline_opt_state`."""
    M = num_microbatches

    def shard_fn(stage_stack, shared, opt_states, batch, rng):
        opt_stage_st, opt_shared_st = opt_states
        my_stage = jax.tree.map(lambda x: x[0], stage_stack)
        my_opt = jax.tree.map(lambda x: x[0], opt_stage_st)
        r = jax.random.fold_in(rng, lax.axis_index("data"))
        rngs = {"dropout": r}

        def loss_fn(my_stage_, shared_):
            ids = batch["input_ids"]
            h0 = staged.embed(shared_, ids, batch["token_type_ids"], True,
                              rngs=rngs)
            mask_mb = _microbatch(
                staged.attn_mask(batch["attention_mask"]), M)
            h0_mb = _microbatch(h0, M)

            def stage_fn(p, x, stage, mb_idx):
                m = lax.dynamic_index_in_dim(mask_mb, mb_idx, 0,
                                             keepdims=False)
                return staged.apply_stage(p, x, m, True, rngs=rngs)

            outs = gpipe_apply(stage_fn, my_stage_, h0_mb, "pipe", M,
                               remat=remat)
            h = outs.reshape(ids.shape[0], ids.shape[1], -1)
            mlm, nsp = staged.head_logits(shared_, h, True)
            return _global_pretrain_loss(mlm, nsp, batch, "data")

        loss, (g_stage, g_shared) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(my_stage, shared)
        # The loss is the GLOBAL weighted mean (psum of sums) and the
        # params are replicated over the axes they don't shard on, so the
        # shard_map AD transpose ALREADY completes their cotangents over
        # those axes — g_stage arrives data-complete and g_shared
        # (data x pipe)-complete. Explicit psums here would overcount by
        # the axis size (caught by the sparse-composition oracle test:
        # stage updates were 2x, shared 4x at dp=pp=2).
        if grad_clip is not None:
            flat = jnp.sqrt(sum(jnp.sum(g ** 2) for g in
                                jax.tree.leaves((g_stage, g_shared))))
            scale = jnp.minimum(1.0, grad_clip / (flat + 1e-12))
            g_stage, g_shared = jax.tree.map(
                lambda g: g * scale, (g_stage, g_shared))

        upd_s, my_opt = optimizer.update(g_stage, my_opt, my_stage)
        my_stage = jax.tree.map(jnp.add, my_stage, upd_s)
        upd_h, opt_shared_st = optimizer.update(g_shared, opt_shared_st,
                                                shared)
        shared = jax.tree.map(jnp.add, shared, upd_h)

        stage_stack = jax.tree.map(lambda x: x[None], my_stage)
        opt_stage_st = jax.tree.map(lambda x: x[None], my_opt)
        return (stage_stack, shared, (opt_stage_st, opt_shared_st),
                {"loss": loss})

    spec_b = P("data")
    batch_specs = {k: spec_b for k in ("input_ids", "token_type_ids",
                                       "attention_mask", "mlm_labels",
                                       "nsp_labels")}
    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("pipe"), P(), (P("pipe"), P()), batch_specs, P()),
        out_specs=(P("pipe"), P(), (P("pipe"), P()), P()))
    return jax.jit(mapped)


def init_pipeline_sparse_states(stage_stack, shared, algo_cfg, dp: int):
    """Per-(data rank, stage) sparse states for the composed step.

    Returns ``(stage_sstate, shared_sstate)``: stage states stacked
    [dp, S, ...] (sharded over data x pipe), shared state stacked
    [dp, ...]. Requires uniform stage sizes (the staged split gives every
    stage the same BertLayer block)."""
    from oktopk_tpu.collectives.state import init_state

    sizes = {int(sum(x[i].size for x in jax.tree.leaves(stage_stack)))
             for i in range(jax.tree.leaves(stage_stack)[0].shape[0])}
    assert len(sizes) == 1, f"non-uniform stage sizes {sizes}"
    n_stage = sizes.pop()
    n_shared = int(sum(x.size for x in jax.tree.leaves(shared)))
    cfg_stage = algo_cfg.replace(n=n_stage, num_workers=dp)
    cfg_shared = algo_cfg.replace(n=n_shared, num_workers=dp)
    S = jax.tree.leaves(stage_stack)[0].shape[0]

    def stack(s, lead):
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, lead + x.shape), s)

    return (stack(init_state(cfg_stage), (dp, S)),
            stack(init_state(cfg_shared), (dp,)))


def build_pipeline_sparse_train_step(staged: StagedBertPretrain, mesh: Mesh,
                                     num_microbatches: int, optimizer,
                                     algo_cfg, compressor: str = "oktopk",
                                     warmup: bool = True,
                                     remat: bool = False):
    """Sparse DP composed with the pipeline: jit ``((stage_stack, shared),
    (stage_sstate, shared_sstate), opt_states, batch, rng) -> (...)`` on
    the (data, pipe) mesh.

    The reference carried exactly this architecture — PipeDream stage
    machinery + sparse allreduce within each stage's DP group — but
    shipped it disabled (stage maps commented out, configs single-stage;
    SURVEY.md §2.3). Composition: each data row computes its own gradient
    (the loss stays row-local, ``data_axis=None``), every pipe rank runs
    the sparse collective over ``data`` on its stage's flat gradient with
    its own SparseState (the reference's per-merged-group compression),
    and the shared embeddings/heads bucket reduces over ``data`` after the
    pipe-psum gather. Params/opt/sparse states use the per-data-rank
    replica layout (leading [dp]; see bert_seq.build_seq_sparse_train_step
    for why VMA tracking requires it): stage_stack [dp, S, ...], shared
    [dp, ...]. Use :func:`init_pipeline_sparse_states`."""
    from oktopk_tpu.collectives.registry import get_algorithm
    from oktopk_tpu.ops.compaction import resolve_use_pallas

    M = num_microbatches
    algo_cfg = resolve_use_pallas(algo_cfg, mesh)
    algo_cfg = algo_cfg.replace(num_workers=int(mesh.shape["data"]))
    algo = get_algorithm(compressor, warmup=warmup)

    def shard_fn(params, sstates, opt_states, batch, rng):
        stage_stack, shared = params
        stage_ss, shared_ss = sstates
        opt_stage_st, opt_shared_st = opt_states
        row2 = lambda t: jax.tree.map(lambda x: x[0, 0], t)
        row = lambda t: jax.tree.map(lambda x: x[0], t)
        my_stage = row2(stage_stack)
        shared_l = row(shared)
        my_stage_ss, my_shared_ss = row2(stage_ss), row(shared_ss)
        my_opt, opt_shared = row2(opt_stage_st), row(opt_shared_st)
        r = jax.random.fold_in(rng, lax.axis_index("data"))
        rngs = {"dropout": r}

        def loss_fn(my_stage_, shared_):
            ids = batch["input_ids"]
            h0 = staged.embed(shared_, ids, batch["token_type_ids"], True,
                              rngs=rngs)
            mask_mb = _microbatch(
                staged.attn_mask(batch["attention_mask"]), M)
            h0_mb = _microbatch(h0, M)

            def stage_fn(p, x, stage, mb_idx):
                m = lax.dynamic_index_in_dim(mask_mb, mb_idx, 0,
                                             keepdims=False)
                return staged.apply_stage(p, x, m, True, rngs=rngs)

            outs = gpipe_apply(stage_fn, my_stage_, h0_mb, "pipe", M,
                               remat=remat)
            h = outs.reshape(ids.shape[0], ids.shape[1], -1)
            mlm, nsp = staged.head_logits(shared_, h, True)
            return _global_pretrain_loss(mlm, nsp, batch, None)

        loss, (g_stage, g_shared) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(my_stage, shared_l)
        # Per-row grads: the shared params are pipe-invariant, so the AD
        # transpose already completes their cotangents over pipe (an
        # explicit pipe psum would overcount by pp — same hazard as the
        # dense step's former data psums); stage grads are complete for
        # this data row by construction. Only the data-axis reduction
        # remains, and that is the sparse collective's job.

        cfg_stage = algo_cfg.replace(
            n=int(sum(x.size for x in jax.tree.leaves(g_stage))))
        cfg_shared = algo_cfg.replace(
            n=int(sum(x.size for x in jax.tree.leaves(g_shared))))
        flat_s, leaves_s, td_s = flatten_tree(g_stage)
        red_s, my_stage_ss = algo(flat_s, my_stage_ss, cfg_stage, "data")
        g_stage = unflatten_tree(red_s, leaves_s, td_s)
        flat_h, leaves_h, td_h = flatten_tree(g_shared)
        red_h, my_shared_ss = algo(flat_h, my_shared_ss, cfg_shared,
                                   "data")
        g_shared = unflatten_tree(red_h, leaves_h, td_h)

        upd_s, my_opt = optimizer.update(g_stage, my_opt, my_stage)
        my_stage = jax.tree.map(jnp.add, my_stage, upd_s)
        upd_h, opt_shared = optimizer.update(g_shared, opt_shared,
                                             shared_l)
        shared_l = jax.tree.map(jnp.add, shared_l, upd_h)

        lead2 = lambda t: jax.tree.map(lambda x: x[None, None], t)
        lead = lambda t: jax.tree.map(lambda x: x[None], t)
        vol = my_stage_ss.last_volume + my_shared_ss.last_volume

        def pmean_varying(x):
            # reduce only over axes the value actually varies on (the loss
            # is already pipe-invariant via the pipeline's final broadcast)
            ax = tuple(a for a in ("data", "pipe")
                       if a in compat.typeof_vma(x))
            return lax.pmean(x, ax) if ax else x

        metrics = {"loss": pmean_varying(loss),
                   "comm_volume": pmean_varying(vol)}
        return ((lead2(my_stage), lead(shared_l)),
                (lead2(my_stage_ss), lead(my_shared_ss)),
                (lead2(my_opt), lead(opt_shared)), metrics)

    spec_b = P("data")
    batch_specs = {k: spec_b for k in ("input_ids", "token_type_ids",
                                       "attention_mask", "mlm_labels",
                                       "nsp_labels")}
    dp2 = P("data", "pipe")
    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=((dp2, P("data")), (dp2, P("data")),
                  (dp2, P("data")), batch_specs, P()),
        out_specs=((dp2, P("data")), (dp2, P("data")),
                   (dp2, P("data")), P()),
        check_vma=True)
    return jax.jit(mapped, donate_argnums=(0, 1, 2))
