"""Sequence-parallel BERT: long-context pretraining over a ``seq`` mesh axis.

The reference has no sequence/context parallelism at all — max_seq_length is
a plain flag and attention is vanilla quadratic BertSelfAttention
(SURVEY.md §5.7); on TPU the sequence is a first-class scaling axis. Here
the WHOLE BertForPreTraining forward runs with the token dimension sharded:

- embeddings per shard (position ids offset by ``shard * T_local``);
- every layer's attention is exact ring attention
  (parallel/ring_attention.py): K/V blocks rotate over ICI ``ppermute``
  hops, online-softmax accumulation, no [T, T] materialisation — activation
  memory per chip scales as T/P;
- LayerNorm/MLP/heads are position-local; the pooler's [CLS] vector lives
  on shard 0 and is replicated with one tiny psum;
- the MLM loss is the global weighted mean (psum of numerator/denominator
  over the seq axis).

The math consumes the *unchanged* ``BertForPreTraining`` parameter tree
(models/bert.py) — flax module layout re-expressed functionally — so
sequence-parallel loss is equivalence-testable against the single-module
oracle to float tolerance, and checkpoints interchange. Composes with data
parallelism by adding a leading ``data`` axis to the mesh (batch sharded
over ``data``, tokens over ``seq``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.models.bert import BertConfig
from oktopk_tpu.parallel.ring_attention import ring_attention
from oktopk_tpu.train import losses  # noqa: F401  (doc cross-ref)


def _layer_norm(p, x, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _dense(p, x):
    return jnp.einsum("...e,ef->...f", x, p["kernel"]) + p["bias"]


def _mha(p, x, kv_mask, axis_name):
    """flax MultiHeadDotProductAttention math with ring attention inside.

    p: the module's params — query/key/value kernels [E, H, D] (+bias
    [H, D]), out kernel [H, D, E] (+bias [E])."""
    def proj(pp):
        return jnp.einsum("bte,ehd->bthd", x, pp["kernel"]) + pp["bias"]

    o = ring_attention(proj(p["query"]), proj(p["key"]), proj(p["value"]),
                       axis_name, kv_mask=kv_mask)
    return jnp.einsum("bthd,hde->bte", o, p["out"]["kernel"]) \
        + p["out"]["bias"]


def _layer(p, x, kv_mask, cfg: BertConfig, axis_name):
    y = _mha(p["attention"], x, kv_mask, axis_name)
    x = _layer_norm(p["attention_ln"], x + y, cfg.layer_norm_eps)
    h = _dense(p["intermediate"], x)
    h = jax.nn.gelu(h, approximate=False)
    h = _dense(p["output"], h)
    return _layer_norm(p["output_ln"], x + h, cfg.layer_norm_eps)


def bert_seq_forward(params, input_ids, token_type_ids, attention_mask,
                     cfg: BertConfig, axis_name: str = "seq"):
    """Sequence-sharded BertForPreTraining forward (deterministic).

    Shards: ``input_ids``/``token_type_ids``/``attention_mask`` are the
    LOCAL [B, T/P] token slices. Returns (mlm_logits [B, T/P, V] local,
    nsp_logits [B, 2] replicated).
    """
    shard = lax.axis_index(axis_name)
    B, Tl = input_ids.shape
    emb = params["bert"]["embeddings"]
    positions = shard * Tl + jnp.arange(Tl)[None, :]
    x = (emb["word_embeddings"]["embedding"][input_ids]
         + emb["position_embeddings"]["embedding"][positions]
         + emb["token_type_embeddings"]["embedding"][token_type_ids])
    x = _layer_norm(emb["LayerNorm_0"], x, cfg.layer_norm_eps)

    kv_mask = attention_mask.astype(bool)
    enc = params["bert"]["encoder"]
    for i in range(cfg.num_layers):
        x = _layer(enc[f"layer_{i}"], x, kv_mask, cfg, axis_name)

    # pooler input: the global [CLS] (= position 0) lives on shard 0
    cls = jnp.where(shard == 0, x[:, 0], jnp.zeros_like(x[:, 0]))
    cls = lax.psum(cls, axis_name)
    pooled = jnp.tanh(_dense(params["bert"]["pooler"], cls))

    h = _dense(params["mlm_dense"], x)
    h = jax.nn.gelu(h, approximate=False)
    h = _layer_norm(params["mlm_ln"], h, cfg.layer_norm_eps)
    table = emb["word_embeddings"]["embedding"]
    mlm = jnp.einsum("bth,vh->btv", h, table.astype(cfg.dtype))
    mlm = mlm + params["mlm_bias"]
    nsp = _dense(params["nsp"], pooled)
    return mlm.astype(jnp.float32), nsp.astype(jnp.float32)


def bert_seq_loss(params, batch, cfg: BertConfig, axis_name: str = "seq",
                  data_axis: Optional[str] = None):
    """Global MLM+NSP loss from local shards (inside shard_map).

    With ``data_axis`` set the mesh is 2-D (batch over ``data``, tokens
    over ``seq``) and the loss reductions span both axes (weighted
    psum-of-sums — a mean of per-shard means would be wrong whenever
    masked-token counts differ across shards)."""
    import optax
    mlm, nsp = bert_seq_forward(params, batch["input_ids"],
                                batch["token_type_ids"],
                                batch["attention_mask"], cfg, axis_name)
    axes = (axis_name,) if data_axis is None else (axis_name, data_axis)
    mask = (batch["mlm_labels"] >= 0).astype(jnp.float32)
    safe = jnp.maximum(batch["mlm_labels"], 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(mlm, safe)
    num = lax.psum(jnp.sum(per_tok * mask), axes)
    den = lax.psum(jnp.sum(mask), axes)
    nsp_ce = optax.softmax_cross_entropy_with_integer_labels(
        nsp, batch["nsp_labels"])
    if data_axis is None:
        nsp_loss = nsp_ce.mean()
    else:
        # equal per-shard batch: mean of per-shard means == global mean,
        # so no collective is needed for the denominator
        nsp_loss = lax.pmean(nsp_ce.mean(), data_axis)
    return num / jnp.maximum(den, 1.0) + nsp_loss


def make_seq_mesh(num_shards: int, devices=None,
                  data_size: int = 1) -> Mesh:
    """1-D ("seq",) mesh, or 2-D ("data", "seq") when ``data_size > 1``."""
    import numpy as np
    devices = list(devices if devices is not None else jax.devices())
    need = num_shards * data_size
    if len(devices) < need:
        raise ValueError(f"seq parallelism needs {need} devices, "
                         f"have {len(devices)}")
    if data_size > 1:
        return Mesh(np.asarray(devices[:need]).reshape(data_size,
                                                       num_shards),
                    ("data", "seq"))
    return Mesh(np.asarray(devices[:num_shards]), ("seq",))


def build_seq_train_step(cfg: BertConfig, mesh: Mesh, optimizer,
                         axis_name: str = "seq"):
    """jit ``(params, opt_state, batch) -> (params, opt_state, loss)``.

    Gradients flow through the shard_map'd loss (ppermute/psum transposes
    are exact under VMA tracking — pinned by
    tests/test_bert_seq.py::test_gradients_match_single_module); params are
    replicated, so the optimizer step runs outside the mesh program.
    Deterministic forward (no dropout) — the long-context regime this path
    exists for pretrains with dropout disabled anyway.
    """
    loss_fn = build_seq_loss(cfg, mesh, axis_name)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch))(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(jnp.add, params, updates)
        return params, opt_state, loss

    return step


def build_seq_loss(cfg: BertConfig, mesh: Mesh,
                   axis_name: str = "seq"):
    """jit ``(params, batch) -> loss`` with batch token dims sharded over
    ``seq`` (and the batch dim over ``data`` if the mesh has that axis —
    the composed dp x sp form). ``nsp_labels`` follows the batch dim;
    everything else [B, T] splits on the token axis."""
    data_axis = "data" if "data" in mesh.axis_names else None
    tok_spec = P(data_axis, axis_name)
    batch_specs = {"input_ids": tok_spec, "token_type_ids": tok_spec,
                   "attention_mask": tok_spec, "mlm_labels": tok_spec,
                   "nsp_labels": P(data_axis)}

    def shard_fn(params, batch):
        return bert_seq_loss(params, batch, cfg, axis_name,
                             data_axis=data_axis)

    mapped = jax.shard_map(shard_fn, mesh=mesh,
                           in_specs=(P(), batch_specs), out_specs=P())
    return jax.jit(mapped)
