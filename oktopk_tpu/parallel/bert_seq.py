"""Sequence-parallel BERT: long-context pretraining over a ``seq`` mesh axis.

The reference has no sequence/context parallelism at all — max_seq_length is
a plain flag and attention is vanilla quadratic BertSelfAttention
(SURVEY.md §5.7); on TPU the sequence is a first-class scaling axis. Here
the WHOLE BertForPreTraining forward runs with the token dimension sharded:

- embeddings per shard (position ids offset by ``shard * T_local``);
- every layer's attention is exact ring attention
  (parallel/ring_attention.py): K/V blocks rotate over ICI ``ppermute``
  hops, online-softmax accumulation, no [T, T] materialisation — activation
  memory per chip scales as T/P;
- LayerNorm/MLP/heads are position-local; the pooler's [CLS] vector lives
  on shard 0 and is replicated with one tiny psum;
- the MLM loss is the global weighted mean (psum of numerator/denominator
  over the seq axis).

The math consumes the *unchanged* ``BertForPreTraining`` parameter tree
(models/bert.py) — flax module layout re-expressed functionally — so
sequence-parallel loss is equivalence-testable against the single-module
oracle to float tolerance, and checkpoints interchange. Composes with data
parallelism by adding a leading ``data`` axis to the mesh (batch sharded
over ``data``, tokens over ``seq``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.comm import compat

from oktopk_tpu.models.bert import BertConfig
from oktopk_tpu.parallel.ring_attention import ring_attention
from oktopk_tpu.train import losses  # noqa: F401  (doc cross-ref)
from oktopk_tpu.utils.flatten import flatten_tree, unflatten_tree


def _layer_norm(p, x, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _dense(p, x):
    return jnp.einsum("...e,ef->...f", x, p["kernel"]) + p["bias"]


def _mha(p, x, kv_mask, axis_name):
    """flax MultiHeadDotProductAttention math with ring attention inside.

    p: the module's params — query/key/value kernels [E, H, D] (+bias
    [H, D]), out kernel [H, D, E] (+bias [E])."""
    def proj(pp):
        return jnp.einsum("bte,ehd->bthd", x, pp["kernel"]) + pp["bias"]

    o = ring_attention(proj(p["query"]), proj(p["key"]), proj(p["value"]),
                       axis_name, kv_mask=kv_mask)
    return jnp.einsum("bthd,hde->bte", o, p["out"]["kernel"]) \
        + p["out"]["bias"]


def _layer(p, x, kv_mask, cfg: BertConfig, axis_name):
    y = _mha(p["attention"], x, kv_mask, axis_name)
    x = _layer_norm(p["attention_ln"], x + y, cfg.layer_norm_eps)
    h = _dense(p["intermediate"], x)
    h = jax.nn.gelu(h, approximate=False)
    h = _dense(p["output"], h)
    return _layer_norm(p["output_ln"], x + h, cfg.layer_norm_eps)


def bert_seq_forward(params, input_ids, token_type_ids, attention_mask,
                     cfg: BertConfig, axis_name: str = "seq"):
    """Sequence-sharded BertForPreTraining forward (deterministic).

    Shards: ``input_ids``/``token_type_ids``/``attention_mask`` are the
    LOCAL [B, T/P] token slices. Returns (mlm_logits [B, T/P, V] local,
    nsp_logits [B, 2] replicated).
    """
    shard = lax.axis_index(axis_name)
    B, Tl = input_ids.shape
    emb = params["bert"]["embeddings"]
    positions = shard * Tl + jnp.arange(Tl)[None, :]
    x = (emb["word_embeddings"]["embedding"][input_ids]
         + emb["position_embeddings"]["embedding"][positions]
         + emb["token_type_embeddings"]["embedding"][token_type_ids])
    x = _layer_norm(emb["LayerNorm_0"], x, cfg.layer_norm_eps)

    kv_mask = attention_mask.astype(bool)
    enc = params["bert"]["encoder"]
    for i in range(cfg.num_layers):
        x = _layer(enc[f"layer_{i}"], x, kv_mask, cfg, axis_name)

    # pooler input: the global [CLS] (= position 0) lives on shard 0
    cls = jnp.where(shard == 0, x[:, 0], jnp.zeros_like(x[:, 0]))
    cls = lax.psum(cls, axis_name)
    pooled = jnp.tanh(_dense(params["bert"]["pooler"], cls))

    h = _dense(params["mlm_dense"], x)
    h = jax.nn.gelu(h, approximate=False)
    h = _layer_norm(params["mlm_ln"], h, cfg.layer_norm_eps)
    table = emb["word_embeddings"]["embedding"]
    mlm = jnp.einsum("bth,vh->btv", h, table.astype(cfg.dtype))
    mlm = mlm + params["mlm_bias"]
    nsp = _dense(params["nsp"], pooled)
    return mlm.astype(jnp.float32), nsp.astype(jnp.float32)


def bert_seq_loss(params, batch, cfg: BertConfig, axis_name: str = "seq",
                  data_axis: Optional[str] = None):
    """Global MLM+NSP loss from local shards (inside shard_map).

    With ``data_axis`` set the mesh is 2-D (batch over ``data``, tokens
    over ``seq``) and the loss reductions span both axes (weighted
    psum-of-sums — a mean of per-shard means would be wrong whenever
    masked-token counts differ across shards)."""
    import optax
    mlm, nsp = bert_seq_forward(params, batch["input_ids"],
                                batch["token_type_ids"],
                                batch["attention_mask"], cfg, axis_name)
    axes = (axis_name,) if data_axis is None else (axis_name, data_axis)
    mask = (batch["mlm_labels"] >= 0).astype(jnp.float32)
    safe = jnp.maximum(batch["mlm_labels"], 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(mlm, safe)
    num = lax.psum(jnp.sum(per_tok * mask), axes)
    den = lax.psum(jnp.sum(mask), axes)
    nsp_ce = optax.softmax_cross_entropy_with_integer_labels(
        nsp, batch["nsp_labels"])
    if data_axis is None:
        nsp_loss = nsp_ce.mean()
    else:
        # equal per-shard batch: mean of per-shard means == global mean,
        # so no collective is needed for the denominator
        nsp_loss = lax.pmean(nsp_ce.mean(), data_axis)
    return num / jnp.maximum(den, 1.0) + nsp_loss


def make_seq_mesh(num_shards: int, devices=None,
                  data_size: int = 1) -> Mesh:
    """1-D ("seq",) mesh, or 2-D ("data", "seq") when ``data_size > 1``."""
    import numpy as np
    devices = list(devices if devices is not None else jax.devices())
    need = num_shards * data_size
    if len(devices) < need:
        raise ValueError(f"seq parallelism needs {need} devices, "
                         f"have {len(devices)}")
    if data_size > 1:
        return Mesh(np.asarray(devices[:need]).reshape(data_size,
                                                       num_shards),
                    ("data", "seq"))
    return Mesh(np.asarray(devices[:num_shards]), ("seq",))


def build_seq_train_step(cfg: BertConfig, mesh: Mesh, optimizer,
                         axis_name: str = "seq"):
    """jit ``(params, opt_state, batch) -> (params, opt_state, loss)``.

    Gradients flow through the shard_map'd loss (ppermute/psum transposes
    are exact under VMA tracking — pinned by
    tests/test_bert_seq.py::test_gradients_match_single_module); params are
    replicated, so the optimizer step runs outside the mesh program.
    Deterministic forward (no dropout) — the long-context regime this path
    exists for pretrains with dropout disabled anyway.
    """
    loss_fn = build_seq_loss(cfg, mesh, axis_name)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch))(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(jnp.add, params, updates)
        return params, opt_state, loss

    return step


def _batch_specs(data_axis, seq_axis):
    """PartitionSpecs for a pretraining batch on a (data,) x seq mesh:
    [B, T] leaves split tokens over ``seq_axis`` (and batch over
    ``data_axis`` when present); ``nsp_labels`` follows the batch dim."""
    tok_spec = P(data_axis, seq_axis)
    return {"input_ids": tok_spec, "token_type_ids": tok_spec,
            "attention_mask": tok_spec, "mlm_labels": tok_spec,
            "nsp_labels": P(data_axis)}


def build_seq_sparse_train_step(cfg: BertConfig, mesh: Mesh, optimizer,
                                algo_cfg, compressor: str = "oktopk",
                                warmup: bool = True,
                                axis_name: str = "seq",
                                data_axis: str = "data",
                                accum_steps: int = 1):
    """Sparse data parallelism composed with sequence parallelism: jit
    ``(params, sparse_state, opt_state, batch) -> (params, sparse_state,
    opt_state, loss)`` on a (data, seq) mesh.

    Each data row computes its own gradient through the ring-attention
    loss (psums over ``seq`` only — ``data_axis=None`` in the loss keeps
    rows independent), the flat gradient goes through the selected sparse
    collective over ``data`` (the reference's whole framework, now riding
    under long context it never had), and each row applies the identical
    reduced gradient.

    Replica model: params / opt_state / sparse_state all carry a leading
    ``[dp]`` axis sharded over ``data`` — each data rank holds its own
    replica, exactly like the reference's MPI DP ranks, and the rows stay
    bitwise identical by construction (same reduced gradient, same
    update). This is also what VMA tracking can type: the collectives'
    gathered outputs are "varying" (equal across ranks but not provably
    so to the type system), and tracking must stay ON because the
    ring-attention / loss-psum gradient transposes are only exact under
    ``check_vma=True``. ``algo_cfg.num_workers`` must equal the data axis
    size and ``algo_cfg.n`` the flat parameter count. Use
    :func:`stack_replicas` to lift single-copy pytrees.

    ``accum_steps > 1`` runs local gradient accumulation before the ONE
    collective (the reference's --gradient_accumulation_steps x
    update_interval semantics, BERT/bert/main_bert.py:914-918): batch
    leaves carry ``accum_steps * b`` examples per data rank and are
    consumed as a ``lax.scan`` over slices."""
    from oktopk_tpu.collectives.registry import get_algorithm
    from oktopk_tpu.ops.compaction import resolve_use_pallas

    algo_cfg = resolve_use_pallas(algo_cfg, mesh)
    algo = get_algorithm(compressor, warmup=warmup)
    batch_specs = _batch_specs(data_axis, axis_name)

    def shard_fn(params, sstate, opt_state, batch):
        row = lambda t: jax.tree.map(lambda x: x[0], t)
        unrow = lambda t: jax.tree.map(lambda x: x[None], t)
        params, sp, opt_state = row(params), row(sstate), row(opt_state)

        def one(p, b):
            return jax.value_and_grad(
                lambda q: bert_seq_loss(q, b, cfg, axis_name,
                                        data_axis=None))(p)

        if accum_steps > 1:
            mb = jax.tree.map(
                lambda x: x.reshape((accum_steps, x.shape[0] // accum_steps)
                                    + x.shape[1:]), batch)

            def body(carry, b):
                g_acc, l_acc = carry
                loss_i, g_i = one(params, b)
                return (jax.tree.map(jnp.add, g_acc, g_i),
                        l_acc + loss_i), None

            # a zeros-init carry is VMA-invariant while the per-slice
            # grads are varying; pvary_like aligns the types so one scan
            # covers every slice (peeling slice 0 instead would embed a
            # second full fwd+bwd in the compiled program)
            # grads/loss share params' vma ({data}: the loss psums leave
            # them seq-invariant), so params is the alignment reference
            from oktopk_tpu.comm.primitives import pvary_like
            zero = pvary_like(
                (jax.tree.map(jnp.zeros_like, params), jnp.float32(0.0)),
                jax.tree.leaves(params)[0])
            (grads, loss), _ = lax.scan(body, zero, mb)
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            loss = loss / accum_steps
        else:
            loss, grads = one(params, batch)
        flat, leaves, treedef = flatten_tree(grads)
        assert flat.size == algo_cfg.n, (flat.size, algo_cfg.n)
        reduced, sp = algo(flat, sp, algo_cfg, data_axis)
        grads = unflatten_tree(reduced, leaves, treedef)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(jnp.add, params, updates)
        # loss is already seq-invariant (the loss psums), so only the
        # data-mean remains
        return (unrow(params), unrow(sp), unrow(opt_state),
                lax.pmean(loss, data_axis))

    spec_d = P(data_axis)
    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(spec_d, spec_d, spec_d, batch_specs),
        out_specs=(spec_d, spec_d, spec_d, P()),
        check_vma=True)
    return jax.jit(mapped, donate_argnums=(0, 1, 2))


def stack_replicas(tree, dp: int):
    """Lift a single-copy pytree to the per-data-rank replica layout
    (leading [dp] axis) used by :func:`build_seq_sparse_train_step`."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (dp,) + x.shape), tree)


def build_seq_loss(cfg: BertConfig, mesh: Mesh,
                   axis_name: str = "seq"):
    """jit ``(params, batch) -> loss`` with batch token dims sharded over
    ``seq`` (and the batch dim over ``data`` if the mesh has that axis —
    the composed dp x sp form). ``nsp_labels`` follows the batch dim;
    everything else [B, T] splits on the token axis."""
    data_axis = "data" if "data" in mesh.axis_names else None
    batch_specs = _batch_specs(data_axis, axis_name)

    def shard_fn(params, batch):
        return bert_seq_loss(params, batch, cfg, axis_name,
                             data_axis=data_axis)

    mapped = compat.shard_map(shard_fn, mesh=mesh,
                              in_specs=(P(), batch_specs), out_specs=P())
    return jax.jit(mapped)
