"""Tensor-parallel BERT: Megatron-style head/FFN sharding over ``model``.

The reference has no tensor parallelism (SURVEY.md §2.3 — absent); this is
a TPU-side extension completing the mesh-axes story (data x pipe x seq x
model). The classic two-psum-per-layer decomposition:

- attention: the head dimension is sharded — each rank runs H/P full
  attention heads (column-parallel QKV, row-parallel output projection,
  ONE psum after the out-projection);
- MLP: column-parallel intermediate Dense, row-parallel output Dense,
  ONE psum after it (biases of row-parallel layers are added post-psum so
  they are counted once);
- LayerNorms, embeddings, pooler and the MLM/NSP heads stay replicated.

As with the other parallel forms (bert_staged, bert_seq), the math consumes
a re-layout of the *unchanged* ``BertForPreTraining`` tree —
``split_tp``/``merge_tp`` interconvert — so loss and gradients are
equivalence-testable against the single-module oracle and checkpoints
interchange.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.comm import compat

from oktopk_tpu.models.bert import BertConfig
from oktopk_tpu.parallel.bert_seq import _dense, _layer_norm


def split_tp(params, num_shards: int):
    """Single-module params -> (tp_stack, shared).

    ``tp_stack`` leaves carry a leading [P] shard axis: per layer, the
    attention query/key/value kernels+biases split on the head dim, the
    out-projection kernel splits on its head input dim, the MLP
    intermediate kernel+bias split on the feature dim and the MLP output
    kernel on its feature input dim. ``shared`` holds everything else
    (including row-parallel output biases, applied once post-psum)."""
    def shard(x, axis):
        parts = jnp.split(x, num_shards, axis=axis)
        return jnp.stack(parts)

    enc = params["bert"]["encoder"]
    tp_layers, sh_layers = {}, {}
    for name, lp in enc.items():
        a = lp["attention"]
        tp_layers[name] = {
            "attention": {
                **{k: {"kernel": shard(a[k]["kernel"], 1),
                       "bias": shard(a[k]["bias"], 0)}
                   for k in ("query", "key", "value")},
                "out": {"kernel": shard(a["out"]["kernel"], 0)},
            },
            "intermediate": {"kernel": shard(lp["intermediate"]["kernel"], 1),
                             "bias": shard(lp["intermediate"]["bias"], 0)},
            "output": {"kernel": shard(lp["output"]["kernel"], 0)},
        }
        sh_layers[name] = {
            "attention_out_bias": a["out"]["bias"],
            "output_bias": lp["output"]["bias"],
            "attention_ln": lp["attention_ln"],
            "output_ln": lp["output_ln"],
        }
    shared = {
        "embeddings": params["bert"]["embeddings"],
        "pooler": params["bert"]["pooler"],
        "mlm_dense": params["mlm_dense"],
        "mlm_ln": params["mlm_ln"],
        "mlm_bias": params["mlm_bias"],
        "nsp": params["nsp"],
        "layers": sh_layers,
    }
    return tp_layers, shared


def merge_tp(tp_layers, shared):
    """Inverse of :func:`split_tp`."""
    def unshard(x, axis):
        return jnp.concatenate([x[i] for i in range(x.shape[0])], axis=axis)

    enc = {}
    for name, lp in tp_layers.items():
        a = lp["attention"]
        sh = shared["layers"][name]
        enc[name] = {
            "attention": {
                **{k: {"kernel": unshard(a[k]["kernel"], 1),
                       "bias": unshard(a[k]["bias"], 0)}
                   for k in ("query", "key", "value")},
                "out": {"kernel": unshard(a["out"]["kernel"], 0),
                        "bias": sh["attention_out_bias"]},
            },
            "attention_ln": sh["attention_ln"],
            "intermediate": {
                "kernel": unshard(lp["intermediate"]["kernel"], 1),
                "bias": unshard(lp["intermediate"]["bias"], 0)},
            "output": {"kernel": unshard(lp["output"]["kernel"], 0),
                       "bias": sh["output_bias"]},
            "output_ln": sh["output_ln"],
        }
    return {
        "bert": {"embeddings": shared["embeddings"],
                 "encoder": enc,
                 "pooler": shared["pooler"]},
        "mlm_dense": shared["mlm_dense"],
        "mlm_ln": shared["mlm_ln"],
        "mlm_bias": shared["mlm_bias"],
        "nsp": shared["nsp"],
    }


def _tp_attention(tp, out_bias, x, attn_mask, axis_name):
    """H/P-head attention + row-parallel out projection (one psum)."""
    def proj(pp):
        return jnp.einsum("bte,ehd->bthd", x, pp["kernel"]) + pp["bias"]

    q = proj(tp["query"])                       # [B, T, Hl, D]
    k = proj(tp["key"])
    v = proj(tp["value"])
    d = q.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", q * (d ** -0.5), k)
    s = jnp.where(attn_mask, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p, v)
    partial = jnp.einsum("bthd,hde->bte", o, tp["out"]["kernel"])
    return lax.psum(partial, axis_name) + out_bias


def _tp_layer(tp, sh, x, attn_mask, cfg: BertConfig, axis_name):
    y = _tp_attention(tp["attention"], sh["attention_out_bias"], x,
                      attn_mask, axis_name)
    x = _layer_norm(sh["attention_ln"], x + y, cfg.layer_norm_eps)
    h = jnp.einsum("bte,ef->btf", x, tp["intermediate"]["kernel"]) \
        + tp["intermediate"]["bias"]
    h = jax.nn.gelu(h, approximate=False)
    partial = jnp.einsum("btf,fe->bte", h, tp["output"]["kernel"])
    h = lax.psum(partial, axis_name) + sh["output_bias"]
    return _layer_norm(sh["output_ln"], x + h, cfg.layer_norm_eps)


def bert_tp_loss(tp_layers, shared, batch, cfg: BertConfig,
                 axis_name: str = "model"):
    """Replicated-batch MLM+NSP loss with tensor-parallel layers (inside
    shard_map; ``tp_layers`` leaves are this rank's [1, ...] shard rows)."""
    tp_local = jax.tree.map(lambda x: x[0], tp_layers)
    return tp_loss_local(tp_local, shared, batch, cfg, axis_name)


def tp_loss_local(tp_local, shared, batch, cfg: BertConfig,
                  axis_name: str = "model"):
    """As :func:`bert_tp_loss` but with the leading shard axis already
    stripped (``tp_local`` leaves are this rank's bare shard) — the form
    the composed dp x tp step consumes."""
    import optax
    ids = batch["input_ids"]
    B, T = ids.shape
    emb = shared["embeddings"]
    positions = jnp.arange(T)[None, :]
    x = (emb["word_embeddings"]["embedding"][ids]
         + emb["position_embeddings"]["embedding"][positions]
         + emb["token_type_embeddings"]["embedding"][batch["token_type_ids"]])
    x = _layer_norm(emb["LayerNorm_0"], x, cfg.layer_norm_eps)

    mask = batch["attention_mask"][:, None, None, :].astype(bool)
    for i in range(cfg.num_layers):
        x = _tp_layer(tp_local[f"layer_{i}"],
                      shared["layers"][f"layer_{i}"], x, mask, cfg,
                      axis_name)

    pooled = jnp.tanh(_dense(shared["pooler"], x[:, 0]))
    h = _dense(shared["mlm_dense"], x)
    h = jax.nn.gelu(h, approximate=False)
    h = _layer_norm(shared["mlm_ln"], h, cfg.layer_norm_eps)
    table = emb["word_embeddings"]["embedding"]
    mlm = (jnp.einsum("bth,vh->btv", h, table.astype(cfg.dtype))
           + shared["mlm_bias"]).astype(jnp.float32)
    nsp = _dense(shared["nsp"], pooled).astype(jnp.float32)

    lmask = (batch["mlm_labels"] >= 0).astype(jnp.float32)
    safe = jnp.maximum(batch["mlm_labels"], 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(mlm, safe)
    mlm_loss = jnp.sum(per_tok * lmask) / jnp.maximum(jnp.sum(lmask), 1.0)
    nsp_loss = optax.softmax_cross_entropy_with_integer_labels(
        nsp, batch["nsp_labels"]).mean()
    return mlm_loss + nsp_loss


def make_tp_mesh(num_shards: int, devices=None, data_size: int = 1) -> Mesh:
    """1-D ("model",) mesh, or 2-D ("data", "model") when
    ``data_size > 1`` (the composed dp x tp form)."""
    import numpy as np
    devices = list(devices if devices is not None else jax.devices())
    need = num_shards * data_size
    if len(devices) < need:
        raise ValueError(f"tensor parallelism needs {need} devices, "
                         f"have {len(devices)}")
    if data_size > 1:
        return Mesh(np.asarray(devices[:need]).reshape(data_size,
                                                       num_shards),
                    ("data", "model"))
    return Mesh(np.asarray(devices[:num_shards]), ("model",))


def build_tp_loss(cfg: BertConfig, mesh: Mesh, axis_name: str = "model"):
    """jit ``(tp_stack, shared, batch) -> loss`` (batch replicated,
    tp_stack sharded over ``model``)."""
    def shard_fn(tp_layers, shared, batch):
        return bert_tp_loss(tp_layers, shared, batch, cfg, axis_name)

    mapped = compat.shard_map(shard_fn, mesh=mesh,
                              in_specs=(P(axis_name), P(), P()),
                              out_specs=P())
    return jax.jit(mapped)


def build_tp_train_step(cfg: BertConfig, mesh: Mesh, optimizer,
                        axis_name: str = "model"):
    """jit ``(tp_stack, shared, opt_tp, opt_sh, batch) -> (tp_stack,
    shared, opt_tp, opt_sh, loss)`` on the ("model",) mesh.

    Grads wrt the replicated ``shared`` tree need no explicit model-axis
    psum: the loss is model-invariant after the layer psums, and the AD
    transpose of the invariant->varying promotion already completes the
    cotangent over ``model`` (an explicit psum would overcount by the
    shard count — the same hazard the pipeline step documents,
    bert_pipeline.py:294-299). The two optimizer states mirror the two
    param trees: ``opt_tp`` sharded over ``model``, ``opt_sh``
    replicated — elementwise optimizers (SGD/Adam) act shard-locally, so
    the sharded moments are exactly the merged moments re-split."""
    def shard_fn(tp_layers, shared, opt_tp, opt_sh, batch):
        row = lambda t: jax.tree.map(lambda x: x[0], t)
        lead = lambda t: jax.tree.map(lambda x: x[None], t)
        tp_local, opt_tp_l = row(tp_layers), row(opt_tp)

        loss, (g_tp, g_sh) = jax.value_and_grad(
            tp_loss_local, argnums=(0, 1))(tp_local, shared, batch, cfg,
                                           axis_name)
        upd_t, opt_tp_l = optimizer.update(g_tp, opt_tp_l, tp_local)
        tp_local = jax.tree.map(jnp.add, tp_local, upd_t)
        upd_s, opt_sh = optimizer.update(g_sh, opt_sh, shared)
        shared = jax.tree.map(jnp.add, shared, upd_s)
        return lead(tp_local), shared, lead(opt_tp_l), opt_sh, loss

    m = P(axis_name)
    mapped = compat.shard_map(shard_fn, mesh=mesh,
                              in_specs=(m, P(), m, P(), P()),
                              out_specs=(m, P(), m, P(), P()),
                              check_vma=True)
    return jax.jit(mapped, donate_argnums=(0, 1, 2, 3))


def init_tp_opt_states(optimizer, tp_layers, shared):
    """Optimizer states for :func:`build_tp_train_step`: ``opt_tp`` is
    initialised per shard row (vmap over the leading [P] axis, so sharded
    moments line up with sharded params), ``opt_sh`` once."""
    return (jax.vmap(optimizer.init)(tp_layers), optimizer.init(shared))


def init_tp_sparse_states(tp_layers, shared, algo_cfg, dp: int):
    """Per-(data rank, model rank) sparse states for the composed step.

    Returns ``(tp_sstate, shared_sstate)``: tp states stacked
    [dp, P, ...] (sharded over data x model), shared state stacked
    [dp, ...]. Requires uniform shard sizes (split_tp's equal splits
    guarantee it)."""
    from oktopk_tpu.collectives.state import init_state

    leaves = jax.tree.leaves(tp_layers)
    tp_shards = leaves[0].shape[0]
    sizes = {int(sum(x[i].size for x in leaves)) for i in range(tp_shards)}
    assert len(sizes) == 1, f"non-uniform tp shard sizes {sizes}"
    n_tp = sizes.pop()
    n_shared = int(sum(x.size for x in jax.tree.leaves(shared)))

    def stack(s, lead):
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x, lead + x.shape), s)

    return (stack(init_state(algo_cfg.replace(n=n_tp, num_workers=dp)),
                  (dp, tp_shards)),
            stack(init_state(algo_cfg.replace(n=n_shared, num_workers=dp)),
                  (dp,)))


def build_tp_sparse_train_step(cfg: BertConfig, mesh: Mesh, optimizer,
                               algo_cfg, compressor: str = "oktopk",
                               warmup: bool = True,
                               axis_name: str = "model",
                               data_axis: str = "data"):
    """Sparse data parallelism composed with tensor parallelism: jit
    ``((tp_stack, shared), (tp_ss, shared_ss), (opt_tp, opt_sh), batch)
    -> (...)`` on the (data, model) mesh — the data x model cell of the
    composition matrix (README/PERF.md), previously loss-only.

    Composition: each (data, model) rank computes its shard's gradient
    through the TP loss (psums over ``model`` only), then runs the sparse
    collective over ``data`` on TWO separate flat vectors with separate
    SparseStates — its tp-shard gradient, and the shared (replicated)
    gradient. The split is load-bearing: compressing one mixed vector
    would let per-model-rank thresholds (driven by the differing tp
    shards) select *different* shared elements on different model ranks,
    and the replicated shared params would silently diverge. With the
    shared vector compressed on its own, its inputs are model-invariant,
    the deterministic algorithm returns model-invariant results, and
    replicas stay bitwise identical — same argument as the pipeline
    composition's shared bucket (bert_pipeline.py:231-348).

    Layouts: tp_stack / tp_ss / opt_tp leaves [dp, P, ...] sharded
    (data, model); shared / shared_ss / opt_sh leaves [dp, ...] sharded
    (data); batch [dp*b, T] split over data, replicated over model."""
    from oktopk_tpu.collectives.registry import get_algorithm
    from oktopk_tpu.ops.compaction import resolve_use_pallas
    from oktopk_tpu.utils.flatten import flatten_tree, unflatten_tree

    algo_cfg = resolve_use_pallas(algo_cfg, mesh)
    algo_cfg = algo_cfg.replace(num_workers=int(mesh.shape[data_axis]))
    algo = get_algorithm(compressor, warmup=warmup)

    def shard_fn(params, sstates, opt_states, batch):
        tp_stack, shared = params
        tp_ss, shared_ss = sstates
        opt_tp, opt_sh = opt_states
        row2 = lambda t: jax.tree.map(lambda x: x[0, 0], t)
        row = lambda t: jax.tree.map(lambda x: x[0], t)
        my_tp, shared_l = row2(tp_stack), row(shared)
        my_tp_ss, my_sh_ss = row2(tp_ss), row(shared_ss)
        my_opt_tp, my_opt_sh = row2(opt_tp), row(opt_sh)

        loss, (g_tp, g_sh) = jax.value_and_grad(
            tp_loss_local, argnums=(0, 1))(my_tp, shared_l, batch, cfg,
                                           axis_name)

        cfg_tp = algo_cfg.replace(
            n=int(sum(x.size for x in jax.tree.leaves(g_tp))))
        cfg_sh = algo_cfg.replace(
            n=int(sum(x.size for x in jax.tree.leaves(g_sh))))
        flat_t, leaves_t, td_t = flatten_tree(g_tp)
        red_t, my_tp_ss = algo(flat_t, my_tp_ss, cfg_tp, data_axis)
        g_tp = unflatten_tree(red_t, leaves_t, td_t)
        flat_h, leaves_h, td_h = flatten_tree(g_sh)
        red_h, my_sh_ss = algo(flat_h, my_sh_ss, cfg_sh, data_axis)
        g_sh = unflatten_tree(red_h, leaves_h, td_h)

        upd_t, my_opt_tp = optimizer.update(g_tp, my_opt_tp, my_tp)
        my_tp = jax.tree.map(jnp.add, my_tp, upd_t)
        upd_s, my_opt_sh = optimizer.update(g_sh, my_opt_sh, shared_l)
        shared_l = jax.tree.map(jnp.add, shared_l, upd_s)

        lead2 = lambda t: jax.tree.map(lambda x: x[None, None], t)
        lead = lambda t: jax.tree.map(lambda x: x[None], t)
        vol = my_tp_ss.last_volume + my_sh_ss.last_volume

        def pmean_varying(x):
            ax = tuple(a for a in (data_axis, axis_name)
                       if a in compat.typeof_vma(x))
            return lax.pmean(x, ax) if ax else x

        metrics = {"loss": pmean_varying(loss),
                   "comm_volume": pmean_varying(vol)}
        return ((lead2(my_tp), lead(shared_l)),
                (lead2(my_tp_ss), lead(my_sh_ss)),
                (lead2(my_opt_tp), lead(my_opt_sh)), metrics)

    dm = P(data_axis, axis_name)
    d = P(data_axis)
    batch_specs = {k: d for k in ("input_ids", "token_type_ids",
                                  "attention_mask", "mlm_labels",
                                  "nsp_labels")}
    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=((dm, d), (dm, d), (dm, d), batch_specs),
        out_specs=((dm, d), (dm, d), (dm, d), P()),
        check_vma=True)
    return jax.jit(mapped, donate_argnums=(0, 1, 2))
