"""Tensor-parallel BERT: Megatron-style head/FFN sharding over ``model``.

The reference has no tensor parallelism (SURVEY.md §2.3 — absent); this is
a TPU-side extension completing the mesh-axes story (data x pipe x seq x
model). The classic two-psum-per-layer decomposition:

- attention: the head dimension is sharded — each rank runs H/P full
  attention heads (column-parallel QKV, row-parallel output projection,
  ONE psum after the out-projection);
- MLP: column-parallel intermediate Dense, row-parallel output Dense,
  ONE psum after it (biases of row-parallel layers are added post-psum so
  they are counted once);
- LayerNorms, embeddings, pooler and the MLM/NSP heads stay replicated.

As with the other parallel forms (bert_staged, bert_seq), the math consumes
a re-layout of the *unchanged* ``BertForPreTraining`` tree —
``split_tp``/``merge_tp`` interconvert — so loss and gradients are
equivalence-testable against the single-module oracle and checkpoints
interchange.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from oktopk_tpu.models.bert import BertConfig
from oktopk_tpu.parallel.bert_seq import _dense, _layer_norm


def split_tp(params, num_shards: int):
    """Single-module params -> (tp_stack, shared).

    ``tp_stack`` leaves carry a leading [P] shard axis: per layer, the
    attention query/key/value kernels+biases split on the head dim, the
    out-projection kernel splits on its head input dim, the MLP
    intermediate kernel+bias split on the feature dim and the MLP output
    kernel on its feature input dim. ``shared`` holds everything else
    (including row-parallel output biases, applied once post-psum)."""
    def shard(x, axis):
        parts = jnp.split(x, num_shards, axis=axis)
        return jnp.stack(parts)

    enc = params["bert"]["encoder"]
    tp_layers, sh_layers = {}, {}
    for name, lp in enc.items():
        a = lp["attention"]
        tp_layers[name] = {
            "attention": {
                **{k: {"kernel": shard(a[k]["kernel"], 1),
                       "bias": shard(a[k]["bias"], 0)}
                   for k in ("query", "key", "value")},
                "out": {"kernel": shard(a["out"]["kernel"], 0)},
            },
            "intermediate": {"kernel": shard(lp["intermediate"]["kernel"], 1),
                             "bias": shard(lp["intermediate"]["bias"], 0)},
            "output": {"kernel": shard(lp["output"]["kernel"], 0)},
        }
        sh_layers[name] = {
            "attention_out_bias": a["out"]["bias"],
            "output_bias": lp["output"]["bias"],
            "attention_ln": lp["attention_ln"],
            "output_ln": lp["output_ln"],
        }
    shared = {
        "embeddings": params["bert"]["embeddings"],
        "pooler": params["bert"]["pooler"],
        "mlm_dense": params["mlm_dense"],
        "mlm_ln": params["mlm_ln"],
        "mlm_bias": params["mlm_bias"],
        "nsp": params["nsp"],
        "layers": sh_layers,
    }
    return tp_layers, shared


def merge_tp(tp_layers, shared):
    """Inverse of :func:`split_tp`."""
    def unshard(x, axis):
        return jnp.concatenate([x[i] for i in range(x.shape[0])], axis=axis)

    enc = {}
    for name, lp in tp_layers.items():
        a = lp["attention"]
        sh = shared["layers"][name]
        enc[name] = {
            "attention": {
                **{k: {"kernel": unshard(a[k]["kernel"], 1),
                       "bias": unshard(a[k]["bias"], 0)}
                   for k in ("query", "key", "value")},
                "out": {"kernel": unshard(a["out"]["kernel"], 0),
                        "bias": sh["attention_out_bias"]},
            },
            "attention_ln": sh["attention_ln"],
            "intermediate": {
                "kernel": unshard(lp["intermediate"]["kernel"], 1),
                "bias": unshard(lp["intermediate"]["bias"], 0)},
            "output": {"kernel": unshard(lp["output"]["kernel"], 0),
                       "bias": sh["output_bias"]},
            "output_ln": sh["output_ln"],
        }
    return {
        "bert": {"embeddings": shared["embeddings"],
                 "encoder": enc,
                 "pooler": shared["pooler"]},
        "mlm_dense": shared["mlm_dense"],
        "mlm_ln": shared["mlm_ln"],
        "mlm_bias": shared["mlm_bias"],
        "nsp": shared["nsp"],
    }


def _tp_attention(tp, out_bias, x, attn_mask, axis_name):
    """H/P-head attention + row-parallel out projection (one psum)."""
    def proj(pp):
        return jnp.einsum("bte,ehd->bthd", x, pp["kernel"]) + pp["bias"]

    q = proj(tp["query"])                       # [B, T, Hl, D]
    k = proj(tp["key"])
    v = proj(tp["value"])
    d = q.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", q * (d ** -0.5), k)
    s = jnp.where(attn_mask, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p, v)
    partial = jnp.einsum("bthd,hde->bte", o, tp["out"]["kernel"])
    return lax.psum(partial, axis_name) + out_bias


def _tp_layer(tp, sh, x, attn_mask, cfg: BertConfig, axis_name):
    y = _tp_attention(tp["attention"], sh["attention_out_bias"], x,
                      attn_mask, axis_name)
    x = _layer_norm(sh["attention_ln"], x + y, cfg.layer_norm_eps)
    h = jnp.einsum("bte,ef->btf", x, tp["intermediate"]["kernel"]) \
        + tp["intermediate"]["bias"]
    h = jax.nn.gelu(h, approximate=False)
    partial = jnp.einsum("btf,fe->bte", h, tp["output"]["kernel"])
    h = lax.psum(partial, axis_name) + sh["output_bias"]
    return _layer_norm(sh["output_ln"], x + h, cfg.layer_norm_eps)


def bert_tp_loss(tp_layers, shared, batch, cfg: BertConfig,
                 axis_name: str = "model"):
    """Replicated-batch MLM+NSP loss with tensor-parallel layers (inside
    shard_map; ``tp_layers`` leaves are this rank's [1, ...] shard rows)."""
    import optax

    tp_local = jax.tree.map(lambda x: x[0], tp_layers)
    ids = batch["input_ids"]
    B, T = ids.shape
    emb = shared["embeddings"]
    positions = jnp.arange(T)[None, :]
    x = (emb["word_embeddings"]["embedding"][ids]
         + emb["position_embeddings"]["embedding"][positions]
         + emb["token_type_embeddings"]["embedding"][batch["token_type_ids"]])
    x = _layer_norm(emb["LayerNorm_0"], x, cfg.layer_norm_eps)

    mask = batch["attention_mask"][:, None, None, :].astype(bool)
    for i in range(cfg.num_layers):
        x = _tp_layer(tp_local[f"layer_{i}"],
                      shared["layers"][f"layer_{i}"], x, mask, cfg,
                      axis_name)

    pooled = jnp.tanh(_dense(shared["pooler"], x[:, 0]))
    h = _dense(shared["mlm_dense"], x)
    h = jax.nn.gelu(h, approximate=False)
    h = _layer_norm(shared["mlm_ln"], h, cfg.layer_norm_eps)
    table = emb["word_embeddings"]["embedding"]
    mlm = (jnp.einsum("bth,vh->btv", h, table.astype(cfg.dtype))
           + shared["mlm_bias"]).astype(jnp.float32)
    nsp = _dense(shared["nsp"], pooled).astype(jnp.float32)

    lmask = (batch["mlm_labels"] >= 0).astype(jnp.float32)
    safe = jnp.maximum(batch["mlm_labels"], 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(mlm, safe)
    mlm_loss = jnp.sum(per_tok * lmask) / jnp.maximum(jnp.sum(lmask), 1.0)
    nsp_loss = optax.softmax_cross_entropy_with_integer_labels(
        nsp, batch["nsp_labels"]).mean()
    return mlm_loss + nsp_loss


def make_tp_mesh(num_shards: int, devices=None) -> Mesh:
    import numpy as np
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < num_shards:
        raise ValueError(f"tensor parallelism needs {num_shards} devices, "
                         f"have {len(devices)}")
    return Mesh(np.asarray(devices[:num_shards]), ("model",))


def build_tp_loss(cfg: BertConfig, mesh: Mesh, axis_name: str = "model"):
    """jit ``(tp_stack, shared, batch) -> loss`` (batch replicated,
    tp_stack sharded over ``model``)."""
    def shard_fn(tp_layers, shared, batch):
        return bert_tp_loss(tp_layers, shared, batch, cfg, axis_name)

    mapped = jax.shard_map(shard_fn, mesh=mesh,
                           in_specs=(P(axis_name), P(), P()),
                           out_specs=P())
    return jax.jit(mapped)
