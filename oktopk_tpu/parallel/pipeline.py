"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

Reference parity target: the PipeDream-fork StageRuntime the reference ships
(BERT/runtime.py:55-1029) — stage partitioning, microbatch warmup, flush
loops (``run_training_loop_with_flushes`` :842 is the one its configs use),
recompute-in-backward (:546-558) — which in practice degenerates to pure DP
because the stage maps are disabled (SURVEY.md §2.3). Here the equivalent is
~80 lines of SPMD: every pipeline rank runs the same program on its own
stage's weights, microbatches hop stage-to-stage with ``ppermute``, and the
classic GPipe schedule (S + M - 1 ticks, bubble included) is a ``lax.scan``.

- "Flush" semantics: all M microbatches complete before the optimizer step —
  identical to the reference's GPipe-with-flushes loop, so no weight stashing
  is needed (stashing exists for PipeDream's 1F1B without flushes; the
  reference only ever runs flushed schedules in its shipped configs).
- Recompute-in-backward: wrap ``stage_fn`` in ``jax.checkpoint`` via
  ``remat=True`` — the XLA-native form of the reference's
  recompute-on-backward flag.
- Restriction: inter-stage activations must share one shape/dtype (true for
  the reference's BERT stages: [B, T, H] hidden states between BertLayers).
  First/last-stage specialisation (embedding in, loss head out) happens
  inside ``stage_fn`` by branching on ``stage_index``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from oktopk_tpu.comm import compat

from oktopk_tpu.comm.primitives import carry_vma as _carry_vma
from oktopk_tpu.comm.primitives import pvary_to as _pvary_to


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _bcast_from_last(x, axis_name):
    """Replicate the LAST stage's ``x`` to every rank.

    Value: ``psum`` of a last-stage-masked buffer (only one rank
    contributes). The custom VJP exists because under
    ``shard_map(..., check_vma=False)`` the default ``psum`` transpose is
    another ``psum``, which overcounts the (replicated) cotangent by the
    axis size — every rank's copy of the SAME downstream loss would be
    summed. The correct transpose of "broadcast from last" is "deliver the
    cotangent to last, zero elsewhere"."""
    P = compat.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    return lax.psum(jnp.where(s == P - 1, x, jnp.zeros_like(x)), axis_name)


def _bcast_from_last_fwd(x, axis_name):
    return _bcast_from_last(x, axis_name), None


def _bcast_from_last_bwd(axis_name, _res, ct):
    P = compat.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    return (jnp.where(s == P - 1, ct, jnp.zeros_like(ct)),)


_bcast_from_last.defvjp(_bcast_from_last_fwd, _bcast_from_last_bwd)




def gpipe_apply(stage_fn: Callable, stage_params, microbatches: jnp.ndarray,
                axis_name: str, num_microbatches: int,
                remat: bool = False) -> jnp.ndarray:
    """Run the pipeline forward over all microbatches.

    Must be called inside ``shard_map`` with ``axis_name`` in scope.

    Args:
      stage_fn: ``(params, x, stage_index, mb_index) -> y`` — this rank's
        stage. ``x`` and ``y`` must have identical shape/dtype. ``mb_index``
        is the microbatch this stage is processing this tick, so the stage
        can index replicated per-microbatch side inputs (attention masks,
        labels) without them riding the wire — the TPU form of the
        reference's named inter-stage tensors (BERT/runtime.py:450-458).
      stage_params: this rank's stage parameters (sharded over the axis).
      microbatches: [M, mb, ...] — the full input, replicated; only stage 0
        reads it.
      num_microbatches: M (static).
      remat: rematerialise stage activations in backward
        (reference recompute, BERT/runtime.py:546-558).

    Returns: [M, mb, ...] outputs of the LAST stage (replicated layout; other
      ranks' rows are garbage and are masked by the caller via psum — see
      ``gpipe_loss``).
    """
    P = compat.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = num_microbatches
    fn = jax.checkpoint(stage_fn, static_argnums=()) if remat else stage_fn

    x_shape = microbatches.shape[1:]
    vma = _carry_vma(microbatches, stage_params, axis_name=axis_name)
    zeros = _pvary_to(jnp.zeros(x_shape, microbatches.dtype), vma)
    outputs = _pvary_to(jnp.zeros((M,) + x_shape, microbatches.dtype), vma)

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 injects microbatch t (while t < M); others take the wire
        inject = lax.dynamic_index_in_dim(microbatches,
                                          jnp.clip(t, 0, M - 1), 0,
                                          keepdims=False)
        x = jnp.where(stage == 0, inject, incoming)
        # stage s processes microbatch t - s this tick
        mb_idx = jnp.clip(t - stage, 0, M - 1)
        y = fn(stage_params, x, stage, mb_idx)
        # last stage banks its result for microbatch t - (P - 1)
        out_idx = jnp.clip(t - (P - 1), 0, M - 1)
        bank = (stage == P - 1) & (t >= P - 1)
        current = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, y, current), out_idx, 0)
        # hop: stage i -> i+1 (last stage's send is discarded at stage 0)
        perm = [(i, (i + 1) % P) for i in range(P)]
        incoming = lax.ppermute(y, axis_name, perm)
        return (incoming, outputs), None

    (_, outputs), _ = lax.scan(tick, (zeros, outputs),
                               jnp.arange(M + P - 1))
    # every rank wrote only its own view; the real outputs live on the last
    # stage — broadcast them (transpose-correct under jax.grad)
    return _bcast_from_last(outputs, axis_name)


def gpipe_loss(stage_fn: Callable, loss_fn: Callable, stage_params,
               microbatches, targets, axis_name: str,
               num_microbatches: int, remat: bool = False):
    """Mean loss over microbatches through the pipeline (differentiable —
    XLA transposes ppermute, so ``jax.grad`` of this is pipeline backward)."""
    outs = gpipe_apply(stage_fn, stage_params, microbatches, axis_name,
                       num_microbatches, remat)
    losses = jax.vmap(loss_fn)(outs, targets)
    return jnp.mean(losses)


def one_f_one_b(stage_fn: Callable, loss_fn: Callable, stage_params,
                microbatches, targets, axis_name: str,
                num_microbatches: int):
    """1F1B-with-flushes schedule: ``(mean_loss, stage_grads)``.

    Reference parity: ``run_training_loop_with_flushes`` with the 1F1B
    ordering (BERT/runtime.py:740 — warmup forwards, steady-state alternate
    fwd/bwd, drain backwards, step at the flush). Numerically identical to
    ``jax.grad(gpipe_loss)`` (same weights for every microbatch — a flush —
    so no weight stashing is needed; stashing lives in
    ``optim/stashing.py`` for the no-flush PipeDream mode), but the
    activation footprint is O(P) ring slots instead of GPipe's O(M):
    each tick runs one forward slot and one backward slot, and a microbatch's
    stage input is held only until its backward drains,
    2·(P−1−s) ticks later.

    Backward is explicit per-stage ``jax.vjp`` on the stashed stage INPUT —
    i.e. within-stage activations are recomputed in backward, the XLA-native
    form of the reference's recompute flag (BERT/runtime.py:546-558,666-667).

    Schedule (tick t, stage s, P stages, M microbatches, T = M + 2P − 2):
      forward of microbatch m at t = m + s;
      backward of microbatch m at t = m + 2(P−1) − s
      (last stage back-props a microbatch the same tick it forwards it).
    Cotangents hop down one stage per tick via ``ppermute``.

    Same restrictions as ``gpipe_apply``: call inside ``shard_map``;
    activations share one shape/dtype; ``stage_fn(params, x, stage_index)``.
    Returns each rank's OWN stage grads (sharded over ``axis_name``) and the
    replicated mean loss.
    """
    P = compat.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = num_microbatches
    W = 2 * P - 1  # max microbatches in flight at stage 0, inclusive

    x_shape = microbatches.shape[1:]
    dtype = microbatches.dtype
    zeros_x = jnp.zeros(x_shape, dtype)
    up = [(i, (i + 1) % P) for i in range(P)]
    down = [(i, (i - 1) % P) for i in range(P)]

    def tick(carry, t):
        fwd_wire, bwd_wire, stash, gacc, lacc = carry

        # -- forward slot: microbatch m_f = t - s
        m_f = t - stage
        do_f = (m_f >= 0) & (m_f < M)
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(m_f, 0, M - 1), 0, keepdims=False)
        x = jnp.where(stage == 0, inject, fwd_wire)
        y = stage_fn(stage_params, x, stage, jnp.clip(m_f, 0, M - 1))
        slot_f = jnp.mod(m_f, W)
        held = lax.dynamic_index_in_dim(stash, slot_f, 0, keepdims=False)
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(do_f, x, held), slot_f, 0)

        # -- backward slot: microbatch m_b = t - 2(P-1) + s
        m_b = t - 2 * (P - 1) + stage
        do_b = (m_b >= 0) & (m_b < M)
        slot_b = jnp.mod(m_b, W)
        x_b = lax.dynamic_index_in_dim(stash, slot_b, 0, keepdims=False)
        tgt = lax.dynamic_index_in_dim(
            targets, jnp.clip(m_b, 0, M - 1), 0, keepdims=False)
        mb_b = jnp.clip(m_b, 0, M - 1)
        y_b, vjp = jax.vjp(lambda p, xx: stage_fn(p, xx, stage, mb_b),
                           stage_params, x_b)
        l, dldy = jax.value_and_grad(
            lambda yy: loss_fn(yy, tgt))(y_b)
        ct_out = jnp.where(stage == P - 1, dldy, bwd_wire)
        gp, ct_in = vjp(ct_out)
        gacc = jax.tree.map(
            lambda a, g: a + jnp.where(do_b, g, jnp.zeros_like(g)), gacc, gp)
        lacc = lacc + jnp.where(do_b & (stage == P - 1),
                                l.astype(jnp.float32), 0.0)

        # -- wires hop: activations up, cotangents down
        fwd_wire = lax.ppermute(jnp.where(do_f, y, jnp.zeros_like(y)),
                                axis_name, up)
        bwd_wire = lax.ppermute(
            jnp.where(do_b, ct_in, jnp.zeros_like(ct_in)), axis_name, down)
        return (fwd_wire, bwd_wire, stash, gacc, lacc), None

    vma = _carry_vma(microbatches, stage_params, targets,
                     axis_name=axis_name)
    init = (_pvary_to(zeros_x, vma), _pvary_to(zeros_x, vma),
            _pvary_to(jnp.zeros((W,) + x_shape, dtype), vma),
            jax.tree.map(lambda p: _pvary_to(jnp.zeros_like(p), vma),
                         stage_params),
            _pvary_to(jnp.zeros((), jnp.float32), vma))
    (_, _, _, gacc, lacc), _ = lax.scan(tick, init,
                                        jnp.arange(M + 2 * P - 2))
    loss = lax.psum(lacc, axis_name) / M
    grads = jax.tree.map(lambda g: g / M, gacc)
    return loss, grads
