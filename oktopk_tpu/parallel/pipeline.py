"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

Reference parity target: the PipeDream-fork StageRuntime the reference ships
(BERT/runtime.py:55-1029) — stage partitioning, microbatch warmup, flush
loops (``run_training_loop_with_flushes`` :842 is the one its configs use),
recompute-in-backward (:546-558) — which in practice degenerates to pure DP
because the stage maps are disabled (SURVEY.md §2.3). Here the equivalent is
~80 lines of SPMD: every pipeline rank runs the same program on its own
stage's weights, microbatches hop stage-to-stage with ``ppermute``, and the
classic GPipe schedule (S + M - 1 ticks, bubble included) is a ``lax.scan``.

- "Flush" semantics: all M microbatches complete before the optimizer step —
  identical to the reference's GPipe-with-flushes loop, so no weight stashing
  is needed (stashing exists for PipeDream's 1F1B without flushes; the
  reference only ever runs flushed schedules in its shipped configs).
- Recompute-in-backward: wrap ``stage_fn`` in ``jax.checkpoint`` via
  ``remat=True`` — the XLA-native form of the reference's
  recompute-on-backward flag.
- Restriction: inter-stage activations must share one shape/dtype (true for
  the reference's BERT stages: [B, T, H] hidden states between BertLayers).
  First/last-stage specialisation (embedding in, loss head out) happens
  inside ``stage_fn`` by branching on ``stage_index``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe_apply(stage_fn: Callable, stage_params, microbatches: jnp.ndarray,
                axis_name: str, num_microbatches: int,
                remat: bool = False) -> jnp.ndarray:
    """Run the pipeline forward over all microbatches.

    Must be called inside ``shard_map`` with ``axis_name`` in scope.

    Args:
      stage_fn: ``(params, x, stage_index) -> y`` — this rank's stage.
        ``x`` and ``y`` must have identical shape/dtype.
      stage_params: this rank's stage parameters (sharded over the axis).
      microbatches: [M, mb, ...] — the full input, replicated; only stage 0
        reads it.
      num_microbatches: M (static).
      remat: rematerialise stage activations in backward
        (reference recompute, BERT/runtime.py:546-558).

    Returns: [M, mb, ...] outputs of the LAST stage (replicated layout; other
      ranks' rows are garbage and are masked by the caller via psum — see
      ``gpipe_loss``).
    """
    P = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = num_microbatches
    fn = jax.checkpoint(stage_fn, static_argnums=()) if remat else stage_fn

    x_shape = microbatches.shape[1:]
    zeros = jnp.zeros(x_shape, microbatches.dtype)
    outputs = jnp.zeros((M,) + x_shape, microbatches.dtype)

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 injects microbatch t (while t < M); others take the wire
        mb_idx = jnp.clip(t, 0, M - 1)
        inject = lax.dynamic_index_in_dim(microbatches, mb_idx, 0,
                                          keepdims=False)
        x = jnp.where(stage == 0, inject, incoming)
        y = fn(stage_params, x, stage)
        # last stage banks its result for microbatch t - (P - 1)
        out_idx = jnp.clip(t - (P - 1), 0, M - 1)
        bank = (stage == P - 1) & (t >= P - 1)
        current = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, y, current), out_idx, 0)
        # hop: stage i -> i+1 (last stage's send is discarded at stage 0)
        perm = [(i, (i + 1) % P) for i in range(P)]
        incoming = lax.ppermute(y, axis_name, perm)
        return (incoming, outputs), None

    (_, outputs), _ = lax.scan(tick, (zeros, outputs),
                               jnp.arange(M + P - 1))
    # every rank wrote only its own view; the real outputs live on the last
    # stage — broadcast them with a masked psum
    outputs = lax.psum(
        jnp.where(stage == P - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs


def gpipe_loss(stage_fn: Callable, loss_fn: Callable, stage_params,
               microbatches, targets, axis_name: str,
               num_microbatches: int, remat: bool = False):
    """Mean loss over microbatches through the pipeline (differentiable —
    XLA transposes ppermute, so ``jax.grad`` of this is pipeline backward)."""
    outs = gpipe_apply(stage_fn, stage_params, microbatches, axis_name,
                       num_microbatches, remat)
    losses = jax.vmap(loss_fn)(outs, targets)
    return jnp.mean(losses)
