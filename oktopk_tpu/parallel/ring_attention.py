"""Ring attention: exact attention over a sequence-sharded mesh axis.

Sequence/context parallelism is absent from the reference (max_seq_length is
a plain flag, attention is vanilla quadratic BertSelfAttention — SURVEY.md
§5.7); on TPU it is a first-class scaling axis. This is the standard ring
formulation: queries stay resident, key/value blocks rotate around the ring
via ``ppermute`` (one ICI hop per step), and softmax is accumulated online
(running max + normaliser), so the full [T, T] score matrix never
materialises and sequence length scales linearly with the number of devices.

Pure function, usable inside ``shard_map`` with a ``seq`` axis; wraps into
``ring_self_attention`` for Flax modules.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from oktopk_tpu.comm import compat


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, kv_mask: Optional[jnp.ndarray] = None,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Exact softmax attention with K/V ring rotation.

    Args:
      q, k, v: local shards [B, T_local, H, D].
      kv_mask: optional [B, T_local] bool — True where the key position is
        attendable (padding mask). Rotates with k/v.
      scale: defaults to 1/sqrt(D).

    Returns: [B, T_local, H, D] attention output for the local queries.
    """
    P = compat.axis_size(axis_name)
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    q = q * scale

    neg = jnp.asarray(-1e30, jnp.float32)
    B, T, H, _ = q.shape
    m = jnp.full((B, T, H), neg, jnp.float32)       # running max
    l = jnp.zeros((B, T, H), jnp.float32)           # running normaliser
    o = jnp.zeros(q.shape, jnp.float32)             # running output

    if kv_mask is None:
        kv_mask = jnp.ones(k.shape[:2], bool)

    def body(carry, _):
        m, l, o, kk, vv, mask = carry
        # scores for this K/V block: [B, T, H, Tk]
        s = jnp.einsum("bthd,bshd->bths", q, kk).astype(jnp.float32)
        s = jnp.where(mask[:, None, None, :], s, neg)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bths,bshd->bthd", p, vv.astype(jnp.float32))
        # rotate K/V (and their mask) one hop around the ring
        perm = [(i, (i + 1) % P) for i in range(P)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        mask = lax.ppermute(mask, axis_name, perm)
        return (m_new, l_new, o_new, kk, vv, mask), None

    # carry must be varying over every axis the inputs vary over (e.g. a
    # composed data x seq mesh), not just the ring axis
    from oktopk_tpu.comm.primitives import carry_vma, pvary_to
    vma = carry_vma(q, k, v, kv_mask, axis_name=axis_name)
    init = jax.tree.map(lambda x: pvary_to(x, vma),
                        (m, l, o, k, v, kv_mask))
    (m, l, o, _, _, _), _ = lax.scan(body, init, None, length=P)
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_self_attention(x: jnp.ndarray, wq, wk, wv, wo, num_heads: int,
                        axis_name: str,
                        kv_mask: Optional[jnp.ndarray] = None):
    """Projection + ring attention + output projection (a functional
    building block for sequence-sharded transformer layers).

    x: [B, T_local, E]; wq/wk/wv: [E, H*D]; wo: [H*D, E].
    """
    B, T, E = x.shape
    D = wq.shape[1] // num_heads
    proj = lambda w: jnp.einsum("bte,ef->btf", x, w).reshape(B, T, num_heads, D)
    out = ring_attention(proj(wq), proj(wk), proj(wv), axis_name,
                         kv_mask=kv_mask)
    return jnp.einsum("btf,fe->bte", out.reshape(B, T, num_heads * D), wo)
