"""Fault injection, in-step anomaly guard, and supervised dense-fallback.

Ok-Topk's error-feedback residuals make sparse training *stateful*: one
NaN/Inf gradient or corrupted wire payload poisons every subsequent step
through the residual, and the reference only ever *warns* on NaN gradient
sparsity (VGG/dl_trainer.py:608-609). The gradient-compression systems
literature (PAPERS.md: "On the Utility of Gradient Compression...",
arXiv 2103.00543; SparCML, arXiv 1802.08021) shows sparse pipelines are
exactly where silent numeric corruption and degraded-fabric behaviour
diverge from dense. This package closes the loop in three layers:

1. `faults`     — deterministic, step-indexed :class:`FaultPlan` with
   injection seams for NaN/Inf gradients, corrupted sparse wire payloads
   (bit-flip / zeroed values at the ``collectives/wire.py`` seam) and
   per-step collective latency inflation. Pure/config-driven so the CPU
   tier-1 suite exercises every path.
2. `guard`      — a jitted in-step anomaly guard: psum a finite-agreement
   flag so all replicas deterministically agree, then skip the optimizer
   update AND roll back the compressor residual/threshold update for the
   step (no error-feedback poisoning), emitting ``steps_skipped``.
3. `supervisor` — host-side escalation: consecutive-anomaly and
   per-bucket strike counters; after N strikes on a bucket its plan flips
   to ``dense`` (reusing the autotune plan-rebuild machinery in
   ``Trainer``); unrecoverable divergence restores from the last good
   checkpoint via ``train/checkpoint.py``.
4. `journal`    — JSONL health log (same shape as ``autotune/journal.py``):
   every fault seen, guard trip, fallback and restore, with step index
   and bucket id.

On top of the detectors sit the closed-loop policies (this PR's
"self-healing control plane", docs/RESILIENCE.md "Closed-loop
policies"):

5. `faults.dead_workers` + ``Supervisor.note_chip_loss`` — chip loss
   escalates straight to a ``remesh`` action; the trainer resizes onto
   the surviving devices without a requeue.
6. `feedback`   — :class:`AutotuneFeedback` watches the obs bus for
   sustained ``regression``/``guard_trip`` streams and forces an
   autotune re-calibrate + re-tune.
7. `density`    — :class:`DensityBackoff` hysteretically backs the
   effective selection density off under repeated near-``abs_limit``
   guard pressure, re-advancing after a clean streak.
8. `drills`     — the deterministic chaos-drill catalog behind
   ``scripts/chaos_drill.py`` and the ``chaos``-marked tests: scripted
   incidents asserting both recovery and the journalled timeline.
"""

from oktopk_tpu.resilience.density import DensityBackoff  # noqa: F401
from oktopk_tpu.resilience.faults import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    dead_workers,
    inject_grad_faults,
    latency_ms,
    make_wire_hook,
    with_latency,
)
from oktopk_tpu.resilience.feedback import AutotuneFeedback  # noqa: F401
from oktopk_tpu.resilience.guard import (  # noqa: F401
    GuardConfig,
    HealthState,
    init_health,
)
from oktopk_tpu.resilience.journal import HealthJournal  # noqa: F401
from oktopk_tpu.resilience.supervisor import (  # noqa: F401
    Action,
    Supervisor,
)
