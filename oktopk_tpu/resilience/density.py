"""Guard-aware density backoff: retreat the schedule under guard pressure.

A ``density_schedule`` marches density upward on a fixed step schedule,
oblivious to what the guard is seeing. When reduced-gradient magnitudes
repeatedly crowd the guard's ``abs_limit`` (or trip it outright), every
additional selected coordinate is another near-absurd value delivered
into the optimizer and another poisoned entry in the error-feedback
residual. This controller is the closed-loop answer: after
``backoff_steps`` consecutive pressured steps it halves (``factor``) the
*effective* density — bounded by ``max_level`` — and only re-advances
one level per ``clean_streak`` consecutive clean steps, so the schedule
is hysteretic in both directions and cannot oscillate on a flapping
fault.

The scale multiplies the schedule's (or per-bucket plan's) densities at
step-build time; capacity sizing stays pinned to ``cfg.density``, so
backing off never re-sizes wire buffers — it only shrinks k. Every level
change is journalled as a ``density_backoff`` event (direction, level,
scale, trigger), giving the run journal the full pressure/relief
timeline next to the guard trips that caused it.

Pressure is either signal the guarded step already computes:
``reduced_absmax`` entering the near band (``near_ratio * abs_limit``)
without tripping, or an outright guard skip. Host-side, plain ints — no
tracing, no recompiles except at an actual level change.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class DensityBackoff:
    """Hysteretic level controller over guard pressure.

    ``observe`` returns None on no change, or a journal-ready dict
    ``{"direction": "backoff"|"advance", "level": int, "scale": float,
    "trigger": str}`` when the level moved (the caller applies
    ``scale`` to its densities and rebuilds the step).
    """

    def __init__(self, abs_limit: float, near_ratio: float = 0.1,
                 backoff_steps: int = 3, factor: float = 0.5,
                 max_level: int = 3, clean_streak: int = 8):
        if not (0.0 < factor < 1.0):
            raise ValueError(f"factor must be in (0, 1), got {factor}")
        for name, val in (("backoff_steps", backoff_steps),
                          ("max_level", max_level),
                          ("clean_streak", clean_streak)):
            if int(val) < 1:
                raise ValueError(f"{name} must be >= 1, got {val}")
        self.abs_limit = float(abs_limit)
        self.near_ratio = float(near_ratio)
        self.backoff_steps = int(backoff_steps)
        self.factor = float(factor)
        self.max_level = int(max_level)
        self.clean_streak = int(clean_streak)
        self.level = 0
        self._near = 0
        self._clean = 0
        self._fidelity = 0  # consecutive quality-breach signals

    @property
    def scale(self) -> float:
        return self.factor ** self.level

    def observe(self, step: int, absmax: float = 0.0,
                skipped: int = 0) -> Optional[Dict[str, Any]]:
        """Digest one step's guard pressure; return a level change."""
        absmax = float(absmax)
        # NaN absmax means the step carried nonfinites — the skip flag is
        # the authoritative signal there (NaN comparisons are False).
        near = bool(skipped) or (absmax == absmax
                                 and absmax > self.near_ratio * self.abs_limit)
        if near:
            self._near += 1
            self._clean = 0
            if self._near >= self.backoff_steps and self.level < self.max_level:
                self.level += 1
                self._near = 0
                return {"direction": "backoff", "level": self.level,
                        "scale": self.scale,
                        "trigger": "guard_skip" if skipped else "near_abs_limit"}
        else:
            self._clean += 1
            self._near = 0
            if self._clean >= self.clean_streak and self.level > 0:
                self.level -= 1
                self._clean = 0
                return {"direction": "advance", "level": self.level,
                        "scale": self.scale, "trigger": "clean_streak"}
        return None

    def note_quality_breach(self, step: int,
                            kind: str) -> Optional[Dict[str, Any]]:
        """Digest one fidelity breach from a ``quality_rollup`` — the
        other half of the closed loop. Guard pressure pushes the level
        DOWN (less density); sustained residual-growth / compression-
        error breaches mean the compressed stream is no longer carrying
        the gradient, so after ``backoff_steps`` such signals the level
        advances back UP one notch (more density). Breach kinds that
        argue for LESS density (``churn_spike``, ``density_collapse``)
        are deliberately not counted here: churn is a selection-
        stability symptom and collapse is a downstream effect of this
        very controller. Same journal-ready return contract as
        :meth:`observe`."""
        if kind not in ("residual_growth", "comp_err"):
            return None
        self._fidelity += 1
        if self._fidelity >= self.backoff_steps and self.level > 0:
            self.level -= 1
            self._fidelity = 0
            self._clean = 0
            return {"direction": "advance", "level": self.level,
                    "scale": self.scale, "trigger": "quality_breach"}
        return None
