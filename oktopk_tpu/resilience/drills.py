"""Deterministic chaos drills: scripted incidents, asserted recoveries.

Each drill runs one end-to-end incident scenario on the emulated CPU
mesh — real jitted steps, real collectives, a deterministic
:class:`~oktopk_tpu.resilience.FaultPlan` — and checks BOTH sides of the
contract: the training outcome (params carried bit-identically, loss
trajectory continuing, no divergence) and the journalled incident
timeline (the unified run journal validates and carries the causal
chain in order). A drill that only checked recovery could pass while
the journal rots; one that only checked the journal could pass while
training silently diverges.

The catalog (``DRILLS``) is shared by ``scripts/chaos_drill.py`` (the
operator-facing CLI) and the ``chaos``-marked tier-1 tests
(tests/test_chaos_drills.py), so the drill an operator runs against a
config change is byte-for-byte the drill CI runs:

- ``chip_loss``       — a rank dies mid-run; the supervisor escalates
  to ``remesh`` and training resumes on the shrunk mesh without a
  requeue (chain: ``fault_seen(chip_loss)`` → ``remesh`` → first
  post-resize ``step``).
- ``latency_retune``  — a sustained latency fault degrades step time;
  the feedback policy forces a re-calibrate + re-tune and the plan
  flips to the algorithm that tolerates the degraded fabric (chain:
  ``regression``... → ``retune`` → ``calibration`` →
  ``autotune_decision``).
- ``density_backoff`` — repeated guard-pressure steps back the
  effective density off hysteretically, then a clean streak re-advances
  it; the same fault without the guard diverges (the contrast case).
- ``ckpt_corruption`` — the supervisor's restore target is damaged at
  rest (each of truncate / bitflip / torn); the divergence-triggered
  restore must fall back to the older *verified* checkpoint
  bit-identically, with ``ckpt_verify_failed`` preceding ``restore`` in
  the journal — plus the async-save drain and legacy-checkpoint
  contracts of the durable state plane (train/durable.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from oktopk_tpu.config import OkTopkConfig, TrainConfig
from oktopk_tpu.data.synthetic import synthetic_batch
from oktopk_tpu.obs.events import validate_journal
from oktopk_tpu.resilience.faults import FaultPlan, FaultSpec, latency_ms

DEFAULT_DNN = "mnistnet"


@dataclasses.dataclass
class DrillReport:
    """Outcome of one drill: named checks + the journal that proves it."""

    name: str
    checks: List[Tuple[str, bool, str]]   # (check, passed, detail)
    journal: List[Dict[str, Any]]
    notes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return all(passed for _, passed, _ in self.checks)

    def failed(self) -> List[str]:
        return [f"{name}: {detail}" for name, passed, detail in self.checks
                if not passed]

    def summary(self) -> str:
        lines = [f"drill {self.name}: {'PASS' if self.ok else 'FAIL'}"]
        for name, passed, detail in self.checks:
            mark = "ok" if passed else "FAIL"
            lines.append(f"  [{mark:4s}] {name}" + (f" — {detail}"
                                                    if detail else ""))
        for k, v in self.notes.items():
            lines.append(f"  note {k}: {v}")
        return "\n".join(lines)


def _check(checks: List[Tuple[str, bool, str]], name: str, passed: bool,
           detail: str = "") -> None:
    checks.append((name, bool(passed), detail))


def _drill_trainer(mesh, fault_plan: Optional[FaultPlan] = None,
                   algo_over: Optional[Dict[str, Any]] = None,
                   **cfg_over):
    """A small, fast, fully-instrumented trainer: mnistnet + oktopk on
    the emulated mesh with warmup off and every recompute cadence at 1
    (the same setpoints the resilience tests use), obs + resilience on
    unless overridden."""
    from oktopk_tpu.train.trainer import Trainer

    kw: Dict[str, Any] = dict(
        dnn=DEFAULT_DNN, dataset="mnist", batch_size=8, lr=0.05,
        compressor="oktopk", density=0.05, num_buckets=1,
        resilience=True, resilience_cooldown=0, obs=True)
    kw.update(cfg_over)
    cfg = TrainConfig(**kw)
    acfg = OkTopkConfig(warmup_steps=0, local_recompute_every=1,
                        global_recompute_every=1, repartition_every=1,
                        **(algo_over or {}))
    return Trainer(cfg, mesh=mesh, algo_cfg=acfg, warmup=False,
                   fault_plan=fault_plan)


def _batches(dnn: str, batch_size: int, seed: int = 9):
    rng = np.random.RandomState(seed)
    while True:
        yield synthetic_batch(dnn, batch_size, rng)


def _leaves_equal(a, b) -> bool:
    fa = jax.tree.leaves(a)
    fb = jax.tree.leaves(b)
    return len(fa) == len(fb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(fa, fb))


def _event_indices(journal, event: str, **match):
    out = []
    for i, e in enumerate(journal):
        if e.get("event") != event:
            continue
        if all(e.get(k) == v for k, v in match.items()):
            out.append(i)
    return out


# ---- drill: chip loss → remesh -----------------------------------------

def drill_chip_loss(mesh=None, steps_before: int = 3, steps_after: int = 3,
                    lose_worker: int = 5, per_worker_bs: int = 2
                    ) -> DrillReport:
    """Rank ``lose_worker`` dies at step ``steps_before``; the supervisor
    must emit ``remesh``, the trainer must resume on the shrunk mesh with
    params bit-identical across the resize and the loss trajectory
    continuing — no requeue, no restore."""
    from oktopk_tpu.comm.mesh import get_mesh

    mesh = mesh if mesh is not None else get_mesh()
    P = int(mesh.shape["data"])
    assert 0 <= lose_worker < P, "lose_worker must be a live rank"
    k = steps_before  # the supervise step at which the chip is seen dead
    plan = FaultPlan((FaultSpec("chip_loss", step=k, worker=lose_worker),))
    tr = _drill_trainer(mesh, fault_plan=plan)
    checks: List[Tuple[str, bool, str]] = []
    losses: List[float] = []
    batches_full = _batches(DEFAULT_DNN, P * per_worker_bs)
    batches_shrunk = _batches(DEFAULT_DNN, (P - 1) * per_worker_bs, seed=10)

    params_pre = params_post = None
    strikes_after_remesh = 0
    for step in range(1, steps_before + steps_after + 1):
        pre_resize = step <= k
        m = tr.train_step(next(batches_full if pre_resize
                               else batches_shrunk))
        losses.append(float(np.asarray(m["loss"]).mean()))
        tr.bus.emit("step", step=step, loss=losses[-1],
                    step_skipped=int(np.asarray(
                        m.get("step_skipped", 0))))
        if step == k:
            params_pre = jax.device_get(tr.state.params)
            # seed a strike right before the remesh so the drill can
            # prove supervisor counters are carried (not reset) through
            # the resize; the step's own clean observe() decays it by
            # exactly one
            tr.supervisor.strikes[0] = 2
        tr.supervise(step, m)
        if step == k:
            params_post = jax.device_get(tr.state.params)
            strikes_after_remesh = tr.supervisor.strikes[0]

    journal = list(tr.run_journal.entries)
    _check(checks, "remesh_emitted",
           tr.supervisor.remesh_events == 1
           and len(_event_indices(journal, "remesh")) == 1,
           f"remesh_events={tr.supervisor.remesh_events}")
    rm = [journal[i] for i in _event_indices(journal, "remesh")]
    if rm:
        e = rm[0]
        _check(checks, "remesh_fields",
               e["old_world"] == P and e["new_world"] == P - 1
               and e["trigger"] == "chip_loss"
               and e["dead_workers"] == [lose_worker]
               and "health" in e["carried"]
               and "supervisor" in e["carried"],
               f"remesh event: {e}")
    else:
        _check(checks, "remesh_fields", False, "no remesh event")
    _check(checks, "world_shrunk",
           tr.cfg.num_workers == P - 1
           and int(np.asarray(tr.mesh.devices).size) == P - 1,
           f"num_workers={tr.cfg.num_workers}")
    _check(checks, "params_bit_identical",
           params_pre is not None and _leaves_equal(params_pre, params_post),
           "params changed across resize")
    _check(checks, "loss_continuing",
           all(np.isfinite(losses)) and len(losses) == steps_before
           + steps_after,
           f"losses={losses}")
    _check(checks, "no_requeue_no_restore",
           tr.supervisor.restore_events == 0
           and not _event_indices(journal, "restore")
           and not _event_indices(journal, "restore_unavailable"),
           "restore path fired")
    _check(checks, "strikes_carried", strikes_after_remesh == 1,
           f"strikes after remesh step: {strikes_after_remesh}")
    idx_fault = _event_indices(journal, "fault_seen", kind="chip_loss")
    idx_remesh = _event_indices(journal, "remesh")
    idx_post = [i for i, e in enumerate(journal)
                if e.get("event") == "step" and e.get("step", 0) > k]
    _check(checks, "journal_chain",
           bool(idx_fault and idx_remesh and idx_post)
           and idx_fault[0] < idx_remesh[0] < idx_post[0],
           f"fault@{idx_fault} remesh@{idx_remesh} post-step@{idx_post[:1]}")
    problems = validate_journal(journal)
    _check(checks, "journal_valid", not problems, "; ".join(problems[:3]))
    return DrillReport("chip_loss", checks, journal,
                       notes={"losses": losses,
                              "world": f"{P}->{tr.cfg.num_workers}"})


# ---- drill: sustained latency → forced re-tune --------------------------

def drill_latency_retune(mesh=None, fault_step: int = 4,
                         fault_duration: int = 6,
                         fault_latency_ms: float = 40.0,
                         num_steps: int = 14, per_worker_bs: int = 2
                         ) -> DrillReport:
    """A sustained latency fault inflates the sparse path's step time;
    the regression stream must trip the feedback policy, which forces a
    re-calibrate + re-tune, and the plan must flip to the algorithm that
    tolerates the degraded fabric (dense: one exchange round instead of
    the sparse path's several). Step time recovers once the fault
    clears."""
    from oktopk_tpu.comm.mesh import get_mesh
    from oktopk_tpu.obs.regress import RegressionDetector

    mesh = mesh if mesh is not None else get_mesh()
    P = int(mesh.shape["data"])
    plan = FaultPlan((FaultSpec("latency", step=fault_step,
                                duration=fault_duration,
                                latency_ms=fault_latency_ms),))
    tr = _drill_trainer(
        mesh, resilience=False, autotune=True,
        autotune_candidates=("dense", "oktopk"),
        resilience_feedback=True, resilience_feedback_window=16,
        resilience_feedback_signals=3,
        resilience_feedback_cooldown=100)
    baseline_ms = 10.0
    tolerance = 1.5
    tr.regress = RegressionDetector(baseline_ms=baseline_ms,
                                    tolerance=tolerance, warmup_windows=0,
                                    bus=tr.bus, key="drill_step_ms")

    # deterministic fabric model through the trial seam: the multi-round
    # sparse exchange pays the injected latency several times per step,
    # dense pays it once — so the degraded-fabric optimum flips
    base = {"dense": 8.0, "oktopk": 5.0}
    cur = {"step": 0}

    def fake(algo: str, n: int, density: float) -> float:
        mult = 1.0 if algo == "dense" else 3.0
        return base.get(algo, 6.0) + mult * latency_ms(plan, cur["step"])

    tr.autotune(step=0, fake_ms=fake)
    checks: List[Tuple[str, bool, str]] = []
    initial_algo = tr._plans[0].algo if tr._plans else "?"
    _check(checks, "initial_plan_sparse", initial_algo == "oktopk",
           f"initial plan: {initial_algo}")
    retune_at = None
    ms_trace: List[float] = []
    batches = _batches(DEFAULT_DNN, P * per_worker_bs)
    for step in range(1, num_steps + 1):
        cur["step"] = step
        m = tr.train_step(next(batches))
        # simulated wall clock: the current plan's algorithm on the
        # currently degraded fabric (same model the trial seam uses)
        algo = tr._plans[0].algo if tr._plans else "oktopk"
        mult = 1.0 if algo == "dense" else 3.0
        ms = base.get(algo, 6.0) + mult * latency_ms(plan, step)
        ms_trace.append(ms)
        tr.bus.emit("step", step=step,
                    loss=float(np.asarray(m["loss"]).mean()), dt_ms=ms)
        tr.regress.observe(step, ms)
        if tr.check_feedback(step) is not None and retune_at is None:
            retune_at = step

    journal = list(tr.run_journal.entries)
    idx_reg = _event_indices(journal, "regression")
    idx_retune = _event_indices(journal, "retune")
    idx_cal = _event_indices(journal, "calibration")
    idx_dec = _event_indices(journal, "autotune_decision")
    _check(checks, "regressions_seen", len(idx_reg) >= 3,
           f"{len(idx_reg)} regression events")
    _check(checks, "retune_fired",
           tr.retune_events == 1 and len(idx_retune) == 1
           and retune_at is not None,
           f"retune_events={tr.retune_events} at step {retune_at}")
    if idx_retune:
        e = journal[idx_retune[0]]
        _check(checks, "retune_evidence",
               e["trigger"] in ("regression", "guard_trip")
               and len(e.get("signals", [])) >= 3
               and idx_reg and idx_reg[0] < idx_retune[0],
               f"retune event: {e}")
        recal = [i for i in idx_cal if i > idx_retune[0]]
        redec = [i for i, j in ((i, journal[i]) for i in idx_dec)
                 if i > idx_retune[0]
                 and j.get("chosen", {}).get("algo") == "dense"]
        _check(checks, "chain_retune_calibration_decision",
               bool(recal) and bool(redec) and recal[0] < redec[0],
               f"retune@{idx_retune[0]} cal@{recal[:1]} dense-dec@{redec[:1]}")
    else:
        _check(checks, "retune_evidence", False, "no retune event")
        _check(checks, "chain_retune_calibration_decision", False,
               "no retune event")
    final_algo = tr._plans[0].algo if tr._plans else "?"
    _check(checks, "plan_flipped_dense", final_algo == "dense",
           f"final plan: {final_algo}")
    _check(checks, "step_time_recovered",
           ms_trace[-1] <= tolerance * baseline_ms,
           f"final step {ms_trace[-1]:.1f} ms vs "
           f"threshold {tolerance * baseline_ms:.1f} ms")
    problems = validate_journal(journal)
    _check(checks, "journal_valid", not problems, "; ".join(problems[:3]))
    return DrillReport("latency_retune", checks, journal,
                       notes={"ms_trace": ms_trace,
                              "retune_at": retune_at,
                              "plan": f"{initial_algo}->{final_algo}"})


# ---- drill: guard pressure → density backoff ----------------------------

def drill_density_backoff(mesh=None, clean_before: int = 3,
                          fault_duration: int = 5, scale: float = 1e8,
                          include_contrast: bool = True,
                          per_worker_bs: int = 2) -> DrillReport:
    """Repeated guard-pressure steps (a finite multiplicative gradient
    blow-up tripping the ``abs_limit`` guard) must back the effective
    density off within ``backoff_steps`` pressured steps — journalled —
    and a clean streak after the fault clears must re-advance it to full
    density. The same fault with the guard off diverges (the contrast
    case)."""
    from oktopk_tpu.comm.mesh import get_mesh

    mesh = mesh if mesh is not None else get_mesh()
    P = int(mesh.shape["data"])
    # health.step (the fault clock) counts attempted steps from 0
    plan = FaultPlan((FaultSpec("scale_grad", step=clean_before,
                                duration=fault_duration, scale=scale),))
    backoff_steps, clean_streak, max_level = 2, 3, 2
    knobs = dict(
        resilience_abs_limit=1e3,      # scaled magnitudes trip, normal don't
        resilience_density_backoff=True,
        resilience_near_ratio=0.5,
        resilience_backoff_steps=backoff_steps,
        resilience_backoff_factor=0.5,
        resilience_backoff_max_level=max_level,
        resilience_clean_streak=clean_streak,
        # this drill is about the density loop: park the strike/restore
        # ladders so they don't consume the same evidence
        resilience_strikes=99, resilience_divergence_limit=99)
    # an actual density_schedule, so the drill proves the backoff scales
    # the schedule itself (the "guard-aware density_schedule" contract)
    sched = {"density_schedule": ((0, 0.02), (2, 0.05)), "density": 0.05}
    tr = _drill_trainer(mesh, fault_plan=plan, algo_over=sched, **knobs)
    checks: List[Tuple[str, bool, str]] = []
    batches = _batches(DEFAULT_DNN, P * per_worker_bs)
    # enough clean tail to fully re-advance: max_level streaks + slack
    total = clean_before + fault_duration + clean_streak * max_level + 2
    skipped: List[int] = []
    for step in range(1, total + 1):
        m = tr.train_step(next(batches))
        skipped.append(int(np.asarray(m.get("step_skipped", 0))))
        tr.bus.emit(
            "step", step=step,
            loss=float(np.asarray(m["loss"]).mean()),
            step_skipped=skipped[-1],
            reduced_absmax=float(np.asarray(m["reduced_absmax"])))
        tr.supervise(step, m)

    journal = list(tr.run_journal.entries)
    idx_back = _event_indices(journal, "density_backoff",
                              direction="backoff")
    idx_adv = _event_indices(journal, "density_backoff",
                             direction="advance")
    backs = [journal[i] for i in idx_back]
    advs = [journal[i] for i in idx_adv]
    first_fault_step = clean_before + 1
    _check(checks, "backed_off_within_n_steps",
           bool(backs) and backs[0]["step"]
           <= first_fault_step + backoff_steps,
           f"first backoff at {backs[0]['step'] if backs else None}, "
           f"fault from {first_fault_step}")
    _check(checks, "backoff_bounded",
           len(backs) <= max_level
           and all(b["level"] <= max_level for b in backs),
           f"{len(backs)} backoffs, levels {[b['level'] for b in backs]}")
    _check(checks, "readvanced_after_clean_streak",
           len(advs) == len(backs) and tr.density_backoff.level == 0
           and tr._density_scale == 1.0,
           f"{len(advs)} advances vs {len(backs)} backoffs, "
           f"final level {tr.density_backoff.level}")
    _check(checks, "guard_contained",
           sum(skipped) == fault_duration
           and all(np.isfinite(np.asarray(x)).all()
                   for x in jax.tree.leaves(
                       jax.device_get(tr.state.params))),
           f"{sum(skipped)} skips for {fault_duration} faulted steps")
    _check(checks, "no_fallback_no_restore",
           not _event_indices(journal, "fallback")
           and not _event_indices(journal, "restore")
           and not _event_indices(journal, "restore_unavailable"),
           "strike/restore ladder fired")
    problems = validate_journal(journal)
    _check(checks, "journal_valid", not problems, "; ".join(problems[:3]))

    notes: Dict[str, Any] = {
        "skipped": skipped,
        "backoff_steps": [b["step"] for b in backs],
        "advance_steps": [a["step"] for a in advs]}
    if include_contrast:
        # contrast: the same fault with no guard poisons params directly
        tr2 = _drill_trainer(mesh, fault_plan=plan, algo_over=sched,
                             resilience=False, obs=False)
        b2 = _batches(DEFAULT_DNN, P * per_worker_bs)
        for _ in range(clean_before + fault_duration + 1):
            tr2.train_step(next(b2))
        mx = max(float(np.max(np.abs(np.asarray(x))))
                 for x in jax.tree.leaves(jax.device_get(tr2.state.params)))
        guarded_mx = max(
            float(np.max(np.abs(np.asarray(x))))
            for x in jax.tree.leaves(jax.device_get(tr.state.params)))
        _check(checks, "unguarded_contrast_diverges",
               not np.isfinite(mx) or mx > 1e3,
               f"unguarded param absmax {mx:.3g}")
        _check(checks, "guarded_run_sane", guarded_mx < 1e3,
               f"guarded param absmax {guarded_mx:.3g}")
        notes["unguarded_param_absmax"] = mx
        notes["guarded_param_absmax"] = guarded_mx
    return DrillReport("density_backoff", checks, journal, notes=notes)


# ---- drill: corrupt restore target → verified fallback -------------------

def drill_ckpt_corruption(mesh=None, per_worker_bs: int = 2,
                          kinds: Tuple[str, ...] = ("ckpt_truncate",
                                                    "ckpt_bitflip",
                                                    "ckpt_torn"),
                          ckpt_dir: Optional[str] = None) -> DrillReport:
    """The storage leg of the self-healing loop: checkpoint A (older,
    good) and B (newer, the supervisor's restore target) are saved
    through the :class:`~oktopk_tpu.train.durable.AsyncCheckpointer`;
    B is then damaged at rest with each ``ckpt_*`` fault kind in turn
    while a NaN fault drives the run to divergence. Every
    divergence-triggered restore must *skip* corrupt B and land on A
    bit-identically (params, residual, health — the whole state tree),
    with the journal showing ``ckpt_verify_failed(B)`` before the
    ``restore`` record naming A. A restore rewinds the replicated
    attempted-step clock, so the same NaN window re-fires after each
    restore — one fault spec drives all three corruption rounds. The
    drill also proves the satellite contracts: an async save in flight
    is drained whole (no torn file), an aged ``*.tmp`` remnant is swept
    by the checkpoint scan, and a legacy manifest-less checkpoint still
    restores (flagged, not rejected)."""
    import os
    import shutil
    import tempfile

    from oktopk_tpu.comm.mesh import get_mesh
    from oktopk_tpu.resilience.faults import corrupt_checkpoint
    from oktopk_tpu.train.checkpoint import (latest_checkpoint,
                                             save_checkpoint)
    from oktopk_tpu.train.durable import (AsyncCheckpointer,
                                          verified_restore,
                                          verify_checkpoint)

    mesh = mesh if mesh is not None else get_mesh()
    P = int(mesh.shape["data"])
    div_limit = 3
    # attempted-step clock counts from 0: host steps 1..4 run attempted
    # 0..3 (clean), attempted >= 4 is the NaN window. A is saved after
    # host step 2 (clock 2), so each post-restore cycle replays 2 clean
    # steps then hits the window again.
    plan = FaultPlan((FaultSpec("nan_grad", step=4, duration=10_000),))
    tr = _drill_trainer(mesh, fault_plan=plan,
                        resilience_divergence_limit=div_limit,
                        resilience_strikes=99)
    checks: List[Tuple[str, bool, str]] = []
    own_dir = ckpt_dir is None
    ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="oktopk-ckpt-drill-")
    ac = AsyncCheckpointer(ckpt_dir, journal=tr.supervisor.journal,
                           on_failure=tr.note_ckpt_failure)
    batches = _batches(DEFAULT_DNN, P * per_worker_bs)
    losses: List[float] = []

    def host_step(step: int):
        m = tr.train_step(next(batches))
        losses.append(float(np.asarray(m["loss"]).mean()))
        tr.bus.emit("step", step=step, loss=losses[-1],
                    step_skipped=int(np.asarray(m.get("step_skipped", 0))))
        tr.supervise(step, m)
        return m

    try:
        step = 0
        snap_a = path_a = path_b = None
        for _ in range(4):
            step += 1
            host_step(step)
            if step in (2, 4):
                path = ac.save(tr.state, step, extra=tr.supervisor_extra(),
                               qualified=tr.checkpoint_qualified)
                ac.drain()
                tr.note_checkpoint(path, step)
                if step == 2:
                    path_a, snap_a = path, jax.device_get(tr.state)
                else:
                    path_b = path
        _check(checks, "saves_verified",
               ac.saves == 2 and ac.write_failures == 0
               and tr.supervisor.last_good_ckpt == path_b,
               f"saves={ac.saves} failures={ac.write_failures} "
               f"target={tr.supervisor.last_good_ckpt}")
        with open(path_b, "rb") as f:
            pristine_b = f.read()
        man_b = path_b[: -len(".msgpack")] + ".manifest.json"
        with open(man_b, "rb") as f:
            pristine_man_b = f.read()

        identical: List[bool] = []
        for i, kind in enumerate(kinds):
            if i:  # re-pristine B so the next kind damages a clean file
                with open(path_b, "wb") as f:
                    f.write(pristine_b)
                with open(man_b, "wb") as f:
                    f.write(pristine_man_b)
            corrupt_checkpoint(path_b, kind)
            safety = 0
            while tr.supervisor.restore_events < i + 1 and safety < 12:
                step += 1
                safety += 1
                host_step(step)
            identical.append(_leaves_equal(jax.device_get(tr.state),
                                           snap_a))
        # post-incident recovery: the two clean steps after the rewind
        for _ in range(2):
            step += 1
            host_step(step)

        journal = list(tr.run_journal.entries)
        n = len(kinds)
        idx_vf = _event_indices(journal, "ckpt_verify_failed",
                                path=path_b)
        idx_cr = _event_indices(journal, "ckpt_restore", path=path_a)
        idx_rs = _event_indices(journal, "restore", ckpt=path_a)
        reasons = [journal[i]["reason"] for i in idx_vf]
        _check(checks, "restores_fired",
               tr.supervisor.restore_events == n and len(idx_rs) == n,
               f"restore_events={tr.supervisor.restore_events}, "
               f"{len(idx_rs)} restore records for A")
        _check(checks, "verify_failed_precedes_restore",
               len(idx_vf) >= n and len(idx_cr) == n
               and all(idx_vf[i] < idx_cr[i] < idx_rs[i]
                       for i in range(min(n, len(idx_rs)))),
               f"verify_failed@{idx_vf} ckpt_restore@{idx_cr} "
               f"restore@{idx_rs}")
        expected = {"ckpt_truncate": "size_mismatch",
                    "ckpt_bitflip": "digest_mismatch",
                    "ckpt_torn": "size_mismatch"}
        _check(checks, "rejection_reasons",
               len(reasons) >= n
               and all(reasons[i].startswith(expected[k])
                       for i, k in enumerate(kinds)),
               f"reasons={reasons}")
        _check(checks, "fallback_depth_one",
               all(journal[i].get("fallback_depth") == 1
                   and journal[i].get("legacy") is False
                   for i in idx_cr),
               f"ckpt_restore events: {[journal[i] for i in idx_cr]}")
        _check(checks, "state_bit_identical",
               len(identical) == n and all(identical),
               f"rounds identical to A: {identical}")
        _check(checks, "recovered",
               all(np.isfinite(losses[-2:])),
               f"post-restore losses {losses[-2:]}")

        # drain barrier: an async save in flight at (simulated)
        # preemption time publishes whole — verified file, no tmp
        final = ac.save(tr.state, step, qualified=tr.checkpoint_qualified)
        drained = ac.drain(timeout=60.0)
        _check(checks, "drain_publishes_whole",
               drained and verify_checkpoint(final).ok
               and not os.path.exists(final + ".tmp"),
               f"drained={drained}")

        # the torn round's stale tmp remnant: fresh tmp files survive
        # the scan (an async writer may own them); aged ones are swept
        remnant = path_b + ".tmp"
        had_remnant = os.path.exists(remnant)
        if had_remnant:
            os.utime(remnant, (0, 0))
        latest_checkpoint(ckpt_dir)
        _check(checks, "stale_tmp_swept",
               had_remnant and not os.path.exists(remnant),
               f"remnant existed={had_remnant}, "
               f"still there={os.path.exists(remnant)}")

        # legacy manifest-less checkpoint: accepted with the flag set
        legacy_dir = os.path.join(ckpt_dir, "legacy")
        save_checkpoint(legacy_dir, tr.state, 1, manifest=False)
        _, lstep, _, _, legacy = verified_restore(
            legacy_dir, tr.state, journal=tr.supervisor.journal,
            step=step)
        _check(checks, "legacy_restores", legacy and lstep == 1,
               f"legacy={legacy} step={lstep}")

        journal = list(tr.run_journal.entries)
        problems = validate_journal(journal)
        _check(checks, "journal_valid", not problems,
               "; ".join(problems[:3]))
        return DrillReport(
            "ckpt_corruption", checks, journal,
            notes={"kinds": list(kinds), "reasons": reasons,
                   "losses": losses,
                   "ckpts": {"a": path_a, "b": path_b}})
    finally:
        ac.close(timeout=60.0)
        if own_dir:
            shutil.rmtree(ckpt_dir, ignore_errors=True)


# ---- catalog ------------------------------------------------------------

DRILLS: Dict[str, Callable[..., DrillReport]] = {
    "chip_loss": drill_chip_loss,
    "latency_retune": drill_latency_retune,
    "density_backoff": drill_density_backoff,
    "ckpt_corruption": drill_ckpt_corruption,
}


def run_drill(name: str, **kwargs) -> DrillReport:
    """Run one catalog drill by name."""
    if name not in DRILLS:
        raise KeyError(f"unknown drill {name!r}; one of {sorted(DRILLS)}")
    return DRILLS[name](**kwargs)
