"""Deterministic, step-indexed fault injection.

A :class:`FaultPlan` is a frozen (hashable) tuple of :class:`FaultSpec`
entries, so it can be closed over by jitted programs exactly like
``OkTopkConfig``. Every injection seam is a pure function of
``(plan, step, rank, bucket)``: the same plan replayed against the same
training run produces the same corruption, which is what makes the
emulated-mesh chaos tests deterministic (and what distinguishes a fault
*drill* from real corruption — the guard/supervisor must not be able to
tell the difference).

Five fault families, mirroring what degrades in real sparse pipelines:

- ``nan_grad`` / ``inf_grad``: the local gradient blows up on one (or
  every) worker — the failure the reference merely warns about
  (VGG/dl_trainer.py:608-609). Injected on the flat per-bucket gradient
  inside ``optim.distributed.build_sparse_grad_step``, *before* the
  residual accumulation, so an unguarded run demonstrably poisons its
  error feedback.
- ``wire_bitflip`` / ``wire_zero``: the sparse message payload is
  corrupted in transit. Injected at the ``collectives/wire.py`` seam
  (:func:`make_wire_hook`), i.e. on the value buffer exactly as it
  crosses the collective, on the chosen sender shard only. A bit-flip
  XORs the top exponent bit (huge-magnitude values, the classic silent
  fabric corruption); zeroing models dropped payloads — note that zeroed
  winners are *recovered* by error feedback (senders keep the mass in
  their residual), which the chaos tests assert.
- ``latency``: per-step collective latency inflation on the emulated
  mesh (:func:`latency_ms` / :func:`with_latency`) — degraded-fabric
  behaviour for the supervisor/autotuner timing paths, host-side so CPU
  tests can exercise it without a slow wire.
- ``scale_grad``: the local gradient is *scaled* (``scale``) rather than
  replaced — the near-``abs_limit`` regime where everything is still
  finite but the reduced magnitudes crowd the guard's absurdity limit.
  Unlike nan/inf, the per-element structure survives, so top-k selection
  stays deterministic; this is the drill fuel for the guard-aware
  density backoff policy (``resilience/density.py``).
- ``chip_loss``: rank ``worker`` (required ≥ 0) dies permanently at
  ``step`` — the orchestrator-visible hardware failure, not a data
  fault. Host-side only (:func:`dead_workers`); the supervisor
  escalates it to a ``remesh`` action that drives
  ``Trainer.resize_workers`` onto the surviving devices. ``duration``
  is ignored: chips do not come back mid-run.
- ``ckpt_truncate`` / ``ckpt_bitflip`` / ``ckpt_torn``: a checkpoint
  *file* is damaged at rest — the storage-leg failures the durable
  state plane (``train/durable.py``) exists to survive. Host-side only
  (:func:`corrupt_checkpoint` mutates the file deterministically;
  never traced): truncation models a crashed writer or lost tail,
  bitflip models at-rest bit rot with the size preserved (only the
  digest catches it), and torn models a non-atomic writer dying
  mid-publish — a prefix in the final file plus a stale ``*.tmp``
  remnant. The chaos drills corrupt the supervisor's restore target and
  assert the verifying restore falls back to the older good file.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Tuple

import jax.numpy as jnp
from jax import lax

FAULT_KINDS = ("nan_grad", "inf_grad", "scale_grad", "wire_bitflip",
               "wire_zero", "latency", "chip_loss",
               "ckpt_truncate", "ckpt_bitflip", "ckpt_torn")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault: ``kind`` active on attempted-step indices
    ``[step, step + duration)``.

    ``worker``/``bucket`` select a single shard / gradient bucket (-1 =
    all). ``count`` bounds the corruption to the leading elements of the
    target buffer (-1 = the whole buffer). ``latency_ms`` applies to
    ``kind == "latency"`` only; ``bit_mask`` overrides the XOR pattern of
    ``wire_bitflip`` (0 = flip the top exponent bit of the wire dtype);
    ``scale`` is the multiplier of ``scale_grad``. ``chip_loss`` is
    permanent (``duration`` ignored) and must name a concrete ``worker``.
    """

    kind: str
    step: int
    duration: int = 1
    worker: int = -1
    bucket: int = -1
    count: int = -1
    latency_ms: float = 0.0
    bit_mask: int = 0
    scale: float = 1.0

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {FAULT_KINDS}")
        if self.duration < 1:
            raise ValueError(f"duration must be >= 1, got {self.duration}")
        if self.step < 0:
            raise ValueError(f"step must be >= 0, got {self.step}")
        if self.kind == "chip_loss" and self.worker < 0:
            raise ValueError("chip_loss must name a concrete worker (>= 0)")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A deterministic schedule of faults (hashable; closed over by jit)."""

    faults: Tuple[FaultSpec, ...] = ()

    def __post_init__(self):
        # accept any iterable of specs but store a hashable tuple
        object.__setattr__(self, "faults", tuple(self.faults))

    def of_kind(self, *kinds: str) -> Tuple[FaultSpec, ...]:
        return tuple(f for f in self.faults if f.kind in kinds)

    @property
    def grad_faults(self) -> Tuple[FaultSpec, ...]:
        return self.of_kind("nan_grad", "inf_grad", "scale_grad")

    @property
    def chip_faults(self) -> Tuple[FaultSpec, ...]:
        return self.of_kind("chip_loss")

    @property
    def wire_faults(self) -> Tuple[FaultSpec, ...]:
        return self.of_kind("wire_bitflip", "wire_zero")

    @property
    def latency_faults(self) -> Tuple[FaultSpec, ...]:
        return self.of_kind("latency")

    @property
    def ckpt_faults(self) -> Tuple[FaultSpec, ...]:
        return self.of_kind("ckpt_truncate", "ckpt_bitflip", "ckpt_torn")


def _active(spec: FaultSpec, step, rank):
    """Traced 0/1 activity flag of ``spec`` at (step, rank)."""
    act = (step >= spec.step) & (step < spec.step + spec.duration)
    if spec.worker >= 0:
        act = act & (rank == spec.worker)
    return act


def _leading_mask(n: int, count: int):
    """Boolean [n] mask of the corrupted prefix (count < 0 = all)."""
    if count < 0 or count >= n:
        return jnp.ones((n,), bool)
    return jnp.arange(n) < count


def inject_grad_faults(plan: FaultPlan, flat: jnp.ndarray, step, rank,
                       bucket: int) -> jnp.ndarray:
    """Poison the local flat gradient of ``bucket`` per the plan.

    ``step``/``rank`` are traced scalars (the monotonic attempted-step
    counter and ``lax.axis_index``); ``bucket`` is the static bucket
    index, so inactive buckets trace no extra ops at all.
    """
    for f in plan.grad_faults:
        if f.bucket >= 0 and f.bucket != bucket:
            continue
        if f.kind == "scale_grad":
            # multiplicative blow-up: finite, structure-preserving — the
            # near-abs_limit regime the density backoff drills target
            corrupted = flat * jnp.asarray(f.scale, flat.dtype)
        else:
            bad = jnp.inf if f.kind == "inf_grad" else jnp.nan
            corrupted = jnp.broadcast_to(
                jnp.asarray(bad, flat.dtype), flat.shape)
        where = _leading_mask(flat.size, f.count)
        poisoned = jnp.where(where, corrupted, flat)
        flat = jnp.where(_active(f, step, rank), poisoned, flat)
    return flat


def dead_workers(plan: FaultPlan, step: int) -> Tuple[int, ...]:
    """Ranks whose chip has died at or before host step ``step``.

    Chip loss is permanent — ``duration`` is ignored — so this is the
    cumulative set, sorted. Host-side by design: a dead chip is an
    orchestrator-level observation, never a traced value.
    """
    return tuple(sorted({f.worker for f in plan.chip_faults
                         if f.step <= step}))


def _bitflip(x: jnp.ndarray, mask: int) -> jnp.ndarray:
    """XOR the float bits of ``x`` (0 = flip the top exponent bit)."""
    if x.dtype == jnp.bfloat16:
        u, default = jnp.uint16, 1 << 14
    elif x.dtype == jnp.float32:
        u, default = jnp.uint32, 1 << 30
    else:  # float64 CPU paths
        u, default = jnp.uint64, 1 << 62
    m = jnp.asarray(mask or default, u)
    return lax.bitcast_convert_type(
        lax.bitcast_convert_type(x, u) ^ m, x.dtype)


def make_wire_hook(plan: FaultPlan, axis_name: str = "data"
                   ) -> Callable[[jnp.ndarray, object, object], jnp.ndarray]:
    """Build the trace-time hook ``collectives/wire.py`` applies to every
    value buffer as it crosses a collective (install with
    ``wire.install_wire_fault``).

    The hook corrupts the payload on the chosen SENDER shard only —
    equivalent to fabric corruption of that shard's outgoing messages —
    and targets one bucket via ``cfg.bucket_index`` (set by the
    multi-bucket step builder). ``step`` is the bucket's allreduce
    counter; call sites that cannot supply one (step=None) are left
    untouched rather than corrupted unconditionally.
    """

    def hook(x, cfg, step):
        if step is None or not plan.wire_faults:
            return x
        rank = lax.axis_index(axis_name)
        for f in plan.wire_faults:
            if f.bucket >= 0 and f.bucket != getattr(cfg, "bucket_index", 0):
                continue
            if f.kind == "wire_zero":
                corrupted = jnp.zeros_like(x)
            else:
                corrupted = _bitflip(x, f.bit_mask)
            where = _leading_mask(x.size, f.count).reshape(x.shape)
            corrupted = jnp.where(where, corrupted, x)
            x = jnp.where(_active(f, step, rank), corrupted, x)
        return x

    return hook


def latency_ms(plan: FaultPlan, step: int, bucket: int = 0) -> float:
    """Total injected collective latency (ms) active at host step ``step``
    for ``bucket`` — the degraded-fabric model for timing paths."""
    return float(sum(
        f.latency_ms for f in plan.latency_faults
        if f.step <= step < f.step + f.duration
        and (f.bucket < 0 or f.bucket == bucket)))


def with_latency(step_fn, plan: FaultPlan, bucket: int = 0,
                 sleep=time.sleep, start_step: int = 0):
    """Wrap a built allreduce/train step with the plan's latency
    inflation: each call sleeps ``latency_ms`` for its (host-side) step
    index before dispatching. This is the emulated-mesh seam for
    exercising timing-sensitive policies (autotune trials, supervisor
    backoff) under a degraded fabric without a slow wire.

    ``start_step`` seeds the internal counter so the plan's step indices
    line up with the run's attempted-step clock after a checkpoint
    restore or an elastic re-mesh — without it a resumed run would replay
    the plan from step 0 and faults would land on the wrong steps. The
    wrapped fn exposes ``wrapped.seek(step)`` to re-seed in place (e.g.
    after a mid-run restore)."""
    counter = {"step": int(start_step)}

    def wrapped(*args, **kwargs):
        ms = latency_ms(plan, counter["step"], bucket)
        counter["step"] += 1
        if ms > 0:
            sleep(ms / 1e3)
        return step_fn(*args, **kwargs)

    def seek(step: int) -> None:
        counter["step"] = int(step)

    wrapped.seek = seek
    return wrapped


def degraded_fake_ms(base: Callable[[str, int, float], float],
                     plan: FaultPlan, bucket_of_n: Optional[dict] = None,
                     step: int = 0) -> Callable[[str, int, float], float]:
    """Inflate an autotune ``fake_ms`` injector by the plan's latency:
    models what the trial phase measures on a degraded fabric.
    ``bucket_of_n`` maps bucket flat sizes to bucket ids (the trial
    signature carries n, not the bucket index)."""

    def fake(algo: str, n: int, density: float) -> float:
        b = (bucket_of_n or {}).get(int(n), 0)
        return float(base(algo, n, density)) + latency_ms(plan, step, b)

    return fake


def corrupt_checkpoint(path: str, kind: str, bit_mask: int = 0x40,
                       offset: int = -1) -> None:
    """Deterministically damage a checkpoint file at rest (host-side;
    the drill seam for the ``ckpt_*`` fault kinds).

    - ``ckpt_truncate``: the file becomes its leading half — a crashed
      writer or lost tail; caught by the manifest size check.
    - ``ckpt_bitflip``: one byte (middle of the file, or ``offset``) is
      XORed with ``bit_mask`` — at-rest bit rot. The size is preserved,
      so only the digest catches it.
    - ``ckpt_torn``: a non-atomic writer died mid-publish — the final
      file holds a prefix AND a stale ``<path>.tmp`` remnant is left
      behind (size check catches the file; the stale-tmp sweep collects
      the remnant).

    The sidecar manifest is left intact on purpose: the corruption is in
    the data, and the manifest is what convicts it.
    """
    if kind not in ("ckpt_truncate", "ckpt_bitflip", "ckpt_torn"):
        raise ValueError(f"not a checkpoint fault kind: {kind!r}")
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 2:
        raise ValueError(f"checkpoint {path} too small to corrupt")
    if kind == "ckpt_truncate":
        with open(path, "wb") as f:
            f.write(data[: len(data) // 2])
    elif kind == "ckpt_bitflip":
        buf = bytearray(data)
        i = offset if 0 <= offset < len(buf) else len(buf) // 2
        buf[i] ^= (bit_mask & 0xFF) or 0x40
        with open(path, "wb") as f:
            f.write(bytes(buf))
    else:  # ckpt_torn
        with open(path, "wb") as f:
            f.write(data[: max(1, 2 * len(data) // 3)])
        with open(path + ".tmp", "wb") as f:
            f.write(data[: max(1, len(data) // 3)])
