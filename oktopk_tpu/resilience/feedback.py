"""Fault→autotune feedback: sustained degradation forces a re-tune.

PR 1's autotuner calibrates the fabric once and re-tunes on a *step
cadence*; PR 2's detectors see what actually changed. This policy closes
the gap "On the Utility of Gradient Compression" (arXiv 2103.00543)
warns about — a statically tuned plan stops paying the moment conditions
drift. It subscribes to the unified obs bus and, when a sustained stream
of ``regression`` events (or guard strikes) lands inside a short window,
tells the trainer to drop its :class:`~oktopk_tpu.autotune.Autotuner`
entirely. A fresh tuner has ``coeffs=None``, so the next ``tune()``
re-probes the (now degraded) fabric before re-deciding — exactly the
path ``Trainer.resize_workers`` already takes after an elastic resize.

The causal chain lands in the journal as linked events:
``fault_seen`` → ``regression``/``guard_trip`` (the evidence) →
``retune`` (this policy firing, carrying the evidence steps) →
``calibration`` (the forced re-probe) → ``autotune_decision`` (the new
plan). ``scripts/obs_report.py`` renders the chain in the incident
timeline.

Host-side and event-driven: nothing here is traced, and a run without
faults never pays more than a list append per flagged event.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple


class AutotuneFeedback:
    """Sliding-window vote over degradation events on the obs bus.

    Fires (returns a trigger descriptor from :meth:`should_retune`) when
    at least ``min_signals`` matching events landed within the last
    ``window_steps`` steps, then backs off for ``cooldown_steps`` so one
    incident cannot thrash the tuner with recompiles — re-tuning is
    expensive (calibration probes + candidate trials), so the evidence
    bar is deliberately higher than the guard's single-step trip.
    """

    def __init__(self, bus=None, window_steps: int = 32,
                 min_signals: int = 3, cooldown_steps: int = 64,
                 kinds: Sequence[str] = ("regression", "guard_trip")):
        self.window_steps = max(1, int(window_steps))
        self.min_signals = max(1, int(min_signals))
        self.cooldown_steps = max(0, int(cooldown_steps))
        self.kinds = tuple(kinds)
        self.signals: List[Tuple[int, str]] = []   # (step, event kind)
        self.fired = 0
        self._cooldown_until = -1
        if bus is not None:
            bus.subscribe(self._on_event)

    # Bus subscriber — must never raise (the bus swallows subscriber
    # failures into its dropped counter, but a silent drop here would
    # lose evidence without a trace).
    def _on_event(self, entry: Dict[str, Any]) -> None:
        if entry.get("event") not in self.kinds:
            return
        if (entry.get("event") == "quality_rollup"
                and not entry.get("breaches")):
            return       # clean fidelity windows are not degradation
        step = entry.get("step")
        if isinstance(step, (int, float)):
            self.signals.append((int(step), str(entry["event"])))

    def should_retune(self, step: int) -> Optional[Dict[str, Any]]:
        """Poll at host step ``step``; consume the evidence and return a
        ``{"trigger": kind, "signals": [steps...]}`` descriptor when the
        window vote passes, else None."""
        step = int(step)
        # stale evidence ages out regardless of cooldown
        self.signals = [(s, k) for s, k in self.signals
                        if step - s < self.window_steps]
        if step < self._cooldown_until:
            return None
        if len(self.signals) < self.min_signals:
            return None
        recent = list(self.signals)
        self.signals = []
        self.fired += 1
        self._cooldown_until = step + self.cooldown_steps
        return {"trigger": recent[-1][1],
                "signals": [s for s, _ in recent]}
