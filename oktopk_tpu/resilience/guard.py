"""Jitted in-step anomaly guard: detect, agree, skip, roll back.

The reference computes gradient sparsity per step and *warns* when it goes
NaN (VGG/dl_trainer.py:608-609) — the update still applies, and under
error feedback one poisoned step contaminates the residual forever. Here
the existing ``grad_nonfinite`` observation becomes an *action*:

1. **detect** — per bucket, count nonfinite elements of the local flat
   gradient (NaN/Inf never survive a ``>= threshold`` compare, so a
   poisoned worker would otherwise silently park the NaNs in its residual)
   plus nonfinite-or-absurd elements of the post-collective reduced
   vector (wire corruption arrives huge, not necessarily nonfinite:
   a flipped exponent bit lands near 1e38 — ``abs_limit`` catches it).
2. **agree** — psum the per-bucket counts over the data axis, so every
   replica derives the *same* skip decision from the same global flags.
   Without this, a fault local to one worker would desynchronise params
   across replicas — the distributed-training equivalent of split brain.
3. **skip + roll back** — when any bucket trips, the optimizer update is
   discarded AND the compressor state update (residual, thresholds,
   drift, boundaries) is rolled back for every bucket, so the step is a
   pure no-op on training state: params and residuals stay bit-identical
   to the previous step. Only the step counters advance (cadence
   bookkeeping; a skipped step still consumed a batch) and the
   :class:`HealthState` records the trip.

The guard is pure compute inside the traced step — one small psum on top
of what the step already does — so it costs nothing host-side and works
identically on the emulated CPU mesh and real hardware.
"""

from __future__ import annotations

import dataclasses

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Static guard knobs (hashable; closed over by the jitted step).

    ``abs_limit`` is the sane-gradient magnitude ceiling for the
    post-collective reduced vector: values beyond it count as anomalies
    even while finite (wire bit-flips typically produce ~1e38, ten orders
    of magnitude above any real gradient, without tripping ``isfinite``).
    """

    abs_limit: float = 1e18

    def __post_init__(self):
        if not self.abs_limit > 0:
            raise ValueError(f"abs_limit must be > 0, got {self.abs_limit}")


@flax.struct.dataclass
class HealthState:
    """Replicated numeric-health counters threaded through the step.

    ``step`` counts *attempted* steps and is the only monotonic step
    index under the guard (per-bucket SparseState counters also advance
    on skips, but HealthState is where fault plans and the supervisor
    index time). ``bucket_trips`` accumulates per-bucket anomaly counts
    so escalation state survives a checkpoint round-trip.
    """

    step: jnp.ndarray               # i32 — attempted steps (monotonic)
    steps_skipped: jnp.ndarray      # i32 — cumulative guard skips
    last_anomaly_step: jnp.ndarray  # i32 — -1 until the first trip
    bucket_trips: jnp.ndarray       # i32[num_buckets] — cumulative trips


def init_health(num_buckets: int = 1) -> HealthState:
    nb = max(1, int(num_buckets))
    return HealthState(
        step=jnp.asarray(0, jnp.int32),
        steps_skipped=jnp.asarray(0, jnp.int32),
        last_anomaly_step=jnp.asarray(-1, jnp.int32),
        bucket_trips=jnp.zeros((nb,), jnp.int32))


def local_anomaly_count(flat: jnp.ndarray, reduced: jnp.ndarray,
                        cfg: GuardConfig) -> jnp.ndarray:
    """This worker's anomaly evidence for one bucket (i32 scalar):
    nonfinite local gradient elements + nonfinite-or-absurd reduced
    elements. Summed, not flagged, so the count is also the
    ``grad_nonfinite``-style observability signal."""
    local_bad = jnp.sum(~jnp.isfinite(flat))
    wire_bad = jnp.sum(~jnp.isfinite(reduced)
                       | (jnp.abs(reduced) > cfg.abs_limit))
    return (local_bad + wire_bad).astype(jnp.int32)


def agree(counts, axis_name: str):
    """psum the stacked per-bucket counts -> (global i32[nb] counts,
    bool any-anomaly flag). After the psum every replica holds identical
    values, so the skip decision below is deterministic across the mesh."""
    total = lax.psum(jnp.stack(counts).astype(jnp.int32), axis_name)
    return total, jnp.sum(total) > 0


def guarded(any_bad, old_tree, new_tree):
    """``new_tree`` normally; bit-identical ``old_tree`` on a skip."""
    return jax.tree.map(
        lambda o, n: jnp.where(any_bad, o, n), old_tree, new_tree)


def advance(health: HealthState, any_bad, bucket_counts) -> HealthState:
    """Post-step health bookkeeping (always advances the attempt
    counter; a skipped step consumed its batch)."""
    bad_i = any_bad.astype(jnp.int32)
    return HealthState(
        step=health.step + 1,
        steps_skipped=health.steps_skipped + bad_i,
        last_anomaly_step=jnp.where(any_bad, health.step,
                                    health.last_anomaly_step),
        bucket_trips=health.bucket_trips
        + (bucket_counts > 0).astype(jnp.int32))
