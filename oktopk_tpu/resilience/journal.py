"""JSONL health journal — the resilience observability surface.

Same shape (and writer) as the autotuner's decision journal
(``autotune/journal.py``): line-delimited JSON, append-only, one
environment header record first so logs are comparable across
containers/relays. Events (all carry ``event`` and ``step``):

  {"event": "header", "jax": "0.4.37", "jaxlib": ..., "device_kind": ...,
   "platform": "cpu", "world_size": 8}

  {"event": "fault_seen", "step": 12, "kind": "planned" | "observed",
   "buckets": [1], "counts": [0, 3]}

  {"event": "guard_trip", "step": 12, "buckets": [1],
   "consecutive_skips": 1, "strikes": [0, 3]}

  {"event": "fallback", "step": 14, "bucket": 1, "algo": "dense",
   "strikes": 3}

  {"event": "restore", "step": 30, "ckpt": ".../ckpt-24.msgpack",
   "last_good_step": 24}

  {"event": "restore_unavailable", "step": 30, "last_good_step": -1}

  {"event": "remesh", "step": 40, "old_world": 8, "new_world": 7,
   "trigger": "chip_loss", "dead_workers": [5],
   "carried": ["params", ...], "reinitialised": ["sparse_state", ...]}

  {"event": "density_backoff", "step": 52, "direction": "backoff",
   "level": 1, "scale": 0.5, "trigger": "guard_skip"}

  {"event": "ckpt_saved", "step": 60, "path": ".../ckpt-60.msgpack",
   "bytes": 123456, "digest": "crc32:0a1b2c3d", "qualified": true,
   "source": "async"}

  {"event": "ckpt_verify_failed", "step": 66, "path": "...",
   "reason": "digest_mismatch"}

  {"event": "ckpt_restore", "step": 66, "path": ".../ckpt-54.msgpack",
   "ckpt_step": 54, "fallback_depth": 1, "legacy": false}
"""

from __future__ import annotations

from typing import Optional, Sequence

from oktopk_tpu.autotune.journal import DecisionJournal


class HealthJournal(DecisionJournal):
    """Append-only JSONL health log (``path=None`` = in-memory only)."""

    def guard_trip(self, step: int, buckets: Sequence[int],
                   consecutive_skips: int, strikes: Sequence[int]):
        return self.record("guard_trip", step=int(step),
                           buckets=[int(b) for b in buckets],
                           consecutive_skips=int(consecutive_skips),
                           strikes=[int(s) for s in strikes])

    def fault_seen(self, step: int, kind: str,
                   buckets: Sequence[int] = (),
                   counts: Optional[Sequence[int]] = None,
                   workers: Optional[Sequence[int]] = None):
        fields = dict(step=int(step), kind=kind,
                      buckets=[int(b) for b in buckets],
                      counts=(None if counts is None
                              else [int(c) for c in counts]))
        if workers is not None:
            fields["workers"] = [int(w) for w in workers]
        return self.record("fault_seen", **fields)

    def fallback(self, step: int, bucket: int, algo: str, strikes: int):
        return self.record("fallback", step=int(step), bucket=int(bucket),
                           algo=algo, strikes=int(strikes))

    def restore(self, step: int, ckpt: Optional[str],
                last_good_step: int):
        if ckpt is None:
            return self.record("restore_unavailable", step=int(step),
                               last_good_step=int(last_good_step))
        return self.record("restore", step=int(step), ckpt=ckpt,
                           last_good_step=int(last_good_step))

    def remesh(self, step: int, old_world: int, new_world: int,
               trigger: str, dead_workers: Sequence[int] = (),
               carried: Sequence[str] = (),
               reinitialised: Sequence[str] = ()):
        return self.record("remesh", step=int(step),
                           old_world=int(old_world),
                           new_world=int(new_world), trigger=str(trigger),
                           dead_workers=[int(w) for w in dead_workers],
                           carried=list(carried),
                           reinitialised=list(reinitialised))

    def density_backoff(self, step: int, direction: str, level: int,
                        scale: float, trigger: str = ""):
        return self.record("density_backoff", step=int(step),
                           direction=str(direction), level=int(level),
                           scale=float(scale), trigger=str(trigger))

    # ---- durable state plane (train/durable.py) ----------------------

    def ckpt_saved(self, step: int, path: str, nbytes: int = 0,
                   digest: str = "", qualified: bool = True,
                   duration_ms: Optional[float] = None,
                   source: str = "sync"):
        fields = dict(step=int(step), path=str(path), bytes=int(nbytes),
                      digest=str(digest), qualified=bool(qualified),
                      source=str(source))
        if duration_ms is not None:
            fields["duration_ms"] = float(duration_ms)
        return self.record("ckpt_saved", **fields)

    def ckpt_verify_failed(self, step: int, path: str, reason: str):
        return self.record("ckpt_verify_failed", step=int(step),
                           path=str(path), reason=str(reason))

    def ckpt_restore(self, step: int, path: str, ckpt_step: int = 0,
                     fallback_depth: int = 0, legacy: bool = False):
        return self.record("ckpt_restore", step=int(step), path=str(path),
                           ckpt_step=int(ckpt_step),
                           fallback_depth=int(fallback_depth),
                           legacy=bool(legacy))
