"""JSONL health journal — the resilience observability surface.

Same shape (and writer) as the autotuner's decision journal
(``autotune/journal.py``): line-delimited JSON, append-only, one
environment header record first so logs are comparable across
containers/relays. Events (all carry ``event`` and ``step``):

  {"event": "header", "jax": "0.4.37", "jaxlib": ..., "device_kind": ...,
   "platform": "cpu", "world_size": 8}

  {"event": "fault_seen", "step": 12, "kind": "planned" | "observed",
   "buckets": [1], "counts": [0, 3]}

  {"event": "guard_trip", "step": 12, "buckets": [1],
   "consecutive_skips": 1, "strikes": [0, 3]}

  {"event": "fallback", "step": 14, "bucket": 1, "algo": "dense",
   "strikes": 3}

  {"event": "restore", "step": 30, "ckpt": ".../ckpt-24.msgpack",
   "last_good_step": 24}

  {"event": "restore_unavailable", "step": 30, "last_good_step": -1}
"""

from __future__ import annotations

from typing import Optional, Sequence

from oktopk_tpu.autotune.journal import DecisionJournal


class HealthJournal(DecisionJournal):
    """Append-only JSONL health log (``path=None`` = in-memory only)."""

    def guard_trip(self, step: int, buckets: Sequence[int],
                   consecutive_skips: int, strikes: Sequence[int]):
        return self.record("guard_trip", step=int(step),
                           buckets=[int(b) for b in buckets],
                           consecutive_skips=int(consecutive_skips),
                           strikes=[int(s) for s in strikes])

    def fault_seen(self, step: int, kind: str,
                   buckets: Sequence[int] = (),
                   counts: Optional[Sequence[int]] = None):
        return self.record("fault_seen", step=int(step), kind=kind,
                           buckets=[int(b) for b in buckets],
                           counts=(None if counts is None
                                   else [int(c) for c in counts]))

    def fallback(self, step: int, bucket: int, algo: str, strikes: int):
        return self.record("fallback", step=int(step), bucket=int(bucket),
                           algo=algo, strikes=int(strikes))

    def restore(self, step: int, ckpt: Optional[str],
                last_good_step: int):
        if ckpt is None:
            return self.record("restore_unavailable", step=int(step),
                               last_good_step=int(last_good_step))
        return self.record("restore", step=int(step), ckpt=ckpt,
                           last_good_step=int(last_good_step))
