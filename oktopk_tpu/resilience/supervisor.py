"""Host-side escalation policy: strikes -> dense fallback -> restore.

The in-step guard (``resilience/guard.py``) makes a single bad step
harmless; the supervisor handles *persistent* degradation, which a pure
in-step mechanism cannot (a corrupted link corrupts every retry). The
escalation ladder, mirroring SparCML's sparse/dense switching
(arXiv 1802.08021) applied to fault handling instead of performance:

1. **observe** — after each step (on the trainer's check cadence) the
   supervisor reads the guard's metrics: which buckets tripped, whether
   the step was skipped.
2. **strike** — per-bucket strike counters accumulate across trips (a
   clean step decays them by one rather than resetting: intermittent
   corruption must still escalate); a consecutive-skip counter tracks
   run-level divergence.
3. **fallback** — after ``max_strikes`` on a bucket, that bucket's plan
   flips to ``dense`` (the trainer rebuilds its jitted step exactly as
   the autotuner's plan changes do). Dense psum has no sparse payload to
   corrupt at the wire seam and no residual to poison — it is the safe
   degraded mode, at 2n volume cost for that bucket only.
4. **restore** — ``divergence_limit`` consecutive skips mean the run is
   not making progress (e.g. params already poisoned before the guard
   was enabled, or every bucket degraded): restore from the last good
   checkpoint registered via :meth:`note_checkpoint`.
5. **remesh** — a chip loss (:meth:`note_chip_loss`, fed by the host
   orchestrator seam ``faults.dead_workers``) is not evidence to weigh:
   the rank is gone. It bypasses strikes *and* the cooldown and emits a
   ``remesh`` action immediately; the trainer executes it via
   ``Trainer.resize_workers`` onto the surviving devices, carrying
   params/opt state and this supervisor's counters across the resize so
   training resumes without a requeue.

After any evidence-based escalation the supervisor backs off for
``cooldown_steps`` before escalating again, so one burst of faults
cannot cascade a fallback AND a restore from the same evidence.

All state is plain Python ints/lists (:meth:`to_state` /
:meth:`load_state`) so it checkpoints alongside the train state and a
resumed run keeps its strike counters and active fallbacks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from oktopk_tpu.resilience.journal import HealthJournal


@dataclasses.dataclass(frozen=True)
class Action:
    """One escalation decision for the trainer to execute."""

    kind: str                    # "fallback" | "restore" | "remesh"
    bucket: int = -1             # fallback target (-1 otherwise)
    ckpt: Optional[str] = None   # restore source (None = unavailable)
    workers: tuple = ()          # remesh: ranks to drop from the mesh


class Supervisor:
    """Per-run escalation state machine (host-side, not traced)."""

    def __init__(self, num_buckets: int = 1, max_strikes: int = 3,
                 divergence_limit: int = 8, cooldown_steps: int = 0,
                 journal: Optional[HealthJournal] = None):
        self.num_buckets = max(1, int(num_buckets))
        self.max_strikes = max(1, int(max_strikes))
        self.divergence_limit = max(1, int(divergence_limit))
        self.cooldown_steps = max(0, int(cooldown_steps))
        self.journal = journal if journal is not None else HealthJournal()
        self.strikes = [0] * self.num_buckets
        self.consecutive_skips = 0
        self.forced_dense: List[int] = []
        self.last_good_step = -1
        self.last_good_ckpt: Optional[str] = None
        self.fallback_events = 0
        self.restore_events = 0
        self.remesh_events = 0
        self.ckpt_write_failures = 0
        self.dead_workers: List[int] = []
        self._cooldown_until = -1

    # ---- inputs -------------------------------------------------------

    def note_checkpoint(self, path: str, step: int) -> None:
        """Register a checkpoint as a restore candidate. Only checkpoints
        taken while the run is healthy qualify — restoring into a
        snapshot saved mid-incident would replay the divergence. Every
        checkpoint is journalled either way, with the ``qualified`` flag
        saying whether it became a restore target."""
        qualified = self.consecutive_skips == 0
        if qualified:
            self.last_good_ckpt = path
            self.last_good_step = int(step)
        self.journal.record("checkpoint", step=int(step), path=path,
                            qualified=qualified)

    def note_chip_loss(self, step: int, workers: Sequence[int]
                       ) -> List[Action]:
        """Record permanently dead ranks; emit a ``remesh`` action for any
        newly observed ones. Idempotent per worker — the trainer can call
        this every supervision cadence with the cumulative dead set. A
        dead chip is a fact, not evidence: no strikes, no cooldown."""
        step = int(step)
        newly = [int(w) for w in workers
                 if int(w) not in self.dead_workers]
        if not newly:
            return []
        self.dead_workers.extend(newly)
        self.remesh_events += 1
        self.journal.fault_seen(step, "chip_loss", workers=newly)
        return [Action("remesh", workers=tuple(newly))]

    def observe(self, step: int, metrics: Dict[str, Any]) -> List[Action]:
        """Digest one step's guard metrics; return escalation actions.

        ``metrics`` needs ``step_skipped`` (0/1) and ``bucket_anomalies``
        (i32[num_buckets] trip flags) — both emitted by the guarded step.
        """
        step = int(step)
        skipped = bool(int(np.asarray(metrics.get("step_skipped", 0))))
        flags = np.asarray(metrics.get(
            "bucket_anomalies", np.zeros(self.num_buckets, np.int32)))
        actions: List[Action] = []
        if skipped:
            self.consecutive_skips += 1
            tripped = [b for b in range(self.num_buckets)
                       if b < flags.size and flags[b]]
            for b in tripped:
                self.strikes[b] += 1
            self.journal.guard_trip(step, tripped, self.consecutive_skips,
                                    self.strikes)
        else:
            self.consecutive_skips = 0
            if self.last_good_step < step:
                self.last_good_step = step
            # decay, don't reset: an every-other-step fault must escalate
            self.strikes = [max(0, s - 1) for s in self.strikes]

        for b in range(self.num_buckets):
            if (self.strikes[b] >= self.max_strikes
                    and b not in self.forced_dense
                    and step >= self._cooldown_until):
                self.forced_dense.append(b)
                self.fallback_events += 1
                self.journal.fallback(step, b, "dense", self.strikes[b])
                actions.append(Action("fallback", bucket=b))
                self._cooldown_until = step + self.cooldown_steps

        if (self.consecutive_skips >= self.divergence_limit
                and step >= self._cooldown_until):
            self.restore_events += 1
            if self.last_good_ckpt is None:
                # nothing to verify or execute: journal right here
                self.journal.restore(step, None, self.last_good_step)
            # a successful restore is journalled by the trainer AFTER
            # checkpoint verification, so ckpt_verify_failed events for
            # a corrupt target precede the restore record and the
            # journal names the file actually loaded, not the intended
            # one (train/durable.py verified_restore)
            actions.append(Action("restore", ckpt=self.last_good_ckpt))
            # the restore (or its unavailability) consumed this evidence
            self.consecutive_skips = 0
            self._cooldown_until = step + self.cooldown_steps
        return actions

    def note_ckpt_write_failure(self, step: int, path: str,
                                error: Any) -> None:
        """An async (or sync) checkpoint save failed to write or verify.
        The writer (``durable.AsyncCheckpointer``) already journalled the
        ``ckpt_verify_failed``; here the failure is *counted* and — if
        the failed file was the registered restore target — the
        registration is dropped, so a later divergence restore falls
        back to the previous good checkpoint instead of chasing a file
        that never published."""
        del error  # journalled by the writer
        self.ckpt_write_failures += 1
        if self.last_good_ckpt == path:
            self.last_good_ckpt = None
            self.last_good_step = -1

    # ---- checkpointable state ----------------------------------------

    def to_state(self) -> Dict[str, Any]:
        """Plain-scalar state for the checkpoint ``extra`` payload."""
        return {
            "strikes": [int(s) for s in self.strikes],
            "consecutive_skips": int(self.consecutive_skips),
            "forced_dense": [int(b) for b in self.forced_dense],
            "last_good_step": int(self.last_good_step),
            "last_good_ckpt": self.last_good_ckpt or "",
            "fallback_events": int(self.fallback_events),
            "restore_events": int(self.restore_events),
            "remesh_events": int(self.remesh_events),
            "ckpt_write_failures": int(self.ckpt_write_failures),
            "dead_workers": [int(w) for w in self.dead_workers],
            "cooldown_until": int(self._cooldown_until),
        }

    def load_state(self, state: Dict[str, Any]) -> "Supervisor":
        """Restore counters/fallbacks saved by :meth:`to_state` (tolerant
        of missing keys, like the checkpoint field merge)."""
        if not state:
            return self
        strikes = [int(s) for s in np.asarray(
            state.get("strikes", self.strikes)).tolist()]
        # bucket count changes (replan) keep the overlapping prefix
        self.strikes = (strikes + [0] * self.num_buckets)[:self.num_buckets]
        self.consecutive_skips = int(state.get("consecutive_skips", 0))
        self.forced_dense = sorted(
            int(b) for b in np.asarray(
                state.get("forced_dense", [])).reshape(-1).tolist()
            if 0 <= int(b) < self.num_buckets)
        self.last_good_step = int(state.get("last_good_step", -1))
        ck = state.get("last_good_ckpt", "")
        if isinstance(ck, bytes):
            ck = ck.decode()
        self.last_good_ckpt = str(ck) or None
        self.fallback_events = int(state.get("fallback_events", 0))
        self.restore_events = int(state.get("restore_events", 0))
        self.remesh_events = int(state.get("remesh_events", 0))
        self.ckpt_write_failures = int(state.get("ckpt_write_failures", 0))
        self.dead_workers = [int(w) for w in np.asarray(
            state.get("dead_workers", [])).reshape(-1).tolist()]
        self._cooldown_until = int(state.get("cooldown_until", -1))
        return self


def plan_with_fallbacks(names: Sequence[str], forced_dense: Sequence[int]
                        ) -> List[str]:
    """Apply the supervisor's forced-dense set to a per-bucket algorithm
    plan (autotuned or uniform) — the single place the escalation ladder
    rewrites a plan, so autotune re-tunes cannot silently resurrect a
    quarantined bucket's sparse collective."""
    out = list(names)
    for b in forced_dense:
        if 0 <= b < len(out):
            out[b] = "dense"
    return out
