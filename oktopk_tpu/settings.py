"""Global debug/profiling flags (reference VGG/settings.py:1-39: DEBUG,
SPARSE, WARMUP, PROFILING, PROFILING_NORM, PROFILING_GRAD, TENSORBOARD
module-level switches).

Unlike the reference these do not silently change hot-path behaviour at
import time; they are read once where the relevant feature is built:

- ``PROFILING_NORM`` -> ``build_sparse_grad_step(profile_norm=True)`` adds an
  ``eps_vs_dense`` metric (runs a dense pmean alongside the sparse collective
  every step, like reference VGG/allreducer.py:584-606,1072-1080);
- ``PROFILING`` -> the trainer logs per-step selection counts/thresholds
  (always present in metrics; this flag widens log verbosity);
- ``PROFILING_GRAD`` -> drivers dump flat-gradient .npy snapshots.

Env overrides: OKTOPK_DEBUG / OKTOPK_PROFILING / OKTOPK_PROFILING_NORM.
"""

import os


def _env_flag(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    return default if v is None else v.lower() in ("1", "true", "yes")


DEBUG = _env_flag("OKTOPK_DEBUG")
PROFILING = _env_flag("OKTOPK_PROFILING")
PROFILING_NORM = _env_flag("OKTOPK_PROFILING_NORM")
PROFILING_GRAD = _env_flag("OKTOPK_PROFILING_GRAD")
TENSORBOARD = _env_flag("OKTOPK_TENSORBOARD")
