"""Training loop layer (reference L4/L5: DLTrainer at VGG/dl_trainer.py:105,
drivers at VGG/main_trainer.py:26 and BERT/bert/main_bert.py:641)."""

from oktopk_tpu.train.losses import (  # noqa: F401
    softmax_cross_entropy,
    lm_cross_entropy,
    ctc_loss,
    bert_pretrain_loss,
)
from oktopk_tpu.train.trainer import Trainer  # noqa: F401
