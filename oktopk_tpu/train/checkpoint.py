"""Checkpoint / resume — including the sparse-algorithm state.

Reference behaviour: VGG/LSTM assemble per-epoch checkpoints (commented-out
save at VGG/dl_trainer.py:623-634,792-793) and resume via --pretrain
(:202-257); BERT saves per-epoch stage checkpoints
(BERT/bert/main_bert.py:207-219,1089-1096). Crucially the reference NEVER
checkpoints compressor residuals, thresholds or region boundaries (class-attr
dicts, VGG/compression.py:28,170) — a resume silently resets error feedback
(SURVEY.md §5.4). Here the whole DistTrainState — params, optimizer moments,
batch stats, residual, thresholds, boundaries, step counters — is one pytree,
serialised with flax msgpack.

Durability (``oktopk_tpu.train.durable``): every save publishes atomically
(tmp file -> fsync -> ``os.replace`` -> dir fsync) and writes a sidecar
manifest with a digest of the bytes; ``restore_checkpoint`` verifies by
default and walks newest -> oldest past corrupt files. Reads go through a
small mtime-keyed cache so ``restore_checkpoint`` + ``load_extra`` on the
same file decode once.

Preemption (save-on-signal -> requeue, reference
BERT/bert/main_bert.py:73-153) lives in ``oktopk_tpu.train.preemption``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, Optional, Tuple

import flax.serialization
import jax
import numpy as np

from oktopk_tpu.train import durable

_log = logging.getLogger("oktopk_tpu")

# Above this fraction of mismatched leaves the checkpoint is almost
# certainly for a different --model/config, and restore raises instead
# of silently training a mostly-fresh model (force=True downgrades the
# raise back to the warning).
MERGE_ESCALATION_FRAC = 0.5


def save_checkpoint(ckpt_dir: str, state: Any, step: int,
                    prefix: str = "ckpt",
                    extra: Optional[dict] = None,
                    qualified: bool = True,
                    manifest: bool = True) -> str:
    """Serialise the full train state to ``<ckpt_dir>/<prefix>-<step>.msgpack``.

    ``extra`` is an optional side payload of plain scalars/lists (e.g.
    the resilience supervisor's strike counters and fallback plan,
    ``Trainer.supervisor_extra``) stored under its own key — it never
    participates in the train-state pytree merge and is read back with
    :func:`load_extra`.

    The data file is published atomically with fsync on the tmp file and
    the directory (no torn-write window), then the sidecar manifest
    (digest, size, environment fingerprint, ``qualified`` bit) is
    published the same way — a crash in between leaves a fully-written
    but manifest-less file, which restore accepts as legacy.
    ``qualified=False`` marks a mid-incident checkpoint (skips in
    flight) that retention may collect but the supervisor will not
    restore-target; ``manifest=False`` reproduces the legacy format
    (tests only)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    host_state = jax.device_get(state)
    path = os.path.join(ckpt_dir, f"{prefix}-{int(step)}.msgpack")
    payload = {"step": int(step), "state": host_state}
    if extra:
        # JSON-encoded: flax's to_state_dict would rewrite lists into
        # index-keyed dicts, and the payload is plain scalars anyway
        payload["extra"] = json.dumps(extra)
    data = flax.serialization.to_bytes(payload)
    durable.atomic_write_bytes(path, data)
    if manifest:
        durable.write_manifest(path, step, data, qualified=qualified)
    return path


def latest_checkpoint(ckpt_dir: str, prefix: str = "ckpt") -> Optional[str]:
    """Newest checkpoint file by step (no verification — use
    ``durable.latest_verified_checkpoint`` on resume paths). Stale
    ``*.tmp`` remnants from a crashed writer are garbage-collected on
    the way through the scan."""
    entries = durable.scan_checkpoints(ckpt_dir, prefix)
    return entries[0][1] if entries else None


# ---------------------------------------------------------------------------
# shared raw reader (one decode per file for restore + load_extra)

_READ_CACHE: Dict[str, Tuple[Tuple[int, int], Any]] = {}
_READ_CACHE_MAX = 4
_READ_CACHE_LOCK = threading.Lock()


def read_payload(path: str, use_cache: bool = True) -> Any:
    """The raw msgpack payload of ``path`` ({"step", "state", "extra"?}).

    ``restore_checkpoint`` and ``load_extra`` both need the same file on
    every resume; a tiny cache keyed on (mtime_ns, size) makes that one
    open + one decode instead of two. Callers must not mutate the
    returned tree (restore shallow-copies before popping keys)."""
    apath = os.path.abspath(path)
    st = os.stat(apath)
    key = (st.st_mtime_ns, st.st_size)
    if use_cache:
        with _READ_CACHE_LOCK:
            hit = _READ_CACHE.get(apath)
            if hit is not None and hit[0] == key:
                return hit[1]
    with open(apath, "rb") as f:
        raw = flax.serialization.msgpack_restore(f.read())
    if use_cache:
        with _READ_CACHE_LOCK:
            if len(_READ_CACHE) >= _READ_CACHE_MAX and apath not in _READ_CACHE:
                _READ_CACHE.pop(next(iter(_READ_CACHE)))
            _READ_CACHE[apath] = (key, raw)
    return raw


def _merge_missing(template, loaded, path="", defaulted=None, dropped=None,
                   counts=None):
    """Overlay ``loaded`` on ``template``, keeping template defaults for keys
    the checkpoint predates (e.g. a DistTrainState field added after the
    checkpoint was saved — strict flax restore would raise 'Missing field').

    A ``None`` in the checkpoint never replaces a non-``None`` template leaf
    (e.g. a momentum buffer the saved run had disabled) — the template's
    freshly-initialised value wins. ``defaulted``/``dropped`` collect the
    key paths that kept template values / were ignored, for diagnostics;
    ``counts`` (keys ``defaulted``/``dropped``) accumulates the same in
    *leaves*, the unit the escalation threshold is measured in."""
    if isinstance(template, dict):
        if not isinstance(loaded, dict):
            return loaded
        for k in loaded:
            if k not in template:
                if dropped is not None:
                    dropped.append(f"{path}{k}")
                if counts is not None:
                    counts["dropped"] += _num_leaves(loaded[k])
        out = {}
        for k, v in template.items():
            if k in loaded:
                lv = loaded[k]
                if lv is None and v is not None:
                    if defaulted is not None:
                        defaulted.append(f"{path}{k}")
                    if counts is not None:
                        counts["defaulted"] += _num_leaves(v)
                    out[k] = v
                else:
                    out[k] = _merge_missing(v, lv, f"{path}{k}/",
                                            defaulted, dropped, counts)
            else:
                if defaulted is not None:
                    defaulted.append(f"{path}{k}")
                if counts is not None:
                    counts["defaulted"] += _num_leaves(v)
                out[k] = v
        return out
    return loaded


def _num_leaves(tree: Any) -> int:
    """Leaves under a state-dict subtree (a dict counts its values
    recursively; anything else, None included, is one leaf)."""
    if isinstance(tree, dict):
        return sum(_num_leaves(v) for v in tree.values())
    return 1


def apply_template(raw: Any, state_template: Any, path: str = "<payload>",
                   force: bool = False) -> Tuple[Any, int]:
    """Merge an already-decoded checkpoint payload into the template's
    pytree structure; returns ``(state, step)``.

    This is the template half of :func:`restore_checkpoint`, split out
    so ``durable.verified_restore`` can verify/decode candidates itself
    and share :func:`read_payload`'s cache. When more than
    ``MERGE_ESCALATION_FRAC`` of the leaves were defaulted or dropped,
    the checkpoint is almost certainly for a different model/config and
    this raises ``ValueError`` (``force=True`` — the ``--ckpt-force``
    flag — downgrades it to the warning)."""
    raw = dict(raw)              # never mutate read_payload's cached tree
    raw.pop("extra", None)       # side payload (load_extra), not train state
    wrapped = {"step": 0, "state": jax.device_get(state_template)}
    wrapped_sd = flax.serialization.to_state_dict(wrapped)
    defaulted, dropped = [], []
    counts = {"defaulted": 0, "dropped": 0}
    merged = _merge_missing(wrapped_sd, raw, defaulted=defaulted,
                            dropped=dropped, counts=counts)
    if defaulted or dropped:
        total = _num_leaves(wrapped_sd) + counts["dropped"]
        frac = (counts["defaulted"] + counts["dropped"]) / max(1, total)
        msg = (f"checkpoint {path} does not fully match the current "
               f"state: {len(defaulted)} field(s) kept fresh template "
               f"values {defaulted[:8]}; {len(dropped)} checkpoint "
               f"field(s) ignored {dropped[:8]} "
               f"({frac:.0%} of leaves mismatched)")
        if frac > MERGE_ESCALATION_FRAC and not force:
            raise ValueError(
                msg + f" — above the {MERGE_ESCALATION_FRAC:.0%} "
                "threshold, this checkpoint is almost certainly for a "
                "different --model/config; pass --ckpt-force to restore "
                "anyway")
        _log.warning("%s", msg)
    payload = flax.serialization.from_state_dict(wrapped, merged)
    return payload["state"], int(payload["step"])


def load_encoder_params(ckpt_dir_or_file: str, params: Any,
                        subtree: str = "bert",
                        prefix: str = "ckpt") -> Any:
    """Warm-start fine-tuning: graft a pretrained encoder subtree into
    freshly initialised params, leaving the task head untouched.

    The reference's GLUE driver loads only the ``bert.*`` weights of a
    pretraining checkpoint into the classification model
    (BERT/bert/compute_glue_scores.py); here the pretraining checkpoint is a
    full DistTrainState msgpack (``save_checkpoint``) and both
    ``BertForPreTraining`` and ``BertForSequenceClassification`` carry the
    encoder under ``params[subtree]``, so the graft is a single subtree
    restore against the fine-tune template. Every leaf is shape-checked
    against the template (flax's ``from_state_dict`` accepts wrong-shaped
    leaves silently; a bert_large checkpoint grafted into a bert_base model
    must fail here, at the ``--ckpt`` flag, not steps later inside XLA).
    """
    path = ckpt_dir_or_file
    if os.path.isdir(path):
        path = durable.latest_verified_checkpoint(path, prefix)
        if path is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir_or_file}")
    raw = read_payload(path)
    loaded = raw.get("state", raw)
    loaded = loaded.get("params", loaded)
    if subtree not in loaded:
        raise KeyError(
            f"checkpoint {path} has no '{subtree}' params subtree "
            f"(top-level keys: {sorted(loaded)[:8]})")
    if subtree not in params:
        raise KeyError(f"model params have no '{subtree}' subtree")
    encoder = flax.serialization.from_state_dict(
        params[subtree], loaded[subtree])
    mismatches = []
    for (path_t, t), (_, l) in zip(
            jax.tree_util.tree_leaves_with_path(params[subtree]),
            jax.tree_util.tree_leaves_with_path(encoder)):
        if tuple(np.shape(t)) != tuple(np.shape(l)):
            mismatches.append(
                f"{jax.tree_util.keystr(path_t)}: template "
                f"{tuple(np.shape(t))} vs checkpoint {tuple(np.shape(l))}")
    if mismatches:
        raise ValueError(
            f"checkpoint {path} encoder shapes do not match the model "
            f"(wrong --model for this checkpoint?): " + "; ".join(
                mismatches[:6]))
    out = dict(params)
    out[subtree] = encoder
    return out


def load_extra(ckpt_dir_or_file: str, prefix: str = "ckpt"
               ) -> Optional[dict]:
    """The ``extra`` side payload of a checkpoint (None when the file
    predates it or was saved without one). Shares :func:`read_payload`'s
    cache with ``restore_checkpoint``, so a resume that reads both pays
    one decode."""
    path = ckpt_dir_or_file
    if os.path.isdir(path):
        path = durable.latest_verified_checkpoint(path, prefix)
        if path is None:
            return None
    extra = read_payload(path).get("extra")
    if extra is None:
        return None
    if isinstance(extra, bytes):
        extra = extra.decode()
    return json.loads(extra)


def restore_checkpoint(ckpt_dir_or_file: str, state_template: Any,
                       prefix: str = "ckpt", verify: bool = True,
                       bus=None, journal=None, step: int = 0,
                       force: bool = False) -> Tuple[Any, int]:
    """Restore into the template's pytree structure; returns (state, step).

    Fields present in the template but absent from the file keep the
    template's (freshly initialised) values, so checkpoints saved before a
    state field existed still resume; a mismatch beyond
    ``MERGE_ESCALATION_FRAC`` of leaves raises (``force`` overrides).

    With ``verify=True`` (the default) candidates are checked against
    their manifests and walked newest -> oldest past corrupt files,
    journalling ``ckpt_verify_failed``/``ckpt_restore`` onto ``journal``
    (a HealthJournal) or ``bus`` when given — see
    ``durable.verified_restore`` for the full contract. ``verify=False``
    restores exactly the named file with no fallback."""
    if verify:
        state, ckpt_step, _, _, _ = durable.verified_restore(
            ckpt_dir_or_file, state_template, prefix=prefix, bus=bus,
            journal=journal, step=step, force=force)
        return state, ckpt_step
    path = ckpt_dir_or_file
    if os.path.isdir(path):
        path = latest_checkpoint(path, prefix)
        if path is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir_or_file}")
    return apply_template(read_payload(path), state_template, path=path,
                          force=force)
