"""Checkpoint / resume — including the sparse-algorithm state.

Reference behaviour: VGG/LSTM assemble per-epoch checkpoints (commented-out
save at VGG/dl_trainer.py:623-634,792-793) and resume via --pretrain
(:202-257); BERT saves per-epoch stage checkpoints
(BERT/bert/main_bert.py:207-219,1089-1096). Crucially the reference NEVER
checkpoints compressor residuals, thresholds or region boundaries (class-attr
dicts, VGG/compression.py:28,170) — a resume silently resets error feedback
(SURVEY.md §5.4). Here the whole DistTrainState — params, optimizer moments,
batch stats, residual, thresholds, boundaries, step counters — is one pytree,
serialised with flax msgpack.

Preemption (save-on-signal -> requeue, reference
BERT/bert/main_bert.py:73-153) lives in ``oktopk_tpu.train.preemption``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import flax.serialization
import jax
import numpy as np


def save_checkpoint(ckpt_dir: str, state: Any, step: int,
                    prefix: str = "ckpt",
                    extra: Optional[dict] = None) -> str:
    """Serialise the full train state to ``<ckpt_dir>/<prefix>-<step>.msgpack``.

    ``extra`` is an optional side payload of plain scalars/lists (e.g.
    the resilience supervisor's strike counters and fallback plan,
    ``Trainer.supervisor_extra``) stored under its own key — it never
    participates in the train-state pytree merge and is read back with
    :func:`load_extra`."""
    os.makedirs(ckpt_dir, exist_ok=True)
    host_state = jax.device_get(state)
    path = os.path.join(ckpt_dir, f"{prefix}-{step}.msgpack")
    payload = {"step": step, "state": host_state}
    if extra:
        # JSON-encoded: flax's to_state_dict would rewrite lists into
        # index-keyed dicts, and the payload is plain scalars anyway
        payload["extra"] = json.dumps(extra)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(flax.serialization.to_bytes(payload))
    os.replace(tmp, path)   # atomic publish
    return path


def latest_checkpoint(ckpt_dir: str, prefix: str = "ckpt") -> Optional[str]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for f in os.listdir(ckpt_dir):
        if f.startswith(prefix + "-") and f.endswith(".msgpack"):
            try:
                steps.append((int(f[len(prefix) + 1:-len(".msgpack")]), f))
            except ValueError:
                continue
    if not steps:
        return None
    return os.path.join(ckpt_dir, max(steps)[1])


def _merge_missing(template, loaded, path="", defaulted=None, dropped=None):
    """Overlay ``loaded`` on ``template``, keeping template defaults for keys
    the checkpoint predates (e.g. a DistTrainState field added after the
    checkpoint was saved — strict flax restore would raise 'Missing field').

    A ``None`` in the checkpoint never replaces a non-``None`` template leaf
    (e.g. a momentum buffer the saved run had disabled) — the template's
    freshly-initialised value wins. ``defaulted``/``dropped`` collect the
    key paths that kept template values / were ignored, for diagnostics."""
    if isinstance(template, dict):
        if not isinstance(loaded, dict):
            return loaded
        if dropped is not None:
            for k in loaded:
                if k not in template:
                    dropped.append(f"{path}{k}")
        out = {}
        for k, v in template.items():
            if k in loaded:
                lv = loaded[k]
                if lv is None and v is not None:
                    if defaulted is not None:
                        defaulted.append(f"{path}{k}")
                    out[k] = v
                else:
                    out[k] = _merge_missing(v, lv, f"{path}{k}/",
                                            defaulted, dropped)
            else:
                if defaulted is not None:
                    defaulted.append(f"{path}{k}")
                out[k] = v
        return out
    return loaded


def load_encoder_params(ckpt_dir_or_file: str, params: Any,
                        subtree: str = "bert",
                        prefix: str = "ckpt") -> Any:
    """Warm-start fine-tuning: graft a pretrained encoder subtree into
    freshly initialised params, leaving the task head untouched.

    The reference's GLUE driver loads only the ``bert.*`` weights of a
    pretraining checkpoint into the classification model
    (BERT/bert/compute_glue_scores.py); here the pretraining checkpoint is a
    full DistTrainState msgpack (``save_checkpoint``) and both
    ``BertForPreTraining`` and ``BertForSequenceClassification`` carry the
    encoder under ``params[subtree]``, so the graft is a single subtree
    restore against the fine-tune template. Every leaf is shape-checked
    against the template (flax's ``from_state_dict`` accepts wrong-shaped
    leaves silently; a bert_large checkpoint grafted into a bert_base model
    must fail here, at the ``--ckpt`` flag, not steps later inside XLA).
    """
    path = ckpt_dir_or_file
    if os.path.isdir(path):
        path = latest_checkpoint(path, prefix)
        if path is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir_or_file}")
    with open(path, "rb") as f:
        raw = flax.serialization.msgpack_restore(f.read())
    loaded = raw.get("state", raw)
    loaded = loaded.get("params", loaded)
    if subtree not in loaded:
        raise KeyError(
            f"checkpoint {path} has no '{subtree}' params subtree "
            f"(top-level keys: {sorted(loaded)[:8]})")
    if subtree not in params:
        raise KeyError(f"model params have no '{subtree}' subtree")
    encoder = flax.serialization.from_state_dict(
        params[subtree], loaded[subtree])
    mismatches = []
    for (path_t, t), (_, l) in zip(
            jax.tree_util.tree_leaves_with_path(params[subtree]),
            jax.tree_util.tree_leaves_with_path(encoder)):
        if tuple(np.shape(t)) != tuple(np.shape(l)):
            mismatches.append(
                f"{jax.tree_util.keystr(path_t)}: template "
                f"{tuple(np.shape(t))} vs checkpoint {tuple(np.shape(l))}")
    if mismatches:
        raise ValueError(
            f"checkpoint {path} encoder shapes do not match the model "
            f"(wrong --model for this checkpoint?): " + "; ".join(
                mismatches[:6]))
    out = dict(params)
    out[subtree] = encoder
    return out


def load_extra(ckpt_dir_or_file: str, prefix: str = "ckpt"
               ) -> Optional[dict]:
    """The ``extra`` side payload of a checkpoint (None when the file
    predates it or was saved without one)."""
    path = ckpt_dir_or_file
    if os.path.isdir(path):
        path = latest_checkpoint(path, prefix)
        if path is None:
            return None
    with open(path, "rb") as f:
        raw = flax.serialization.msgpack_restore(f.read())
    extra = raw.get("extra")
    if extra is None:
        return None
    if isinstance(extra, bytes):
        extra = extra.decode()
    return json.loads(extra)


def restore_checkpoint(ckpt_dir_or_file: str, state_template: Any,
                       prefix: str = "ckpt") -> Tuple[Any, int]:
    """Restore into the template's pytree structure; returns (state, step).

    Fields present in the template but absent from the file keep the
    template's (freshly initialised) values, so checkpoints saved before a
    state field existed still resume."""
    path = ckpt_dir_or_file
    if os.path.isdir(path):
        path = latest_checkpoint(path, prefix)
        if path is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir_or_file}")
    with open(path, "rb") as f:
        raw = flax.serialization.msgpack_restore(f.read())
    raw.pop("extra", None)   # side payload (load_extra), not train state
    wrapped = {"step": 0, "state": jax.device_get(state_template)}
    defaulted, dropped = [], []
    merged = _merge_missing(flax.serialization.to_state_dict(wrapped), raw,
                            defaulted=defaulted, dropped=dropped)
    if defaulted or dropped:
        import logging
        logging.getLogger("oktopk_tpu").warning(
            "checkpoint %s does not fully match the current state: "
            "%d field(s) kept fresh template values %s; %d checkpoint "
            "field(s) ignored %s", path, len(defaulted), defaulted[:8],
            len(dropped), dropped[:8])
    payload = flax.serialization.from_state_dict(wrapped, merged)
    return payload["state"], int(payload["step"])


