"""Durable state plane: verified, async, self-healing checkpoints.

``train/checkpoint.py`` serialises the whole DistTrainState — params,
optimizer moments, and crucially the error-feedback state (residuals,
thresholds, boundaries) whose loss the reference never notices
(SURVEY.md §5.4). Every recovery path in the repo bottoms out there:
the supervisor's divergence restore (``resilience/supervisor.py``),
remesh carry-over, and preemption park/requeue. A checkpoint that lies
— truncated by a crashed writer, bit-rotted on disk, half-replaced by
a torn write — is therefore a *silent accuracy regression*, not just a
crash. This module makes the storage leg of the self-healing loop as
trustworthy as the in-step leg:

- **Manifests** (:func:`write_manifest`): every checkpoint gets a
  ``ckpt-<step>.manifest.json`` sidecar carrying a digest of the
  msgpack bytes, the payload size, the environment fingerprint from
  ``environment_header()`` (schema/jax/device), and a ``qualified`` bit
  mirroring the supervisor's good-vs-mid-incident distinction.
- **Verification** (:func:`verify_checkpoint`,
  :func:`verified_restore`): restore walks candidates newest → oldest,
  skipping digest/size mismatches and torn writes, journalling a
  ``ckpt_verify_failed`` event per rejected file and a ``ckpt_restore``
  for the one that loaded — a restore that fell back two checkpoints is
  visible on the incident timeline. Manifest-less (legacy) checkpoints
  are accepted with a journalled warning, never rejected.
- **Async saving** (:class:`AsyncCheckpointer`): the caller thread only
  pays ``jax.device_get``; serialize + fsync'd atomic write + post-write
  verify run on a background thread with bounded queue depth,
  barrier-on-exit (:meth:`AsyncCheckpointer.drain` — the preemption
  epilogue and ``main_trainer.py`` drain it), and write-failure
  escalation to the supervisor instead of a swallowed exception.
- **Retention** (:func:`apply_retention`): keep-last-N plus an
  always-pin of the newest *qualified* checkpoint, so the supervisor's
  divergence restore never loses its target to garbage collection.

Offline, ``scripts/ckpt_fsck.py`` runs the same verification over a
checkpoint directory as a pre-resume CI/cron gate.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

MANIFEST_VERSION = 1
MANIFEST_SUFFIX = ".manifest.json"

_log = logging.getLogger("oktopk_tpu")


# ---------------------------------------------------------------------------
# digests

def _crc32(data: bytes) -> str:
    return f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"


_DIGESTS: Dict[str, Callable[[bytes], str]] = {"crc32": _crc32}
try:  # optional, never installed here — gate, don't require
    import xxhash as _xxhash

    _DIGESTS["xxh64"] = lambda data: _xxhash.xxh64(data).hexdigest()
except Exception:  # pragma: no cover - container has no xxhash
    pass

DEFAULT_DIGEST = "crc32"


def compute_digest(data: bytes, algo: str = DEFAULT_DIGEST) -> str:
    """``"<algo>:<hex>"`` of ``data`` (crc32 always available; xxh64 when
    the library exists — the manifest records which, so a file written
    with one can verify on a host that has both)."""
    if algo not in _DIGESTS:
        raise ValueError(f"unknown digest algo {algo!r}; "
                         f"one of {sorted(_DIGESTS)}")
    return f"{algo}:{_DIGESTS[algo](data)}"


def _digest_matches(data: bytes, recorded: str) -> Optional[bool]:
    """True/False when the recorded digest's algo is computable here,
    None when it is not (treated as unverifiable, not corrupt)."""
    algo = recorded.split(":", 1)[0]
    if algo not in _DIGESTS:
        return None
    return compute_digest(data, algo) == recorded


# ---------------------------------------------------------------------------
# atomic, torn-write-safe file publication

def fsync_dir(dirpath: str) -> None:
    """fsync a directory so a just-published rename survives power loss
    (best-effort: not every filesystem exposes a dir fd)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp-file -> flush -> fsync -> ``os.replace`` -> dir fsync: a
    reader never sees a partial file, and a crash between any two steps
    leaves either the old file or a ``*.tmp`` remnant (which the
    checkpoint scan garbage-collects), never a torn publish."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))


def clean_stale_tmp(ckpt_dir: str, max_age_s: float = 3600.0) -> List[str]:
    """Remove ``*.tmp`` remnants left by a crashed writer. Only files
    older than ``max_age_s`` go — an in-flight :class:`AsyncCheckpointer`
    write must not have its tmp file deleted from under it."""
    removed: List[str] = []
    if not os.path.isdir(ckpt_dir):
        return removed
    now = time.time()
    for name in os.listdir(ckpt_dir):
        if not name.endswith(".tmp"):
            continue
        path = os.path.join(ckpt_dir, name)
        try:
            if now - os.path.getmtime(path) >= max_age_s:
                os.remove(path)
                removed.append(path)
        except OSError:
            continue
    return removed


# ---------------------------------------------------------------------------
# manifests

def manifest_path(ckpt_path: str) -> str:
    """``ckpt-<step>.msgpack`` -> ``ckpt-<step>.manifest.json``."""
    base = ckpt_path
    if base.endswith(".msgpack"):
        base = base[: -len(".msgpack")]
    return base + MANIFEST_SUFFIX


def write_manifest(ckpt_path: str, step: int, data: bytes,
                   qualified: bool = True,
                   digest_algo: str = DEFAULT_DIGEST) -> Dict[str, Any]:
    """Publish the sidecar manifest for an already-published checkpoint
    file. Written atomically AFTER the data file: a crash in between
    leaves a fully-written but manifest-less checkpoint, which the
    verifying path accepts as legacy (with a journalled warning) rather
    than rejecting a good file."""
    from oktopk_tpu.autotune.journal import environment_header

    man = {
        "manifest_version": MANIFEST_VERSION,
        "file": os.path.basename(ckpt_path),
        "step": int(step),
        "bytes": len(data),
        "digest": compute_digest(data, digest_algo),
        "qualified": bool(qualified),
        "environment": environment_header(),
        "created": time.time(),
    }
    atomic_write_bytes(manifest_path(ckpt_path),
                       (json.dumps(man, sort_keys=True) + "\n").encode())
    return man


def read_manifest(ckpt_path: str) -> Optional[Dict[str, Any]]:
    """The parsed sidecar manifest, or None when absent/unparseable."""
    try:
        with open(manifest_path(ckpt_path)) as f:
            man = json.load(f)
        return man if isinstance(man, dict) else None
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# verification

@dataclasses.dataclass
class VerifyResult:
    """Verdict for one checkpoint file."""

    path: str
    ok: bool
    reason: str = "ok"           # why it failed (or "ok" / "no_manifest")
    legacy: bool = False         # no manifest: accepted, but unverifiable
    qualified: bool = True       # manifest's qualified bit (True if legacy)
    manifest: Optional[Dict[str, Any]] = None
    env_mismatch: bool = False   # saved under a different jax/schema


def verify_checkpoint(ckpt_path: str, deep: bool = False) -> VerifyResult:
    """Check one checkpoint file against its manifest.

    Failure modes, in check order: missing/empty file; manifest present
    but size mismatched (truncation / torn write); digest mismatched
    (bit rot / flipped bytes). A missing manifest is NOT a failure — the
    file predates the durable plane — but flags ``legacy`` so callers
    can journal the warning. ``deep=True`` additionally decodes the
    msgpack container (fsck's thorough mode; legacy files get no other
    check)."""
    if not os.path.isfile(ckpt_path):
        return VerifyResult(ckpt_path, False, reason="missing_file")
    try:
        with open(ckpt_path, "rb") as f:
            data = f.read()
    except OSError as e:
        return VerifyResult(ckpt_path, False, reason=f"unreadable: {e}")
    if not data:
        return VerifyResult(ckpt_path, False, reason="empty_file")

    man = read_manifest(ckpt_path)
    if man is None:
        res = VerifyResult(ckpt_path, True, reason="no_manifest",
                           legacy=True)
    else:
        if int(man.get("bytes", -1)) != len(data):
            return VerifyResult(
                ckpt_path, False, manifest=man,
                qualified=bool(man.get("qualified", True)),
                reason=f"size_mismatch: manifest {man.get('bytes')} B "
                       f"vs file {len(data)} B")
        match = _digest_matches(data, str(man.get("digest", "")))
        if match is False:
            return VerifyResult(
                ckpt_path, False, manifest=man,
                qualified=bool(man.get("qualified", True)),
                reason="digest_mismatch")
        env = man.get("environment") or {}
        from oktopk_tpu.obs.events import SCHEMA_VERSION
        env_mismatch = (env.get("schema_version") is not None
                        and int(env["schema_version"]) != SCHEMA_VERSION)
        res = VerifyResult(ckpt_path, True, manifest=man,
                           qualified=bool(man.get("qualified", True)),
                           reason=("digest_unverifiable"
                                   if match is None else "ok"),
                           env_mismatch=env_mismatch)
    if deep:
        try:
            import flax.serialization
            flax.serialization.msgpack_restore(data)
        except Exception as e:
            return VerifyResult(ckpt_path, False, legacy=res.legacy,
                                manifest=res.manifest,
                                qualified=res.qualified,
                                reason=f"decode_error: {type(e).__name__}")
    return res


def scan_checkpoints(ckpt_dir: str, prefix: str = "ckpt",
                     clean_tmp: bool = True,
                     stale_tmp_age_s: float = 3600.0
                     ) -> List[Tuple[int, str]]:
    """``[(step, path), ...]`` newest first; optionally garbage-collects
    stale ``*.tmp`` remnants on the way through."""
    if not os.path.isdir(ckpt_dir):
        return []
    if clean_tmp:
        clean_stale_tmp(ckpt_dir, max_age_s=stale_tmp_age_s)
    out = []
    for name in os.listdir(ckpt_dir):
        if name.startswith(prefix + "-") and name.endswith(".msgpack"):
            try:
                out.append((int(name[len(prefix) + 1:-len(".msgpack")]),
                            os.path.join(ckpt_dir, name)))
            except ValueError:
                continue
    return sorted(out, reverse=True)


def candidate_paths(ckpt_dir_or_file: str, prefix: str = "ckpt"
                    ) -> List[str]:
    """Restore candidates newest -> oldest. A directory yields its whole
    scan; a file yields that file first, then any strictly-older
    siblings with the same prefix (the fallback ladder for a supervisor
    restore whose registered target turns out corrupt)."""
    if os.path.isdir(ckpt_dir_or_file):
        return [p for _, p in scan_checkpoints(ckpt_dir_or_file, prefix)]
    d, name = os.path.split(ckpt_dir_or_file)
    step = None
    if name.startswith(prefix + "-") and name.endswith(".msgpack"):
        try:
            step = int(name[len(prefix) + 1:-len(".msgpack")])
        except ValueError:
            step = None
    if step is None:
        return [ckpt_dir_or_file]
    older = [p for s, p in scan_checkpoints(d, prefix) if s < step]
    return [ckpt_dir_or_file] + older


def _emit(journal, bus, event: str, **fields) -> None:
    """One durable-plane event onto whichever sink the caller has: the
    health journal (which forwards to the bus itself) wins over a bare
    bus so the event is never double-delivered."""
    if journal is not None:
        journal.record(event, **fields)
    elif bus is not None:
        bus.emit(event, **fields)


def latest_verified_checkpoint(ckpt_dir: str, prefix: str = "ckpt",
                               bus=None, journal=None,
                               step: int = 0) -> Optional[str]:
    """Newest checkpoint that passes verification (legacy accepted),
    journalling a ``ckpt_verify_failed`` for each newer file skipped —
    the verifying replacement for ``checkpoint.latest_checkpoint`` on
    every resume path."""
    for path in candidate_paths(ckpt_dir, prefix):
        v = verify_checkpoint(path)
        if v.ok:
            return path
        _emit(journal, bus, "ckpt_verify_failed", step=int(step),
              path=path, reason=v.reason)
        _log.warning("checkpoint %s failed verification (%s); skipping",
                     path, v.reason)
    return None


def verified_restore(ckpt_dir_or_file: str, state_template: Any,
                     prefix: str = "ckpt", bus=None, journal=None,
                     step: int = 0, force: bool = False
                     ) -> Tuple[Any, int, str, int, bool]:
    """Restore from the newest checkpoint that verifies AND decodes,
    walking candidates newest -> oldest.

    Returns ``(state, ckpt_step, path, fallback_depth, legacy)`` where
    ``fallback_depth`` counts the newer checkpoints that had to be
    skipped (0 = the intended target loaded). Journals one
    ``ckpt_verify_failed`` per rejected file (digest/size mismatch,
    torn write, undecodable legacy) and one ``ckpt_restore`` for the
    winner, so the incident timeline shows exactly how far back the run
    had to reach. Raises ``FileNotFoundError`` when no candidate is
    restorable; a template/checkpoint structure mismatch beyond the
    merge threshold raises ``ValueError`` *without* falling back — a
    wrong ``--model`` must fail loudly, not restore an older wrong
    checkpoint (``force=True`` is the escape hatch)."""
    from oktopk_tpu.train import checkpoint as ckpt

    depth = 0
    candidates = candidate_paths(ckpt_dir_or_file, prefix)
    for path in candidates:
        v = verify_checkpoint(path)
        if not v.ok:
            _emit(journal, bus, "ckpt_verify_failed", step=int(step),
                  path=path, reason=v.reason)
            _log.warning("checkpoint %s failed verification (%s); "
                         "falling back", path, v.reason)
            depth += 1
            continue
        try:
            raw = ckpt.read_payload(path)
        except Exception as e:
            # digest-clean files cannot hit this; an unverifiable legacy
            # file (truncated before manifests existed) can
            _emit(journal, bus, "ckpt_verify_failed", step=int(step),
                  path=path, reason=f"decode_error: {type(e).__name__}")
            _log.warning("checkpoint %s undecodable (%r); falling back",
                         path, e)
            depth += 1
            continue
        if v.legacy:
            _log.warning("checkpoint %s has no manifest (predates the "
                         "durable state plane): restoring unverified",
                         path)
        if v.env_mismatch:
            _log.warning("checkpoint %s was saved under a different "
                         "journal schema: %s", path,
                         (v.manifest or {}).get("environment"))
        state, ckpt_step = ckpt.apply_template(raw, state_template,
                                               path=path, force=force)
        _emit(journal, bus, "ckpt_restore", step=int(step), path=path,
              ckpt_step=int(ckpt_step), fallback_depth=depth,
              legacy=bool(v.legacy))
        return state, int(ckpt_step), path, depth, bool(v.legacy)
    raise FileNotFoundError(
        f"no restorable checkpoint in {ckpt_dir_or_file!r} "
        f"({len(candidates)} candidate(s), all failed verification)")


# ---------------------------------------------------------------------------
# retention

def apply_retention(ckpt_dir: str, prefix: str = "ckpt",
                    keep_last: int = 0, pin_qualified: bool = True
                    ) -> List[str]:
    """Delete checkpoints (and their manifests) beyond the newest
    ``keep_last``, always keeping the newest *qualified* one so the
    supervisor's divergence restore never loses its target
    (``keep_last=0`` disables retention entirely). Returns the deleted
    paths."""
    if keep_last <= 0:
        return []
    entries = scan_checkpoints(ckpt_dir, prefix, clean_tmp=False)
    keep = {p for _, p in entries[:keep_last]}
    if pin_qualified:
        for _, p in entries:
            man = read_manifest(p)
            if man is None or man.get("qualified", True):
                keep.add(p)   # legacy files count as qualified: never
                break         # garbage-collect the only restore target
    deleted = []
    for _, p in entries:
        if p in keep:
            continue
        for f in (p, manifest_path(p)):
            try:
                os.remove(f)
            except OSError:
                continue
        deleted.append(p)
    return deleted


# ---------------------------------------------------------------------------
# async checkpointing

class AsyncCheckpointer:
    """Non-blocking checkpoint writer with a bounded queue.

    ``save()`` snapshots the state with ``jax.device_get`` on the caller
    thread (the only part that must see a consistent train state) and
    enqueues it; a daemon worker serialises, writes atomically
    (fsync + ``os.replace`` via ``checkpoint.save_checkpoint``),
    re-reads and verifies the published file against its manifest, and
    applies the retention policy. The queue depth bounds host memory:
    when ``queue_depth`` snapshots are already in flight, ``save()``
    blocks — training throttles rather than OOMing on a slow disk.

    Failures are escalated, never swallowed: a write or post-write
    verify error journals ``ckpt_verify_failed`` (reason
    ``write_failed: ...``), increments ``write_failures`` and invokes
    ``on_failure(step, path, exc)`` — the trainer wires that to the
    supervisor (``Trainer.note_ckpt_failure``).

    **Barrier-on-exit:** callers must :meth:`drain` (or :meth:`close`)
    before exiting — the preemption epilogue and ``main_trainer.py`` do
    — so an async save in flight at preemption time is published whole,
    never torn.
    """

    def __init__(self, ckpt_dir: str, prefix: str = "ckpt",
                 queue_depth: int = 2, keep_last: int = 0,
                 pin_qualified: bool = True, bus=None, journal=None,
                 on_failure: Optional[Callable[[int, str, BaseException],
                                               None]] = None,
                 verify: bool = True):
        self.ckpt_dir = ckpt_dir
        self.prefix = prefix
        self.keep_last = int(keep_last)
        self.pin_qualified = bool(pin_qualified)
        self.bus = bus
        self.journal = journal
        self.on_failure = on_failure
        self.verify = bool(verify)
        self.saves = 0              # completed, verified saves
        self.verify_failures = 0    # post-write verification failures
        self.write_failures = 0     # any failed save (verify included)
        self.last_path: Optional[str] = None
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, queue_depth))
        self._pending = 0
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="oktopk-async-ckpt", daemon=True)
        self._thread.start()

    # ---- producer side ------------------------------------------------

    def path_for(self, step: int) -> str:
        return os.path.join(self.ckpt_dir,
                            f"{self.prefix}-{int(step)}.msgpack")

    def save(self, state: Any, step: int, extra: Optional[dict] = None,
             qualified: bool = True) -> str:
        """Snapshot ``state`` to host and enqueue the write; returns the
        path the checkpoint WILL occupy once published (register it with
        ``Trainer.note_checkpoint`` — a restore that races the write
        self-heals by falling back to an older verified file)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        import jax

        host = jax.device_get(state)
        with self._cond:
            self._pending += 1
        self._q.put((host, int(step), extra, bool(qualified)))
        return self.path_for(step)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every enqueued save has been written and verified
        (the exit barrier). Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending > 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def close(self, timeout: Optional[float] = None) -> bool:
        """Drain, then stop the worker thread."""
        drained = self.drain(timeout)
        if not self._closed:
            self._closed = True
            self._q.put(None)
            self._thread.join(timeout)
        return drained

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- worker side --------------------------------------------------

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            host, step, extra, qualified = item
            path = self.path_for(step)
            t0 = time.monotonic()
            try:
                from oktopk_tpu.train.checkpoint import save_checkpoint

                path = save_checkpoint(self.ckpt_dir, host, step,
                                       prefix=self.prefix, extra=extra,
                                       qualified=qualified)
                if self.verify:
                    v = verify_checkpoint(path)
                    if not v.ok:
                        self.verify_failures += 1
                        raise RuntimeError(
                            f"post-write verification failed: {v.reason}")
                if self.keep_last:
                    apply_retention(self.ckpt_dir, self.prefix,
                                    self.keep_last, self.pin_qualified)
                self.saves += 1
                self.last_path = path
                man = read_manifest(path) or {}
                _emit(self.journal, self.bus, "ckpt_saved",
                      step=int(step), path=path,
                      bytes=int(man.get("bytes", 0)),
                      digest=str(man.get("digest", "")),
                      qualified=bool(qualified), source="async",
                      duration_ms=(time.monotonic() - t0) * 1e3)
            except Exception as e:
                self.write_failures += 1
                _emit(self.journal, self.bus, "ckpt_verify_failed",
                      step=int(step), path=path,
                      reason=f"write_failed: {type(e).__name__}: {e}")
                _log.error("async checkpoint save @ step %d failed: %r",
                           step, e)
                if self.on_failure is not None:
                    try:
                        self.on_failure(step, path, e)
                    except Exception:  # escalation must not kill the
                        pass           # writer thread
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()
