"""Checkpoint evaluation driver (reference VGG/evaluate.py:20: load per-epoch
checkpoints, run trainer.test). For the speech workload (lstman4*) each
eval batch is scored with real CTC loss plus greedy-decoded WER/CER
(Trainer.eval_step -> utils.decoder.GreedyDecoder — the reference's test
loop, VGG/dl_trainer.py:743-762), so the averaged metrics printed here
include ``wer``/``cer``.

Usage:
    python -m oktopk_tpu.train.evaluate --dnn vgg16 --dataset cifar10 \\
        --ckpt ./ckpts [--fake-devices 4]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dnn", default="vgg16")
    p.add_argument("--dataset", default="cifar10")
    p.add_argument("--data-dir", default="./data")
    p.add_argument("--ckpt", required=True)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-batches", type=int, default=0,
                   help="0 = one pass over eval split (synthetic: 16)")
    p.add_argument("--fake-devices", type=int, default=0)
    args = p.parse_args(argv)

    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}")
    import jax
    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")

    from oktopk_tpu.config import TrainConfig
    from oktopk_tpu.data import make_dataset
    from oktopk_tpu.train.checkpoint import restore_checkpoint
    from oktopk_tpu.train.trainer import Trainer
    from oktopk_tpu.utils.logging import get_logger

    logger = get_logger("oktopk_tpu.eval")
    cfg = TrainConfig(dnn=args.dnn, dataset=args.dataset,
                      batch_size=args.batch_size,
                      num_workers=len(jax.devices()))
    trainer = Trainer(cfg, warmup=False)
    trainer.state, step = restore_checkpoint(args.ckpt, trainer.state)
    logger.info("evaluating %s checkpoint @ step %d", args.dnn, step)

    data_iter, meta = make_dataset(args.dataset, args.dnn, args.batch_size,
                                   path=args.data_dir, split="test")
    nb = args.num_batches or (
        16 if meta.get("synthetic")
        else max(1, meta["num_examples"] // args.batch_size))
    totals = {}
    for _ in range(nb):
        m = trainer.eval_step(next(data_iter))
        for k, v in m.items():
            totals.setdefault(k, []).append(float(np.asarray(v)))
    for k, vs in totals.items():
        logger.info("%s: %.4f", k, sum(vs) / len(vs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
