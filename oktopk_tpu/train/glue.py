"""GLUE fine-tuning / evaluation (reference C22:
BERT/bert/compute_glue_scores.py — processors for MRPC/MNLI/CoLA/SST-2/
STS-B/QQP/QNLI/RTE/WNLI at :202-516, feature conversion, per-task metrics).

Each processor is a TSV column map instead of a class hierarchy; metrics are
numpy (accuracy, F1, Matthews corr for CoLA, Pearson/Spearman for STS-B).
Fine-tuning reuses the framework's distributed step via a classification
Trainer-like loop; with no GLUE data on disk the driver exits with a clear
message (fine-tuning quality is meaningless on synthetic text).

Usage:
    python -m oktopk_tpu.train.glue --task mrpc --data-dir ./data/glue/MRPC \\
        --ckpt pretrain_ckpt_dir --epochs 3
"""

from __future__ import annotations

import argparse
import csv
import os
import sys
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class GlueTask:
    name: str
    train_file: str
    dev_file: str
    text_a: int                  # column indices
    text_b: Optional[int]
    label: int
    labels: Optional[Tuple[str, ...]]   # None => regression (STS-B)
    skip_header: bool = True
    metric: str = "accuracy"


TASKS = {
    "cola": GlueTask("cola", "train.tsv", "dev.tsv", 3, None, 1,
                     ("0", "1"), skip_header=False, metric="matthews"),
    "sst-2": GlueTask("sst-2", "train.tsv", "dev.tsv", 0, None, 1,
                      ("0", "1")),
    "mrpc": GlueTask("mrpc", "train.tsv", "dev.tsv", 3, 4, 0,
                     ("0", "1"), metric="acc_f1"),
    "sts-b": GlueTask("sts-b", "train.tsv", "dev.tsv", 7, 8, 9, None,
                      metric="pearson_spearman"),
    "qqp": GlueTask("qqp", "train.tsv", "dev.tsv", 3, 4, 5,
                    ("0", "1"), metric="acc_f1"),
    "mnli": GlueTask("mnli", "train.tsv", "dev_matched.tsv", 8, 9, -1,
                     ("contradiction", "entailment", "neutral")),
    "qnli": GlueTask("qnli", "train.tsv", "dev.tsv", 1, 2, -1,
                     ("entailment", "not_entailment")),
    "rte": GlueTask("rte", "train.tsv", "dev.tsv", 1, 2, -1,
                    ("entailment", "not_entailment")),
    "wnli": GlueTask("wnli", "train.tsv", "dev.tsv", 1, 2, -1,
                     ("0", "1")),
}


def read_examples(task: GlueTask, path: str, split: str):
    fname = task.train_file if split == "train" else task.dev_file
    rows = []
    with open(os.path.join(path, fname), encoding="utf-8") as f:
        reader = csv.reader(f, delimiter="\t", quotechar=None)
        for i, line in enumerate(reader):
            if task.skip_header and i == 0:
                continue
            try:
                a = line[task.text_a]
                b = line[task.text_b] if task.text_b is not None else None
                lab = line[task.label]
            except IndexError:
                continue
            if task.labels is None:
                y = float(lab)
            else:
                if lab not in task.labels:
                    continue
                y = task.labels.index(lab)
            rows.append((a, b, y))
    return rows


def featurize(rows, tokenizer, max_len: int, regression: bool):
    ids, types, masks, ys = [], [], [], []
    for a, b, y in rows:
        i, t, m = tokenizer.encode_pair(a, b, max_len)
        ids.append(i); types.append(t); masks.append(m); ys.append(y)
    return {
        "input_ids": np.asarray(ids, np.int32),
        "token_type_ids": np.asarray(types, np.int32),
        "attention_mask": np.asarray(masks, np.int32),
        "label": np.asarray(ys, np.float32 if regression else np.int32),
    }


# ---- metrics (reference compute_glue_scores.py metric map) ---------------

def matthews_corr(y_true, y_pred):
    tp = np.sum((y_pred == 1) & (y_true == 1))
    tn = np.sum((y_pred == 0) & (y_true == 0))
    fp = np.sum((y_pred == 1) & (y_true == 0))
    fn = np.sum((y_pred == 0) & (y_true == 1))
    denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
    return float((tp * tn - fp * fn) / denom) if denom else 0.0


def f1_score(y_true, y_pred):
    tp = np.sum((y_pred == 1) & (y_true == 1))
    fp = np.sum((y_pred == 1) & (y_true == 0))
    fn = np.sum((y_pred == 0) & (y_true == 1))
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    return float(2 * prec * rec / max(prec + rec, 1e-12))


def pearson(a, b):
    a, b = a - a.mean(), b - b.mean()
    return float((a * b).sum()
                 / max(np.sqrt((a * a).sum() * (b * b).sum()), 1e-12))


def spearman(a, b):
    ra = np.argsort(np.argsort(a)).astype(np.float64)
    rb = np.argsort(np.argsort(b)).astype(np.float64)
    return pearson(ra, rb)


def task_metrics(task: GlueTask, y_true, y_pred):
    if task.metric == "matthews":
        return {"matthews": matthews_corr(y_true, y_pred)}
    if task.metric == "acc_f1":
        return {"accuracy": float(np.mean(y_true == y_pred)),
                "f1": f1_score(y_true, y_pred)}
    if task.metric == "pearson_spearman":
        return {"pearson": pearson(y_true, y_pred),
                "spearman": spearman(y_true, y_pred)}
    return {"accuracy": float(np.mean(y_true == y_pred))}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--task", required=True, choices=sorted(TASKS))
    p.add_argument("--data-dir", required=True)
    p.add_argument("--vocab-file", default=None)
    p.add_argument("--ckpt", default=None,
                   help="pretraining checkpoint to warm-start the encoder")
    p.add_argument("--model", default="bert_base",
                   choices=["bert_base", "bert_large", "bert_tiny"])
    p.add_argument("--max-seq-length", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=2e-5)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--fake-devices", type=int, default=0)
    args = p.parse_args(argv)

    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}")
    import jax
    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from oktopk_tpu.data.tokenization import FullTokenizer
    from oktopk_tpu.models.bert import (BertConfig,
                                        BertForSequenceClassification)
    from oktopk_tpu.optim import bert_adam
    from oktopk_tpu.utils.logging import get_logger

    logger = get_logger("oktopk_tpu.glue")
    task = TASKS[args.task]
    if not os.path.exists(os.path.join(args.data_dir, task.train_file)):
        logger.error("GLUE data not found at %s — download the task TSVs "
                     "(fine-tuning on synthetic text is meaningless)",
                     args.data_dir)
        return 1

    num_labels = 1 if task.labels is None else len(task.labels)
    cfg = {"bert_base": BertConfig.base, "bert_large": BertConfig.large,
           "bert_tiny": BertConfig.tiny}[args.model]()
    # Token ids must fit the embedding table: the hash-fallback tokenizer is
    # sized to the model's vocab; a real vocab file dictates the size instead
    # (and must then match the pretraining checkpoint's table — the
    # warm-start shape check enforces that).
    tokenizer = FullTokenizer(args.vocab_file, fallback_size=cfg.vocab_size)
    if tokenizer.vocab_size != cfg.vocab_size:
        import dataclasses
        logger.info("vocab file has %d entries; resizing model vocab from %d",
                    tokenizer.vocab_size, cfg.vocab_size)
        cfg = dataclasses.replace(cfg, vocab_size=tokenizer.vocab_size)
    train = featurize(read_examples(task, args.data_dir, "train"),
                      tokenizer, args.max_seq_length, task.labels is None)
    dev = featurize(read_examples(task, args.data_dir, "dev"),
                    tokenizer, args.max_seq_length, task.labels is None)
    logger.info("%s: %d train / %d dev", args.task,
                len(train["label"]), len(dev["label"]))
    model = BertForSequenceClassification(cfg, num_labels=num_labels)
    rng = jax.random.PRNGKey(0)
    ex = jnp.zeros((2, args.max_seq_length), jnp.int32)
    params = model.init({"params": rng, "dropout": rng}, ex, ex,
                        jnp.ones_like(ex), train=False)["params"]

    if args.ckpt:
        from oktopk_tpu.train.checkpoint import load_encoder_params
        # warm-start the encoder from a pretraining checkpoint; heads stay
        # freshly initialised (reference loads bert.* weights only)
        params = load_encoder_params(args.ckpt, params)
        logger.info("warm-started encoder subtree from %s", args.ckpt)

    steps_per_epoch = max(1, len(train["label"]) // args.batch_size)
    opt = bert_adam(lr=args.lr, warmup=0.1,
                    t_total=steps_per_epoch * args.epochs)
    opt_state = opt.init(params)

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["input_ids"],
                             batch["token_type_ids"],
                             batch["attention_mask"], train=True,
                             rngs={"dropout": rng})
        if task.labels is None:
            return jnp.mean((logits[:, 0] - batch["label"]) ** 2)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]).mean()

    @jax.jit
    def train_step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree.map(jnp.add, params, updates)
        return params, opt_state, loss

    @jax.jit
    def predict(params, batch):
        logits = model.apply({"params": params}, batch["input_ids"],
                             batch["token_type_ids"],
                             batch["attention_mask"], train=False)
        return logits[:, 0] if task.labels is None else jnp.argmax(logits, -1)

    nrng = np.random.RandomState(0)
    for epoch in range(args.epochs):
        order = nrng.permutation(len(train["label"]))
        losses = []
        for i in range(steps_per_epoch):
            sel = order[i * args.batch_size:(i + 1) * args.batch_size]
            batch = {k: jnp.asarray(v[sel]) for k, v in train.items()}
            rng, sub = jax.random.split(rng)
            params, opt_state, loss = train_step(params, opt_state, batch,
                                                 sub)
            losses.append(float(loss))
        preds = []
        for i in range(0, len(dev["label"]), args.batch_size):
            batch = {k: jnp.asarray(v[i:i + args.batch_size])
                     for k, v in dev.items()}
            preds.append(np.asarray(predict(params, batch)))
        preds = np.concatenate(preds)
        scores = task_metrics(task, dev["label"], preds)
        logger.info("epoch %d: train loss %.4f  %s", epoch,
                    float(np.mean(losses)),
                    "  ".join(f"{k}={v:.4f}" for k, v in scores.items()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
