"""Workload loss functions.

Reference criteria: CrossEntropyLoss for CNNs and PTB (VGG/dl_trainer.py:
181-186,661-677), warp-ctc CTCLoss for AN4 (:181-182 — replaced by
``optax.ctc_loss``, SURVEY.md §2.4), and BERT's masked-LM + NSP cross
entropies with ignore_index=-1 (BERT/runtime.py criterion path :573-640).
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def softmax_cross_entropy(logits, labels):
    """Mean CE over integer labels [B] (CNN classification). Logits cast
    to f32 so bf16 compute never runs the softmax reduction in bf16."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels).mean()


def lm_cross_entropy(logits, targets):
    """Mean CE over [B, T] targets (PTB language modelling; perplexity =
    exp(loss))."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets).mean()


def ctc_loss(logits, logit_lengths, labels, label_lengths, blank_id: int = 0):
    """CTC on per-frame logits [B, T, C] (replaces warpctc_pytorch).

    ``optax.ctc_loss`` wants paddings, not lengths — convert.
    """
    bt = logits.shape[:2]
    t_ids = jnp.arange(bt[1])[None, :]
    logit_pad = (t_ids >= logit_lengths[:, None]).astype(jnp.float32)
    l_ids = jnp.arange(labels.shape[1])[None, :]
    label_pad = (l_ids >= label_lengths[:, None]).astype(jnp.float32)
    per_seq = optax.ctc_loss(logits, logit_pad, labels, label_pad,
                             blank_id=blank_id)
    return per_seq.mean()


def bert_pretrain_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels):
    """Masked-LM CE (ignore_index=-1) + next-sentence CE, as in the
    reference's pretraining criterion."""
    vocab = mlm_logits.shape[-1]
    mask = (mlm_labels >= 0).astype(jnp.float32)
    safe_labels = jnp.maximum(mlm_labels, 0)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        mlm_logits, safe_labels)
    mlm = jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    nsp = optax.softmax_cross_entropy_with_integer_labels(
        nsp_logits, nsp_labels).mean()
    return mlm + nsp, {"mlm_loss": mlm, "nsp_loss": nsp}
