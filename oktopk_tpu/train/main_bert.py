"""CLI driver for BERT pretraining (reference BERT/bert/main_bert.py:641-1100
with the bert_oktopk.sh flag surface: --dataparallel --compressor oktopk
--density 0.01, bs 8/worker, seq 128, BertAdam lr 2e-4 warmup-linear).

The reference's SLURM rendezvous (init_distrib_slurm, :159-203), stage-module
importlib machinery (:806-822) and shape-inference dry run (:838-868) are all
unnecessary here: one process drives the mesh, the model is a single Flax
module, and shapes are static.

Example:
    python -m oktopk_tpu.train.main_bert --model bert_base \\
        --compressor oktopk --density 0.01 --num-minibatches 1024
"""

from __future__ import annotations

import argparse
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="bert_base",
                   choices=["bert_base", "bert_large", "bert_tiny"])
    p.add_argument("--batch-size", type=int, default=8,
                   help="per-worker microbatch (reference bs 8)")
    p.add_argument("--max-seq-length", type=int, default=None,
                   help="default: 128 (32 for bert_tiny)")
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--warmup-proportion", type=float, default=0.01)
    p.add_argument("--num-minibatches", type=int, default=1024)
    p.add_argument("--gradient-accumulation-steps", type=int, default=1)
    p.add_argument("--compressor", default="oktopk")
    p.add_argument("--compute-dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--wire-dtype", default="bfloat16",
                   choices=["bfloat16", "float32"],
                   help="sparse message VALUE dtype on the wire "
                        "(float32 = reference-exact uncompressed)")
    p.add_argument("--density", type=float, default=0.01)
    p.add_argument("--pipeline-stages", type=int, default=1,
                   help="pipeline depth: split the encoder over a "
                        "data x pipe mesh (reference staged models "
                        "BERT/bert/models/bert/depth=N + StageRuntime, "
                        "BERT/runtime.py:842); 1 = pure DP")
    p.add_argument("--num-microbatches", type=int, default=4,
                   help="GPipe microbatches per flush when pipelining")
    p.add_argument("--remat", action="store_true",
                   help="rematerialise stage activations in backward "
                        "(the reference's recompute mode, "
                        "BERT/runtime.py:546-558)")
    p.add_argument("--seq-shards", type=int, default=1,
                   help="sequence/context parallelism: shard the token "
                        "axis over a seq mesh with ring attention "
                        "(long-context extension; the reference has none, "
                        "SURVEY.md 5.7); 1 = off")
    p.add_argument("--seq-data-shards", type=int, default=1,
                   help="data axis of the composed data x seq mesh: "
                        "sparse-allreduce DP (any --compressor) riding "
                        "under sequence parallelism; 1 = pure seq mesh "
                        "(dense only)")
    p.add_argument("--expert-shards", type=int, default=1,
                   help="expert parallelism: Switch-style top-1 MoE FFNs "
                        "sharded over an expert mesh, GShard all_to_all "
                        "dispatch (extension; the reference has none, "
                        "SURVEY.md 2.3); 1 = off")
    p.add_argument("--num-experts", type=int, default=0,
                   help="experts per MoE layer (default: = expert-shards)")
    p.add_argument("--expert-data-shards", type=int, default=1,
                   help="data axis of the composed data x expert mesh: "
                        "sparse-allreduce DP (any --compressor) riding "
                        "with the MoE dispatch; 1 = pure expert mesh "
                        "(dense only)")
    p.add_argument("--capacity-factor", type=float, default=1.25,
                   help="MoE token capacity per expert, as a multiple of "
                        "the even-routing share")
    p.add_argument("--data-dir", default="./data")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--fake-devices", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--resume", default=None)
    p.add_argument("--handle-preemption", action="store_true",
                   help="graceful preempt: checkpoint + requeue on SIGUSR1 "
                        "(reference BERT/bert/main_bert.py:73-203)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.max_seq_length is None:
        args.max_seq_length = 32 if args.model == "bert_tiny" else 128
    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}")
    import jax
    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
    else:
        # Multi-host rendezvous (reference init_distrib_slurm,
        # BERT/bert/main_bert.py:159-203) — no-op for single-process jobs.
        from oktopk_tpu.launch import maybe_initialize
        maybe_initialize()

    from oktopk_tpu.config import OkTopkConfig, TrainConfig
    from oktopk_tpu.data import make_dataset
    from oktopk_tpu.train.trainer import Trainer
    from oktopk_tpu.utils.logging import get_logger

    if args.pipeline_stages > 1:
        return run_pipeline(args)
    if args.seq_shards > 1:
        return run_seq_parallel(args)
    if args.seq_data_shards > 1:
        raise SystemExit("--seq-data-shards composes with sequence "
                         "parallelism — it needs --seq-shards > 1 "
                         "(plain sparse DP is the default path)")
    if args.expert_shards > 1:
        return run_expert_parallel(args)

    num_workers = len(jax.devices())
    cfg = TrainConfig(
        dnn=args.model, dataset="wikipedia", batch_size=args.batch_size,
        lr=args.lr, compressor=args.compressor, density=args.density,
        nsteps_update=args.gradient_accumulation_steps, seed=args.seed,
        warmup_proportion=args.warmup_proportion,
        compute_dtype=args.compute_dtype,
        total_steps=args.num_minibatches, num_workers=num_workers)
    logger = get_logger("oktopk_tpu.bert")
    logger.info("BERT pretrain: %s on %d devices, compressor=%s density=%g",
                args.model, num_workers, args.compressor, args.density)

    algo_cfg = _bert_algo_cfg(args)

    trainer = Trainer(cfg, algo_cfg=algo_cfg)
    preempt = None
    if args.handle_preemption:
        from oktopk_tpu.train.preemption import PreemptionHandler
        preempt = PreemptionHandler()
    start = 0
    if args.resume:
        from oktopk_tpu.train.checkpoint import restore_checkpoint
        trainer.state, start = restore_checkpoint(args.resume, trainer.state)
        logger.info("resumed at step %d", start)
    elif args.handle_preemption:
        from oktopk_tpu.train.preemption import load_interrupted_state
        parked = load_interrupted_state(trainer.state)
        if parked is not None:
            trainer.state, start = parked
            logger.info("resumed interrupted state at step %d", start)

    global_bs = (args.batch_size * num_workers
                 * args.gradient_accumulation_steps)
    data_iter, meta = make_dataset("wikipedia", args.model, global_bs,
                                   path=args.data_dir, seed=args.seed,
                                   seq_len=args.max_seq_length)
    if meta.get("synthetic"):
        logger.warning("Wikipedia shards not found: synthetic MLM/NSP data")

    remaining = max(0, args.num_minibatches - start)
    m = trainer.train(data_iter, remaining,
                      log_every=args.log_every, logger=logger,
                      start_step=start,
                      should_stop=(preempt.should_stop if preempt else None))
    if preempt is not None:
        from oktopk_tpu.train.preemption import epilogue
        rc = epilogue(trainer.state, trainer.last_step, preempt, logger,
                      rank=jax.process_index(),
                      completed=trainer.last_step >= args.num_minibatches)
        if rc:
            return rc
    if m:
        logger.info("done: loss %.4f comm volume/step %.0f elems",
                    float(m["loss"]), float(m["comm_volume"]))
    # rank-0 writes only (reference saves via rank_in_stage==0,
    # BERT/bert/main_bert.py:207-219): shared-filesystem safety.
    if args.ckpt_dir and jax.process_index() == 0:
        from oktopk_tpu.train.checkpoint import save_checkpoint
        save_checkpoint(args.ckpt_dir, trainer.state, args.num_minibatches)
    return 0


def run_pipeline(args):
    """Pipeline-parallel pretraining path: data x pipe mesh, staged encoder
    (reference StageRuntime GPipe-with-flushes mode, BERT/runtime.py:842)."""
    import jax
    import numpy as np

    from oktopk_tpu.models.bert import BertConfig
    from oktopk_tpu.models.bert_staged import StagedBertPretrain
    from oktopk_tpu.optim import bert_adam
    from oktopk_tpu.parallel.bert_pipeline import (
        build_pipeline_train_step, init_pipeline_opt_state,
        make_pipeline_mesh)
    from oktopk_tpu.data import make_dataset
    from oktopk_tpu.utils.logging import get_logger

    logger = get_logger("oktopk_tpu.bert")
    cfg = {"bert_base": BertConfig.base, "bert_large": BertConfig.large,
           "bert_tiny": BertConfig.tiny}[args.model]()
    staged = StagedBertPretrain(cfg, args.pipeline_stages)
    mesh = make_pipeline_mesh(args.pipeline_stages)
    dp = mesh.shape["data"]
    logger.info("pipeline BERT: %s over mesh data=%d x pipe=%d, M=%d",
                args.model, dp, args.pipeline_stages, args.num_microbatches)

    params = staged.init(jax.random.PRNGKey(args.seed), 2,
                         args.max_seq_length)
    params = _maybe_warm_start(
        args, logger, {"params": params, "model_state": {}})["params"]
    stack, shared = staged.split(params)
    opt = bert_adam(lr=args.lr, warmup=args.warmup_proportion,
                    t_total=args.num_minibatches)

    sparse = args.compressor != "dense"
    if sparse:
        # composed sparse DP x pipeline: per-data-rank replica layout
        # (the architecture the reference shipped disabled — PipeDream
        # stages + per-stage-group sparse allreduce, SURVEY.md 2.3)
        import jax.numpy as jnp

        from oktopk_tpu.parallel.bert_pipeline import (
            build_pipeline_sparse_train_step, init_pipeline_sparse_states)
        from oktopk_tpu.parallel.bert_seq import stack_replicas
        if dp < 2:
            raise SystemExit("sparse pipeline composition needs a data "
                             "axis (more devices than --pipeline-stages) "
                             "— or pass --compressor dense")
        acfg = _bert_algo_cfg(args, density=args.density)
        stage_ss, shared_ss = init_pipeline_sparse_states(
            stack, shared, acfg, dp)
        opt_states = (stack_replicas(jax.vmap(opt.init)(stack), dp),
                      stack_replicas(opt.init(shared), dp))
        stack = stack_replicas(stack, dp)
        shared = stack_replicas(shared, dp)
        sstates = (stage_ss, shared_ss)
        step0 = build_pipeline_sparse_train_step(
            staged, mesh, num_microbatches=args.num_microbatches,
            optimizer=opt, algo_cfg=acfg, compressor=args.compressor,
            warmup=False, remat=args.remat)
        logger.info("sparse pipeline: compressor=%s density=%g",
                    args.compressor, args.density)
    else:
        opt_states = init_pipeline_opt_state(opt, stack, shared)
        step0 = build_pipeline_train_step(
            staged, mesh, num_microbatches=args.num_microbatches,
            optimizer=opt, remat=args.remat)

    global_bs = args.batch_size * dp * args.num_microbatches
    data_iter, meta = make_dataset("wikipedia", args.model, global_bs,
                                   path=args.data_dir, seed=args.seed,
                                   seq_len=args.max_seq_length)
    if meta.get("synthetic"):
        logger.warning("Wikipedia shards not found: synthetic MLM/NSP data")

    rng = jax.random.PRNGKey(args.seed + 1)
    import time
    t0 = time.time()
    for i in range(args.num_minibatches):
        rng, sub = jax.random.split(rng)
        if sparse:
            (stack, shared), sstates, opt_states, m = step0(
                (stack, shared), sstates, opt_states,
                next(data_iter), sub)
        else:
            stack, shared, opt_states, m = step0(stack, shared, opt_states,
                                                 next(data_iter), sub)
        if (i + 1) % args.log_every == 0:
            dt = (time.time() - t0) / args.log_every
            logger.info("iter %d loss %.4f %.3fs/it", i + 1,
                        float(m["loss"]), dt)
            t0 = time.time()
    if args.ckpt_dir and jax.process_index() == 0:
        from oktopk_tpu.train.checkpoint import save_checkpoint
        if sparse:   # row 0 of the replicas is the canonical copy
            stack_c = jax.tree.map(lambda x: x[0], stack)
            shared_c = jax.tree.map(lambda x: x[0], shared)
        else:
            stack_c, shared_c = stack, shared
        save_checkpoint(args.ckpt_dir,
                        {"params": staged.merge(stack_c, shared_c),
                         "model_state": {}}, args.num_minibatches)
        logger.info("saved single-module-layout checkpoint to %s",
                    args.ckpt_dir)
    return 0


def _bert_algo_cfg(args, **kw):
    """The BERT sparse-allreduce tuning: dense warmup disabled (reference
    BERT/bert/allreducer.py:355), retuned cadences/scales (:359-361,
    :188-190). One definition for every BERT path."""
    from oktopk_tpu.config import OkTopkConfig
    return OkTopkConfig(
        warmup_steps=0, local_recompute_every=128,
        global_recompute_every=128, repartition_every=64,
        local_adapt_scale=1.025, global_adapt_scale=1.036,
        wire_dtype=args.wire_dtype, **kw)


def _maybe_warm_start(args, logger, template):
    """Params-only warm start for the extension paths: restore the saved
    payload shape into ``template`` and return it. Optimizer / sparse
    state start fresh (these paths checkpoint the canonical single-module
    or moe payload, not the full replica carry); the DP path keeps its
    full-state resume."""
    if not args.resume:
        return template
    import jax
    import numpy as np

    from oktopk_tpu.train.checkpoint import restore_checkpoint
    restored, rstep = restore_checkpoint(args.resume, template)
    # restore_checkpoint keeps template leaves for missing payload keys,
    # so a layout mismatch (e.g. a DP {"params": ...} checkpoint fed to
    # the moe path) would silently train from random init; and flax
    # accepts wrong-shaped leaves silently. Validate both.
    changed = False
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(template),
            jax.tree_util.tree_leaves_with_path(restored)):
        if np.shape(a) != np.shape(b):
            raise SystemExit(
                f"--resume leaf {jax.tree_util.keystr(pa)} has shape "
                f"{np.shape(b)} but this model expects {np.shape(a)} "
                f"(wrong --model for the checkpoint?)")
        if not changed and not np.array_equal(np.asarray(a),
                                              np.asarray(b)):
            changed = True
    if not changed:
        raise SystemExit(
            f"--resume {args.resume} restored nothing — its payload "
            f"layout does not match this path's checkpoint format")
    logger.info("warm-started from %s (saved at step %d; optimizer and "
                "sparse state start fresh)", args.resume, rstep)
    return restored


def _pretrain_loop(args, logger, step_fn, params, opt_state, global_bs,
                   checkpoint_payload):
    """Shared dataset/loop/log/checkpoint tail of the whole-model parallel
    paths (seq, expert): ``step_fn(params, opt_state, batch) -> (params,
    opt_state, loss)``; ``checkpoint_payload(params) -> dict`` shapes what
    rank 0 saves."""
    import time

    import jax

    from oktopk_tpu.data import make_dataset

    data_iter, meta = make_dataset("wikipedia", args.model, global_bs,
                                   path=args.data_dir, seed=args.seed,
                                   seq_len=args.max_seq_length)
    if meta.get("synthetic"):
        logger.warning("Wikipedia shards not found: synthetic MLM/NSP data")

    t0 = time.time()
    for i in range(args.num_minibatches):
        params, opt_state, loss = step_fn(params, opt_state,
                                          next(data_iter))
        if (i + 1) % args.log_every == 0:
            dt = (time.time() - t0) / args.log_every
            logger.info("iter %d loss %.4f %.3fs/it", i + 1, float(loss),
                        dt)
            t0 = time.time()
    if args.ckpt_dir and jax.process_index() == 0:
        from oktopk_tpu.train.checkpoint import save_checkpoint
        save_checkpoint(args.ckpt_dir, checkpoint_payload(params),
                        args.num_minibatches)
    return params


def run_seq_parallel(args):
    """Sequence-parallel pretraining: token axis sharded over a seq mesh
    with ring attention (long-context path; see parallel/bert_seq.py)."""
    import jax

    from oktopk_tpu.data import make_dataset
    from oktopk_tpu.models.bert import BertConfig, BertForPreTraining
    from oktopk_tpu.optim import bert_adam
    from oktopk_tpu.parallel.bert_seq import (build_seq_train_step,
                                              make_seq_mesh)
    from oktopk_tpu.utils.logging import get_logger
    import jax.numpy as jnp

    logger = get_logger("oktopk_tpu.bert")
    dp = args.seq_data_shards
    if args.max_seq_length % args.seq_shards:
        raise SystemExit("--max-seq-length must divide by --seq-shards")
    if args.compressor != "dense" and dp <= 1:
        raise SystemExit(
            "sparse collectives over a pure seq mesh have no data axis to "
            "reduce over — add --seq-data-shards N for the composed "
            "data x seq mesh, or pass --compressor dense")
    if args.gradient_accumulation_steps != 1 and not (
            dp > 1 and args.compressor != "dense"):
        raise SystemExit("--gradient-accumulation-steps on the seq path "
                         "needs the composed sparse form "
                         "(--seq-data-shards N, sparse --compressor)")
    import dataclasses
    dtype = jnp.dtype(args.compute_dtype)
    cfg = {"bert_base": BertConfig.base, "bert_large": BertConfig.large,
           "bert_tiny": BertConfig.tiny}[args.model](dtype=dtype)
    if cfg.max_position < args.max_seq_length:
        # long-context runs need position rows for every global position —
        # the embedding gather clamps silently under jit otherwise
        cfg = dataclasses.replace(cfg, max_position=args.max_seq_length)
    mesh = make_seq_mesh(args.seq_shards, data_size=dp)
    logger.info("seq-parallel BERT: %s, T=%d over %d shards "
                "(T/P=%d per chip)%s", args.model, args.max_seq_length,
                args.seq_shards, args.max_seq_length // args.seq_shards,
                f", data axis dp={dp} compressor={args.compressor}"
                if dp > 1 else "")

    ex = jnp.zeros((2, args.max_seq_length), jnp.int32)
    rng = jax.random.PRNGKey(args.seed)
    params = BertForPreTraining(cfg).init(
        {"params": rng, "dropout": rng}, ex, ex, jnp.ones_like(ex),
        train=False)["params"]
    params = _maybe_warm_start(
        args, logger, {"params": params, "model_state": {}})["params"]
    opt = bert_adam(lr=args.lr, warmup=args.warmup_proportion,
                    t_total=args.num_minibatches)

    if dp > 1 and args.compressor != "dense":
        # composed sparse DP x seq: per-data-rank replica layout
        from oktopk_tpu.collectives.state import init_state
        from oktopk_tpu.config import OkTopkConfig
        from oktopk_tpu.parallel.bert_seq import (
            build_seq_sparse_train_step, stack_replicas)

        n = sum(x.size for x in jax.tree.leaves(params))
        acfg = _bert_algo_cfg(args, n=n, num_workers=dp,
                              density=args.density)
        sstep = build_seq_sparse_train_step(
            cfg, mesh, opt, acfg, compressor=args.compressor,
            warmup=False,
            accum_steps=args.gradient_accumulation_steps)
        carry = (stack_replicas(params, dp),
                 stack_replicas(init_state(acfg), dp))
        opt_state = stack_replicas(opt.init(params), dp)

        def step(ps, opt_state, batch):
            p, ss = ps
            p, ss, opt_state, loss = sstep(p, ss, opt_state, batch)
            return (p, ss), opt_state, loss

        _pretrain_loop(
            args, logger, step, carry, opt_state,
            # --batch-size is per data rank per microstep
            args.batch_size * dp * args.gradient_accumulation_steps,
            # row 0 of the replicas IS the single-module layout
            lambda ps: {"params": jax.tree.map(lambda x: x[0], ps[0]),
                        "model_state": {}})
        return 0

    opt_state = opt.init(params)
    step = build_seq_train_step(cfg, mesh, opt)
    _pretrain_loop(args, logger, step, params, opt_state,
                   args.batch_size * dp,
                   lambda p: {"params": p, "model_state": {}})
    return 0


def run_expert_parallel(args):
    """Expert-parallel MoE pretraining: Switch-style top-1 MoE FFNs with
    GShard all_to_all dispatch over an expert mesh; batch sharded on the
    same axis (see parallel/bert_moe.py)."""
    import jax
    import jax.numpy as jnp

    from oktopk_tpu.models.bert import BertConfig, BertForPreTraining
    from oktopk_tpu.optim import bert_adam
    from oktopk_tpu.parallel.bert_moe import (MoEConfig,
                                              build_moe_train_step,
                                              experts_from_dense,
                                              make_moe_mesh)
    from oktopk_tpu.utils.logging import get_logger

    logger = get_logger("oktopk_tpu.bert")
    E = args.num_experts or args.expert_shards
    if E % args.expert_shards:
        raise SystemExit("--num-experts must divide by --expert-shards")
    dpx = args.expert_data_shards
    if args.compressor != "dense" and dpx <= 1:
        raise SystemExit(
            "sparse collectives over a pure expert mesh have no data axis "
            "to reduce over — add --expert-data-shards N for the composed "
            "data x expert mesh, or pass --compressor dense")
    if args.gradient_accumulation_steps != 1:
        raise SystemExit("--gradient-accumulation-steps is not wired into "
                         "the expert-parallel path yet")
    dtype = jnp.dtype(args.compute_dtype)
    cfg = {"bert_base": BertConfig.base, "bert_large": BertConfig.large,
           "bert_tiny": BertConfig.tiny}[args.model](dtype=dtype)
    mcfg = MoEConfig(num_experts=E,
                     capacity_factor=args.capacity_factor)
    mesh = make_moe_mesh(args.expert_shards, data_size=dpx)
    logger.info("expert-parallel MoE BERT: %s, %d experts over %d shards "
                "(cap factor %.2f)%s", args.model, E, args.expert_shards,
                args.capacity_factor,
                f", data axis dp={dpx} compressor={args.compressor}"
                if dpx > 1 else "")

    ex = jnp.zeros((2, args.max_seq_length), jnp.int32)
    rng = jax.random.PRNGKey(args.seed)
    dense_params = BertForPreTraining(cfg).init(
        {"params": rng, "dropout": rng}, ex, ex, jnp.ones_like(ex),
        train=False)["params"]
    # gate_scale > 0: a zero router ties every token to expert 0 and the
    # capacity bound then drops most of the batch (bert_moe.py docstring)
    params = experts_from_dense(dense_params, E, gate_scale=0.02,
                                seed=args.seed)
    restored = _maybe_warm_start(
        args, logger, {"moe_params": {"layers": params[0],
                                      "shared": params[1]},
                       "model_state": {}})
    params = (restored["moe_params"]["layers"],
              restored["moe_params"]["shared"])
    opt = bert_adam(lr=args.lr, warmup=args.warmup_proportion,
                    t_total=args.num_minibatches)
    # --batch-size is per-worker (as in the DP/pipeline paths); the MoE
    # batch is sharded over the (data x) expert axes, so request global
    global_bs = args.batch_size * args.expert_shards * dpx

    if dpx > 1 and args.compressor != "dense":
        # composed sparse DP x expert: per-data-rank replica layout
        from oktopk_tpu.parallel.bert_moe import (
            build_moe_sparse_train_step, init_moe_sparse_opt,
            init_moe_sparse_states)
        from oktopk_tpu.parallel.bert_seq import stack_replicas
        moe, shared = params
        acfg = _bert_algo_cfg(args, density=args.density)
        sstep = build_moe_sparse_train_step(
            cfg, mcfg, mesh, opt, acfg, compressor=args.compressor,
            warmup=False)
        carry = ((stack_replicas(moe, dpx), stack_replicas(shared, dpx)),
                 init_moe_sparse_states(moe, shared, acfg, dpx,
                                        args.expert_shards))
        opt_state = init_moe_sparse_opt(opt, moe, shared, dpx)

        def step_fn(ps, opt_st, batch):
            pr, ss = ps
            pr, ss, opt_st, m = sstep(pr, ss, opt_st, batch)
            return (pr, ss), opt_st, m["loss"]

        _pretrain_loop(
            args, logger, step_fn, carry, opt_state, global_bs,
            lambda ps: {"moe_params": {
                "layers": jax.tree.map(lambda x: x[0], ps[0][0]),
                "shared": jax.tree.map(lambda x: x[0], ps[0][1])},
                "model_state": {}})
        return 0

    opt_state = opt.init(params)
    step = build_moe_train_step(cfg, mcfg, mesh, opt)
    # MoE params cannot collapse to the single-module layout once the
    # experts diverge — save them under a distinct key so nothing mistakes
    # the tuple for BertForPreTraining params
    _pretrain_loop(args, logger, step, params, opt_state, global_bs,
                   lambda p: {"moe_params": {"layers": p[0],
                                             "shared": p[1]},
                              "model_state": {}})
    return 0


if __name__ == "__main__":
    sys.exit(main())
