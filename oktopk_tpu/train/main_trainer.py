"""CLI driver for the CNN/LSTM workloads (reference VGG/main_trainer.py and
LSTM/main_trainer.py: robust_ssgd + argparse at :143-180).

The reference launches one MPI rank per GPU node via srun; here one process
drives the whole mesh. ``--fake-devices N`` reproduces the multi-worker
topology on CPU for dry runs (the reference's two-local-process trick,
SURVEY.md §4).

Example:
    python -m oktopk_tpu.train.main_trainer --dnn vgg16 --dataset cifar10 \\
        --batch-size 16 --lr 0.1 --compressor oktopk --density 0.02 \\
        --max-iters 200
"""

from __future__ import annotations

import argparse
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    # reference flag surface (VGG/main_trainer.py:144-159)
    p.add_argument("--dnn", default="vgg16")
    p.add_argument("--dataset", default="cifar10")
    p.add_argument("--data-dir", default="./data")
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=5e-4)
    p.add_argument("--nesterov", action="store_true")
    p.add_argument("--max-epochs", type=int, default=161)
    p.add_argument("--max-iters", type=int, default=0,
                   help="if set, run exactly this many iterations")
    p.add_argument("--nsteps-update", type=int, default=1)
    p.add_argument("--compute-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="model computation dtype (bf16 = 2x MXU; params/"
                        "grads/collective stay f32 - the apex-amp role)")
    p.add_argument("--wire-dtype", default="bfloat16",
                   choices=["bfloat16", "float32"],
                   help="sparse message VALUE dtype on the wire (the "
                        "reference's fp16 MPI datatype role; float32 = "
                        "reference-exact uncompressed messages)")
    p.add_argument("--num-buckets", type=int, default=1,
                   help="reverse-layer-order gradient buckets, one sparse "
                        "collective each (reference <=640MiB bucketing, "
                        "VGG/allreducer.py:27); 1 = whole-model flat")
    p.add_argument("--compressor", default="oktopk")
    p.add_argument("--autotune", action="store_true",
                   help="pick each bucket's collective + density at "
                        "runtime (autotune/: calibrated cost-model prior "
                        "-> timed trial posterior); --compressor becomes "
                        "the pre-plan fallback")
    p.add_argument("--autotune-candidates", default="dense,oktopk",
                   help="comma-separated registry names to trial")
    p.add_argument("--autotune-trial-steps", type=int, default=3)
    p.add_argument("--autotune-retune-every", type=int, default=0,
                   help="steps between re-tunes (0 = tune once)")
    p.add_argument("--autotune-journal", default=None,
                   help="JSONL decision-journal path (see docs/PERF.md)")
    p.add_argument("--resilience", action="store_true",
                   help="numeric-health guard + supervisor (resilience/): "
                        "psum-agreed skip of anomalous steps with "
                        "residual rollback, per-bucket dense fallback "
                        "after repeated strikes, checkpoint restore on "
                        "divergence")
    p.add_argument("--resilience-strikes", type=int, default=3,
                   help="guard trips on a bucket before it falls back "
                        "to the dense collective")
    p.add_argument("--resilience-abs-limit", type=float, default=1e18,
                   help="reduced-gradient magnitude treated as anomalous "
                        "even while finite (wire bit-flips land ~1e38)")
    p.add_argument("--resilience-journal", default=None,
                   help="JSONL health-journal path (docs/RESILIENCE.md)")
    p.add_argument("--resilience-feedback", action="store_true",
                   help="fault->autotune feedback: a sustained stream of "
                        "regression/guard_trip events forces an autotune "
                        "re-calibrate + re-tune against the degraded "
                        "fabric (resilience/feedback.py; needs --obs)")
    p.add_argument("--resilience-feedback-window", type=int, default=32,
                   help="steps a feedback signal stays live in the vote")
    p.add_argument("--resilience-feedback-signals", type=int, default=3,
                   help="signals within the window needed to force a "
                        "re-tune")
    p.add_argument("--resilience-feedback-cooldown", type=int, default=64,
                   help="steps between forced re-tunes")
    p.add_argument("--resilience-density-backoff", action="store_true",
                   help="guard-aware density backoff: repeated "
                        "near-abs-limit/guard-skip steps back the "
                        "effective density off (bounded, hysteretic, "
                        "journalled; resilience/density.py)")
    p.add_argument("--resilience-near-ratio", type=float, default=0.1,
                   help="fraction of abs-limit counted as guard pressure")
    p.add_argument("--resilience-backoff-steps", type=int, default=3,
                   help="pressured steps before one backoff level")
    p.add_argument("--resilience-backoff-factor", type=float, default=0.5,
                   help="density multiplier per backoff level")
    p.add_argument("--resilience-backoff-max-level", type=int, default=3,
                   help="deepest backoff level")
    p.add_argument("--resilience-clean-streak", type=int, default=8,
                   help="clean steps before re-advancing one level")
    p.add_argument("--obs", action="store_true",
                   help="unified run journal (obs/): per-step metrics, "
                        "autotune decisions, guard trips, checkpoints, "
                        "trace captures and volume reports in ONE JSONL "
                        "file (docs/OBSERVABILITY.md)")
    p.add_argument("--obs-journal", default=None,
                   help="run-journal path (default: "
                        "<logdir>/<slug>/run_journal.jsonl)")
    p.add_argument("--obs-trace-on-anomaly", action="store_true",
                   help="arm a bounded jax.profiler window on guard_trip/"
                        "fallback events (obs/tracing.py)")
    p.add_argument("--obs-trace-steps", type=int, default=3,
                   help="steps per anomaly-triggered trace window")
    p.add_argument("--obs-regress-key", default=None,
                   help="BENCH_r*.json parsed key (e.g. oktopk_ms) to "
                        "baseline step-time regression checks against")
    p.add_argument("--obs-quality", action="store_true",
                   help="in-jit signal-fidelity taps (obs/quality.py): "
                        "per-bucket compression error, residual growth, "
                        "effective density, threshold drift and index "
                        "churn accumulated in device-side rings and "
                        "journalled every --obs-quality-every steps")
    p.add_argument("--obs-quality-every", type=int, default=32,
                   help="quality ring capacity / host-flush cadence "
                        "(steps); between flushes the taps add zero "
                        "host syncs")
    p.add_argument("--density", type=float, default=0.02)
    p.add_argument("--sigma-scale", type=float, default=2.5)
    p.add_argument("--grad-clip", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--warmup-steps", type=int, default=None,
                   help="dense warmup iterations (default: reference's 512)")
    p.add_argument("--fake-devices", type=int, default=0,
                   help="virtual CPU devices for dry runs")
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--logdir", default="./logs")
    p.add_argument("--trace-at", type=int, default=0,
                   help="capture a jax.profiler trace starting at this "
                        "step (0 = off); view with xprof/tensorboard")
    p.add_argument("--trace-steps", type=int, default=3)
    p.add_argument("--phase-timers", action="store_true",
                   help="log data-wait vs device-step phase table every "
                        "--log-every steps (reference _print_profiling, "
                        "VGG/allreducer.py:379-439)")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=0,
                   help="checkpoint every N iterations (0 = off)")
    p.add_argument("--ckpt-async", action="store_true",
                   help="write checkpoints on a background thread "
                        "(durable.AsyncCheckpointer): the step loop only "
                        "pays jax.device_get; serialize+fsync+verify run "
                        "off-thread with bounded queue depth and a drain "
                        "barrier on exit")
    p.add_argument("--ckpt-keep", type=int, default=0,
                   help="retention: keep the newest N checkpoints plus "
                        "the newest qualified one (0 = keep everything)")
    p.add_argument("--ckpt-force", action="store_true",
                   help="restore a checkpoint even when most of its "
                        "leaves mismatch the model (normally that raises "
                        "— it almost always means the wrong --model for "
                        "this checkpoint)")
    p.add_argument("--resume", default=None,
                   help="checkpoint directory to resume from")
    p.add_argument("--handle-preemption", action="store_true",
                   help="install SIGTERM/SIGUSR1/SIGUSR2 handlers: on "
                        "preemption, checkpoint to ~/.interrupted_states "
                        "and (SIGUSR1) scontrol requeue — reference "
                        "BERT/bert/main_bert.py:73-203")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}")
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    if args.fake_devices:
        jax.config.update("jax_platforms", "cpu")
    else:
        # Multi-host rendezvous (reference init_distrib_slurm,
        # BERT/bert/main_bert.py:159-203) — no-op for single-process jobs.
        from oktopk_tpu.launch import maybe_initialize
        penv = maybe_initialize()
        if penv.num_processes > 1:
            print(f"[launch] process {penv.process_id}/{penv.num_processes}"
                  f" via {penv.source}, coordinator={penv.coordinator}")

    from oktopk_tpu.config import OkTopkConfig, TrainConfig
    from oktopk_tpu.data import make_dataset
    from oktopk_tpu.train.trainer import Trainer
    from oktopk_tpu.utils.logging import get_logger

    cfg = TrainConfig(
        dnn=args.dnn, dataset=args.dataset, batch_size=args.batch_size,
        lr=args.lr, momentum=args.momentum, weight_decay=args.weight_decay,
        nesterov=args.nesterov, max_epochs=args.max_epochs,
        nsteps_update=args.nsteps_update, compressor=args.compressor,
        num_buckets=args.num_buckets,
        compute_dtype=args.compute_dtype,
        density=args.density, sigma_scale=args.sigma_scale,
        grad_clip=args.grad_clip, seed=args.seed,
        num_workers=len(jax.devices()),
        autotune=args.autotune,
        autotune_candidates=tuple(
            s for s in args.autotune_candidates.split(",") if s),
        autotune_trial_steps=args.autotune_trial_steps,
        autotune_retune_every=args.autotune_retune_every,
        autotune_journal=args.autotune_journal,
        resilience=args.resilience,
        resilience_strikes=args.resilience_strikes,
        resilience_abs_limit=args.resilience_abs_limit,
        resilience_journal=args.resilience_journal,
        resilience_feedback=args.resilience_feedback,
        resilience_feedback_window=args.resilience_feedback_window,
        resilience_feedback_signals=args.resilience_feedback_signals,
        resilience_feedback_cooldown=args.resilience_feedback_cooldown,
        resilience_density_backoff=args.resilience_density_backoff,
        resilience_near_ratio=args.resilience_near_ratio,
        resilience_backoff_steps=args.resilience_backoff_steps,
        resilience_backoff_factor=args.resilience_backoff_factor,
        resilience_backoff_max_level=args.resilience_backoff_max_level,
        resilience_clean_streak=args.resilience_clean_streak,
        obs=args.obs,
        obs_trace_on_anomaly=args.obs_trace_on_anomaly,
        obs_trace_steps=args.obs_trace_steps,
        obs_regress_key=args.obs_regress_key,
        obs_quality=args.obs_quality,
        obs_quality_every=args.obs_quality_every)
    slug = cfg.experiment_slug()
    # Observability and checkpoints are rank-0 work (the reference gates its
    # writer/checkpointer the same way, VGG/dl_trainer.py:614-616) — on a
    # shared filesystem every process writing the same paths corrupts them.
    is_rank0 = jax.process_index() == 0
    if args.obs and is_rank0:
        # non-rank-0 processes keep the bus with an in-memory journal
        # (tracer arming still works) but never write the shared file
        import dataclasses as _dc
        cfg = _dc.replace(
            cfg, obs_journal=(args.obs_journal or os.path.join(
                args.logdir, slug, "run_journal.jsonl")))
    logger = get_logger(
        "oktopk_tpu",
        os.path.join(args.logdir, slug, f"rank{jax.process_index()}.log"))
    logger.info("experiment %s on %d devices", slug, len(jax.devices()))

    algo_cfg = OkTopkConfig(sigma_scale=args.sigma_scale,
                            wire_dtype=args.wire_dtype)
    if args.warmup_steps is not None:
        algo_cfg = algo_cfg.replace(warmup_steps=args.warmup_steps)

    trainer = Trainer(cfg, algo_cfg=algo_cfg)

    preempt = None
    if args.handle_preemption:
        from oktopk_tpu.train.preemption import (PreemptionHandler,
                                                 load_interrupted_state)
        preempt = PreemptionHandler()

    start_iter = 0
    if args.resume:
        from oktopk_tpu.train.checkpoint import restore_checkpoint
        # verifying resume: digest-checked against the sidecar manifest,
        # walking newest -> oldest past corrupt files, journalled on the
        # run's bus (ckpt_verify_failed / ckpt_restore)
        trainer.state, start_iter = restore_checkpoint(
            args.resume, trainer.state, bus=trainer.bus,
            force=args.ckpt_force)
        # re-arm the escalation ladder: strike counters + any active
        # per-bucket dense fallbacks resume with the train state
        trainer.restore_supervisor(args.resume)
        logger.info("resumed from %s at iter %d", args.resume, start_iter)
    elif args.handle_preemption:
        parked = load_interrupted_state(trainer.state)
        if parked is not None:
            trainer.state, start_iter = parked
            from oktopk_tpu.train.preemption import interrupted_state_path
            trainer.restore_supervisor(interrupted_state_path() + ".d")
            logger.info("resumed interrupted state at iter %d", start_iter)

    # global batch = per-worker batch * workers * accumulation
    global_bs = (args.batch_size * trainer.algo_cfg.num_workers
                 * args.nsteps_update)
    data_iter, meta = make_dataset(args.dataset, args.dnn, global_bs,
                                   path=args.data_dir, seed=args.seed)
    if meta.get("synthetic"):
        logger.warning("dataset %s not found on disk: using synthetic data",
                       args.dataset)

    iters_per_epoch = max(1, meta["num_examples"] // global_bs)
    total = args.max_iters or args.max_epochs * iters_per_epoch
    logger.info("training %d iterations (%d/epoch)", total, iters_per_epoch)

    from oktopk_tpu.utils.profiling import (MetricWriter, PhaseTimers,
                                            TraceWindow, device_memory_stats)
    rundir = os.path.join(args.logdir, slug)
    checkpointer = None
    if is_rank0 and args.ckpt_dir and args.ckpt_every and args.ckpt_async:
        from oktopk_tpu.train.durable import AsyncCheckpointer
        journal = (trainer.supervisor.journal
                   if trainer.supervisor is not None else None)
        checkpointer = AsyncCheckpointer(
            args.ckpt_dir, keep_last=args.ckpt_keep,
            journal=journal, bus=trainer.bus,
            on_failure=trainer.note_ckpt_failure)
    writer = MetricWriter(rundir) if is_rank0 else None
    timers = PhaseTimers(every=args.log_every) if args.phase_timers else None
    trace = (TraceWindow(os.path.join(rundir, "trace"), args.trace_at,
                         args.trace_steps) if args.trace_at and is_rank0
             else None)

    done = start_iter
    try:
        while done < total:
            if preempt is not None and preempt.should_stop():
                break
            chunk = min(total - done, iters_per_epoch)
            m = trainer.train(data_iter, chunk, log_every=args.log_every,
                              logger=logger, metric_writer=writer,
                              timers=timers, trace=trace, start_step=done,
                              should_stop=(preempt.should_stop
                                           if preempt else None))
            done = trainer.last_step if preempt is not None else done + chunk
            if not m:  # stopped before the first step of this chunk
                break
            from oktopk_tpu import settings
            if settings.PROFILING_GRAD and is_rank0:
                # gradient-stream snapshot (reference dumps raw .npy grads at
                # fixed iterations, VGG/allreducer.py:608-623): the residual
                # IS the un-transmitted gradient mass plus thresholds/counts.
                import numpy as _np
                ss = jax.device_get(trainer.state.sparse_state)
                dump_dir = os.path.join(rundir, "grad_dumps")
                os.makedirs(dump_dir, exist_ok=True)
                _np.savez_compressed(
                    os.path.join(dump_dir, f"iter_{done}.npz"),
                    residual=_np.asarray(ss.residual),
                    local_threshold=_np.asarray(ss.local_threshold),
                    global_threshold=_np.asarray(ss.global_threshold))
            mem = device_memory_stats()
            logger.info(
                "epoch done @ iter %d: loss %.4f vol/step %.0f hbm %.0fMiB",
                done, float(m["loss"]), float(m["comm_volume"]),
                mem.get("bytes_in_use", 0) / 2**20)
            if (is_rank0 and args.ckpt_dir and args.ckpt_every
                    and done % args.ckpt_every == 0):
                if checkpointer is not None:
                    path = checkpointer.save(
                        trainer.state, done,
                        extra=trainer.supervisor_extra(),
                        qualified=trainer.checkpoint_qualified)
                else:
                    from oktopk_tpu.train.checkpoint import save_checkpoint
                    path = save_checkpoint(
                        args.ckpt_dir, trainer.state, done,
                        extra=trainer.supervisor_extra(),
                        qualified=trainer.checkpoint_qualified)
                    if args.ckpt_keep:
                        from oktopk_tpu.train.durable import apply_retention
                        apply_retention(args.ckpt_dir,
                                        keep_last=args.ckpt_keep)
                trainer.note_checkpoint(path, done)
    finally:
        if writer is not None:
            writer.close()
        if trace is not None:
            trace.close()
        if checkpointer is not None and preempt is None:
            # with a preemption handler the epilogue drains instead (an
            # async save in flight must publish whole before exit)
            checkpointer.close(timeout=300.0)

    if preempt is not None:
        # park-state/requeue (or clear on success) — reference
        # main_bert.py:99-153, actually wired here.
        from oktopk_tpu.train.preemption import epilogue
        return epilogue(trainer.state, done, preempt, logger,
                        rank=jax.process_index(), completed=done >= total,
                        extra=trainer.supervisor_extra(),
                        checkpointer=checkpointer)
    return 0


if __name__ == "__main__":
    sys.exit(main())
