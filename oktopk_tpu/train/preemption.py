"""SLURM preemption handling: graceful stop -> save -> requeue.

Reference shape (BERT/bert/main_bert.py:73-203): SIGINT/SIGTERM/SIGUSR2 set
a clean-exit Event, SIGUSR1 sets a requeue flag; ``save_interrupted_state``/
``load_interrupted_state`` park the run state under
``~/.interrupted_states/$SLURM_JOBID.pth``; ``requeue_job`` runs ``scontrol
requeue`` on rank 0 after a barrier. The reference declares these but never
wires them into its training loop (SURVEY.md §5.3) — here they are wired:
the CLI drivers poll :meth:`PreemptionHandler.should_stop` between steps and
run the save/requeue epilogue on the way out.

On TPU pods the same signals arrive from the orchestrator (SLURM, GKE
maintenance notices piped to a signal, etc.); state save uses the framework
checkpoint (which, unlike the reference, includes compressor residuals and
thresholds — SURVEY.md §5.4's gap).
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
from typing import Iterable, Optional

DEFAULT_STATE_DIR = os.environ.get(
    "OKTOPK_STATE_DIR", os.path.expanduser("~/.interrupted_states"))


class PreemptionHandler:
    """Signal-driven stop/requeue flags.

    ``exit_signals`` request a clean stop (checkpoint + exit);
    ``requeue_signals`` additionally request ``scontrol requeue`` (SLURM's
    pre-preemption warning, reference main_bert.py:84-88).
    """

    def __init__(self,
                 exit_signals: Iterable[int] = (signal.SIGINT,
                                                signal.SIGTERM,
                                                signal.SIGUSR2),
                 requeue_signals: Iterable[int] = (signal.SIGUSR1,)):
        self._stop = threading.Event()
        self._requeue = threading.Event()
        self._prev = {}
        for s in exit_signals:
            self._prev[s] = signal.signal(s, self._on_exit_signal)
        for s in requeue_signals:
            self._prev[s] = signal.signal(s, self._on_requeue_signal)

    # handlers run on the main thread; Event.set is async-signal-safe enough
    def _on_exit_signal(self, signum, frame):
        self._stop.set()

    def _on_requeue_signal(self, signum, frame):
        self._requeue.set()
        self._stop.set()

    def should_stop(self) -> bool:
        return self._stop.is_set()

    @property
    def requeue_requested(self) -> bool:
        return self._requeue.is_set()

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()


def interrupted_state_path(state_dir: str = DEFAULT_STATE_DIR,
                           job_id: Optional[str] = None) -> str:
    """``<state_dir>/<job id>.msgpack`` (reference
    ``~/.interrupted_states/$SLURM_JOBID.pth``, main_bert.py:99-135).

    Job id precedence: explicit arg > SLURM_JOBID > OKTOPK_RUN_ID >
    ``"local"``. The last is a *stable* fallback (never the pid): a
    restarted non-SLURM process must find the state its predecessor parked."""
    jid = (job_id or os.environ.get("SLURM_JOBID")
           or os.environ.get("OKTOPK_RUN_ID") or "local")
    return os.path.join(state_dir, f"{jid}.msgpack")


def save_interrupted_state(state, step: int,
                           state_dir: str = DEFAULT_STATE_DIR,
                           job_id: Optional[str] = None,
                           extra: Optional[dict] = None) -> str:
    """Park the full train state (params + optimizer + sparse residuals and
    thresholds) for a requeued restart. ``extra`` rides along like in
    ``checkpoint.save_checkpoint`` (e.g. supervisor escalation state)."""
    from oktopk_tpu.train.checkpoint import save_checkpoint

    path = interrupted_state_path(state_dir, job_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # save_checkpoint writes <dir>/<prefix>-<step>.msgpack atomically; park
    # under a jobid-keyed subdir so the latest one is unambiguous.
    d, base = os.path.split(path)
    sub = os.path.join(d, base + ".d")
    return save_checkpoint(sub, state, step, extra=extra)


def load_interrupted_state(state_template,
                           state_dir: str = DEFAULT_STATE_DIR,
                           job_id: Optional[str] = None):
    """(state, step) from a parked run, or None if there is nothing parked."""
    from oktopk_tpu.train.checkpoint import restore_checkpoint

    sub = interrupted_state_path(state_dir, job_id) + ".d"
    if not os.path.isdir(sub):
        return None
    try:
        return restore_checkpoint(sub, state_template)
    except FileNotFoundError:
        return None


def clear_interrupted_state(state_dir: str = DEFAULT_STATE_DIR,
                            job_id: Optional[str] = None) -> None:
    import shutil

    sub = interrupted_state_path(state_dir, job_id) + ".d"
    shutil.rmtree(sub, ignore_errors=True)


def epilogue(state, last_step: int, preempt: "PreemptionHandler", logger,
             rank: int = 0, completed: bool = False,
             state_dir: str = DEFAULT_STATE_DIR,
             extra: Optional[dict] = None, checkpointer=None) -> int:
    """Shared driver exit path. If ``preempt`` fired before the run finished:
    park state (rank 0), requeue when requested, and return exit code 3.
    Otherwise clear any parked state for this job id (a completed run must
    not be resumable into a stale snapshot) and return 0.

    ``checkpointer`` is the run's ``durable.AsyncCheckpointer`` (or
    None): it is drained FIRST, whatever the exit reason — an async save
    in flight when the preemption signal lands must publish whole, never
    be left as a torn file for the requeued run to trip over."""
    if checkpointer is not None:
        if not checkpointer.drain(timeout=300.0):
            logger.warning("async checkpointer failed to drain before "
                           "exit; a queued save may be lost")
    if preempt is not None and preempt.should_stop() and not completed:
        if rank == 0:
            path = save_interrupted_state(state, last_step,
                                          state_dir=state_dir,
                                          extra=extra)
            logger.info("preempted @ step %d: state parked at %s",
                        last_step, path)
        if preempt.requeue_requested and requeue_job(rank=rank):
            logger.info("requeue issued")
        return 3
    if preempt is not None and rank == 0:
        clear_interrupted_state(state_dir=state_dir)
    return 0


def requeue_job(rank: int = 0, job_id: Optional[str] = None,
                runner=subprocess.run) -> bool:
    """``scontrol requeue $SLURM_JOBID`` from rank 0 (reference
    main_bert.py:138-153). Returns True if the requeue was issued."""
    jid = job_id or os.environ.get("SLURM_JOBID")
    if rank != 0 or not jid:
        return False
    try:
        runner(["scontrol", "requeue", jid], check=True, timeout=60)
        return True
    except Exception:
        return False
