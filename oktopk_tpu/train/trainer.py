"""Trainer: model + data + distributed optimizer wiring.

Reference analogue: ``DLTrainer`` (VGG/dl_trainer.py:105-796) builds the net,
data loaders and base optimizer; ``robust_ssgd`` (VGG/main_trainer.py:26)
wraps it with the distributed optimizer and runs the epoch loop; BERT's
``main`` (BERT/bert/main_bert.py:641) does the same with BertAdam. Here one
Trainer covers all three drivers: the workload decides the loss function and
optimizer family, and the distributed step comes from
``optim.build_sparse_grad_step``.

The initial-model broadcast (reference ``comm.bcast(net.state_dict())``,
VGG/main_trainer.py:52-54) is unnecessary: params are initialised once on
host and replicated by sharding spec.
"""

from __future__ import annotations

import dataclasses
import time
import warnings
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from oktopk_tpu.config import OkTopkConfig, TrainConfig
from oktopk_tpu.models import create_model
from oktopk_tpu.optim import bert_adam, sgd
from oktopk_tpu.optim.distributed import (
    DistTrainState,
    build_sparse_grad_step,
    flat_size,
    init_dist_state,
)
from oktopk_tpu.train import losses
from oktopk_tpu.comm.mesh import get_mesh

CNN_DNNS = {"vgg16", "vgg19", "resnet20", "resnet56", "resnet110",
            "resnet50", "alexnet", "mnistnet"}


def _ctc_frame_len(spect_lengths):
    """Input-spectrogram-frame lengths (what data/audio.py and
    data/synthetic.py emit) -> output-logit-frame units for ctc_loss and
    the greedy decoder: the conv frontend downsamples time by
    CONV_TIME_STRIDE (the reference likewise divides loader lengths by its
    frontend stride before warpctc, VGG/dl_trainer.py:743)."""
    from oktopk_tpu.models.deepspeech import CONV_TIME_STRIDE
    s = CONV_TIME_STRIDE
    return (spect_lengths + s - 1) // s


class Trainer:
    """End-to-end distributed trainer over a data-parallel mesh."""

    def __init__(self, cfg: TrainConfig, mesh: Optional[Mesh] = None,
                 algo_cfg: Optional[OkTopkConfig] = None,
                 model_kwargs: Optional[Dict[str, Any]] = None,
                 axis_name: str = "data", warmup: bool = True,
                 profile_norm: Optional[bool] = None,
                 fault_plan=None):
        from oktopk_tpu import settings
        if profile_norm is None:
            profile_norm = settings.PROFILING_NORM
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else get_mesh()
        self.axis_name = axis_name
        num_workers = int(np.prod(
            [self.mesh.shape[a] for a in (axis_name,)]))
        if cfg.num_workers != num_workers:
            cfg = dataclasses.replace(cfg, num_workers=num_workers)
        self.cfg = cfg

        mk = dict(model_kwargs or {})
        if cfg.compute_dtype != "float32":
            # mixed precision: flax `dtype` sets computation dtype only;
            # params stay float32 (flax param_dtype default) — the apex-amp
            # replacement (SURVEY.md §2.4)
            mk.setdefault("dtype", jnp.dtype(cfg.compute_dtype))
        self.model, example_fn = create_model(cfg.dnn, **mk)
        self.example_fn = example_fn

        rng = jax.random.PRNGKey(cfg.seed)
        init_batch = self._example_batch(2)
        variables = self._init_variables(rng, init_batch)
        params = variables.pop("params")
        self.model_state = dict(variables)

        n = flat_size(params)
        self.algo_cfg = (algo_cfg or OkTopkConfig()).replace(
            n=n, num_workers=num_workers, density=cfg.density)

        # Momentum correction (DGC-style) folds momentum into the compressed
        # gradient stream; it belongs to the SGD path only — Adam has its own
        # moment accumulators, so folding on top would double-smooth.
        if cfg.dnn.startswith("bert"):
            if cfg.momentum_correction:
                warnings.warn(
                    "momentum_correction is an SGD-path feature (reference "
                    "VGG/distributed_optimizer.py:56,81-88); ignored for "
                    "BERT/Adam workloads", stacklevel=2)
            self._mc_factor = 0.0
            self.optimizer = bert_adam(
                lr=cfg.lr, warmup=cfg.warmup_proportion,
                t_total=cfg.total_steps or -1)
        else:
            self._mc_factor = (cfg.momentum if cfg.momentum_correction
                               else 0.0)
            # with momentum correction the momentum lives in the compressed
            # gradient stream, so the base SGD runs momentum-free
            self.optimizer = sgd(
                cfg.lr,
                momentum=0.0 if self._mc_factor else cfg.momentum,
                weight_decay=cfg.weight_decay, nesterov=cfg.nesterov)

        self._warmup = warmup
        self._profile_norm = profile_norm

        # ---- unified observability (obs/): event bus + run journal ----
        # Built BEFORE the resilience/autotune journals so both can be
        # constructed as thin views over the same bus.
        self.bus = None
        self.run_journal = None
        self.tracer = None
        self.regress = None
        self.rollup = None
        self._quality_cfg = None
        self.quality_flushes = 0   # host drains of the device rings
        self._q_cursors = {}       # bucket -> last drained ring cursor
        if cfg.obs:
            from oktopk_tpu.obs.journal import EventBus, RunJournal
            self.bus = EventBus()
            self.run_journal = RunJournal(cfg.obs_journal, bus=self.bus)
            if cfg.obs_quality:
                # journal first, rollup engine second: the engine's
                # nested emit then lands each quality_rollup directly
                # after its quality event in the file
                from oktopk_tpu.obs.quality import QualityConfig
                from oktopk_tpu.obs.rollup import RollupEngine
                self._quality_cfg = QualityConfig(
                    every=cfg.obs_quality_every,
                    sig_bins=cfg.obs_quality_sig_bins)
                self.rollup = RollupEngine(
                    self.bus,
                    growth_limit=cfg.obs_quality_growth_limit,
                    collapse_ratio=cfg.obs_quality_collapse_ratio,
                    churn_limit=cfg.obs_quality_churn_limit,
                    comp_err_limit=cfg.obs_quality_comp_err_limit,
                    on_breach=self._on_quality_breach)
            if cfg.obs_trace_on_anomaly:
                import os
                import tempfile
                from oktopk_tpu.obs.tracing import AnomalyTracer
                tdir = cfg.obs_trace_dir
                if tdir is None:
                    tdir = (os.path.join(os.path.dirname(
                                os.path.abspath(cfg.obs_journal)), "traces")
                            if cfg.obs_journal
                            else tempfile.mkdtemp(prefix="oktopk_traces_"))
                self.tracer = AnomalyTracer(
                    tdir, bus=self.bus, num_steps=cfg.obs_trace_steps,
                    max_captures=cfg.obs_max_traces)
            if cfg.obs_regress_key:
                from oktopk_tpu.obs.regress import RegressionDetector
                self.regress = RegressionDetector.from_bench_records(
                    key=cfg.obs_regress_key, bus=self.bus,
                    tolerance=cfg.obs_regress_tolerance,
                    phase_limits=cfg.obs_phase_limits)

        # ---- numeric-health guard + supervisor (resilience/) ----------
        self._fault_plan = fault_plan
        self._guard = None
        self.supervisor = None
        if cfg.resilience:
            from oktopk_tpu.resilience import (GuardConfig, HealthJournal,
                                               Supervisor)
            self._guard = GuardConfig(abs_limit=cfg.resilience_abs_limit)
            self.supervisor = Supervisor(
                num_buckets=cfg.num_buckets,
                max_strikes=cfg.resilience_strikes,
                divergence_limit=cfg.resilience_divergence_limit,
                cooldown_steps=cfg.resilience_cooldown,
                journal=HealthJournal(cfg.resilience_journal,
                                      bus=self.bus))
            if fault_plan is not None:
                # chaos drill: announce the planned schedule up front so
                # the journal distinguishes drills from real corruption
                for f in fault_plan.faults:
                    self.supervisor.journal.fault_seen(
                        f.step, f"planned:{f.kind}", buckets=[f.bucket])

        # ---- closed-loop policies (resilience/feedback.py, density.py)
        self.feedback = None
        if cfg.resilience_feedback and self.bus is not None:
            from oktopk_tpu.resilience import AutotuneFeedback
            kinds = ("regression", "guard_trip")
            if self._quality_cfg is not None:
                # breached quality rollups vote alongside guard trips and
                # perf regressions in the forced-retune window
                kinds = kinds + ("quality_rollup",)
            self.feedback = AutotuneFeedback(
                self.bus, window_steps=cfg.resilience_feedback_window,
                min_signals=cfg.resilience_feedback_signals,
                cooldown_steps=cfg.resilience_feedback_cooldown,
                kinds=kinds)
        self.density_backoff = None
        if cfg.resilience and cfg.resilience_density_backoff:
            from oktopk_tpu.resilience import DensityBackoff
            self.density_backoff = DensityBackoff(
                abs_limit=cfg.resilience_abs_limit,
                near_ratio=cfg.resilience_near_ratio,
                backoff_steps=cfg.resilience_backoff_steps,
                factor=cfg.resilience_backoff_factor,
                max_level=cfg.resilience_backoff_max_level,
                clean_streak=cfg.resilience_clean_streak)
        self._density_scale = 1.0  # density-backoff multiplier (≤ 1)
        self.retune_events = 0     # forced re-calibrations executed
        self._fake_ms = None       # remembered trial-timing injector

        self.state = init_dist_state(
            params, self.model_state, self.optimizer, self.algo_cfg,
            momentum_correction=bool(self._mc_factor),
            num_buckets=cfg.num_buckets,
            with_health=self._with_health,
            quality=self._quality_cfg)
        self.autotuner = None      # built lazily by autotune()
        self._plans = None         # per-bucket BucketPlan list, or None
        self.step_fn = self._build_step()
        self._rng = jax.random.PRNGKey(cfg.seed + 1)
        self.metrics_history = []

    @property
    def _with_health(self) -> bool:
        return self._guard is not None or self._fault_plan is not None

    @property
    def _forced_dense(self):
        return self.supervisor.forced_dense if self.supervisor else ()

    def _build_step(self):
        nb = max(1, self.cfg.num_buckets)
        compressor = self.cfg.compressor
        densities = None
        if self._plans:
            compressor = [p.algo for p in self._plans]
            densities = [p.density for p in self._plans]
        acfg = self.algo_cfg
        if self._density_scale < 1.0:
            # guard-aware backoff: shrink the *effective* selection
            # density (schedule included) without touching cfg.density —
            # capacity sizing stays pinned so wire buffers never re-size
            # across a backoff level change
            if acfg.density_schedule:
                acfg = acfg.replace(density_schedule=tuple(
                    (s, d * self._density_scale)
                    for s, d in acfg.density_schedule))
            else:
                densities = [d * self._density_scale for d in
                             (densities if densities is not None
                              else [self.cfg.density] * nb)]
        if self._forced_dense:
            from oktopk_tpu.resilience.supervisor import plan_with_fallbacks
            names = (list(compressor) if not isinstance(compressor, str)
                     else [compressor] * nb)
            compressor = plan_with_fallbacks(names, self._forced_dense)
            if densities is not None:
                densities = [1.0 if b in self._forced_dense else d
                             for b, d in enumerate(densities)]
        return build_sparse_grad_step(
            self._loss_fn, self.optimizer, acfg, self.mesh,
            compressor=compressor, axis_name=self.axis_name,
            nsteps_update=self.cfg.nsteps_update,
            grad_clip=self.cfg.grad_clip, warmup=self._warmup,
            profile_norm=self._profile_norm,
            momentum_correction=self._mc_factor,
            num_buckets=self.cfg.num_buckets,
            bucket_densities=densities,
            guard=self._guard, fault_plan=self._fault_plan,
            quality=self._quality_cfg)

    # ---- signal-fidelity telemetry (obs/quality.py) -------------------

    def _flush_quality(self, step: int) -> None:
        """Drain the device-side quality rings to the journal — the ONLY
        device→host movement the telemetry plane performs. One
        ``jax.device_get`` of the ring leaves per flush; each bucket's
        new rows become a schema-versioned ``quality`` event, which the
        RollupEngine immediately aggregates into a ``quality_rollup``."""
        if self._quality_cfg is None or self.bus is None:
            return
        if self.state.quality is None:
            return
        from oktopk_tpu.obs.metrics_buffer import rows_since
        from oktopk_tpu.obs.quality import quality_event
        names, densities = self._bucket_plan()
        if self.rollup is not None:
            self.rollup.target_densities = [float(d) for d in densities]
        single = self.cfg.num_buckets <= 1
        bufs = ([self.state.quality] if single
                else list(self.state.quality))
        host = jax.device_get(bufs)
        for b, hb in enumerate(host):
            cursor = int(np.asarray(hb.cursor).reshape(-1)[0])
            prev = self._q_cursors.get(b, 0)
            if cursor == prev:
                continue
            rows = rows_since(np.asarray(hb.ring), cursor, prev)
            self._q_cursors[b] = cursor
            algo = names[b] if b < len(names) else self.cfg.compressor
            ev = quality_event(step, b, algo, rows)
            self.bus.emit("quality", **ev)
        self.quality_flushes += 1

    def _on_quality_breach(self, step: int, bucket: int, breaches) -> None:
        """RollupEngine breach hook: route sustained FIDELITY breaches to
        the density-backoff controller. Guard pressure pushes density
        down; compression-quality pressure pulls it back up — the two
        halves of the closed loop meet in the same hysteretic policy."""
        if self.density_backoff is None:
            return
        change = None
        for kind in breaches:
            change = self.density_backoff.note_quality_breach(
                int(step), str(kind)) or change
        if change is not None:
            self._density_scale = float(change["scale"])
            if self.supervisor is not None:
                self.supervisor.journal.density_backoff(int(step), **change)
            elif self.bus is not None:
                self.bus.emit("density_backoff", step=int(step), **change)
            self.step_fn = self._build_step()

    # ---- autotuning ---------------------------------------------------

    def _make_autotuner(self, fake_ms=None):
        from oktopk_tpu.autotune import (Autotuner, AutotunePolicy,
                                         DecisionJournal, TrialRunner)
        from oktopk_tpu.autotune.policy import make_candidates
        from oktopk_tpu.optim.distributed import (bucket_partition,
                                                  bucket_sizes)

        cfg = self.cfg
        densities = tuple(cfg.autotune_densities) or (cfg.density,)
        policy = AutotunePolicy(
            candidates=make_candidates(cfg.autotune_candidates, densities),
            hysteresis=cfg.autotune_hysteresis,
            retune_every=cfg.autotune_retune_every,
            max_trials=cfg.autotune_max_trials)
        runner = TrialRunner(
            mesh=self.mesh, axis_name=self.axis_name,
            trial_steps=cfg.autotune_trial_steps, seed=cfg.seed,
            base_cfg=self.algo_cfg, fake_ms=fake_ms)
        sizes = bucket_sizes(self.state.params,
                             bucket_partition(self.state.params,
                                              cfg.num_buckets))
        return Autotuner(
            sizes, self.cfg.num_workers, policy, runner,
            journal=DecisionJournal(cfg.autotune_journal, bus=self.bus))

    def autotune(self, step: int = 0, fake_ms=None):
        """Run (or re-run) the calibrate -> trial -> policy pass and adopt
        the resulting per-bucket plan. The jitted step is rebuilt only
        when the plan actually changed — the policy's hysteresis is what
        keeps borderline buckets from forcing a recompile every re-tune.
        Returns the plan list.

        ``fake_ms(algo, n, density) -> ms`` injects synthetic trial
        timings (CPU tests of the decision logic; see autotune/trial.py).
        """
        from oktopk_tpu.autotune import Autotuner

        if fake_ms is not None:
            # remember the injector: a forced re-tune (force_retune) or
            # elastic resize rebuilds the tuner and must keep measuring
            # through the same seam
            self._fake_ms = fake_ms
        if self.autotuner is None:
            self.autotuner = self._make_autotuner(fake_ms=self._fake_ms)
        old = self._plans
        self._plans = self.autotuner.tune(step=step, mesh=self.mesh)
        if Autotuner.plans_changed(self._plans, old):
            self.step_fn = self._build_step()
        return self._plans

    def maybe_autotune(self, step: int):
        """Tune on first use and on the configured re-tune cadence."""
        if not self.cfg.autotune:
            return
        if self.autotuner is None or self.autotuner.should_retune(step):
            self.autotune(step=step)

    def force_retune(self, step: int, trigger: str = "manual",
                     signals=()):
        """Drop the autotuner and re-tune from scratch — the
        fault→autotune feedback path (resilience/feedback.py). A fresh
        tuner has no fabric coefficients, so the next ``tune()``
        re-calibrates against the *current* (possibly degraded) fabric
        before re-deciding; the journal carries the causal chain as
        ``retune`` (with the evidence steps) → ``calibration`` →
        ``autotune_decision``. Returns the new plan (None when autotune
        is off — the retune is still journalled so the evidence isn't
        lost)."""
        self.retune_events += 1
        if self.bus is not None:
            self.bus.emit("retune", step=int(step), trigger=str(trigger),
                          signals=[int(s) for s in signals],
                          cleared="autotuner")
        self.autotuner = None
        if self.cfg.autotune:
            return self.autotune(step=step)
        return None

    def check_feedback(self, step: int):
        """Poll the fault→autotune feedback policy; execute the forced
        re-calibrate + re-tune when its window vote passes. Returns the
        trigger descriptor (or None)."""
        if self.feedback is None:
            return None
        trig = self.feedback.should_retune(step)
        if trig is not None:
            self.force_retune(step, trigger=trig["trigger"],
                              signals=trig["signals"])
        return trig

    # ---- resilience supervision ---------------------------------------

    def supervise(self, step: int, metrics) -> None:
        """Feed one step's guard metrics to the supervisor and execute
        whatever it escalates to: a per-bucket dense fallback rebuilds
        the jitted step exactly like an autotune plan change; a restore
        reloads the last good checkpoint registered via
        :meth:`note_checkpoint` (journalled either way); a chip loss
        remeshes onto the surviving devices; and the density-backoff
        policy digests the step's guard pressure."""
        if self.supervisor is None:
            return
        # chip loss is a host/orchestrator observation, not a guard
        # metric: poll the plan's dead set (faults.dead_workers) and let
        # the supervisor escalate any newly dead rank straight to remesh
        if self._fault_plan is not None:
            from oktopk_tpu.resilience.faults import dead_workers
            dead = dead_workers(self._fault_plan, step)
            if dead:
                for act in self.supervisor.note_chip_loss(step, dead):
                    self._execute_action(act, step)
        host = {k: np.asarray(metrics[k])
                for k in ("step_skipped", "bucket_anomalies")
                if k in metrics}
        for act in self.supervisor.observe(step, host):
            self._execute_action(act, step)
        if self.density_backoff is not None and "reduced_absmax" in metrics:
            change = self.density_backoff.observe(
                step, absmax=float(np.asarray(metrics["reduced_absmax"])),
                skipped=int(np.asarray(metrics.get("step_skipped", 0))))
            if change is not None:
                self._density_scale = float(change["scale"])
                self.supervisor.journal.density_backoff(step, **change)
                self.step_fn = self._build_step()

    def _execute_action(self, act, step: int) -> None:
        """Execute one supervisor escalation action."""
        if act.kind == "fallback":
            # forced_dense already updated by the supervisor
            self.step_fn = self._build_step()
        elif act.kind == "restore" and act.ckpt:
            # verified restore: walk newest -> oldest past corrupt
            # files, journalling ckpt_verify_failed per rejected file
            # BEFORE the restore record — so the journal names the
            # checkpoint actually loaded, not the intended target
            from oktopk_tpu.train.durable import verified_restore
            journal = (self.supervisor.journal
                       if self.supervisor is not None else None)
            try:
                self.state, ckpt_step, used, _, _ = verified_restore(
                    act.ckpt, self.state, journal=journal, bus=self.bus,
                    step=step)
            except FileNotFoundError:
                # every candidate corrupt: a restore cannot happen —
                # journal the fact and fail loudly rather than keep
                # training a diverged model
                if journal is not None:
                    journal.restore(step, None, -1)
                raise
            if journal is not None:
                journal.restore(step, used, ckpt_step)
        elif act.kind == "remesh":
            self._execute_remesh(step, act.workers)

    def _execute_remesh(self, step: int, workers) -> None:
        """Shrink the mesh to the devices whose ranks survive and resize
        onto it — the no-requeue recovery path for chip loss. Rank i is
        position i in the flattened device list (the data-parallel-only
        layout every emulated drill uses)."""
        dead = {int(w) for w in workers}
        devs = [d for i, d in enumerate(
                    np.asarray(self.mesh.devices).reshape(-1))
                if i not in dead]
        if not devs:
            raise RuntimeError(
                f"chip_loss at step {step} left no surviving devices")
        new_mesh = get_mesh(axis_names=self.mesh.axis_names, devices=devs)
        self.resize_workers(new_mesh, trigger="chip_loss",
                            dead_workers=sorted(dead), step=step)

    def note_checkpoint(self, path: str, step: int) -> None:
        """Register a saved checkpoint as a restore candidate (and record
        the supervisor's own state next to it, see ``supervisor_extra``).
        Journalled either way: via the supervisor's health journal when
        resilience is on, straight onto the bus otherwise."""
        if self.supervisor is not None:
            self.supervisor.note_checkpoint(path, step)
        elif self.bus is not None:
            self.bus.emit("checkpoint", step=int(step), path=path,
                          qualified=True)

    @property
    def checkpoint_qualified(self) -> bool:
        """Whether a checkpoint taken NOW would be a restore target (no
        skips in flight) — recorded into the manifest's ``qualified``
        bit so the retention policy and offline fsck see the same
        good/mid-incident distinction the supervisor does."""
        if self.supervisor is None:
            return True
        return self.supervisor.consecutive_skips == 0

    def note_ckpt_failure(self, step: int, path: str, error) -> None:
        """Escalate a failed (async) checkpoint write to the supervisor —
        the ``on_failure`` hook for ``durable.AsyncCheckpointer``."""
        if self.supervisor is not None:
            self.supervisor.note_ckpt_write_failure(step, path, error)
        elif self.bus is not None:
            self.bus.emit("ckpt_verify_failed", step=int(step), path=path,
                          reason=f"write_failed: {error}")

    def supervisor_extra(self):
        """The ``extra`` payload for ``checkpoint.save_checkpoint``: the
        supervisor's strike counters, active fallbacks, and last-good
        marker, so a resumed run keeps its escalation state."""
        if self.supervisor is None:
            return None
        return {"supervisor": self.supervisor.to_state()}

    def restore_supervisor(self, ckpt_dir_or_file: str) -> None:
        """Re-arm the supervisor from a checkpoint's extra payload and
        re-apply its per-bucket fallbacks to the jitted step."""
        if self.supervisor is None:
            return
        from oktopk_tpu.train.checkpoint import load_extra
        extra = load_extra(ckpt_dir_or_file) or {}
        self.supervisor.load_state(extra.get("supervisor") or {})
        if self.supervisor.forced_dense:
            self.step_fn = self._build_step()

    # ---- workload-specific pieces -------------------------------------

    def _init_variables(self, rng, batch):
        rngs = {"params": rng, "dropout": jax.random.fold_in(rng, 1)}
        if self.cfg.dnn in ("lstm", "lstm_tiny"):
            return self.model.init(rngs, batch["tokens"], train=False)
        if self.cfg.dnn.startswith("bert"):
            return self.model.init(rngs, batch["input_ids"],
                                   batch["token_type_ids"],
                                   batch["attention_mask"], train=False)
        if self.cfg.dnn.startswith("lstman4"):
            return self.model.init(rngs, batch["spect"], train=False)
        return self.model.init(rngs, batch["image"], train=False)

    def _example_batch(self, bs: int):
        """Zero-filled batch with the workload's shapes (for init/tracing)."""
        dnn = self.cfg.dnn
        if dnn in ("lstm", "lstm_tiny"):
            t = 35
            return {"tokens": jnp.zeros((bs, t), jnp.int32),
                    "targets": jnp.zeros((bs, t), jnp.int32)}
        if dnn.startswith("bert"):
            t = 32 if dnn == "bert_tiny" else 128
            return {"input_ids": jnp.zeros((bs, t), jnp.int32),
                    "token_type_ids": jnp.zeros((bs, t), jnp.int32),
                    "attention_mask": jnp.ones((bs, t), jnp.int32),
                    "mlm_labels": jnp.full((bs, t), -1, jnp.int32),
                    "nsp_labels": jnp.zeros((bs,), jnp.int32)}
        if dnn.startswith("lstman4"):
            return {"spect": jnp.zeros((bs, 161, 201, 1), jnp.float32),
                    "spect_lengths": jnp.full((bs,), 201, jnp.int32),
                    "labels": jnp.zeros((bs, 40), jnp.int32),
                    "label_lengths": jnp.full((bs,), 10, jnp.int32)}
        img = self.example_fn(bs)
        return {"image": img,
                "label": jnp.zeros((bs,), jnp.int32)}

    def _loss_fn(self, params, model_state, batch, rng):
        dnn = self.cfg.dnn
        variables = {"params": params, **model_state}
        mutable = [k for k in model_state]
        rngs = {"dropout": rng}

        if dnn in ("lstm", "lstm_tiny"):
            (logits, _), mut = self.model.apply(
                variables, batch["tokens"], train=True, mutable=mutable,
                rngs=rngs)
            loss = losses.lm_cross_entropy(logits, batch["targets"])
            return loss, (dict(mut), {})
        if dnn.startswith("bert"):
            (mlm, nsp), mut = self.model.apply(
                variables, batch["input_ids"], batch["token_type_ids"],
                batch["attention_mask"], train=True, mutable=mutable,
                rngs=rngs)
            loss, aux = losses.bert_pretrain_loss(
                mlm, nsp, batch["mlm_labels"], batch["nsp_labels"])
            return loss, (dict(mut), aux)
        if dnn.startswith("lstman4"):
            logits, mut = self.model.apply(
                variables, batch["spect"], train=True, mutable=mutable,
                rngs=rngs)
            frames = logits.shape[1]
            frame_len = jnp.minimum(_ctc_frame_len(batch["spect_lengths"]),
                                    frames)
            loss = losses.ctc_loss(logits, frame_len, batch["labels"],
                                   batch["label_lengths"])
            return loss, (dict(mut), {})
        logits, mut = self.model.apply(
            variables, batch["image"], train=True, mutable=mutable, rngs=rngs)
        loss = losses.softmax_cross_entropy(logits, batch["label"])
        return loss, (dict(mut), {})

    # ---- loops --------------------------------------------------------

    def train_step(self, batch):
        self._rng, rng = jax.random.split(self._rng)
        self.state, metrics = self.step_fn(self.state, batch, rng)
        return metrics

    def train(self, data_iter: Iterable, num_iters: int,
              log_every: int = 50, logger=None, metric_writer=None,
              timers=None, trace=None, start_step: int = 0,
              should_stop=None):
        """Run ``num_iters`` steps (reference trainer.train(nsteps),
        VGG/dl_trainer.py:597). Returns the last metrics dict.

        Optional observability hooks (SURVEY.md §5.1): ``metric_writer``
        (utils.profiling.MetricWriter) records per-step scalars,
        ``timers`` (PhaseTimers) splits data-wait vs device-step time,
        ``trace`` (TraceWindow) captures a bounded jax.profiler trace.
        """
        metrics = {}
        pending = []  # (step, device-metrics) — flushed on the log cadence
        # so the writer never forces a per-step device sync
        nf_window = []  # per-step nonfinite-grad counters (device scalars;
        # summed host-side only on the log cadence)

        def flush_pending():
            for s, dm in pending:
                host = {k: float(np.asarray(v).mean())
                        for k, v in dm.items()}
                if metric_writer is not None:
                    metric_writer.write(s, host)
                if self.bus is not None:
                    self.bus.emit("step", step=s, **host)
            pending.clear()

        t0 = time.time()
        self.last_step = start_step
        for i in range(num_iters):
            if should_stop is not None and should_stop():
                # preemption: break between steps so state is consistent
                # (reference's clean-exit Event, BERT/bert/main_bert.py:73-96)
                break
            step = start_step + i + 1
            self.last_step = step
            # plan (or re-plan) the per-bucket collectives before the step
            # runs; a no-change verdict leaves step_fn (and its compiled
            # program) untouched
            self.maybe_autotune(step)
            if trace is not None:
                trace.on_step(step)
            if self.tracer is not None:
                # anomaly-armed profiler window (obs/tracing.py): opens
                # here on the step after a guard_trip/fallback event,
                # closes num_steps later with a trace_captured event
                self.tracer.on_step(step)
            if timers is not None:
                with timers.phase("data"):
                    batch = next(data_iter)
                with timers.phase("step"):
                    metrics = self.train_step(batch)
                    jax.block_until_ready(metrics["loss"])
            else:
                batch = next(data_iter)
                metrics = self.train_step(batch)
            if (self.supervisor is not None
                    and step % max(1, self.cfg.resilience_check_every) == 0):
                # reacting to guard trips costs a device sync on the
                # check cadence; escalation may rebuild step_fn or
                # restore state before the next iteration
                self.supervise(step, metrics)
            if (self._quality_cfg is not None
                    and step % self._quality_cfg.every == 0):
                # drain the device metric rings on the flush cadence —
                # steady state between flushes adds zero host syncs
                self._flush_quality(step)
            if self.feedback is not None:
                # fault→autotune feedback: a passing window vote forces
                # a re-calibrate + re-tune (host-side list ops only
                # until it actually fires)
                self.check_feedback(step)
            if metric_writer is not None or self.bus is not None:
                pending.append((step, metrics))
            if "grad_nonfinite" in metrics:
                nf_window.append(metrics["grad_nonfinite"])
            if (i + 1) % log_every == 0:
                if pending:
                    flush_pending()
                dt = (time.time() - t0) / log_every
                if self.regress is not None:
                    self.regress.observe(step, dt * 1e3)
                if logger:
                    # absolute step, not the loop index: after a preemption
                    # resume the log must agree with scalars.csv/checkpoints
                    logger.info(
                        "iter %d loss %.4f vol %.0f %.3fs/it", step,
                        float(metrics["loss"]),
                        float(metrics["comm_volume"]), dt)
                    nf = sum(float(x) for x in nf_window)
                    if nf:
                        # the reference warns on NaN gradient sparsity
                        # (VGG/dl_trainer.py:608-609); the whole window is
                        # summed so a mid-window blow-up cannot hide
                        logger.warning(
                            "window ending iter %d: %d nonfinite gradient "
                            "elements", step, int(nf))
                    nf_window.clear()
                if timers is not None and self.bus is not None:
                    phase_summary = timers.summary()
                    self.bus.emit("phase", step=step, phases=phase_summary)
                    if self.regress is not None:
                        # host-phase durations vs configured phase limits
                        # (key="phase:<name>" regressions on the bus)
                        self.regress.observe_phases(step, phase_summary)
                t0 = time.time()
            if timers is not None and logger is not None:
                timers.maybe_log(step, logger)
        if pending:
            flush_pending()
        if self.tracer is not None:
            self.tracer.finish(self.last_step)
        if self._quality_cfg is not None:
            # partial-window flush so the tail of the run is journalled
            self._flush_quality(self.last_step)
        if self.bus is not None:
            self._emit_volume_report()
        self.metrics_history.append(
            {k: float(np.asarray(v).mean()) for k, v in metrics.items()})
        return metrics

    def _bucket_plan(self):
        """Per-bucket (algo name, density) after autotune plans and forced
        dense fallbacks — the same resolution :meth:`_build_step`
        performs, exposed for reporting."""
        nb = max(1, self.cfg.num_buckets)
        names = [self.cfg.compressor] * nb
        densities = [self.cfg.density] * nb
        if self._plans:
            names = [p.algo for p in self._plans]
            densities = [p.density for p in self._plans]
        if self._density_scale < 1.0 and not self.algo_cfg.density_schedule:
            densities = [d * self._density_scale for d in densities]
        for b in self._forced_dense:
            if 0 <= b < nb:
                names[b] = "dense"
                densities[b] = 1.0
        return names, densities

    def _emit_volume_report(self):
        """One ``volume_report`` event per bucket: mean realised wire
        bytes per step (from the SparseState accounting) against the
        algorithm's analytic budget (obs/volume.py). The mean covers the
        WHOLE run — dense warmup steps and exact recomputes included —
        so a warmed-up sparse run legitimately reports above the
        steady-state budget; the per-algorithm conformance guarantee is
        asserted by the steady-state tests, not here."""
        from oktopk_tpu.obs import volume as obs_volume
        names, densities = self._bucket_plan()
        single = self.cfg.num_buckets <= 1
        sps = ([self.state.sparse_state] if single
               else list(self.state.sparse_state))
        for b, (nm, dens) in enumerate(zip(names, densities)):
            sp = sps[b]
            steps_done = int(np.asarray(sp.step)[0])
            wb = float(np.asarray(sp.wire_bytes)[0])
            n_b = int(np.asarray(sp.residual).shape[-1])
            cfg_b = self.algo_cfg.replace(n=n_b, density=float(dens))
            rep = obs_volume.volume_report(
                nm, cfg_b, wb / max(1, steps_done), bucket=b,
                step=getattr(self, "last_step", 0), steps=steps_done)
            self.bus.emit("volume_report", **rep)

    # ---- elasticity ---------------------------------------------------

    def resize_workers(self, new_mesh: Mesh, trigger: str = "manual",
                       dead_workers=(), step: Optional[int] = None):
        """Rebuild the distributed step for a new world size, keeping model
        and optimizer state.

        Reference analogue: the elastic hooks ``err_callback`` ->
        ``trainer.update_nworker`` which rebuild samplers/loaders for a new
        world size (VGG/main_trainer.py:42-44, VGG/dl_trainer.py:472-493).
        Detection lives in the supervisor's chip-loss path
        (:meth:`supervise` → ``note_chip_loss`` → ``remesh`` action →
        here with ``trigger="chip_loss"``); an orchestrator-driven resize
        calls this directly (``trigger="manual"``). Per-worker algorithm
        state (residuals, boundaries) is re-initialised for the new
        topology; replicated state — params, model/opt state, the health
        attempted-step clock, and the host-side supervisor counters —
        carries over, so fault plans and strike histories stay aligned
        with the run's step indices. The resize is journalled as a
        schema-versioned ``remesh`` event naming exactly which state
        carried vs was re-initialised.
        """
        old_world = int(self.cfg.num_workers)
        num_workers = int(new_mesh.shape[self.axis_name])
        self.mesh = new_mesh
        self.cfg = dataclasses.replace(self.cfg, num_workers=num_workers)
        self.algo_cfg = self.algo_cfg.replace(num_workers=num_workers)
        # pull replicated state off the old mesh's devices before re-placing;
        # params/model/opt state carry over, per-worker state re-initialises
        old = jax.device_get(
            (self.state.params, self.state.model_state, self.state.opt_state))
        old_health = (jax.device_get(self.state.health)
                      if self.state.health is not None else None)
        self.state = init_dist_state(
            old[0], old[1], self.optimizer, self.algo_cfg,
            momentum_correction=bool(self._mc_factor), opt_state=old[2],
            num_buckets=self.cfg.num_buckets,
            with_health=self._with_health,
            quality=self._quality_cfg)
        carried = ["params", "model_state", "opt_state"]
        reinit = ["sparse_state", "local_momentum", "autotuner"]
        if self._quality_cfg is not None:
            # fresh per-worker rings for the new topology; drained-cursor
            # bookkeeping restarts with them so the first post-resize
            # flush doesn't replay stale rows
            self._q_cursors = {}
            reinit.append("quality")
        if old_health is not None and self.state.health is not None:
            # the attempted-step counter is the clock every fault plan
            # and supervisor cadence indexes by — it must stay monotonic
            # across the resize, not restart at 0
            self.state = self.state.replace(health=old_health)
            carried.append("health")
        elif self.state.health is not None:
            reinit.append("health")
        if self.supervisor is not None:
            carried.append("supervisor")
        # trial measurements were taken on the old topology: drop the
        # tuner (it re-tunes against the new mesh on the next cadence)
        # but keep the current plan so the rebuilt step stays consistent
        self.autotuner = None
        self.step_fn = self._build_step()
        ev = dict(step=int(step if step is not None
                           else getattr(self, "last_step", 0)),
                  old_world=old_world, new_world=num_workers,
                  trigger=str(trigger),
                  dead_workers=[int(w) for w in dead_workers],
                  carried=carried, reinitialised=reinit)
        if self.supervisor is not None:
            self.supervisor.journal.remesh(**ev)
        elif self.bus is not None:
            self.bus.emit("remesh", **ev)

    # ---- eval ---------------------------------------------------------

    def eval_step(self, batch):
        """Forward-only accuracy/loss on a replicated batch (reference
        DLTrainer.test, VGG/dl_trainer.py:709)."""
        params = self.state.params
        variables = {"params": params, **self.state.model_state}
        dnn = self.cfg.dnn
        if dnn in ("lstm", "lstm_tiny"):
            logits, _ = self.model.apply(variables, batch["tokens"],
                                         train=False)
            loss = losses.lm_cross_entropy(logits, batch["targets"])
            return {"loss": loss, "ppl": jnp.exp(loss)}
        if dnn.startswith("bert"):
            mlm, nsp = self.model.apply(
                variables, batch["input_ids"], batch["token_type_ids"],
                batch["attention_mask"], train=False)
            loss, aux = losses.bert_pretrain_loss(
                mlm, nsp, batch["mlm_labels"], batch["nsp_labels"])
            return {"loss": loss, **aux}
        if dnn.startswith("lstman4"):
            # real CTC loss + greedy-decoded WER/CER — the reference's test
            # loop decodes every eval batch and averages word/char distances
            # (VGG/dl_trainer.py:743-762, decoder at VGG/decoder.py:23-197)
            from oktopk_tpu.data.audio import AN4_LABELS
            from oktopk_tpu.utils.decoder import GreedyDecoder

            logits = self.model.apply(variables, batch["spect"], train=False)
            frames = logits.shape[1]
            frame_len = jnp.minimum(_ctc_frame_len(batch["spect_lengths"]),
                                    frames)
            loss = losses.ctc_loss(logits, frame_len, batch["labels"],
                                   batch["label_lengths"])
            dec = GreedyDecoder(AN4_LABELS)
            hyps = dec.decode(np.asarray(logits), np.asarray(frame_len))
            labs = np.asarray(batch["labels"])
            lens = np.asarray(batch["label_lengths"])
            refs = ["".join(AN4_LABELS[c] for c in labs[b, : lens[b]])
                    for b in range(labs.shape[0])]
            wer = float(np.mean([dec.wer(h, r) for h, r in zip(hyps, refs)]))
            cer = float(np.mean([dec.cer(h, r) for h, r in zip(hyps, refs)]))
            return {"loss": loss, "wer": jnp.asarray(wer),
                    "cer": jnp.asarray(cer)}
        logits = self.model.apply(variables, batch["image"], train=False)
        loss = losses.softmax_cross_entropy(logits, batch["label"])
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
        return {"loss": loss, "accuracy": acc}
