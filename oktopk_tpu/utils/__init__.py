from oktopk_tpu.utils.cost_model import (  # noqa: F401
    allgather_cost,
    allreduce_cost,
    sparse_allreduce_cost,
    topk_cost,
)
from oktopk_tpu.utils.logging import get_logger  # noqa: F401
