"""Analytic α-β communication cost models.

Reference: VGG/utils.py:86-134 — latency/bandwidth (α-β) models for topk,
allgather and allreduce used to reason about density selection. Re-derived
here with ICI-flavoured defaults; these feed the comm-volume accounting that
reproduces the paper's <6k claim analytically (SURVEY.md §7.3.7), since XLA
hides wire bytes.
"""

from __future__ import annotations

# Piz Daint-era defaults in the reference; ICI is ~2 orders faster. Both kept
# so ablations can model either fabric.
MPI_ALPHA = 5e-6        # per-message latency, seconds
MPI_BETA = 1e-9         # per-element time (≈1 GB/s/element-ish, f32)
ICI_ALPHA = 1e-6
ICI_BETA = 1e-11


def topk_cost(n: int, gamma: float = 1e-9) -> float:
    """Local top-k selection cost ~ gamma * n (sort-free threshold count)."""
    return gamma * n


def allgather_cost(k: int, p: int, alpha: float = ICI_ALPHA,
                   beta: float = ICI_BETA) -> float:
    """Ring allgather of k elements from each of p workers."""
    return (p - 1) * alpha + (p - 1) * k * beta


def allreduce_cost(n: int, p: int, alpha: float = ICI_ALPHA,
                   beta: float = ICI_BETA) -> float:
    """Ring allreduce: reduce-scatter + allgather, ~2n(p-1)/p elements."""
    return 2 * (p - 1) * alpha + 2.0 * n * (p - 1) / p * beta


def sparse_allreduce_cost(k: int, p: int, alpha: float = ICI_ALPHA,
                          beta: float = ICI_BETA) -> float:
    """Ok-Topk two-phase cost: O(1) latency rounds, <6k elements
    (paper property; reference README.md:2)."""
    phase_a = alpha + 4.0 * k * beta          # all_to_all of ~2k scalars each way
    phase_b = (p - 1) * alpha + 2.0 * k * beta
    return phase_a + phase_b
