"""Greedy CTC decoding + WER/CER metrics (reference VGG/decoder.py:23-197:
GreedyDecoder with Levenshtein word/char error rates, used by
DLTrainer.test for the AN4 workload, VGG/dl_trainer.py:743-762)."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def levenshtein(a: Sequence, b: Sequence) -> int:
    """Edit distance (the reference uses the python-Levenshtein package;
    this is the standard DP, dependency-free)."""
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


class GreedyDecoder:
    """argmax-per-frame, collapse repeats, strip blanks."""

    def __init__(self, labels: str, blank_index: int = 0):
        self.labels = labels
        self.blank = blank_index

    def decode(self, logits: np.ndarray,
               lengths: np.ndarray = None) -> List[str]:
        """logits [B, T, C] -> list of decoded strings."""
        out = []
        ids = np.argmax(logits, axis=-1)
        for b in range(ids.shape[0]):
            t_max = int(lengths[b]) if lengths is not None else ids.shape[1]
            prev = -1
            chars = []
            for t in range(t_max):
                c = int(ids[b, t])
                if c != self.blank and c != prev:
                    chars.append(self.labels[c])
                prev = c
            out.append("".join(chars))
        return out

    @staticmethod
    def wer(hyp: str, ref: str) -> float:
        rw = ref.split()
        return levenshtein(hyp.split(), rw) / max(len(rw), 1)

    @staticmethod
    def cer(hyp: str, ref: str) -> float:
        return levenshtein(hyp, ref) / max(len(ref), 1)
