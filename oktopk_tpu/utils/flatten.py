"""Flat-vector <-> pytree helpers for the sparse-allreduce seam.

Every composed train step (optim/distributed.py buckets,
parallel/bert_seq.py, parallel/bert_pipeline.py) flattens a gradient
pytree into the collective's flat vector and scatters the reduced result
back; one definition keeps the offset/reshape logic identical."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def flatten_tree(tree):
    """-> (flat [n], leaves, treedef)."""
    leaves, treedef = jax.tree.flatten(tree)
    return (jnp.concatenate([x.reshape(-1) for x in leaves]), leaves,
            treedef)


def unflatten_tree(flat, leaves, treedef):
    """Inverse of :func:`flatten_tree` (shapes from ``leaves``)."""
    off, out = 0, []
    for x in leaves:
        out.append(flat[off:off + x.size].reshape(x.shape))
        off += x.size
    return jax.tree.unflatten(treedef, out)
