"""Model complexity accounting (reference C21: the vendored ptflops
per-layer MACs/params hooks, BERT/ptflops/flops_counter.py:19-410, reported
at startup by main_bert.py:861-869).

TPU-native form: XLA already computes a cost model for every compiled
program; ``jax.jit(...).lower().compile().cost_analysis()`` exposes it, so no
per-layer hooks are needed and the numbers reflect the *fused* program that
actually runs."""

from __future__ import annotations

from typing import Any, Dict

import jax


def param_count(params) -> int:
    return int(sum(x.size for x in jax.tree.leaves(params)))


def model_complexity(fn, *args) -> Dict[str, Any]:
    """Compile ``fn(*args)`` and report XLA's flop/byte estimates."""
    compiled = jax.jit(fn).lower(*args).compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):     # older jax returns [dict]
        cost = cost[0] if cost else {}
    return {
        "flops": float(cost.get("flops", -1)),
        "bytes_accessed": float(cost.get("bytes accessed", -1)),
        "cost_analysis": dict(cost),
    }
