"""Hostname-tagged logging + per-experiment log files (reference
VGG/settings.py:27-38 and the logfile wiring in VGG/main_trainer.py:165-176)."""

from __future__ import annotations

import logging
import os
import socket
from typing import Optional


def _fmt() -> logging.Formatter:
    host = socket.gethostname()
    return logging.Formatter(
        f"%(asctime)s [{host}] %(levelname)s %(name)s: %(message)s")


def get_logger(name: str = "oktopk_tpu", logfile: Optional[str] = None,
               level=logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        logger.setLevel(level)
        sh = logging.StreamHandler()
        sh.setFormatter(_fmt())
        logger.addHandler(sh)
    if logfile:
        # A later call with a logfile must still attach it: the old
        # if-handlers early-return silently dropped the file when the
        # logger had already been created (e.g. console-only at import,
        # per-experiment file once the rundir exists).
        target = os.path.abspath(logfile)
        attached = any(
            isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == target
            for h in logger.handlers)
        if not attached:
            d = os.path.dirname(target)
            if d:
                os.makedirs(d, exist_ok=True)
            fh = logging.FileHandler(target)
            fh.setFormatter(_fmt())
            logger.addHandler(fh)
    return logger
