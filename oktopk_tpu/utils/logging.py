"""Hostname-tagged logging + per-experiment log files (reference
VGG/settings.py:27-38 and the logfile wiring in VGG/main_trainer.py:165-176)."""

from __future__ import annotations

import logging
import os
import socket
from typing import Optional


def get_logger(name: str = "oktopk_tpu", logfile: Optional[str] = None,
               level=logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if logger.handlers:
        return logger
    logger.setLevel(level)
    host = socket.gethostname()
    fmt = logging.Formatter(
        f"%(asctime)s [{host}] %(levelname)s %(name)s: %(message)s")
    sh = logging.StreamHandler()
    sh.setFormatter(fmt)
    logger.addHandler(sh)
    if logfile:
        os.makedirs(os.path.dirname(logfile), exist_ok=True)
        fh = logging.FileHandler(logfile)
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    return logger
