"""Profiling / tracing subsystem (SURVEY.md §5.1).

Reference shape: per-phase wall-clock timer dicts in the allreducer
(``_merge/_compression/_allreduce/_demerge/_d2h/_h2d_timers``,
VGG/allreducer.py:256-262) dumped every 50 steps as a per-layer-group table
by ``_print_profiling`` (VGG/allreducer.py:379-439), plus TensorBoard scalars
(VGG/dl_trainer.py:611-613) and GPU/CPU memory logging
(VGG/dl_trainer.py:697-699).

TPU-native reality: the compression/collective phases fuse into ONE XLA
program, so intra-step phase timing moves to (a) coarse host-side phases
(data wait / step / eval), (b) analytic counters carried in SparseState
(selection counts, comm volume), and (c) ``jax.profiler`` traces for
op-level attribution in xprof. This module provides all three:

- :class:`PhaseTimers` — host-side phase accounting with the reference's
  every-N-steps table dump;
- :class:`MetricWriter` — per-step scalar log (CSV; the reference's
  tensorboardX writer equivalent, gated to stay dependency-free);
- :func:`trace_window` / :class:`TraceWindow` — a bounded
  ``jax.profiler`` trace around chosen steps;
- :func:`device_memory_stats` — HBM in-use/limit (the
  ``torch.cuda.memory_allocated`` analogue).
"""

from __future__ import annotations

import csv
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional


class PhaseTimers:
    """Rolling per-phase wall-clock accounting.

    ``with timers.phase("step"): ...`` accumulates a sample; ``table()``
    renders the reference-style mean/total dump (VGG/allreducer.py:379-439),
    and ``maybe_log(step, logger)`` prints it every ``every`` steps then
    resets, like the reference's 50-step cadence.
    """

    def __init__(self, every: int = 50, sink=None):
        self.every = every
        # optional obs.tracing.ChromeTraceSink (anything with
        # add(name, ts_s, dur_s)): every phase sample also becomes a
        # Chrome trace-event for chrome://tracing / Perfetto
        self.sink = sink
        self._samples: Dict[str, list] = defaultdict(list)

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            self._samples[name].append(dur)
            if self.sink is not None:
                self.sink.add(name, t0, dur)

    def add(self, name: str, seconds: float) -> None:
        self._samples[name].append(seconds)

    def table(self) -> str:
        rows = [f"{'phase':<14}{'mean_ms':>10}{'total_s':>10}{'count':>8}"]
        for name in sorted(self._samples):
            s = self._samples[name]
            if not s:
                # defaultdict access can register a phase with no
                # samples; render it instead of dividing by zero
                rows.append(f"{name:<14}{'-':>10}{'-':>10}{0:>8d}")
                continue
            mean = sum(s) / len(s)
            rows.append(
                f"{name:<14}{mean * 1e3:>10.2f}{sum(s):>10.3f}{len(s):>8d}")
        return "\n".join(rows)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Machine-readable form of :meth:`table` (for the run
        journal's ``phase`` events): mean/min/max and nearest-rank
        p50/p95 per phase, so host-phase spread sits next to the device
        anatomy in one report (scripts/obs_report.py)."""
        out: Dict[str, Dict[str, float]] = {}
        for name, s in self._samples.items():
            if not s:
                out[name] = {"mean_ms": 0.0, "min_ms": 0.0, "max_ms": 0.0,
                             "p50_ms": 0.0, "p95_ms": 0.0,
                             "total_s": 0.0, "count": 0.0}
                continue
            srt = sorted(s)
            cnt = len(srt)

            def rank(q: float) -> float:
                # nearest-rank percentile: exact order statistic, no
                # interpolation inventing never-observed durations
                return srt[min(cnt - 1, max(0, int(q * cnt + 0.5) - 1))]

            out[name] = {
                "mean_ms": sum(s) / cnt * 1e3,
                "min_ms": srt[0] * 1e3,
                "max_ms": srt[-1] * 1e3,
                "p50_ms": rank(0.50) * 1e3,
                "p95_ms": rank(0.95) * 1e3,
                "total_s": float(sum(s)),
                "count": float(cnt),
            }
        return out

    def reset(self) -> None:
        self._samples.clear()

    def maybe_log(self, step: int, logger) -> bool:
        if self.every and step % self.every == 0 and self._samples:
            logger.info("phase timing @ step %d\n%s", step, self.table())
            self.reset()
            return True
        return False


class MetricWriter:
    """Append-only per-step scalar log: ``<logdir>/scalars.csv``.

    Stands in for the reference's rank-0 tensorboardX writer
    (VGG/main_trainer.py:170-172, VGG/dl_trainer.py:611-613) without the
    dependency; the CSV loads straight into pandas for the same plots.
    """

    def __init__(self, logdir: str, filename: str = "scalars.csv"):
        os.makedirs(logdir, exist_ok=True)
        self.path = os.path.join(logdir, filename)
        self._existing_fields: Optional[list] = None
        if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            with open(self.path, newline="") as f:
                header = next(csv.reader(f), None)
            if header and header[0] == "step":
                self._existing_fields = header[1:]
        self._file = open(self.path, "a", newline="")
        self._writer = csv.writer(self._file)
        self._fields: Optional[list] = None

    def write(self, step: int, scalars: Dict[str, float]) -> None:
        if self._fields is None:
            self._fields = sorted(scalars)
            if self._existing_fields is None:
                self._writer.writerow(["step"] + self._fields)
            elif self._existing_fields != self._fields:
                # resuming with a different metric set: rotate to a fresh
                # file rather than appending misaligned rows
                self._file.close()
                base, ext = os.path.splitext(self.path)
                i = 1
                while os.path.exists(f"{base}-{i}{ext}"):
                    i += 1
                self.path = f"{base}-{i}{ext}"
                self._file = open(self.path, "a", newline="")
                self._writer = csv.writer(self._file)
                self._writer.writerow(["step"] + self._fields)
        row = [step] + [format(float(scalars.get(k, float("nan"))), ".8g")
                        for k in self._fields]
        self._writer.writerow(row)
        self._file.flush()

    def close(self) -> None:
        self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TraceWindow:
    """Start a ``jax.profiler`` trace at ``start_step`` and stop it
    ``num_steps`` later — a bounded xprof capture (the TPU replacement for
    the reference's flag-gated deep profiling, VGG/settings.py:20-26)."""

    def __init__(self, logdir: str, start_step: int, num_steps: int = 3):
        self.logdir = logdir
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False

    def on_step(self, step: int) -> None:
        import jax

        # range test, not equality: a resumed run may first observe a step
        # past start_step and should still capture the remaining window
        if self.start_step <= step < self.stop_step and not self._active:
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step >= self.stop_step and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


@contextmanager
def trace_window(logdir: str):
    """Trace everything inside the block (convenience for benchmarks).

    Degrades to a no-op when the profiler cannot start (CPU-only
    backends without profiler support, or a trace already running —
    e.g. nested inside an obs/tracing.py anomaly window): the traced
    code must run either way."""
    import jax

    started = False
    try:
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
        started = True
    except Exception:
        pass
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def device_memory_stats(device=None) -> Dict[str, float]:
    """HBM usage for one device (reference logs
    ``torch.cuda.memory_allocated``/psutil RSS, VGG/dl_trainer.py:697-699).
    Returns {} on backends without memory_stats (CPU)."""
    import jax

    dev = device or jax.local_devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if not stats:
        return {}
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if key in stats:
            out[key] = float(stats[key])
    return out


def host_memory_stats() -> Dict[str, float]:
    """Host RSS via /proc (psutil-free)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return {"host_rss_bytes": float(line.split()[1]) * 1024}
    except OSError:
        pass
    return {}
