"""Device-tunnel liveness probe.

When the TPU is reached through the axon tunnel (the site plugin's
``PALLAS_AXON_POOL_IPS`` env), a dead local relay makes ``jax.devices()``
block forever inside C — no exception, signal handlers never run. The only
safe pattern is to probe the relay socket *before* any backend use (and to
put hard deadlines on child processes that do touch the backend). Shared by
``bench.py`` and the opt-in hardware tests.
"""

from __future__ import annotations

import os
import socket

DEFAULT_RELAY_PORT = 8113


def relay_port() -> int:
    return int(os.environ.get("OKTOPK_RELAY_PORT", str(DEFAULT_RELAY_PORT)))


def relay_expected() -> bool:
    """True when this environment reaches the accelerator through the
    tunnel relay at all (a CPU-only box or a directly attached TPU keeps
    its normal path and needs no probe)."""
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def relay_listening(port: int | None = None, timeout: float = 1.0) -> bool:
    """True when something accepts on the tunnel relay's local port."""
    s = socket.socket()
    s.settimeout(timeout)
    try:
        s.connect(("127.0.0.1", relay_port() if port is None else port))
        return True
    except OSError:
        return False
    finally:
        s.close()
