"""Ok-Topk LSTM quality-gap ablation (VERDICT r3 item #3).

Round-3 evidence (logs/convergence/lstm_tiny_*.jsonl) shows oktopk is the
worst sparse algorithm on the recurrent workload: best eval 0.732 vs
topkA 0.465 — but at 245k elems/step vs topkA's 788k, i.e. 3.2x less
traffic. This harness isolates WHY, one knob at a time, on the exact
round-3 recipe (lstm_tiny, 8-worker mesh, SGD lr 5.0, 1000 steps,
200-step dense warmup, density 0.05):

- density 0.10 / 0.16:   oktopk applies ~k global winners per step where
  topkA applies the up-to-P*k union of local selections (reference
  VGG/allreducer.py:819-846 vs :1171-1217), so at equal nominal density
  oktopk moves ~3x less information. d=0.16 is the ISO-VOLUME point:
  ~5k scalars/step * 0.16 * n ~ topkA@0.05's 788k.
- warmup 400:            the recurrent family is warmup-sensitive
  (docs/PERF.md:190-195); test whether more dense steps close the gap.
- band@k:                the controller band [2k/3, k] admits sustained
  ~0.7k under-selection (observed global_k 30-41k vs k=49280); target
  [k, 1.5k] instead.
- drift_ema 0.5:         damp the drift estimate — recurrent gradient
  scale is spiky (grad_norm 0.17->1.1 within 20 steps in the r3 logs),
  so a fully-adopted per-window rate may overshoot.
- recompute 8:           4x more frequent exact threshold recomputes, in
  case recurrent-scale drift outruns the predictor between windows.

Each variant writes logs/ablation/lstm_tiny_oktopk_<name>.jsonl in the
convergence-log schema, so the same analysis tooling reads both.

Usage: python scripts/ablate_lstm.py [--variants d010,d016,...] [--steps 1000]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# name -> (TrainConfig overrides, OkTopkConfig overrides)
VARIANTS = {
    "base":    ({}, {}),
    "d010":    ({"density": 0.10}, {}),
    "d016":    ({"density": 0.16}, {}),
    "w400":    ({}, {"warmup_steps": 400}),
    # setpoints ride along: band_lo=1.0 forces the exact-k operating
    # point, so the sub-k r5 defaults would violate band_lo <= target
    "bandk":   ({}, {"band_lo": 1.0, "band_hi": 1.5, "band_hi_global": 1.5,
                     "local_k_target": 1.0, "global_k_target": 1.0}),
    "drift05": ({}, {"drift_ema": 0.5}),
    "rec8":    ({}, {"local_recompute_every": 8, "global_recompute_every": 8}),
    # the two knobs that moved the needle, combined (warmup is free —
    # same steady-state volume; d016 is the iso-volume point vs topkA)
    "w400d016": ({"density": 0.16}, {"warmup_steps": 400}),
    "w400d010": ({"density": 0.10}, {"warmup_steps": 400}),
}


def run_variant(name: str, steps: int, mesh, out_dir: str):
    import json
    import time

    import numpy as np

    from oktopk_tpu.config import OkTopkConfig, TrainConfig
    from oktopk_tpu.data.synthetic import finite_pool_iterator
    from oktopk_tpu.train.trainer import Trainer

    tr_over, algo_over = VARIANTS[name]
    cfg = TrainConfig(dnn="lstm_tiny", dataset="synthetic-teacher",
                      batch_size=8, lr=5.0, compressor="oktopk",
                      density=tr_over.get("density", 0.05))
    algo_kw = {"warmup_steps": 200}
    algo_kw.update(algo_over)
    trainer = Trainer(cfg, mesh=mesh, algo_cfg=OkTopkConfig(**algo_kw))
    P = trainer.cfg.num_workers
    it = finite_pool_iterator("lstm_tiny", 8 * P, seed=7)
    eval_batch = next(it)

    path = os.path.join(out_dir, f"lstm_tiny_oktopk_{name}.jsonl")
    t0 = time.time()
    with open(path, "w") as f:
        header = {"model": "lstm_tiny", "compressor": "oktopk",
                  "variant": name, "steps": steps, "workers": P,
                  "density": cfg.density, "lr": cfg.lr, "batch_size": 8,
                  "n_params": trainer.algo_cfg.n,
                  "overrides": {**tr_over, **algo_kw}}
        f.write(json.dumps(header) + "\n")
        for i in range(steps):
            m = trainer.train_step(next(it))
            if (i + 1) % 10 == 0 or i == 0 or i + 1 == steps:
                rec = {"step": i + 1, "loss": float(m["loss"]),
                       "comm_volume": float(m["comm_volume"])}
                if (i + 1) % 50 == 0 or i + 1 == steps:
                    em = trainer.eval_step(eval_batch)
                    rec.update({f"eval_{k}": float(np.asarray(v))
                                for k, v in em.items()})
                for k in ("local_k", "global_k", "grad_norm",
                          "grad_nonfinite"):
                    if k in m:
                        rec[k] = float(np.asarray(m[k]).mean())
                f.write(json.dumps(rec) + "\n")
                f.flush()
    print(f"[ablate] {name}: final loss {float(m['loss']):.4f} "
          f"({time.time()-t0:.0f}s) -> {path}", flush=True)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--variants", default=",".join(k for k in VARIANTS
                                                  if k != "base"))
    p.add_argument("--steps", type=int, default=1000)
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--out", default="logs/ablation")
    args = p.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.workers}")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from oktopk_tpu.comm.mesh import get_mesh

    mesh = get_mesh((args.workers,), ("data",))
    os.makedirs(args.out, exist_ok=True)
    for name in args.variants.split(","):
        run_variant(name, args.steps, mesh, args.out)


if __name__ == "__main__":
    main()
